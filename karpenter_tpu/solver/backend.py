"""Solver backends: the pluggable `Solver` seam (BASELINE.json north_star).

- `ReferenceSolver` — the exact sequential Python path (ground truth).
- `TPUSolver` — encodes to tensors, runs the device FFD kernel, decodes back.
  If the input contains constructs the device kernel can't express yet
  (fallback groups — see encode.py), it transparently routes the WHOLE solve
  to the reference path so semantics never fork mid-solve.

Both operate on MiB-quantized inputs (encode.quantize_input) so decisions are
bit-identical; `tests/test_solver_parity.py` asserts it.
"""

from __future__ import annotations

import abc
import logging
import threading as _threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import faults
from ..api import wellknown as wk
from ..provisioning.scheduler import (
    ClaimResult,
    ExistingNode,
    NodePoolSpec,
    Scheduler,
    SolverInput,
    SolverResult,
)
from ..scheduling.requirements import IN, Requirement, Requirements
from ..metrics.registry import (
    SOLVER_DECODE_BYTES,
    SOLVER_EXPLAIN_BYTES,
    SOLVER_EXPLAIN_WIDE,
    SOLVER_MESH_DEVICES,
    SOLVER_RELAX_DISPATCHES,
    SOLVER_RESUME_HIT_RATE,
    SOLVER_RUNS_SKIPPED,
    SOLVER_SHARD_FIXUP_RUNS,
    SOLVER_SHARDED_FALLBACK,
    SOLVER_SOLVES,
    SOLVER_WIDE_REFETCH,
)
from ..obs import explain as obsexplain
from ..obs import trace as obstrace
from ..utils.resources import PODS, Resources
from .encode import EncodedInput, UnpackableInput, encode, quantize_input

log = logging.getLogger("karpenter_tpu")


class Solver(abc.ABC):
    @abc.abstractmethod
    def solve(self, inp: SolverInput) -> SolverResult:
        ...


def concrete_backend(solver):
    """The concrete executor at the bottom of a wrapper chain (resilient /
    class-aware / fleet layers all delegate via `.inner` or `.solver`).
    Wrappers' `__getattr__` passthrough makes hasattr unusable here — only
    attributes in the instance __dict__ count as real links."""
    seen = set()
    while id(solver) not in seen:
        seen.add(id(solver))
        d = getattr(solver, "__dict__", {})
        nxt = d.get("inner") or d.get("solver")
        if nxt is None or isinstance(nxt, (str, bytes)):
            break
        solver = nxt
    return solver


class ReferenceSolver(Solver):
    def solve(self, inp: SolverInput) -> SolverResult:
        # each CONCRETE executor counts itself exactly once per logical
        # solve; delegation layers count nothing (no double counting)
        SOLVER_SOLVES.inc(backend="oracle")
        with obstrace.span("backend.oracle"):
            res = canonicalize_placements(inp, Scheduler(inp).solve())
        if obsexplain.enabled():
            obsexplain.capture(inp, res, "oracle")
        return res


def canonicalize_placements(inp: SolverInput, res: SolverResult) -> SolverResult:
    """Canonical uid→target assignment within each run of identical pods.

    Pods of one run are fungible (same signature ⇒ same scheduling
    behavior); the sequential oracle may visit targets in interleaved order
    (zone budgets rotate domains), while the tensor path assigns run pods to
    targets in (existing-node input order, then claim creation order) —
    SPEC.md "Determinism". This post-pass re-sorts the oracle's per-run
    assignments into that canonical order; per-target COUNTS, claim
    contents-as-sets, and error counts are untouched. A no-op for
    monotone-fill runs (anything without zone budgets)."""
    from dataclasses import replace as _replace

    from .encode import _pod_signature

    from ..provisioning.scheduler import ffd_sort

    pods = ffd_sort([p for p in inp.pods if not p.scheduling_gated and not p.bound])
    runs: List[list] = []
    last_sig = object()
    for p in pods:
        s = _pod_signature(p)
        if runs and s == last_sig:
            runs[-1].append(p)
        else:
            runs.append([p])
            last_sig = s

    node_order = {n.id: i for i, n in enumerate(inp.nodes)}

    def tkey(t):
        if t[0] == "node":
            return (0, node_order.get(t[1], 0))
        return (1, t[1])

    placements: Dict[str, Tuple[str, object]] = {}
    errors: Dict[str, str] = {}
    claim_pods: Dict[int, List[str]] = {i: [] for i in range(len(res.claims))}
    for rp in runs:
        counts: Dict[Tuple[str, object], int] = {}
        err_msg = None
        n_err = 0
        for p in rp:
            t = res.placements.get(p.meta.uid)
            if t is None:
                n_err += 1
                err_msg = err_msg or res.errors.get(p.meta.uid, "unschedulable")
            else:
                counts[t] = counts.get(t, 0) + 1
        i = 0
        for t, c in sorted(counts.items(), key=lambda kv: tkey(kv[0])):
            for _ in range(c):
                uid = rp[i].meta.uid
                placements[uid] = t
                if t[0] == "claim":
                    claim_pods[t[1]].append(uid)
                i += 1
        for j in range(i, len(rp)):
            # keep each pod's own diagnostic when the source recorded one;
            # the run-level message only backfills pods whose uid moved
            # within the run during canonicalization
            uid = rp[j].meta.uid
            errors[uid] = res.errors.get(uid) or err_msg or "unschedulable"

    claims = [
        _replace(c, pod_uids=claim_pods[i]) for i, c in enumerate(res.claims)
    ]
    return SolverResult(placements=placements, claims=claims, errors=errors)


def pack_bits32(rows: np.ndarray) -> np.ndarray:
    """Pack a trailing bool axis (≤32 bits) into one uint32 per row."""
    nb = rows.shape[-1]
    if nb > 32:
        raise ValueError(f"cannot pack {nb} bits into uint32")
    pw = (np.uint64(1) << np.arange(nb, dtype=np.uint64)).astype(np.uint64)
    return (rows.astype(np.uint64) * pw).sum(axis=-1).astype(np.uint32)


def pack_words(rows: np.ndarray, width: int) -> np.ndarray:
    """Pack a trailing bool axis into ceil(width/32) uint32 words per row."""
    W = (width + 31) // 32
    out = np.zeros(rows.shape[:-1] + (W,), dtype=np.uint32)
    for w in range(W):
        chunk = rows[..., w * 32 : min((w + 1) * 32, rows.shape[-1])]
        if chunk.shape[-1]:
            out[..., w] = pack_bits32(chunk)
    return out


def unpack_zc_bits(bits: np.ndarray, Z: int, C: int) -> Tuple[np.ndarray, np.ndarray]:
    """Recover per-row zone/ct masks from packed joint (z*C+c) bits. Joint
    sets are always PRODUCTS (zones × cts) — intersections of products stay
    products — so the marginals reconstruct the state exactly."""
    joint = ((bits[:, None] >> np.arange(Z * C, dtype=np.uint32)[None, :]) & 1).astype(bool)
    joint = joint.reshape(-1, Z, C)
    return joint.any(axis=2), joint.any(axis=1)


# Padded HOST-side core kernel args cached across solves: the pod/pool/type
# stage of an encode is shared by every solve of an unchanged pending set
# (encode._EncodeCore), so its ~25 padded arrays build once per core
# REVISION — keyed on enc.core_rev, which encode_cache.try_patch preserves,
# so a delta-patched encode (pods moved within the known signature universe)
# reuses the padded tables a plain id()-keyed cache would rebuild.
_CORE_HOST_CACHE: dict = {}
_CORE_HOST_CACHE_MAX = 4

# ARG_SPEC entries that are pure functions of (core tables, pad dims) —
# provenance-tagged; the rest rebuild per solve and are content-hashed by
# their consumers (the argument arena / the device-conversion cache).
STATIC_CORE_NAMES = frozenset({
    "group_req", "group_compat_t", "group_zc_bits", "group_pool",
    "group_pair_nok", "group_device", "type_alloc", "type_charge",
    "offer_zc_bits", "pool_type", "pool_zc_bits", "pool_daemon",
    "q_member", "q_owner", "q_kind", "q_cap", "v_member", "v_owner",
    "v_kind", "v_cap", "v_primary", "v_aff", "zone_col_mask", "col_axis",
    "group_daxis",
})
PER_SOLVE_NAMES = frozenset({
    "run_group", "run_count", "pool_limit", "pool_usage0", "node_free",
    "node_compat", "node_q_member", "node_q_owner", "v_count0", "node_zone",
    "node_dom2",
})


def host_kernel_args(enc: EncodedInput, bucket) -> Tuple[tuple, dict, tuple]:
    """Padded HOST (numpy) positional arrays for tpu.ffd.ffd_solve (order =
    ffd.ARG_SPEC), their dims, and per-entry provenance tokens.

    Shapes bucket to bounded sizes so compilations cache across solves
    (SURVEY.md §7: bucketed padding avoids recompilation storms). Zone ×
    capacity-type admission and offering availability are packed into uint32
    bit masks (ffd.py "Bit-packing"); raises UnpackableInput when Z*C > 32
    (the hybrid solver falls back).

    prov[i] is a hashable content-identity token (same token ⇒ same bytes)
    for STATIC_CORE_NAMES entries when the encode carries a core revision,
    else None — consumers (solver/arena.py ArgumentArena, _device_args)
    use tokens to skip hashing/re-uploading unchanged arrays.
    """
    INT32_MAX_NP = np.int32(2**31 - 1)
    S, G, T, E, P = len(enc.run_group), enc.G, enc.T, enc.E, enc.P
    R, Z, C = enc.group_req.shape[1], len(enc.zones), len(enc.capacity_types)
    if Z * C > 32:
        raise UnpackableInput(f"Z*C = {Z * C} exceeds the 32-bit joint-offering packing")
    Sp, Gp, Tp, Ep, Pp = (
        bucket(S, 16, 16),
        bucket(G, 16, 16),
        bucket(T, 128, 128),
        bucket(E, 32, 8),
        bucket(P, 4, 4),
    )
    Qp = bucket(enc.Q, 8, 8)
    Vp = bucket(enc.V, 4, 4)
    W = (Gp + 31) // 32

    def pad(a, shape, fill=0):
        out = np.full(shape, fill, dtype=a.dtype)
        out[tuple(slice(0, s) for s in a.shape)] = a
        return out

    # domain axis of the V sigs: zone columns (default) or lex-ordered ct
    # columns — the kernel's "zone" tables are really domain tables, and the
    # joint packing is untouched either way (column masks select bits)
    D = len(enc.v_domains) if enc.v_domains is not None else Z
    # static-core key: Sp-independent (the run split is per-solve), so one
    # cached pad set serves every pod-count bucket of the same core
    core_rev = getattr(enc, "core_rev", -1)
    skey = (
        (core_rev, R, Z, C, Gp, Tp, Pp, Qp, Vp, D, enc.v_axis)
        if core_rev >= 0
        else None
    )
    core_args = _CORE_HOST_CACHE.get(skey) if skey is not None else None
    if core_args is None:
        zone_col = np.zeros(D, dtype=np.uint32)
        col_axis = np.zeros(D, dtype=np.int32)
        if enc.v_axis == "ct":
            # per-ct joint-bit columns: bit z*C+c for every z, in the
            # canonical domain order (enc.v_domain_perm — shared with the
            # native marshal swap)
            lex = enc.v_domain_perm
            for d, c in enumerate(lex):
                for z in range(Z):
                    zone_col[d] |= np.uint32(1) << np.uint32(z * C + c)
        elif enc.v_axis == "mixed":
            # both axes concatenated: Z zone columns, then C lex-ordered ct
            # columns — each column masks its value's joint bits
            for z in range(Z):
                for c in range(C):
                    zone_col[z] |= np.uint32(1) << np.uint32(z * C + c)
            ct_lex_idx = sorted(range(C), key=lambda i: enc.capacity_types[i])
            for d, c in enumerate(ct_lex_idx):
                col_axis[Z + d] = 1
                for z in range(Z):
                    zone_col[Z + d] |= np.uint32(1) << np.uint32(z * C + c)
        else:
            # per-zone joint-bit columns: bit z*C+c for every c
            for z in range(Z):
                for c in range(C):
                    zone_col[z] |= np.uint32(1) << np.uint32(z * C + c)
        type_charge = np.where(
            enc.charge_axes[None, :], enc.type_capacity, 0
        ).astype(np.int32)
        group_zc = pack_bits32(
            (enc.group_zone[:, :, None] & enc.group_ct[:, None, :]).reshape(G, Z * C)
        )
        pool_zc = pack_bits32(
            (enc.pool_zone[:, :, None] & enc.pool_ct[:, None, :]).reshape(P, Z * C)
        )
        offer_zc = pack_bits32(enc.offer_avail.reshape(T, Z * C))
        # pairwise-INcompatibility words; padded groups are compatible with all
        pair_nok = pack_words(~pad(enc.group_pair, (Gp, Gp), fill=True), Gp)
        core_args = {
            "group_req": pad(enc.group_req, (Gp, R)),
            "group_compat_t": pad(enc.group_compat_t, (Gp, Tp)),
            "group_zc_bits": pad(group_zc, (Gp,)),
            "group_pool": pad(enc.group_pool, (Gp, Pp)),
            "group_pair_nok": pair_nok,
            "group_device": pad(~enc.group_fallback, (Gp,)),
            "type_alloc": pad(enc.type_alloc, (Tp, R)),
            "type_charge": pad(type_charge, (Tp, R)),
            "offer_zc_bits": pad(offer_zc, (Tp,)),
            "pool_type": pad(enc.pool_type, (Pp, Tp)),
            "pool_zc_bits": pad(pool_zc, (Pp,)),
            "pool_daemon": pad(enc.pool_daemon, (Pp, R)),
            "q_member": pad(enc.q_member, (Gp, Qp)),
            "q_owner": pad(enc.q_owner, (Gp, Qp)),
            "q_kind": pad(enc.q_kind, (Qp,)),
            "q_cap": pad(enc.q_cap, (Qp,), fill=1),
            "v_member": pad(enc.v_member, (Gp, Vp)),
            "v_owner": pad(enc.v_owner, (Gp, Vp)),
            "v_kind": pad(enc.v_kind, (Vp,)),
            "v_cap": pad(enc.v_cap, (Vp,), fill=1),
            "v_primary": pad(enc.v_primary, (Gp,), fill=np.int32(-1)),
            "v_aff": pad(enc.v_aff, (Gp,), fill=np.int32(-1)),
            "zone_col_mask": zone_col,
            "col_axis": col_axis,
            "group_daxis": (
                pad(enc.group_daxis, (Gp,))
                if enc.group_daxis is not None
                else np.zeros(Gp, np.int32)
            ),
        }
        if skey is not None:
            if len(_CORE_HOST_CACHE) >= _CORE_HOST_CACHE_MAX:
                _CORE_HOST_CACHE.pop(next(iter(_CORE_HOST_CACHE)))
            _CORE_HOST_CACHE[skey] = core_args
    per_solve = {
        "run_group": pad(enc.run_group, (Sp,)),
        "run_count": pad(enc.run_count, (Sp,)),
        "pool_limit": pad(enc.pool_limit, (Pp, R), fill=INT32_MAX_NP),
        "pool_usage0": pad(enc.pool_usage, (Pp, R)),
        "node_free": pad(enc.node_free, (Ep, R)),
        "node_compat": pad(enc.node_compat, (Gp, Ep)),
        "node_q_member": pad(enc.node_q_member, (Ep, Qp)),
        "node_q_owner": pad(enc.node_q_owner, (Ep, Qp)),
        "v_count0": pad(enc.v_count0, (Vp, D)),
        "node_zone": pad(
            enc.v_node_domain if enc.v_node_domain is not None else enc.node_zone,
            (Ep,),
            fill=np.int32(-1),
        ),
        "node_dom2": (
            pad(enc.node_dom2, (Ep,), fill=np.int32(-1))
            if enc.node_dom2 is not None
            else np.full(Ep, -1, np.int32)
        ),
    }
    from .tpu.ffd import ARG_SPEC

    assert STATIC_CORE_NAMES | PER_SOLVE_NAMES == set(ARG_SPEC) and not (
        STATIC_CORE_NAMES & PER_SOLVE_NAMES
    ), "static/per-solve partition out of sync with ffd.ARG_SPEC"
    assert list(ARG_SPEC) == [
        "run_group", "run_count", "group_req", "group_compat_t", "group_zc_bits",
        "group_pool", "group_pair_nok", "group_device", "type_alloc", "type_charge",
        "offer_zc_bits", "pool_type", "pool_zc_bits", "pool_daemon", "pool_limit",
        "pool_usage0", "node_free", "node_compat", "q_member", "q_owner", "q_kind",
        "q_cap", "node_q_member", "node_q_owner", "v_member", "v_owner", "v_kind",
        "v_cap", "v_primary", "v_aff", "v_count0", "node_zone", "zone_col_mask",
        "node_dom2", "col_axis", "group_daxis",
    ], "kernel_args order out of sync with ffd.ARG_SPEC"
    args = tuple(
        core_args[n] if n in STATIC_CORE_NAMES else per_solve[n] for n in ARG_SPEC
    )
    prov = tuple(
        (skey, n) if (skey is not None and n in STATIC_CORE_NAMES) else None
        for n in ARG_SPEC
    )
    dims = dict(
        S=S, G=G, T=T, E=E, P=P, R=R, Z=Z, C=C,
        Sp=Sp, Gp=Gp, Tp=Tp, Ep=Ep, Pp=Pp, Qp=Qp, Vp=Vp, W=W,
    )
    return args, dims, prov


# Device conversions of provenance-tagged host arrays — the plain (non-
# arena) upload path: keyed by the same (static key, name) tokens the
# arena uses, so a patched encode re-uploads none of the tables it shares
# with its donor core. Bounded FIFO sized for ~4 cores × ~25 static
# entries; tokens embed a monotonic core_rev, so eviction tracks core age.
_DEV_CACHE: dict = {}
_DEV_CACHE_MAX = 128


def _device_args(host_args: tuple, prov: tuple, ledger=None) -> tuple:
    """Per-array jnp conversion of host_kernel_args output (arena-off path:
    one host→device message per stale array, the pre-arena behavior)."""
    import jax.numpy as jnp

    out = []
    up_bytes = 0
    up_arrays = 0
    for a, tok in zip(host_args, prov):
        if tok is None:
            out.append(jnp.asarray(a))
            up_bytes += a.nbytes
            up_arrays += 1
            continue
        hit = _DEV_CACHE.get(tok)
        if hit is None:
            hit = jnp.asarray(a)
            while len(_DEV_CACHE) >= _DEV_CACHE_MAX:
                _DEV_CACHE.pop(next(iter(_DEV_CACHE)))
            _DEV_CACHE[tok] = hit
            up_bytes += a.nbytes
            up_arrays += 1
        out.append(hit)
    if ledger is not None:
        ledger.record_upload(up_bytes, up_arrays, msgs=up_arrays)
    return tuple(out)


def kernel_args(enc: EncodedInput, bucket) -> Tuple[tuple, dict]:
    """Device-resident padded positional arrays for tpu.ffd.ffd_solve (order
    = ffd.ARG_SPEC), plus dims — a device-conversion wrapper over
    `host_kernel_args`. Shared by the driver entry points, the AOT prewarm,
    and tests; TPUSolver's solve path goes through the argument arena
    instead (solver/arena.py) for packed delta uploads."""
    host_args, dims, prov = host_kernel_args(enc, bucket)
    return _device_args(host_args, prov), dims


# Bucketed shape of every ffd.ARG_SPEC positional, in dim symbols — the AOT
# prewarm (TPUSolver.prewarm_aot) builds ShapeDtypeStructs from this without
# materializing arrays, and self-checks the table against a concrete
# kernel_args() result before compiling anything, so it can never drift
# silently. W = (Gp+31)//32 pair-words; D = the domain-axis width
# (Z / C / Z+C by v_axis).
_AOT_SHAPES = {
    "run_group": ("Sp",), "run_count": ("Sp",),
    "group_req": ("Gp", "R"), "group_compat_t": ("Gp", "Tp"),
    "group_zc_bits": ("Gp",), "group_pool": ("Gp", "Pp"),
    "group_pair_nok": ("Gp", "W"), "group_device": ("Gp",),
    "type_alloc": ("Tp", "R"), "type_charge": ("Tp", "R"),
    "offer_zc_bits": ("Tp",), "pool_type": ("Pp", "Tp"),
    "pool_zc_bits": ("Pp",), "pool_daemon": ("Pp", "R"),
    "pool_limit": ("Pp", "R"), "pool_usage0": ("Pp", "R"),
    "node_free": ("Ep", "R"), "node_compat": ("Gp", "Ep"),
    "q_member": ("Gp", "Qp"), "q_owner": ("Gp", "Qp"),
    "q_kind": ("Qp",), "q_cap": ("Qp",),
    "node_q_member": ("Ep", "Qp"), "node_q_owner": ("Ep", "Qp"),
    "v_member": ("Gp", "Vp"), "v_owner": ("Gp", "Vp"),
    "v_kind": ("Vp",), "v_cap": ("Vp",),
    "v_primary": ("Gp",), "v_aff": ("Gp",),
    "v_count0": ("Vp", "D"), "node_zone": ("Ep",),
    "zone_col_mask": ("D",), "node_dom2": ("Ep",),
    "col_axis": ("D",), "group_daxis": ("Gp",),
}


def min_values_post_check(qinp: SolverInput, result: SolverResult) -> bool:
    """minValues floors for the tensor backends (nodepools.md:268-330): the
    kernels narrow type sets without counting distinct requirement values, so
    each claim's FINAL surviving set is checked here — equivalent to the
    oracle's per-add checks because options only shrink (scheduler.
    min_values_ok). A violation routes the whole solve to the fallback
    chain, whose oracle enforces floors during packing."""
    floors = {}
    types_by_pool = {}
    for p in qinp.nodepools:
        fl = [(k, r) for k, r in p.requirements.items() if r.min_values]
        if fl:
            floors[p.name] = fl
            types_by_pool[p.name] = {it.name: it for it in p.instance_types}
    if not floors:
        return True
    from ..provisioning.scheduler import distinct_values_at_least

    for claim in result.claims:
        fl = floors.get(claim.nodepool)
        if not fl:
            continue
        types = types_by_pool[claim.nodepool]
        survivors = [types[n] for n in claim.instance_type_names if n in types]
        for k, r in fl:
            eff = r
            cr = claim.requirements.get(k)
            if cr is not None:
                eff = r.intersect(cr)
            if not distinct_values_at_least(k, eff, r.min_values, survivors):
                return False
    return True


def initial_claim_bucket(total_pods: int, max_claims: int) -> int:
    """First claim-slot bucket M for a solve of `total_pods` pods: the
    smallest power-of-two ≥ min(total_pods+1, 512), capped at max_claims.
    The solver doubles on saturation (overflow retry); bench.py uses the
    same helper so the benchmarked bucket can't drift from production."""
    M = 64
    while M < min(total_pods + 1, 512):
        M *= 2
    return min(M, max(max_claims, 64))


_PACK_CACHE: dict = {}


def _pack_outputs(out):
    """Flatten every decoded-by-the-host kernel output into ONE int32 device
    buffer (bool mask rows bit-packed to words, uint32 bitcast) so the
    device→host hop is a single transfer: on a tunneled link each fetched
    array pays per-message overhead on top of the shared roundtrip, and the
    9-array fetch measured ~2× the bare RTT.

    take_e/take_c dominate the buffer; they pack as uint16 pairs (per-run
    takes are bounded by per-node pod capacity in practice). A leading
    overflow flag records any value > 65535 — the host re-fetches wide via
    _pack_outputs_wide in that (pathological) case, so correctness never
    depends on the bound."""
    import jax
    import jax.numpy as jnp

    def go(out):
        st = out.state
        b32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)

        def pack16(x):
            flat = x.ravel()
            n = flat.shape[0]
            flat = jnp.pad(flat, (0, (-n) % 2))
            u16 = flat.astype(jnp.uint16).reshape(-1, 2)
            return jax.lax.bitcast_convert_type(u16, jnp.int32)

        M, Tp = st.c_mask.shape
        W = (Tp + 31) // 32
        cm = jnp.pad(st.c_mask, ((0, 0), (0, W * 32 - Tp))).reshape(M, W, 32)
        weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
        cm_words = (cm.astype(jnp.uint32) * weights[None, None, :]).sum(
            axis=2, dtype=jnp.uint32
        )
        overflow = (
            (jnp.max(out.take_e, initial=0) > 65535)
            | (jnp.max(out.take_c, initial=0) > 65535)
        ).astype(jnp.int32)
        parts = [
            overflow.reshape(1),
            pack16(out.take_e),
            pack16(out.take_c),
            out.leftover.ravel(),
            b32(cm_words).ravel(),
            b32(st.c_zc_bits).ravel(),
            b32(st.c_gbits).ravel(),
            st.c_pool.ravel(),
            st.c_cum.ravel(),
            st.used.reshape(1),
        ]
        return jnp.concatenate(parts)

    fn = _PACK_CACHE.get("pack16")
    if fn is None:
        fn = jax.jit(go)
        _PACK_CACHE["pack16"] = fn
    return fn(out)


def _pack_outputs_wide(out):
    """Full-width (int32) packing — the overflow fallback of _pack_outputs."""
    import jax
    import jax.numpy as jnp

    def go(out):
        st = out.state
        b32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)
        M, Tp = st.c_mask.shape
        W = (Tp + 31) // 32
        cm = jnp.pad(st.c_mask, ((0, 0), (0, W * 32 - Tp))).reshape(M, W, 32)
        weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
        cm_words = (cm.astype(jnp.uint32) * weights[None, None, :]).sum(
            axis=2, dtype=jnp.uint32
        )
        parts = [
            out.take_e.ravel(),
            out.take_c.ravel(),
            out.leftover.ravel(),
            b32(cm_words).ravel(),
            b32(st.c_zc_bits).ravel(),
            b32(st.c_gbits).ravel(),
            st.c_pool.ravel(),
            st.c_cum.ravel(),
            st.used.reshape(1),
        ]
        return jnp.concatenate(parts)

    fn = _PACK_CACHE.get("pack_wide")
    if fn is None:
        fn = jax.jit(go)
        _PACK_CACHE["pack_wide"] = fn
    return fn(out)


DELTA_CAP_QUANTUM = 256  # entry-capacity bucket, bounds compile variants
DELTA_UNIQ_QUANTUM = 16  # unique claim-meta row capacity bucket


def delta_capacity(total_pods: int, Sp: int, Ep: int, Mb: int) -> int:
    """Entry capacity of the claim-delta buffer (SPEC.md "Decode & ladder
    semantics"). Every nonzero take entry accounts for ≥ 1 placed pod, so
    `total_pods` is a hard ceiling, and Sp·(Ep+Mb) is the structural one;
    the steady-state heuristic Sp + 2·Ep + 4·Mb (one entry per run, a
    couple of runs per existing node, a handful of pouring runs per claim
    — measured ~3.6 on the 50k surge bench) is far tighter for surge
    fleets, where runs are large and few. A solve that genuinely exceeds
    the capacity trips the overflow flag and re-fetches full width —
    correctness never depends on the bound."""
    need = min(total_pods, Sp + 2 * Ep + 4 * Mb, Sp * (Ep + Mb))
    q = DELTA_CAP_QUANTUM
    return max(q, ((need + q - 1) // q) * q)


def delta_uniq_capacity(Sp: int, Mb: int) -> int:
    """Unique claim-meta row capacity. Distinct rows track deployment
    waves (~runs), not claims — claims of one wave differ only in c_cum,
    which never crosses the link (the host rebuilds it from the entries).
    Sp + 48 leaves ~50% headroom over the measured 50k surge (52 rows at
    32 runs: each wave contributes its full-claim mask plus a partial-fill
    variant); genuine excess trips the overflow re-fetch."""
    q = DELTA_UNIQ_QUANTUM
    need = min(Mb, Sp + 48)
    return max(q, ((need + q - 1) // q) * q)


def _pack_outputs_delta(out, cap: int, cap_u: int):
    """Delta packing: same single-buffer discipline as _pack_outputs, but
    (a) the take tables travel as the on-device compaction's run-major
    (code, count) uint16 pairs plus per-run entry counts — the dominant
    O(S×E + S×M) term of the fetch drops to O(actual placements); (b) the
    per-claim identity rows (type-mask words, zone/ct bits, group bits,
    pool) are deduped on device into a unique-row table + uint16 ids; and
    (c) c_cum never crosses the link at all — the host rebuilds it from
    the entries (pool daemon base + take × group_req, _claim_cum_from_
    entries). Header [overflow, n, n_u] leads; overflow covers >65535
    takes, entry-count saturation, AND unique-row saturation — all
    re-fetched full-width by the host."""
    import jax
    import jax.numpy as jnp

    from .tpu.ffd import compact_claim_meta, compact_takes

    def go(out):
        st = out.state
        M, Tp = st.c_mask.shape
        W = (Tp + 31) // 32
        cm = jnp.pad(st.c_mask, ((0, 0), (0, W * 32 - Tp))).reshape(M, W, 32)
        weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
        cm_words = (cm.astype(jnp.uint32) * weights[None, None, :]).sum(
            axis=2, dtype=jnp.uint32
        )
        overflow_t, n, cnt16, pairs = compact_takes(
            out.take_e, out.take_c, cap
        )
        overflow_u, n_u, uniq, mid16 = compact_claim_meta(
            cm_words, st.c_zc_bits, st.c_gbits, st.c_pool, cap_u
        )
        parts = [
            (overflow_t | overflow_u).reshape(1),
            n.reshape(1),
            n_u.reshape(1),
            cnt16.ravel(),
            pairs.ravel(),
            out.leftover.ravel(),
            uniq.ravel(),
            mid16.ravel(),
            st.used.reshape(1),
        ]
        return jnp.concatenate(parts)

    fn = _PACK_CACHE.get(("delta", cap, cap_u))
    if fn is None:
        fn = jax.jit(go)
        _PACK_CACHE[("delta", cap, cap_u)] = fn
    return fn(out)


def _unpack_flat(flat: np.ndarray, shapes: dict) -> dict:
    """Host-side inverse of _pack_outputs; `shapes` carries the device-side
    array shapes (known locally from the output metadata, no transfer)."""
    res = {}
    off = 0
    for name, (shape, dtype) in shapes.items():
        n = int(np.prod(shape)) if shape else 1
        a = flat[off : off + n]
        off += n
        if dtype == "u32":
            a = a.view(np.uint32)
        res[name] = a.reshape(shape) if shape else a[0]
    return res


class _CohortOverflow(Exception):
    """Internal: a fused cohort lane saturated its claim bucket. The member
    replays through its full solo path (which owns the M-doubling ladder);
    co-members keep their fused results. Never escapes the backend."""


class AsyncSolve:
    """Handle for an in-flight solve: the kernel is dispatched and the packed
    output is streaming to the host; result() blocks, decodes, and returns
    the SolverResult. Lets a control loop overlap host encode/decode of one
    solve with device compute + link transfer of another (the tunnel RTT is
    the e2e seam's floor — pipelining hides it across solves).

    result() is idempotent and thread-safe: the pipelined SolveService
    (solver/pipeline.py) decodes handles on its own thread while the
    submitting controller may also hold the handle — the deferred fn must
    run exactly once no matter who resolves first."""

    def __init__(self, fn):
        self._fn = fn
        self._result: Optional[SolverResult] = None
        self._done = False
        self._lock = _threading.Lock()

    def result(self) -> SolverResult:
        with self._lock:
            if not self._done:
                self._result = self._fn()
                self._done = True
            return self._result


class TPUSolver(Solver):
    """Tensorized FFD on device (JAX/XLA; see tpu/ffd.py).

    max_claims bounds the claim-slot array; inputs that overflow it (or use
    unsupported constructs) fall back to the reference path.
    """

    def __init__(self, max_claims: int = 1024, fallback: Optional[Solver] = None,
                 arena: bool = True, resume: bool = True,
                 ckpt_every: int = 16, ckpt_slots: int = 4,
                 device_decode: bool = True, relax_ladder: bool = True,
                 shards: int = 0, arena_budget_mb: int = 0,
                 sparse: str = "auto"):
        self.max_claims = max_claims
        # sparse constraint engine (SPEC.md "Sparse constraint semantics"):
        # "auto" compacts the V/Q axes into run-major index tables when the
        # fleet's constraint density clears encode.use_sparse_constraints;
        # "on" forces compaction for any constrained fleet, "off" keeps the
        # dense tables (debug escape hatch / parity oracle). Decisions are
        # bit-identical either way — the knob only changes evaluation cost.
        if sparse not in ("auto", "on", "off"):
            raise ValueError(f"sparse must be auto/on/off, got {sparse!r}")
        self.sparse = sparse
        if fallback is None:
            # fallback chain: native C++ core (compiled-class speed), which
            # itself degrades to the python oracle for constructs neither
            # encoded path expresses (topology/affinity, pending kernels)
            from .native import NativeSolver

            fallback = NativeSolver()
        self.fallback = fallback
        self.stats: Dict[str, int] = {
            "device_solves": 0, "fallback_solves": 0,
            "resume_solves": 0, "resume_runs_skipped": 0,
            "wide_refetches": 0, "ladder_solves": 0,
            "relax_dispatches": 0, "ladder_rungs_used": 0,
            "sharded_solves": 0, "shard_fixup_runs": 0,
            "sharded_fallbacks": 0, "shard_resume_solves": 0,
            "shard_resume_runs_skipped": 0,
            "event_stage_hits": 0, "event_stage_misses": 0,
            "fused_dispatches": 0, "fused_members": 0,
            "sparse_dispatches": 0,
        }
        # cohort dispatch mesh (solve_cohort_async): lazy like _shard_mesh,
        # but over ALL visible devices — the fused batch axis buckets to a
        # multiple of the device count, so any width divides evenly
        self._cohort_mesh_cache: object = None
        # streaming run-table staging (solver/streaming.py, SPEC.md
        # "Streaming semantics"): when on, each device solve first tries to
        # sync the arena's resident run tables via an edit-triplet scatter
        # (arena.apply_run_events) so adopt() sees them fresh and the h2d
        # payload shrinks to the triplets. Default off — the StreamingSolver
        # flips it; decisions are identical either way (the stage only
        # changes HOW the same bytes reach the device).
        self.stream_run_events = False
        # mesh-sharded provisioning solve (ISSUE 7, SPEC.md "Sharding
        # semantics"): shards >= 2 partitions ONE solve's run axis across a
        # device mesh (block-local scans + host carry-exchange stitch,
        # decision-identical to the one-device scan); 0/1 keeps every solve
        # single-device. The actual mesh is the largest power of 2 ≤
        # min(shards, visible devices, 16), built lazily (_shard_mesh).
        self.shards = max(0, int(shards))
        self._shard_mesh_cache: object = False  # False = not yet probed
        self._shard_prewarmed: set = set()  # mesh device-set tokens AOT'd
        # multi-host run-axis solve (ISSUE 18, SPEC.md "Federation
        # semantics"): host_mesh, when set to a parallel/hostmesh
        # HostMeshPool, scatters the run blocks to subprocess worker hosts
        # instead of a local device mesh — the virtual stand-in for a
        # jax.distributed pod slice. _shard_local_blocks is the contiguous
        # [lo, hi) block range THIS process owns on a process-spanning mesh
        # (per-process arena adoption uploads only that partition);
        # _process_mesh_error records a fail-closed mesh decline.
        self.host_mesh = None
        self._shard_local_blocks: Optional[Tuple[int, int]] = None
        self._process_mesh_error: Optional[str] = None
        # on-device decode (tpu/ffd.compact_takes + decode_delta): fetch the
        # take tables as a packed claim-delta instead of dense grids;
        # false = dense uint16 packing (debug escape hatch / parity oracle)
        self.device_decode = bool(device_decode)
        # device-resident relax ladder (ffd_solve_ladder): fold the host
        # relax-and-redispatch loop into one kernel dispatch when every
        # laddered run is homogeneous; false = host loop (`_relax_solve`)
        self.relax_ladder = bool(relax_ladder)
        # device-resident argument arena + transfer accounting (solver/
        # arena.py): arena=False restores the per-array upload path (debug
        # escape hatch, `--solver-arena false`); the ledger counts either way
        from .arena import ArgumentArena, TransferLedger

        self.ledger = TransferLedger()
        # arena_budget_mb > 0 bounds TOTAL accounted residency (all classes,
        # all tenants) with LRU whole-bucket eviction — `--arena-budget-mb`
        self.arena: Optional[ArgumentArena] = (
            ArgumentArena(
                self.ledger,
                budget_bytes=max(0, int(arena_budget_mb)) * 1024 * 1024,
            ) if arena else None
        )
        # checkpointed-scan resume (solver/tpu/ffd.py CheckpointRing +
        # SPEC.md "Resume semantics"): cold solves harvest an FFDState
        # snapshot ring every ckpt_every scan steps; a later solve whose run
        # list shares a validated prefix replays only the suffix. The
        # checkpoints are a residency class of the arena (they die with it
        # on invalidate()), so resume requires the arena.
        self.resume = bool(resume) and arena
        self.ckpt_every = max(1, int(ckpt_every))
        self.ckpt_slots = max(1, int(ckpt_slots))
        # fault-injection identity: a fleet names each owner's solver so a
        # chaos plan can wedge ONE owner (faults.check tag= on the wedge-
        # class sites); None = untagged, matches only untagged scripts
        self.fault_tag: Optional[str] = None

    def _shard_mesh(self):
        """Lazy mesh for mesh-sharded provisioning solves: the largest
        power-of-2 device count ≤ min(shards, visible devices, 16) on a
        1-D "shards" axis, or None when fewer than 2 devices are usable.
        Cached — mesh construction touches the device registry. The 16 cap
        matches ffd.SHARD_BLOCK_MULT: the padded run axis is always a
        multiple of 16, so any mesh this returns divides it evenly."""
        if self.shards < 2:
            return None
        if self._shard_mesh_cache is not False:
            return self._shard_mesh_cache
        mesh = None
        try:
            import jax

            from ..parallel.sharded import (
                MeshConstructionError,
                make_mesh,
                make_process_mesh,
            )

            limit = min(self.shards, len(jax.devices()), 16)
            n = 1
            while n * 2 <= limit:
                n *= 2
            nproc = int(jax.process_count())
            if nproc > 1:
                # true multi-host mesh (ISSUE 18): the run axis spans every
                # jax process. Construction is fail-closed — a grid the
                # processes cannot divide evenly raises the typed error,
                # which DECLINES to the single-device path (decision-
                # identical) rather than building a wrong mesh; the error
                # text is kept for /healthz + debugging.
                try:
                    if n >= max(2, nproc):
                        mesh, self._shard_local_blocks = make_process_mesh(
                            n, axis="shards"
                        )
                except MeshConstructionError as e:
                    self._process_mesh_error = str(e)
                    mesh = None
            elif n >= 2:
                mesh = make_mesh(n, axis="shards")
        except Exception:
            mesh = None
        self._shard_mesh_cache = mesh
        if mesh is not None:
            SOLVER_MESH_DEVICES.set(int(mesh.devices.size))
        return mesh

    def invalidate_arena(self) -> None:
        """Drop every device-resident kernel-arg buffer AND the checkpoint
        ring (checkpoints are derived state of the same solves — a replay
        must trust neither; SPEC.md "Resume semantics"). The resilience
        layer calls this before ANY fallback replay (gate rejection, device
        failure, timeout): a failed device solve leaves residency in an
        unknown state, and a replay must never trust it (SPEC.md "Transfer
        semantics"). The next device solve pays one full packed upload and
        runs cold."""
        if self.arena is not None:
            self.arena.invalidate()

    def solve(self, inp: SolverInput) -> SolverResult:
        return self.solve_async(inp).result()

    def solve_async(self, inp: SolverInput) -> AsyncSolve:
        """Encode + dispatch now; fetch + decode when result() is called."""
        qinp = quantize_input(inp)
        from . import relax as rx

        relax_plan = rx.plan(qinp)
        if relax_plan is not None:
            # Respect-mode preferences with only device-expressible kinds:
            # host-driven relax-and-redispatch, every iteration on device
            # (solver/relax.py). The common satisfiable case is ONE dispatch
            # — dispatched EAGERLY here so the async pipelining the
            # provisioner seam relies on still overlaps host and device work.
            from ..provisioning.scheduler import ffd_sort

            # Sort the FILTERED list (gated/bound pods dropped first): the
            # oracle sorts only schedulable pods, and sorting the full list
            # shifts signature first-appearance within equal-size blocks,
            # diverging the relax path's processing order from the oracle's.
            order = ffd_sort(
                [
                    p
                    for p in qinp.pods
                    if not p.scheduling_gated and p.node_name is None
                ]
            )
            if self.relax_ladder:
                # device-resident ladder: rungs pre-materialized as ghost
                # groups, ONE dispatch walks them in-kernel — decision-
                # identical to the host loop (see _ladder_dispatch). Bails
                # to the host loop for mixed-ladder runs / fallback classes.
                lad = self._ladder_dispatch(qinp, relax_plan, order)
                if lad is not None:
                    return AsyncSolve(
                        lambda: self._ladder_finish(qinp, relax_plan, order, lad)
                    )
            dropped = {u: 0 for u in relax_plan}
            first = self._relax_dispatch(qinp, relax_plan, order, dropped)
            return AsyncSolve(
                lambda: self._relax_solve(qinp, relax_plan, order, dropped, first)
            )
        with obstrace.span("backend.encode"):
            enc = encode(qinp)
        if (
            enc.group_fallback.any()
            or enc.has_topology
            or enc.has_affinity
            or enc.G == 0
        ):
            # Zone/capacity-type TSC+affinity and hostname constraints run
            # on device (Q/V axes, tpu/ffd.py; ct via the domain-axis swap;
            # zone+ct MIXES via the concatenated-axis layout); what still
            # routes the whole solve to the fallback chain: flagged fallback
            # groups (OR'd node affinity, preferred terms, multiple SAME-kind
            # domain terms per pod, single pods constrained on BOTH domain
            # axes, ≥3-way custom-label conflicts), custom-key spread, and
            # duplicate node hostnames. Whole-solve fallback keeps semantics
            # unforked.
            self.stats["fallback_solves"] += 1
            return AsyncSolve(lambda: self.fallback.solve(qinp))
        handle = self._device_solve_async(enc)
        if handle is None:
            self.stats["fallback_solves"] += 1
            return AsyncSolve(lambda: self.fallback.solve(qinp))

        def finish():
            out = handle()
            if out is None or not min_values_post_check(qinp, out):
                self.stats["fallback_solves"] += 1
                return self.fallback.solve(qinp)
            self.stats["device_solves"] += 1
            SOLVER_SOLVES.inc(backend="device")
            if obsexplain.enabled():
                # the EXPLAIN table decoded from the device wire rides the
                # result (stashed by _device_solve_async); None = a carve-out
                # (resume/shard/overflow) — the host deriver recomputes
                tbl = getattr(out, "_explain_table", None)
                obsexplain.capture(qinp, out, "tpu", enc=enc, table=tbl)
            return out

        return AsyncSolve(finish)

    # -- cross-tenant fused cohort dispatch (SPEC.md "Cohort semantics") -----

    def _cohort_mesh(self):
        if self._cohort_mesh_cache is None:
            from ..parallel.sharded import make_mesh

            self._cohort_mesh_cache = make_mesh(axis="cohort")
        return self._cohort_mesh_cache

    def _cohort_prep(self, inp: SolverInput):
        """Probe one member's fuse eligibility WITHOUT dispatching. Returns
        the prepared per-member state, or None when the member must ride its
        exact solo path (relax plan, fallback gate, sharded solve, arg
        overflow) — the caller re-submits it through solve_async so every
        ineligible member keeps byte-identical solo semantics."""
        qinp = quantize_input(inp)
        from . import relax as rx

        if rx.plan(qinp) is not None:
            return None
        with obstrace.span("backend.encode"):
            enc = encode(qinp)
        if (
            enc.group_fallback.any()
            or enc.has_topology
            or enc.has_affinity
            or enc.G == 0
        ):
            return None
        if self.shards >= 2:
            # the mesh-sharded run-axis solve partitions ONE solve across
            # the mesh; it cannot also carry a cohort batch axis
            return None
        try:
            host_args, dims, prov = host_kernel_args(enc, self._bucket)
        except UnpackableInput:
            return None
        total_pods = int(sum(len(p) for p in enc.group_pods))
        M0 = initial_claim_bucket(total_pods, self.max_claims)
        # exact fuse key: identical padded shapes/dtypes (one compiled
        # executable), same zone-engine static, same claim bucket — the
        # mux's quantum-bucket heuristic is re-verified here, exactly
        fkey = (
            tuple((a.shape, a.dtype.str) for a in host_args),
            bool(enc.V > 0),
            M0,
        )
        return {
            "inp": inp, "qinp": qinp, "enc": enc, "host_args": host_args,
            "dims": dims, "total_pods": total_pods, "M0": M0, "fkey": fkey,
        }

    def solve_cohort_async(self, inps, traces=None):
        """Fused cohort entry point: dispatch MANY tenants' solves as one
        vmapped kernel launch (parallel/sharded.batched_solve over the
        frozen ARG_SPEC), then fan the fused result out to per-member
        decode. Returns finish() -> list aligned with `inps`, each element
        a SolverResult or the Exception that member's path raised — one
        poison member never fails its co-members.

        Members whose exact fuse key (padded shapes + zone-engine static +
        claim bucket) doesn't match any co-member — or whose input needs a
        solo-only path (relax, fallback gate, sharding) — are re-submitted
        through solve_async and keep byte-identical solo semantics. Each
        fused member's decode/explain/metering path replicates its solo
        dispatch exactly (parity pinned by tests/test_cohort.py)."""
        n = len(inps)
        traces = list(traces) if traces is not None else [None] * n
        solo: dict = {}
        preps: list = [None] * n
        groups: "OrderedDict[tuple, list]" = OrderedDict()
        for i, inp in enumerate(inps):
            with obstrace.attached(traces[i]):
                try:
                    preps[i] = self._cohort_prep(inp)
                except Exception as e:  # noqa: BLE001 — isolate per member
                    solo[i] = e
                    continue
            if preps[i] is not None:
                groups.setdefault(preps[i]["fkey"], []).append(i)
        for fkey, idxs in list(groups.items()):
            if len(idxs) < 2:
                del groups[fkey]
        fused_idx = {i for idxs in groups.values() for i in idxs}
        for i in range(n):
            if i in fused_idx or i in solo:
                continue
            with obstrace.attached(traces[i]):
                try:
                    solo[i] = self.solve_async(inps[i])
                except Exception as e:  # noqa: BLE001 — isolate per member
                    solo[i] = e
        finishers = []
        for idxs in groups.values():
            try:
                finishers.append(self._cohort_dispatch(idxs, preps, traces))
            except Exception as e:  # noqa: BLE001 — a whole-dispatch
                # failure (wedge-class chaos, OOM) is every MEMBER's error,
                # like a fenced device; attribution stays per member upstream
                finishers.append(lambda _e=e, _ix=tuple(idxs):
                                 {i: _e for i in _ix})

        def finish():
            results: list = [None] * n
            fused_results: dict = {}
            for g in finishers:
                fused_results.update(g())
            for i in range(n):
                if i in fused_results:
                    results[i] = fused_results[i]
                    continue
                h = solo.get(i)
                if isinstance(h, BaseException):
                    results[i] = h
                    continue
                try:
                    with obstrace.attached(traces[i]):
                        results[i] = h.result()
                except Exception as e:  # noqa: BLE001 — per-member outcome
                    results[i] = e
            return results

        return finish

    def _cohort_dispatch(self, idxs, preps, traces):
        """One fused launch for `idxs` (all sharing a fuse key): stack the
        36 host arrays member-major, adopt the stack under the shared
        cohort residency namespace (each tenant's own `bucket_key ns=`
        buckets stay authoritative for solo replays), pad to the batch
        bucket with a replicated member, vmap-solve, and start each lane's
        packed d2h copy. Returns finish() -> {index: outcome}."""
        import jax

        from ..parallel.sharded import batch_bucket, batched_solve, pad_batch

        mesh = self._cohort_mesh()
        n_real = len(idxs)
        lead = preps[idxs[0]]
        zone = lead["fkey"][1]
        M0 = lead["M0"]
        # power-of-two cohort bucket (bounded compile count per fuse key),
        # rounded to a multiple of the mesh width
        B = batch_bucket(1 << (n_real - 1).bit_length(), mesh, mult=1)
        arity = len(lead["host_args"])
        stacked = tuple(
            np.stack([preps[i]["host_args"][j] for i in idxs])
            for j in range(arity)
        )
        faults.check("solver.device_hang", tag=self.fault_tag)
        faults.check("solver.device_lost", tag=self.fault_tag)
        self.ledger.begin_solve()
        with obstrace.attached(traces[idxs[0]]), \
                obstrace.span("cohort.dispatch"):
            obstrace.annotate(
                cohort_size=n_real, cohort_batch=B,
                member_solve_ids=",".join(
                    (traces[i].solve_id if traces[i] is not None else "-")
                    for i in idxs
                ),
            )
            with obstrace.span("backend.upload"):
                if self.arena is not None:
                    faults.check("solver.arena_corrupt", tag=self.fault_tag)
                    # suppress the ambient-trace tenant attribution: ONE
                    # stacked upload serves every member, and each member
                    # is billed its own rows explicitly below
                    with self.ledger.unmetered():
                        args = self.arena.adopt(
                            stacked, (None,) * arity, ns="__cohort__"
                        )
                    stale = self.arena.last_stale
                else:
                    with self.ledger.unmetered():
                        args = _device_args(
                            stacked, (None,) * arity, ledger=self.ledger
                        )
                    stale = tuple(range(arity))
            # per-member h2d metering parity: a member pays exactly the
            # bytes its solo dispatch would have uploaded for the entries
            # this adopt found stale (its own rows of the stacked arrays)
            from ..obs import slo as obsslo

            for i in idxs:
                obsslo.meter_bytes(
                    getattr(preps[i]["enc"], "tenant_id", None),
                    h2d=sum(
                        int(preps[i]["host_args"][j].nbytes) for j in stale
                    ),
                )
            args = pad_batch(args, B)
            faults.check("solver.device_dispatch")
            with obstrace.span("backend.dispatch"):
                out = batched_solve(mesh, args, max_claims=M0,
                                    zone_engine=zone)
        self.stats["fused_dispatches"] += 1
        self.stats["fused_members"] += n_real
        flats = []
        for k, i in enumerate(idxs):
            lane = jax.tree_util.tree_map(lambda a, _k=k: a[_k], out)
            flat_dev, unpack = self._pack_dispatch(
                lane, total_pods=preps[i]["total_pods"]
            )
            flats.append((i, lane, flat_dev, unpack))

        def finish():
            results: dict = {}
            replays: list = []
            try:
                for i, lane, flat_dev, unpack in flats:
                    prep = preps[i]
                    with obstrace.attached(traces[i]):
                        try:
                            results[i] = self._cohort_lane_finish(
                                prep, lane, flat_dev, unpack, M0
                            )
                        except _CohortOverflow:
                            replays.append(i)
                        except Exception as e:  # noqa: BLE001 — poison
                            results[i] = e  # member: only ITS lane fails
            finally:
                self.ledger.end_solve()
            for i in replays:
                # claim-slot saturation at M0: the solo path owns the
                # doubling ladder — replay the member whole (its tenant-ns
                # arena buckets are still authoritative, so no extra state)
                with obstrace.attached(traces[i]):
                    obstrace.annotate(cohort_overflow_replay=True)
                    try:
                        results[i] = self.solve_async(preps[i]["inp"]).result()
                    except Exception as e:  # noqa: BLE001 — per-member
                        results[i] = e
            return results

        return finish

    def _cohort_lane_finish(self, prep, lane, flat_dev, unpack, M0: int):
        """Fetch + decode ONE fused lane — the solo finish() path minus
        resume/checkpoint (fused lanes never resume; solo replays still do)
        and minus the in-place overflow ladder (raises _CohortOverflow so
        the caller replays the member through solve_async)."""
        enc, dims, qinp = prep["enc"], prep["dims"], prep["qinp"]
        S, E, T, G = dims["S"], dims["E"], dims["T"], dims["G"]
        Z, C = dims["Z"], dims["C"]
        with obstrace.span("backend.fetch"):
            flat = np.asarray(flat_dev)
            self.ledger.record_fetch(flat.nbytes)
            f = unpack(flat)
            used = int(f["used"])
            if used >= M0:
                raise _CohortOverflow()
            obstrace.annotate(fetch_bytes=int(flat.nbytes),
                              claim_bucket_final=M0)
        faults.check("solver.decode", tag=enc.tenant_id)
        with obstrace.span("backend.decode"):
            c_mask = _unpack_words(f["c_mask_words"], T)
            c_zone, c_ct = unpack_zc_bits(f["c_zc_bits"], Z, C)
            c_gmask = _unpack_gmask(f["c_gbits"], G)
            if "entries" in f:
                Ep_ = f["Ep"]
                entries_p = f["entries"]
                leftover_p = f["leftover"][:S]
                c_cum = _claim_cum_from_entries(
                    enc, entries_p, f["c_pool"], Ep_, M0
                )
                res = decode_delta(enc, entries_p, leftover_p, E, Ep_,
                                   c_mask, c_zone, c_ct, f["c_pool"],
                                   c_gmask, c_cum, used)
            else:
                res = decode(enc, f["take_e"][:S][:, :E], f["take_c"][:S],
                             f["leftover"][:S], c_mask, c_zone, c_ct,
                             f["c_pool"], c_gmask, f["c_cum"], used)
        if res is None or not min_values_post_check(qinp, res):
            self.stats["fallback_solves"] += 1
            return self.fallback.solve(qinp)
        self.stats["device_solves"] += 1
        SOLVER_SOLVES.inc(backend="device")
        if obsexplain.enabled():
            # same EXPLAIN contract as a cold solo dispatch: the side
            # kernel runs over this lane's device-resident take table, so
            # the captured table is bit-identical to the solo one
            try:
                tbl = self._device_explain(enc, lane)
            except Exception:  # noqa: BLE001 — never fails a solve
                log.exception("explain: cohort device table dispatch failed")
                tbl = None
            obsexplain.capture(qinp, res, "tpu", enc=enc, table=tbl)
        return res

    def _relax_dispatch(self, qinp, items_map, order, dropped):
        """Materialize + encode + dispatch one relax iteration. Returns
        (minp, enc, handle) or None when this iteration cannot run on
        device (non-preference fallback class present / dispatch declined)."""
        import dataclasses

        from . import relax as rx

        pods2 = [
            rx.materialize_pod(p, items_map[p.meta.uid], dropped[p.meta.uid])
            if p.meta.uid in items_map
            else p
            for p in order
        ]
        minp = dataclasses.replace(qinp, pods=pods2, presorted=True)
        with obstrace.span("backend.encode"):
            enc = encode(minp)
        if (
            enc.group_fallback.any()
            or enc.has_topology
            or enc.has_affinity
            or enc.G == 0
        ):
            return None
        handle = self._device_solve_async(enc)
        if handle is None:
            return None
        return minp, enc, handle

    def _relax_solve(self, qinp: SolverInput, items_map, order, dropped,
                     first=None) -> SolverResult:
        """Drive the oracle's per-pod relaxation by whole-solve redispatch:
        each iteration materializes the current per-pod active preference
        sets (in the ORIGINAL pods' FFD order — see relax.py on why) and
        solves on device; the FIRST failing pod with droppable preferences
        left drops its lowest-weight one. Equivalence to the sequential
        oracle is by induction: pods before the relaxed one replay
        identically, the relaxed pod retries under the same state."""
        budget = 1 + sum(len(v) for v in items_map.values())
        n_disp = 0
        for it in range(budget):
            disp = first if (it == 0 and first is not None) else (
                self._relax_dispatch(qinp, items_map, order, dropped)
            )
            if disp is None:
                break
            minp, enc, handle = disp
            n_disp += 1
            out = handle()
            if out is None or not min_values_post_check(minp, out):
                break
            cand = None
            for uid in enc.sorted_uids.tolist():
                if uid in out.errors and dropped.get(uid, 0) < len(
                    items_map.get(uid, ())
                ):
                    cand = uid
                    break
            if cand is None:
                self.stats["device_solves"] += 1
                self.stats["relax_dispatches"] = n_disp
                self.stats["ladder_rungs_used"] = max(
                    dropped.values(), default=0
                )
                SOLVER_SOLVES.inc(backend="device")
                SOLVER_RELAX_DISPATCHES.set(float(n_disp))
                # per-pod relaxation SPLITS original runs (a relaxed pod's
                # materialized signature differs from its unrelaxed twins),
                # so canonicalize fungible-pod assignments over the ORIGINAL
                # pods — the same post-pass ReferenceSolver applies
                final = canonicalize_placements(qinp, out)
                if obsexplain.enabled():
                    # relaxed/materialized runs differ from the original
                    # encode frame, so the table host-derives against the
                    # ORIGINAL input; the rungs each pod dropped ride as a
                    # leg annotation (an execution detail, not a decision
                    # fact — excluded from the parity fingerprint)
                    obsexplain.capture(
                        qinp, final, "tpu",
                        annotations={
                            "relax_dispatches": n_disp,
                            "relax_dropped": {
                                u: r for u, r in dropped.items() if r
                            },
                        },
                    )
                return final
            dropped[cand] += 1
        self.stats["fallback_solves"] += 1
        return self.fallback.solve(qinp)

    # -- device-resident relax ladder ---------------------------------------

    def _ladder_dispatch(self, qinp, items_map, order):
        """Pre-materialize the whole relax ladder and dispatch it as ONE
        kernel launch (ffd_solve_ladder), instead of the host loop's
        dispatch-per-dropped-preference.

        Construction: level-0 materializations of the ordered pods form the
        base runs — identical to the host loop's first iteration. For every
        run whose pods share one ladder (the same (weight, kind, idx) item
        list — relax.py's ORIGINAL-order invariant makes the drop order a
        pure function of it), one GHOST pod per rung l ≥ 1 — the run's
        representative re-materialized with its l lowest-weight preferences
        dropped — is appended AFTER the originals. encode() then interns the
        rung's group tables (signature interning merges a rung with any
        same-spec native group, exactly as the host loop's re-encode
        would), but the run axis is truncated to the original runs before
        dispatch, so a ghost never pours. run_ladder[s, l-1] carries rung
        l's group id, -1 past the run's ladder.

        Decision identity with the host loop, by induction over the scan:
        the host loop drops one preference of the FIRST failing pod per
        redispatch, and every pod before it replays identically (prefix
        stability), so each pod individually walks rungs 0..L until it
        places or exhausts, retrying from rung 0 after any placement (a
        rung placement can open a claim its unrelaxed twins join on the
        host loop's next redispatch). That is exactly the kernel cascade;
        failed attempts never mutate the carry, and identical pods fail
        identically once one exhausts, so the cascade commits the same
        leftovers without re-walking each twin.

        Returns an in-flight dispatch record, or None to use the host loop:
        a run mixing different ladders (a natively-hard pod whose level-0
        signature collides with a materialized one), a ghost signature
        merging into the last original run, a fallback-class encode, or
        unpackable kernel args."""
        import dataclasses

        from . import relax as rx
        from .encode import _pod_signature

        pods0 = [
            rx.materialize_pod(p, items_map[p.meta.uid], 0)
            if p.meta.uid in items_map
            else p
            for p in order
        ]
        n_orig = len(pods0)
        if n_orig == 0:
            return None
        sigs = [_pod_signature(p) for p in pods0]
        runs: List[List[int]] = []  # [start, count]
        for i, sg in enumerate(sigs):
            if runs and sg == sigs[i - 1]:
                runs[-1][1] += 1
            else:
                runs.append([i, 1])
        ladders = []
        for start, cnt in runs:
            keys = {
                tuple(
                    (w, k, ix)
                    for (w, k, _t, ix) in items_map.get(order[j].meta.uid, ())
                )
                for j in range(start, start + cnt)
            }
            if len(keys) != 1:
                return None  # mixed ladder within one run — host loop
            ladders.append(next(iter(keys)))
        ghosts = []
        ghost_of = []  # (run_idx, rung_level) per ghost
        for ri, (start, cnt) in enumerate(runs):
            items = items_map.get(order[start].meta.uid, ())
            if not items:
                continue
            rep = order[start]
            for lvl in range(1, len(items) + 1):
                gp = rx.materialize_pod(rep, items, lvl)
                gp = dataclasses.replace(
                    gp,
                    meta=dataclasses.replace(
                        gp.meta,
                        name=f"~rung-{lvl}-{rep.meta.name}",
                        uid=f"~rung:{rep.meta.uid}:{lvl}",
                    ),
                )
                ghosts.append(gp)
                ghost_of.append((ri, lvl))
        if not ghosts:
            return None
        minp = dataclasses.replace(qinp, pods=pods0 + ghosts, presorted=True)
        with obstrace.span("backend.encode"):
            enc = encode(minp)
        if (
            enc.group_fallback.any()
            or enc.has_topology
            or enc.has_affinity
            or enc.G == 0
        ):
            return None
        rc = np.asarray(enc.run_count)
        rg = np.asarray(enc.run_group)
        cum = np.cumsum(rc)
        bidx = int(np.searchsorted(cum, n_orig))
        if bidx >= len(rc) or int(cum[bidx]) != n_orig:
            return None  # a ghost merged into the last original run
        S_orig = bidx + 1
        if S_orig != len(runs) or not np.array_equal(
            rc[:S_orig], np.asarray([c for _, c in runs], dtype=rc.dtype)
        ):
            return None  # encode split the originals differently
        if str(enc.sorted_uids[n_orig]) != ghosts[0].meta.uid:
            return None  # presorted order not preserved — don't guess
        pod_run = np.repeat(np.arange(len(rc)), rc)
        Lmax = max(len(l) for l in ladders)
        Lp = self._bucket(Lmax, 2, 2)
        ladder_rows = np.full((S_orig, Lp), -1, np.int32)
        for j, (ri, lvl) in enumerate(ghost_of):
            ladder_rows[ri, lvl - 1] = rg[pod_run[n_orig + j]]
        # truncated view: run axis = original runs only; the group axis (and
        # group_pods, for decode's requirement unions) keeps the rung groups
        enc2 = dataclasses.replace(
            enc,
            run_group=np.ascontiguousarray(rg[:S_orig]),
            run_count=np.ascontiguousarray(rc[:S_orig]),
            sorted_uids=enc.sorted_uids[:n_orig],
        )
        try:
            host_args, dims, prov = host_kernel_args(enc2, self._bucket)
        except UnpackableInput:
            return None
        self.ledger.begin_solve()
        with obstrace.span("backend.upload"):
            if self.arena is not None:
                args = self.arena.adopt(host_args, prov, ns=enc2.tenant_id)
            else:
                args = _device_args(host_args, prov, ledger=self.ledger)
            Sp = int(host_args[0].shape[0])
            lad_host = np.full((Sp, Lp), -1, np.int32)
            lad_host[:S_orig] = ladder_rows
            dev_lad = self._ladder_arg(host_args, lad_host,
                                       ns=enc2.tenant_id)
            sparse_dev = None
            if self._sparse_gate(enc2):
                from .encode import sparse_run_tables

                sq, sv = sparse_run_tables(
                    enc2, Sp, run_ladder=lad_host[:S_orig])
                sparse_dev = self._sparse_arg(host_args, enc2, sq, sv,
                                              ns=enc2.tenant_id)
        M0 = initial_claim_bucket(n_orig, self.max_claims)
        obstrace.annotate(ladder=True, ladder_rungs=int(Lmax),
                          claim_bucket=M0)
        with obstrace.span("backend.dispatch"):
            flat_dev, unpack, _ = self._ladder_kernel(enc2, dev_lad, args, M0,
                                                      n_orig,
                                                      sparse=sparse_dev)
        return {
            "enc": enc2,
            "args": args,
            "dev_lad": dev_lad,
            "flat_dev": flat_dev,
            "unpack": unpack,
            "dims": dims,
            "M0": M0,
            "n_orig": n_orig,
            "rungs": int(Lmax),
            "sparse": sparse_dev,
        }

    def _ladder_arg(self, host_args, lad_host: np.ndarray, ns=None):
        """Upload (or reuse) the run_ladder table. Ladder rungs are a
        per-bucket arena residency class like checkpoints (solver/arena.py
        _ladders): keyed by the arg bucket + a content digest, dropped by
        invalidate() together with buffers and the checkpoint ring — a
        fallback replay can never reuse a stale ladder."""
        import jax

        if self.arena is not None:
            key = self.arena.bucket_key(host_args, ns=ns)
            dev = self.arena.get_ladder(key, lad_host)
            if dev is not None:
                return dev
            dev = jax.device_put(lad_host)
            self.ledger.record_upload(lad_host.nbytes, 1, msgs=1)
            self.arena.put_ladder(key, lad_host, dev)
            return dev
        dev = jax.device_put(lad_host)
        self.ledger.record_upload(lad_host.nbytes, 1, msgs=1)
        return dev

    def _ladder_kernel(self, enc: EncodedInput, dev_lad, args, M: int,
                       n_orig: int, sparse=None):
        from .tpu.ffd import ffd_solve_ladder, ffd_solve_ladder_sparse

        faults.check("solver.device_dispatch")
        if sparse is not None:
            self.stats["sparse_dispatches"] += 1
            out = ffd_solve_ladder_sparse(
                dev_lad, sparse[0], sparse[1], *args,
                max_claims=M, zone_engine=enc.V > 0)
        else:
            out = ffd_solve_ladder(dev_lad, *args, max_claims=M,
                                   zone_engine=enc.V > 0)
        flat_dev, unpack = self._pack_dispatch(out, total_pods=n_orig)
        return flat_dev, unpack, out

    def _ladder_finish(self, qinp: SolverInput, items_map, order,
                       lad) -> SolverResult:
        """Fetch + decode the ladder dispatch. Any failure to stand the
        result up (claim overflow past max_claims, min-values violation)
        replays on the host relax loop, which itself degrades to the
        fallback chain — the ladder only ever SHORTCUTS the host loop."""
        enc, dims = lad["enc"], lad["dims"]
        res = None
        try:
            M = lad["M0"]
            up = lad["unpack"]
            flat = np.asarray(lad["flat_dev"])
            self.ledger.record_fetch(flat.nbytes)
            f = None
            while True:
                f = up(flat)
                used = int(f["used"])
                if used < M:
                    break
                if M >= self.max_claims:
                    f = None  # true overflow — host loop replay
                    break
                M = min(M * 2, self.max_claims)
                fd, up, _ = self._ladder_kernel(
                    enc, lad["dev_lad"], lad["args"], M, lad["n_orig"],
                    sparse=lad.get("sparse"),
                )
                flat = np.asarray(fd)
                self.ledger.record_fetch(flat.nbytes)
            if f is not None:
                faults.check("solver.decode")
                S, E = dims["S"], dims["E"]
                T, G, Z, C = dims["T"], dims["G"], dims["Z"], dims["C"]
                c_mask = _unpack_words(f["c_mask_words"], T)
                c_zone, c_ct = unpack_zc_bits(f["c_zc_bits"], Z, C)
                c_gmask = _unpack_gmask(f["c_gbits"], G)
                if "entries" in f:
                    # rung pours charge the base group's requests (relaxation
                    # drops preferences, never resources), so the c_cum
                    # rebuild over run_group is exact on the ladder too
                    c_cum = _claim_cum_from_entries(
                        enc, f["entries"], f["c_pool"], f["Ep"], M
                    )
                    res = decode_delta(
                        enc, f["entries"], f["leftover"][:S], E, f["Ep"],
                        c_mask, c_zone, c_ct, f["c_pool"], c_gmask,
                        c_cum, used,
                    )
                else:
                    res = decode(
                        enc, f["take_e"][:S, :E], f["take_c"][:S],
                        f["leftover"][:S], c_mask, c_zone, c_ct,
                        f["c_pool"], c_gmask, f["c_cum"], used,
                    )
        finally:
            self.ledger.end_solve()
        if res is not None and min_values_post_check(qinp, res):
            self.stats["device_solves"] += 1
            self.stats["ladder_solves"] += 1
            self.stats["relax_dispatches"] = 1
            self.stats["ladder_rungs_used"] = lad["rungs"]
            SOLVER_SOLVES.inc(backend="device")
            SOLVER_RELAX_DISPATCHES.set(1.0)
            final = canonicalize_placements(qinp, res)
            if obsexplain.enabled():
                # same frame rule as _relax_solve: table host-derives
                # against the original input (the ladder enc carries ghost
                # rung groups); rung count is a leg annotation
                obsexplain.capture(
                    qinp, final, "tpu",
                    annotations={"relax_dispatches": 1,
                                 "ladder_rungs": lad["rungs"]},
                )
            return final
        dropped = {u: 0 for u in items_map}
        return self._relax_solve(qinp, items_map, order, dropped, None)

    def warmup(self, instance_types, zones, capacity_types=("on-demand", "spot"),
               pod_presets=(12, 600), with_zone_spread=True) -> int:
        """Pre-compile the standard shape buckets so the first production
        solve is not a 6-15s compile stall (VERDICT r3 next #3). Each preset
        solves a synthetic single-pool surge shaped to the production
        bucketing (Sp/Gp floors, M doubling ladder); with_zone_spread also
        compiles the zone-engine variant. Compilations land in the in-process
        jit cache and the persistent compilation cache. Returns the number of
        warm solves executed; call from a background thread at operator start
        (operator.py) so boot isn't blocked."""
        from ..api import wellknown as wk
        from ..api.objects import ObjectMeta, Pod, TopologySpreadConstraint
        from ..provisioning.scheduler import NodePoolSpec, SolverInput
        from ..scheduling.requirements import IN, Requirement, Requirements

        pool = NodePoolSpec(
            name="warmup",
            weight=0,
            requirements=Requirements.of(
                Requirement.create(wk.NODEPOOL_LABEL, IN, ["warmup"])
            ),
            taints=[],
            instance_types=list(instance_types),
        )
        sizes = [("100m", "128Mi"), ("250m", "512Mi"), ("500m", "1Gi"),
                 ("1", "2Gi"), ("2", "4Gi"), ("4", "8Gi")]
        from ..utils.resources import Resources

        n_warm = 0
        for n in pod_presets:
            pods = [
                Pod(
                    meta=ObjectMeta(name=f"wu{i:05d}", uid=f"wu{i:05d}"),
                    requests=Resources.parse(dict(zip(("cpu", "memory"), sizes[i % len(sizes)]))),
                )
                for i in range(n)
            ]
            self.solve(SolverInput(pods=pods, nodes=[], nodepools=[pool],
                                   zones=tuple(zones), capacity_types=tuple(capacity_types)))
            n_warm += 1
        if with_zone_spread and zones:
            tsc = TopologySpreadConstraint(
                max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "wu"}
            )
            pods = [
                Pod(
                    meta=ObjectMeta(name=f"wz{i:05d}", uid=f"wz{i:05d}",
                                    labels={"app": "wu"}),
                    requests=Resources.parse({"cpu": "500m", "memory": "1Gi"}),
                    topology_spread=[tsc],
                )
                for i in range(12)
            ]
            self.solve(SolverInput(pods=pods, nodes=[], nodepools=[pool],
                                   zones=tuple(zones), capacity_types=tuple(capacity_types)))
            n_warm += 1
        return n_warm

    def prewarm_aot(self, instance_types, zones,
                    capacity_types=("on-demand", "spot"),
                    expected_pods: int = 50_000, with_zone_engine: bool = True,
                    claim_buckets=None) -> int:
        """Ahead-of-time compile the kernel's bucket lattice WITHOUT running
        solves: lower `ffd_solve` on ShapeDtypeStructs for every claim bucket
        the configured scale can reach (initial_claim_bucket ladder +
        overflow doublings to max_claims) and compile. Unlike warmup() this
        executes nothing on device and covers the M ladder in one pass; the
        compilations land in the persistent compilation cache (operator
        options `compile_cache_dir` wires jax_compilation_cache_dir), so
        production dispatches — including overflow retries — skip XLA
        compilation even in a fresh process.

        Returns the number of lattice points compiled (0 when the shape
        table drifted from kernel_args — the guard refuses to compile shapes
        production would never request)."""
        import jax

        from ..api import wellknown as wk
        from ..api.objects import ObjectMeta, Pod
        from ..provisioning.scheduler import NodePoolSpec, SolverInput
        from ..scheduling.requirements import IN, Requirement, Requirements
        from ..utils.resources import Resources
        from .encode import encode, quantize_input
        from .tpu.ffd import ARG_SPEC, ffd_solve

        # one tiny encode against the REAL catalog fixes every
        # catalog-derived bucket (Tp/Pp/R/Z/C) and all arg dtypes
        pool = NodePoolSpec(
            name="prewarm", weight=0,
            requirements=Requirements.of(
                Requirement.create(wk.NODEPOOL_LABEL, IN, ["prewarm"])
            ),
            taints=[], instance_types=list(instance_types),
        )
        pods = [
            Pod(meta=ObjectMeta(name=f"pw{i:03d}", uid=f"pw{i:03d}"),
                requests=Resources.parse({"cpu": "100m", "memory": "128Mi"}))
            for i in range(4)
        ]
        enc = encode(quantize_input(SolverInput(
            pods=pods, nodes=[], nodepools=[pool],
            zones=tuple(zones), capacity_types=tuple(capacity_types),
        )))
        from ..obs import telemetry as obstelemetry

        try:
            args0, dims = kernel_args(enc, self._bucket)
        except UnpackableInput as e:
            obstelemetry.note_prewarm_failure("encode", e)
            obstelemetry.note_prewarm(1, 0)
            return 0
        dims = dict(dims)
        dims["D"] = int(args0[ARG_SPEC.index("zone_col_mask")].shape[0])
        for i, name in enumerate(ARG_SPEC):
            if tuple(args0[i].shape) != tuple(dims[s] for s in _AOT_SHAPES[name]):
                # table out of sync with kernel_args — never compile shapes
                # production would not request; surfaced as zero coverage
                obstelemetry.note_prewarm_failure(
                    "shape_table", f"{name} drifted from _AOT_SHAPES")
                obstelemetry.note_prewarm(1, 0)
                return 0
        if claim_buckets is None:
            mc = self.max_claims
            # initial buckets for small/medium/configured surges, plus the
            # overflow-retry ceiling (doubling always ends at max_claims)
            claim_buckets = sorted({
                initial_claim_bucket(64, mc),
                initial_claim_bucket(600, mc),
                initial_claim_bucket(int(expected_pods), mc),
                max(mc, 64),
            })
        specs = tuple(
            jax.ShapeDtypeStruct(
                tuple(dims[s] for s in _AOT_SHAPES[name]), args0[i].dtype
            )
            for i, name in enumerate(ARG_SPEC)
        )
        import jax.numpy as jnp

        from .tpu.ffd import FFDState, ffd_resume, ffd_solve_ckpt

        idx = {name: i for i, name in enumerate(ARG_SPEC)}
        E, R = specs[idx["node_free"]].shape
        T = specs[idx["group_compat_t"]].shape[1]
        P = specs[idx["pool_type"]].shape[0]
        Q = specs[idx["q_kind"]].shape[0]
        V = specs[idx["v_kind"]].shape[0]
        D = specs[idx["zone_col_mask"]].shape[0]
        W = specs[idx["group_pair_nok"]].shape[1]

        def state_spec(M):
            sds = jax.ShapeDtypeStruct
            return FFDState(
                e_cum=sds((E, R), jnp.int32), c_cum=sds((M, R), jnp.int32),
                c_mask=sds((M, T), jnp.bool_),
                c_zc_bits=sds((M,), jnp.uint32),
                c_gbits=sds((M, W), jnp.uint32), c_pool=sds((M,), jnp.int32),
                used=sds((), jnp.int32), p_usage=sds((P, R), jnp.int32),
                e_cm=sds((E, Q), jnp.int32), e_co=sds((E, Q), jnp.int32),
                c_cm=sds((M, Q), jnp.int32), c_co=sds((M, Q), jnp.int32),
                v_count=sds((V, D), jnp.int32),
                v_owner_z=sds((V, D), jnp.bool_),
                c_vm=sds((M, V), jnp.int32), c_vo=sds((M, V), jnp.bool_),
            )

        # the steady-state resume dispatch runs over the smallest suffix
        # bucket (16 runs) — that is the shape a warm append-tail re-solve
        # requests
        resume_specs = tuple(
            jax.ShapeDtypeStruct((16,), s.dtype) if i < 2 else s
            for i, s in enumerate(specs)
        )
        # lattice points requested, in the unit `n` counts: the compile
        # observability coverage gauge is compiled/requested — <1.0 when a
        # compile failed or the sharded leg was cut short (/healthz WARN)
        requested = len(claim_buckets) * (2 if with_zone_engine else 1)
        n = 0
        for M in claim_buckets:
            for ze in (False, True) if with_zone_engine else (False,):
                try:
                    ffd_solve.lower(
                        *specs, max_claims=int(M), zone_engine=ze
                    ).compile()
                    if self.resume:
                        ck = dict(ckpt_every=self.ckpt_every,
                                  n_ckpt=self.ckpt_slots)
                        ffd_solve_ckpt.lower(
                            *specs, max_claims=int(M), zone_engine=ze, **ck
                        ).compile()
                        ffd_resume.lower(
                            state_spec(int(M)), *resume_specs,
                            max_claims=int(M), zone_engine=ze, **ck
                        ).compile()
                except Exception as e:
                    # a compile failure would repeat at every point — stop,
                    # but COUNT it: the old silent best-effort return left a
                    # broken compile cache to show up as mystery hot-path
                    # compiles at the first production dispatch
                    obstelemetry.note_prewarm_failure(
                        f"M={int(M)},zone_engine={ze}", e)
                    obstelemetry.note_prewarm(requested, n)
                    return n
                n += 1
        mesh = self._shard_mesh()
        if mesh is not None:
            # mesh-sharded entry point: lower once per mesh (keyed on the
            # device set — a resized slice must relower) with sharding-
            # carrying ShapeDtypeStructs so the AOT executable bakes in the
            # same GSPMD partitioning production dispatches request.
            # zone_engine=True lanes (V>0 fleets shard since the sparse
            # constraint engine lifted the V/Q decline) compile on first
            # dispatch — the zoned sharded bucket is rare enough that
            # prewarming it would double this loop for cold rigs.
            token = tuple(int(d.id) for d in mesh.devices.flat)
            Nd = int(mesh.devices.size)
            Sp = specs[0].shape[0]
            if token not in self._shard_prewarmed and Sp % Nd == 0:
                requested += len(claim_buckets)
                try:
                    from jax.sharding import NamedSharding, PartitionSpec

                    from .tpu.ffd import ffd_solve_sharded

                    blocked = NamedSharding(mesh,
                                            PartitionSpec("shards", None))
                    repl = NamedSharding(mesh, PartitionSpec())
                    sh_specs = tuple(
                        jax.ShapeDtypeStruct((Nd, Sp // Nd), s.dtype,
                                             sharding=blocked)
                        if i < 2 else
                        jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=repl)
                        for i, s in enumerate(specs)
                    )
                    for M in claim_buckets:
                        ffd_solve_sharded.lower(
                            *sh_specs, max_claims=int(M), zone_engine=False
                        ).compile()
                        n += 1
                    self._shard_prewarmed.add(token)
                except Exception as e:
                    obstelemetry.note_prewarm_failure(f"sharded:{token}", e)
                    obstelemetry.note_prewarm(requested, n)
                    return n
        obstelemetry.note_prewarm(requested, n)
        return n

    # -- device path --------------------------------------------------------

    @staticmethod
    def _bucket(n: int, mult: int, floor: int) -> int:
        """Round up to a multiple of `mult` (min `floor`) — bounds the number
        of distinct compiled shapes (SURVEY.md §7 hard parts: bucketed padding
        avoids recompilation storms)."""
        return max(floor, ((n + mult - 1) // mult) * mult)

    def _sparse_gate(self, enc: EncodedInput) -> bool:
        """Whether this solve evaluates constraints through the compacted
        V/Q index tables (SPEC.md "Sparse constraint semantics")."""
        if self.sparse == "off":
            return False
        from .encode import use_sparse_constraints

        if self.sparse == "on":
            return (enc.Q + enc.V) > 0
        return use_sparse_constraints(enc)

    def _sparse_arg(self, host_args, enc: EncodedInput,
                    run_q_idx: np.ndarray, run_v_idx: np.ndarray,
                    sharding=None, dev_sharding=None, ns=None):
        """Upload (or reuse) the sparse constraint index pair. Like
        run_ladder tables, the pair is a per-bucket arena side-residency
        class (solver/arena.py _sparse) keyed by the arg bucket + a
        staleness token of (encode core rev, content digests) — the core
        rev is the delta-upload anchor: a patch-hit re-encode keeps the
        rev, so try_patch solves ship zero sparse-table bytes."""
        import jax

        if self.arena is not None:
            key = self.arena.bucket_key(host_args, sharding, ns=ns)
            dev = self.arena.get_sparse(key, enc.core_rev, run_q_idx,
                                        run_v_idx)
            if dev is not None:
                return dev
            dev = (jax.device_put(run_q_idx, dev_sharding),
                   jax.device_put(run_v_idx, dev_sharding))
            self.ledger.record_upload(
                run_q_idx.nbytes + run_v_idx.nbytes, 2, msgs=2)
            self.arena.put_sparse(key, enc.core_rev, run_q_idx, run_v_idx,
                                  dev)
            return dev
        dev = (jax.device_put(run_q_idx, dev_sharding),
               jax.device_put(run_v_idx, dev_sharding))
        self.ledger.record_upload(
            run_q_idx.nbytes + run_v_idx.nbytes, 2, msgs=2)
        return dev

    def _dispatch(self, enc: EncodedInput, args, M: int, harvest: bool = False,
                  total_pods: Optional[int] = None, sparse=None):
        """Dispatch kernel + output packing; start the device→host copy.
        Returns (flat_device_array, unpack_fn, out, ring). `harvest` (and
        the resume knob) selects ffd_solve_ckpt so the solve also produces
        a device-resident checkpoint ring for later suffix resumes — the
        ring never crosses the tunnel. `sparse` is the device-resident
        (run_q_idx, run_v_idx) pair, or None for dense V/Q evaluation."""
        from .tpu.ffd import (
            ffd_solve,
            ffd_solve_ckpt,
            ffd_solve_ckpt_sparse,
            ffd_solve_sparse,
        )

        faults.check("solver.device_dispatch")
        ring = None
        if sparse is not None:
            self.stats["sparse_dispatches"] += 1
        if harvest and self.resume:
            if sparse is not None:
                out, ring = ffd_solve_ckpt_sparse(
                    sparse[0], sparse[1], *args,
                    max_claims=M, zone_engine=enc.V > 0,
                    ckpt_every=self.ckpt_every, n_ckpt=self.ckpt_slots,
                )
            else:
                out, ring = ffd_solve_ckpt(
                    *args, max_claims=M, zone_engine=enc.V > 0,
                    ckpt_every=self.ckpt_every, n_ckpt=self.ckpt_slots,
                )
        elif sparse is not None:
            out = ffd_solve_sparse(sparse[0], sparse[1], *args,
                                   max_claims=M, zone_engine=enc.V > 0)
        else:
            out = ffd_solve(*args, max_claims=M, zone_engine=enc.V > 0)
        flat_dev, unpack = self._pack_dispatch(out, total_pods=total_pods)
        return flat_dev, unpack, out, ring

    def _pack_dispatch(self, out, total_pods: Optional[int] = None):
        # ONE device→host transfer: all outputs packed into a single
        # int32 buffer on device (bit-packed masks, uint16 takes), so the
        # tunnel pays one roundtrip per solve — not one per output array
        # (VERDICT r2 'what's weak' #1: 9 sync fetches dominated the seam).
        # With the device-decode knob on (and a known pod count), the take
        # tables additionally compact on device to a claim-delta (tpu/
        # ffd.compact_takes) — O(actual placements) uint16 instead of
        # O(S×E + S×M) — with the overflow flag re-fetching wide. uint16
        # run/code coding caps the delta path at 65535 runs and a combined
        # node+claim axis of 65536; larger shapes keep the dense packing.
        Sp, Ep = out.take_e.shape
        Mb, Tp = out.state.c_mask.shape
        Wm = (Tp + 31) // 32
        Wg = out.state.c_gbits.shape[1]
        Rr = out.state.c_cum.shape[1]

        wide_shapes = {
            "take_e": ((Sp, Ep), "i32"),
            "take_c": ((Sp, Mb), "i32"),
            "leftover": ((Sp,), "i32"),
            "c_mask_words": ((Mb, Wm), "u32"),
            "c_zc_bits": ((Mb,), "u32"),
            "c_gbits": ((Mb, Wg), "u32"),
            "c_pool": ((Mb,), "i32"),
            "c_cum": ((Mb, Rr), "i32"),
            "used": ((), "i32"),
        }
        tail_shapes = {
            "leftover": ((Sp,), "i32"),
            "c_mask_words": ((Mb, Wm), "u32"),
            "c_zc_bits": ((Mb,), "u32"),
            "c_gbits": ((Mb, Wg), "u32"),
            "c_pool": ((Mb,), "i32"),
            "c_cum": ((Mb, Rr), "i32"),
            "used": ((), "i32"),
        }

        ledger = self.ledger
        use_delta = (
            self.device_decode
            and total_pods is not None
            and Sp <= 65535
            and Ep + Mb <= 65535
        )

        if use_delta:
            cap = delta_capacity(total_pods, Sp, Ep, Mb)
            cap_u = delta_uniq_capacity(Sp, Mb)
            Wt = Wm + 1 + Wg + 1  # meta row: cm_words ++ zc ++ gbits ++ pool

            def unpack(flat: np.ndarray) -> dict:
                if flat[0]:  # uint16/capacity overflow — re-fetch wide (rare)
                    SOLVER_WIDE_REFETCH.inc()
                    self.stats["wide_refetches"] += 1
                    wide = np.asarray(_pack_outputs_wide(out))
                    ledger.record_fetch(wide.nbytes)
                    return _unpack_flat(wide, wide_shapes)
                n = int(flat[1])
                off = 3
                cnt = flat[off : off + Sp // 2].view(np.uint16)[:Sp]
                off += Sp // 2
                pairs = (
                    flat[off : off + cap].view(np.uint16).reshape(cap, 2)
                )
                off += cap
                leftover = flat[off : off + Sp]
                off += Sp
                uniq = (
                    flat[off : off + cap_u * Wt]
                    .view(np.uint32)
                    .reshape(cap_u, Wt)
                )
                off += cap_u * Wt
                mid = flat[off : off + Mb // 2].view(np.uint16)[:Mb]
                off += Mb // 2
                used = flat[off]
                # entries: run-major (code, count) pairs + per-run counts
                # rebuild the run column with one repeat
                s_col = np.repeat(
                    np.arange(Sp, dtype=np.int64), cnt.astype(np.int64)
                )
                entries = np.stack(
                    [
                        s_col,
                        pairs[:n, 0].astype(np.int64),
                        pairs[:n, 1].astype(np.int64),
                    ],
                    axis=1,
                )
                # expand the deduped claim-identity rows back to [Mb]
                meta = uniq[np.minimum(mid.astype(np.int64), cap_u - 1)]
                c_pool = (
                    np.ascontiguousarray(meta[:, Wt - 1]).view(np.int32)
                )
                return {
                    "entries": entries,
                    "Ep": Ep,
                    "leftover": leftover,
                    "c_mask_words": meta[:, :Wm],
                    "c_zc_bits": np.ascontiguousarray(meta[:, Wm]),
                    "c_gbits": np.ascontiguousarray(
                        meta[:, Wm + 1 : Wm + 1 + Wg]
                    ),
                    "c_pool": c_pool,
                    "used": used,
                }

            flat_dev = _pack_outputs_delta(out, cap, cap_u)
        else:

            def unpack(flat: np.ndarray) -> dict:
                if flat[0]:  # take overflowed uint16 — re-fetch full width
                    SOLVER_WIDE_REFETCH.inc()
                    self.stats["wide_refetches"] += 1
                    wide = np.asarray(_pack_outputs_wide(out))
                    ledger.record_fetch(wide.nbytes)
                    return _unpack_flat(wide, wide_shapes)
                off = 1
                f = {}
                for name, (sh, n) in (
                    ("take_e", ((Sp, Ep), Sp * Ep)),
                    ("take_c", ((Sp, Mb), Sp * Mb)),
                ):
                    w = (n + 1) // 2
                    f[name] = (
                        flat[off : off + w]
                        .view(np.uint16)[:n]
                        .astype(np.int32)
                        .reshape(sh)
                    )
                    off += w
                f.update(_unpack_flat(flat[off:], tail_shapes))
                return f

            flat_dev = _pack_outputs(out)
        try:
            flat_dev.copy_to_host_async()
        except AttributeError:
            pass  # backend without async host copies: asarray will block
        return flat_dev, unpack

    def _device_explain(self, enc: EncodedInput, out):
        """Dispatch the EXPLAIN side kernel (tpu/ffd.explain_pack) over the
        solve's device-resident take table plus the host-built side tables
        (encode.explain_tables), fetch the int32 wire buffer through the
        transfer ledger, and decode the real-group prefix. Returns
        (n_rejected, words) or None when the node axis overflows the uint16
        entry half — the host deriver recomputes at full width, counted by
        SOLVER_EXPLAIN_WIDE (same carve-out discipline as the claim-delta
        wide refetch). The group axis pads to a power of two so the jit
        cache stays bounded; Z/C widths pad to >= 1 with all-False columns,
        the same rule the numpy twin applies, keeping the tables bit-equal."""
        from .tpu.ffd import explain_pack, unpack_explain
        from .encode import explain_tables

        take_e = out.take_e
        Sp, Ep = int(take_e.shape[0]), int(take_e.shape[1])
        if Ep > 0xFFFF:
            SOLVER_EXPLAIN_WIDE.inc()
            return None
        t = explain_tables(enc)
        G = int(t["group_req"].shape[0])
        E = int(t["node_free"].shape[0])
        R = int(t["group_req"].shape[1])
        S = int(t["run_group"].shape[0])
        Gp = 1 << (max(G, 1) - 1).bit_length()
        gz = np.asarray(t["group_zone"], bool).reshape(G, -1)
        gc = np.asarray(t["group_ct"], bool).reshape(G, -1)
        Z, C = max(1, gz.shape[1]), max(1, gc.shape[1])
        run_group = np.zeros(Sp, dtype=np.int32)
        run_group[:S] = t["run_group"]
        group_req = np.zeros((Gp, R), dtype=np.int32)
        group_req[:G] = t["group_req"]
        node_free = np.zeros((Ep, R), dtype=np.int32)
        node_free[:E] = t["node_free"]
        node_compat = np.zeros((Gp, Ep), dtype=bool)
        node_compat[:G, :E] = t["node_compat"]
        node_zone = np.full(Ep, -1, dtype=np.int32)
        node_zone[:E] = t["node_zone"]
        node_ct = np.full(Ep, -1, dtype=np.int32)
        node_ct[:E] = t["node_ct"]
        group_zone = np.zeros((Gp, Z), dtype=bool)
        group_zone[:G, : gz.shape[1]] = gz
        group_ct = np.zeros((Gp, C), dtype=bool)
        group_ct[:G, : gc.shape[1]] = gc
        group_topo = np.zeros(Gp, dtype=bool)
        group_topo[:G] = t["group_topo"]
        group_aff = np.zeros(Gp, dtype=bool)
        group_aff[:G] = t["group_aff"]
        k = obsexplain.top_k()
        flat = np.asarray(explain_pack(
            take_e, run_group, group_req, node_free, node_compat,
            node_zone, node_ct, group_zone, group_ct, group_topo,
            group_aff, np.int32(E), np.int32(G), top_k=k,
        ))
        self.ledger.record_fetch(flat.nbytes)
        SOLVER_EXPLAIN_BYTES.set(float(flat.nbytes))
        overflow, n_rej, words = unpack_explain(flat, G)
        if overflow:
            SOLVER_EXPLAIN_WIDE.inc()
            return None
        return n_rej, words

    def _device_solve_async(self, enc: EncodedInput):
        try:
            host_args, dims, prov = host_kernel_args(enc, self._bucket)
        except UnpackableInput:
            return None  # Z*C > 32 — replay on fallback
        # wedge-class chaos sites (ISSUE 8): device_hang BLOCKS the calling
        # (dispatcher) thread — a hung XLA dispatch, detectable only by a
        # liveness deadline; device_lost raises DeviceLost (unrecoverable
        # by retry on this owner). Both run before any ledger/arena state
        # changes so a wedged solve leaves residency untouched.
        faults.check("solver.device_hang", tag=self.fault_tag)
        faults.check("solver.device_lost", tag=self.fault_tag)
        if self.shards >= 2 or self.host_mesh is not None:
            # mesh-sharded run-axis solve; declines (inexpressible carry
            # combine, no usable mesh, stitch overflow) fall through to the
            # single-device path below — trivially decision-identical
            sharded = self._sharded_solve_async(enc, host_args, dims, prov)
            if sharded is not None:
                obstrace.annotate(sharded=True)
                return sharded
        # transfer ledger window: every host→device byte of this solve
        # (arena packed upload OR per-array conversions) and every fetched
        # result byte lands in one per-solve record (solver/arena.py)
        self.ledger.begin_solve()
        with obstrace.span("backend.upload"):
            if self.arena is not None:
                # arena_corrupt chaos site: fires BEFORE residency is trusted —
                # the raised ArenaCorrupt classifies as a device error, the
                # resilience layer invalidates the arena, and the replay (or
                # the re-routed owner) pays one full re-adoption upload
                faults.check("solver.arena_corrupt", tag=self.fault_tag)
                if self.stream_run_events:
                    # streaming stage: scatter run-table edits on device so
                    # the adopt below digest-hits entries 0/1 (zero
                    # run-array upload); a declined stage just falls back
                    # to adopt's normal packed delta — same bytes land
                    staged = self.arena.apply_run_events(
                        host_args, prov, ns=enc.tenant_id)
                    self.stats[
                        "event_stage_hits" if staged else "event_stage_misses"
                    ] += 1
                # device-resident arena: only stale entries upload, packed
                # into ONE buffer; an exact encode-cache hit uploads nothing
                args = self.arena.adopt(host_args, prov, ns=enc.tenant_id)
            else:
                args = _device_args(host_args, prov, ledger=self.ledger)
            sparse_host = None
            sparse_dev = None
            if self._sparse_gate(enc):
                from .encode import sparse_run_tables

                sparse_host = sparse_run_tables(
                    enc, int(host_args[0].shape[0]))
                sparse_dev = self._sparse_arg(
                    host_args, enc, *sparse_host, ns=enc.tenant_id)
        S, E, T, G = dims["S"], dims["E"], dims["T"], dims["G"]
        Z, C = dims["Z"], dims["C"]
        total_pods = int(sum(len(p) for p in enc.group_pods))
        # Claim slots sized from the input with overflow retry: start small
        # (most solves open far fewer claims than pods) and double on
        # saturation — each M is a cached compile bucket, and a too-big M
        # inflates every [M,T] intermediate (VERDICT r1: M=8192 for a
        # 462-claim solve was ~17× wasted bandwidth). Redispatches reuse the
        # same resident device args — no re-upload.
        M0 = initial_claim_bucket(total_pods, self.max_claims)
        plan = self._plan_resume(enc, host_args, M0, S)
        obstrace.annotate(claim_bucket=M0, total_pods=total_pods,
                          resume=plan is not None,
                          resume_k=plan["k"] if plan is not None else 0)
        with obstrace.span("backend.dispatch"):
            if plan is not None:
                flat_dev, unpack, out, ring = self._dispatch_resume(
                    enc, args, host_args, plan, M0, S,
                    total_pods=total_pods, sparse_host=sparse_host,
                )
            else:
                flat_dev, unpack, out, ring = self._dispatch(
                    enc, args, M0, harvest=True, total_pods=total_pods,
                    sparse=sparse_dev,
                )

        def finish() -> Optional[SolverResult]:
            try:
                M = M0
                cur_plan, cur_out, cur_ring = plan, out, ring

                def stash_explain(res):
                    # EXPLAIN side section: cold dispatches only — a resumed
                    # solve's take table is stitched host-side, so the
                    # device rows alone would disagree with the final
                    # decisions; those solves host-derive (carve-out).
                    # Stashed as a plain attribute: solve_async's finish
                    # hands it to obs/explain.capture as the wire table.
                    if res is None or cur_plan is not None:
                        return res
                    if not obsexplain.enabled():
                        return res
                    try:
                        tbl = self._device_explain(enc, cur_out)
                    except Exception:  # noqa: BLE001 — provenance never
                        log.exception(  # fails a solve; host deriver covers
                            "explain: device table dispatch failed")
                        tbl = None
                    if tbl is not None:
                        res._explain_table = tbl
                    return res

                with obstrace.span("backend.fetch"):
                    flat, up = np.asarray(flat_dev), unpack
                    self.ledger.record_fetch(flat.nbytes)
                    while True:
                        f = up(flat)
                        used = int(f["used"])
                        if used < M:
                            break
                        if cur_plan is not None:
                            # a resumed dispatch saturated its claim slots;
                            # the donor record's M no longer matches, so the
                            # retry replays COLD at the doubled bucket (still
                            # against the arena-resident args — no re-upload)
                            cur_plan = None
                        if M >= self.max_claims:
                            return None  # true overflow — replay on fallback
                        M = min(M * 2, self.max_claims)
                        fd, up, cur_out, cur_ring = self._dispatch(
                            enc, args, M, harvest=True,
                            total_pods=total_pods, sparse=sparse_dev,
                        )
                        flat = np.asarray(fd)
                        self.ledger.record_fetch(flat.nbytes)
                    obstrace.annotate(fetch_bytes=int(flat.nbytes),
                                      claim_bucket_final=M)
                faults.check("solver.decode")
                with obstrace.span("backend.decode"):
                    c_mask = _unpack_words(f["c_mask_words"], T)
                    c_zone, c_ct = unpack_zc_bits(f["c_zc_bits"], Z, C)
                    c_gmask = _unpack_gmask(f["c_gbits"], G)
                    if "entries" in f:
                        # delta-decoded fetch: the take tables never crossed
                        # the link. A resumed dispatch splices the donor's
                        # recorded dense prefix rows in as triples (suffix
                        # runs shift by k); decode_delta rebuilds decode()'s
                        # exact codes stream from the merged entry set.
                        Ep_ = f["Ep"]
                        if cur_plan is not None:
                            with obstrace.span("backend.stitch"):
                                k = cur_plan["k"]
                                rec = cur_plan["rec"]
                                pre = _entries_from_dense(
                                    rec["take_e"][:k], rec["take_c"][:k], Ep_
                                )
                                suf = f["entries"].astype(np.int64)
                                suf[:, 0] += k
                                entries_p = np.concatenate([pre, suf])
                                leftover_p = np.concatenate(
                                    [rec["leftover"][:k], f["leftover"][: S - k]]
                                )
                            self.stats["resume_solves"] += 1
                            self.stats["resume_runs_skipped"] += k
                            SOLVER_RUNS_SKIPPED.inc(k)
                        else:
                            entries_p = f["entries"]
                            leftover_p = f["leftover"][:S]
                        c_cum = _claim_cum_from_entries(
                            enc, entries_p, f["c_pool"], Ep_, M
                        )
                        res = decode_delta(enc, entries_p, leftover_p, E, Ep_,
                                           c_mask, c_zone, c_ct, f["c_pool"],
                                           c_gmask, c_cum, used)
                        if self.resume:
                            # the resume donor record stays DENSE (its
                            # stitching contract predates the delta path);
                            # reconstruct the rows host-side — same bytes a
                            # dense fetch carries
                            take_e_p, take_c_p = _dense_from_entries(
                                entries_p, S, Ep_, M
                            )
                            self._record_checkpoint(
                                enc, host_args, M, S, cur_plan, cur_out,
                                cur_ring, take_e_p, take_c_p, leftover_p,
                            )
                        SOLVER_RESUME_HIT_RATE.set(self.resume_hit_rate)
                        return stash_explain(res)
                    if cur_plan is not None:
                        # suffix dispatch: rows [0:k] of the full take tables
                        # are the donor record's (decision-identical by
                        # construction — the checkpoint IS the carry after
                        # those rows), rows [k:S] come from this dispatch.
                        # State outputs (c_*) need no stitching: the suffix's
                        # final state equals a full replay's.
                        with obstrace.span("backend.stitch"):
                            k = cur_plan["k"]
                            rec = cur_plan["rec"]
                            take_e_p = np.concatenate(
                                [rec["take_e"][:k], f["take_e"][: S - k]]
                            )
                            take_c_p = np.concatenate(
                                [rec["take_c"][:k], f["take_c"][: S - k]]
                            )
                            leftover_p = np.concatenate(
                                [rec["leftover"][:k], f["leftover"][: S - k]]
                            )
                        self.stats["resume_solves"] += 1
                        self.stats["resume_runs_skipped"] += k
                        SOLVER_RUNS_SKIPPED.inc(k)
                    else:
                        take_e_p = f["take_e"][:S]
                        take_c_p = f["take_c"][:S]
                        leftover_p = f["leftover"][:S]
                    res = decode(enc, take_e_p[:, :E], take_c_p,
                                 leftover_p, c_mask,
                                 c_zone, c_ct, f["c_pool"], c_gmask,
                                 f["c_cum"], used)
                    self._record_checkpoint(
                        enc, host_args, M, S, cur_plan, cur_out, cur_ring,
                        take_e_p, take_c_p, leftover_p,
                    )
                    SOLVER_RESUME_HIT_RATE.set(self.resume_hit_rate)
                    return stash_explain(res)
            finally:
                self.ledger.end_solve()

        return finish

    @property
    def resume_hit_rate(self) -> float:
        """Fraction of device dispatches that resumed from a checkpoint."""
        return self.stats["resume_solves"] / max(1, self.ledger.solves)

    def _plan_resume(self, enc: EncodedInput, host_args, M0: int, S: int):
        """Pick the newest valid checkpoint for this dispatch, or None.

        Prefix validity (SPEC.md "Resume semantics"): (a) a record exists
        for the CURRENT arena bucket (same padded shapes ⇒ same compile
        bucket as the donor), (b) every non-run kernel arg is byte-identical
        to the donor's (arena context signature — the node-table-revision
        leg), (c) the donor/current run lists share a common prefix of
        (snum, group, count) triples, (d) the donor's claim bucket and
        zone-engine static match this dispatch. The chosen checkpoint is
        the one covering the most runs within the common prefix; the
        donor's device-resident FINAL state (covering its whole run list)
        wins on pure appends regardless of the ring interval."""
        if not self.resume or self.arena is None:
            return None
        from . import encode_cache as ec
        from .tpu.ffd import ARG_INDEX

        run_idx = (ARG_INDEX["run_group"], ARG_INDEX["run_count"])
        key = self.arena.bucket_key(host_args, ns=enc.tenant_id)
        recs = self.arena.get_checkpoints(key)
        if not recs:
            return None
        rec = recs[0]
        if rec["M"] != M0 or rec["zone_engine"] != (enc.V > 0):
            return None
        ctx = self.arena.context_signature(key, exclude=run_idx)
        if ctx is None or ctx != rec["ctx_sig"]:
            return None  # node/pool/core tables moved since the donor solve
        cur = ec.run_identity(enc)
        if not cur or len(cur) != S:
            return None  # signatures not interned — prefixes not comparable
        lcp = ec.run_lcp(rec["run_ident"], cur)
        if lcp < 1:
            return None
        if lcp == len(cur) == len(rec["run_ident"]):
            # identical run list: the exact-hit cold path already dispatches
            # with ZERO upload bytes (arena residency); a resume would add
            # suffix-array uploads just to skip a scan the jit cache replays
            # cheaply. Keep the seed's exact-hit ledger invariants intact.
            return None
        if rec["final_covered"] <= lcp:
            k, init = rec["final_covered"], rec["final_state"]
        else:
            cand = None
            for covered, slot in rec["ring_covered"]:
                if 1 <= covered <= lcp and (cand is None or covered > cand[0]):
                    cand = (covered, slot)
            if cand is None or rec["ring"] is None:
                return None
            import jax

            k = cand[0]
            slot = cand[1]
            init = jax.tree_util.tree_map(lambda a: a[slot],
                                          rec["ring"].states)
        return {"k": k, "init": init, "rec": rec, "key": key, "ctx_sig": ctx}

    def _dispatch_resume(self, enc: EncodedInput, args, host_args, plan,
                         M: int, S: int, total_pods: Optional[int] = None,
                         sparse_host=None):
        """Dispatch only runs[k:] on top of the planned checkpoint. The 34
        non-run args are the arena-resident buffers (zero upload — the
        unchanged prefix ships nothing); only the two tiny suffix run
        arrays (plus, under the sparse gate, their constraint index rows)
        cross the tunnel."""
        import jax

        from .tpu.ffd import ffd_resume, ffd_resume_sparse

        faults.check("solver.device_dispatch")
        k = plan["k"]
        Sp2 = self._bucket(S - k, 16, 16)
        sg = np.zeros((Sp2,), host_args[0].dtype)
        sc = np.zeros((Sp2,), host_args[1].dtype)
        sg[: S - k] = np.asarray(host_args[0])[k:S]
        sc[: S - k] = np.asarray(host_args[1])[k:S]
        dev_sg = jax.device_put(sg)
        dev_sc = jax.device_put(sc)
        self.ledger.record_upload(sg.nbytes + sc.nbytes, 2, msgs=2)
        if sparse_host is not None:
            rqi, rvi = sparse_host
            sq = np.full((Sp2, rqi.shape[1]), -1, rqi.dtype)
            sv = np.full((Sp2, rvi.shape[1]), -1, rvi.dtype)
            sq[: S - k] = rqi[k:S]
            sv[: S - k] = rvi[k:S]
            dev_sq, dev_sv = jax.device_put(sq), jax.device_put(sv)
            self.ledger.record_upload(sq.nbytes + sv.nbytes, 2, msgs=2)
            self.stats["sparse_dispatches"] += 1
            out, ring = ffd_resume_sparse(
                plan["init"], dev_sq, dev_sv, dev_sg, dev_sc, *args[2:],
                max_claims=M, zone_engine=enc.V > 0,
                ckpt_every=self.ckpt_every, n_ckpt=self.ckpt_slots,
            )
        else:
            out, ring = ffd_resume(
                plan["init"], dev_sg, dev_sc, *args[2:],
                max_claims=M, zone_engine=enc.V > 0,
                ckpt_every=self.ckpt_every, n_ckpt=self.ckpt_slots,
            )
        flat_dev, unpack = self._pack_dispatch(out, total_pods=total_pods)
        return flat_dev, unpack, out, ring

    def _ring_coverage(self, Sp: int, S_real: int, base: int):
        """Host-side recomputation of which REAL-run prefix each ring slot
        covers — deterministic from the slot schedule (step j*K writes slot
        (j-1) % n; last write wins; padded steps past S_real don't mutate
        state, so a checkpoint at position p covers min(p, S_real) real
        runs). No device fetch of CheckpointRing.prefix is ever needed."""
        K, n = self.ckpt_every, self.ckpt_slots
        cov: Dict[int, int] = {}
        for j in range(1, Sp // K + 1):
            cov[(j - 1) % n] = base + min(j * K, S_real)
        return sorted(((c, s) for s, c in cov.items()), reverse=True)

    def _record_checkpoint(self, enc: EncodedInput, host_args, M: int,
                           S: int, plan, out, ring, take_e_p, take_c_p,
                           leftover_p) -> None:
        """After a successful device solve, record its checkpoints as the
        bucket's resume donor: run identity, host-side take rows (a resumed
        successor needs prefix rows it won't re-execute), and the
        device-resident ring + final state (never fetched)."""
        if not self.resume or self.arena is None or out is None:
            return
        from . import encode_cache as ec

        ident = ec.run_identity(enc)
        if not ident or len(ident) != S:
            return
        from .tpu.ffd import ARG_INDEX

        key = self.arena.bucket_key(host_args, ns=enc.tenant_id)
        ctx = self.arena.context_signature(
            key, exclude=(ARG_INDEX["run_group"], ARG_INDEX["run_count"])
        )
        if ctx is None:
            return
        if plan is not None:
            base, suffix_real = plan["k"], S - plan["k"]
            Sp_disp = self._bucket(suffix_real, 16, 16)
        else:
            base, suffix_real = 0, S
            Sp_disp = int(host_args[0].shape[0])
        self.arena.put_checkpoint(key, {
            "run_ident": ident,
            "take_e": np.asarray(take_e_p),
            "take_c": np.asarray(take_c_p),
            "leftover": np.asarray(leftover_p),
            "M": M,
            "zone_engine": enc.V > 0,
            "ctx_sig": ctx,
            "ring": ring,
            "ring_covered": self._ring_coverage(Sp_disp, suffix_real, base),
            "final_state": out.state,
            "final_covered": S,
        })

    # -- mesh-sharded solve (ISSUE 7; SPEC.md "Sharding semantics") ----------
    #
    # One provisioning solve partitioned across a device mesh: the padded
    # run axis splits into Nd contiguous blocks (encode.mesh_run_blocks),
    # every device scans its block from the INITIAL carry in parallel
    # (ffd.ffd_solve_sharded — the same traced scan body as ffd_solve), and
    # a host-side carry-exchange pass stitches blocks left-to-right into the
    # sequential result. For each block the stitch either ACCEPTS the
    # block-local decisions (proved non-interacting with the true prefix
    # carry — claims renumber by offset, counts combine additively over the
    # scan's initial bases) or REPLAYS the block via ffd_resume from the
    # stitched carry (the replay IS the sequential scan for that block, so
    # it is the universal correctness escape hatch). Decision identity with
    # the one-device scan is by induction over blocks; the accept conditions
    # are conservative SUPERSETS of every cross-block interaction the kernel
    # can express (see _shard_stitch). Fleets the combine can't express —
    # active domain event engine (V>0) or hostname-constraint axis (Q>0) —
    # decline up front, counted in karpenter_solver_sharded_fallback_total,
    # and run the single-device path: trivially decision-identical.

    # FFDState fields indexed by claim slot — the rows the accept path
    # renumbers by the prefix claim offset
    _SHARD_CLAIM_FIELDS = ("c_cum", "c_mask", "c_zc_bits", "c_gbits",
                           "c_pool", "c_cm", "c_co", "c_vm", "c_vo")

    def _shard_decline(self, reason: str) -> None:
        """Count a sharded-solve decline with its diagnosable reason:
        tiny_fleet (run axis narrower than the mesh / block-misaligned),
        no_mesh (sharded request without a usable multi-device mesh),
        v_axis / q_axis (reserved — the sparse constraint engine lifted
        the V/Q restriction, so nothing emits these today; a future
        inexpressible-carry construct would)."""
        self.stats["sharded_fallbacks"] += 1
        SOLVER_SHARDED_FALLBACK.inc(reason=reason)

    def _shard_bases(self, host_args) -> dict:
        """The non-zero initial values of the scan carry (state0 seeds
        p_usage/e_cm/e_co/v_count from these tables), as host int32 — the
        additive combine must subtract them so a block's LOCAL deltas add
        onto the true prefix carry exactly once."""
        from .tpu.ffd import ARG_INDEX

        return {
            "p_usage": np.asarray(host_args[ARG_INDEX["pool_usage0"]],
                                  dtype=np.int32),
            "e_cm": np.asarray(host_args[ARG_INDEX["node_q_member"]],
                               dtype=np.int32),
            "e_co": np.asarray(host_args[ARG_INDEX["node_q_owner"]],
                               dtype=np.int32),
            "v_count": np.asarray(host_args[ARG_INDEX["v_count0"]],
                                  dtype=np.int32),
        }

    @staticmethod
    def _shard_state0(lane_state, bases) -> dict:
        """Host analog of the kernel's state0 carry (shapes from one lane's
        fetched state): the stitch's running true carry starts here."""
        from .tpu.ffd import FFDState

        st = {f: np.zeros_like(np.asarray(getattr(lane_state, f)[0]))
              for f in FFDState._fields}
        st["c_pool"] = np.full_like(st["c_pool"], -1)
        st["p_usage"] = bases["p_usage"].copy()
        st["e_cm"] = bases["e_cm"].copy()
        st["e_co"] = bases["e_co"].copy()
        st["v_count"] = bases["v_count"].copy()
        return st

    def _sharded_solve_async(self, enc: EncodedInput, host_args, dims, prov):
        """Dispatch one solve mesh-sharded; None declines to the
        single-device path (decline reasons that reflect an inexpressible
        carry combine are counted — no-mesh is not a fallback, it is the
        normal shape of a 1-device rig)."""
        if self.host_mesh is not None:
            return self._hostmesh_solve_async(
                enc, host_args, dims, prov, self.host_mesh
            )
        mesh = self._shard_mesh()
        if mesh is None:
            return None
        Nd = int(mesh.devices.size)
        S = dims["S"]
        Sp = int(host_args[0].shape[0])
        if S < Nd or Sp % Nd:
            self._shard_decline("tiny_fleet")
            return None
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from .encode import mesh_run_blocks, sparse_run_tables
        from .tpu.ffd import ffd_solve_sharded, ffd_solve_sharded_sparse

        Sblk = Sp // Nd
        SOLVER_MESH_DEVICES.set(Nd)
        rgb, rcb = mesh_run_blocks(
            np.asarray(host_args[0]), np.asarray(host_args[1]), Nd
        )
        sh_args = (rgb, rcb) + tuple(host_args[2:])
        blocked = NamedSharding(mesh, PartitionSpec("shards", None))
        repl = NamedSharding(mesh, PartitionSpec())
        shardings = (blocked, blocked) + (repl,) * (len(host_args) - 2)
        self.ledger.begin_solve()
        key = None
        try:
            nproc = int(jax.process_count())
        except Exception:  # noqa: BLE001 — backendless probe
            nproc = 1
        if nproc > 1 and self._shard_local_blocks is not None:
            # per-process adoption (ISSUE 18, SPEC.md "Federation
            # semantics"): each process uploads ONLY its local partition's
            # run blocks (put_process_sharded assembles the global array
            # from per-process single-device shards); the replicated core
            # tables device_put once per process. Resume/shard donor
            # records stay off (key=None) — they assume whole-axis
            # residency, which no single process holds on a pod slice.
            from ..parallel.sharded import put_process_sharded

            lo, hi = self._shard_local_blocks
            args = (
                put_process_sharded(mesh, rgb, lo, hi),
                put_process_sharded(mesh, rcb, lo, hi),
            ) + tuple(jax.device_put(a, repl) for a in sh_args[2:])
            local_bytes = (
                rgb[lo:hi].nbytes + rcb[lo:hi].nbytes
                + sum(a.nbytes for a in sh_args[2:])
            )
            self.ledger.record_upload(
                local_bytes, len(sh_args), msgs=len(sh_args),
                shard_bytes=rgb[lo:hi].nbytes + rcb[lo:hi].nbytes,
            )
        elif self.arena is not None:
            args = self.arena.adopt(sh_args, prov, sharding=shardings,
                                    ns=enc.tenant_id)
            key = self.arena.bucket_key(sh_args, shardings, ns=enc.tenant_id)
        else:
            up = 0
            up_shard = 0
            for a in sh_args[:2]:
                up += a.nbytes
                up_shard += a.nbytes
            for a in sh_args[2:]:
                up += a.nbytes
            args = tuple(
                jax.device_put(a, s) for a, s in zip(sh_args, shardings)
            )
            self.ledger.record_upload(up, len(sh_args), msgs=len(sh_args),
                                      shard_bytes=up_shard)
        zone = enc.V > 0
        sparse = None
        if nproc == 1 and self._sparse_gate(enc):
            # compacted constraint tables partitioned over the shard axis
            # (each lane reads only its block's index rows); the federated
            # multi-process path keeps dense V/Q evaluation — same
            # decisions, and per-process partial adoption of side tables
            # isn't worth the seam
            rqi, rvi = sparse_run_tables(enc, Sp)
            sqb = np.ascontiguousarray(rqi.reshape(Nd, Sblk, -1))
            svb = np.ascontiguousarray(rvi.reshape(Nd, Sblk, -1))
            blocked3 = NamedSharding(mesh, PartitionSpec("shards", None,
                                                         None))
            dev_pair = self._sparse_arg(
                sh_args, enc, sqb, svb, sharding=shardings,
                dev_sharding=blocked3, ns=enc.tenant_id)
            sparse = {"host": (rqi, rvi), "dev": dev_pair}
        total_pods = int(sum(len(p) for p in enc.group_pods))
        M0 = initial_claim_bucket(total_pods, self.max_claims)
        plan = self._plan_shard_resume(enc, key, M0, S, Nd, Sblk)
        if plan is not None:
            return self._dispatch_shard_resume(
                enc, host_args, dims, mesh, args, plan, M0, Nd, Sblk,
                sparse=sparse,
            )
        faults.check("solver.device_dispatch")
        if sparse is not None:
            self.stats["sparse_dispatches"] += 1
            out = ffd_solve_sharded_sparse(
                sparse["dev"][0], sparse["dev"][1], *args,
                max_claims=M0, zone_engine=zone)
        else:
            out = ffd_solve_sharded(*args, max_claims=M0, zone_engine=zone)

        def finish() -> Optional[SolverResult]:
            try:
                return self._sharded_finish(
                    enc, host_args, dims, mesh, args, out, M0, key,
                    sparse=sparse,
                )
            finally:
                self.ledger.end_solve()

        return finish

    def _sharded_finish(self, enc, host_args, dims, mesh, args, out, M0,
                        key, redispatch=None,
                        sparse=None) -> Optional[SolverResult]:
        """Stitch loop with claim-overflow doubling (mirrors the cold
        finish): a saturated stitch redispatches the whole sharded solve at
        the doubled bucket against the same resident args. `redispatch(M)`
        overrides the in-process mesh launch — the virtual host mesh
        re-scatters the blocks to its worker processes instead."""
        from .tpu.ffd import ffd_solve_sharded, ffd_solve_sharded_sparse

        zone = enc.V > 0
        M, cur = M0, out
        while True:
            res = self._shard_stitch(enc, host_args, dims, mesh, args, cur,
                                     M, sparse=sparse)
            if res is not None:
                break
            if M >= self.max_claims:
                return None  # true overflow — replay on the fallback chain
            M = min(M * 2, self.max_claims)
            faults.check("solver.device_dispatch")
            if redispatch is not None:
                cur = redispatch(M)
            elif sparse is not None:
                cur = ffd_solve_sharded_sparse(
                    sparse["dev"][0], sparse["dev"][1], *args,
                    max_claims=M, zone_engine=zone)
            else:
                cur = ffd_solve_sharded(*args, max_claims=M,
                                        zone_engine=zone)
        take_e_p, take_c_p, leftover_p, P, fixup, carries = res
        self.stats["sharded_solves"] += 1
        self.stats["shard_fixup_runs"] += fixup
        if fixup:
            SOLVER_SHARD_FIXUP_RUNS.inc(fixup)
        res_out = self._shard_decode(enc, dims, take_e_p, take_c_p,
                                     leftover_p, P)
        self._record_shard(enc, key, M, dims["S"], len(carries),
                           carries, take_e_p, take_c_p, leftover_p)
        return res_out

    def _hostmesh_solve_async(self, enc, host_args, dims, prov, pool):
        """Dispatch one solve across the VIRTUAL host mesh
        (parallel/hostmesh.HostMeshPool): subprocess worker hosts each scan
        a contiguous slice of the run-axis blocks — the hardware-free
        analog of a process-spanning device mesh — and the parent stitches
        the gathered lanes with the SAME accept/replay proof as the
        in-process mesh (_shard_stitch), so decision identity carries over
        unchanged. Same decline rules as the device mesh; the replay
        escape hatch runs on the parent's own device. Broadcast tables ride
        the pipe once per residency context (the worker-side ctx cache is
        the pipe analog of arena adoption)."""
        Nd = pool.width
        S = dims["S"]
        Sp = int(host_args[0].shape[0])
        if Nd < 2:
            self._shard_decline("no_mesh")
            return None
        if S < Nd or Sp % Nd:
            self._shard_decline("tiny_fleet")
            return None
        import jax

        from ..parallel.sharded import make_mesh
        from .encode import mesh_run_blocks, sparse_run_tables

        SOLVER_MESH_DEVICES.set(Nd)
        rgb, rcb = mesh_run_blocks(
            np.asarray(host_args[0]), np.asarray(host_args[1]), Nd
        )
        rest = tuple(np.asarray(a) for a in host_args[2:])
        sh_args = (rgb, rcb) + rest
        zone = enc.V > 0
        sparse = None
        sqb = svb = None
        if self._sparse_gate(enc):
            rqi, rvi = sparse_run_tables(enc, Sp)
            Sblk = Sp // Nd
            sqb = np.ascontiguousarray(rqi.reshape(Nd, Sblk, -1))
            svb = np.ascontiguousarray(rvi.reshape(Nd, Sblk, -1))
            # parent-side stitch replays device_put block rows on demand;
            # cold redispatches go back through the worker pool, so no
            # parent device pair is needed
            sparse = {"host": (rqi, rvi), "dev": None}
        # replay/resume device args live on the PARENT (1-device mesh):
        # the stitch's sequential escape hatch is host-side either way
        local_mesh = make_mesh(1, axis="shards")
        args = tuple(jax.device_put(a) for a in sh_args)
        ctx = None
        if self.arena is not None:
            key = self.arena.bucket_key(
                sh_args, ("hostmesh", Nd), ns=enc.tenant_id
            )
            ctx = f"hm{abs(hash(key)):x}"
        self.ledger.begin_solve()
        self.ledger.record_upload(
            sum(a.nbytes for a in sh_args), len(sh_args), msgs=len(sh_args),
            shard_bytes=rgb.nbytes + rcb.nbytes,
        )
        total_pods = int(sum(len(p) for p in enc.group_pods))
        M0 = initial_claim_bucket(total_pods, self.max_claims)

        def redispatch(M):
            faults.check("solver.device_dispatch")
            return pool.scatter_blocks(rgb, rcb, rest, max_claims=M,
                                       ctx=ctx, zone_engine=zone,
                                       sqb=sqb, svb=svb)

        out = redispatch(M0)

        def finish() -> Optional[SolverResult]:
            try:
                return self._sharded_finish(
                    enc, host_args, dims, local_mesh, args, out, M0, None,
                    redispatch=redispatch, sparse=sparse,
                )
            finally:
                self.ledger.end_solve()

        return finish

    def _shard_stitch(self, enc, host_args, dims, mesh, args, out, M,
                      sparse=None):
        """Fetch the lane-local outputs and stitch blocks left-to-right
        under the running TRUE carry P. Returns (take_e [Sp, Ep], take_c
        [Sp, M], leftover [Sp], final carry dict, fixup_runs, block-boundary
        carries) or None when any path saturates the claim bucket.

        Block d ACCEPTS iff all of (evaluated against P at block start —
        valid for every run of the block because claim capacity/type masks/
        offering bits only shrink and node/pool state only grows):
          (a) no run of the block resource+compat-fits ANY open claim of P
              (the fit test ignores offering bits, pair compatibility, and
              pool admission — a strict SUPERSET of kernel-admissible
              pours, so "no superset fit" proves the kernel pours nothing
              into prefix claims);
          (b) the prefix never touched existing nodes (e_cum at zero,
              hostname counts at their seeds) — node capacity is monotone,
              so an untouched prefix means the lane saw true node state;
          (c) no finite-limit pool's usage moved from its seed (prefix
              consumed no limited headroom the lane assumed free);
          (d) P.used + lane.used <= M and the lane itself never saturated —
              sufficient for slot-clamp equivalence: a lane clamped by
              slots_left must end at used == M, so an unsaturated lane was
              never clamped, and the bound keeps the sequential scan
              unclamped too;
          (e) no spread counter the block's groups TOUCH (V sigs they are
              member or owner of) moved from its seed, and no touched sig
              gained a committed owner zone — the lane evaluated domain
              admission/placement against the seed counters, so untouched
              rows mean it saw true spread state (per-block touch masks,
              SPEC.md "Sparse constraint semantics");
          (f) no touched positive-affinity (kind-2) Q sig has membership
              or ownership recorded on a PREFIX claim — kind-2 is the one
              hostname-constraint rule whose allowance reads CROSS-claim
              sums (tot_m_q / c_pos bootstrap), so prefix-claim columns the
              lane could not see force a replay; kinds 0/1 read only
              per-claim local counters, covered by (a) and (b).
        Otherwise the block REPLAYS via ffd_resume from P — sequentially
        exact by construction — and its replayed real runs count into the
        fix-up gauge."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from .tpu.ffd import (
            ARG_INDEX,
            FFDState,
            ffd_resume,
            ffd_resume_sparse,
        )

        INT32_MAX_NP = np.int32(2**31 - 1)
        h = jax.tree_util.tree_map(np.asarray, out)
        self.ledger.record_fetch(
            sum(x.nbytes for x in jax.tree_util.tree_leaves(h)), msgs=1
        )
        st = h.state
        Nd = int(st.used.shape[0])
        Sblk = int(h.take_e.shape[1])
        T = dims["T"]
        bases = self._shard_bases(host_args)
        P = self._shard_state0(st, bases)
        rg = np.asarray(host_args[0]).reshape(Nd, Sblk)
        rc = np.asarray(host_args[1]).reshape(Nd, Sblk)
        type_alloc = np.asarray(host_args[ARG_INDEX["type_alloc"]])
        group_req = np.asarray(host_args[ARG_INDEX["group_req"]])
        group_compat_t = np.asarray(host_args[ARG_INDEX["group_compat_t"]])
        pool_limit = np.asarray(host_args[ARG_INDEX["pool_limit"]])
        finite_pool = (pool_limit < INT32_MAX_NP).any(axis=1)
        # per-block constraint touch masks (conditions (e)/(f)): which V/Q
        # sigs each block's groups can read — any prefix movement of a
        # touched sig forces a replay, while fleets whose blocks touch
        # DISJOINT sigs (the common constraint-heavy shape: many apps,
        # each spreading only itself) stitch without serializing
        has_vq = enc.V > 0 or enc.Q > 0
        if has_vq:
            v_act = (np.asarray(host_args[ARG_INDEX["v_member"]], bool)
                     | np.asarray(host_args[ARG_INDEX["v_owner"]], bool))
            q_act = (np.asarray(host_args[ARG_INDEX["q_member"]], bool)
                     | np.asarray(host_args[ARG_INDEX["q_owner"]], bool))
            q_kind2 = np.asarray(host_args[ARG_INDEX["q_kind"]]) == 2
        zone = enc.V > 0
        repl = NamedSharding(mesh, PartitionSpec())
        rows_e = []
        rows_c = []
        rows_l = []
        carries = []
        fixup = 0
        for d in range(Nd):
            real = rc[d] > 0
            n_real = int(real.sum())
            if n_real == 0:
                # pure padding block: no-op lanes, nothing to stitch
                rows_e.append(np.asarray(h.take_e[d]))
                rows_c.append(np.zeros((Sblk, M), h.take_c.dtype))
                rows_l.append(np.asarray(h.leftover[d]))
                carries.append({f: v.copy() for f, v in P.items()})
                continue
            lane_used = int(st.used[d])
            offset = int(P["used"])
            replay = lane_used >= M or offset + lane_used > M  # (d)
            if not replay and d > 0:
                if P["e_cum"].any() or (P["e_cm"] != bases["e_cm"]).any() \
                        or (P["e_co"] != bases["e_co"]).any():
                    replay = True  # (b)
                elif (finite_pool[:, None]
                      & (P["p_usage"] != bases["p_usage"])).any():
                    replay = True  # (c)
                if not replay and has_vq:
                    gs = np.unique(rg[d][real])
                    v_t = v_act[gs].any(axis=0) if v_act.size else \
                        np.zeros(0, bool)
                    if v_t.any() and (
                            (P["v_count"][v_t]
                             != bases["v_count"][v_t]).any()
                            or P["v_owner_z"][v_t].any()):
                        replay = True  # (e)
                    else:
                        q2_t = (q_act[gs].any(axis=0) & q_kind2
                                if q_act.size else np.zeros(0, bool))
                        if q2_t.any() and (P["c_cm"][:, q2_t].any()
                                           or P["c_co"][:, q2_t].any()):
                            replay = True  # (f)
                if not replay and offset > 0:
                    open_m = np.flatnonzero(P["c_pool"] >= 0)
                    if open_m.size:
                        # (a) superset fit: claim survives if EVERY nonzero
                        # request axis still fits under some surviving type
                        # the group tolerates
                        room = (
                            type_alloc[None, :, :].astype(np.int64)
                            - P["c_cum"][open_m][:, None, :]
                        )  # [m, Tp, R]
                        cmask = P["c_mask"][open_m]  # [m, Tp]
                        for g in np.unique(rg[d][real]):
                            req = group_req[int(g)]
                            fit = ((room >= req[None, None, :])
                                   | (req[None, None, :] == 0)).all(axis=2)
                            if (fit & cmask
                                    & group_compat_t[int(g)][None, :]).any():
                                replay = True
                                break
            if not replay:
                u = lane_used
                row_c = np.zeros((Sblk, M), h.take_c.dtype)
                if u:
                    row_c[:, offset:offset + u] = h.take_c[d][:, :u]
                    for f in self._SHARD_CLAIM_FIELDS:
                        P[f][offset:offset + u] = np.asarray(
                            getattr(st, f)[d][:u]
                        )
                P["used"] = np.int32(offset + u)
                P["e_cum"] = P["e_cum"] + np.asarray(st.e_cum[d])
                P["e_cm"] = P["e_cm"] + np.asarray(st.e_cm[d]) - bases["e_cm"]
                P["e_co"] = P["e_co"] + np.asarray(st.e_co[d]) - bases["e_co"]
                P["p_usage"] = (P["p_usage"] + np.asarray(st.p_usage[d])
                                - bases["p_usage"])
                P["v_count"] = (P["v_count"] + np.asarray(st.v_count[d])
                                - bases["v_count"])
                P["v_owner_z"] = P["v_owner_z"] | np.asarray(st.v_owner_z[d])
                rows_e.append(np.asarray(h.take_e[d]))
                rows_c.append(row_c)
                rows_l.append(np.asarray(h.leftover[d]))
            else:
                # fix-up replay: the block re-runs sequentially from the
                # true carry; claims number from P.used automatically
                fixup += n_real
                faults.check("solver.device_dispatch")
                init = jax.device_put(
                    FFDState(**{f: P[f] for f in FFDState._fields}), repl
                )
                dev_sg = jax.device_put(rg[d], repl)
                dev_sc = jax.device_put(rc[d], repl)
                self.ledger.record_upload(
                    sum(v.nbytes for v in P.values())
                    + rg[d].nbytes + rc[d].nbytes,
                    len(P) + 2, msgs=3,
                )
                if sparse is not None:
                    rqi, rvi = sparse["host"]
                    sq_blk = rqi.reshape(Nd, Sblk, -1)[d]
                    sv_blk = rvi.reshape(Nd, Sblk, -1)[d]
                    dev_sq = jax.device_put(sq_blk, repl)
                    dev_sv = jax.device_put(sv_blk, repl)
                    self.ledger.record_upload(
                        sq_blk.nbytes + sv_blk.nbytes, 2, msgs=2)
                    r_out, _ = ffd_resume_sparse(
                        init, dev_sq, dev_sv, dev_sg, dev_sc, *args[2:],
                        max_claims=M, zone_engine=zone,
                        ckpt_every=self.ckpt_every, n_ckpt=self.ckpt_slots,
                    )
                else:
                    r_out, _ = ffd_resume(
                        init, dev_sg, dev_sc, *args[2:],
                        max_claims=M, zone_engine=zone,
                        ckpt_every=self.ckpt_every, n_ckpt=self.ckpt_slots,
                    )
                rh = jax.tree_util.tree_map(np.asarray, r_out)
                self.ledger.record_fetch(
                    sum(x.nbytes
                        for x in jax.tree_util.tree_leaves(rh)), msgs=1
                )
                if int(rh.state.used) >= M:
                    return None  # replay saturated the bucket — double M
                P = {f: np.array(getattr(rh.state, f))  # writable copies
                     for f in rh.state._fields}
                rows_e.append(rh.take_e)
                rows_c.append(rh.take_c)
                rows_l.append(rh.leftover)
            carries.append({f: np.copy(v) for f, v in P.items()})
        if int(P["used"]) > M:
            return None
        return (
            np.concatenate(rows_e),
            np.concatenate(rows_c),
            np.concatenate(rows_l),
            P,
            fixup,
            carries,
        )

    def _shard_decode(self, enc, dims, take_e_p, take_c_p, leftover_p, P):
        """Dense decode of the stitched tables — the stitched carry already
        lives host-side, so claim metadata unpacks straight from it."""
        S, E, T, G = dims["S"], dims["E"], dims["T"], dims["G"]
        Z, C = dims["Z"], dims["C"]
        c_mask = np.asarray(P["c_mask"])[:, :T]
        c_zone, c_ct = unpack_zc_bits(np.asarray(P["c_zc_bits"]), Z, C)
        c_gmask = _unpack_gmask(np.asarray(P["c_gbits"]), G)
        return decode(
            enc, take_e_p[:S, :E], take_c_p[:S], leftover_p[:S], c_mask,
            c_zone, c_ct, np.asarray(P["c_pool"]), c_gmask,
            np.asarray(P["c_cum"]), int(P["used"]),
        )

    def _record_shard(self, enc, key, M, S, Nd, carries, take_e_p, take_c_p,
                      leftover_p) -> None:
        """Record the sharded solve as its bucket's shard-resume donor: the
        block-boundary carries ARE the per-device checkpoints (host-side —
        unlike the plain ring they already crossed the link during the
        stitch), so a later solve differing only from block b onward
        replays one suffix from carries[b-1]."""
        if not self.resume or self.arena is None or key is None:
            return
        from . import encode_cache as ec
        from .tpu.ffd import ARG_INDEX

        ident = ec.run_identity(enc)
        if not ident or len(ident) != S:
            return
        ctx = self.arena.context_signature(
            key, exclude=(ARG_INDEX["run_group"], ARG_INDEX["run_count"])
        )
        if ctx is None:
            return
        self.arena.put_shard_record(key, {
            "run_ident": ident,
            "M": M,
            "n_shards": Nd,
            "zone_engine": enc.V > 0,
            "ctx_sig": ctx,
            "carries": carries,
            "take_e": np.asarray(take_e_p),
            "take_c": np.asarray(take_c_p),
            "leftover": np.asarray(leftover_p),
        })

    def _plan_shard_resume(self, enc, key, M0: int, S: int, Nd: int,
                           Sblk: int):
        """Newest valid shard record reusable from a whole-block boundary:
        same bucket/claim bucket/mesh width, byte-identical non-run context
        (arena signature leg), and a run-identity common prefix covering
        b >= 1 complete blocks. Identical run lists keep the zero-upload
        exact-hit cold path, mirroring _plan_resume."""
        if not self.resume or self.arena is None or key is None:
            return None
        from . import encode_cache as ec
        from .tpu.ffd import ARG_INDEX

        rec = self.arena.get_shard_record(key)
        if rec is None or rec["M"] != M0 or rec["n_shards"] != Nd \
                or rec.get("zone_engine", False) != (enc.V > 0):
            return None
        ctx = self.arena.context_signature(
            key, exclude=(ARG_INDEX["run_group"], ARG_INDEX["run_count"])
        )
        if ctx is None or ctx != rec["ctx_sig"]:
            return None
        cur = ec.run_identity(enc)
        if not cur or len(cur) != S:
            return None
        lcp = ec.run_lcp(rec["run_ident"], cur)
        if lcp == len(cur) == len(rec["run_ident"]):
            return None  # exact hit — cold sharded path is already 0-upload
        b = min(lcp // Sblk, Nd - 1)
        if b < 1:
            return None
        return {"b": b, "carry": rec["carries"][b - 1], "rec": rec}

    def _dispatch_shard_resume(self, enc, host_args, dims, mesh, args, plan,
                               M: int, Nd: int, Sblk: int, sparse=None):
        """Replay only blocks [b:] as ONE replicated ffd_resume from the
        recorded block-boundary carry; rows [0, b*Sblk) splice from the
        donor record. Composes suffix resume with sharding: the per-device
        checkpoints (block carries) bound the replay to the changed tail."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from .tpu.ffd import FFDState, ffd_resume, ffd_resume_sparse

        faults.check("solver.device_dispatch")
        b = plan["b"]
        k = b * Sblk
        Sp = int(host_args[0].shape[0])
        repl = NamedSharding(mesh, PartitionSpec())
        carry = plan["carry"]
        sg = np.asarray(host_args[0])[k:Sp]
        sc = np.asarray(host_args[1])[k:Sp]
        init = jax.device_put(
            FFDState(**{f: carry[f] for f in FFDState._fields}), repl
        )
        dev_sg = jax.device_put(sg, repl)
        dev_sc = jax.device_put(sc, repl)
        self.ledger.record_upload(
            sum(v.nbytes for v in carry.values()) + sg.nbytes + sc.nbytes,
            len(carry) + 2, msgs=3,
        )
        zone = enc.V > 0
        if sparse is not None:
            rqi, rvi = sparse["host"]
            dev_sq = jax.device_put(np.ascontiguousarray(rqi[k:Sp]), repl)
            dev_sv = jax.device_put(np.ascontiguousarray(rvi[k:Sp]), repl)
            self.ledger.record_upload(
                rqi[k:Sp].nbytes + rvi[k:Sp].nbytes, 2, msgs=2)
            out, _ = ffd_resume_sparse(
                init, dev_sq, dev_sv, dev_sg, dev_sc, *args[2:],
                max_claims=M, zone_engine=zone,
                ckpt_every=self.ckpt_every, n_ckpt=self.ckpt_slots,
            )
        else:
            out, _ = ffd_resume(
                init, dev_sg, dev_sc, *args[2:],
                max_claims=M, zone_engine=zone,
                ckpt_every=self.ckpt_every, n_ckpt=self.ckpt_slots,
            )

        def finish() -> Optional[SolverResult]:
            try:
                import jax as _jax

                rh = _jax.tree_util.tree_map(np.asarray, out)
                self.ledger.record_fetch(
                    sum(x.nbytes
                        for x in _jax.tree_util.tree_leaves(rh)), msgs=1
                )
                if int(rh.state.used) >= M:
                    # suffix overflowed the donor's bucket: redo COLD
                    # sharded at the doubled bucket (resident args reused)
                    from .tpu.ffd import (
                        ffd_solve_sharded,
                        ffd_solve_sharded_sparse,
                    )

                    if M >= self.max_claims:
                        return None
                    M2 = min(M * 2, self.max_claims)
                    faults.check("solver.device_dispatch")
                    if sparse is not None:
                        cold = ffd_solve_sharded_sparse(
                            sparse["dev"][0], sparse["dev"][1], *args,
                            max_claims=M2, zone_engine=zone,
                        )
                    else:
                        cold = ffd_solve_sharded(
                            *args, max_claims=M2, zone_engine=zone
                        )
                    return self._sharded_finish(
                        enc, host_args, dims, mesh, args, cold, M2, None,
                        sparse=sparse,
                    )
                rec = plan["rec"]
                pre_c = rec["take_c"][:k]
                if rec["take_c"].shape[1] < M:
                    pad = np.zeros(
                        (k, M - rec["take_c"].shape[1]), pre_c.dtype
                    )
                    pre_c = np.concatenate([pre_c, pad], axis=1)
                take_e_p = np.concatenate([rec["take_e"][:k], rh.take_e])
                take_c_p = np.concatenate([pre_c, rh.take_c])
                leftover_p = np.concatenate(
                    [rec["leftover"][:k], rh.leftover]
                )
                P = {f: np.asarray(getattr(rh.state, f))
                     for f in rh.state._fields}
                self.stats["sharded_solves"] += 1
                self.stats["shard_resume_solves"] += 1
                self.stats["shard_resume_runs_skipped"] += k
                return self._shard_decode(
                    enc, dims, take_e_p, take_c_p, leftover_p, P
                )
            finally:
                self.ledger.end_solve()

        return finish


def _unpack_words(words: np.ndarray, width: int) -> np.ndarray:
    """[N, W] uint32 words -> [N, width] bool (inverse of bit-packing)."""
    N, W = words.shape
    bits = (words[:, :, None] >> np.arange(32, dtype=np.uint32)[None, None, :]) & 1
    return bits.reshape(N, W * 32)[:, :width].astype(bool)


def _unpack_gmask(gbits: np.ndarray, G: int) -> np.ndarray:
    """[M, W] uint32 words -> [M, G] bool group-membership mask."""
    return _unpack_words(gbits, G)


def decode(
    enc: EncodedInput,
    take_e: np.ndarray,  # [S, E]
    take_c: np.ndarray,  # [S, M]
    leftover: np.ndarray,  # [S]
    c_mask: np.ndarray,  # [M, T]
    c_zone: np.ndarray,  # [M, Z]
    c_ct: np.ndarray,  # [M, C]
    c_pool: np.ndarray,  # [M]
    c_gmask: np.ndarray,  # [M, G]
    c_cum: np.ndarray,  # [M, R]
    used: int,
) -> SolverResult:
    """Reassemble a SolverResult: pods assigned in index order per run
    (existing nodes first, then claim slots — exactly first-fit order).

    Fully vectorized over the run arrays: per-pod work is C-speed numpy /
    dict construction, never a Python loop over 50k pods (VERDICT r2 next
    item 1). Target tuples are interned — one object per distinct target,
    shared by every pod placed there."""
    S = len(enc.run_group)
    E = take_e.shape[1] if take_e.ndim == 2 else 0
    uid_sorted = enc.sorted_uids
    # per-run code segments: node e -> e, claim m -> E+m, unplaced -> -1,
    # emitted in first-fit order (nodes, then claims, then leftovers)
    segs: List[np.ndarray] = []
    for s in range(S):
        te, tc, lo = take_e[s], take_c[s], int(leftover[s])
        parts: List[np.ndarray] = []
        e_idx = np.flatnonzero(te)
        if e_idx.size:
            parts.append(np.repeat(e_idx, te[e_idx]))
        c_idx = np.flatnonzero(tc)
        if c_idx.size:
            parts.append(np.repeat(c_idx + E, tc[c_idx]))
        if lo:
            parts.append(np.full(lo, -1, np.int64))
        if parts:
            segs.append(np.concatenate([p.astype(np.int64, copy=False) for p in parts]))
    codes = np.concatenate(segs) if segs else np.zeros(0, np.int64)
    return _decode_from_codes(
        enc, codes, E, c_mask, c_zone, c_ct, c_pool, c_gmask, c_cum, used
    )


def decode_delta(
    enc: EncodedInput,
    entries: np.ndarray,  # [n, 3] int32 (run, code, count), code = e | Ep+m
    leftover: np.ndarray,  # [S]
    E: int,  # unpadded node count
    Ep: int,  # padded node axis the device codes split on
    c_mask: np.ndarray,
    c_zone: np.ndarray,
    c_ct: np.ndarray,
    c_pool: np.ndarray,
    c_gmask: np.ndarray,
    c_cum: np.ndarray,
    used: int,
) -> SolverResult:
    """Rebuild the exact codes stream decode() derives from the dense take
    tables, from the packed claim-delta instead — bit-identical by
    construction: within a run, node codes (< Ep, ascending) sort before
    claim codes (Ep+m -> E+m, ascending in m since E+m preserves order)
    sort before the leftover row (sentinel key), which is precisely
    decode()'s per-run emission order (nodes, claims, leftovers)."""
    S = len(enc.run_group)
    s = entries[:, 0].astype(np.int64)
    cd = entries[:, 1].astype(np.int64)
    v = entries[:, 2].astype(np.int64)
    keep = (s < S) & (v > 0)
    s, cd, v = s[keep], cd[keep], v[keep]
    code = np.where(cd >= Ep, cd - Ep + E, cd)
    lo = leftover[:S].astype(np.int64)
    ls = np.flatnonzero(lo)
    SENT = np.int64(np.iinfo(np.int64).max)
    s_all = np.concatenate([s, ls])
    code_all = np.concatenate([code, np.full(ls.size, SENT)])
    v_all = np.concatenate([v, lo[ls]])
    order = np.lexsort((code_all, s_all))
    codes = np.repeat(
        np.where(code_all[order] == SENT, np.int64(-1), code_all[order]),
        v_all[order],
    )
    return _decode_from_codes(
        enc, codes, E, c_mask, c_zone, c_ct, c_pool, c_gmask, c_cum, used
    )


def _entries_from_dense(take_e: np.ndarray, take_c: np.ndarray,
                        Ep: int) -> np.ndarray:
    """Dense take rows -> (run, code, count) triples in the device coding
    (claims offset by the PADDED node axis). Used to splice a resume donor's
    recorded prefix rows into a delta-decoded suffix."""
    rs, cs = np.nonzero(take_e)
    rs2, cs2 = np.nonzero(take_c)
    return np.concatenate(
        [
            np.stack([rs, cs, take_e[rs, cs]], axis=1),
            np.stack([rs2, cs2 + Ep, take_c[rs2, cs2]], axis=1),
        ]
    ).astype(np.int64)


def _claim_cum_from_entries(enc: EncodedInput, entries: np.ndarray,
                            c_pool: np.ndarray, Ep: int,
                            Mb: int) -> np.ndarray:
    """Rebuild the kernel's c_cum [M, R] from the claim-delta: every opened
    claim starts at its pool's daemonset overhead and accumulates
    take × group_req per pouring run — exactly ffd's pour arithmetic
    (pool_daemon[p] on open, + take·req per pour), in int32 wraparound
    semantics, so the result is bit-identical to fetching c_cum and the
    requests decode() derives from it never diverge."""
    R = enc.group_req.shape[1]
    cum = np.zeros((Mb, R), dtype=np.int64)
    pool = np.asarray(c_pool[:Mb]).astype(np.int64)
    opened = pool >= 0
    cum[opened] = enc.pool_daemon[pool[opened]].astype(np.int64)
    s = entries[:, 0].astype(np.int64)
    cd = entries[:, 1].astype(np.int64)
    v = entries[:, 2].astype(np.int64)
    csel = (cd >= Ep) & (cd - Ep < Mb) & (s < len(enc.run_group))
    if csel.any():
        m = cd[csel] - Ep
        g = enc.run_group[s[csel]].astype(np.int64)
        np.add.at(cum, m, v[csel, None] * enc.group_req[g].astype(np.int64))
    return cum.astype(np.int32)  # int64 -> int32 truncation == device wrap


def _dense_from_entries(entries: np.ndarray, S: int, Ep: int,
                        Mb: int) -> Tuple[np.ndarray, np.ndarray]:
    """Inverse of the compaction for the checkpoint record: a resume donor
    stores dense take rows (the resume machinery predates the delta path
    and its stitching contract stays dense)."""
    take_e = np.zeros((S, Ep), np.int32)
    take_c = np.zeros((S, Mb), np.int32)
    s = entries[:, 0].astype(np.int64)
    cd = entries[:, 1].astype(np.int64)
    v = entries[:, 2].astype(np.int64)
    keep = s < S
    s, cd, v = s[keep], cd[keep], v[keep]
    node = cd < Ep
    take_e[s[node], cd[node]] = v[node]
    take_c[s[~node], cd[~node] - Ep] = v[~node]
    return take_e, take_c


def _decode_from_codes(
    enc: EncodedInput,
    codes: np.ndarray,  # [total_pods] int64: node e -> e, claim m -> E+m, -1
    E: int,
    c_mask: np.ndarray,  # [M, T]
    c_zone: np.ndarray,  # [M, Z]
    c_ct: np.ndarray,  # [M, C]
    c_pool: np.ndarray,  # [M]
    c_gmask: np.ndarray,  # [M, G]
    c_cum: np.ndarray,  # [M, R]
    used: int,
) -> SolverResult:
    """Shared tail of decode()/decode_delta(): codes stream (aligned with
    enc.sorted_uids) -> SolverResult."""
    uid_sorted = enc.sorted_uids
    targets = np.empty(E + used, dtype=object)
    for e in range(E):
        targets[e] = ("node", enc.node_ids[e])
    for m in range(used):
        targets[E + m] = ("claim", m)

    ok = codes >= 0
    placements: Dict[str, Tuple[str, object]] = dict(
        zip(uid_sorted[ok].tolist(), targets[codes[ok]].tolist())
    )
    errors: Dict[str, str] = dict.fromkeys(
        uid_sorted[~ok].tolist(), "no instance type in any nodepool satisfies the pod"
    )
    # per-claim pod uid lists: stable sort by claim code, then split by counts
    ccodes = codes - E
    csel = ccodes >= 0
    cc = ccodes[csel]
    cuids = uid_sorted[csel][np.argsort(cc, kind="stable")]
    offs = np.concatenate(([0], np.cumsum(np.bincount(cc, minlength=used)))) if used else np.zeros(1, np.int64)
    claim_pods: Dict[int, List[str]] = {
        m: cuids[offs[m] : offs[m + 1]].tolist() for m in range(used)
    }

    # Claim templates dedupe by identity row (pool, zone/ct/group/type bits):
    # a 50k-pod surge opens hundreds of claims from a handful of distinct
    # deployment waves, so the Requirements/type-name construction runs once
    # per distinct template. The reqs/type_names objects are shared across
    # claims of one template; consumers copy before mutating (provisioner
    # re-wraps requirements, ClaimResult lists are copied at NodeClaim build).
    claims: List[ClaimResult] = []
    if used:
        key_rows = np.concatenate(
            [
                # full-width pool index bytes (a uint8 cast would alias pool
                # indices 256 apart into one template)
                np.ascontiguousarray(c_pool[:used].astype(">i4")).view(np.uint8).reshape(used, 4),
                np.packbits(c_zone[:used], axis=1),
                np.packbits(c_ct[:used], axis=1),
                np.packbits(c_gmask[:used], axis=1),
                np.packbits(c_mask[:used], axis=1),
            ],
            axis=1,
        )
        _, tmpl_first, tmpl_of = np.unique(
            key_rows, axis=0, return_index=True, return_inverse=True
        )
        tmpl_of = tmpl_of.ravel()
        templates = {}
        for ti, m0 in enumerate(tmpl_first):
            m0 = int(m0)
            pool_name = enc.pool_names[int(c_pool[m0])]
            type_names = [enc.type_names[t] for t in np.flatnonzero(c_mask[m0])]
            reqs = Requirements.of(
                Requirement.create(wk.NODEPOOL_LABEL, IN, [pool_name])
            )
            zones = [enc.zones[z] for z in np.flatnonzero(c_zone[m0])]
            cts = [enc.capacity_types[c] for c in np.flatnonzero(c_ct[m0])]
            if zones:
                reqs.add(Requirement.create(wk.ZONE_LABEL, IN, zones))
            if cts:
                reqs.add(Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, cts))
            for g in np.flatnonzero(c_gmask[m0]):
                reqs = reqs.union(enc.group_pods[int(g)][0].scheduling_requirements())
            templates[ti] = (pool_name, type_names, reqs)
        # MiB-keyed columns decode back to bytes; others pass through
        mult = np.fromiter(
            (
                1024**2 if k in ("memory", "ephemeral-storage") else 1
                for k in enc.resource_keys
            ),
            np.int64,
            len(enc.resource_keys),
        )
        vals = c_cum[:used].astype(np.int64) * mult[None, :]
        rkeys = enc.resource_keys
        for m in range(used):
            pool_name, type_names, reqs = templates[int(tmpl_of[m])]
            row = vals[m]
            requests = Resources()
            for i, v in enumerate(row.tolist()):
                if v:
                    requests[rkeys[i]] = v
            claims.append(
                ClaimResult(
                    nodepool=pool_name,
                    requirements=reqs,
                    instance_type_names=type_names,
                    pod_uids=claim_pods[m],
                    requests=requests,
                    taints=[],
                    hostname=f"claim-{m}",
                )
            )
    return SolverResult(placements=placements, claims=claims, errors=errors)
