"""Solver backends: the pluggable `Solver` seam (BASELINE.json north_star).

- `ReferenceSolver` — the exact sequential Python path (ground truth).
- `TPUSolver` — encodes to tensors, runs the device FFD kernel, decodes back.
  If the input contains constructs the device kernel can't express yet
  (fallback groups — see encode.py), it transparently routes the WHOLE solve
  to the reference path so semantics never fork mid-solve.

Both operate on MiB-quantized inputs (encode.quantize_input) so decisions are
bit-identical; `tests/test_solver_parity.py` asserts it.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import wellknown as wk
from ..provisioning.scheduler import (
    ClaimResult,
    ExistingNode,
    NodePoolSpec,
    Scheduler,
    SolverInput,
    SolverResult,
)
from ..scheduling.requirements import IN, Requirement, Requirements
from ..utils.resources import PODS, Resources
from .encode import EncodedInput, encode, quantize_input


class Solver(abc.ABC):
    @abc.abstractmethod
    def solve(self, inp: SolverInput) -> SolverResult:
        ...


class ReferenceSolver(Solver):
    def solve(self, inp: SolverInput) -> SolverResult:
        return Scheduler(inp).solve()


def kernel_args(enc: EncodedInput, bucket) -> Tuple[tuple, dict]:
    """The 20 padded positional arrays for tpu.ffd.ffd_solve, plus dims.

    Shapes bucket to bounded sizes so compilations cache across solves
    (SURVEY.md §7: bucketed padding avoids recompilation storms). Shared by
    the single-solve path, the driver entry points, and the batched
    consolidation evaluator.
    """
    import jax.numpy as jnp

    INT32_MAX_NP = np.int32(2**31 - 1)
    S, G, T, E, P = len(enc.run_group), enc.G, enc.T, enc.E, enc.P
    R, Z, C = enc.group_req.shape[1], len(enc.zones), len(enc.capacity_types)
    Sp, Gp, Tp, Ep, Pp = (
        bucket(S, 64, 64),
        bucket(G, 16, 16),
        bucket(T, 128, 128),
        bucket(E, 64, 64),
        bucket(P, 4, 4),
    )
    Qp = bucket(enc.Q, 8, 8)

    def pad(a, shape, fill=0):
        out = np.full(shape, fill, dtype=a.dtype)
        out[tuple(slice(0, s) for s in a.shape)] = a
        return out

    type_charge = np.where(enc.charge_axes[None, :], enc.type_capacity, 0).astype(np.int32)
    args = (
        jnp.asarray(pad(enc.run_group, (Sp,))),
        jnp.asarray(pad(enc.run_count, (Sp,))),
        jnp.asarray(pad(enc.group_req, (Gp, R))),
        jnp.asarray(pad(enc.group_compat_t, (Gp, Tp))),
        jnp.asarray(pad(enc.group_zone, (Gp, Z))),
        jnp.asarray(pad(enc.group_ct, (Gp, C))),
        jnp.asarray(pad(enc.group_pool, (Gp, Pp))),
        jnp.asarray(pad(enc.group_pair, (Gp, Gp), fill=True)),
        jnp.asarray(pad(~enc.group_fallback, (Gp,))),
        jnp.asarray(pad(enc.type_alloc, (Tp, R))),
        jnp.asarray(pad(type_charge, (Tp, R))),
        jnp.asarray(pad(enc.offer_avail, (Tp, Z, C))),
        jnp.asarray(pad(enc.pool_type, (Pp, Tp))),
        jnp.asarray(pad(enc.pool_zone, (Pp, Z))),
        jnp.asarray(pad(enc.pool_ct, (Pp, C))),
        jnp.asarray(pad(enc.pool_daemon, (Pp, R))),
        jnp.asarray(pad(enc.pool_limit, (Pp, R), fill=INT32_MAX_NP)),
        jnp.asarray(pad(enc.pool_usage, (Pp, R))),
        jnp.asarray(pad(enc.node_free, (Ep, R))),
        jnp.asarray(pad(enc.node_compat, (Gp, Ep))),
        jnp.asarray(pad(enc.q_member, (Gp, Qp))),
        jnp.asarray(pad(enc.q_owner, (Gp, Qp))),
        jnp.asarray(pad(enc.q_kind, (Qp,))),
        jnp.asarray(pad(enc.q_cap, (Qp,), fill=1)),
        jnp.asarray(pad(enc.node_q_member, (Ep, Qp))),
        jnp.asarray(pad(enc.node_q_owner, (Ep, Qp))),
    )
    dims = dict(S=S, G=G, T=T, E=E, P=P, R=R, Z=Z, C=C, Sp=Sp, Gp=Gp, Tp=Tp, Ep=Ep, Pp=Pp, Qp=Qp)
    return args, dims


class TPUSolver(Solver):
    """Tensorized FFD on device (JAX/XLA; see tpu/ffd.py).

    max_claims bounds the claim-slot array; inputs that overflow it (or use
    unsupported constructs) fall back to the reference path.
    """

    def __init__(self, max_claims: int = 1024, fallback: Optional[Solver] = None):
        self.max_claims = max_claims
        if fallback is None:
            # fallback chain: native C++ core (compiled-class speed), which
            # itself degrades to the python oracle for constructs neither
            # encoded path expresses (topology/affinity, pending kernels)
            from .native import NativeSolver

            fallback = NativeSolver()
        self.fallback = fallback
        self.stats: Dict[str, int] = {"device_solves": 0, "fallback_solves": 0}

    def solve(self, inp: SolverInput) -> SolverResult:
        qinp = quantize_input(inp)
        enc = encode(qinp)
        if (
            enc.group_fallback.any()
            or enc.has_topology
            or enc.has_affinity
            or enc.G == 0
        ):
            # v1 device kernel: configs 1-2 (resources + masks). Topology /
            # affinity kernels land next; until then whole-solve fallback
            # keeps semantics unforked.
            self.stats["fallback_solves"] += 1
            return self.fallback.solve(qinp)
        out = self._device_solve(enc)
        if out is None:
            self.stats["fallback_solves"] += 1
            return self.fallback.solve(qinp)
        self.stats["device_solves"] += 1
        return out

    # -- device path --------------------------------------------------------

    @staticmethod
    def _bucket(n: int, mult: int, floor: int) -> int:
        """Round up to a multiple of `mult` (min `floor`) — bounds the number
        of distinct compiled shapes (SURVEY.md §7 hard parts: bucketed padding
        avoids recompilation storms)."""
        return max(floor, ((n + mult - 1) // mult) * mult)

    def _device_solve(self, enc: EncodedInput) -> Optional[SolverResult]:
        from .tpu.ffd import ffd_solve

        args, dims = kernel_args(enc, self._bucket)
        S, E, T, G = dims["S"], dims["E"], dims["T"], dims["G"]
        total_pods = int(sum(len(p) for p in enc.group_pods))
        m = 64
        while m < min(total_pods + 1, self.max_claims):
            m *= 2
        M = min(m, max(self.max_claims, 64))

        out = ffd_solve(*args, max_claims=M)
        used = int(out.state.used)
        if used >= M:
            return None  # possible overflow — replay on fallback
        return decode(enc, np.asarray(out.take_e)[:S, :E], np.asarray(out.take_c)[:S],
                      np.asarray(out.leftover)[:S], np.asarray(out.state.c_mask)[:, :T],
                      np.asarray(out.state.c_zone), np.asarray(out.state.c_ct),
                      np.asarray(out.state.c_pool), np.asarray(out.state.c_gmask)[:, :G],
                      np.asarray(out.state.c_cum), used)


def decode(
    enc: EncodedInput,
    take_e: np.ndarray,  # [S, E]
    take_c: np.ndarray,  # [S, M]
    leftover: np.ndarray,  # [S]
    c_mask: np.ndarray,  # [M, T]
    c_zone: np.ndarray,  # [M, Z]
    c_ct: np.ndarray,  # [M, C]
    c_pool: np.ndarray,  # [M]
    c_gmask: np.ndarray,  # [M, G]
    c_cum: np.ndarray,  # [M, R]
    used: int,
) -> SolverResult:
    """Reassemble a SolverResult: pods assigned in index order per run
    (existing nodes first, then claim slots — exactly first-fit order)."""
    placements: Dict[str, Tuple[str, object]] = {}
    errors: Dict[str, str] = {}
    cursor = {g: 0 for g in range(enc.G)}
    claim_pods: Dict[int, List[str]] = {m: [] for m in range(used)}

    S = len(enc.run_group)
    for s in range(S):
        g = int(enc.run_group[s])
        n = int(enc.run_count[s])
        pods = enc.group_pods[g][cursor[g] : cursor[g] + n]
        cursor[g] += n
        i = 0
        for e in np.nonzero(take_e[s])[0]:
            for _ in range(int(take_e[s, e])):
                placements[pods[i].meta.uid] = ("node", enc.node_ids[e])
                i += 1
        for m in np.nonzero(take_c[s])[0]:
            for _ in range(int(take_c[s, m])):
                placements[pods[i].meta.uid] = ("claim", int(m))
                claim_pods[int(m)].append(pods[i].meta.uid)
                i += 1
        for _ in range(int(leftover[s])):
            errors[pods[i].meta.uid] = "no instance type in any nodepool satisfies the pod"
            i += 1

    claims: List[ClaimResult] = []
    for m in range(used):
        pool_name = enc.pool_names[int(c_pool[m])]
        type_names = [enc.type_names[t] for t in np.nonzero(c_mask[m])[0]]
        reqs = Requirements.of(Requirement.create(wk.NODEPOOL_LABEL, IN, [pool_name]))
        zones = [enc.zones[z] for z in np.nonzero(c_zone[m])[0]]
        cts = [enc.capacity_types[c] for c in np.nonzero(c_ct[m])[0]]
        if zones:
            reqs.add(Requirement.create(wk.ZONE_LABEL, IN, zones))
        if cts:
            reqs.add(Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, cts))
        for g in np.nonzero(c_gmask[m])[0]:
            reqs = reqs.union(enc.group_pods[int(g)][0].scheduling_requirements())
        requests = Resources()
        for i, k in enumerate(enc.resource_keys):
            v = int(c_cum[m, i])
            if k in ("memory", "ephemeral-storage"):
                v *= 1024**2  # decode MiB back to bytes
            if v:
                requests[k] = v
        claims.append(
            ClaimResult(
                nodepool=pool_name,
                requirements=reqs,
                instance_type_names=type_names,
                pod_uids=claim_pods[m],
                requests=requests,
                taints=[],
                hostname=f"claim-{m}",
            )
        )
    return SolverResult(placements=placements, claims=claims, errors=errors)
