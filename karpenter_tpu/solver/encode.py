"""Host-side encoder: SolverInput -> dense tensors for the TPU solver.

This is the bridge between the control plane's object model and the device
kernel (BASELINE.json north_star: "dense pod×instance-type resource-fit
tensors plus boolean constraint masks"). It performs:

  1. **Group compression** — pods with identical scheduling footprint dedupe
    into groups (the reference batches identical pods the same way; SURVEY.md
    §7 "hard parts": pairwise [P,P] terms explode at 50k pods otherwise).
  2. **Run splitting** — the exact FFD pod order (SPEC.md) is cut into runs
    of consecutive same-group pods, so the device scan processes "k identical
    pods" per step while preserving bit-identical pod order.
  3. **Quantization** — cpu milli / memory+storage MiB / counts, all int32.
    Pod requests round UP, capacities round DOWN (conservative; never
    over-packs). Both backends receive the SAME quantized numbers, so
    decisions stay bit-identical (SPEC.md "Determinism").
  4. **Mask precomputation** — [G,T] requirement compatibility, [G,E] existing
    node compatibility, [G,P] nodepool admission, [P,T] pool-type admission,
    [T,Z,C] offering availability/price, [G,G] pairwise group compatibility.

Pods the device kernel cannot express (OR'd node-affinity alternatives,
custom-topology-key terms — including custom-key weighted antis,
stacked positive hostname terms, kind-2 groups
that are also domain-constrained, single pods domain-constrained on BOTH
the zone and ct axes, or ≥3-way custom-label joint conflicts) are flagged
`fallback` — the hybrid solver routes those to the reference path (see
karpenter_tpu/solver/backend.py). Respect-mode preferences on the known
keys (ScheduleAnyway spreads, weighted positive affinity, preferred node
affinity, zone/ct/hostname weighted antis) are served on device by the
relax loop (solver/relax.py), which materializes them as required — or,
for antis, admission-only kind-3 — constraints before this encoder runs. Zone-
and capacity-type-granular spread/affinity run ON DEVICE — including solves
MIXING the two axes (concatenated domain columns, per-group axis binding) —
as does positive hostname affinity (V domain axis / Q kind 2).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import wellknown as wk
from ..api.objects import Pod, tolerates_all
from ..provisioning.scheduler import (
    ExistingNode,
    NodePoolSpec,
    SolverInput,
    ffd_sort,
    ffd_sort_with_sigs,
)
from ..scheduling.requirements import Requirements
from ..utils.resources import CPU, EPHEMERAL_STORAGE, MEMORY, PODS, Resources

MIB = 1024**2
INT32_MAX = np.int32(2**31 - 1)


class UnpackableInput(ValueError):
    """The input exceeds a device-kernel packing bound (e.g. Z*C > 32 joint
    offering bits); the hybrid solver falls back to a host path. A dedicated
    type so fallback handlers don't swallow unrelated ValueErrors."""


def mesh_run_blocks(run_group: np.ndarray, run_count: np.ndarray,
                    n_shards: int) -> Tuple[np.ndarray, np.ndarray]:
    """Partition the PADDED run axis into `n_shards` equal contiguous blocks
    for the mesh-sharded solve: [Sp] -> [n_shards, Sp/n_shards].

    Alignment contract: backend.host_kernel_args buckets S with
    mult=floor=16 (= ffd.SHARD_BLOCK_MULT), so Sp is always a multiple of
    every power-of-2 mesh size up to 16 — blocks come out equal-length with
    no extra padding, and block d is exactly runs [d*Sblk, (d+1)*Sblk) of
    the one-device scan order (padding rides at the tail of the last
    blocks, where run_count == 0 steps are no-ops). Each block row is one
    device's lane input for ffd.ffd_solve_sharded."""
    Sp = int(run_group.shape[0])
    if n_shards < 1 or Sp % n_shards:
        raise UnpackableInput(
            f"run axis Sp={Sp} does not divide into {n_shards} mesh blocks"
        )
    return (
        np.ascontiguousarray(run_group.reshape(n_shards, Sp // n_shards)),
        np.ascontiguousarray(run_count.reshape(n_shards, Sp // n_shards)),
    )


# Resource keys quantized to MiB granularity.
_MIB_KEYS = (MEMORY, EPHEMERAL_STORAGE)


def _quantize(res: Resources, keys: Sequence[str], ceil: bool) -> List[int]:
    out = []
    for k in keys:
        v = res.get_(k)
        if k in _MIB_KEYS:
            q, r = divmod(v, MIB)
            v = q + (1 if (ceil and r) else 0)
        out.append(min(int(v), int(INT32_MAX)))
    return out


def _pod_signature(pod: Pod) -> tuple:
    """Scheduling-footprint identity: pods with equal signatures behave
    identically in the solver (requests, constraints, AND labels — labels
    affect other pods' TSC/affinity selectors).

    Cached on the pod object: signatures are the encoder's only O(pods)
    Python cost, and pods are immutable during/between solves (controllers
    replace objects on update, never mutate scheduling fields in place), so
    the 50k-pod surge pays signature construction once, not once per solve."""
    sig = pod.__dict__.get("_solver_sig")
    if sig is not None:
        return sig
    sig = _pod_signature_uncached(pod)
    pod.__dict__["_solver_sig"] = sig
    return sig


# Global signature intern table: maps signature tuples to small ints so the
# per-solve group key is an int compare/hash instead of re-hashing a large
# nested tuple per pod per solve. Bounded: on overflow the table resets and
# the epoch bumps, invalidating every pod's cached id (and the compat cache
# entries keyed by (epoch, id)).
_SIG_IDS: Dict[tuple, int] = {}
_SIG_EPOCH: int = 0
_SIG_CAP = 100_000


def sig_num(pod: Pod) -> int:
    """Interned scheduling-signature id (stable within the current epoch)."""
    global _SIG_IDS, _SIG_EPOCH
    ent = pod.__dict__.get("_sig_num")
    if ent is not None and ent[0] == _SIG_EPOCH:
        return ent[1]
    if len(_SIG_IDS) >= _SIG_CAP:
        _SIG_IDS = {}
        _SIG_EPOCH += 1
        # compat-cache keys embed the epoch; entries from prior epochs are
        # unreachable forever — drop them rather than leak a generation
        _GROUP_COMPAT_CACHE.clear()
    sig = _pod_signature(pod)
    n = _SIG_IDS.setdefault(sig, len(_SIG_IDS))
    pod.__dict__["_sig_num"] = (_SIG_EPOCH, n)
    return n


def sig_nums(pods: Sequence[Pod]) -> Tuple[np.ndarray, bool]:
    """Interned ids for a batch, guaranteed mutually consistent (one epoch).

    If the intern table resets mid-batch (epoch bump), ids from before the
    bump could collide with fresh ids of different signatures — so retry once
    against the fresh table; a batch with more distinct signatures than the
    table cap falls back to batch-local interning (second value False: the
    ids are then NOT stable across calls and must not key persistent caches).
    """
    n = len(pods)
    for _ in range(2):
        e0 = _SIG_EPOCH
        arr = np.fromiter((sig_num(p) for p in pods), np.int64, n)
        if _SIG_EPOCH == e0:
            return arr, True
    local: Dict[tuple, int] = {}
    return (
        np.fromiter(
            (local.setdefault(_pod_signature(p), len(local)) for p in pods),
            np.int64,
            n,
        ),
        False,
    )


def _pod_signature_uncached(pod: Pod) -> tuple:
    return (
        tuple(sorted((k, v) for k, v in pod.requests.items() if v)),
        tuple(sorted(pod.node_selector.items())),
        tuple(
            tuple(sorted((r.key, r.complement, tuple(sorted(r.values)), r.greater_than, r.less_than, r.require_present) for r in term.values()))
            for term in pod.node_affinity
        ),
        tuple(sorted((t.key, t.operator, t.value, t.effect) for t in pod.tolerations)),
        tuple(
            (t.max_skew, t.topology_key, t.when_unsatisfiable, tuple(sorted(t.label_selector.items())))
            for t in pod.topology_spread
        ),
        tuple(
            (tuple(sorted(t.label_selector.items())), t.topology_key, t.anti,
             t.weight, t.admission_only)
            for t in pod.affinity_terms
        ),
        tuple(
            (w, tuple(sorted((r.key, tuple(sorted(r.values))) for r in reqs.values())))
            for w, reqs in pod.preferred_node_affinity
        ),
        tuple(sorted(pod.meta.labels.items())),
        pod.priority,
        pod.volume_zones,
    )


@dataclass
class EncodedInput:
    # dimensions
    resource_keys: List[str]  # the R axis
    zones: List[str]  # Z axis
    capacity_types: List[str]  # C axis
    type_names: List[str]  # T axis (catalog order)
    pool_names: List[str]  # P axis (weight desc, name asc — SPEC order)

    # groups (G axis)
    group_pods: List[List[Pod]]  # pods per group, in FFD order
    group_req: np.ndarray  # [G, R] int32 (ceil)
    group_compat_t: np.ndarray  # [G, T] bool (pod reqs vs type reqs)
    group_zone: np.ndarray  # [G, Z] bool
    group_ct: np.ndarray  # [G, C] bool
    group_pool: np.ndarray  # [G, P] bool (tolerations + reqs compat)
    group_pair: np.ndarray  # [G, G] bool (pairwise requirement compatibility)
    group_fallback: np.ndarray  # [G] bool — route to reference path

    # runs (S axis): FFD order split into same-group runs
    run_group: np.ndarray  # [S] int32
    run_count: np.ndarray  # [S] int32

    # instance types
    type_alloc: np.ndarray  # [T, R] int32 (floor)
    type_capacity: np.ndarray  # [T, R] int32 — raw capacity, for limit charging
    offer_avail: np.ndarray  # [T, Z, C] bool
    offer_price: np.ndarray  # [T, Z, C] float32 (+inf where absent)
    charge_axes: np.ndarray  # [R] bool — cpu/memory participate in limit charges

    # nodepools
    pool_type: np.ndarray  # [P, T] bool (pool reqs vs type reqs + offering overlap)
    pool_zone: np.ndarray  # [P, Z] bool
    pool_ct: np.ndarray  # [P, C] bool
    pool_daemon: np.ndarray  # [P, R] int32 (daemonset overhead incl. pod count)
    pool_limit: np.ndarray  # [P, R] int32 (INT32_MAX where unlimited)
    pool_usage: np.ndarray  # [P, R] int32

    # existing nodes (E axis)
    node_free: np.ndarray  # [E, R] int32 (floor)
    node_compat: np.ndarray  # [G, E] bool (labels+taints admission)
    node_zone: np.ndarray  # [E] int32 (index into zones, -1 unknown)
    node_ct: np.ndarray  # [E] int32
    node_ids: List[str]

    # pod uids in FFD-sorted order (= concatenation of runs); decode's
    # vectorized result assembly indexes this instead of walking pod objects
    sorted_uids: np.ndarray = field(default_factory=lambda: np.zeros(0, dtype=object))

    # topology / affinity (config 3-4) — filled by encode, used by tpu kernels
    # True only for constructs still off-device (custom-key spread, positive
    # hostname affinity, mixed zone+ct domain axes, duplicate node
    # hostnames); zone- and ct-granular terms run on device via the V axis.
    has_topology: bool = False
    has_affinity: bool = False

    # tenancy (solver/tenancy.py): stamped from SolverInput.tenant_id so the
    # backend can namespace arena RESIDENCY per tenant while compile buckets
    # stay shape-keyed and shared. Never consulted by the solving math.
    tenant_id: Optional[str] = None

    # zone-granular constraints (V axis), run by the device event engine
    # (ffd.py zone loop; SPEC.md "Topology spread" / "Inter-pod affinity"):
    # v_kind 0 = zone TSC (cap = maxSkew), 1 = zone anti-affinity,
    # 2 = zone positive affinity.
    v_member: Optional[np.ndarray] = None  # [G, V] bool — pods match sig selector
    v_owner: Optional[np.ndarray] = None  # [G, V] bool — pods carry the constraint
    v_kind: Optional[np.ndarray] = None  # [V] int32
    v_cap: Optional[np.ndarray] = None  # [V] int32 (maxSkew for TSC)
    v_primary: Optional[np.ndarray] = None  # [G] int32 — group's owned zone-TSC sig (-1)
    v_aff: Optional[np.ndarray] = None  # [G] int32 — group's owned positive-affinity sig (-1)
    v_count0: Optional[np.ndarray] = None  # [V, D] int32 initial matching-pod counts
    # per-node share of v_count0 (node e contributes node_v_member[e] at its
    # domain) — lets the batched consolidation evaluator subtract a removed
    # candidate node's bound pods from the domain counts per subset
    node_v_member: Optional[np.ndarray] = None  # [E, V] int32
    # which axis the V sigs spread over — "zone" (default) or "ct": the
    # event engine is domain-generic, so capacity-type TSC/affinity runs on
    # it by presenting lex-ordered ct values as the domain axis (the D in
    # the shapes above); v_node_domain maps nodes into that axis
    v_axis: str = "zone"
    v_domains: Optional[List[str]] = None  # D axis values, lex order
    v_node_domain: Optional[np.ndarray] = None  # [E] int32 (-1 unknown)
    # mixed-axis ("mixed") extras — see ffd.ARG_SPEC tail
    sig_axis: Optional[np.ndarray] = None  # [V] i32 axis id per sig
    group_daxis: Optional[np.ndarray] = None  # [G] i32 axis per group
    node_dom2: Optional[np.ndarray] = None  # [E] i32 second-axis column (-1)

    # scheduling-class tensors (SPEC.md "Priority, preemption & gang
    # semantics"; ffd.CLASS_ARG_SPEC): per-run dense priority rank (higher
    # priority ⇒ higher rank — lossless for the strict-order comparisons
    # preemption makes), per-run gang index (-1 = no gang) into the per-gang
    # tables, and the per-gang declared size / minimum ranks. These ride a
    # SIDE table, not ffd.ARG_SPEC: the base scan is class-blind (priority
    # already orders the runs), so the frozen 36-tensor contract — arena
    # residency, AOT shapes, resume/ladder/sharded splices — stays intact.
    run_prio16: Optional[np.ndarray] = None  # [S] uint16
    run_gang: Optional[np.ndarray] = None  # [S] int32 (-1 = none)
    gang_size: Optional[np.ndarray] = None  # [NG] int32
    gang_min_ranks: Optional[np.ndarray] = None  # [NG] int32
    gang_ids: Optional[List[str]] = None  # NG axis values, lex order

    # revision stamp of the encode core this input was assembled around
    # (_EncodeCore.core_rev): same stamp ⇒ byte-identical core tables.
    # backend.host_kernel_args derives per-entry provenance tokens from it
    # so the argument arena skips hashing/uploading core-derived args.
    core_rev: int = -1
    # interned sort-signature number per group (same universe as
    # encode_cache's patch check); () when sigs were not interned. Run-list
    # prefix matching (encode_cache.run_identity) keys on these so a group
    # index means the same pod spec across two encodes.
    group_snums: tuple = ()

    @property
    def v_domain_perm(self) -> List[int]:
        """ct-mode only: indices into capacity_types in canonical v_domains
        order — THE single source of the lex tiebreak, shared by the device
        column masks (backend.kernel_args) and the native marshal swap."""
        return [self.capacity_types.index(d) for d in self.v_domains]

    @property
    def V(self) -> int:
        return 0 if self.v_kind is None else len(self.v_kind)

    # hostname-granular constraints (Q axis), handled closed-form on device:
    # per-(node, sig) matching-pod counts cap the pour. q_kind 0 = hostname
    # TSC (cap = maxSkew, floor-0 rule per SPEC.md), 1 = hostname
    # anti-affinity (owner blocked where members present and vice versa).
    q_member: Optional[np.ndarray] = None  # [G, Q] bool — group's pods match sig selector
    q_owner: Optional[np.ndarray] = None  # [G, Q] bool — group's pods carry the constraint
    q_kind: Optional[np.ndarray] = None  # [Q] int32
    q_cap: Optional[np.ndarray] = None  # [Q] int32 (maxSkew for TSC; 1 for anti)
    node_q_member: Optional[np.ndarray] = None  # [E, Q] int32 initial matching-pod counts
    node_q_owner: Optional[np.ndarray] = None  # [E, Q] int32 initial owner-pod presence

    @property
    def Q(self) -> int:
        return 0 if self.q_kind is None else len(self.q_kind)

    @property
    def G(self) -> int:
        return len(self.group_pods)

    @property
    def T(self) -> int:
        return len(self.type_names)

    @property
    def E(self) -> int:
        return len(self.node_ids)

    @property
    def P(self) -> int:
        return len(self.pool_names)


def quantize_resources(res: Resources, ceil: bool) -> Resources:
    """MiB-quantize memory-like values (requests ceil, capacities floor).

    The canonical solver arithmetic is MiB-granular (SPEC.md); feeding both
    backends identically-quantized inputs is what makes decisions
    bit-identical. Conservative direction: never over-packs."""
    out = Resources(res)
    for k in _MIB_KEYS:
        if k in out:
            q, r = divmod(out[k], MIB)
            out[k] = (q + (1 if (ceil and r) else 0)) * MIB
    return out


_QUANTIZED_TYPE_CACHE: dict = {}

# id(type) -> (type, rkeys tuple, alloc row, capacity row) — see encode()
_TYPE_ROW_CACHE: dict = {}

# pod-signature -> (catalog id-tuple, pinned types, [T] bool compat row)
_GROUP_COMPAT_CACHE: dict = {}

# Label-dict intern table + selector-match verdict cache: the Q/V member
# tables (both the [G,*] group side and the [E,*] node side) reduce to
# "does selector S match label-set L" — a pure function of content. Interning
# every distinct label dict to a small id and caching the verdict per
# (selector, label-id) turns the former per-(node, sig, bound-pod) Python
# loops into one verdict per DISTINCT (selector, label-set) plus vectorized
# gathers. Both tables clear together on overflow (verdict keys embed label
# ids, so a stale verdict can never pair with a recycled id).
_LAB_IDS: Dict[tuple, int] = {}
_LAB_CAP = 200_000
_SEL_MATCH: Dict[tuple, bool] = {}


def _lab_id(labels: dict) -> int:
    global _LAB_IDS, _SEL_MATCH
    key = tuple(sorted(labels.items()))
    n = _LAB_IDS.get(key)
    if n is None:
        if len(_LAB_IDS) >= _LAB_CAP:
            _LAB_IDS = {}
            _LAB_KEYS.clear()
            _SEL_MATCH.clear()
        n = len(_LAB_IDS)
        _LAB_IDS[key] = n
        _LAB_KEYS[n] = key
    return n


_LAB_KEYS: Dict[int, tuple] = {}  # reverse map (rebuilt lazily on clear)


def _sel_verdicts(sel_sig: tuple, lids: np.ndarray) -> np.ndarray:
    """[len(lids)] bool — does the selector match each interned label set."""
    out = np.empty(len(lids), dtype=bool)
    sel = dict(sel_sig)
    for i, lid in enumerate(lids.tolist()):
        v = _SEL_MATCH.get((sel_sig, lid))
        if v is None:
            lab = dict(_LAB_KEYS[lid])
            v = all(lab.get(k) == val for k, val in sel.items())
            _SEL_MATCH[(sel_sig, lid)] = v
        out[i] = v
    return out


def _quantize_type(it):
    """Per-InstanceType quantization, cached by object identity (the catalog
    is static across solves; 50k-pod solves must not pay a deepcopy)."""
    cached = _QUANTIZED_TYPE_CACHE.get(id(it))
    if cached is not None and cached[0] is it:
        return cached[1]
    from dataclasses import replace as _replace

    q = _replace(
        it,
        capacity=quantize_resources(it.capacity, ceil=False),
        overhead=quantize_resources(it.overhead, ceil=True),
    )
    if len(_QUANTIZED_TYPE_CACHE) > 8192:
        _QUANTIZED_TYPE_CACHE.clear()  # bound against catalog-churn growth
    _QUANTIZED_TYPE_CACHE[id(it)] = (it, q)
    return q


def _already_mib_aligned(res: Resources) -> bool:
    for k in _MIB_KEYS:
        v = res.get(k)
        if v is not None and v % MIB:
            return False
    return True


_QUANT_PODS_CACHE: Dict[tuple, list] = {}
_QUANT_PODS_CACHE_MAX = 4


def _quantized_pods(pods: list) -> list:
    """MiB-quantized pod list, cached by (mutation epoch, identity
    fingerprint): a control loop re-quantizing an unchanged 50k-pod surge
    pays a fingerprint pass instead of a per-pod alignment walk."""
    from dataclasses import replace as _replace

    from ..api.objects import pod_mutation_epoch

    n = len(pods)
    ids = None
    if n > 64:
        ids = np.fromiter(map(id, pods), np.uint64, n)
        key = (
            pod_mutation_epoch(),
            n,
            int(ids.sum(dtype=np.uint64)),
            int(np.bitwise_xor.reduce(ids)),
        )
        hit = _QUANT_PODS_CACHE.get(key)
        # exact id-array compare: the aggregate fingerprint can collide
        # between distinct live pod sets; pinned entries make ids stable
        if hit is not None and np.array_equal(ids, hit[0]):
            return hit[2]
    else:
        key = None

    def qpod(p):
        # alignment verdict cached on the pod (invalidated by field assignment,
        # objects.py Pod.__setattr__): typical requests are MiB-aligned, so a
        # 50k-pod surge pays one dict hit per pod instead of a Resources walk
        a = p.__dict__.get("_mib_aligned")
        if a is None:
            a = _already_mib_aligned(p.requests)
            p.__dict__["_mib_aligned"] = a
        if a:
            return p
        return _replace(p, requests=quantize_resources(p.requests, ceil=True))

    out = [qpod(p) for p in pods]
    if key is not None:
        if len(_QUANT_PODS_CACHE) >= _QUANT_PODS_CACHE_MAX:
            _QUANT_PODS_CACHE.pop(next(iter(_QUANT_PODS_CACHE)))
        # pin the INPUT pods too: unaligned pods are replaced in `out`, and
        # without a reference the originals could be freed and their ids
        # recycled into a colliding fingerprint (fresh pods never bump the
        # mutation epoch)
        _QUANT_PODS_CACHE[key] = (ids, tuple(pods), out)
    return out


def quantize_input(inp: SolverInput) -> SolverInput:
    """A structurally-shared copy of `inp` with all resources MiB-quantized —
    what the hybrid production path and the parity tests feed the reference
    solver so both backends see identical numbers. Only fields that actually
    need quantizing become fresh objects; everything else is shared IDENTITY
    (nothing downstream mutates pods/types), which keeps per-pod caches
    (signature, FFD key) warm across solves — typical requests like "1Gi"
    are already MiB-aligned, so a 50k-pod surge copies nothing."""
    from dataclasses import replace as _replace

    def qnode(n):
        if _already_mib_aligned(n.free):
            return n
        return _replace(n, free=quantize_resources(n.free, ceil=False))

    return SolverInput(
        pods=_quantized_pods(inp.pods),
        nodes=[qnode(n) for n in inp.nodes],
        nodepools=[
            _replace(pool, instance_types=[_quantize_type(it) for it in pool.instance_types])
            for pool in inp.nodepools
        ],
        daemonset_pods=_quantized_pods(inp.daemonset_pods),
        zones=inp.zones,
        capacity_types=inp.capacity_types,
        preference_policy=inp.preference_policy,
        state_rev=getattr(inp, "state_rev", None),
        tenant_id=getattr(inp, "tenant_id", None),
    )


@dataclass
class _EncodeCore:
    """The pod/pool/type-dependent stage of encode(), cached across solves.

    Keyed by (pod-mutation epoch, identity fingerprint of the filtered pod
    set, pool/type content-and-identity keys, axes): a control loop that
    re-solves an unchanged pending surge pays O(1) host work instead of the
    O(pods) sort/signature/grouping passes (the e2e Solve() seam's dominant
    host cost at 50k pods). Existing-node tensors and pool usage/limits are
    rebuilt every call — they change between solves."""

    zones: List[str]
    cts: List[str]
    type_names: List[str]
    pool_names: List[str]
    rkeys: List[str]
    charge_axes: np.ndarray
    group_pods: List[List[Pod]]
    group_req: np.ndarray
    group_compat_t: np.ndarray
    group_zone: np.ndarray
    group_ct: np.ndarray
    group_pool: np.ndarray
    group_pair: np.ndarray
    fallback: np.ndarray
    run_group: np.ndarray
    run_count: np.ndarray
    sorted_uids: np.ndarray
    group_reqsets: List[Requirements]
    has_topo: bool
    has_aff: bool
    hostname_sigs: Dict[tuple, int]
    zone_sigs: Dict[tuple, int]  # (axis, kind, sel_sig, cap) -> v index
    v_axis: str  # "zone" | "ct" | "mixed" — domain-axis layout of the V sigs
    sig_axis: np.ndarray  # [V] i32 — axis id per sig (0 zones, 1 cts)
    group_daxis: np.ndarray  # [G] i32 — axis a constrained group's engine uses
    q_member: np.ndarray
    q_owner: np.ndarray
    q_kind: np.ndarray
    q_cap: np.ndarray
    v_member: np.ndarray
    v_owner: np.ndarray
    v_kind: np.ndarray
    v_cap: np.ndarray
    v_primary: np.ndarray
    v_aff: np.ndarray
    type_alloc: np.ndarray
    type_capacity: np.ndarray
    offer_avail: np.ndarray
    offer_price: np.ndarray
    pool_type: np.ndarray
    pool_zone: np.ndarray
    pool_ct: np.ndarray
    pool_daemon: np.ndarray
    all_req_keys: List[str]
    zid: Dict[str, int]
    cid: Dict[str, int]
    # patch-layer identity (solver/encode_cache.py): the ordered DISTINCT
    # interned signature ids this core was built from, and the intern epoch
    # they are valid in. Every [G]/[T]/[P]-indexed table above is a pure
    # function of (this sequence, the catalog segment of the cache key), so
    # a new pod set producing the same sequence under the same epoch can
    # reuse them verbatim. () / -1 = not patchable (batch-local sig ids).
    group_snums: tuple = ()
    sig_epoch: int = -1
    # content-identity revision (encode_cache.next_core_rev): stamped by
    # every full _build_core, PRESERVED by try_patch (shared tables are the
    # donor's). (core_rev, table name) is the provenance token the argument
    # arena / device-conversion caches key on. -1 = no provenance.
    core_rev: int = -1
    # scheduling-class tables: priority and gang labels are INSIDE the pod
    # signature, so these are pure functions of the distinct-signature
    # sequence like every other [G] table — try_patch shares them verbatim,
    # and a priority/gang edit changes the affected snums, invalidating
    # exactly the runs it touches (encode_cache.run_identity).
    group_prio16: Optional[np.ndarray] = None  # [G] uint16 dense rank
    group_gang: Optional[np.ndarray] = None  # [G] int32 (-1 = none)
    gang_size: Optional[np.ndarray] = None  # [NG] int32
    gang_min_ranks: Optional[np.ndarray] = None  # [NG] int32
    gang_ids: Optional[List[str]] = None  # NG axis, lex order


_CORE_CACHE: Dict[tuple, tuple] = {}
_CORE_CACHE_MAX = 4


def _group_structure(pods_sorted: List[Pod], sigs: np.ndarray):
    """Group/run decomposition of an FFD-sorted pod list: per-group pod
    lists (first-appearance order), the run split, and the ordered distinct
    signature sequence. Pure NumPy except the run-slice extends."""
    n_pods = len(pods_sorted)
    if not n_pods:
        return [], np.zeros(0, np.int32), np.zeros(0, np.int32), ()
    # group ids in first-appearance order over the sorted sequence
    _, first_idx, inv = np.unique(sigs, return_index=True, return_inverse=True)
    rank = np.empty(len(first_idx), np.int64)
    rank[np.argsort(first_idx, kind="stable")] = np.arange(len(first_idx))
    gids = rank[inv]
    G = len(first_idx)
    # runs: consecutive same-group stretches of the sorted pod list
    change = np.flatnonzero(np.diff(gids) != 0) + 1
    starts = np.concatenate(([0], change))
    run_group = gids[starts].astype(np.int32)
    run_count = np.diff(np.concatenate((starts, [n_pods]))).astype(np.int32)
    # per-group pod lists assembled run-by-run (S slices of the sorted
    # list, C-speed extend) — NOT via an object ndarray: numpy's
    # list→object-array fill probes every element and costs ~70ms at 50k
    group_pods: List[List[Pod]] = [[] for _ in range(G)]
    pos = 0
    for s in range(len(run_group)):
        c = int(run_count[s])
        group_pods[int(run_group[s])].extend(pods_sorted[pos : pos + c])
        pos += c
    group_snums = tuple(int(s) for s in sigs[np.sort(first_idx)])
    return group_pods, run_group, run_count, group_snums


def _reqs_key(reqs: Requirements) -> tuple:
    return tuple(
        sorted(
            (k, r.complement, tuple(sorted(r.values)), r.greater_than,
             r.less_than, r.require_present)
            for k, r in reqs.items()
        )
    )


def _core_key(pods_f: List[Pod], inp: SolverInput) -> Tuple[tuple, np.ndarray]:
    """Cache key + the exact ordered pod-id array. The key's pod part is an
    aggregate fingerprint (fast dict hash); a hit must ALSO compare the id
    array exactly — aggregates can collide between distinct live sets. Pinning
    (group_pods in the cached core, instance types in the entry) guarantees a
    matching id refers to the same live object, never a recycled address."""
    from ..api.objects import pod_mutation_epoch

    n = len(pods_f)
    if n:
        ids = np.fromiter(map(id, pods_f), np.uint64, n)
        pod_fp = (n, int(ids.sum(dtype=np.uint64)), int(np.bitwise_xor.reduce(ids)))
    else:
        ids = np.zeros(0, np.uint64)
        pod_fp = (0, 0, 0)
    pools_key = tuple(
        (
            p.name,
            p.weight,
            _reqs_key(p.requirements),
            tuple((t.key, t.value, t.effect) for t in p.taints),
            tuple(map(id, p.instance_types)),
        )
        for p in inp.nodepools
    )
    ds_key = tuple(
        (
            tuple(sorted(dp.requests.items())),
            tuple((t.key, t.operator, t.value, t.effect) for t in dp.tolerations),
            _reqs_key(dp.scheduling_requirements()),
        )
        for dp in inp.daemonset_pods
    )
    return (
        (
            pod_mutation_epoch(),
            pod_fp,
            pools_key,
            ds_key,
            tuple(inp.zones),
            tuple(inp.capacity_types),
            inp.preference_policy,
            getattr(inp, "presorted", False),
        ),
        ids,
    )


# Catalog CONTENT fingerprint (solver/vault.py): the cache key's catalog
# segment compares instance types BY OBJECT ID (cheap, and pinned entries
# make ids safe within a process) — but ids mean nothing across a process
# boundary, so vault donors are re-keyed by this content hash instead.
# Memoized on pools_key (which embeds the type ids, so a hit proves the
# same live objects → same content) and bounded; computed only on the
# cache-INSERT path, never per solve.
_CAT_FP_CACHE: Dict[tuple, bytes] = {}
_CAT_FP_CACHE_MAX = 8


def _catalog_content_fp(pools_key: tuple, inp: SolverInput) -> bytes:
    import hashlib

    fp = _CAT_FP_CACHE.get(pools_key)
    if fp is not None:
        return fp
    parts: List[tuple] = []
    for p in inp.nodepools:
        parts.append((
            p.name,
            p.weight,
            _reqs_key(p.requirements),
            tuple((t.key, t.value, t.effect) for t in p.taints),
            tuple(
                (
                    it.name,
                    tuple(sorted(it.capacity.items())),
                    tuple(sorted(it.overhead.items())),
                    _reqs_key(it.requirements),
                    tuple(
                        sorted(
                            (o.zone, o.capacity_type, o.price, o.available)
                            for o in it.offerings
                        )
                    ),
                )
                for it in p.instance_types
            ),
        ))
    fp = hashlib.blake2b(repr(parts).encode(), digest_size=16).digest()
    if len(_CAT_FP_CACHE) >= _CAT_FP_CACHE_MAX:
        _CAT_FP_CACHE.pop(next(iter(_CAT_FP_CACHE)))
    _CAT_FP_CACHE[pools_key] = fp
    return fp


def _sig_content_seq(group_pods: List[List[Pod]]) -> tuple:
    """Ordered distinct signature CONTENT sequence of a group structure —
    the process-portable twin of group_snums (interned numbers are
    process-local; the signature tuples they intern are pure content)."""
    return tuple(_pod_signature(pl[0]) for pl in group_pods)


def encode(inp: SolverInput) -> EncodedInput:
    from . import encode_cache as ec

    tenant_id = getattr(inp, "tenant_id", None)
    pods_f = [p for p in inp.pods if not p.scheduling_gated and p.node_name is None]
    if getattr(inp, "presorted", False):
        # relax-loop encodes materialize FRESH pod objects every iteration:
        # caching them would only evict hot production cores and pin dead
        # pod lists (r5 review) — build uncached
        enc = _encode_with_nodes(_build_core(inp, pods_f), inp)
        enc.tenant_id = tenant_id
        return enc
    # tenancy: each tenant patches/evicts inside its OWN core-cache
    # namespace (solver/tenancy.py sharing boundary) — a noisy tenant can't
    # evict another tenant's hot core or donate a patch across clusters.
    # tenant_id=None keeps using the module-global _CORE_CACHE verbatim.
    cache = ec.tenant_core_cache(tenant_id, _CORE_CACHE)
    key, ids = _core_key(pods_f, inp)
    ent = cache.get(key)
    if ent is not None and np.array_equal(ids, ent[0]):
        ec.STATS["hits"] += 1
        core = ent[1]
    else:
        # delta-patch path: same sig universe + same catalog as a cached
        # core (pods added/removed within known groups) reuses every
        # group/type/pool table and rebuilds only the run split — falls
        # back to a full build for any other delta class
        presort = ffd_sort_with_sigs(pods_f, presorted=False)
        structure = _group_structure(presort[0], presort[1])
        state_rev = getattr(inp, "state_rev", None)
        cat_fp = _catalog_content_fp(key[2], inp)
        core = ec.try_patch(key, presort, structure, cache, state_rev)
        if core is not None:
            ec.STATS["patches"] += 1
        elif ec._VAULT_DONORS:
            # vault-restored donors (solver/vault.py) are keyed by CONTENT
            # — signature sequence + catalog fingerprint — so a restarted
            # process adopts its predecessor's tables instead of paying the
            # cluster-size-bounded rebuild
            core = ec.adopt_vault_donor(
                key, structure, _sig_content_seq(structure[0]), cat_fp,
                presort,
            )
            if core is not None:
                ec.STATS["vault_adopts"] += 1
        if core is None:
            core = _build_core(inp, pods_f, presort, structure)
            ec.STATS["rebuilds"] += 1
        if len(cache) >= _CORE_CACHE_MAX:
            cache.pop(next(iter(cache)))
        # entry pins the instance-type objects whose ids appear in the key
        # (pods are pinned via core.group_pods), so ids can't be recycled
        # while the entry lives
        type_pins = tuple(it for p in inp.nodepools for it in p.instance_types)
        cache[key] = (ids, core, type_pins, state_rev, cat_fp)
    enc = _encode_with_nodes(core, inp)
    enc.tenant_id = tenant_id
    return enc


def _build_core(
    inp: SolverInput,
    pods_f: List[Pod],
    presort: Optional[tuple] = None,
    structure: Optional[tuple] = None,
) -> _EncodeCore:
    # ---- axes -------------------------------------------------------------
    zones = list(inp.zones)
    cts = list(inp.capacity_types)
    pools = sorted(inp.nodepools, key=lambda p: (-p.weight, p.name))
    pool_names = [p.name for p in pools]

    # union catalog over pools, preserving first-seen (catalog) order
    type_names: List[str] = []
    types_by_name: Dict[str, object] = {}
    for p in pools:
        for it in p.instance_types:
            if it.name not in types_by_name:
                types_by_name[it.name] = it
                type_names.append(it.name)
    T = len(type_names)

    # ---- groups (vectorized: the only O(pods) work is cached-key gathering)
    if presort is None:
        presort = ffd_sort_with_sigs(
            pods_f, presorted=getattr(inp, "presorted", False)
        )
    pods_sorted, sigs, sorted_uids, sigs_interned = presort
    if structure is None:
        structure = _group_structure(pods_sorted, sigs)
    group_pods, run_group, run_count, group_snums = structure
    G = len(group_pods)

    # ---- resource axis (from group representatives — same-group pods have
    # identical requests, so the scan is O(groups), not O(pods)) -------------
    rkeys = [CPU, MEMORY, PODS]
    seen = set(rkeys)
    for pod in [pl[0] for pl in group_pods] + list(inp.daemonset_pods):
        for k, v in pod.requests.items():
            if v and k not in seen:
                seen.add(k)
                rkeys.append(k)
    R = len(rkeys)

    group_req = np.zeros((G, R), dtype=np.int32)
    for g, pl in enumerate(group_pods):
        req = Resources(pl[0].requests)
        req[PODS] = req.get_(PODS) + 1  # each pod consumes one pod slot
        group_req[g] = _quantize(req, rkeys, ceil=True)

    # representative requirement set per group (v1: single alternative)
    group_reqsets: List[Requirements] = []
    fallback = np.zeros(G, dtype=bool)
    has_topo = False
    has_aff = False
    hostname_sigs: Dict[tuple, int] = {}  # (kind, sel_sig, cap) -> q index
    zone_sigs: Dict[tuple, int] = {}  # (kind, sel_sig, cap) -> v index
    ct_sigs: Dict[tuple, int] = {}  # capacity-type-granular sigs (same shape)
    # per-group owned sigs, collected to fill v_owner / v_primary below
    group_zone_tscs: List[List[tuple]] = []
    group_zone_antis: List[List[tuple]] = []
    group_zone_affs: List[List[tuple]] = []
    group_ct_tscs: List[List[tuple]] = []
    group_ct_antis: List[List[tuple]] = []
    group_ct_affs: List[List[tuple]] = []
    group_h2: List[bool] = []  # owns a positive hostname-affinity term
    # hostname sigs OWNED per group, collected during the term scan below —
    # a term that constructs/merges a sig key is exactly what the former
    # per-sig rescan matched, so collection is the same ownership relation
    # without the O(G·Q) second pass
    group_h_owned: List[List[tuple]] = []
    respect_prefs = inp.preference_policy != "Ignore"
    for g, pl in enumerate(group_pods):
        pod = pl[0]
        h_owned: List[tuple] = []
        if len(pod.node_affinity) > 1:
            fallback[g] = True
        if respect_prefs and (
            pod.preferred_node_affinity
            or any(t.when_unsatisfiable != "DoNotSchedule" for t in pod.topology_spread)
            or any(t.weight is not None for t in pod.affinity_terms)
        ):
            # preferences relax as-required in the oracle (scheduling.md:
            # 212-219); under --preference-policy=Ignore they vanish and the
            # device path keeps the solve
            fallback[g] = True
        ztscs: List[tuple] = []
        zantis: List[tuple] = []
        zaffs: List[tuple] = []
        ctscs: List[tuple] = []
        cantis: List[tuple] = []
        caffs: List[tuple] = []
        for t in pod.topology_spread:
            if t.when_unsatisfiable != "DoNotSchedule":
                continue
            if t.topology_key == wk.HOSTNAME_LABEL:
                # closed-form on device (per-node matching-pod cap = maxSkew,
                # SPEC.md hostname floor-0 rule)
                sig = (0, tuple(sorted(t.label_selector.items())), t.max_skew)
                hostname_sigs.setdefault(sig, len(hostname_sigs))
                h_owned.append(sig)
            elif t.topology_key == wk.ZONE_LABEL:
                sig = (0, tuple(sorted(t.label_selector.items())), t.max_skew)
                zone_sigs.setdefault(sig, len(zone_sigs))
                ztscs.append(sig)
            elif t.topology_key == wk.CAPACITY_TYPE_LABEL:
                sig = (0, tuple(sorted(t.label_selector.items())), t.max_skew)
                ct_sigs.setdefault(sig, len(ct_sigs))
                ctscs.append(sig)
            else:
                has_topo = True  # custom-key spread: fallback path
        has_h2 = False
        n_h2 = 0
        for t in pod.affinity_terms:
            if t.weight is not None:
                continue
            if t.anti and t.topology_key == wk.HOSTNAME_LABEL:
                # kind 3 = admission-only (relax-materialized weighted anti):
                # same blocking allowance as kind 1, but the e_co/c_co owner
                # registrations stay kind-1-only — future members unblocked
                sig = (3 if t.admission_only else 1,
                       tuple(sorted(t.label_selector.items())), 1)
                hostname_sigs.setdefault(sig, len(hostname_sigs))
                h_owned.append(sig)
            elif t.topology_key == wk.HOSTNAME_LABEL:
                # positive hostname affinity (kind 2): per-target allowance
                # where members are present + a one-claim bootstrap budget
                # (ffd._hostname_allowance / fast())
                sig = (2, tuple(sorted(t.label_selector.items())), 0)
                hostname_sigs.setdefault(sig, len(hostname_sigs))
                h_owned.append(sig)
                has_h2 = True
                n_h2 += 1
            elif t.topology_key == wk.ZONE_LABEL:
                # kind 3 = admission-only anti (relax-materialized weighted
                # anti): blocks THIS pod's placement like a required anti but
                # never registers as an owned anti — the oracle's bookkeeping
                # records only original required terms
                kind = (3 if t.admission_only else 1) if t.anti else 2
                sig = (kind, tuple(sorted(t.label_selector.items())), 1 if t.anti else 0)
                zone_sigs.setdefault(sig, len(zone_sigs))
                (zantis if t.anti else zaffs).append(sig)
            elif t.topology_key == wk.CAPACITY_TYPE_LABEL:
                kind = (3 if t.admission_only else 1) if t.anti else 2
                sig = (kind, tuple(sorted(t.label_selector.items())), 1 if t.anti else 0)
                ct_sigs.setdefault(sig, len(ct_sigs))
                (cantis if t.anti else caffs).append(sig)
            else:
                has_aff = True  # custom-key affinity: fallback
        # the domain event engine drives ONE owned TSC and ONE positive
        # affinity per pod — including BOTH on the same pod (round 5: the
        # engine's allowed set already intersects the TSC budget with the
        # affinity present-set exactly as the oracle's sequential narrowing
        # does; parity pinned by tests/test_stacked_device.py). Multiple
        # terms of the SAME kind still fall back.
        if len(ztscs) > 1 or len(zaffs) > 1:
            fallback[g] = True
        if len(ctscs) > 1 or len(caffs) > 1:
            fallback[g] = True
        if n_h2 > 1:
            # stacked positive hostname terms: the single-target bootstrap
            # derivation only covers one term — oracle handles the corner
            fallback[g] = True
        group_zone_tscs.append(ztscs)
        group_zone_antis.append(zantis)
        group_zone_affs.append(zaffs)
        group_ct_tscs.append(ctscs)
        group_ct_antis.append(cantis)
        group_ct_affs.append(caffs)
        group_h2.append(has_h2)
        group_h_owned.append(h_owned)
        group_reqsets.append(pod.scheduling_requirements())

    # ---- domain-axis resolution -------------------------------------------
    # The V-axis event engine is domain-GENERIC: it sees only per-domain
    # column masks of the joint (zone, ct) bits, per-domain counts, and a
    # node→domain map — so capacity-type-granular constraints (the third of
    # the reference's exactly-three topology keys, scheduling.md:383-387)
    # run on the SAME engine by presenting the C axis as the domain axis.
    # A solve mixing zone- and ct-granular sigs runs with BOTH axes'
    # columns concatenated on the domain axis ("mixed"): each sig and each
    # constrained group binds to ONE axis (group_daxis), counts record per
    # axis wherever a target's domain is determined, and only pods whose
    # own constraint set genuinely spans both axes fall back.
    v_axis = "zone"
    if ct_sigs and zone_sigs:
        v_axis = "mixed"
    elif ct_sigs:
        v_axis = "ct"

    # normalize sigs to (axis, kind, sel, cap) keys; zone sigs keep their
    # indices so single-axis solves stay bit- and shape-identical
    if v_axis == "mixed":
        vsigs = {(0,) + s: i for s, i in zone_sigs.items()}
        off = len(zone_sigs)
        vsigs.update({(1,) + s: off + i for s, i in ct_sigs.items()})
        g_tscs = [
            [(0,) + s for s in group_zone_tscs[g]]
            + [(1,) + s for s in group_ct_tscs[g]]
            for g in range(G)
        ]
        g_antis = [
            [(0,) + s for s in group_zone_antis[g]]
            + [(1,) + s for s in group_ct_antis[g]]
            for g in range(G)
        ]
        g_affs = [
            [(0,) + s for s in group_zone_affs[g]]
            + [(1,) + s for s in group_ct_affs[g]]
            for g in range(G)
        ]
    elif v_axis == "ct":
        vsigs = {(0,) + s: i for s, i in ct_sigs.items()}
        g_tscs = [[(0,) + s for s in group_ct_tscs[g]] for g in range(G)]
        g_antis = [[(0,) + s for s in group_ct_antis[g]] for g in range(G)]
        g_affs = [[(0,) + s for s in group_ct_affs[g]] for g in range(G)]
    else:
        vsigs = {(0,) + s: i for s, i in zone_sigs.items()}
        g_tscs = [[(0,) + s for s in group_zone_tscs[g]] for g in range(G)]
        g_antis = [[(0,) + s for s in group_zone_antis[g]] for g in range(G)]
        g_affs = [[(0,) + s for s in group_zone_affs[g]] for g in range(G)]

    # ---- domain-sig (V axis) tables -----------------------------------------
    V = len(vsigs)
    v_member = np.zeros((G, V), dtype=bool)
    v_owner = np.zeros((G, V), dtype=bool)
    v_kind = np.zeros(V, dtype=np.int32)
    v_cap = np.zeros(V, dtype=np.int32)
    sig_axis = np.zeros(V, dtype=np.int32)
    v_primary = np.full(G, -1, dtype=np.int32)
    v_aff = np.full(G, -1, dtype=np.int32)
    group_daxis = np.zeros(G, dtype=np.int32)
    # member tables are selector-vs-representative-label verdicts: intern
    # the label dicts, evaluate once per DISTINCT (selector, label set)
    # (global cache), and gather — replaces the per-(sig, group) Python scan
    if G and (vsigs or hostname_sigs):
        rep_lids = np.fromiter(
            (_lab_id(pl[0].meta.labels) for pl in group_pods), np.int64, G
        )
        uniq_l, inv_l = np.unique(rep_lids, return_inverse=True)
    for (ax, kind, sel_sig, cap), v in vsigs.items():
        v_kind[v] = kind
        v_cap[v] = cap
        sig_axis[v] = ax
        if G:
            v_member[:, v] = _sel_verdicts(sel_sig, uniq_l)[inv_l]
    for g in range(G):
        axes = set()
        for sig in g_tscs[g]:
            v_owner[g, vsigs[sig]] = True
            v_primary[g] = vsigs[sig]
            axes.add(sig[0])
        for sig in g_antis[g]:
            v_owner[g, vsigs[sig]] = True
            axes.add(sig[0])
        for sig in g_affs[g]:
            v_owner[g, vsigs[sig]] = True
            v_aff[g] = vsigs[sig]
            axes.add(sig[0])
        # a membership in an anti sig blocks domains on that sig's axis —
        # it binds the group to the axis just like ownership does
        manti = v_member[g] & (v_kind == 1)
        if manti.any():
            axes.update(int(a) for a in sig_axis[manti])
        if len(axes) > 1:
            # genuinely two-axis pod (e.g. zone TSC + ct spread on ONE pod,
            # or zone-constrained while a ct anti selects it): the engine
            # drives one rotation state per group — oracle handles it
            fallback[g] = True
        elif axes:
            group_daxis[g] = axes.pop()
    # kind-2 hostname affinity is implemented in the FAST branch only (the
    # one-claim bootstrap budget is not threaded through the zoned event
    # engine's open paths): a group owning one that is ALSO domain-
    # constrained (owns V sigs or is a member of a domain anti — either
    # routes it to the zoned branch) falls back
    for g in range(G):
        if group_h2[g] and (
            v_owner[g].any() or (v_member[g] & (v_kind == 1)).any()
        ):
            fallback[g] = True

    Q = len(hostname_sigs)
    q_member = np.zeros((G, Q), dtype=bool)
    q_owner = np.zeros((G, Q), dtype=bool)
    q_kind = np.zeros(Q, dtype=np.int32)
    q_cap = np.ones(Q, dtype=np.int32)
    for (kind, sel_sig, cap), q in hostname_sigs.items():
        q_kind[q] = kind
        q_cap[q] = cap
        if G:
            q_member[:, q] = _sel_verdicts(sel_sig, uniq_l)[inv_l]
    # ownership collected during the term scan: a group owns exactly the
    # sigs its representative's terms constructed (the sig key encodes
    # kind/selector/cap, so key identity IS the former rescan's match)
    for g, owned in enumerate(group_h_owned):
        for s in owned:
            q_owner[g, hostname_sigs[s]] = True

    # ---- instance-type tensors ---------------------------------------------
    type_alloc = np.zeros((T, R), dtype=np.int32)
    type_capacity = np.zeros((T, R), dtype=np.int32)
    offer_avail = np.zeros((T, len(zones), len(cts)), dtype=bool)
    offer_price = np.full((T, len(zones), len(cts)), np.inf, dtype=np.float32)
    zid = {z: i for i, z in enumerate(zones)}
    cid = {c: i for i, c in enumerate(cts)}
    rkeys_tuple = tuple(rkeys)
    if len(_TYPE_ROW_CACHE) > 8192:
        # catalog churn (e.g. ICE-seq rebuilds) creates fresh type objects;
        # bound the id-keyed cache so stale generations don't accumulate
        _TYPE_ROW_CACHE.clear()
    for t, name in enumerate(type_names):
        it = types_by_name[name]
        # alloc = floor(capacity) - ceil(overhead): matches quantize_input's
        # per-field rounding exactly (allocatable() of quantized fields).
        # Rows cache per (type object, resource axis) — the catalog is static
        # across solves, so steady state is a dict hit per type.
        ent = _TYPE_ROW_CACHE.get(id(it))
        if ent is not None and ent[0] is it and ent[1] == rkeys_tuple:
            type_alloc[t], type_capacity[t] = ent[2], ent[3]
        else:
            cap_q = np.asarray(_quantize(it.capacity, rkeys, ceil=False), dtype=np.int64)
            ovh_q = np.asarray(_quantize(it.overhead, rkeys, ceil=True), dtype=np.int64)
            alloc_row = np.maximum(cap_q - ovh_q, 0).astype(np.int32)
            cap_row = cap_q.astype(np.int32)
            type_alloc[t], type_capacity[t] = alloc_row, cap_row
            _TYPE_ROW_CACHE[id(it)] = (it, rkeys_tuple, alloc_row, cap_row)
        for o in it.offerings:
            if o.zone in zid and o.capacity_type in cid:
                zi, ci = zid[o.zone], cid[o.capacity_type]
                if o.available:
                    offer_avail[t, zi, ci] = True
                    offer_price[t, zi, ci] = min(offer_price[t, zi, ci], o.price)

    # ---- group×type / group×zone / group×ct --------------------------------
    # group×type compatibility rows cache by interned pod signature id: a
    # recurring group (same deployment, next solve) costs a dict hit instead
    # of T requirement-algebra calls. The catalog is identified by object ids,
    # with the referenced types pinned in the cache entry so ids can't be
    # recycled under us; the epoch in the key invalidates entries when the
    # signature intern table resets.
    types_tuple = tuple(types_by_name[n] for n in type_names)
    types_ids = tuple(map(id, types_tuple))
    group_compat_t = np.zeros((G, T), dtype=bool)
    group_zone = np.zeros((G, len(zones)), dtype=bool)
    group_ct = np.zeros((G, len(cts)), dtype=bool)
    if len(_GROUP_COMPAT_CACHE) > 8192:
        _GROUP_COMPAT_CACHE.clear()
    for g, reqs in enumerate(group_reqsets):
        zr = reqs.get(wk.ZONE_LABEL)
        for i, z in enumerate(zones):
            group_zone[g, i] = zr is None or zr.has(z)
        cr = reqs.get(wk.CAPACITY_TYPE_LABEL)
        for i, c in enumerate(cts):
            group_ct[g, i] = cr is None or cr.has(c)
        key = (_SIG_EPOCH, group_snums[g]) if sigs_interned else None
        ent = _GROUP_COMPAT_CACHE.get(key) if key is not None else None
        if ent is not None and ent[0] == types_ids:
            group_compat_t[g] = ent[2]
        else:
            row = np.fromiter(
                (reqs.compatible(it.requirements) for it in types_tuple),
                dtype=bool,
                count=T,
            )
            group_compat_t[g] = row
            if key is not None:
                _GROUP_COMPAT_CACHE[key] = (types_ids, types_tuple, row)

    # ---- pool tensors (usage/limits are per-solve: _encode_with_nodes) -----
    P = len(pools)
    pool_type = np.zeros((P, T), dtype=bool)
    pool_zone = np.zeros((P, len(zones)), dtype=bool)
    pool_ct = np.zeros((P, len(cts)), dtype=bool)
    pool_daemon = np.zeros((P, R), dtype=np.int32)
    group_pool = np.zeros((G, P), dtype=bool)
    for p, pool in enumerate(pools):
        in_pool = {it.name for it in pool.instance_types}
        zr = pool.requirements.get(wk.ZONE_LABEL)
        for i, z in enumerate(zones):
            pool_zone[p, i] = zr is None or zr.has(z)
        cr = pool.requirements.get(wk.CAPACITY_TYPE_LABEL)
        for i, c in enumerate(cts):
            pool_ct[p, i] = cr is None or cr.has(c)
        for t, name in enumerate(type_names):
            if name not in in_pool:
                continue
            it = types_by_name[name]
            if not pool.requirements.compatible(it.requirements):
                continue
            # needs ≥1 available offering within pool zone/ct masks
            ok = (offer_avail[t] & pool_zone[p][:, None] & pool_ct[p][None, :]).any()
            pool_type[p, t] = ok
        # daemonset overhead (SPEC: daemonsets admitted by pool requirements)
        dres = Resources()
        dcount = 0
        for dp in inp.daemonset_pods:
            if not tolerates_all(dp.tolerations, pool.taints):
                continue
            if not dp.scheduling_requirements().compatible(pool.requirements):
                continue
            dres = dres.add(dp.requests)
            dcount += 1
        dres[PODS] = dres.get_(PODS) + dcount
        pool_daemon[p] = _quantize(dres, rkeys, ceil=True)
        for g, pl in enumerate(group_pods):
            pod = pl[0]
            if not tolerates_all(pod.tolerations, pool.taints):
                continue
            group_pool[g, p] = group_reqsets[g].compatible(pool.requirements)

    # ---- pairwise group compatibility --------------------------------------
    # compatible() is pure requirement algebra, so dedupe by DISTINCT
    # requirement-set content: D distinct sets cost D·(D+1)/2 calls instead
    # of G·(G-1)/2 (the s-stress shape — thousands of groups, one distinct
    # reqset — collapses to a single call), then gather to [G, G]. The
    # diagonal is forced True afterwards exactly as the original never
    # computed it (a self-incompatible reqset still pairs False off-diagonal).
    uniq_req: Dict[tuple, int] = {}
    req_rep_idx = np.fromiter(
        (uniq_req.setdefault(_reqs_key(r), len(uniq_req)) for r in group_reqsets),
        np.int64,
        G,
    )
    Dreq = len(uniq_req)
    rep_reqs: List[Optional[Requirements]] = [None] * Dreq
    for g in range(G):
        if rep_reqs[req_rep_idx[g]] is None:
            rep_reqs[req_rep_idx[g]] = group_reqsets[g]
    rep_pair = np.ones((Dreq, Dreq), dtype=bool)
    for a in range(Dreq):
        for b in range(a, Dreq):
            ok = rep_reqs[a].compatible(rep_reqs[b])
            rep_pair[a, b] = rep_pair[b, a] = ok
    group_pair = rep_pair[np.ix_(req_rep_idx, req_rep_idx)]
    np.fill_diagonal(group_pair, True)
    # ≥3-way custom-label joint conflicts the pairwise mask can't see:
    # detect custom keys with ≥3 distinct finite value-sets among groups.
    custom_sets: Dict[str, set] = {}
    tracked = {wk.ZONE_LABEL, wk.CAPACITY_TYPE_LABEL, wk.INSTANCE_TYPE_LABEL}
    for reqs in group_reqsets:
        for k, r in reqs.items():
            if k in tracked or r.complement:
                continue
            custom_sets.setdefault(k, set()).add(tuple(sorted(r.values)))
    for k, vsets in custom_sets.items():
        if len(vsets) >= 3:
            for g, reqs in enumerate(group_reqsets):
                if k in reqs:
                    fallback[g] = True

    # ---- scheduling-class tables (priority ranks + gang membership) --------
    # Group representatives are exact: priority and the gang labels ride the
    # pod signature, so every pod in a group agrees on them.
    n_groups = len(group_pods)
    g_prios = np.fromiter((gp[0].priority for gp in group_pods), np.int64,
                          n_groups)
    group_prio16 = np.searchsorted(np.unique(g_prios), g_prios).astype(np.uint16)
    g_gangs = [gp[0].gang() for gp in group_pods]
    gang_ids = sorted({g[0] for g in g_gangs if g is not None})
    gang_rank = {gid: i for i, gid in enumerate(gang_ids)}
    group_gang = np.fromiter(
        (gang_rank[g[0]] if g is not None else -1 for g in g_gangs),
        np.int32, n_groups,
    )
    # a gang id declared with conflicting size/min-ranks across groups takes
    # the MAX of each (conservative: harder to commit, never a partial gang)
    gang_size = np.zeros(len(gang_ids), np.int32)
    gang_min_ranks = np.zeros(len(gang_ids), np.int32)
    for g in g_gangs:
        if g is None:
            continue
        i = gang_rank[g[0]]
        gang_size[i] = max(gang_size[i], g[1])
        gang_min_ranks[i] = max(gang_min_ranks[i], g[2])
    gang_min_ranks = np.minimum(gang_min_ranks, gang_size)

    return _EncodeCore(
        zones=zones,
        cts=cts,
        type_names=type_names,
        pool_names=pool_names,
        rkeys=rkeys,
        charge_axes=np.asarray([k in (CPU, MEMORY) for k in rkeys], dtype=bool),
        group_pods=group_pods,
        group_req=group_req,
        group_compat_t=group_compat_t,
        group_zone=group_zone,
        group_ct=group_ct,
        group_pool=group_pool,
        group_pair=group_pair,
        fallback=fallback,
        run_group=np.asarray(run_group, dtype=np.int32),
        run_count=np.asarray(run_count, dtype=np.int32),
        sorted_uids=sorted_uids,
        group_reqsets=group_reqsets,
        has_topo=has_topo,
        has_aff=has_aff,
        hostname_sigs=hostname_sigs,
        zone_sigs=vsigs,
        v_axis=v_axis,
        sig_axis=sig_axis,
        group_daxis=group_daxis,
        q_member=q_member,
        q_owner=q_owner,
        q_kind=q_kind,
        q_cap=q_cap,
        v_member=v_member,
        v_owner=v_owner,
        v_kind=v_kind,
        v_cap=v_cap,
        v_primary=v_primary,
        v_aff=v_aff,
        type_alloc=type_alloc,
        type_capacity=type_capacity,
        offer_avail=offer_avail,
        offer_price=offer_price,
        pool_type=pool_type,
        pool_zone=pool_zone,
        pool_ct=pool_ct,
        pool_daemon=pool_daemon,
        all_req_keys=sorted({k for reqs in group_reqsets for k in reqs}),
        zid=zid,
        cid=cid,
        group_snums=group_snums if sigs_interned else (),
        sig_epoch=_SIG_EPOCH if sigs_interned else -1,
        core_rev=_fresh_core_rev(),
        group_prio16=group_prio16,
        group_gang=group_gang,
        gang_size=gang_size,
        gang_min_ranks=gang_min_ranks,
        gang_ids=gang_ids,
    )


def _fresh_core_rev() -> int:
    from . import encode_cache as ec

    return ec.next_core_rev()


def _encode_with_nodes(core: _EncodeCore, inp: SolverInput) -> EncodedInput:
    """Per-solve stage: existing-node tensors + pool usage/limits (both
    change between solves) assembled around the cached core."""
    zones, cts, rkeys = core.zones, core.cts, core.rkeys
    group_pods, group_reqsets = core.group_pods, core.group_reqsets
    hostname_sigs, zone_sigs = core.hostname_sigs, core.zone_sigs
    zid, cid = core.zid, core.cid
    G = len(group_pods)
    R = len(rkeys)
    Q = len(hostname_sigs)
    V = len(zone_sigs)
    has_topo = core.has_topo

    # pool usage/limits from the fresh pool objects, in core's pool order
    pools = sorted(inp.nodepools, key=lambda p: (-p.weight, p.name))
    P = len(pools)
    pool_limit = np.full((P, R), INT32_MAX, dtype=np.int32)
    pool_usage = np.zeros((P, R), dtype=np.int32)
    for p, pool in enumerate(pools):
        for i, k in enumerate(rkeys):
            if k in pool.limits:
                pool_limit[p, i] = min(int(pool.limits[k]), int(INT32_MAX))
        pool_usage[p] = _quantize(pool.usage, rkeys, ceil=True)

    # ---- existing nodes -----------------------------------------------------
    E = len(inp.nodes)
    node_free = np.zeros((E, R), dtype=np.int32)
    node_compat = np.zeros((G, E), dtype=bool)
    node_zone = np.full(E, -1, dtype=np.int32)
    node_ct = np.full(E, -1, dtype=np.int32)
    node_ids = [n.id for n in inp.nodes]
    node_q_member = np.zeros((E, Q), dtype=np.int32)
    node_q_owner = np.zeros((E, Q), dtype=np.int32)  # unknowable from labels
    sig_list = sorted(hostname_sigs.items(), key=lambda kv: kv[1])
    if Q:
        # The device Q axis treats each node ROW as one hostname domain; if
        # two nodes share a kubernetes.io/hostname label they are ONE domain
        # per SPEC.md, which the per-row counts can't express — fallback.
        from ..provisioning.scheduler import node_hostname

        hostnames = [node_hostname(n) for n in inp.nodes]
        if len(set(hostnames)) < len(hostnames):
            has_topo = True
    # domain axis for the V sigs: zone (default), capacity-type, or BOTH
    # concatenated ("mixed": zone columns then lex-ordered ct columns) — the
    # engine's index-order tiebreaks must match the oracle's string-lex
    # domain tiebreaks (scheduler._affinity_admits / commit rules)
    ct_lex = sorted(cts)
    ct_rank = {c: i for i, c in enumerate(ct_lex)}
    Zc = len(zones)
    if core.v_axis == "ct":
        v_domains = ct_lex
        dom_rank = dict(ct_rank)
        node_domain_of = lambda n: dom_rank.get(
            n.labels.get(wk.CAPACITY_TYPE_LABEL, ""), -1
        )
    elif core.v_axis == "mixed":
        v_domains = list(zones) + ct_lex
        dom_rank = {z: i for i, z in enumerate(zones)}
        node_domain_of = lambda n: dom_rank.get(n.labels.get(wk.ZONE_LABEL, ""), -1)
    else:
        v_domains = list(zones)
        dom_rank = {z: i for i, z in enumerate(v_domains)}
        node_domain_of = lambda n: dom_rank.get(n.labels.get(wk.ZONE_LABEL, ""), -1)
    v_node_domain = np.full(E, -1, dtype=np.int32)
    # second-axis column per node (mixed only): Z + lex rank of its ct
    node_dom2 = np.full(E, -1, dtype=np.int32)
    v_count0 = np.zeros((V, len(v_domains)), dtype=np.int32)
    node_v_member = np.zeros((E, V), dtype=np.int32)
    zsig_list = sorted(zone_sigs.items(), key=lambda kv: kv[1])
    all_req_keys = core.all_req_keys
    profile_cols: Dict[tuple, np.ndarray] = {}
    if E:
        # node_free in one pass: gather raw values, then vectorized MiB
        # floor on memory-like columns / truncation elsewhere — identical
        # to per-node _quantize(ceil=False)
        raw = np.fromiter(
            (n.free.get_(k) for n in inp.nodes for k in rkeys),
            np.float64,
            E * R,
        ).reshape(E, R)
        mib_cols = np.asarray([k in _MIB_KEYS for k in rkeys])
        qv = np.where(mib_cols[None, :], np.floor_divide(raw, MIB), np.trunc(raw))
        node_free = np.minimum(qv, float(INT32_MAX)).astype(np.int32)
    for e, n in enumerate(inp.nodes):
        node_zone[e] = zid.get(n.labels.get(wk.ZONE_LABEL, ""), -1)
        node_ct[e] = cid.get(n.labels.get(wk.CAPACITY_TYPE_LABEL, ""), -1)
        v_node_domain[e] = node_domain_of(n)
        if core.v_axis == "mixed":
            cr = ct_rank.get(n.labels.get(wk.CAPACITY_TYPE_LABEL, ""), -1)
            node_dom2[e] = Zc + cr if cr >= 0 else -1
    # Q/V bound-pod counts: intern every bound pod's label dict, evaluate
    # each selector once per DISTINCT label set (global verdict cache), and
    # scatter per-node counts — replaces the former O(E · (Q+V) · pods)
    # per-node Python scans with O(distinct labels · sigs) verdicts plus
    # vectorized bincounts.
    if (Q or V) and E:
        pod_lids = [
            np.fromiter(
                (_lab_id(pl) for pl in n.pod_labels), np.int64, len(n.pod_labels)
            )
            for n in inp.nodes
        ]
        lens = np.fromiter((len(a) for a in pod_lids), np.int64, E)
        if lens.sum():
            lids_all = np.concatenate(pod_lids)
            nidx = np.repeat(np.arange(E), lens)
            uniq_n, inv_n = np.unique(lids_all, return_inverse=True)
            for (kind, sel_sig, cap), q in sig_list:
                hit = _sel_verdicts(sel_sig, uniq_n)[inv_n]
                node_q_member[:, q] = np.bincount(nidx[hit], minlength=E)
            if V:
                # only nodes with a determined domain contribute (and
                # record) member counts — undetermined rows stay zero,
                # matching the oracle's "placement records every known
                # topology key" rule
                det = (v_node_domain >= 0) | (node_dom2 >= 0)
                for (ax, kind, sel_sig, cap), v in zsig_list:
                    hit = _sel_verdicts(sel_sig, uniq_n)[inv_n]
                    cnts = np.bincount(nidx[hit], minlength=E)
                    cnts[~det] = 0
                    node_v_member[:, v] = cnts
                m1 = v_node_domain >= 0
                if m1.any():
                    np.add.at(v_count0.T, v_node_domain[m1], node_v_member[m1])
                m2 = node_dom2 >= 0
                if m2.any():
                    np.add.at(v_count0.T, node_dom2[m2], node_v_member[m2])
    for e, n in enumerate(inp.nodes):
        if not n.schedulable:
            continue
        # Node-profile dedupe: strictly_compatible only reads the labels at
        # the groups' requirement keys, and toleration checks only read
        # taints — so nodes sharing (taints, referenced-label values) share
        # the whole [G] compat column. A homogeneous fleet computes G×profiles
        # algebra calls instead of G×E.
        prof = (
            tuple((t.key, t.value, t.effect) for t in n.taints),
            tuple(n.labels.get(k) for k in all_req_keys),
        )
        col = profile_cols.get(prof)
        if col is None:
            node_reqs = Requirements.from_labels(n.labels)
            col = np.fromiter(
                (
                    tolerates_all(group_pods[g][0].tolerations, n.taints)
                    and group_reqsets[g].strictly_compatible(node_reqs)
                    for g in range(G)
                ),
                bool,
                G,
            )
            profile_cols[prof] = col
        node_compat[:, e] = col

    return EncodedInput(
        resource_keys=rkeys,
        zones=zones,
        capacity_types=cts,
        type_names=core.type_names,
        pool_names=core.pool_names,
        group_pods=group_pods,
        group_req=core.group_req,
        group_compat_t=core.group_compat_t,
        group_zone=core.group_zone,
        group_ct=core.group_ct,
        group_pool=core.group_pool,
        group_pair=core.group_pair,
        group_fallback=core.fallback,
        run_group=core.run_group,
        run_count=core.run_count,
        sorted_uids=core.sorted_uids,
        type_alloc=core.type_alloc,
        type_capacity=core.type_capacity,
        charge_axes=core.charge_axes,
        offer_avail=core.offer_avail,
        offer_price=core.offer_price,
        pool_type=core.pool_type,
        pool_zone=core.pool_zone,
        pool_ct=core.pool_ct,
        pool_daemon=core.pool_daemon,
        pool_limit=pool_limit,
        pool_usage=pool_usage,
        node_free=node_free,
        node_compat=node_compat,
        node_zone=node_zone,
        node_ct=node_ct,
        node_ids=node_ids,
        has_topology=has_topo,
        has_affinity=core.has_aff,
        q_member=core.q_member,
        q_owner=core.q_owner,
        q_kind=core.q_kind,
        q_cap=core.q_cap,
        node_q_member=node_q_member,
        node_q_owner=node_q_owner,
        v_member=core.v_member,
        v_owner=core.v_owner,
        v_kind=core.v_kind,
        v_cap=core.v_cap,
        v_primary=core.v_primary,
        v_aff=core.v_aff,
        v_count0=v_count0,
        node_v_member=node_v_member,
        v_axis=core.v_axis,
        v_domains=v_domains,
        v_node_domain=v_node_domain,
        sig_axis=core.sig_axis,
        group_daxis=core.group_daxis,
        node_dom2=node_dom2,
        core_rev=core.core_rev,
        group_snums=core.group_snums,
        run_prio16=(
            core.group_prio16[core.run_group]
            if core.group_prio16 is not None else None
        ),
        run_gang=(
            core.group_gang[core.run_group]
            if core.group_gang is not None else None
        ),
        gang_size=core.gang_size,
        gang_min_ranks=core.gang_min_ranks,
        gang_ids=core.gang_ids,
    )


# ---------------------------------------------------------------------------
# Sparse constraint tables (ISSUE 20: compacted V/Q-axis evaluation)
# ---------------------------------------------------------------------------
#
# The dense kernel charges every run full Q/V width even when its group
# touches a handful of sigs. These run-major index tables list, per run,
# exactly the constraint sigs its group is member or owner of (-1 padded
# to a quantum-bucketed width), and the sparse kernel entry points
# (tpu/ffd.SPARSE_ARG_SPEC) gather only those columns. Because the kernel
# re-gathers the membership flags through the index, any SUPERSET list is
# decision-identical — which is what makes the ladder union and the
# density gate free to be approximate about WIDTH, never about membership.

SPARSE_IDX_MULT = 8  # quantum bucket for the per-run index-list width
SPARSE_IDX_FLOOR = 8
SPARSE_MIN_SIGS = 8  # combined Q+V width below which dense is already fine
SPARSE_DENSITY_MAX = 0.25  # gate: active (run, sig) fraction


def _sparse_width(n: int) -> int:
    """Bucket an index-list width so compile buckets stay shared."""
    return max(
        SPARSE_IDX_FLOOR,
        ((n + SPARSE_IDX_MULT - 1) // SPARSE_IDX_MULT) * SPARSE_IDX_MULT,
    )


def constraint_density(enc: "EncodedInput") -> float:
    """Fraction of (run, sig) pairs that are active — the quantity the
    sparse engine makes the kernel pay for, replacing the flat V/Q factors
    in the cost model (ARCHITECTURE §5)."""
    Q, V = enc.Q, enc.V
    S = int(len(enc.run_group))
    if Q + V == 0 or S == 0:
        return 0.0
    rg = np.asarray(enc.run_group, np.int64)
    nnz = 0
    if Q:
        act_q = np.asarray(enc.q_member, bool) | np.asarray(enc.q_owner, bool)
        nnz += int(act_q[rg].sum())
    if V:
        act_v = np.asarray(enc.v_member, bool) | np.asarray(enc.v_owner, bool)
        nnz += int(act_v[rg].sum())
    return nnz / float(S * (Q + V))


def use_sparse_constraints(enc: "EncodedInput") -> bool:
    """Density gate between the dense tables and the compacted form: sparse
    wins when the sig axes are wide enough to charge real rent AND most
    (run, sig) pairs are inactive. Both thresholds are deliberately plain
    constants — the boundary is pinned by tests, not tuned per fleet."""
    if enc.Q + enc.V < SPARSE_MIN_SIGS:
        return False
    return constraint_density(enc) <= SPARSE_DENSITY_MAX


def _sparse_axis_table(act, rg, Sp, run_ladder):
    """One axis's run-major index table: [Sp, K] i32, -1 padded, where row
    s lists the active sig indices of run s's group (unioned over rung
    groups in ladder mode). Vectorized CSR-style fill: np.nonzero walks
    row-major, so each hit's rank within its row is its column slot."""
    S = rg.shape[0]
    run_act = act[rg]  # [S, X]
    if run_ladder is not None:
        lad = np.asarray(run_ladder, np.int64)
        for j in range(lad.shape[1]):
            gv = lad[:, j]
            ok = gv >= 0
            if ok.any():
                run_act[ok] |= act[gv[ok]]
    counts = run_act.sum(axis=1)
    K = _sparse_width(int(counts.max(initial=0)))
    out = np.full((Sp, K), -1, np.int32)
    rows, cols = np.nonzero(run_act)
    if rows.size:
        starts = np.searchsorted(rows, np.arange(S))
        pos = np.arange(rows.size) - starts[rows]
        out[rows, pos] = cols
    return out


def sparse_run_tables(enc: "EncodedInput", Sp: int, run_ladder=None):
    """Build the compacted constraint tables (tpu/ffd.SPARSE_ARG_SPEC):
    (run_q_idx [Sp, Kq] i32, run_v_idx [Sp, Kv] i32). `Sp` is the padded
    run-axis width (padding rows are all -1 = no active sigs, matching the
    padded runs' count==0 skip). In ladder mode each row is the union over
    the run's base group and every materialized rung group, so one gathered
    view covers the whole cascade."""
    rg = np.asarray(enc.run_group, np.int64)
    if enc.Q:
        act_q = np.asarray(enc.q_member, bool) | np.asarray(enc.q_owner, bool)
        run_q_idx = _sparse_axis_table(act_q, rg, Sp, run_ladder)
    else:
        run_q_idx = np.full((Sp, SPARSE_IDX_FLOOR), -1, np.int32)
    if enc.V:
        act_v = np.asarray(enc.v_member, bool) | np.asarray(enc.v_owner, bool)
        run_v_idx = _sparse_axis_table(act_v, rg, Sp, run_ladder)
    else:
        run_v_idx = np.full((Sp, SPARSE_IDX_FLOOR), -1, np.int32)
    return run_q_idx, run_v_idx


# ---------------------------------------------------------------------------
# Decision-provenance side tables (obs/explain.py, tpu/ffd.explain_pack)
# ---------------------------------------------------------------------------


# (id(group_pods), core_rev) -> (group_topo, group_aff); tiny bounded memo
# for the O(pods) flags walk below. id() alone is NOT a safe key — CPython
# recycles addresses after GC — but a recycled address cannot arrive with
# the SAME core_rev: a fresh group_pods list exists only on a fresh core
# build, which stamps a fresh monotone rev (encode_cache.next_core_rev),
# while delta-patched copies share BOTH the list identity and the donor's
# rev. The pair is therefore collision-free without pinning pod lists
# alive the way the old strong-ref guard did
# (tests/test_sparse_constraints.py::test_explain_flags_cache_id_reuse).
_EXPLAIN_FLAGS_CACHE: dict = {}


def explain_tables(enc: EncodedInput) -> dict:
    """The EXPLAIN side-kernel inputs (tpu/ffd.EXPLAIN_ARG_SPEC minus the
    scan-owned take_e and the padding scalars), unpadded — the encoder
    already owns every one of these tensors, so the explain path adds no
    new object walks beyond the per-group engine flags. Shared verbatim by
    the device kernel dispatch (backend) and the host deriver
    (obs/explain.host_table), which is what makes their outputs
    bit-comparable.

    The per-group engine-flags walk is O(pods), too hot to repeat per
    solve (the explain on-path budget is 2%): the flags memoize keyed on
    (identity of enc.group_pods, enc.core_rev) — delta-patched enc copies
    share both by reference (dataclasses.replace keeps field refs), so
    warm solves hit, while an id() recycled by GC always carries a fresh
    core_rev and misses. Hand-built encs without a stamped rev (< 0) are
    computed fresh and never cached. The cheap array dict is rebuilt from
    the current enc every call because node tables DO change across
    patches."""
    gp = enc.group_pods
    ckey = (id(gp), enc.core_rev)
    hit = _EXPLAIN_FLAGS_CACHE.get(ckey) if enc.core_rev >= 0 else None
    if hit is not None:
        group_topo, group_aff = hit
    else:
        G = int(enc.group_req.shape[0])
        group_topo = np.zeros(G, dtype=bool)
        group_aff = np.zeros(G, dtype=bool)
        for g in range(G):
            topo = aff = False
            for p in gp[g]:
                topo = topo or bool(getattr(p, "topology_spread", None))
                aff = aff or bool(getattr(p, "affinity_terms", None))
                if topo and aff:
                    break
            group_topo[g] = topo
            group_aff[g] = aff
        if enc.core_rev >= 0:
            if len(_EXPLAIN_FLAGS_CACHE) >= 8:
                _EXPLAIN_FLAGS_CACHE.pop(next(iter(_EXPLAIN_FLAGS_CACHE)))
            _EXPLAIN_FLAGS_CACHE[ckey] = (group_topo, group_aff)
    return {
        "run_group": enc.run_group,
        "group_req": enc.group_req,
        "node_free": enc.node_free,
        "node_compat": enc.node_compat,
        "node_zone": enc.node_zone,
        "node_ct": enc.node_ct,
        "group_zone": enc.group_zone,
        "group_ct": enc.group_ct,
        "group_topo": group_topo,
        "group_aff": group_aff,
    }
