"""Streaming delta-solve: event batches in, kernel dispatches out (ISSUE 13).

Every snapshot solve pays a host tax proportional to CLUSTER SIZE — list the
store, rebuild every ExistingNode, re-derive the pool catalog — before the
encode cache and the argument arena can even start shaving the device side.
This module makes the solve path proportional to EVENT RATE instead: a
`StreamingSolver` subscribes to the `ClusterJournal` (state/cluster.py) and
folds ordered event batches into a resident incremental model of the solve
universe, so `build_input()` is a cache assembly, not a cluster scan.

The resident model mirrors exactly what `Provisioner.build_input` reads:

  - pod / node / claim / pool / daemonset mirrors, keyed like the store and
    holding the SAME live objects (the store mutates in place — content is
    never stale; only membership and derived caches need events);
  - per-node `ExistingNode` views (the expensive Resources math), rebuilt
    only for nodes an event dirtied, via the SAME `existing_node_view`
    helper the snapshot path uses — the two can never drift;
  - per-node pool-usage contributions, folded in the snapshot path's
    state-node order so the aggregate is bit-identical;
  - the pool catalog (instance types, zone/capacity-type universes), reused
    while the provider's `catalog_token()` holds and no catalog-kind store
    event fired.

Downstream, everything already composes: the streamed input carries the same
`state_rev` stamp, so `encode_cache.try_patch` hits, `run_identity`/LCP
resume dispatches `ffd_resume` from the deepest device checkpoint, and the
backend's `stream_run_events` staging (arena.apply_run_events) ships the run
tables as edit triplets — h2d is only the changed runs, d2h stays the packed
claim delta.

Safety protocol (solver/SPEC.md "Streaming semantics"):

  - journal loss (overflow, detach) forces a full re-baseline — the model
    never extends a gapped stream;
  - catalog-kind events and provider token changes are INEXPRESSIBLE as
    deltas: the catalog caches rebuild from the store, decision-identical
    to the snapshot path (the fallback table in SPEC.md);
  - every `epoch_every` applied batches, a full snapshot re-derivation runs
    and is compared against the streamed model; any drift re-baselines and
    counts `karpenter_streaming_rebaseline_total{reason="drift"}`;
  - a fleet fence (fleet.fence_listeners) re-baselines, matching the arena
    invalidation — replays never act on device state the model presumed
    resident;
  - a `pod_mutation_epoch` bump (in-place sig mutation, no store event)
    resyncs the pod-derived maps.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..api import wellknown as wk
from ..api.objects import pod_mutation_epoch
from ..controllers import store as st
from ..metrics.registry import (
    STREAMING_BATCHES_APPLIED,
    STREAMING_EVENTS_APPLIED,
    STREAMING_JOURNAL_DEPTH,
    STREAMING_REBASELINE,
    STREAMING_STATE_AGE,
)
from ..provisioning.scheduler import ExistingNode, NodePoolSpec, SolverInput
from ..state.cluster import Cluster, StateNode, existing_node_view
from ..utils.resources import Resources

_CATALOG_KINDS = frozenset((st.NODEPOOLS, st.NODECLASSES, st.DAEMONSETS))


class StreamingSolver:
    """Incremental solve-universe model fed by the ClusterJournal.

    Not a `Solver` — it sits ABOVE the solver seam: the provisioner calls
    `pump()` each tick (fold pending journal events), reads `pending_pods()`
    for batching, and `build_input(pending)` for the solve; the input then
    flows through the unchanged service/fleet/backend stack. Thread-safe:
    pump/build run under one lock (the provisioner and the epoch check are
    the only writers; fence listeners only set a flag).
    """

    def __init__(self, cluster: Cluster, cloud_provider,
                 preference_policy: str = "Respect",
                 epoch_every: int = 64, clock=time.monotonic):
        self.cluster = cluster
        self.store = cluster.store
        self.journal = cluster.journal
        self.cloud_provider = cloud_provider
        self.preference_policy = preference_policy
        self.epoch_every = max(0, int(epoch_every))  # 0 = never
        self.clock = clock
        self._lock = threading.RLock()
        self._rebaseline_wanted: Optional[str] = None  # fence flag
        self.stats: Dict[str, int] = {
            "batches_applied": 0, "events_applied": 0,
            "rebaseline_total": 0, "epoch_checks": 0, "drift_detected": 0,
            "catalog_rebuilds": 0, "streamed_solves": 0,
        }
        self._attached = False
        self._applied_seq = 0
        self._baseline_at = self.clock()
        # last re-baseline provenance for the health surface: the
        # `karpenter_streaming_rebaseline_total{reason}` series says HOW
        # OFTEN; /healthz wants the most recent WHY without a metrics query
        self.last_rebaseline: Dict[str, object] = {"reason": None, "count": 0}
        # -- mirrors (store order; values are the LIVE stored objects) ------
        self._pods: Dict[str, object] = {}
        self._nodes: Dict[str, object] = {}       # by meta.name
        self._claims: Dict[str, object] = {}
        # -- pod-derived maps ----------------------------------------------
        self._pod_ord: Dict[str, int] = {}        # store insertion order
        self._ord = 0
        self._pod_node: Dict[str, Optional[str]] = {}
        self._by_node: Dict[str, Dict[str, object]] = {}
        self._pod_epoch = pod_mutation_epoch()
        # -- per-state-node derived caches ---------------------------------
        self._claim_names: Dict[str, Set[str]] = {}
        self._en_cache: Dict[str, Optional[ExistingNode]] = {}
        self._usage_cache: Dict[str, Optional[Tuple[str, Resources]]] = {}
        self._dirty: Set[str] = set()
        # -- catalog caches -------------------------------------------------
        self._catalog_dirty = True
        self._pool_token: object = None
        self._pool_types: Dict[str, list] = {}
        self._daemonsets: List[object] = []
        self._zones: Tuple[str, ...] = ()
        self._cts: Tuple[str, ...] = ()
        self._since_epoch_check = 0

    # -- lifecycle -----------------------------------------------------------

    def on_fence(self, reason: str) -> None:
        """Fleet fence listener: the next pump re-baselines. Only sets a
        flag — the fence path must stay failure-proof."""
        self._rebaseline_wanted = "fence"

    def force_rebaseline(self, reason: str = "forced") -> None:
        self._rebaseline_wanted = reason

    def _rebaseline(self, reason: str) -> None:
        """Full snapshot resync: re-attach the journal, rebuild every mirror
        and derived map from the store. The attach-then-list order closes
        the race: events landing between attach() and the list() calls are
        re-delivered by the next drain, and folding them is idempotent
        (level-triggered — the mirror re-reads the same live object)."""
        STREAMING_REBASELINE.inc(reason=reason)
        self.stats["rebaseline_total"] += 1
        self.last_rebaseline = {
            "reason": reason, "count": self.stats["rebaseline_total"],
        }
        self._applied_seq = self.journal.attach()
        self._pods.clear()
        self._pod_ord.clear()
        self._ord = 0
        self._pod_node.clear()
        self._by_node.clear()
        self._nodes.clear()
        self._claims.clear()
        self._claim_names.clear()
        self._en_cache.clear()
        self._usage_cache.clear()
        self._dirty.clear()
        for p in self.store.list(st.PODS):
            self._fold_pod("ADDED", f"{p.meta.namespace}/{p.meta.name}", p)
        for n in self.store.list(st.NODES):
            self._nodes[n.meta.name] = n
        for c in self.store.list(st.NODECLAIMS):
            self._fold_claim(
                "ADDED", f"{c.meta.namespace}/{c.meta.name}", c)
        self._catalog_dirty = True
        self._pod_epoch = pod_mutation_epoch()
        self._attached = True
        self._baseline_at = self.clock()
        self._since_epoch_check = 0
        self.journal.mark_applied(self._applied_seq)

    # -- event folding -------------------------------------------------------

    def _fold_pod(self, event: str, key: str, pod) -> None:
        prev_node = self._pod_node.get(key)
        if event == "DELETED":
            self._pods.pop(key, None)
            self._pod_ord.pop(key, None)
            self._pod_node.pop(key, None)
            if prev_node:
                self._by_node.get(prev_node, {}).pop(key, None)
                self._dirty.add(prev_node)
            return
        if key not in self._pods:
            self._ord += 1
            self._pod_ord[key] = self._ord
        self._pods[key] = pod
        cur_node = pod.node_name or None
        self._pod_node[key] = cur_node
        if prev_node and prev_node != cur_node:
            self._by_node.get(prev_node, {}).pop(key, None)
            self._dirty.add(prev_node)
        if cur_node:
            self._by_node.setdefault(cur_node, {})[key] = pod
            # a bound pod's content change (requests, labels, deleting)
            # moves its node's free/evictability — dirty unconditionally
            self._dirty.add(cur_node)

    def _fold_claim(self, event: str, key: str, claim) -> None:
        prev = self._claim_names.get(key, set())
        if event == "DELETED":
            self._claims.pop(key, None)
            self._claim_names.pop(key, None)
            self._dirty |= prev
            return
        self._claims[key] = claim
        names = {n for n in (claim.node_name, claim.name) if n}
        self._claim_names[key] = names
        self._dirty |= prev | names

    def _fold(self, ev) -> None:
        if ev.kind == st.PODS:
            self._fold_pod(ev.event, ev.key, ev.obj)
        elif ev.kind == st.NODES:
            name = ev.obj.meta.name
            if ev.event == "DELETED":
                self._nodes.pop(name, None)
            else:
                self._nodes[name] = ev.obj
            self._dirty.add(name)
        elif ev.kind == st.NODECLAIMS:
            self._fold_claim(ev.event, ev.key, ev.obj)
        elif ev.kind in _CATALOG_KINDS:
            # inexpressible as a delta (SPEC.md fallback table): pool
            # contents / daemonset overhead / axes universes rebuild from
            # the store next build_input — decision-identical snapshot leg
            self._catalog_dirty = True
        # PDBs / PVs / PVCs: not provisioning inputs; PVC zone resolution
        # reaches pods as pod mutations (controllers/volume.py)

    def pump(self) -> int:
        """Fold every journal event since the last pump; returns the seq of
        the newest folded event (the solve's journal attribution). Cheap
        when nothing happened; re-baselines on stream loss, a pending fence
        flag, or an in-place pod sig mutation epoch bump."""
        with self._lock:
            want = self._rebaseline_wanted
            if want is not None:
                self._rebaseline_wanted = None
                self._rebaseline(want)
            elif not self._attached:
                self._rebaseline("baseline")
            else:
                events, lost = self.journal.drain(self._applied_seq)
                if lost:
                    self._rebaseline("journal_lost")
                elif events:
                    for ev in events:
                        self._fold(ev)
                    self._applied_seq = events[-1].seq
                    self.stats["batches_applied"] += 1
                    self.stats["events_applied"] += len(events)
                    STREAMING_BATCHES_APPLIED.inc()
                    STREAMING_EVENTS_APPLIED.inc(len(events))
                    self.journal.mark_applied(self._applied_seq)
                    self._since_epoch_check += 1
                    if self.epoch_every and (
                            self._since_epoch_check >= self.epoch_every):
                        self._epoch_check()
            if pod_mutation_epoch() != self._pod_epoch:
                # in-place sig mutation: no store event fired, but bound-pod
                # requests/labels may have moved — resync the pod maps
                self._rebaseline("pod_epoch")
            STREAMING_JOURNAL_DEPTH.set(float(self.journal.depth()))
            STREAMING_STATE_AGE.set(self.clock() - self._baseline_at)
            return self._applied_seq

    # -- assembly ------------------------------------------------------------

    def pending_pods(self) -> List[object]:
        """Same predicate + order as Cluster.pending_pods(), over the mirror
        (store insertion order) instead of a store list."""
        with self._lock:
            return [
                p for p in self._pods.values()
                if not p.bound and not p.scheduling_gated
                and p.phase == "Pending" and not p.meta.deleting
            ]

    def _node_pods(self, name: str) -> List[object]:
        d = self._by_node.get(name)
        if not d:
            return []
        return [p for _, p in sorted(
            d.items(), key=lambda kv: self._pod_ord.get(kv[0], 0))]

    def _state_nodes(self) -> List[StateNode]:
        """The snapshot path's state-node join, over the mirrors: claims in
        store order (joined to their nodes), then unclaimed nodes — the fold
        order `nodepool_usage` aggregates in must match bit-for-bit."""
        out: List[StateNode] = []
        claimed: Set[str] = set()
        for c in self._claims.values():
            node = self._nodes.get(c.node_name) if c.node_name else None
            if node is not None:
                claimed.add(node.meta.name)
            out.append(StateNode(node=node, claim=c))
        for name, n in self._nodes.items():
            if name not in claimed:
                out.append(StateNode(node=n, claim=None))
        return out

    def _refresh_views(self) -> Tuple[List[ExistingNode], Dict[str, Resources]]:
        ens: List[ExistingNode] = []
        usage: Dict[str, Resources] = {}
        dirty = self._dirty
        for sn in self._state_nodes():
            name = sn.name
            if name in dirty or name not in self._en_cache:
                self._en_cache[name] = existing_node_view(
                    sn, self._node_pods(name))
                np_name = sn.nodepool
                cap = None
                if sn.claim is not None and sn.claim.capacity:
                    cap = sn.claim.capacity
                elif sn.node is not None:
                    cap = sn.node.capacity
                self._usage_cache[name] = (
                    (np_name, cap) if np_name and cap else None
                )
            en = self._en_cache[name]
            if en is not None:
                ens.append(en)
            contrib = self._usage_cache[name]
            if contrib is not None:
                usage[contrib[0]] = usage.get(
                    contrib[0], Resources()).add(contrib[1])
        self._dirty = set()
        ens.sort(key=lambda n: n.id)
        return ens, usage

    def _refresh_catalog(self) -> None:
        """Rebuild the instance-type / zone / capacity-type / daemonset
        caches from the store + provider — the snapshot path's loop,
        verbatim. Runs on catalog-kind events and provider token changes;
        a provider with no catalog_token() can never prove reuse, so the
        caches rebuild every solve (still snapshot-identical)."""
        self.stats["catalog_rebuilds"] += 1
        self._pool_types = {}
        zones: set = set()
        cts: set = set()
        for np_obj in self.store.list(st.NODEPOOLS):
            if np_obj.meta.deleting:
                continue
            types = self.cloud_provider.get_instance_types(np_obj.name)
            self._pool_types[np_obj.name] = types
            for it in types:
                zr = it.requirements.get(wk.ZONE_LABEL)
                if zr:
                    zones.update(zr.values_list())
                cr = it.requirements.get(wk.CAPACITY_TYPE_LABEL)
                if cr:
                    cts.update(cr.values_list())
        self._zones = tuple(sorted(zones))
        self._cts = tuple(sorted(cts))
        self._daemonsets = [d for d in self.store.list(st.DAEMONSETS)]
        self._catalog_dirty = False

    def build_input(self, pending: List[object]) -> SolverInput:
        """Assemble the streamed SolverInput — content-equal to
        `Provisioner.build_input(pending)` on the same universe (the parity
        the epoch check and tests/test_streaming_solve.py enforce)."""
        with self._lock:
            self.stats["streamed_solves"] += 1
            tok_fn = getattr(self.cloud_provider, "catalog_token", None)
            tok = tok_fn() if callable(tok_fn) else None
            if self._catalog_dirty or tok is None or tok != self._pool_token:
                self._refresh_catalog()
                self._pool_token = tok
            ens, usage = self._refresh_views()
            pools: List[NodePoolSpec] = []
            for np_obj in self.store.list(st.NODEPOOLS):
                if np_obj.meta.deleting:
                    continue
                types = self._pool_types.get(np_obj.name)
                if types is None:
                    # pool raced in after the catalog refresh without an
                    # event reaching us yet — fetch; the event re-dirties
                    types = self.cloud_provider.get_instance_types(
                        np_obj.name)
                pools.append(NodePoolSpec(
                    name=np_obj.name,
                    weight=np_obj.weight,
                    requirements=np_obj.scheduling_requirements(),
                    taints=list(np_obj.template.taints),
                    instance_types=types,
                    limits=np_obj.limits,
                    usage=usage.get(np_obj.name, type(np_obj.limits)()),
                ))
            state_rev = None
            deltas = getattr(self.cluster, "encode_deltas", None)
            if deltas is not None and tok is not None:
                tracker, crev, prev, nrev = deltas.snapshot()
                state_rev = (tracker, (crev, tok), prev, nrev)
            return SolverInput(
                pods=pending,
                nodes=ens,
                nodepools=pools,
                daemonset_pods=self._daemonsets,
                zones=self._zones,
                capacity_types=self._cts or ("on-demand", "spot"),
                preference_policy=self.preference_policy,
                state_rev=state_rev,
            )

    # -- epoch / parity ------------------------------------------------------

    def _epoch_check(self) -> None:
        """Periodic reconciliation: re-derive the pod/node legs from a full
        store scan and compare against the streamed model. Drift means an
        event class the fold missed — re-baseline rather than let decisions
        extend a wrong universe. Caller holds the lock."""
        self.stats["epoch_checks"] += 1
        self._since_epoch_check = 0
        snap_pending_keys = [
            f"{p.meta.namespace}/{p.meta.name}"
            for p in self.cluster.pending_pods()
        ]
        mine_pending_keys = [
            f"{p.meta.namespace}/{p.meta.name}" for p in self.pending_pods()
        ]
        snap_nodes = self.cluster.existing_nodes_for_scheduler()
        snap_usage = self.cluster.nodepool_usage()
        dirty_backup = set(self._dirty)
        mine_nodes, mine_usage = self._refresh_views()
        self._dirty |= dirty_backup
        if (snap_pending_keys != mine_pending_keys
                or snap_nodes != mine_nodes or snap_usage != mine_usage):
            self.stats["drift_detected"] += 1
            self._rebaseline("drift")

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                **self.stats,
                "applied_seq": self._applied_seq,
                "journal_depth": self.journal.depth(),
                "journal_overflows": self.journal.overflows,
                "resident_state_age_s": self.clock() - self._baseline_at,
            }

    def health(self) -> Dict[str, object]:
        """The /healthz "streaming" object (registered as a telemetry
        provider by the operator): journal lag — newest store event vs the
        seq this consumer has folded — plus re-baseline provenance. Lag
        that keeps growing means the pump stalled; a climbing re-baseline
        count means fold-drift/overflow is forcing snapshot resyncs."""
        with self._lock:
            rev = self.journal.rev()
            return {
                "journal": {
                    "rev": rev,
                    "applied_seq": self._applied_seq,
                    "lag": max(0, rev - self._applied_seq),
                    "depth": self.journal.depth(),
                    "overflows": self.journal.overflows,
                },
                "last_rebaseline": dict(self.last_rebaseline),
                "rebaseline_total": self.stats["rebaseline_total"],
                "streamed_solves": self.stats["streamed_solves"],
            }
