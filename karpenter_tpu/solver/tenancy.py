"""Tenancy layer: many clusters' solve streams on one shared owner pool.

The fleet (solver/fleet.py) gives us N health-probed device owners behind
the SolveService surface — but one surface serves ONE cluster's state.
This module is the subsystem between callers and that surface: a
`TenantRegistry` of tenant specs (weight, admission depth) and a
`TenantMux` that multiplexes per-tenant solve streams onto the shared
pool. The contract, pinned by tests/test_tenancy.py and solver/SPEC.md
"Tenancy semantics":

Sharing boundary — per-tenant state is exactly the state one tenant's
churn could poison for another: the encode core-cache namespace
(encode_cache.tenant_core_cache), the arena RESIDENCY namespace
(arena.bucket_key ns= — buffers, checkpoints, ladders, shard records),
the circuit breaker, and the oracle-fallback rung. Everything keyed by
SHAPE stays shared: jit/AOT compile buckets, the arena `_UNPACK_CACHE`,
claim-bucket lattices — two tenants with the same padded shapes hit the
same compiled kernel, so compiles stay flat as tenants grow.

Scheduling — per-tenant FIFO queues drained by virtual-time weighted-fair
queueing: the dispatcher picks the backlogged tenant with the smallest
virtual finish `max(V, F_t) + 1/w_t`, so under saturation throughput
shares converge to the weights, an idle tenant re-enters at the current
virtual time (no burst credit), and within one tenant order is FIFO.
Admission control bounds each tenant's open requests (queued + in flight)
at `max_queue_depth`; past it, submit raises the typed
`TenantAdmissionReject` — backpressure lands on the noisy tenant alone.

Failure isolation — each tenant carries its OWN CircuitBreaker and
oracle rung. A device-path failure charges only that tenant's breaker
and replays on that tenant's oracle (the ticket still resolves — poison
degrades, it never drops); an open breaker routes that tenant's input
solves straight to its oracle lane (a dedicated thread, so a slow oracle
replay can't stall other tenants' dispatches) until a half-open probe
closes it. Owner-level canary fencing stays global — a wedged DEVICE is
everyone's problem — and the fleet's fence-requeue already replays
survivors in original submission order, preserving per-tenant FIFO.

Device-bound closures (submit_fn) bypass the breaker: they are bound to
a specific owner's device state and cannot replay on an oracle, so the
mux forwards them as-is and surfaces their failures verbatim.

Tenancy off (no registry configured) means no TenantMux is constructed
at all — the operator wires the provisioner straight to the fleet /
pipeline seam, byte-identical to the pre-tenancy path.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional

from ..metrics.registry import (
    SOLVER_COHORT_POISON_REPLAYS,
    SOLVER_COHORT_SIZE,
    SOLVER_FUSED_DISPATCHES,
    TENANT_ADMISSION_REJECTS,
    TENANT_BREAKER_STATE,
    TENANT_DEGRADED,
    TENANT_QUEUE_DEPTH,
    TENANT_SOLVE_SECONDS,
)
from ..obs import trace as obstrace
from .backend import ReferenceSolver
from .pipeline import (
    DISRUPTION,
    PROVISIONING,
    ServiceStopped,
    SolveTicket,
    Superseded,
)
from .resilient import CircuitBreaker

log = logging.getLogger("karpenter_tpu")


class TenantAdmissionReject(Exception):
    """Typed admission refusal: the tenant's open-request count is at its
    configured depth. The caller (a per-cluster provisioner) sheds load or
    retries after its next reconcile — nothing was enqueued."""

    def __init__(self, tenant_id: str, depth: int, limit: int):
        super().__init__(
            f"tenant {tenant_id!r}: {depth} open solve requests at the "
            f"admission limit ({limit})"
        )
        self.tenant_id = tenant_id
        self.depth = depth
        self.limit = limit


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    tenant_id: str
    weight: float = 1.0
    max_queue_depth: int = 64

    def __post_init__(self):
        if not self.tenant_id:
            raise ValueError("tenant_id must be non-empty")
        if not self.weight > 0:
            raise ValueError(
                f"tenant {self.tenant_id!r}: weight must be > 0, "
                f"got {self.weight}"
            )
        if self.max_queue_depth < 1:
            raise ValueError(
                f"tenant {self.tenant_id!r}: max_queue_depth must be >= 1, "
                f"got {self.max_queue_depth}"
            )


class TenantRegistry:
    """Ordered tenant universe. Registration order is the WFQ tie-break and
    the operator's 'first tenant' (its own provisioner's view)."""

    def __init__(self, specs=()):
        self._specs: "OrderedDict[str, TenantSpec]" = OrderedDict()
        for spec in specs:
            self.register(spec)

    def register(self, spec: TenantSpec) -> TenantSpec:
        if spec.tenant_id in self._specs:
            raise ValueError(f"duplicate tenant {spec.tenant_id!r}")
        self._specs[spec.tenant_id] = spec
        return spec

    def spec(self, tenant_id: str) -> TenantSpec:
        try:
            return self._specs[tenant_id]
        except KeyError:
            raise KeyError(
                f"unknown tenant {tenant_id!r} (registered: "
                f"{list(self._specs)})"
            ) from None

    def tenants(self) -> List[TenantSpec]:
        return list(self._specs.values())

    def first(self) -> TenantSpec:
        return next(iter(self._specs.values()))

    def __len__(self) -> int:
        return len(self._specs)

    def __contains__(self, tenant_id: str) -> bool:
        return tenant_id in self._specs

    def remove(self, tenant_id: str) -> None:
        """Drop a tenant and release its encode-cache namespace. (Arena
        residency is per-owner device state; it ages out by bucket LRU.)"""
        from . import encode_cache as ec

        self._specs.pop(tenant_id, None)
        ec.drop_tenant(tenant_id)

    @classmethod
    def parse(cls, tenants: str, weights: str = "",
              max_queue_depth: int = 64) -> "TenantRegistry":
        """Build a registry from the operator's flag syntax: `tenants` is a
        comma-separated id list, `weights` is `id=float,...` (unlisted ids
        weigh 1.0). Fail-closed: raises ValueError on duplicates, unknown
        weight keys, non-positive weights, or a depth < 1 — the operator
        refuses to start on a bad tenancy config rather than mis-serving."""
        ids = [t.strip() for t in tenants.split(",") if t.strip()]
        if not ids:
            raise ValueError("--solver-tenants: no tenant ids")
        wmap: Dict[str, float] = {}
        for part in (p.strip() for p in weights.split(",") if p.strip()):
            if "=" not in part:
                raise ValueError(
                    f"--tenant-weights: {part!r} is not id=weight"
                )
            tid, _, w = part.partition("=")
            tid = tid.strip()
            if tid not in ids:
                raise ValueError(
                    f"--tenant-weights: {tid!r} is not in --solver-tenants"
                )
            if tid in wmap:
                raise ValueError(f"--tenant-weights: duplicate {tid!r}")
            try:
                wmap[tid] = float(w)
            except ValueError:
                raise ValueError(
                    f"--tenant-weights: {w!r} is not a number"
                ) from None
        reg = cls()
        for tid in ids:
            reg.register(TenantSpec(
                tenant_id=tid,
                weight=wmap.get(tid, 1.0),
                max_queue_depth=max_queue_depth,
            ))
        return reg


class _TenantBreaker(CircuitBreaker):
    """Per-tenant breaker: exports its own tenant-labeled gauge series and
    flight-records with the tenant tag — one tenant's deadline storm shows
    up in ITS series and ITS dump, never the global breaker's."""

    def __init__(self, tenant_id: str, threshold: int = 3,
                 probe_interval_s: float = 30.0, clock=time.monotonic):
        self.tenant_id = tenant_id
        super().__init__(
            threshold=threshold, probe_interval_s=probe_interval_s,
            clock=clock, gauge=TENANT_BREAKER_STATE,
            labels={"tenant": tenant_id},
        )

    def _on_open(self, failures: int) -> None:
        obstrace.dump("tenant_breaker_open", tenant=self.tenant_id,
                      failures=failures, threshold=self.threshold)


def quantum_bucket(inp) -> tuple:
    """Cheap fusion-eligibility key for a queued SolverInput: heads whose
    padded kernel shapes could match share a bucket. The backend re-checks
    the EXACT padded arg shapes before fusing (backend._cohort_prep's fuse
    key), so this key only has to avoid gathering heads that can never
    fuse — it rounds each population up to a coarse granularity rather
    than reproducing the encode layer's bucketing."""

    def up(n: int, m: int) -> int:
        return ((int(n) + m - 1) // m) * m if n else 0

    return (
        up(len(getattr(inp, "pods", ()) or ()), 16),
        up(len(getattr(inp, "nodes", ()) or ()), 16),
        up(len(getattr(inp, "nodepools", ()) or ()), 4),
        len(getattr(inp, "zones", ()) or ()),
    )


class _CohortSlot:
    """One downstream slot shared by every member of a fused cohort: the
    slot frees when the LAST member resolves (or lane-routes away), so a
    fused dispatch occupies exactly the pipeline depth one solo dispatch
    would — that is the whole throughput win."""

    __slots__ = ("pending",)

    def __init__(self, pending: int):
        self.pending = pending


class _MuxRequest:
    __slots__ = ("ticket", "inp", "fn", "kind", "rev", "trace", "qspan",
                 "t0", "slotted", "vtag", "qkey", "cslot", "fused")

    def __init__(self, ticket: SolveTicket, inp=None, fn=None,
                 kind: str = PROVISIONING, rev=None, trace=None,
                 qspan=None, t0: float = 0.0):
        self.ticket = ticket
        self.inp = inp
        self.fn = fn
        self.kind = kind
        self.rev = rev
        self.trace = trace
        self.qspan = qspan  # "tenant.queue" span: submit -> mux dispatch
        self.t0 = t0  # submit timestamp (mux clock) for the latency series
        self.slotted = False  # holds one of the mux's downstream slots
        # WFQ finish tag, stamped ONCE when this request first reaches its
        # tenant's head (start-time fair queueing): re-deriving it from the
        # advancing virtual clock every scan would inflate a backlogged
        # light tenant's tag in lockstep with a heavy tenant's and starve it
        self.vtag: Optional[float] = None
        self.qkey: Optional[tuple] = None  # quantum_bucket(inp); None for fns
        self.cslot: Optional[_CohortSlot] = None  # shared slot when fused
        self.fused = False  # dispatched as a cohort member (metrics tag)


class _TenantState:
    __slots__ = ("spec", "breaker", "oracle", "queue", "lane", "lane_thread",
                 "vfinish", "open_count", "stats")

    def __init__(self, spec: TenantSpec, breaker: _TenantBreaker):
        self.spec = spec
        self.breaker = breaker
        self.oracle = ReferenceSolver()  # this tenant's own fallback rung
        self.queue: deque = deque()  # FIFO, both kinds — per-tenant order
        self.lane: deque = deque()  # degraded requests for the oracle lane
        self.lane_thread: Optional[threading.Thread] = None
        self.vfinish = 0.0  # last virtual finish tag (WFQ)
        self.open_count = 0  # queued + forwarded + lane, vs max_queue_depth
        self.stats: Dict[str, int] = {
            "submitted": 0,
            "completed": 0,
            "failed": 0,
            "rejected": 0,
            "degraded": 0,
            "superseded": 0,
        }


class TenantMux:
    """Multiplexes registered tenants' solve streams onto one downstream
    SolveService/SolverFleet. Owns WFQ dispatch, admission control, and
    per-tenant breaker/oracle isolation; the downstream surface stays
    untouched (tenancy off = callers hold the downstream directly)."""

    def __init__(self, service, registry: TenantRegistry,
                 max_inflight: Optional[int] = None,
                 breaker_threshold: int = 3,
                 breaker_probe_s: float = 30.0,
                 clock=time.monotonic,
                 own_service: bool = True,
                 cohort: bool = True,
                 cohort_max: int = 8):
        if not len(registry):
            raise ValueError("TenantMux needs at least one registered tenant")
        # fail-closed: a nonsensical cohort width is a config error, not a
        # silent fall-back to solo dispatch
        if int(cohort_max) < 1:
            raise ValueError(
                f"cohort_max must be >= 1, got {cohort_max}"
            )
        self._cohort_max = int(cohort_max) if cohort else 1
        self._service = service
        self.registry = registry
        self._clock = clock
        self._own_service = own_service
        if max_inflight is None:
            # keep the downstream pipeline full (every owner x its depth)
            # while the REST of the backlog waits at the mux, where WFQ —
            # not arrival order — decides who goes next
            max_inflight = (getattr(service, "size", 1)
                            * getattr(service, "depth", 2))
        self.max_inflight = max(1, int(max_inflight))
        self._cv = threading.Condition()
        self._tenants: "OrderedDict[str, _TenantState]" = OrderedDict()
        for spec in registry.tenants():
            self._tenants[spec.tenant_id] = _TenantState(
                spec,
                _TenantBreaker(spec.tenant_id, threshold=breaker_threshold,
                               probe_interval_s=breaker_probe_s, clock=clock),
            )
            TENANT_QUEUE_DEPTH.set(0, tenant=spec.tenant_id)
        self._vtime = 0.0
        self._inflight = 0  # forwarded to the downstream, unresolved
        self._closing = False
        self._open: set = set()  # _MuxRequest not yet resolved
        # Superseded deliveries whose superseding downstream ticket is mid-
        # forward (coalescing fires INSIDE service.submit, before _forward
        # can record the mapping): (state, stale_req, superseding_dticket)
        self._superseded_waiting: list = []
        self._fwd: Dict[SolveTicket, _MuxRequest] = {}  # dticket -> req
        self.mux_stats: Dict[str, int] = {
            "mux_submitted": 0,
            "forwarded": 0,
            "degraded": 0,
            "rejected": 0,
            "mux_coalesced": 0,
            "cohort_dispatches": 0,
            "cohort_members": 0,
        }
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="tenant-mux-dispatch"
        )
        self._dispatcher.start()

    # -- submission ----------------------------------------------------------

    def _state(self, tenant_id: Optional[str]) -> _TenantState:
        if tenant_id is None or tenant_id not in self._tenants:
            raise KeyError(
                f"unknown tenant {tenant_id!r} (registered: "
                f"{list(self._tenants)})"
            )
        return self._tenants[tenant_id]

    def _mint_trace(self, ticket: SolveTicket, kind: str):
        tr, owned = obstrace.adopt_or_begin(kind)
        if tr is None:
            return None, None
        ticket.solve_id = tr.solve_id
        obstrace.set_tenant(tr, ticket.tenant_id)
        if owned:
            ticket.on_done(
                lambda t, _tr=tr: obstrace.finish(
                    _tr, obstrace.status_of(t.error())
                )
            )
        qspan = tr.start_span("tenant.queue", parent=tr.root)
        qspan.set(tenant_id=ticket.tenant_id, kind=kind)
        return tr, qspan

    def _admit_locked(self, state: _TenantState) -> None:
        if self._closing:
            raise ServiceStopped("tenant mux is closed")
        if state.open_count >= state.spec.max_queue_depth:
            state.stats["rejected"] += 1
            self.mux_stats["rejected"] += 1
            TENANT_ADMISSION_REJECTS.inc(tenant=state.spec.tenant_id)
            raise TenantAdmissionReject(
                state.spec.tenant_id, state.open_count,
                state.spec.max_queue_depth,
            )

    def submit(self, inp, tenant_id: Optional[str] = None,
               kind: str = PROVISIONING, rev=None) -> SolveTicket:
        """Queue one tenant's SolverInput. Same-tenant provisioning
        snapshots coalesce at the mux (newest wins, Superseded delivered) —
        a stale snapshot must not spend the tenant's WFQ turn."""
        if tenant_id is None:
            tenant_id = getattr(inp, "tenant_id", None)
        state = self._state(tenant_id)
        if rev is None:
            rev = getattr(inp, "state_rev", None)
        # stamp the input so encode/arena namespace residency per tenant;
        # a fresh (shallow) copy — the caller's object is never mutated
        if dataclasses.is_dataclass(inp) and \
                getattr(inp, "tenant_id", None) != tenant_id:
            inp = dataclasses.replace(inp, tenant_id=tenant_id)
        with self._cv:
            self._admit_locked(state)
            ticket = SolveTicket(kind, rev=rev, tenant_id=tenant_id)
            tr, qspan = self._mint_trace(ticket, kind)
            req = _MuxRequest(ticket, inp=inp, kind=kind, rev=rev, trace=tr,
                              qspan=qspan, t0=self._clock())
            req.qkey = quantum_bucket(inp)
            if kind == PROVISIONING:
                keep: deque = deque()
                while state.queue:
                    stale = state.queue.popleft()
                    if stale.kind != PROVISIONING or stale.inp is None:
                        keep.append(stale)
                        continue
                    self.mux_stats["mux_coalesced"] += 1
                    if stale.qspan is not None:
                        stale.qspan.end("superseded")
                    self._finish_locked(state, stale,
                                        error=Superseded(by=ticket))
                state.queue = keep
            state.queue.append(req)
            state.open_count += 1
            state.stats["submitted"] += 1
            self.mux_stats["mux_submitted"] += 1
            self._open.add(req)
            TENANT_QUEUE_DEPTH.set(len(state.queue),
                                   tenant=state.spec.tenant_id)
            self._cv.notify_all()
        return ticket

    def submit_fn(self, dispatch_fn: Callable,
                  tenant_id: Optional[str] = None,
                  kind: str = DISRUPTION) -> SolveTicket:
        """Queue device-bound work for a tenant. Never coalesced; bypasses
        the tenant breaker (a closure cannot replay on the oracle)."""
        state = self._state(tenant_id)
        with self._cv:
            self._admit_locked(state)
            ticket = SolveTicket(kind, tenant_id=tenant_id)
            tr, qspan = self._mint_trace(ticket, kind)
            req = _MuxRequest(ticket, fn=dispatch_fn, kind=kind, trace=tr,
                              qspan=qspan, t0=self._clock())
            state.queue.append(req)
            state.open_count += 1
            state.stats["submitted"] += 1
            self.mux_stats["mux_submitted"] += 1
            self._open.add(req)
            TENANT_QUEUE_DEPTH.set(len(state.queue),
                                   tenant=state.spec.tenant_id)
            self._cv.notify_all()
        return ticket

    def view(self, tenant_id: str) -> "TenantView":
        self._state(tenant_id)  # fail fast on unknown tenants
        return TenantView(self, tenant_id)

    # -- WFQ dispatch --------------------------------------------------------

    def _pick_locked(self):
        """Pop the next dispatchable request(s) under the mux lock: the
        backlogged tenant with the smallest virtual finish whose path can
        act now (device path needs a downstream slot; the degrade path only
        needs its lane). Degraded heads route to the oracle lane in-line
        and selection repeats. With cohorting on, a device-path winner is
        extended into a fused cohort by continuing the SAME winner
        simulation (_gather_cohort_locked). Returns a non-empty list of
        (state, req) to forward together, or None."""
        while True:
            slot_free = self._inflight < self.max_inflight
            best = None
            for idx, state in enumerate(self._tenants.values()):
                if not state.queue:
                    continue
                head = state.queue[0]
                if head.vtag is None:
                    # stamp the finish tag at head arrival and FREEZE it: an
                    # idle tenant re-enters at the current virtual time (no
                    # burst credit), while a backlogged tenant's tag stays
                    # put so the advancing clock eventually reaches it
                    head.vtag = (max(self._vtime, state.vfinish)
                                 + 1.0 / state.spec.weight)
                device = head.inp is None or state.breaker.peek_allow()
                if device and not slot_free:
                    continue
                if best is None or (head.vtag, idx) < (best[0], best[1]):
                    best = (head.vtag, idx, state, device)
            if best is None:
                return None
            _, _, state, device = best
            req = state.queue.popleft()
            TENANT_QUEUE_DEPTH.set(len(state.queue),
                                   tenant=state.spec.tenant_id)
            # allow() is the mutating twin of the peek above: it may flip
            # OPEN -> HALF_OPEN and consume the probe slot — call it only
            # for the tenant actually being dispatched
            if device and req.inp is not None and not state.breaker.allow():
                device = False  # raced with a failure; degrade after all
            if not device:
                if req.qspan is not None:
                    req.qspan.end("degraded")
                self._lane_put_locked(state, req)
                continue
            # WFQ accounting: only DEVICE dispatches consume the shared
            # pool, so only they advance the tags; oracle-lane work rides
            # the tenant's own thread and is free from the pool's view
            state.vfinish = req.vtag
            self._vtime = max(self._vtime,
                              req.vtag - 1.0 / state.spec.weight)
            picked = [(state, req)]
            if self._cohort_max > 1 and req.inp is not None:
                self._gather_cohort_locked(picked, req.qkey)
            # the whole cohort consumes ONE downstream slot; a lone winner
            # keeps the legacy per-request slot accounting byte-identical
            self._inflight += 1
            if len(picked) == 1:
                req.slotted = True
            else:
                cslot = _CohortSlot(len(picked))
                for _, r in picked:
                    r.cslot = cslot
                    r.fused = True
            for _, r in picked:
                if r.qspan is not None:
                    r.qspan.end()
            return picked

    def _gather_cohort_locked(self, picked: list, qkey) -> None:
        """Extend a WFQ winner into a fused cohort (SPEC.md "Cohort
        semantics"): keep simulating the legacy scan — repeatedly take the
        next smallest-virtual-finish head — and STOP at the first winner
        that cannot ride the same fused dispatch (a tenant already in the
        cohort, a device-bound closure, or a different quantum bucket).
        The dispatch sequence is therefore exactly the legacy order, just
        grouped into one launch, and virtual tags advance per MEMBER —
        never per dispatch — so fusing cannot distort fairness. A
        breaker-open winner was never going to the device: it lane-routes
        (free from the pool's view) and gathering continues past it."""
        in_cohort = {id(s) for s, _ in picked}
        while len(picked) < self._cohort_max:
            best = None
            for idx, state in enumerate(self._tenants.values()):
                if not state.queue:
                    continue
                head = state.queue[0]
                if head.vtag is None:
                    head.vtag = (max(self._vtime, state.vfinish)
                                 + 1.0 / state.spec.weight)
                if best is None or (head.vtag, idx) < (best[0], best[1]):
                    best = (head.vtag, idx, state)
            if best is None:
                return
            _, _, state = best
            head = state.queue[0]
            if id(state) in in_cohort or head.inp is None \
                    or head.qkey != qkey:
                return  # prefix rule: first non-fusable winner ends the scan
            if not (state.breaker.peek_allow() and state.breaker.allow()):
                req = state.queue.popleft()
                TENANT_QUEUE_DEPTH.set(len(state.queue),
                                       tenant=state.spec.tenant_id)
                if req.qspan is not None:
                    req.qspan.end("degraded")
                self._lane_put_locked(state, req)
                continue
            req = state.queue.popleft()
            TENANT_QUEUE_DEPTH.set(len(state.queue),
                                   tenant=state.spec.tenant_id)
            state.vfinish = req.vtag
            self._vtime = max(self._vtime,
                              req.vtag - 1.0 / state.spec.weight)
            in_cohort.add(id(state))
            picked.append((state, req))

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                jobs = self._pick_locked()
                while jobs is None:
                    if self._closing:
                        return
                    self._cv.wait()
                    jobs = self._pick_locked()
            # forward OUTSIDE the lock: service.submit runs coalescing
            # callbacks (and, fully degraded, even oracle solves) inline
            if len(jobs) == 1:
                self._forward(*jobs[0])
            else:
                self._forward_cohort(jobs)

    def _forward(self, state: _TenantState, req: _MuxRequest) -> None:
        tid = state.spec.tenant_id
        try:
            with obstrace.attached(req.trace):
                if req.fn is not None:
                    dticket = self._service.submit_fn(
                        req.fn, kind=req.kind, tenant_id=tid
                    )
                else:
                    dticket = self._service.submit(
                        req.inp, kind=req.kind, rev=req.rev, tenant_id=tid
                    )
        except ServiceStopped as e:
            self._finish(state, req, error=e)
            return
        except Exception as e:  # noqa: BLE001 — isolate: charge + degrade
            self._on_device_failure(state, req, e)
            return
        with self._cv:
            self._fwd[dticket] = req
            self.mux_stats["forwarded"] += 1
            # flush Superseded deliveries parked on the downstream ticket
            # this submit just created (their coalescing callback ran
            # inside service.submit, before the mapping above existed)
            flushes = [(s, r) for (s, r, by) in self._superseded_waiting
                       if by is dticket]
            if flushes:
                self._superseded_waiting = [
                    (s, r, by) for (s, r, by) in self._superseded_waiting
                    if by is not dticket
                ]
        for s, r in flushes:
            self._finish(s, r, error=Superseded(by=req.ticket))
        dticket.on_done(
            lambda t, s=state, r=req: self._on_downstream_done(s, r, t)
        )

    def _forward_cohort(self, jobs: list) -> None:
        """Forward a fused cohort downstream as ONE dispatch. A downstream
        without the cohort seam falls back to per-member solo forwards
        (the shared cohort slot converts to per-member slots in place, so
        accounting stays exact). Failure attribution is per member: a
        whole-dispatch error charges each member's own breaker and replays
        each on its own oracle lane, exactly as a solo failure would."""
        sub = getattr(self._service, "submit_cohort", None)
        if sub is None:
            with self._cv:
                for k, (_, r) in enumerate(jobs):
                    r.cslot = None
                    r.fused = False
                    r.slotted = True
                    if k > 0:
                        self._inflight += 1
            for state, req in jobs:
                self._forward(state, req)
            return
        members = [
            dict(inp=r.inp, kind=r.kind, rev=r.rev,
                 tenant_id=s.spec.tenant_id, trace=r.trace)
            for s, r in jobs
        ]
        try:
            dtickets = sub(members)
        except ServiceStopped as e:
            for state, req in jobs:
                self._finish(state, req, error=e)
            return
        except Exception as e:  # noqa: BLE001 — isolate: charge + degrade
            for state, req in jobs:
                self._on_device_failure(state, req, e)
            return
        SOLVER_FUSED_DISPATCHES.inc()
        SOLVER_COHORT_SIZE.observe(float(len(jobs)))
        with self._cv:
            self.mux_stats["forwarded"] += len(jobs)
            self.mux_stats["cohort_dispatches"] += 1
            self.mux_stats["cohort_members"] += len(jobs)
            flushes = []
            for (_, req), dt in zip(jobs, dtickets):
                self._fwd[dt] = req
                fl = [(s2, r2) for (s2, r2, by) in self._superseded_waiting
                      if by is dt]
                if fl:
                    self._superseded_waiting = [
                        (s2, r2, by)
                        for (s2, r2, by) in self._superseded_waiting
                        if by is not dt
                    ]
                    flushes.extend(
                        (s2, r2, req.ticket) for (s2, r2) in fl
                    )
        for s2, r2, by_ticket in flushes:
            self._finish(s2, r2, error=Superseded(by=by_ticket))
        for (state, req), dt in zip(jobs, dtickets):
            dt.on_done(
                lambda t, s=state, r=req: self._on_downstream_done(s, r, t)
            )

    def _on_downstream_done(self, state: _TenantState, req: _MuxRequest,
                            dticket: SolveTicket) -> None:
        with self._cv:
            self._fwd.pop(dticket, None)
        if req.ticket.done():
            return
        err = dticket.error()
        if err is None:
            state.breaker.record_success()
            self._finish(state, req, result=dticket.result())
            return
        if isinstance(err, Superseded):
            # map the superseding DOWNSTREAM ticket back to its mux ticket;
            # park mid-forward deliveries exactly like the fleet does
            with self._cv:
                by_req = self._fwd.get(err.by) if err.by is not None else None
                if by_req is None and err.by is not None and not self._closing:
                    self._superseded_waiting.append((state, req, err.by))
                    return
            self._finish(state, req, error=Superseded(
                by=by_req.ticket if by_req is not None else None
            ))
            return
        if isinstance(err, ServiceStopped):
            # infrastructure teardown, not this tenant's fault: no breaker
            # charge, no oracle replay (the input may outlive the pool)
            self._finish(state, req, error=err)
            return
        self._on_device_failure(state, req, err)

    def _on_device_failure(self, state: _TenantState, req: _MuxRequest,
                           err: BaseException) -> None:
        """Charge THIS tenant's breaker; replay inputs on THIS tenant's
        oracle rung (the solve still lands — poison degrades, never drops);
        closures surface the failure verbatim. A failed COHORT member
        charges only its own breaker and replays solo — co-members keep
        their fused results untouched."""
        state.breaker.record_failure()
        if req.fused and req.inp is not None:
            SOLVER_COHORT_POISON_REPLAYS.inc(tenant=state.spec.tenant_id)
        if req.inp is None:
            self._finish(state, req, error=err)
            return
        log.warning(
            "tenant %s: device-path solve failed (%s: %s) — replaying on "
            "the tenant oracle", state.spec.tenant_id, type(err).__name__,
            err, extra={"solve_id": req.ticket.solve_id,
                        "tenant_id": state.spec.tenant_id},
        )
        with self._cv:
            self._lane_put_locked(state, req)
            self._cv.notify_all()

    # -- per-tenant oracle lane ----------------------------------------------

    def _release_slot_locked(self, req: _MuxRequest) -> None:
        """Release whatever downstream slot this request holds: a fused
        member releases its share of the cohort slot (the slot itself
        frees with the LAST member); a solo request releases its own."""
        if req.cslot is not None:
            cs, req.cslot = req.cslot, None
            cs.pending -= 1
            if cs.pending == 0:
                self._inflight -= 1
        elif req.slotted:
            req.slotted = False
            self._inflight -= 1

    def _lane_put_locked(self, state: _TenantState, req: _MuxRequest) -> None:
        self._release_slot_locked(req)
        if req.inp is None:
            # device-bound closure with an open breaker: cannot replay —
            # mirror the fleet's no-healthy-owner contract
            req_err = ServiceStopped(
                f"tenant {state.spec.tenant_id!r} breaker open: "
                "device-bound work cannot replay on the oracle"
            )
            self._finish_locked(state, req, error=req_err)
            return
        state.lane.append(req)
        if state.lane_thread is None:
            state.lane_thread = threading.Thread(
                target=self._lane_loop, args=(state,), daemon=True,
                name=f"tenant-oracle-{state.spec.tenant_id}",
            )
            state.lane_thread.start()
        # every lane append must wake the lane thread HERE: the WFQ scan
        # (_pick_locked) routes breaker-open heads to the lane and then goes
        # back to waiting without notifying, so an idle lane thread that won
        # the race for submit()'s notify (and re-waited on an empty lane)
        # would otherwise sleep forever on a resolvable ticket
        self._cv.notify_all()

    def _lane_loop(self, state: _TenantState) -> None:
        while True:
            with self._cv:
                while not state.lane and not self._closing:
                    self._cv.wait()
                if not state.lane:
                    return  # closing and drained
                req = state.lane.popleft()
            self._oracle_solve(state, req)

    def _oracle_solve(self, state: _TenantState, req: _MuxRequest) -> None:
        tid = state.spec.tenant_id
        with self._cv:
            state.stats["degraded"] += 1
            self.mux_stats["degraded"] += 1
        TENANT_DEGRADED.inc(tenant=tid)
        try:
            with obstrace.attached(req.trace), obstrace.span("tenant.oracle"):
                # degraded solves stay attributable in /debug/trace and
                # flight dumps even though no owner service saw them
                obstrace.annotate(tenant_id=tid, kind=req.kind)
                res = state.oracle.solve(req.inp)
        except Exception as e:  # noqa: BLE001 — delivered to the caller
            self._finish(state, req, error=e)
            return
        self._finish(state, req, result=res)

    # -- resolution ----------------------------------------------------------

    def _finish(self, state: _TenantState, req: _MuxRequest, result=None,
                error: Optional[BaseException] = None) -> None:
        with self._cv:
            self._finish_locked(state, req, result=result, error=error)
            self._cv.notify_all()

    def _finish_locked(self, state: _TenantState, req: _MuxRequest,
                       result=None,
                       error: Optional[BaseException] = None) -> None:
        delivered = req.ticket._deliver(result=result, error=error)
        if req in self._open:
            self._open.discard(req)
            state.open_count = max(0, state.open_count - 1)
        self._release_slot_locked(req)
        if not delivered:
            return
        if error is None:
            state.stats["completed"] += 1
            TENANT_SOLVE_SECONDS.observe(
                max(0.0, self._clock() - req.t0),
                tenant=state.spec.tenant_id,
            )
        elif isinstance(error, Superseded):
            state.stats["superseded"] += 1
        else:
            state.stats["failed"] += 1

    # -- introspection (SolveService-surface compatible) ---------------------

    def queue_depth(self) -> int:
        with self._cv:
            held = sum(len(s.queue) + len(s.lane)
                       for s in self._tenants.values())
        return held + self._service.queue_depth()

    def occupancy(self) -> float:
        return self._service.occupancy()

    def unresolved(self) -> int:
        """Mux tickets not yet resolved (the soak harness's dropped-solve
        detector reads this after a full drain: it must be 0)."""
        with self._cv:
            return sum(1 for r in self._open if not r.ticket.done())

    def tenant_stats(self) -> Dict[str, Dict[str, object]]:
        with self._cv:
            return {
                tid: dict(
                    state.stats,
                    queued=len(state.queue),
                    lane=len(state.lane),
                    open=state.open_count,
                    weight=state.spec.weight,
                    breaker=state.breaker.state,
                )
                for tid, state in self._tenants.items()
            }

    @property
    def stats(self) -> Dict[str, int]:
        agg = dict(self._service.stats)
        with self._cv:
            agg.update(self.mux_stats)
            agg["tenants"] = len(self._tenants)
            agg["mux_open"] = len(self._open)
        return agg

    @property
    def solver(self):
        return self._service.solver

    def resume_stats(self) -> Dict[str, float]:
        return self._service.resume_stats()

    def shard_stats(self) -> Dict[str, float]:
        return self._service.shard_stats()

    def decode_stats(self) -> Dict[str, float]:
        return self._service.decode_stats()

    def streaming_stats(self) -> Dict[str, float]:
        return self._service.streaming_stats()

    # -- blue/green handover (solver/handover.py) ----------------------------

    def swap_downstream(self, new_service, own: bool = True,
                        drain_s: float = 5.0) -> Dict[str, object]:
        """Atomically retarget the mux at a NEW downstream service (the
        blue/green cutover seam). Zero-drop contract: requests already
        forwarded to the old service stay mapped in `_fwd` and resolve
        through their existing on_done callbacks — the old service is
        DRAINED (bounded by `drain_s`) before it is closed, because closing
        it with work in flight would deliver ServiceStopped, which the mux
        treats as an infra error rather than replaying. Requests still
        queued at the mux never see the swap at all: the dispatcher reads
        `self._service` at forward time, so from the swap onward every
        forward lands on the new service.

        Returns a report dict: tickets drained from the old service, drain
        timeouts (unresolved when the budget expired), and whether the old
        service was closed here."""
        with self._cv:
            if self._closing:
                raise ServiceStopped("tenant mux is closed")
            old_service = self._service
            old_own = self._own_service
            pending_old = list(self._fwd)
            self._service = new_service
            self._own_service = own
            self.max_inflight = max(1, (getattr(new_service, "size", 1)
                                        * getattr(new_service, "depth", 2)))
            self._cv.notify_all()
        deadline = self._clock() + max(0.0, drain_s)
        timeouts = 0
        for dt in pending_old:
            remaining = deadline - self._clock()
            if dt.done():
                continue
            if remaining <= 0:
                timeouts += 1
                continue
            try:
                dt.result(timeout=remaining)
            except TimeoutError:
                timeouts += 1
            except Exception:  # noqa: BLE001 — an error delivery still
                pass  # resolves the ticket; the mux callback handled it
        closed = False
        if old_own and timeouts == 0:
            # fully drained: the old service can die without a single
            # ServiceStopped reaching a mux ticket
            old_service.close()
            closed = True
        elif old_own:
            # stragglers keep the old service alive; closing it now WOULD
            # drop them — leave it to the caller (handover reports this)
            log.warning(
                "tenant mux: downstream swap left %d ticket(s) undrained "
                "after %.1fs — old service left running", timeouts, drain_s,
            )
        return {
            "drained": len(pending_old) - timeouts,
            "timeouts": timeouts,
            "old_service_closed": closed,
        }

    # -- shutdown ------------------------------------------------------------

    def close(self) -> None:
        """Stop accepting work; fail everything still held at the mux with
        ServiceStopped; close the downstream (when owned — its stop
        resolves every forwarded ticket); join the worker threads. No mux
        ticket is ever left unresolved."""
        with self._cv:
            if self._closing:
                return
            self._closing = True
            drained: List[_MuxRequest] = []
            for state in self._tenants.values():
                while state.queue:
                    drained.append((state, state.queue.popleft()))
                while state.lane:
                    drained.append((state, state.lane.popleft()))
                TENANT_QUEUE_DEPTH.set(0, tenant=state.spec.tenant_id)
            self._cv.notify_all()
        err = ServiceStopped("tenant mux is closed")
        for state, req in drained:
            if req.qspan is not None:
                req.qspan.end("stopped")
            self._finish(state, req, error=err)
        if self._own_service:
            self._service.close()
        # downstream close resolved every forwarded ticket; anything the
        # callbacks missed (not own_service + caller never closed) fails now
        with self._cv:
            leftover = list(self._open)
        for req in leftover:
            state = self._tenants.get(req.ticket.tenant_id)
            if state is not None:
                self._finish(state, req, error=err)
        self._dispatcher.join(timeout=5)
        for state in self._tenants.values():
            if state.lane_thread is not None:
                state.lane_thread.join(timeout=5)


class TenantView:
    """One tenant's SolveService-shaped handle on the mux: the operator
    wires its own provisioner/disruption controller to `mux.view(tenant)`
    so every submission is pinned to that tenant; introspection falls
    through to the mux (and from there the shared downstream)."""

    def __init__(self, mux: TenantMux, tenant_id: str):
        self._mux = mux
        self.tenant_id = tenant_id

    def submit(self, inp, kind: str = PROVISIONING, rev=None) -> SolveTicket:
        return self._mux.submit(inp, tenant_id=self.tenant_id, kind=kind,
                                rev=rev)

    def submit_fn(self, dispatch_fn: Callable,
                  kind: str = DISRUPTION) -> SolveTicket:
        return self._mux.submit_fn(dispatch_fn, tenant_id=self.tenant_id,
                                   kind=kind)

    def close(self) -> None:
        self._mux.close()

    def __getattr__(self, name):
        return getattr(self._mux, name)
