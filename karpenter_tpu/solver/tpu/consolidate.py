"""Batched consolidation simulation (BASELINE config 5).

The disruption engine's inner loop re-solves scheduling once per candidate
subset (SURVEY.md §3.2 HOT LOOP #2). The reference evaluates candidates
SEQUENTIALLY (single-node: one simulation per node; multi-node: a binary
search over cost-ordered prefixes, disruption.md:104-106). Here every subset
is a row of a leading batch axis evaluated in ONE vmapped kernel call:

  - per-subset pods: the union of the subset's reschedulable pods, expressed
    as (group, candidate)-granular runs with per-row run counts zeroed for
    candidates outside the subset;
  - per-subset capacity: the shared existing-node tensors with the subset's
    nodes masked out of [G, E] compat;
  - everything else (groups, types, pools) broadcasts unbatched.

Decisions are identical to the sequential path — each row IS the sequential
simulation — so the controller's semantics (first-success ordering, largest
feasible prefix) are preserved while wall-clock drops from O(subsets) kernel
launches to O(1).

max_claims for simulations is small (a subset needing >1 replacement is
rejected anyway); slot saturation can only under-count claims for rows that
are already rejected (used > 1), never flip a reject into an accept.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .ffd import ARG_INDEX, ffd_solve

# Batched axes (documented in ffd.ARG_SPEC; indices derived from that single
# signature table so a kernel-signature change can never silently skew the
# batch layout):
#   run_count    per-subset member pod COUNTS per natural run
#   node_compat  per-subset node removal — derived ON DEVICE from a tiny
#                [B, n_cand] membership matrix + a shared [E] node→candidate
#                map, so the [B, G, E] tensor never crosses the host link
#   v_count0     removed candidates' zone-count contributions subtracted —
#                their pods are re-posed as pending runs, and hostname (Q)
#                counts on removed nodes are inert because the nodes are
#                compat-masked, but zone (V) counts are GLOBAL
# everything else broadcasts.
_RUN_COUNT = ARG_INDEX["run_count"]
_NODE_COMPAT = ARG_INDEX["node_compat"]
_V_COUNT0 = ARG_INDEX["v_count0"]
_NODE_QM = ARG_INDEX["node_q_member"]
_NODE_QO = ARG_INDEX["node_q_owner"]


def _batched_ffd_core(
    shared_args,
    b_run_count,  # [B, Sp]
    b_v_count0,  # [B, Vp, Z]
    cand_member,  # [B, NC] bool — candidate ids in each subset
    node_cand,  # [E] int32 — candidate id owning node e (-1 none)
    # statics positional: pjit rejects kwargs when in_shardings is set
    max_claims: int = 16,
    emit_takes: bool = True,
    zone_engine: bool = True,
):
    node_compat = shared_args[_NODE_COMPAT]
    nc = cand_member.shape[1]

    def one(rc, vc0, cm):
        removed = (node_cand >= 0) & cm[jnp.clip(node_cand, 0, max(nc - 1, 0))]
        args = list(shared_args)
        args[_RUN_COUNT] = rc
        args[_NODE_COMPAT] = node_compat & ~removed[None, :]
        args[_V_COUNT0] = vc0
        # Q-axis analog of the v_count0 subtraction: kind-2 (positive
        # hostname affinity) reads GLOBAL member sums (tot_m_q = Σ e_cm) for
        # its bootstrap check, so a removed node's members must vanish from
        # the counts exactly as the sequential simulate deletes the node
        # object. Zeroing the removed ROWS is sufficient — every other Q
        # read is per-row and removed rows are already compat-masked out of
        # targeting.
        keep = (~removed)[:, None]
        args[_NODE_QM] = shared_args[_NODE_QM] * keep
        args[_NODE_QO] = shared_args[_NODE_QO] * keep
        return ffd_solve.__wrapped__(
            *args,
            max_claims=max_claims,
            emit_takes=emit_takes,
            zone_engine=zone_engine,
        )

    return jax.vmap(one)(b_run_count, b_v_count0, cand_member)


_batched_ffd = jax.jit(_batched_ffd_core, static_argnums=(5, 6, 7))

# ---- multi-chip dispatch (SURVEY §2.10): the candidate batch axis is the
# scale-out axis — shard it across a Mesh so each chip evaluates its shard
# of subsets; the shared universe replicates; no cross-candidate
# communication exists during the solve, so only the result gather rides
# ICI. Single-device rigs keep the plain jit (no resharding overhead).
_MESH = None
_MESH_INIT = False


def make_candidate_mesh(devices=None, hosts: int = 1):
    """Mesh for the candidate batch axis.

    Single-host: a flat 1-D mesh — the batch splits across chips, the
    result gather rides ICI only. Multi-host (hosts > 1, e.g. under
    jax.distributed across DCN-connected workers): a 2-level (dcn, ici)
    mesh with device-major host order, so XLA partitions the candidate
    axis hierarchically — each host's shard subdivides across its own
    chips, and only the final verdict gather (a few bytes per subset)
    crosses DCN. The solve itself needs NO cross-candidate communication
    either way (SURVEY.md §2.10: independent solves are the scale axis)."""
    from jax.sharding import Mesh

    devs = list(devices if devices is not None else jax.devices())
    if hosts > 1:
        # group by owning process FIRST — jax.devices() id-order is not
        # guaranteed process-contiguous on real topologies, and a naive
        # reshape would put devices from different hosts in one "ici" row,
        # silently routing per-shard traffic over DCN
        by_proc: dict = {}
        for d in devs:
            by_proc.setdefault(getattr(d, "process_index", 0), []).append(d)
        rows = sorted(by_proc.items())
        per = min(len(r) for _, r in rows)
        if len(rows) >= hosts and per > 0:
            grid = np.asarray([r[:per] for _, r in rows[:hosts]])
        else:  # virtual meshes (one process): contiguous split
            per = len(devs) // hosts
            grid = np.asarray(devs[: per * hosts]).reshape(hosts, per)
        return Mesh(grid, ("dcn", "ici"))
    return Mesh(np.asarray(devs), ("candidates",))


def candidate_mesh():
    global _MESH, _MESH_INIT
    if not _MESH_INIT:
        devs = jax.devices()
        if len(devs) > 1:
            # under jax.distributed each worker sees the GLOBAL device
            # list; shard hierarchically so host boundaries align with DCN
            n_proc = jax.process_count()
            _MESH = make_candidate_mesh(devs, hosts=n_proc if n_proc > 1 else 1)
        _MESH_INIT = True
    return _MESH


@functools.lru_cache(maxsize=None)
def _sharded_ffd():
    """jit of the batched solve with candidate-axis sharding over the
    process's one candidate mesh (built at most once — see candidate_mesh)."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = _MESH
    repl = NamedSharding(mesh, PartitionSpec())
    # the batch axis shards over EVERY mesh axis — (candidates,) flat on one
    # host, (dcn, ici) hierarchically across hosts
    shard = NamedSharding(mesh, PartitionSpec(tuple(mesh.axis_names)))
    n_shared = len(ARG_INDEX)
    return jax.jit(
        _batched_ffd_core,
        static_argnums=(5, 6, 7),
        in_shardings=((repl,) * n_shared, shard, shard, shard, repl),
        out_shardings=shard,
    )


def universe_sharding():
    """Replicated placement for the shared consolidation universe on the
    process's candidate mesh, or None on single-device rigs. This is the
    sharding the argument arena keys its universe bucket on when the
    batched evaluator adopts through it (disruption/batched.py prepare):
    one packed upload lands replicated on every mesh device."""
    mesh = candidate_mesh()
    if mesh is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def replicate_shared(kernel_args: tuple) -> tuple:
    """Commit the shared universe to every mesh device ONCE (prepare time):
    without this, the jit's replicated in_shardings re-broadcasts the whole
    constant universe on every dispatch — per-batch traffic proportional to
    the problem, not the batch. (Arena-off path only: with the argument
    arena the evaluator adopts the universe instead — packed delta uploads
    straight into replicated residency.)"""
    repl = universe_sharding()
    if repl is None:
        return tuple(jax.device_put(a) for a in kernel_args)
    return tuple(jax.device_put(a, repl) for a in kernel_args)


def simulate_subsets(
    kernel_args: tuple,
    pod_cand: np.ndarray,  # [N] int64 — candidate id per pod, FFD order
    pod_run: np.ndarray,  # [N] int64 — run index per pod, FFD order
    subsets: Sequence[Sequence[int]],  # candidate-id subsets to evaluate
    candidate_node_idx: dict,  # candidate id -> existing-node index (E axis)
    max_claims: int = 16,
    candidate_v_delta: Optional[dict] = None,  # cid -> [V, Z] zone-count share
    verdict_only: bool = False,
    zone_engine: bool = True,
    v_count0_host: Optional[np.ndarray] = None,  # host copy of args[v_count0]
):
    """Evaluate each subset; returns FFDOutput with leading batch axis B.

    kernel_args: the shared (padded) ffd_solve arrays (order = ffd.ARG_SPEC)
    for the FULL simulation universe (all candidates' pods pending, all
    nodes present), with runs at NATURAL group granularity: same-group pods
    are fungible, so a subset's pods are expressed as per-run COUNTS
    (segment-count of member pods), not per-candidate run splits — the
    kernel's sequential scan stays O(distinct pod specs), not O(candidates),
    and removing pods from a sorted list preserves FFD order exactly.
    verdict_only skips the per-run take outputs (the disruption filter only
    reads leftovers + final claim state).
    """
    # shapes/dtypes read off the device arrays directly (no transfer); the
    # v_count0 VALUES are needed host-side to build the per-subset deltas —
    # callers pass a host copy saved at prepare time to avoid a per-dispatch
    # device fetch over the link
    rc = kernel_args[_RUN_COUNT]
    run_count_dtype = np.dtype(rc.dtype)
    v_count0 = (
        v_count0_host
        if v_count0_host is not None
        else np.asarray(kernel_args[_V_COUNT0])
    )
    B = len(subsets)
    S = rc.shape[0]
    G, E = kernel_args[_NODE_COMPAT].shape
    # candidate-id universe: pods AND nodes (an empty candidate has no pods
    # but its node must still be removed from subset capacity)
    NC = 1
    if pod_cand.size:
        NC = max(NC, int(pod_cand.max()) + 1)
    if candidate_node_idx:
        NC = max(NC, max(candidate_node_idx) + 1)
    # bucket the traced dims so dispatches compile once per bucket, not once
    # per (candidate count, phase width); padded rows simulate an empty
    # subset and are sliced off before verdict decoding. The batch bucket
    # must divide evenly across the candidate mesh when one exists.
    NC = ((NC + 63) // 64) * 64
    mesh = candidate_mesh()
    from ...parallel.sharded import batch_bucket

    Bp = batch_bucket(B, mesh)

    b_run_count = np.zeros((Bp, S), dtype=run_count_dtype)
    b_v_count0 = np.broadcast_to(v_count0, (Bp,) + v_count0.shape).copy()
    cand_member = np.zeros((Bp, NC), dtype=bool)
    for b, subset in enumerate(subsets):
        sub = np.asarray(list(subset), dtype=np.int64)
        cand_member[b, sub[sub < NC]] = True
        member = np.isin(pod_cand, sub)
        b_run_count[b] = np.bincount(
            pod_run[member], minlength=S
        ).astype(run_count_dtype)
        for cid in subset:
            if candidate_v_delta is not None:
                d = candidate_v_delta.get(cid)
                if d is not None and d.size:
                    V, Z = d.shape
                    b_v_count0[b, :V, :Z] -= d

    node_cand = np.full(E, -1, dtype=np.int32)
    for cid, e in candidate_node_idx.items():
        if 0 <= e < E and cid < NC:
            node_cand[e] = cid
    fn = _batched_ffd if mesh is None else _sharded_ffd()
    return fn(
        tuple(kernel_args),
        jnp.asarray(b_run_count),
        jnp.asarray(b_v_count0),
        jnp.asarray(cand_member),
        jnp.asarray(node_cand),
        max_claims,
        not verdict_only,
        zone_engine,
    )


@jax.jit
def _pack_verdicts(out):
    """Flatten every host-consumed verdict field into ONE int32 buffer so a
    tunneled link pays a single device→host roundtrip per dispatch (same
    rationale as backend._pack_outputs). c_mask bit-packs to uint32 words —
    32× less link traffic than int32-per-bool."""
    st = out.state
    b32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)
    B, M, Tp = st.c_mask.shape
    W = (Tp + 31) // 32
    cm = jnp.pad(st.c_mask, ((0, 0), (0, 0), (0, W * 32 - Tp))).reshape(B, M, W, 32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    cm_words = (cm.astype(jnp.uint32) * weights).sum(axis=3, dtype=jnp.uint32)
    return jnp.concatenate(
        [
            out.leftover.sum(axis=1).reshape(B, 1),
            st.used.reshape(B, 1),
            b32(st.c_zc_bits),  # [B, M]
            b32(cm_words).reshape(B, M * W),
        ],
        axis=1,
    ).ravel()


def fetch_verdicts(out, T: int, n_rows: int):
    """One-transfer fetch of the per-subset verdict fields, sliced to the
    first n_rows real (non-padding) subsets.

    Returns (leftover_total [B], used [B], c_zc_bits [B, M] u32,
    c_mask [B, M, T] bool)."""
    st = out.state
    B, M = st.c_zc_bits.shape
    Tp = st.c_mask.shape[2]
    W = (Tp + 31) // 32
    flat = np.asarray(_pack_verdicts(out)).reshape(B, -1)[:n_rows]
    leftover = flat[:, 0]
    used = flat[:, 1]
    zc = flat[:, 2 : 2 + M].view(np.uint32)
    words = flat[:, 2 + M :].view(np.uint32).reshape(n_rows, M, W)
    bits = (
        words[:, :, :, None] >> np.arange(32, dtype=np.uint32)[None, None, None, :]
    ) & 1
    cm = bits.reshape(n_rows, M, W * 32)[:, :, :T].astype(bool)
    return leftover, used, zc, cm


def replacement_min_price(
    c_mask_row: np.ndarray,  # [T] bool (sliced to real T)
    c_zone_row: np.ndarray,  # [Z] bool
    c_ct_row: np.ndarray,  # [C] bool
    offer_avail: np.ndarray,  # [T, Z, C]
    offer_price: np.ndarray,  # [T, Z, C]
) -> Optional[float]:
    """Cheapest offering reachable by the simulated replacement claim."""
    ok = (
        offer_avail
        & c_mask_row[:, None, None]
        & c_zone_row[None, :, None]
        & c_ct_row[None, None, :]
    )
    if not ok.any():
        return None
    return float(offer_price[ok].min())
