"""Batched consolidation simulation (BASELINE config 5).

The disruption engine's inner loop re-solves scheduling once per candidate
subset (SURVEY.md §3.2 HOT LOOP #2). The reference evaluates candidates
SEQUENTIALLY (single-node: one simulation per node; multi-node: a binary
search over cost-ordered prefixes, disruption.md:104-106). Here every subset
is a row of a leading batch axis evaluated in ONE vmapped kernel call:

  - per-subset pods: the union of the subset's reschedulable pods, expressed
    as (group, candidate)-granular runs with per-row run counts zeroed for
    candidates outside the subset;
  - per-subset capacity: the shared existing-node tensors with the subset's
    nodes masked out of [G, E] compat;
  - everything else (groups, types, pools) broadcasts unbatched.

Decisions are identical to the sequential path — each row IS the sequential
simulation — so the controller's semantics (first-success ordering, largest
feasible prefix) are preserved while wall-clock drops from O(subsets) kernel
launches to O(1).

max_claims for simulations is small (a subset needing >1 replacement is
rejected anyway); slot saturation can only under-count claims for rows that
are already rejected (used > 1), never flip a reject into an accept.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .ffd import ARG_INDEX, IN_AXES, ffd_solve

# vmap axes derived from ffd.ARG_SPEC — the single signature table — so a
# kernel-signature change can never silently skew the batch layout again:
#   run_count    batched (per-subset membership zeroing)
#   node_compat  batched (per-subset node removal)
#   v_count0     batched (removed candidates' zone-count contributions
#                subtracted — their pods are re-posed as pending runs, and
#                hostname (Q) counts on removed nodes are inert because the
#                nodes are compat-masked, but zone (V) counts are GLOBAL)
#   everything else broadcasts
_IN_AXES = IN_AXES
_RUN_COUNT = ARG_INDEX["run_count"]
_NODE_COMPAT = ARG_INDEX["node_compat"]
_V_COUNT0 = ARG_INDEX["v_count0"]


@functools.partial(jax.jit, static_argnames=("max_claims",))
def _batched_ffd(args_shared_and_batched, *, max_claims: int):
    fn = jax.vmap(
        functools.partial(ffd_solve.__wrapped__, max_claims=max_claims), in_axes=_IN_AXES
    )
    return fn(*args_shared_and_batched)


def simulate_subsets(
    kernel_args: tuple,
    run_candidate: np.ndarray,  # [S] int32 — candidate id owning each run (-1 = none)
    subsets: Sequence[Sequence[int]],  # candidate-id subsets to evaluate
    candidate_node_idx: dict,  # candidate id -> existing-node index (E axis)
    max_claims: int = 16,
    candidate_v_delta: Optional[dict] = None,  # cid -> [V, Z] zone-count share
):
    """Evaluate each subset; returns FFDOutput with leading batch axis B.

    kernel_args: the shared (padded) ffd_solve arrays (order = ffd.ARG_SPEC)
    for the FULL simulation universe (all candidates' pods as runs, all
    nodes present).
    """
    run_count = np.asarray(kernel_args[_RUN_COUNT])
    node_compat = np.asarray(kernel_args[_NODE_COMPAT])
    v_count0 = np.asarray(kernel_args[_V_COUNT0])
    B = len(subsets)
    S = run_count.shape[0]
    G, E = node_compat.shape

    b_run_count = np.zeros((B, S), dtype=run_count.dtype)
    b_node_compat = np.broadcast_to(node_compat, (B, G, E)).copy()
    b_v_count0 = np.broadcast_to(v_count0, (B,) + v_count0.shape).copy()
    for b, subset in enumerate(subsets):
        member = np.isin(run_candidate, np.asarray(list(subset), dtype=np.int64))
        b_run_count[b] = np.where(member, run_count, 0)
        for cid in subset:
            e = candidate_node_idx.get(cid)
            if e is not None and e < E:
                b_node_compat[b, :, e] = False
            if candidate_v_delta is not None:
                d = candidate_v_delta.get(cid)
                if d is not None and d.size:
                    V, Z = d.shape
                    b_v_count0[b, :V, :Z] -= d

    args = list(kernel_args)
    args[_RUN_COUNT] = jnp.asarray(b_run_count)
    args[_NODE_COMPAT] = jnp.asarray(b_node_compat)
    args[_V_COUNT0] = jnp.asarray(b_v_count0)
    return _batched_ffd(tuple(args), max_claims=max_claims)


def replacement_min_price(
    c_mask_row: np.ndarray,  # [T] bool (sliced to real T)
    c_zone_row: np.ndarray,  # [Z] bool
    c_ct_row: np.ndarray,  # [C] bool
    offer_avail: np.ndarray,  # [T, Z, C]
    offer_price: np.ndarray,  # [T, Z, C]
) -> Optional[float]:
    """Cheapest offering reachable by the simulated replacement claim."""
    ok = (
        offer_avail
        & c_mask_row[:, None, None]
        & c_zone_row[None, :, None]
        & c_ct_row[None, None, :]
    )
    if not ok.any():
        return None
    return float(offer_price[ok].min())
