"""TPU FFD bin-packing kernel.

The tensor re-expression of the reference's `Scheduler.Solve()` hot loop
(SURVEY.md §3.1 HOT LOOP #1; designs/bin-packing.md:17-43). Key idea: FFD
processes pods in sorted order; identical pods form *runs*, and pouring a run
of k identical pods first-fit across open nodes is

    take_n = clamp(k - prefix_sum(cap)_{n-1}, 0, cap_n)

i.e. a vectorized per-node capacity computation + one prefix sum — no
sequential inner loop. Opening new nodes is closed-form: each new node holds
`kmax` pods (the best surviving instance type's capacity), so
`ceil(remaining / kmax)` nodes open at once, with per-pool limit accounting
in closed form as well. The only sequential axis is the run axis (≈ number
of distinct pod specs), walked with `lax.scan`.

Bit-packing (v2): the (zone × capacity-type) offering feasibility of a claim
is a PRODUCT SET (zones ∩ … ) × (cts ∩ …), and intersections of product sets
intersect componentwise — so each claim's joint feasibility is one uint32
(`c_zc_bits`, bit z*C+c), each instance type's availability is one uint32,
and the joint "does any offering survive" check is a single [M,T] bitwise
AND instead of an [M,ZC]×[ZC,T] contraction. Group-membership state packs
the same way into ceil(G/32) words. This collapsed the step's dominant
memory traffic and the XLA graph size (the round-1 kernel compiled in ~15
minutes and ran 2× over the latency target; see BENCH_r01).

Per-step work is O((E+M)·T·R) fully-vectorized integer ops — VPU-friendly,
HBM-bandwidth-bound, no data-dependent Python control flow, static shapes
(SPEC: compile once per (E, M, T, R, P, S, Q, W) bucket). Padded scan steps
(run_count == 0) skip their body via `lax.cond`.

Decisions are bit-identical to the reference path by construction: same FFD
order (runs follow it), same first-fit node order (array index = creation
order), same type-survival rule, same pool priority and limit charging
(solver/SPEC.md).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

INT32_MAX = jnp.int32(2**31 - 1)
BIG = jnp.int32(2**30)

# Positional argument table for ffd_solve. The second element is the batch
# axis used by the consolidation evaluator's vmap (None = shared/broadcast,
# 0 = per-candidate row). consolidate.py and backend.py derive indices from
# THIS table — never hand-count positions (the round-1 hand-counted indices
# silently skewed when the signature grew; VERDICT "what's weak" #6).
ARG_SPEC = (
    ("run_group", None),
    ("run_count", 0),
    ("group_req", None),
    ("group_compat_t", None),
    ("group_zc_bits", None),
    ("group_pool", None),
    ("group_pair_nok", None),
    ("group_device", None),
    ("type_alloc", None),
    ("type_charge", None),
    ("offer_zc_bits", None),
    ("pool_type", None),
    ("pool_zc_bits", None),
    ("pool_daemon", None),
    ("pool_limit", None),
    ("pool_usage0", None),
    ("node_free", None),
    ("node_compat", 0),
    ("q_member", None),
    ("q_owner", None),
    ("q_kind", None),
    ("q_cap", None),
    ("node_q_member", None),
    ("node_q_owner", None),
)

ARG_INDEX = {name: i for i, (name, _ax) in enumerate(ARG_SPEC)}
IN_AXES = tuple(ax for _name, ax in ARG_SPEC)


class FFDState(NamedTuple):
    e_cum: jnp.ndarray  # [E, R] int32 — requests placed on existing nodes
    c_cum: jnp.ndarray  # [M, R] int32 — requests on claim slots (incl daemon)
    c_mask: jnp.ndarray  # [M, T] bool — surviving instance types
    c_zc_bits: jnp.ndarray  # [M] uint32 — joint (zone, ct) feasibility bits
    c_gbits: jnp.ndarray  # [M, W] uint32 — groups placed on each claim
    c_pool: jnp.ndarray  # [M] int32 — pool index, -1 if unopened
    used: jnp.ndarray  # scalar int32 — claims opened so far
    p_usage: jnp.ndarray  # [P, R] int32 — pool usage (limit accounting)
    # hostname-constraint counts (Q axis; see encode.py):
    e_cm: jnp.ndarray  # [E, Q] int32 — matching (member) pods per sig
    e_co: jnp.ndarray  # [E, Q] int32 — anti-owner pod presence per sig
    c_cm: jnp.ndarray  # [M, Q] int32
    c_co: jnp.ndarray  # [M, Q] int32


class FFDOutput(NamedTuple):
    take_e: jnp.ndarray  # [S, E] int32 — pods of run s placed per existing node
    take_c: jnp.ndarray  # [S, M] int32 — pods of run s placed per claim slot
    leftover: jnp.ndarray  # [S] int32 — pods of run s that failed to place
    state: FFDState


def _fit_count(alloc, cum, req):
    """[N] per-node count of additional `req` pods fitting: min over R of
    floor((alloc - cum) / req); req==0 axes don't constrain. Clamped >= 0."""
    # alloc/cum: [N, R]; req: [R]
    safe_req = jnp.maximum(req, 1)
    k = jnp.where(req[None, :] > 0, (alloc - cum) // safe_req[None, :], BIG)
    return jnp.maximum(jnp.min(k, axis=1), 0).astype(jnp.int32)


def _fit_count_nt(alloc_t, cum_n, req):
    """[N, T]: pods fitting per (node, type). alloc_t [T,R], cum_n [N,R].

    Statically unrolled over R to avoid materializing [N,T,R] — each r-slice
    is a rank-1 broadcast subtract + divide, which XLA fuses."""
    N, R = cum_n.shape
    T = alloc_t.shape[0]
    k = jnp.full((N, T), BIG, jnp.int32)
    for r in range(R):
        kr = jnp.where(
            req[r] > 0,
            (alloc_t[None, :, r] - cum_n[:, r][:, None]) // jnp.maximum(req[r], 1),
            BIG,
        )
        k = jnp.minimum(k, kr.astype(jnp.int32))
    return jnp.maximum(k, 0)


def _pour(cap, remaining):
    """First-fit pour of `remaining` identical pods into nodes with per-node
    capacity `cap` (in index order). Returns (take [N], left scalar)."""
    prefix = jnp.cumsum(cap) - cap  # exclusive prefix
    take = jnp.clip(remaining - prefix, 0, cap).astype(jnp.int32)
    return take, remaining - jnp.sum(take)


def _hostname_allowance(cm, co, q_kind, q_cap, member_g, owner_g):
    """[N] per-node additional-pod allowance for group g under the hostname
    constraint sigs (encode.py Q axis; SPEC.md hostname floor-0 rule):

      TSC (kind 0), owner+member : cap − cm
      TSC (kind 0), owner only   : ∞ while cm+1 ≤ cap, else 0
      anti (kind 1), owner       : 1 if member else ∞ — while cm == 0, else 0
      anti (kind 1), member only : ∞ while no owner pod present, else 0
    """
    kind0 = q_kind[None, :] == 0
    relevant = owner_g[None, :] | ((q_kind[None, :] == 1) & member_g[None, :])
    tsc_allow = jnp.where(
        member_g[None, :],
        q_cap[None, :] - cm,
        jnp.where(cm + 1 <= q_cap[None, :], BIG, 0),
    )
    anti_owner_allow = jnp.where(
        cm == 0, jnp.where(member_g[None, :], 1, BIG), 0
    )
    anti_member_allow = jnp.where(co == 0, BIG, 0)
    per_q = jnp.where(
        kind0,
        tsc_allow,
        jnp.where(owner_g[None, :], anti_owner_allow, anti_member_allow),
    )
    per_q = jnp.where(relevant, per_q, BIG)
    return jnp.maximum(jnp.min(per_q, axis=1), 0).astype(jnp.int32)


def _gbit_word(g, W):
    """[W] uint32 one-hot word for group index g."""
    word = (g >> 5).astype(jnp.int32)
    bit = (g & 31).astype(jnp.uint32)
    return jnp.where(
        jnp.arange(W, dtype=jnp.int32) == word, jnp.uint32(1) << bit, jnp.uint32(0)
    )


@functools.partial(jax.jit, static_argnames=("max_claims",))
def ffd_solve(
    # runs
    run_group,  # [S] i32
    run_count,  # [S] i32
    # groups
    group_req,  # [G, R] i32
    group_compat_t,  # [G, T] bool
    group_zc_bits,  # [G] u32 — packed (zone × ct) admission bits
    group_pool,  # [G, P] bool
    group_pair_nok,  # [G, W] u32 — packed ~pairwise-compatibility words
    group_device,  # [G] bool — False => fallback group, skip on device
    # types
    type_alloc,  # [T, R] i32
    type_charge,  # [T, R] i32 — capacity on charge axes, 0 elsewhere
    offer_zc_bits,  # [T] u32 — packed offering availability bits
    # pools
    pool_type,  # [P, T] bool
    pool_zc_bits,  # [P] u32
    pool_daemon,  # [P, R] i32
    pool_limit,  # [P, R] i32
    pool_usage0,  # [P, R] i32
    # existing nodes
    node_free,  # [E, R] i32
    node_compat,  # [G, E] bool
    # hostname constraint sigs (Q axis; encode.py)
    q_member,  # [G, Q] bool
    q_owner,  # [G, Q] bool
    q_kind,  # [Q] i32
    q_cap,  # [Q] i32
    node_q_member,  # [E, Q] i32
    node_q_owner,  # [E, Q] i32
    *,
    max_claims: int,
) -> FFDOutput:
    E, R = node_free.shape
    G, T = group_compat_t.shape
    P = pool_type.shape[0]
    Q = q_kind.shape[0]
    W = group_pair_nok.shape[1]
    M = max_claims

    state = FFDState(
        e_cum=jnp.zeros((E, R), jnp.int32),
        c_cum=jnp.zeros((M, R), jnp.int32),
        c_mask=jnp.zeros((M, T), bool),
        c_zc_bits=jnp.zeros((M,), jnp.uint32),
        c_gbits=jnp.zeros((M, W), jnp.uint32),
        c_pool=jnp.full((M,), -1, jnp.int32),
        used=jnp.int32(0),
        p_usage=pool_usage0.astype(jnp.int32),
        e_cm=node_q_member.astype(jnp.int32),
        e_co=node_q_owner.astype(jnp.int32),
        c_cm=jnp.zeros((M, Q), jnp.int32),
        c_co=jnp.zeros((M, Q), jnp.int32),
    )

    def step_body(st: FFDState, g, count):
        req = group_req[g]  # [R]
        compat_t = group_compat_t[g]  # [T]
        g_zc = group_zc_bits[g]  # u32
        gpool = group_pool[g]  # [P]
        g_nok = group_pair_nok[g]  # [W]
        member_g = q_member[g]  # [Q]
        owner_g = q_owner[g]  # [Q]
        on_device = group_device[g]

        remaining = jnp.where(on_device, count, 0).astype(jnp.int32)

        # ---- 1. existing nodes --------------------------------------------
        e_cap = _fit_count(node_free, st.e_cum, req)
        e_cap = jnp.where(node_compat[g], e_cap, 0)
        e_cap = jnp.minimum(
            e_cap, _hostname_allowance(st.e_cm, st.e_co, q_kind, q_cap, member_g, owner_g)
        )
        take_e, remaining = _pour(e_cap, remaining)
        e_cum = st.e_cum + take_e[:, None] * req[None, :]
        e_cm = st.e_cm + take_e[:, None] * member_g[None, :].astype(jnp.int32)
        e_co = st.e_co + (
            (take_e[:, None] > 0) & owner_g[None, :] & (q_kind[None, :] == 1)
        ).astype(jnp.int32)

        # ---- 2. open claims -----------------------------------------------
        # joint offering feasibility: one bitwise AND per (claim, type)
        A_bits = offer_zc_bits & g_zc  # [T] u32
        ok_off = (st.c_zc_bits[:, None] & A_bits[None, :]) != 0  # [M, T]

        # pairwise group compatibility with everything on the node
        pair_ok = ~jnp.any((st.c_gbits & g_nok[None, :]) != 0, axis=1)  # [M]
        # pod must tolerate the claim's pool taints
        is_open = st.c_pool >= 0
        pool_ok = jnp.where(is_open, gpool[jnp.clip(st.c_pool, 0, P - 1)], False)

        k_nt = _fit_count_nt(type_alloc, st.c_cum, req)  # [M, T]
        fit_nt = st.c_mask & compat_t[None, :] & ok_off  # [M, T]
        node_ok = is_open & pair_ok & pool_ok  # [M]
        k_nt = jnp.where(fit_nt & node_ok[:, None], k_nt, 0)
        c_cap = jnp.max(k_nt, axis=1)  # [M]
        c_cap = jnp.minimum(
            c_cap, _hostname_allowance(st.c_cm, st.c_co, q_kind, q_cap, member_g, owner_g)
        )
        take_c, remaining = _pour(c_cap, remaining)

        added = take_c > 0
        c_cum = st.c_cum + take_c[:, None] * req[None, :]
        c_mask = jnp.where(added[:, None], fit_nt & (k_nt >= take_c[:, None]), st.c_mask)
        c_zc_bits = jnp.where(added, st.c_zc_bits & g_zc, st.c_zc_bits)
        gword = _gbit_word(g, W)  # [W]
        c_gbits = st.c_gbits | jnp.where(added[:, None], gword[None, :], jnp.uint32(0))
        c_cm = st.c_cm + take_c[:, None] * member_g[None, :].astype(jnp.int32)
        c_co = st.c_co + (
            added[:, None] & owner_g[None, :] & (q_kind[None, :] == 1)
        ).astype(jnp.int32)

        # ---- 3. new claims, pool by pool in priority order ----------------
        # fresh-node allowance under hostname constraints (counts start at 0)
        fresh_allow = _hostname_allowance(
            jnp.zeros((1, Q), jnp.int32),
            jnp.zeros((1, Q), jnp.int32),
            q_kind,
            q_cap,
            member_g,
            owner_g,
        )[0]

        def open_pool(p, carry):
            (remaining, used, c_cum, c_mask, c_zc_bits, c_gbits, c_pool,
             p_usage, take_new, c_cm, c_co) = carry

            # per-type pod capacity for a fresh node of pool p
            new_bits = pool_zc_bits[p] & g_zc  # u32
            off_ok = (offer_zc_bits & new_bits) != 0  # [T]
            fit_t = compat_t & pool_type[p] & off_ok  # [T]
            daemon = pool_daemon[p]  # [R]
            safe_req = jnp.maximum(req, 1)
            k_t = jnp.where(
                req[None, :] > 0, (type_alloc - daemon[None, :]) // safe_req[None, :], BIG
            )
            k_t = jnp.maximum(jnp.min(k_t, axis=1), 0).astype(jnp.int32)
            k_t = jnp.where(fit_t, k_t, 0)
            kmax = jnp.max(k_t)
            # hostname constraints cap pods-per-fresh-node below the
            # resource capacity (e.g. hostname spread: maxSkew per node)
            full_take = jnp.minimum(kmax, fresh_allow)

            # limit accounting (SPEC: claim blocked if any limited resource
            # usage >= limit at creation; charge = min type charge among the
            # survivors AT CREATION, i.e. after the claim's FIRST pod — the
            # oracle charges right after the opening pod lands)
            one_set = fit_t & (k_t >= 1)
            charge_one = jnp.min(
                jnp.where(one_set[:, None], type_charge, INT32_MAX), axis=0
            )  # [R]
            charge_one = jnp.where(charge_one == INT32_MAX, 0, charge_one)
            headroom = pool_limit[p] - p_usage[p]  # [R] (may be negative)
            # claims before resource r trips: ceil(headroom / charge)
            trips = jnp.where(
                charge_one > 0,
                jnp.maximum(-(-headroom // jnp.maximum(charge_one, 1)), 0),
                BIG,
            )
            already_over = jnp.any(p_usage[p] >= pool_limit[p])
            allow = jnp.where(already_over, 0, jnp.min(trips)).astype(jnp.int32)

            n_want = jnp.where(full_take > 0, -(-remaining // jnp.maximum(full_take, 1)), 0)
            slots_left = M - used
            n_new = jnp.minimum(jnp.minimum(n_want, allow), slots_left).astype(jnp.int32)
            eligible = gpool[p] & (full_take > 0)
            n_new = jnp.where(eligible, n_new, 0)

            def apply(ops):
                (c_cum, c_mask, c_zc_bits, c_gbits, c_pool, p_usage, take_new,
                 c_cm, c_co) = ops
                idx = jnp.arange(M, dtype=jnp.int32)
                is_new = (idx >= used) & (idx < used + n_new)
                # node j (0-based among new) takes min(full_take, remaining - j*full_take)
                j = idx - used
                take_j = jnp.where(
                    is_new, jnp.clip(remaining - j * full_take, 0, full_take), 0
                ).astype(jnp.int32)

                c_cum = jnp.where(
                    is_new[:, None], daemon[None, :] + take_j[:, None] * req[None, :], c_cum
                )
                new_mask = fit_t[None, :] & (k_t[None, :] >= take_j[:, None])
                c_mask = jnp.where(is_new[:, None], new_mask, c_mask)
                c_zc_bits = jnp.where(is_new, new_bits, c_zc_bits)
                c_gbits = jnp.where(is_new[:, None], gword[None, :], c_gbits)
                c_pool = jnp.where(is_new, p, c_pool)
                c_cm = jnp.where(
                    is_new[:, None], take_j[:, None] * member_g[None, :].astype(jnp.int32), c_cm
                )
                c_co = jnp.where(
                    is_new[:, None],
                    ((take_j[:, None] > 0) & owner_g[None, :] & (q_kind[None, :] == 1)).astype(
                        jnp.int32
                    ),
                    c_co,
                )
                # charge pool usage: every claim charges its at-creation
                # (1-pod survivor) minimum — n_new claims, charge_one each
                p_usage = p_usage.at[p].add((charge_one * n_new).astype(jnp.int32))
                take_new = take_new + take_j
                return (c_cum, c_mask, c_zc_bits, c_gbits, c_pool, p_usage, take_new,
                        c_cm, c_co, jnp.sum(take_j))

            def skip(ops):
                return ops + (jnp.int32(0),)

            (c_cum, c_mask, c_zc_bits, c_gbits, c_pool, p_usage, take_new, c_cm,
             c_co, placed_new) = jax.lax.cond(
                n_new > 0,
                apply,
                skip,
                (c_cum, c_mask, c_zc_bits, c_gbits, c_pool, p_usage, take_new, c_cm, c_co),
            )

            remaining = remaining - placed_new
            used = used + n_new
            return (remaining, used, c_cum, c_mask, c_zc_bits, c_gbits, c_pool,
                    p_usage, take_new, c_cm, c_co)

        carry = (
            remaining,
            st.used,
            c_cum,
            c_mask,
            c_zc_bits,
            c_gbits,
            st.c_pool,
            st.p_usage,
            jnp.zeros((M,), jnp.int32),
            c_cm,
            c_co,
        )
        carry = jax.lax.fori_loop(0, P, open_pool, carry)
        (remaining, used, c_cum, c_mask, c_zc_bits, c_gbits, c_pool2, p_usage,
         take_new, c_cm, c_co) = carry

        new_state = FFDState(
            e_cum=e_cum,
            c_cum=c_cum,
            c_mask=c_mask,
            c_zc_bits=c_zc_bits,
            c_gbits=c_gbits,
            c_pool=c_pool2,
            used=used,
            p_usage=p_usage,
            e_cm=e_cm,
            e_co=e_co,
            c_cm=c_cm,
            c_co=c_co,
        )
        return new_state, (take_e, take_c + take_new, remaining)

    def step(st: FFDState, run):
        g, count = run
        # padded runs (count == 0) skip the whole body — bucketed S padding
        # costs ~nothing at runtime
        return jax.lax.cond(
            count > 0,
            lambda s: step_body(s, g, count),
            lambda s: (
                s,
                (
                    jnp.zeros((E,), jnp.int32),
                    jnp.zeros((M,), jnp.int32),
                    jnp.int32(0),
                ),
            ),
            st,
        )

    state, (take_e, take_c, leftover) = jax.lax.scan(step, state, (run_group, run_count))
    return FFDOutput(take_e=take_e, take_c=take_c, leftover=leftover, state=state)
