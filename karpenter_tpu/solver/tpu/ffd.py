"""TPU FFD bin-packing kernel.

The tensor re-expression of the reference's `Scheduler.Solve()` hot loop
(SURVEY.md §3.1 HOT LOOP #1; designs/bin-packing.md:17-43). Key idea: FFD
processes pods in sorted order; identical pods form *runs*, and pouring a run
of k identical pods first-fit across open nodes is

    take_n = clamp(k - prefix_sum(cap)_{n-1}, 0, cap_n)

i.e. a vectorized per-node capacity computation + one prefix sum — no
sequential inner loop. Opening new nodes is closed-form: each new node holds
`kmax` pods (the best surviving instance type's capacity), so
`ceil(remaining / kmax)` nodes open at once, with per-pool limit accounting
in closed form as well. The only sequential axis is the run axis (≈ number
of distinct pod specs), walked with `lax.scan`.

Per-step work is O((E+M)·T·R) fully-vectorized integer ops — VPU-friendly,
HBM-bandwidth-bound, no data-dependent Python control flow, static shapes
(SPEC: compile once per (E, M, T, R, Z, C, P, G, S) bucket).

Decisions are bit-identical to the reference path by construction: same FFD
order (runs follow it), same first-fit node order (array index = creation
order), same type-survival rule, same pool priority and limit charging
(solver/SPEC.md).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

INT32_MAX = jnp.int32(2**31 - 1)
BIG = jnp.int32(2**30)


class FFDState(NamedTuple):
    e_cum: jnp.ndarray  # [E, R] int32 — requests placed on existing nodes
    c_cum: jnp.ndarray  # [M, R] int32 — requests on claim slots (incl daemon)
    c_mask: jnp.ndarray  # [M, T] bool — surviving instance types
    c_zone: jnp.ndarray  # [M, Z] bool
    c_ct: jnp.ndarray  # [M, C] bool
    c_gmask: jnp.ndarray  # [M, G] bool — groups placed on each claim
    c_pool: jnp.ndarray  # [M] int32 — pool index, -1 if unopened
    c_open: jnp.ndarray  # [M] bool
    used: jnp.ndarray  # scalar int32 — claims opened so far
    p_usage: jnp.ndarray  # [P, R] int32 — pool usage (limit accounting)
    # hostname-constraint counts (Q axis; see encode.py):
    e_cm: jnp.ndarray  # [E, Q] int32 — matching (member) pods per sig
    e_co: jnp.ndarray  # [E, Q] int32 — anti-owner pod presence per sig
    c_cm: jnp.ndarray  # [M, Q] int32
    c_co: jnp.ndarray  # [M, Q] int32


class FFDOutput(NamedTuple):
    take_e: jnp.ndarray  # [S, E] int32 — pods of run s placed per existing node
    take_c: jnp.ndarray  # [S, M] int32 — pods of run s placed per claim slot
    leftover: jnp.ndarray  # [S] int32 — pods of run s that failed to place
    state: FFDState


def _fit_count(alloc, cum, req):
    """[N] per-node count of additional `req` pods fitting: min over R of
    floor((alloc - cum) / req); req==0 axes don't constrain. Clamped >= 0."""
    # alloc/cum: [N, R]; req: [R]
    safe_req = jnp.maximum(req, 1)
    k = jnp.where(req[None, :] > 0, (alloc - cum) // safe_req[None, :], BIG)
    return jnp.maximum(jnp.min(k, axis=1), 0).astype(jnp.int32)


def _fit_count_nt(alloc_t, cum_n, req):
    """[N, T]: pods fitting per (node, type). alloc_t [T,R], cum_n [N,R].

    Statically unrolled over R to avoid materializing [N,T,R] — each r-slice
    is a rank-1 broadcast subtract + divide, which XLA fuses."""
    N, R = cum_n.shape
    T = alloc_t.shape[0]
    k = jnp.full((N, T), BIG, jnp.int32)
    for r in range(R):
        kr = jnp.where(
            req[r] > 0,
            (alloc_t[None, :, r] - cum_n[:, r][:, None]) // jnp.maximum(req[r], 1),
            BIG,
        )
        k = jnp.minimum(k, kr.astype(jnp.int32))
    return jnp.maximum(k, 0)


def _pour(cap, remaining):
    """First-fit pour of `remaining` identical pods into nodes with per-node
    capacity `cap` (in index order). Returns (take [N], left scalar)."""
    prefix = jnp.cumsum(cap) - cap  # exclusive prefix
    take = jnp.clip(remaining - prefix, 0, cap).astype(jnp.int32)
    return take, remaining - jnp.sum(take)


def _hostname_allowance(cm, co, q_kind, q_cap, member_g, owner_g):
    """[N] per-node additional-pod allowance for group g under the hostname
    constraint sigs (encode.py Q axis; SPEC.md hostname floor-0 rule):

      TSC (kind 0), owner+member : cap − cm
      TSC (kind 0), owner only   : ∞ while cm+1 ≤ cap, else 0
      anti (kind 1), owner       : 1 if member else ∞ — while cm == 0, else 0
      anti (kind 1), member only : ∞ while no owner pod present, else 0
    """
    kind0 = q_kind[None, :] == 0
    relevant = owner_g[None, :] | ((q_kind[None, :] == 1) & member_g[None, :])
    tsc_allow = jnp.where(
        member_g[None, :],
        q_cap[None, :] - cm,
        jnp.where(cm + 1 <= q_cap[None, :], BIG, 0),
    )
    anti_owner_allow = jnp.where(
        cm == 0, jnp.where(member_g[None, :], 1, BIG), 0
    )
    anti_member_allow = jnp.where(co == 0, BIG, 0)
    per_q = jnp.where(
        kind0,
        tsc_allow,
        jnp.where(owner_g[None, :], anti_owner_allow, anti_member_allow),
    )
    per_q = jnp.where(relevant, per_q, BIG)
    return jnp.maximum(jnp.min(per_q, axis=1), 0).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("max_claims",))
def ffd_solve(
    # runs
    run_group,  # [S] i32
    run_count,  # [S] i32
    # groups
    group_req,  # [G, R] i32
    group_compat_t,  # [G, T] bool
    group_zone,  # [G, Z] bool
    group_ct,  # [G, C] bool
    group_pool,  # [G, P] bool
    group_pair,  # [G, G] bool
    group_device,  # [G] bool — False => fallback group, skip on device
    # types
    type_alloc,  # [T, R] i32
    type_charge,  # [T, R] i32 — capacity on charge axes, 0 elsewhere
    offer_avail,  # [T, Z, C] bool
    # pools
    pool_type,  # [P, T] bool
    pool_zone,  # [P, Z] bool
    pool_ct,  # [P, C] bool
    pool_daemon,  # [P, R] i32
    pool_limit,  # [P, R] i32
    pool_usage0,  # [P, R] i32
    # existing nodes
    node_free,  # [E, R] i32
    node_compat,  # [G, E] bool
    # hostname constraint sigs (Q axis; encode.py)
    q_member,  # [G, Q] bool
    q_owner,  # [G, Q] bool
    q_kind,  # [Q] i32
    q_cap,  # [Q] i32
    node_q_member,  # [E, Q] i32
    node_q_owner,  # [E, Q] i32
    *,
    max_claims: int,
) -> FFDOutput:
    E, R = node_free.shape
    G, T = group_compat_t.shape
    P = pool_type.shape[0]
    Z = group_zone.shape[1]
    C = group_ct.shape[1]
    Q = q_kind.shape[0]
    M = max_claims

    state = FFDState(
        e_cum=jnp.zeros((E, R), jnp.int32),
        c_cum=jnp.zeros((M, R), jnp.int32),
        c_mask=jnp.zeros((M, T), bool),
        c_zone=jnp.zeros((M, Z), bool),
        c_ct=jnp.zeros((M, C), bool),
        c_gmask=jnp.zeros((M, G), bool),
        c_pool=jnp.full((M,), -1, jnp.int32),
        c_open=jnp.zeros((M,), bool),
        used=jnp.int32(0),
        p_usage=pool_usage0.astype(jnp.int32),
        e_cm=node_q_member.astype(jnp.int32),
        e_co=node_q_owner.astype(jnp.int32),
        c_cm=jnp.zeros((M, Q), jnp.int32),
        c_co=jnp.zeros((M, Q), jnp.int32),
    )

    def step(st: FFDState, run):
        g, count = run
        req = group_req[g]  # [R]
        compat_t = group_compat_t[g]  # [T]
        gz = group_zone[g]  # [Z]
        gc = group_ct[g]  # [C]
        gpool = group_pool[g]  # [P]
        gpair = group_pair[g]  # [G]
        member_g = q_member[g]  # [Q]
        owner_g = q_owner[g]  # [Q]
        on_device = group_device[g]

        remaining = jnp.where(on_device, count, 0).astype(jnp.int32)

        # ---- 1. existing nodes --------------------------------------------
        e_cap = _fit_count(node_free, st.e_cum, req)
        e_cap = jnp.where(node_compat[g], e_cap, 0)
        e_cap = jnp.minimum(e_cap, _hostname_allowance(st.e_cm, st.e_co, q_kind, q_cap, member_g, owner_g))
        take_e, remaining = _pour(e_cap, remaining)
        e_cum = st.e_cum + take_e[:, None] * req[None, :]
        e_cm = st.e_cm + take_e[:, None] * member_g[None, :].astype(jnp.int32)
        e_co = st.e_co + ((take_e[:, None] > 0) & owner_g[None, :] & (q_kind[None, :] == 1)).astype(jnp.int32)

        # ---- 2. open claims -----------------------------------------------
        # offering availability under group+node zone/ct masks — exact joint
        # check: ok_off[n,t] = exists (z,c): avail & c_zone[n,z] & c_ct[n,c]
        # & gz[z] & gc[c]. Flatten (z,c) and contract: [M,ZC] @ [ZC,T].
        A = offer_avail & gz[None, :, None] & gc[None, None, :]  # [T, Z, C]
        ZC = A.shape[1] * A.shape[2]
        nzc = (st.c_zone[:, :, None] & st.c_ct[:, None, :]).reshape(-1, ZC)  # [M, ZC]
        ok_off = (
            jnp.einsum("nx,tx->nt", nzc.astype(jnp.int32), A.reshape(-1, ZC).astype(jnp.int32)) > 0
        )  # [M, T]

        # pairwise group compatibility with everything on the node
        pair_ok = ~jnp.any(st.c_gmask & ~gpair[None, :], axis=1)  # [M]
        # pod must tolerate the claim's pool taints
        pool_ok = jnp.where(st.c_pool >= 0, gpool[jnp.clip(st.c_pool, 0, P - 1)], False)

        k_nt = _fit_count_nt(type_alloc, st.c_cum, req)  # [M, T]
        fit_nt = st.c_mask & compat_t[None, :] & ok_off  # [M, T]
        node_ok = st.c_open & pair_ok & pool_ok  # [M]
        k_nt = jnp.where(fit_nt & node_ok[:, None], k_nt, 0)
        c_cap = jnp.max(k_nt, axis=1)  # [M]
        c_cap = jnp.minimum(c_cap, _hostname_allowance(st.c_cm, st.c_co, q_kind, q_cap, member_g, owner_g))
        take_c, remaining = _pour(c_cap, remaining)

        added = take_c > 0
        c_cum = st.c_cum + take_c[:, None] * req[None, :]
        c_mask = jnp.where(added[:, None], fit_nt & (k_nt >= take_c[:, None]), st.c_mask)
        c_zone = jnp.where(added[:, None], st.c_zone & gz[None, :], st.c_zone)
        c_ct = jnp.where(added[:, None], st.c_ct & gc[None, :], st.c_ct)
        c_gmask = st.c_gmask.at[:, g].set(st.c_gmask[:, g] | added)
        c_cm = st.c_cm + take_c[:, None] * member_g[None, :].astype(jnp.int32)
        c_co = st.c_co + (added[:, None] & owner_g[None, :] & (q_kind[None, :] == 1)).astype(jnp.int32)

        # ---- 3. new claims, pool by pool in priority order ----------------
        # fresh-node allowance under hostname constraints (counts start at 0)
        fresh_allow = _hostname_allowance(
            jnp.zeros((1, Q), jnp.int32), jnp.zeros((1, Q), jnp.int32),
            q_kind, q_cap, member_g, owner_g,
        )[0]

        def open_pool(p, carry):
            (remaining, used, c_cum, c_mask, c_zone, c_ct, c_gmask, c_pool,
             c_open, p_usage, take_new, c_cm, c_co) = carry

            # per-type pod capacity for a fresh node of pool p
            pz = pool_zone[p] & gz  # [Z]
            pc = pool_ct[p] & gc  # [C]
            off_ok = jnp.any(offer_avail & pz[None, :, None] & pc[None, None, :], axis=(1, 2))  # [T]
            fit_t = compat_t & pool_type[p] & off_ok  # [T]
            daemon = pool_daemon[p]  # [R]
            safe_req = jnp.maximum(req, 1)
            k_t = jnp.where(
                req[None, :] > 0, (type_alloc - daemon[None, :]) // safe_req[None, :], BIG
            )
            k_t = jnp.maximum(jnp.min(k_t, axis=1), 0).astype(jnp.int32)
            k_t = jnp.where(fit_t, k_t, 0)
            kmax = jnp.max(k_t)
            # hostname constraints cap pods-per-fresh-node below the
            # resource capacity (e.g. hostname spread: maxSkew per node)
            full_take = jnp.minimum(kmax, fresh_allow)

            # limit accounting (SPEC: claim blocked if any limited resource
            # usage >= limit at creation; charge = min type charge among the
            # survivors AT CREATION, i.e. after the claim's FIRST pod — the
            # oracle charges right after the opening pod lands)
            one_set = fit_t & (k_t >= 1)
            charge_one = jnp.min(
                jnp.where(one_set[:, None], type_charge, INT32_MAX), axis=0
            )  # [R]
            charge_one = jnp.where(charge_one == INT32_MAX, 0, charge_one)
            headroom = pool_limit[p] - p_usage[p]  # [R] (may be negative)
            # claims before resource r trips: ceil(headroom / charge)
            trips = jnp.where(
                charge_one > 0,
                jnp.maximum(-(-headroom // jnp.maximum(charge_one, 1)), 0),
                BIG,
            )
            already_over = jnp.any(p_usage[p] >= pool_limit[p])
            allow = jnp.where(already_over, 0, jnp.min(trips)).astype(jnp.int32)

            n_want = jnp.where(full_take > 0, -(-remaining // jnp.maximum(full_take, 1)), 0)
            slots_left = M - used
            n_new = jnp.minimum(jnp.minimum(n_want, allow), slots_left).astype(jnp.int32)
            eligible = gpool[p] & (full_take > 0)
            n_new = jnp.where(eligible, n_new, 0)

            idx = jnp.arange(M, dtype=jnp.int32)
            is_new = (idx >= used) & (idx < used + n_new)
            # node j (0-based among new) takes min(full_take, remaining - j*full_take)
            j = idx - used
            take_j = jnp.where(
                is_new, jnp.clip(remaining - j * full_take, 0, full_take), 0
            ).astype(jnp.int32)

            c_cum = jnp.where(is_new[:, None], daemon[None, :] + take_j[:, None] * req[None, :], c_cum)
            new_mask = fit_t[None, :] & (k_t[None, :] >= take_j[:, None])
            c_mask = jnp.where(is_new[:, None], new_mask, c_mask)
            c_zone = jnp.where(is_new[:, None], pz[None, :], c_zone)
            c_ct = jnp.where(is_new[:, None], pc[None, :], c_ct)
            c_gmask = c_gmask.at[:, g].set(c_gmask[:, g] | is_new)
            c_pool = jnp.where(is_new, p, c_pool)
            c_open = c_open | is_new
            c_cm = jnp.where(
                is_new[:, None], take_j[:, None] * member_g[None, :].astype(jnp.int32), c_cm
            )
            c_co = jnp.where(
                is_new[:, None],
                ((take_j[:, None] > 0) & owner_g[None, :] & (q_kind[None, :] == 1)).astype(jnp.int32),
                c_co,
            )

            # charge pool usage: every claim charges its at-creation (1-pod
            # survivor) minimum — n_new claims, charge_one each
            placed_new = jnp.sum(take_j)
            p_usage = p_usage.at[p].add((charge_one * n_new).astype(jnp.int32))

            take_new = take_new + take_j
            remaining = remaining - placed_new
            used = used + n_new
            return (remaining, used, c_cum, c_mask, c_zone, c_ct, c_gmask, c_pool,
                    c_open, p_usage, take_new, c_cm, c_co)

        carry = (
            remaining,
            st.used,
            c_cum,
            c_mask,
            c_zone,
            c_ct,
            c_gmask,
            st.c_pool,
            st.c_open,
            st.p_usage,
            jnp.zeros((M,), jnp.int32),
            c_cm,
            c_co,
        )
        carry = jax.lax.fori_loop(0, P, open_pool, carry)
        (remaining, used, c_cum, c_mask, c_zone, c_ct, c_gmask, c_pool2, c_open,
         p_usage, take_new, c_cm, c_co) = carry

        new_state = FFDState(
            e_cum=e_cum,
            c_cum=c_cum,
            c_mask=c_mask,
            c_zone=c_zone,
            c_ct=c_ct,
            c_gmask=c_gmask,
            c_pool=c_pool2,
            c_open=c_open,
            used=used,
            p_usage=p_usage,
            e_cm=e_cm,
            e_co=e_co,
            c_cm=c_cm,
            c_co=c_co,
        )
        return new_state, (take_e, take_c + take_new, remaining)

    state, (take_e, take_c, leftover) = jax.lax.scan(step, state, (run_group, run_count))
    return FFDOutput(take_e=take_e, take_c=take_c, leftover=leftover, state=state)
