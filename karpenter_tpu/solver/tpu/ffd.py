"""TPU FFD bin-packing kernel.

The tensor re-expression of the reference's `Scheduler.Solve()` hot loop
(SURVEY.md §3.1 HOT LOOP #1; designs/bin-packing.md:17-43). Key idea: FFD
processes pods in sorted order; identical pods form *runs*, and pouring a run
of k identical pods first-fit across open nodes is

    take_n = clamp(k - prefix_sum(cap)_{n-1}, 0, cap_n)

i.e. a vectorized per-node capacity computation + one prefix sum — no
sequential inner loop. Opening new nodes is closed-form: each new node holds
`kmax` pods (the best surviving instance type's capacity), so
`ceil(remaining / kmax)` nodes open at once, with per-pool limit accounting
in closed form as well. The only sequential axis is the run axis (≈ number
of distinct pod specs), walked with `lax.scan`.

Bit-packing: the (zone × capacity-type) offering feasibility of a claim is a
PRODUCT SET (zones ∩ …) × (cts ∩ …), and intersections of product sets
intersect componentwise — so each claim's joint feasibility is one uint32
(`c_zc_bits`, bit z*C+c), each instance type's availability is one uint32,
and the joint "does any offering survive" check is a single [M,T] bitwise
AND. Group-membership state packs the same way into ceil(G/32) words.

Domain topology spread + inter-pod affinity (BASELINE configs 3-4) run
through the **domain event engine**: a `lax.while_loop` entered (per run,
via `lax.cond`) only for groups owning V-axis constraints. The engine is
domain-GENERIC — it sees per-domain column masks over the joint (zone, ct)
bits, per-domain counts, and a node→domain map — so zone-granular AND
capacity-type-granular sigs run on the same kernel (encode picks the axis;
mixed-axis solves fall back). Each event places a closed-form batch of
pods: per-domain consecutive budgets `m2 + maxSkew − cnt` for spread
(SPEC.md skew rule), blocked/present domain sets for (anti-)affinity,
claim domain commitment to `argmin(count, lex)` / `argmax(count, lex)`.
Three closed forms keep events at ≤1 per run on the headline configs:
*water-fill mega* (pure maxSkew-1 self-matching spread lays out entirely —
water-fill the counts from ARBITRARY floors, drain per-domain claim
targets by prefix pour, open fresh claims slot-ordered by (count-at-open,
lex)); *fixed-zone affinity bulk* (post-bootstrap positive affinity drains
every eligible claim in one prefix pour + budgeted multi-open); and
*balanced cycles* (equal counts with targets everywhere batch whole
rotation rounds). Positive HOSTNAME affinity is a Q-axis closed form in
the fast branch (member-gated allowance + a one-target first-fit
bootstrap). Every event places ≥1 pod, bounding the loop by `remaining`.

Per-step work is O((E+M)·T·R) fully-vectorized integer ops — VPU-friendly,
HBM-bandwidth-bound, no data-dependent Python control flow, static shapes
(compile once per (E, M, T, R, P, S, Q, V, W, Z) bucket). Padded scan steps
(run_count == 0) skip their body via `lax.cond`.

Decisions are bit-identical to the reference path by construction: same FFD
order, same first-fit target order (array index = creation order), same
type-survival rule, same pool priority and limit charging, same domain
commit rules; uid assignment within a run follows SPEC.md's canonical order
(solver/SPEC.md "Determinism").
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

INT32_MAX = jnp.int32(2**31 - 1)
BIG = jnp.int32(2**30)

# Kernel diagnostic for event-engine perf work: when set, each zoned run's
# `leftover` output REPORTS THE NUMBER OF WHILE-LOOP EVENTS CONSUMED instead
# of unplaced pods. This corrupts solver results (decode sees phantom
# unplaced pods) and is read at TRACE time, so it bakes into the jit cache —
# never set it in a process that serves real solves. Used by perf probes to
# verify the closed-form batching paths (cycle / water-fill mega / aff bulk)
# are actually firing instead of per-claim trickle events.
import os as _os

# positive allowlist, matching the repo's env-bool convention (options.py)
_DEBUG_EVENTS = _os.environ.get("KTPU_DEBUG_EVENTS", "").lower() in (
    "1", "true", "yes",
)
if _DEBUG_EVENTS:
    import sys as _sys

    print(
        "karpenter_tpu.solver.tpu.ffd: KTPU_DEBUG_EVENTS set — leftover "
        "outputs are EVENT COUNTS, solver results are invalid",
        file=_sys.stderr,
    )

# Positional argument table for ffd_solve. consolidate.py and backend.py
# derive indices from THIS table — never hand-count positions. The batched
# consolidation evaluator (consolidate._batched_ffd) substitutes run_count,
# v_count0, and a device-derived node_compat per subset row; everything else
# broadcasts.
ARG_SPEC = (
    "run_group",
    "run_count",
    "group_req",
    "group_compat_t",
    "group_zc_bits",
    "group_pool",
    "group_pair_nok",
    "group_device",
    "type_alloc",
    "type_charge",
    "offer_zc_bits",
    "pool_type",
    "pool_zc_bits",
    "pool_daemon",
    "pool_limit",
    "pool_usage0",
    "node_free",
    "node_compat",
    "q_member",
    "q_owner",
    "q_kind",
    "q_cap",
    "node_q_member",
    "node_q_owner",
    # zone constraint sigs (V axis; encode.py) — the zone event engine
    "v_member",
    "v_owner",
    "v_kind",
    "v_cap",
    "v_primary",
    "v_aff",
    "v_count0",
    "node_zone",
    "zone_col_mask",
    # mixed-axis support: the domain columns may concatenate TWO axes
    # (zones then capacity types). node_dom2 is each node's second-axis
    # column (-1 on single-axis solves), col_axis labels every column with
    # its axis id, and group_daxis picks which axis a constrained group's
    # event engine runs over (its owned/anti sigs are single-axis by
    # encode's fallback rules; genuinely two-axis pods stay on the oracle).
    "node_dom2",
    "col_axis",
    "group_daxis",
)

ARG_INDEX = {name: i for i, name in enumerate(ARG_SPEC)}


class FFDState(NamedTuple):
    e_cum: jnp.ndarray  # [E, R] int32 — requests placed on existing nodes
    c_cum: jnp.ndarray  # [M, R] int32 — requests on claim slots (incl daemon)
    c_mask: jnp.ndarray  # [M, T] bool — surviving instance types
    c_zc_bits: jnp.ndarray  # [M] uint32 — joint (zone, ct) feasibility bits
    c_gbits: jnp.ndarray  # [M, W] uint32 — groups placed on each claim
    c_pool: jnp.ndarray  # [M] int32 — pool index, -1 if unopened
    used: jnp.ndarray  # scalar int32 — claims opened so far
    p_usage: jnp.ndarray  # [P, R] int32 — pool usage (limit accounting)
    # hostname-constraint counts (Q axis; see encode.py):
    e_cm: jnp.ndarray  # [E, Q] int32 — matching (member) pods per sig
    e_co: jnp.ndarray  # [E, Q] int32 — anti-owner pod presence per sig
    c_cm: jnp.ndarray  # [M, Q] int32
    c_co: jnp.ndarray  # [M, Q] int32
    # zone-constraint counts (V axis):
    v_count: jnp.ndarray  # [V, Z] int32 — matching pods per (sig, zone)
    v_owner_z: jnp.ndarray  # [V, Z] bool — anti owners recorded per zone
    # claim-local affinity state: same claim ⇒ same (eventual) zone, so
    # (anti-)affinity must see co-located pods even on multi-zone claims
    c_vm: jnp.ndarray  # [M, V] int32 — sig-matching pods per claim
    c_vo: jnp.ndarray  # [M, V] bool — claim holds an owner of anti sig v


class FFDOutput(NamedTuple):
    take_e: jnp.ndarray  # [S, E] int32 — pods of run s placed per existing node
    take_c: jnp.ndarray  # [S, M] int32 — pods of run s placed per claim slot
    leftover: jnp.ndarray  # [S] int32 — pods of run s that failed to place
    state: FFDState


class CheckpointRing(NamedTuple):
    """Fixed-size ring of FFDState snapshots taken every `ckpt_every` scan
    steps. `states` holds each FFDState field stacked along a leading
    [n_ckpt] axis; `prefix[slot]` is the 1-based count of scan steps already
    applied when slot was written (-1 = never written). Because the scan
    carry IS the complete decision state, resuming from `states[slot]` over
    `runs[prefix[slot]:]` is decision-identical to a cold solve by
    construction. Slot positions are deterministic (step j·ckpt_every lands
    in slot (j-1) % n_ckpt), so the host never needs to fetch `prefix` —
    it recomputes coverage from (S, ckpt_every, n_ckpt) alone. Padded steps
    (run_count == 0) do not mutate the state, so a checkpoint at position p
    covers min(p, S_real) REAL runs."""

    states: FFDState  # each field: [n_ckpt, ...field shape]
    prefix: jnp.ndarray  # [n_ckpt] int32 — scan steps applied, -1 empty


def _fit_count(alloc, cum, req):
    """[N] per-node count of additional `req` pods fitting: min over R of
    floor((alloc - cum) / req); req==0 axes don't constrain. Clamped >= 0."""
    safe_req = jnp.maximum(req, 1)
    k = jnp.where(req[None, :] > 0, (alloc - cum) // safe_req[None, :], BIG)
    return jnp.maximum(jnp.min(k, axis=1), 0).astype(jnp.int32)


def _fit_count_nt(alloc_t, cum_n, req):
    """[N, T]: pods fitting per (node, type). alloc_t [T,R], cum_n [N,R].

    Statically unrolled over R to avoid materializing [N,T,R] — each r-slice
    is a rank-1 broadcast subtract + divide, which XLA fuses."""
    N, R = cum_n.shape
    T = alloc_t.shape[0]
    k = jnp.full((N, T), BIG, jnp.int32)
    for r in range(R):
        kr = jnp.where(
            req[r] > 0,
            (alloc_t[None, :, r] - cum_n[:, r][:, None]) // jnp.maximum(req[r], 1),
            BIG,
        )
        k = jnp.minimum(k, kr.astype(jnp.int32))
    return jnp.maximum(k, 0)


def _pour(cap, remaining):
    """First-fit pour of `remaining` identical pods into nodes with per-node
    capacity `cap` (in index order). Returns (take [N], left scalar)."""
    prefix = jnp.cumsum(cap) - cap  # exclusive prefix
    take = jnp.clip(remaining - prefix, 0, cap).astype(jnp.int32)
    return take, remaining - jnp.sum(take)


def _hostname_allowance(cm, co, q_kind, q_cap, member_g, owner_g):
    """[N] per-node additional-pod allowance for group g under the hostname
    constraint sigs (encode.py Q axis; SPEC.md hostname floor-0 rule):

      TSC (kind 0), owner+member : cap − cm
      TSC (kind 0), owner only   : ∞ while cm+1 ≤ cap, else 0
      anti (kind 1), owner       : 1 if member else ∞ — while cm == 0, else 0
      anti (kind 1), member only : ∞ while no owner pod present, else 0
      affinity (kind 2), owner   : ∞ where matching pods present, else 0
                                   (fresh-claim bootstrap is a claim-COUNT
                                   cap handled by the caller, not a per-node
                                   allowance — see fast())
    """
    kind0 = q_kind[None, :] == 0
    kind2 = q_kind[None, :] == 2
    relevant = owner_g[None, :] | ((q_kind[None, :] == 1) & member_g[None, :])
    tsc_allow = jnp.where(
        member_g[None, :],
        q_cap[None, :] - cm,
        jnp.where(cm + 1 <= q_cap[None, :], BIG, 0),
    )
    anti_owner_allow = jnp.where(
        cm == 0, jnp.where(member_g[None, :], 1, BIG), 0
    )
    anti_member_allow = jnp.where(co == 0, BIG, 0)
    pos_allow = jnp.where(cm > 0, BIG, 0)
    per_q = jnp.where(
        kind0,
        tsc_allow,
        jnp.where(
            kind2,
            pos_allow,
            jnp.where(owner_g[None, :], anti_owner_allow, anti_member_allow),
        ),
    )
    per_q = jnp.where(relevant, per_q, BIG)
    return jnp.maximum(jnp.min(per_q, axis=1), 0).astype(jnp.int32)


def _gbit_word(g, W):
    """[W] uint32 one-hot word for group index g."""
    word = (g >> 5).astype(jnp.int32)
    bit = (g & 31).astype(jnp.uint32)
    return jnp.where(
        jnp.arange(W, dtype=jnp.int32) == word, jnp.uint32(1) << bit, jnp.uint32(0)
    )


# --- post-scan take compaction (on-device decode; SPEC.md "Decode &
# ladder semantics") -------------------------------------------------------
#
# The dense take tables are O(S×E + S×M) but almost entirely zero: every
# nonzero entry accounts for >= 1 placed pod. Compacting them to (run,
# code, count) uint16 triples ON DEVICE shrinks the d2h fetch to O(actual
# placements); backend._pack_outputs_delta splices the result into the
# single packed output buffer and decode_delta rebuilds the codes stream
# bit-identically. Layout constants are pinned by test_arg_spec_drift.py.

DELTA_HEADER_WORDS = 3  # [overflow_flag, entry_count, uniq_meta_count] i32
DELTA_ENTRY_U16 = 2  # (code, count) uint16 per entry word; code = e | E+m

# Mesh-sharded solve (ffd_solve_sharded): the padded run axis Sp is always a
# multiple of this (backend buckets S with mult=floor=16), so any power-of-2
# mesh up to 16 devices divides it into equal contiguous blocks with NO
# resharding padding. encode.mesh_run_blocks relies on it; pinned by
# tests/test_arg_spec_drift.py.
SHARD_BLOCK_MULT = 16

# --- streaming event-batch apply (SPEC.md "Streaming semantics") ------------
#
# The streaming delta-solve subsystem (solver/streaming.py) keeps run_group/
# run_count device-resident across solves and edits them in place: an
# arrival/eviction batch becomes a tiny table of (pos, gid, cnt) triplets
# scattered into the resident run arrays, instead of re-uploading the whole
# [Sp] pair. One int32 row per edited run position; padding rows carry
# pos = -1 and are dropped by the scatter (mode="drop"), so the triplet
# count buckets to a handful of compile variants. Layout pinned by
# tests/test_arg_spec_drift.py.

EVENT_ENTRY_WORDS = 3  # (pos, gid, cnt) int32 per run edit
EVENT_PAD_POS = -1  # padding rows scatter out of range and are dropped


@functools.partial(jax.jit)
def ffd_apply_events(run_group, run_count, events):
    """Scatter an event batch into the resident run tables.

    run_group/run_count are the device-resident [Sp] int32 arrays (ARG_SPEC
    entries 0 and 1); events is [K, EVENT_ENTRY_WORDS] int32 of (pos, gid,
    cnt) edits. Positions outside [0, Sp) — including EVENT_PAD_POS padding
    — are dropped. Returns the edited (run_group, run_count) pair; inputs
    are NOT donated (no jit in this repo donates), so the caller swaps the
    arena's resident buffers for the returned ones."""
    pos = events[:, 0]
    rg = run_group.at[pos].set(events[:, 1], mode="drop")
    rc = run_count.at[pos].set(events[:, 2], mode="drop")
    return rg, rc


def compact_takes(take_e, take_c, cap: int):
    """[Sp,E]/[Sp,M] dense takes -> run-major packed nonzero entries.

    Returns (overflow i32 scalar, n i32 scalar, cnt16 [Sp/2] i32,
    pairs [cap] i32). Entries travel as (code, count) uint16 pairs — one
    int32 word each — in row-major (= run-major) order; the per-run entry
    counts `cnt16` (uint16, also bitcast-packed) let the host rebuild the
    run index with one np.repeat, so the run column never crosses the
    link. `overflow` is set when a take exceeds uint16 range OR more than
    `cap` entries exist — the host re-fetches full width in that (rare)
    case, so correctness never depends on the bounds."""
    Sp = take_e.shape[0]
    K = take_e.shape[1] + take_c.shape[1]
    grid = jnp.concatenate([take_e, take_c], axis=1)  # [Sp, K]
    val = grid.ravel()
    code = jnp.tile(jnp.arange(K, dtype=jnp.int32), Sp)
    mask = val > 0
    cnt_s = jnp.sum((grid > 0).astype(jnp.int32), axis=1)  # [Sp]
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    n = jnp.sum(mask.astype(jnp.int32))
    tgt = jnp.where(mask, pos, cap)  # overflow/padding scatters drop
    ent_c = jnp.zeros((cap,), jnp.int32).at[tgt].set(code, mode="drop")
    ent_v = jnp.zeros((cap,), jnp.int32).at[tgt].set(val, mode="drop")
    overflow = ((n > cap) | (jnp.max(val, initial=0) > 65535)).astype(
        jnp.int32
    )
    pair = jnp.stack([ent_c, ent_v], axis=1)  # [cap, 2]
    pairs = jax.lax.bitcast_convert_type(pair.astype(jnp.uint16), jnp.int32)
    c16 = cnt_s.astype(jnp.uint16).reshape(-1, 2)  # Sp is 16-bucketed: even
    cnt16 = jax.lax.bitcast_convert_type(c16, jnp.int32)
    return overflow, n, cnt16, pairs


def compact_claim_meta(cm_words, c_zc_bits, c_gbits, c_pool, cap_u: int):
    """Dedup the per-claim identity rows (type-mask words ++ zone/ct bits ++
    group bits ++ pool) into a unique-row table + per-claim uint16 ids.

    Hundreds of claims open from a handful of deployment waves, so the
    distinct rows number ~runs, not ~claims — fetching `uniq [cap_u, Wt]`
    plus `mid16` ids replaces the O(M×T) c_mask fetch that dominated the
    dense tail. Returns (overflow_u i32 scalar, n_u i32 scalar,
    uniq [cap_u, Wt] i32, mid16 [M/2] i32)."""
    M = c_pool.shape[0]
    meta = jnp.concatenate(
        [
            cm_words.astype(jnp.uint32),
            c_zc_bits[:, None].astype(jnp.uint32),
            c_gbits.astype(jnp.uint32),
            jax.lax.bitcast_convert_type(c_pool, jnp.uint32)[:, None],
        ],
        axis=1,
    )  # [M, Wt]
    eq = jnp.all(meta[:, None, :] == meta[None, :, :], axis=2)  # [M, M]
    first = jnp.argmax(eq, axis=1)  # first row equal to mine (diag is True)
    is_rep = first == jnp.arange(M, dtype=first.dtype)
    pos = jnp.cumsum(is_rep.astype(jnp.int32)) - 1
    n_u = jnp.sum(is_rep.astype(jnp.int32))
    tgt = jnp.where(is_rep, pos, cap_u)
    uniq = (
        jnp.zeros((cap_u, meta.shape[1]), jnp.uint32)
        .at[tgt]
        .set(meta, mode="drop")
    )
    mid = pos[first]  # [M] in [0, n_u)
    overflow_u = (n_u > cap_u).astype(jnp.int32)
    m16 = mid.astype(jnp.uint16).reshape(-1, 2)  # M is >=128-bucketed: even
    mid16 = jax.lax.bitcast_convert_type(m16, jnp.int32)
    return overflow_u, n_u, jax.lax.bitcast_convert_type(uniq, jnp.int32), mid16


def _ffd_scan(
    # runs
    run_group,  # [S] i32
    run_count,  # [S] i32
    # groups
    group_req,  # [G, R] i32
    group_compat_t,  # [G, T] bool
    group_zc_bits,  # [G] u32 — packed (zone × ct) admission bits
    group_pool,  # [G, P] bool
    group_pair_nok,  # [G, W] u32 — packed ~pairwise-compatibility words
    group_device,  # [G] bool — False => fallback group, skip on device
    # types
    type_alloc,  # [T, R] i32
    type_charge,  # [T, R] i32 — capacity on charge axes, 0 elsewhere
    offer_zc_bits,  # [T] u32 — packed offering availability bits
    # pools
    pool_type,  # [P, T] bool
    pool_zc_bits,  # [P] u32
    pool_daemon,  # [P, R] i32
    pool_limit,  # [P, R] i32
    pool_usage0,  # [P, R] i32
    # existing nodes
    node_free,  # [E, R] i32
    node_compat,  # [G, E] bool
    # hostname constraint sigs (Q axis; encode.py)
    q_member,  # [G, Q] bool
    q_owner,  # [G, Q] bool
    q_kind,  # [Q] i32
    q_cap,  # [Q] i32
    node_q_member,  # [E, Q] i32
    node_q_owner,  # [E, Q] i32
    # zone constraint sigs (V axis; encode.py)
    v_member,  # [G, V] bool
    v_owner,  # [G, V] bool
    v_kind,  # [V] i32
    v_cap,  # [V] i32
    v_primary,  # [G] i32 — owned zone-TSC sig per group (-1 none)
    v_aff,  # [G] i32 — owned positive zone-affinity sig per group (-1 none)
    v_count0,  # [V, Z] i32
    node_zone,  # [E] i32 — zone index per node (-1 unknown)
    zone_col_mask,  # [Z] u32 — joint-bit columns per zone
    node_dom2,  # [E] i32 — second-axis domain column (-1 single-axis)
    col_axis,  # [Z] i32 — axis id per domain column (0 zones, 1 cts)
    group_daxis,  # [G] i32 — domain axis a constrained group's engine runs on
    *,
    max_claims: int,
    emit_takes: bool = True,
    zone_engine: bool = True,
    init_state: FFDState | None = None,
    ckpt_every: int = 0,
    n_ckpt: int = 0,
    run_ladder=None,  # [S, L] i32 — per-run relax rung groups (-1 pad)
    run_q_idx=None,  # [S, Kq] i32 — per-run active Q-sig indices (-1 pad)
    run_v_idx=None,  # [S, Kv] i32 — per-run active V-sig indices (-1 pad)
):
    E, R = node_free.shape
    G, T = group_compat_t.shape
    P = pool_type.shape[0]
    Q = q_kind.shape[0]
    W = group_pair_nok.shape[1]
    V = v_kind.shape[0]
    Z = zone_col_mask.shape[0]
    M = max_claims
    zidx = jnp.arange(Z, dtype=jnp.int32)
    eidx = jnp.arange(E, dtype=jnp.int32)
    midx = jnp.arange(M, dtype=jnp.int32)

    state0 = FFDState(
        e_cum=jnp.zeros((E, R), jnp.int32),
        c_cum=jnp.zeros((M, R), jnp.int32),
        c_mask=jnp.zeros((M, T), bool),
        c_zc_bits=jnp.zeros((M,), jnp.uint32),
        c_gbits=jnp.zeros((M, W), jnp.uint32),
        c_pool=jnp.full((M,), -1, jnp.int32),
        used=jnp.int32(0),
        p_usage=pool_usage0.astype(jnp.int32),
        e_cm=node_q_member.astype(jnp.int32),
        e_co=node_q_owner.astype(jnp.int32),
        c_cm=jnp.zeros((M, Q), jnp.int32),
        c_co=jnp.zeros((M, Q), jnp.int32),
        v_count=v_count0.astype(jnp.int32),
        v_owner_z=jnp.zeros((V, Z), bool),
        c_vm=jnp.zeros((M, V), jnp.int32),
        c_vo=jnp.zeros((M, V), bool),
    )
    # a resume replays the suffix against the donor's final carry; cold
    # solves start from the zero/input-derived state above
    state = state0 if init_state is None else init_state

    # a node marks its column on EVERY axis (its zone and, under mixed-axis
    # solves, its capacity type) — matching the oracle, which records every
    # determined topology key of a placement target
    e_zone_1h = (node_zone[:, None] == zidx[None, :]) | (
        node_dom2[:, None] == zidx[None, :]
    )  # [E, Z]
    axis_cols = col_axis[None, :] == jnp.arange(2, dtype=jnp.int32)[:, None]  # [2, Z]

    def zone_sets(bits):
        """[...] u32 joint bits -> [..., Z] bool zone marginals."""
        return (bits[..., None] & zone_col_mask) != 0

    def step_body(st: FFDState, g, count, q_row=None, v_row=None):
        req = group_req[g]  # [R]
        compat_t = group_compat_t[g]  # [T]
        g_zc = group_zc_bits[g]  # u32
        gpool = group_pool[g]  # [P]
        g_nok = group_pair_nok[g]  # [W]
        member_g = q_member[g]  # [Q]
        owner_g = q_owner[g]  # [Q]
        member_v = v_member[g]  # [V]
        owner_v = v_owner[g]  # [V]
        gword = _gbit_word(g, W)  # [W]
        on_device = group_device[g]
        remaining0 = jnp.where(on_device, count, 0).astype(jnp.int32)

        # --- compacted constraint view (sparse V/Q-axis evaluation) ------
        # With run-major index tables present, the fast branch evaluates
        # hostname (Q) and zone-sig (V) state over ONLY the run's active
        # columns. The gathered member/owner flags mask -1 padding to
        # False, and a non-member/non-owner column contributes exactly the
        # neutral element everywhere (BIG to allowance mins, 0 to count
        # updates) — which is why any SUPERSET gather list is bit-identical
        # to the dense full-width evaluation. Scatter indices map padding
        # out of range so mode="drop" discards it.
        sparse = q_row is not None
        if sparse:
            qvalid = q_row >= 0  # [Kq]
            qi = jnp.where(qvalid, q_row, 0)
            qsc = jnp.where(qvalid, q_row, Q)  # pad -> OOB, dropped
            m_g = member_g[qi] & qvalid
            o_g = owner_g[qi] & qvalid
            kq = q_kind[qi]
            cq = q_cap[qi]
            vvalid = v_row >= 0  # [Kv]
            vi = jnp.where(vvalid, v_row, 0)
            vsc = jnp.where(vvalid, v_row, V)
            m_v = member_v[vi] & vvalid
            o_v = owner_v[vi] & vvalid
            vk = v_kind[vi]
            Qw = q_row.shape[0]
        else:
            m_g, o_g, kq, cq = member_g, owner_g, q_kind, q_cap
            m_v, o_v, vk = member_v, owner_v, v_kind
            Qw = Q

        def q_cols(a):
            """[X, Q] counters -> the run's active columns [X, Kq]."""
            return jnp.take(a, qi, axis=1) if sparse else a

        def q_add(a, vals):
            """Add gathered-width count deltas back into [X, Q] state."""
            return a.at[:, qsc].add(vals, mode="drop") if sparse else a + vals

        def q_open(a, vals, is_new):
            """Claim-open rows: dense REPLACES the (known-zero) row, the
            sparse form scatter-adds onto it — identical on int zeros."""
            if sparse:
                return a.at[:, qsc].add(
                    jnp.where(is_new[:, None], vals, 0), mode="drop"
                )
            return jnp.where(is_new[:, None], vals, a)

        def v_add(a, vals):
            return a.at[:, vsc].add(vals, mode="drop") if sparse else a + vals

        def v_open(a, vals, is_new):
            if sparse:
                return a.at[:, vsc].add(
                    jnp.where(is_new[:, None], vals, 0), mode="drop"
                )
            return jnp.where(is_new[:, None], vals, a)

        # fresh-node allowance under hostname constraints (counts start at
        # 0). Kind-2 (positive hostname affinity) is EXCLUDED here — at
        # cm=0 it would zero every fresh claim, but its real semantics is a
        # claim-COUNT budget: ONE bootstrap claim when no members exist
        # anywhere (the group co-locates on it, self-satisfying the term),
        # zero otherwise (a fresh claim can never already hold members).
        fresh_allow = _hostname_allowance(
            jnp.zeros((1, Qw), jnp.int32),
            jnp.zeros((1, Qw), jnp.int32),
            kq,
            cq,
            m_g,
            o_g & (kq != 2),
        )[0]
        owned2 = o_g & (kq == 2)  # [Kq]
        tot_m_q = jnp.sum(q_cols(st.e_cm), axis=0) + jnp.sum(
            q_cols(st.c_cm), axis=0
        )  # [Kq]
        boot_ok = jnp.all(~owned2 | (m_g & (tot_m_q == 0)))

        def count_contrib(take_e, take_c, c_zc_after):
            """[Z] recorded-pod count deltas: node domains + claims whose
            domain is determined PER AXIS (a claim multi-valued on an axis
            records no count on that axis — SPEC.md / the oracle's
            domains.get(key) is None rule)."""
            contrib = jnp.sum(take_e[:, None] * e_zone_1h, axis=0)  # [Z]
            cz = zone_sets(c_zc_after)  # [M, Z]
            rec = jnp.zeros_like(cz)
            for a in range(2):
                axm = axis_cols[a]  # [Z]
                single_a = jnp.sum(cz & axm[None, :], axis=1) == 1
                rec = rec | (cz & axm[None, :] & single_a[:, None])
            contrib = contrib + jnp.sum(take_c[:, None] * rec, axis=0)
            return contrib.astype(jnp.int32)

        # =================================================================
        # FAST branch: group owns no zone constraint — run-granular pours
        # =================================================================
        def fast(st: FFDState):
            remaining = remaining0
            # kind-2 bootstrap (positive hostname affinity, no members
            # anywhere yet): the first pod lands FIRST-FIT anywhere — first
            # eligible node, else first open claim, else one fresh claim —
            # and the rest of the group follows it (members now exist only
            # there). Under bootstrap the kind-2 allowance is ignored and the
            # pour is restricted to that single target.
            boot2 = jnp.any(owned2) & boot_ok

            # ---- 1. existing nodes ----------------------------------------
            e_base = _fit_count(node_free, st.e_cum, req)
            e_base = jnp.where(node_compat[g], e_base, 0)
            owner_nb = o_g & (kq != 2)
            e_cm_k = q_cols(st.e_cm)
            e_allow_nb = _hostname_allowance(
                e_cm_k, q_cols(st.e_co), kq, cq, m_g, owner_nb
            )
            # kind-2 component derived from the SAME counts (owner_g =
            # owner_nb | owned2), so the allowance kernel runs once per axis
            e_pos = jnp.min(
                jnp.where(
                    owned2[None, :], jnp.where(e_cm_k > 0, BIG, 0), BIG
                ),
                axis=1,
            ).astype(jnp.int32)
            e_cap_full = jnp.minimum(e_base, jnp.minimum(e_allow_nb, e_pos))
            e_cap_boot = jnp.minimum(e_base, e_allow_nb)
            has_e_boot = jnp.any(e_cap_boot > 0)
            e_first = jnp.argmax(e_cap_boot > 0)
            e_cap = jnp.where(
                boot2,
                jnp.where(eidx == e_first, e_cap_boot, 0),
                e_cap_full,
            )
            take_e, remaining = _pour(e_cap, remaining)
            e_cum = st.e_cum + take_e[:, None] * req[None, :]
            e_cm = q_add(
                st.e_cm, take_e[:, None] * m_g[None, :].astype(jnp.int32)
            )
            e_co = q_add(
                st.e_co,
                (
                    (take_e[:, None] > 0) & o_g[None, :] & (kq[None, :] == 1)
                ).astype(jnp.int32),
            )

            # ---- 2. open claims -------------------------------------------
            A_bits = offer_zc_bits & g_zc  # [T] u32
            ok_off = (st.c_zc_bits[:, None] & A_bits[None, :]) != 0  # [M, T]
            pair_ok = ~jnp.any((st.c_gbits & g_nok[None, :]) != 0, axis=1)  # [M]
            is_open = st.c_pool >= 0
            pool_ok = jnp.where(is_open, gpool[jnp.clip(st.c_pool, 0, P - 1)], False)

            k_nt = _fit_count_nt(type_alloc, st.c_cum, req)  # [M, T]
            fit_nt = st.c_mask & compat_t[None, :] & ok_off  # [M, T]
            node_ok = is_open & pair_ok & pool_ok  # [M]
            k_nt = jnp.where(fit_nt & node_ok[:, None], k_nt, 0)
            c_base = jnp.max(k_nt, axis=1)  # [M]
            c_cm_k = q_cols(st.c_cm)
            c_allow_nb = _hostname_allowance(
                c_cm_k, q_cols(st.c_co), kq, cq, m_g, owner_nb
            )
            c_pos = jnp.min(
                jnp.where(
                    owned2[None, :], jnp.where(c_cm_k > 0, BIG, 0), BIG
                ),
                axis=1,
            ).astype(jnp.int32)
            c_cap_full = jnp.minimum(c_base, jnp.minimum(c_allow_nb, c_pos))
            c_cap_boot = jnp.minimum(c_base, c_allow_nb)
            has_c_boot = jnp.any(c_cap_boot > 0)
            c_first = jnp.argmax(c_cap_boot > 0)
            c_cap = jnp.where(
                boot2,
                jnp.where(
                    has_e_boot, 0, jnp.where(midx == c_first, c_cap_boot, 0)
                ),
                c_cap_full,
            )
            take_c, remaining = _pour(c_cap, remaining)

            added = take_c > 0
            c_cum = st.c_cum + take_c[:, None] * req[None, :]
            c_mask = jnp.where(
                added[:, None], fit_nt & (k_nt >= take_c[:, None]), st.c_mask
            )
            c_zc_bits = jnp.where(added, st.c_zc_bits & g_zc, st.c_zc_bits)
            c_gbits = st.c_gbits | jnp.where(
                added[:, None], gword[None, :], jnp.uint32(0)
            )
            c_cm = q_add(
                st.c_cm, take_c[:, None] * m_g[None, :].astype(jnp.int32)
            )
            c_co = q_add(
                st.c_co,
                (
                    added[:, None] & o_g[None, :] & (kq[None, :] == 1)
                ).astype(jnp.int32),
            )
            c_vm = v_add(
                st.c_vm, take_c[:, None] * m_v[None, :].astype(jnp.int32)
            )

            # ---- 3. new claims, pool by pool in priority order ------------
            def open_pool(p, carry):
                (remaining, used, c_cum, c_mask, c_zc_bits, c_gbits, c_pool,
                 p_usage, take_new, c_cm, c_co, c_vm, cap2) = carry

                new_bits = pool_zc_bits[p] & g_zc  # u32
                off_ok = (offer_zc_bits & new_bits) != 0  # [T]
                fit_t = compat_t & pool_type[p] & off_ok  # [T]
                daemon = pool_daemon[p]  # [R]
                safe_req = jnp.maximum(req, 1)
                k_t = jnp.where(
                    req[None, :] > 0,
                    (type_alloc - daemon[None, :]) // safe_req[None, :],
                    BIG,
                )
                k_t = jnp.maximum(jnp.min(k_t, axis=1), 0).astype(jnp.int32)
                k_t = jnp.where(fit_t, k_t, 0)
                kmax = jnp.max(k_t)
                full_take = jnp.minimum(kmax, fresh_allow)

                one_set = fit_t & (k_t >= 1)
                charge_one = jnp.min(
                    jnp.where(one_set[:, None], type_charge, INT32_MAX), axis=0
                )  # [R]
                charge_one = jnp.where(charge_one == INT32_MAX, 0, charge_one)
                headroom = pool_limit[p] - p_usage[p]  # [R]
                trips = jnp.where(
                    charge_one > 0,
                    jnp.maximum(-(-headroom // jnp.maximum(charge_one, 1)), 0),
                    BIG,
                )
                already_over = jnp.any(p_usage[p] >= pool_limit[p])
                allow = jnp.where(already_over, 0, jnp.min(trips)).astype(jnp.int32)

                n_want = jnp.where(
                    full_take > 0, -(-remaining // jnp.maximum(full_take, 1)), 0
                )
                slots_left = M - used
                n_new = jnp.minimum(jnp.minimum(n_want, allow), slots_left).astype(
                    jnp.int32
                )
                # kind-2 bootstrap budget: at most cap2 new claims across
                # ALL pools this run (1 when bootstrapping, 0 once members
                # exist anywhere, BIG without kind-2 terms)
                n_new = jnp.minimum(n_new, cap2)
                eligible = gpool[p] & (full_take > 0)
                n_new = jnp.where(eligible, n_new, 0)

                def apply(ops):
                    (c_cum, c_mask, c_zc_bits, c_gbits, c_pool, p_usage, take_new,
                     c_cm, c_co, c_vm) = ops
                    is_new = (midx >= used) & (midx < used + n_new)
                    j = midx - used
                    take_j = jnp.where(
                        is_new, jnp.clip(remaining - j * full_take, 0, full_take), 0
                    ).astype(jnp.int32)

                    c_cum = jnp.where(
                        is_new[:, None],
                        daemon[None, :] + take_j[:, None] * req[None, :],
                        c_cum,
                    )
                    new_mask = fit_t[None, :] & (k_t[None, :] >= take_j[:, None])
                    c_mask = jnp.where(is_new[:, None], new_mask, c_mask)
                    c_zc_bits = jnp.where(is_new, new_bits, c_zc_bits)
                    c_gbits = jnp.where(is_new[:, None], gword[None, :], c_gbits)
                    c_pool = jnp.where(is_new, p, c_pool)
                    c_cm = q_open(
                        c_cm,
                        take_j[:, None] * m_g[None, :].astype(jnp.int32),
                        is_new,
                    )
                    c_co = q_open(
                        c_co,
                        (
                            (take_j[:, None] > 0)
                            & o_g[None, :]
                            & (kq[None, :] == 1)
                        ).astype(jnp.int32),
                        is_new,
                    )
                    c_vm = v_open(
                        c_vm,
                        take_j[:, None] * m_v[None, :].astype(jnp.int32),
                        is_new,
                    )
                    p_usage = p_usage.at[p].add((charge_one * n_new).astype(jnp.int32))
                    take_new = take_new + take_j
                    return (c_cum, c_mask, c_zc_bits, c_gbits, c_pool, p_usage,
                            take_new, c_cm, c_co, c_vm, jnp.sum(take_j))

                def skip(ops):
                    return ops + (jnp.int32(0),)

                (c_cum, c_mask, c_zc_bits, c_gbits, c_pool, p_usage, take_new, c_cm,
                 c_co, c_vm, placed_new) = jax.lax.cond(
                    n_new > 0,
                    apply,
                    skip,
                    (c_cum, c_mask, c_zc_bits, c_gbits, c_pool, p_usage, take_new,
                     c_cm, c_co, c_vm),
                )
                remaining = remaining - placed_new
                used = used + n_new
                cap2b = cap2 - n_new
                return (remaining, used, c_cum, c_mask, c_zc_bits, c_gbits, c_pool,
                        p_usage, take_new, c_cm, c_co, c_vm, cap2b)

            # kind-2 new-claim budget: ONE fresh bootstrap claim, and only
            # when no eligible node/claim target existed (first-fit order);
            # zero once members exist anywhere; unbounded without kind-2
            new_claim_cap0 = jnp.where(
                jnp.any(owned2),
                jnp.where(boot2 & ~has_e_boot & ~has_c_boot, 1, 0),
                BIG,
            ).astype(jnp.int32)
            carry = (
                remaining, st.used, c_cum, c_mask, c_zc_bits, c_gbits, st.c_pool,
                st.p_usage, jnp.zeros((M,), jnp.int32), c_cm, c_co, c_vm,
                new_claim_cap0,
            )
            carry = jax.lax.fori_loop(0, P, open_pool, carry)
            (remaining, used, c_cum, c_mask, c_zc_bits, c_gbits, c_pool2, p_usage,
             take_new, c_cm, c_co, c_vm, _cap2) = carry

            take_c_total = take_c + take_new
            # zone-sig membership counts (this group may match other pods'
            # selectors even without owning a constraint)
            contrib = count_contrib(take_e, take_c_total, c_zc_bits)
            if sparse:
                v_count = st.v_count.at[vsc, :].add(
                    m_v.astype(jnp.int32)[:, None] * contrib[None, :],
                    mode="drop",
                )
            else:
                v_count = st.v_count + (
                    m_v.astype(jnp.int32)[:, None] * contrib[None, :]
                )

            new_state = FFDState(
                e_cum=e_cum, c_cum=c_cum, c_mask=c_mask, c_zc_bits=c_zc_bits,
                c_gbits=c_gbits, c_pool=c_pool2, used=used, p_usage=p_usage,
                e_cm=e_cm, e_co=e_co, c_cm=c_cm, c_co=c_co,
                v_count=v_count, v_owner_z=st.v_owner_z,
                c_vm=c_vm, c_vo=st.c_vo,
            )
            return new_state, (take_e, take_c_total, remaining)

        # =================================================================
        # ZONE branch: the event engine (SPEC.md topology/affinity rules)
        # =================================================================
        def zoned(st: FFDState):
            # the group's event engine runs over ONE axis's columns; its
            # admission marginals and node domains restrict to that axis
            # (encode guarantees owned/anti sigs of a device group are
            # single-axis — two-axis pods are fallback groups)
            g_ax = group_daxis[g]
            gax_cols = col_axis == g_ax  # [Z]
            nd = jnp.where(g_ax == 0, node_zone, node_dom2)  # [E]
            gz_zones = zone_sets(g_zc[None])[0] & gax_cols  # [Z] group's own zone admission
            psig_g = v_primary[g]
            has_tsc = psig_g >= 0
            psig = jnp.clip(psig_g, 0, V - 1)
            cap_p = v_cap[psig]
            # self-matching spread: the group's own pods count toward its TSC
            # selector, so pours advance the rotation — the closed forms
            # below assume this (owner-not-member spreads stay eventful)
            is_self = member_v[psig]
            asig_g = v_aff[g]
            has_affs = asig_g >= 0
            asig = jnp.clip(asig_g, 0, V - 1)
            owned_anti = owner_v & (v_kind == 1)  # [V] — registering antis
            # kind 3 = admission-only anti (relax-materialized weighted
            # anti): blocks AND commits for the owning pod exactly like
            # kind 1, but never REGISTERS (no v_owner_z / c_vo writes) —
            # the oracle records only original required terms, so satisfied
            # preferences cannot block future members
            owned_blk = owner_v & ((v_kind == 1) | (v_kind == 3))  # [V]
            member_anti = member_v & (v_kind == 1)
            self_anti = jnp.any(owned_blk & member_v)
            is_member_a = member_v[asig]
            has_owned = jnp.any(owner_v)

            def cond(carry):
                (remaining, progress, fuel) = carry[0], carry[1], carry[2]
                return (remaining > 0) & progress & (fuel > 0)

            def body(carry):
                (remaining, _progress, fuel, take_e_acc, take_c_acc, e_cum, c_cum,
                 c_mask, c_zc_bits, c_gbits, c_pool, used, p_usage, e_cm, e_co,
                 c_cm, c_co, v_count, v_owner_z, c_vm_st, c_vo_st) = carry

                # ---- allowed zones A and per-zone budgets B ----------------
                elig = gz_zones
                cnt_p = v_count[psig]  # [Z]
                cm_ = jnp.where(elig, cnt_p, BIG)
                m1 = jnp.min(cm_)
                amin = jnp.argmin(cm_)
                nmin = jnp.sum(cm_ == m1)
                second = jnp.min(jnp.where(zidx == amin, BIG, cm_))
                m2 = jnp.where((nmin == 1) & (zidx == amin), second, m1)  # [Z]
                allowed_tsc = elig & (cnt_p + 1 - m1 <= cap_p)
                budget_tsc = jnp.clip(m2 + cap_p - cnt_p, 0, BIG)
                A = jnp.where(has_tsc, allowed_tsc, elig)
                B = jnp.where(has_tsc, budget_tsc, BIG)

                blocked_m = jnp.any(owned_blk[:, None] & (v_count > 0), axis=0)
                blocked_o = jnp.any(member_anti[:, None] & v_owner_z, axis=0)
                A = A & ~blocked_m & ~blocked_o
                B = jnp.where(self_anti, jnp.minimum(B, 1), B)

                cnt_a = v_count[asig]  # [Z]
                present = cnt_a > 0
                any_present = jnp.any(present)
                A_base = A  # TSC + anti-zone exclusions, pre-affinity
                A = jnp.where(
                    has_affs,
                    jnp.where(
                        any_present, A & present, jnp.where(is_member_a, A, False)
                    ),
                    A,
                )
                bootstrap = has_affs & ~any_present
                B = jnp.where(bootstrap, jnp.minimum(B, 1), B)

                # ---- existing-node candidate ------------------------------
                e_fit = _fit_count(node_free, e_cum, req)
                e_host = _hostname_allowance(e_cm, e_co, q_kind, q_cap, member_g, owner_g)
                nz_ok = jnp.where(
                    nd >= 0, A[jnp.clip(nd, 0, Z - 1)], ~has_owned
                )
                elig_e_base = node_compat[g] & (e_fit > 0) & (e_host > 0)
                elig_e = elig_e_base & nz_ok
                found_e = jnp.any(elig_e)
                e_star = jnp.argmax(elig_e)
                z_e = nd[e_star]

                # ---- open-claim candidates --------------------------------
                # claim-local affinity: a co-located matching pod satisfies a
                # positive term (and blocks anti terms) regardless of the
                # claim's still-multi-valued zone — same claim, same domain
                local_aff = has_affs & (c_vm_st[:, asig] > 0)  # [M]
                # owner side uses owned_blk (kind 3 blocks and commits like
                # a required anti); ONLY the registration writes (v_owner_z /
                # c_vo) stay kind-1, so satisfied weighted antis never block
                # FUTURE members — the oracle records only original terms
                anti_claim_ok = jnp.all(
                    ~owned_blk[None, :] | (c_vm_st == 0), axis=1
                ) & jnp.all(~member_anti[None, :] | ~c_vo_st, axis=1)  # [M]

                cz = zone_sets(c_zc_bits)  # [M, Z]
                zcount_m = jnp.sum(cz & gax_cols[None, :], axis=1)
                A_m = jnp.where(local_aff[:, None], A_base[None, :], A[None, :])
                inter = cz & A_m  # [M, Z]
                has_inter = jnp.any(inter, axis=1)
                # an owned anti term commits the claim to one zone too —
                # multi-valued claims could later materialize in the same
                # zone and violate the term (SPEC.md anti commit, lex-first)
                has_anti = jnp.any(owned_blk)
                commit_m = has_tsc | (has_affs & any_present & ~local_aff) | has_anti
                score_tsc = jnp.where(inter, cnt_p[None, :] * 64 + zidx[None, :], BIG)
                score_aff = jnp.where(inter, -cnt_a[None, :] * 64 + zidx[None, :], BIG)
                score_lex = jnp.where(inter, zidx[None, :], BIG)
                d_m = jnp.where(
                    has_tsc,
                    jnp.argmin(score_tsc, axis=1),
                    jnp.where(
                        has_affs & any_present & ~local_aff,
                        jnp.argmin(score_aff, axis=1),
                        jnp.argmin(score_lex, axis=1),
                    ),
                ).astype(jnp.int32)  # [M]
                azmask = jnp.sum(
                    jnp.where(inter, zone_col_mask[None, :], jnp.uint32(0)),
                    axis=1,
                    dtype=jnp.uint32,
                )  # [M] — OR of disjoint bit columns
                bits_eff = (
                    jnp.where(commit_m, zone_col_mask[d_m], azmask)
                    & c_zc_bits
                    & g_zc
                )  # [M]

                ok_off = (bits_eff[:, None] & offer_zc_bits[None, :]) != 0  # [M, T]
                pair_ok = ~jnp.any((c_gbits & g_nok[None, :]) != 0, axis=1)
                is_open = c_pool >= 0
                pool_ok = jnp.where(is_open, gpool[jnp.clip(c_pool, 0, P - 1)], False)
                k_raw = _fit_count_nt(type_alloc, c_cum, req)  # [M, T]
                fit_nt = c_mask & compat_t[None, :] & ok_off
                node_ok = (
                    is_open & pair_ok & pool_ok & has_inter & (bits_eff != 0)
                    & anti_claim_ok
                )
                k_nt = jnp.where(fit_nt & node_ok[:, None], k_raw, 0)
                k_m = jnp.max(k_nt, axis=1)  # [M]
                c_host = _hostname_allowance(c_cm, c_co, q_kind, q_cap, member_g, owner_g)
                elig_m = (k_m > 0) & (c_host > 0)
                found_c = jnp.any(elig_m)
                m_star = jnp.argmax(elig_m)
                fin_z = zone_sets(bits_eff[m_star][None])[0] & gax_cols  # [Z]
                nz_fin = jnp.sum(fin_z)
                z_c = jnp.argmax(fin_z).astype(jnp.int32)

                # ---- first-fit preemption bound ---------------------------
                # Pouring into the unique min-count zone raises the floor,
                # which can re-ADMIT a blocked zone; if that zone's first
                # eligible target precedes the current one, the sequential
                # scheduler switches targets there — budgets must stop at
                # that point (SPEC.md first-fit order).
                # per-zone first eligible target position (nodes 0..E-1,
                # then claims E..E+M-1; new claims = +inf):
                pos_node = jnp.min(
                    jnp.where(
                        elig_e_base[:, None] & e_zone_1h, eidx[:, None], BIG
                    ),
                    axis=0,
                )  # [Z]
                bits_z = c_zc_bits[:, None] & zone_col_mask[None, :] & g_zc  # [M, Z]
                off_zt = (bits_z[:, :, None] & offer_zc_bits[None, None, :]) != 0
                fit_base = c_mask & compat_t[None, :] & (k_raw >= 1)  # [M, T]
                elig_m_z = jnp.any(off_zt & fit_base[:, None, :], axis=2)  # [M, Z]
                elig_m_z = elig_m_z & (
                    is_open & pair_ok & pool_ok & (c_host > 0) & anti_claim_ok
                )[:, None]
                pos_claim = jnp.min(
                    jnp.where(elig_m_z, E + midx[:, None], BIG), axis=0
                )  # [Z]
                pos_z = jnp.minimum(pos_node, pos_claim)

                def preempt_bound(zt, pos_t):
                    """Max consecutive pods into zone zt before a blocked
                    zone with an earlier target re-enters the allowed set."""
                    uniq = (nmin == 1) & (zt == amin)
                    cand = (
                        elig
                        & ~A
                        & ~blocked_m
                        & ~blocked_o
                        & (pos_z < pos_t)
                        & ((cnt_p + 1 - cap_p) <= second)
                    )
                    j = cnt_p + 1 - cap_p - cnt_p[jnp.clip(zt, 0, Z - 1)]
                    val = jnp.min(jnp.where(cand, j, BIG))
                    return jnp.where(has_tsc & uniq, jnp.maximum(val, 0), BIG)

                Bz_e = jnp.where(
                    z_e >= 0,
                    jnp.minimum(
                        B[jnp.clip(z_e, 0, Z - 1)], preempt_bound(z_e, e_star)
                    ),
                    BIG,
                )
                q_e = jnp.minimum(
                    jnp.minimum(remaining, e_fit[e_star]),
                    jnp.minimum(e_host[e_star], Bz_e),
                )
                Bz_c = jnp.where(
                    nz_fin == 1,
                    jnp.minimum(B[z_c], preempt_bound(z_c, E + m_star)),
                    BIG,
                )
                q_c = jnp.minimum(
                    jnp.minimum(remaining, k_m[m_star]),
                    jnp.minimum(c_host[m_star], Bz_c),
                )
                q_c = jnp.where(self_anti, jnp.minimum(q_c, 1), q_c)

                # ---- new-claim candidates (per pool) ----------------------
                pz_bits = pool_zc_bits & g_zc  # [P]
                pzz = zone_sets(pz_bits)  # [P, Z]
                inter_p = pzz & A[None, :]
                has_inter_p = jnp.any(inter_p, axis=1)
                score_tsc_p = jnp.where(
                    inter_p, cnt_p[None, :] * 64 + zidx[None, :], BIG
                )
                score_aff_p = jnp.where(
                    inter_p, -cnt_a[None, :] * 64 + zidx[None, :], BIG
                )
                score_lex_p = jnp.where(inter_p, zidx[None, :], BIG)
                commit_p = has_tsc | (has_affs & any_present) | has_anti
                d_p = jnp.where(
                    has_tsc,
                    jnp.argmin(score_tsc_p, axis=1),
                    jnp.where(
                        has_affs & any_present,
                        jnp.argmin(score_aff_p, axis=1),
                        jnp.argmin(score_lex_p, axis=1),
                    ),
                ).astype(jnp.int32)
                azmask_p = jnp.sum(
                    jnp.where(inter_p, zone_col_mask[None, :], jnp.uint32(0)),
                    axis=1,
                    dtype=jnp.uint32,
                )
                nbits_p = (
                    jnp.where(commit_p, zone_col_mask[d_p], azmask_p) & pz_bits
                )  # [P]
                off_ok_p = (nbits_p[:, None] & offer_zc_bits[None, :]) != 0  # [P, T]
                fit_tp = compat_t[None, :] & pool_type & off_ok_p
                k_tp = jnp.full((P, T), BIG, jnp.int32)
                for r in range(R):
                    kr = jnp.where(
                        req[r] > 0,
                        (type_alloc[None, :, r] - pool_daemon[:, r][:, None])
                        // jnp.maximum(req[r], 1),
                        BIG,
                    )
                    k_tp = jnp.minimum(k_tp, kr.astype(jnp.int32))
                k_tp = jnp.maximum(k_tp, 0)
                k_tp = jnp.where(fit_tp, k_tp, 0)
                kmax_p = jnp.max(k_tp, axis=1)  # [P]
                one_set_p = fit_tp & (k_tp >= 1)  # [P, T]
                charge_one_p = jnp.min(
                    jnp.where(one_set_p[:, :, None], type_charge[None, :, :], INT32_MAX),
                    axis=1,
                )  # [P, R]
                charge_one_p = jnp.where(charge_one_p == INT32_MAX, 0, charge_one_p)
                already_over_p = jnp.any(p_usage >= pool_limit, axis=1)  # [P]
                elig_p = (
                    gpool
                    & has_inter_p
                    & (kmax_p > 0)
                    & ~already_over_p
                    & (used < M)
                    & (fresh_allow > 0)
                )
                found_p = jnp.any(elig_p)
                p_star = jnp.argmax(elig_p)
                fin_zp = zone_sets(nbits_p[p_star][None])[0] & gax_cols
                nz_fin_p = jnp.sum(fin_zp)
                z_p = jnp.argmax(fin_zp).astype(jnp.int32)
                Bz_p = jnp.where(
                    nz_fin_p == 1,
                    jnp.minimum(B[z_p], preempt_bound(z_p, E + used)),
                    BIG,
                )
                q_p = jnp.minimum(
                    jnp.minimum(remaining, jnp.minimum(kmax_p[p_star], fresh_allow)),
                    Bz_p,
                )
                q_p = jnp.where(self_anti, jnp.minimum(q_p, 1), q_p)

                # ---- (C) fixed-zone bulk drain ----------------------------
                # Positive zone affinity after bootstrap (or anti-free lex
                # commit): the commit zone is the count-argmax, every pour
                # reinforces it, and with every eligible claim committed to
                # that same zone the drain phase is one first-fit prefix
                # pour over claim slots — the budgeted multi-open (A) then
                # funds the remainder in the SAME event. Without this, a
                # late run of small pods trickle-drains the residue of every
                # earlier claim one event at a time (config 4's cost).
                # committed mode: zone members exist; all pours reinforce the
                # count-argmax zone, so it cannot move mid-pour
                aff_committed = (
                    any_present & (nz_fin_p == 1)
                    & jnp.all(~elig_m | ((bits_eff & ~zone_col_mask[z_p]) == 0))
                )
                # zone-free bootstrap mode: no committed members anywhere, a
                # self-matching group satisfies its term claim-locally, and
                # as long as every eligible claim and every fresh open stays
                # MULTI-zone, no pour records a zone count (count_contrib
                # single-zone rule) — any_present stays false throughout, so
                # the whole drain is mode-stable
                ze_cnt = jnp.sum(zone_sets(bits_eff) & gax_cols[None, :], axis=1)  # [M]
                aff_zonefree = (
                    ~any_present & is_member_a
                    & jnp.all(~elig_m | (ze_cnt > 1)) & (nz_fin_p > 1)
                )
                aff_bulk = (
                    has_affs & ~has_tsc & ~self_anti
                    & ~jnp.any(owned_blk) & ~jnp.any(member_anti)
                    & ~found_e & found_c & found_p
                    & (aff_committed | aff_zonefree)
                )
                caps_aff = jnp.where(elig_m, jnp.minimum(k_m, c_host), 0)
                pref_aff = jnp.cumsum(caps_aff) - caps_aff
                aff_drain_m = jnp.where(
                    aff_bulk, jnp.clip(remaining - pref_aff, 0, caps_aff), 0
                ).astype(jnp.int32)

                # ---- balanced-phase cycle batching ------------------------
                # condition: pure single-TSC group, equal counts across
                # eligible zones, no eligible multi-zone claim, and every
                # eligible zone has a fixed target. Then one rotation round
                # places maxSkew pods per zone; batch all full rounds.
                counts_equal = jnp.max(jnp.where(elig, cnt_p, -BIG)) == m1
                multi_claim = jnp.any(elig_m & (zcount_m > 1))
                pure_tsc = (
                    has_tsc
                    & ~self_anti
                    & ~has_affs
                    & ~jnp.any(member_anti)
                    & ~jnp.any(owned_blk)
                )
                # is_self: like the water-fill form, the cycle assumes pours
                # advance the rotation counts — an owner-not-member spread
                # never moves its counts, so the sequential pour fills the
                # lex-first target to capacity instead of rotating
                cyc_ok = (
                    pure_tsc & is_self & counts_equal & ~multi_claim
                    & (found_e | found_c)
                )
                # per-zone first targets (nodes before claims), unrolled on Z
                tgt_cap_list = []
                tgt_has_list = []
                tgt_e_1h = jnp.zeros((E,), bool)
                tgt_c_1h = jnp.zeros((M,), bool)
                for z in range(Z):
                    elig_ez = elig_e & (nd == z)
                    found_ez = jnp.any(elig_ez)
                    e_z = jnp.argmax(elig_ez)
                    cap_ez = jnp.minimum(e_fit[e_z], e_host[e_z])
                    sc_z = elig_m & cz[:, z] & (zcount_m == 1)
                    found_cz = jnp.any(sc_z)
                    m_z = jnp.argmax(sc_z)
                    cap_cz = jnp.minimum(k_m[m_z], c_host[m_z])
                    has_t = found_ez | found_cz
                    cap_z = jnp.where(found_ez, cap_ez, cap_cz)
                    relevant = elig[z]
                    tgt_has_list.append(jnp.where(relevant, has_t, True))
                    tgt_cap_list.append(jnp.where(relevant & has_t, cap_z, BIG))
                    use_node = relevant & found_ez
                    use_claim = relevant & ~found_ez & found_cz
                    tgt_e_1h = tgt_e_1h | (use_node & (eidx == e_z))
                    tgt_c_1h = tgt_c_1h | (use_claim & (midx == m_z))
                tgt_has = jnp.stack(tgt_has_list)  # [Z]
                tgt_cap = jnp.stack(tgt_cap_list)  # [Z]
                cyc_ok = cyc_ok & jnp.all(tgt_has)
                n_zones = jnp.sum(elig).astype(jnp.int32)
                k_sk = jnp.maximum(cap_p, 1)
                rounds = jnp.minimum(
                    jnp.min(tgt_cap // k_sk),
                    remaining // jnp.maximum(k_sk * n_zones, 1),
                ).astype(jnp.int32)
                cyc_ok = cyc_ok & (rounds >= 1) & (n_zones >= 1)
                per_tgt = k_sk * rounds

                # ---- (A) multi-claim opening quantities --------------------
                # Without a TSC the commit zone cannot rotate away between
                # claims: positive affinity reinforces its argmax, anti/lex
                # choices ignore counts, and the allowed set A is invariant
                # to our own pours (self-matching anti is excluded). So the
                # whole budgeted pour opens ALL its claims in ONE event
                # instead of one event per claim (config 4's cost).
                full_p = jnp.minimum(kmax_p[p_star], fresh_allow)
                multi_ok = ~has_tsc & ~self_anti
                # under an (C) aff-bulk drain the open stage funds only what
                # the claim drains leave over
                rem_p = remaining - jnp.sum(aff_drain_m)
                q_tot_p = jnp.where(multi_ok, jnp.minimum(rem_p, Bz_p), q_p)
                headroom_p = pool_limit[p_star] - p_usage[p_star]  # [R]
                ch_p = charge_one_p[p_star]
                trips_p = jnp.min(jnp.where(
                    ch_p > 0,
                    jnp.maximum(-(-headroom_p // jnp.maximum(ch_p, 1)), 0),
                    BIG,
                )).astype(jnp.int32)
                n_want_p = jnp.where(
                    full_p > 0, -(-q_tot_p // jnp.maximum(full_p, 1)), 0
                ).astype(jnp.int32)
                n_open_p = jnp.where(
                    multi_ok,
                    jnp.minimum(jnp.minimum(n_want_p, trips_p), M - used),
                    1,
                ).astype(jnp.int32)

                # ---- (B) closed-form water-fill batching -------------------
                # Pure maxSkew-1 self-matching spread (config 3's cost): the
                # sequential pour is a strict (level, lex-zone) rotation —
                # each pod goes to the lex-first minimum-count zone — so with
                # one covering pool, uniform per-zone type capacity, at most
                # ONE single-zone claim target per zone and no node targets,
                # the ENTIRE remaining run lays out in closed form even from
                # UNBALANCED starting counts: water-fill the zone counts
                # (floors = current counts, remainder to the lex-first zones
                # at the water line), drain each zone's claim target first,
                # then open fresh claims; fresh slot order sorts by key
                # (count at open = c_z + drained_z + g*kmax, lex zone), which
                # for balanced counts reduces to the generation-major /
                # lex-zone-minor order of the earlier balanced-only form.
                pz_star = pz_bits[p_star]
                off_zt_star = (
                    (zone_col_mask[:, None] & pz_star) & offer_zc_bits[None, :]
                ) != 0  # [Z, T]
                fit_zt = compat_t[None, :] & pool_type[p_star][None, :] & off_zt_star
                k_cap_t = jnp.full((T,), BIG, jnp.int32)
                for r in range(R):
                    kr = jnp.where(
                        req[r] > 0,
                        (type_alloc[:, r] - pool_daemon[p_star, r])
                        // jnp.maximum(req[r], 1),
                        BIG,
                    )
                    k_cap_t = jnp.minimum(k_cap_t, kr.astype(jnp.int32))
                k_cap_t = jnp.maximum(k_cap_t, 0)
                k_zt = jnp.where(fit_zt, k_cap_t[None, :], 0)  # [Z, T]
                kmax_z = jnp.max(k_zt, axis=1)  # [Z]
                z_first = jnp.argmax(elig)
                kmax0 = kmax_z[z_first]
                kmax_eq = jnp.all(~elig | (kmax_z == kmax0))
                one_zt = fit_zt & (k_zt >= 1)
                charge_zr = jnp.min(
                    jnp.where(one_zt[:, :, None], type_charge[None, :, :], INT32_MAX),
                    axis=1,
                )  # [Z, R]
                charge_zr = jnp.where(charge_zr == INT32_MAX, 0, charge_zr)
                charge0 = charge_zr[z_first]
                charge_eq = jnp.all(~elig[:, None] | (charge_zr == charge0[None, :]))
                covers = jnp.all(~elig | pzz[p_star])
                km0 = jnp.maximum(kmax0, 1)
                trips0 = jnp.min(jnp.where(
                    charge0 > 0,
                    jnp.maximum(
                        -(-(pool_limit[p_star] - p_usage[p_star])
                          // jnp.maximum(charge0, 1)),
                        0,
                    ),
                    BIG,
                )).astype(jnp.int32)
                # per-zone claim targets: ALL eligible single-zone claims
                # drain first-fit in slot order (zone totals are fixed by the
                # water-fill, and within a zone first-fit always fills the
                # lowest eligible slot, so a prefix pour is exact regardless
                # of how the sequential rotation interleaves zones)
                cand_z = elig_m_z & elig[None, :]  # [M, Z]
                k_pz = jnp.max(
                    jnp.where(off_zt & fit_base[:, None, :], k_raw[:, None, :], 0),
                    axis=2,
                )  # [M, Z] per-zone claim space
                caps_mz = jnp.where(
                    cand_z, jnp.minimum(k_pz, c_host[:, None]), 0
                )  # [M, Z]
                no_node = jnp.all(~elig | (pos_node >= BIG))
                tgts_ok = ~jnp.any(cand_z & (zcount_m > 1)[:, None])
                # water-fill: theta = max level with sum(max(0, theta-c)) <=
                # remaining, solved on the sorted counts; remainder pods go
                # one each to the lex-first zones sitting at the water line
                celig = jnp.where(elig, cnt_p, BIG)
                cs = jnp.sort(celig)  # ascending, BIG-padded
                kk = jnp.arange(1, Z + 1, dtype=jnp.int32)
                pref = jnp.cumsum(jnp.where(cs < BIG, cs, 0))
                nz_e = jnp.sum(elig).astype(jnp.int32)
                th_k = (remaining + pref) // kk
                cs_next = jnp.concatenate([cs[1:], jnp.full((1,), BIG, cs.dtype)])
                ok_k = (kk <= nz_e) & (th_k >= cs) & (th_k <= cs_next)
                theta = jnp.max(jnp.where(ok_k, th_k, -BIG))
                sfill = jnp.sum(jnp.where(elig, jnp.clip(theta - celig, 0, BIG), 0))
                r_rem = remaining - sfill
                at_lvl = elig & (celig <= theta)
                lexr = jnp.cumsum(at_lvl.astype(jnp.int32)) - 1
                bonus = at_lvl & (lexr < r_rem)
                T_zv = (
                    jnp.where(elig, jnp.clip(theta - celig, 0, BIG), 0)
                    + bonus.astype(jnp.int32)
                ).astype(jnp.int32)  # per-zone total adds
                pref_mz = jnp.cumsum(caps_mz, axis=0) - caps_mz
                take_mz = jnp.clip(
                    T_zv[None, :] - pref_mz, 0, caps_mz
                ).astype(jnp.int32)  # per-(claim, zone) drains
                tm_z = jnp.sum(take_mz, axis=0)  # [Z] target drains
                fr_z = T_zv - tm_z  # fresh-claim pods
                n_z = -(-fr_z // km0)  # fresh claims per zone [Z]
                n_mega = jnp.sum(n_z).astype(jnp.int32)
                mega_ok = (
                    pure_tsc & is_self & no_node & tgts_ok & found_p
                    # cap == 1 ONLY: with maxSkew >= 2 the per-pod first-fit
                    # re-admits earlier claims mid-rotation (skew headroom),
                    # so pours are not clean rotation chunks; maxSkew=1 is
                    # strict and the closed form is exact
                    & (cap_p == 1)
                    & (kmax0 > 0) & kmax_eq & charge_eq & covers
                    & (fresh_allow >= kmax0)
                    & (n_mega <= M - used) & (trips0 >= n_mega)
                    & (remaining > 0)
                    # theta-solve sanity: the fill must account for every pod
                    & (jnp.sum(T_zv) == remaining)
                )
                # fresh-claim slot order: rank claims (z, g) by key
                # (open level = c_z + tm_z + g*kmax, lex zone) and scatter
                base_z = jnp.where(elig, cnt_p + tm_z, BIG)  # [Z]
                Garr = jnp.arange(M, dtype=jnp.int32)
                K_zg = base_z[:, None] + Garr[None, :] * km0  # [Z, M] keys
                diff = K_zg[:, :, None] - base_z[None, None, :]  # [Z, M, Z]
                below = jnp.clip(-(-diff // km0), 0, n_z[None, None, :])
                tied = (
                    (diff >= 0)
                    & (diff % km0 == 0)
                    & ((diff // km0) < n_z[None, None, :])
                    & (zidx[None, None, :] < zidx[:, None, None])
                )
                rank_zg = (jnp.sum(below, axis=2) + jnp.sum(tied, axis=2)).astype(
                    jnp.int32
                )  # [Z, M]
                valid_zg = (Garr[None, :] < n_z[:, None]) & elig[:, None]
                scat_idx = jnp.where(valid_zg, rank_zg, M)  # OOB rows dropped
                scat_z = (
                    jnp.zeros((M,), jnp.int32)
                    .at[scat_idx.reshape(-1)]
                    .set(
                        jnp.broadcast_to(zidx[:, None], (Z, M)).reshape(-1),
                        mode="drop",
                    )
                )
                take_fr = jnp.clip(
                    fr_z[:, None] - Garr[None, :] * km0, 0, km0
                ).astype(jnp.int32)
                scat_take = (
                    jnp.zeros((M,), jnp.int32)
                    .at[scat_idx.reshape(-1)]
                    .set(take_fr.reshape(-1), mode="drop")
                )
                j_off = midx - used  # [M]
                in_mega = mega_ok & (j_off >= 0) & (j_off < n_mega)
                jc = jnp.clip(j_off, 0, M - 1)
                zsel = scat_z[jc]
                take_mega = jnp.where(in_mega, scat_take[jc], 0).astype(jnp.int32)
                # target-drain quantities land on their existing slots
                # (claims are single-zone under tgts_ok, so the per-zone
                # takes of one claim never overlap)
                drain_m = jnp.where(
                    mega_ok, jnp.sum(take_mz, axis=1), 0
                ).astype(jnp.int32)

                # ---- selection & unified masked apply ---------------------
                # the water-fill mega subsumes the balanced cycle (balanced
                # counts are its special case) and may fire with existing
                # claim targets (found_c) — it takes precedence everywhere
                cyc_eff = cyc_ok & ~mega_ok
                use_e = found_e & ~cyc_eff & ~mega_ok
                use_c = ~found_e & found_c & ~cyc_eff & ~mega_ok & ~aff_bulk
                # aff_bulk keeps the open stage live even though found_c is
                # true: the bulk drain and the multi-open share the event
                use_p = (
                    ~found_e & (~found_c | aff_bulk) & found_p
                    & ~cyc_eff & ~mega_ok
                )

                take_e_add = (
                    jnp.where(use_e & (eidx == e_star), q_e, 0)
                    + jnp.where(cyc_eff & tgt_e_1h, per_tgt, 0)
                ).astype(jnp.int32)
                take_c_add = (
                    jnp.where(use_c & (midx == m_star), q_c, 0)
                    + jnp.where(cyc_eff & tgt_c_1h, per_tgt, 0)
                    + aff_drain_m
                ).astype(jnp.int32)

                # existing-node state
                e_cum = e_cum + take_e_add[:, None] * req[None, :]
                e_cm = e_cm + take_e_add[:, None] * member_g[None, :].astype(jnp.int32)
                e_co = e_co + (
                    (take_e_add[:, None] > 0)
                    & owner_g[None, :]
                    & (q_kind[None, :] == 1)
                ).astype(jnp.int32)

                # open-claim state
                added = take_c_add > 0
                c_cum = c_cum + take_c_add[:, None] * req[None, :]
                c_mask = jnp.where(
                    added[:, None], fit_nt & (k_nt >= take_c_add[:, None]), c_mask
                )
                c_zc_bits = jnp.where(added, bits_eff, c_zc_bits)
                c_gbits = c_gbits | jnp.where(
                    added[:, None], gword[None, :], jnp.uint32(0)
                )
                c_cm = c_cm + take_c_add[:, None] * member_g[None, :].astype(jnp.int32)
                c_co = c_co + (
                    added[:, None] & owner_g[None, :] & (q_kind[None, :] == 1)
                ).astype(jnp.int32)
                c_vm_st = c_vm_st + take_c_add[:, None] * member_v[None, :].astype(
                    jnp.int32
                )
                c_vo_st = c_vo_st | (added[:, None] & owned_anti[None, :])

                # water-fill target drains: pour tm_z into each zone's
                # single-zone claim target (zone bits unchanged — the claim
                # is already committed to that zone; pure_tsc ⇒ no anti
                # registration). k_raw is the event-start fit count, so the
                # capacity narrowing matches a sequential pod-by-pod pour.
                drained = drain_m > 0
                ok_off_all = (c_zc_bits[:, None] & offer_zc_bits[None, :]) != 0
                c_cum = c_cum + drain_m[:, None] * req[None, :]
                c_mask = jnp.where(
                    drained[:, None],
                    c_mask & compat_t[None, :] & ok_off_all
                    & (k_raw >= drain_m[:, None]),
                    c_mask,
                )
                c_gbits = c_gbits | jnp.where(
                    drained[:, None], gword[None, :], jnp.uint32(0)
                )
                c_cm = c_cm + drain_m[:, None] * member_g[None, :].astype(jnp.int32)
                c_co = c_co + (
                    drained[:, None] & owner_g[None, :] & (q_kind[None, :] == 1)
                ).astype(jnp.int32)
                c_vm_st = c_vm_st + drain_m[:, None] * member_v[None, :].astype(
                    jnp.int32
                )

                # new-claim open: n_open_p slots in the committed zone (A)
                is_new = use_p & (j_off >= 0) & (j_off < n_open_p)
                tq = jnp.where(
                    is_new,
                    jnp.where(
                        multi_ok,
                        jnp.clip(q_tot_p - j_off * jnp.maximum(full_p, 1), 0, full_p),
                        q_p,
                    ),
                    0,
                ).astype(jnp.int32)
                c_cum = jnp.where(
                    is_new[:, None],
                    pool_daemon[p_star][None, :] + tq[:, None] * req[None, :],
                    c_cum,
                )
                c_mask = jnp.where(
                    is_new[:, None],
                    fit_tp[p_star][None, :] & (k_tp[p_star][None, :] >= tq[:, None]),
                    c_mask,
                )
                c_zc_bits = jnp.where(is_new, nbits_p[p_star], c_zc_bits)
                c_gbits = jnp.where(is_new[:, None], gword[None, :], c_gbits)
                c_pool = jnp.where(is_new, p_star.astype(jnp.int32), c_pool)
                c_cm = jnp.where(
                    is_new[:, None],
                    tq[:, None] * member_g[None, :].astype(jnp.int32),
                    c_cm,
                )
                c_co = jnp.where(
                    is_new[:, None],
                    (
                        (tq[:, None] > 0) & owner_g[None, :] & (q_kind[None, :] == 1)
                    ).astype(jnp.int32),
                    c_co,
                )
                c_vm_st = jnp.where(
                    is_new[:, None],
                    tq[:, None] * member_v[None, :].astype(jnp.int32),
                    c_vm_st,
                )
                c_vo_st = jnp.where(
                    is_new[:, None], (tq[:, None] > 0) & owned_anti[None, :], c_vo_st
                )
                p_usage = p_usage.at[p_star].add(
                    (charge_one_p[p_star]
                     * jnp.where(use_p, n_open_p, 0)).astype(jnp.int32)
                )
                used = used + jnp.where(use_p, n_open_p, 0)

                # mega-generation open (B): rotating zone per slot
                fit_sel = fit_zt[zsel]  # [M, T]
                k_sel = k_zt[zsel]  # [M, T]
                c_cum = jnp.where(
                    in_mega[:, None],
                    pool_daemon[p_star][None, :] + take_mega[:, None] * req[None, :],
                    c_cum,
                )
                c_mask = jnp.where(
                    in_mega[:, None], fit_sel & (k_sel >= take_mega[:, None]), c_mask
                )
                c_zc_bits = jnp.where(in_mega, zone_col_mask[zsel] & pz_star, c_zc_bits)
                c_gbits = jnp.where(in_mega[:, None], gword[None, :], c_gbits)
                c_pool = jnp.where(in_mega, p_star.astype(jnp.int32), c_pool)
                c_cm = jnp.where(
                    in_mega[:, None],
                    take_mega[:, None] * member_g[None, :].astype(jnp.int32),
                    c_cm,
                )
                c_co = jnp.where(
                    in_mega[:, None],
                    (
                        (take_mega[:, None] > 0)
                        & owner_g[None, :]
                        & (q_kind[None, :] == 1)
                    ).astype(jnp.int32),
                    c_co,
                )
                c_vm_st = jnp.where(
                    in_mega[:, None],
                    take_mega[:, None] * member_v[None, :].astype(jnp.int32),
                    c_vm_st,
                )
                p_usage = p_usage.at[p_star].add(
                    (charge0 * jnp.where(mega_ok, n_mega, 0)).astype(jnp.int32)
                )
                used = used + jnp.where(mega_ok, n_mega, 0)

                # domain-count recording: one unified pass over the POST-
                # update claim bits — open-claim pours, water-fill drains,
                # fresh opens, and mega slots all record exactly where their
                # final bits are single per axis (count_contrib's rule), which
                # reproduces the old z_p/T_zv special cases on the group's
                # axis and additionally records other-axis counts for claims
                # that happen to be determined there (mixed-axis solves)
                contrib = count_contrib(
                    take_e_add, take_c_add + drain_m + tq + take_mega, c_zc_bits
                )
                v_count = v_count + member_v.astype(jnp.int32)[:, None] * contrib[None, :]
                # anti-owner registration keys on the target's recorded zone,
                # member or not (the oracle registers owned terms' domains)
                owner_rec = (
                    (use_e & (z_e >= 0) & (zidx == jnp.clip(z_e, 0, Z - 1)))
                    | (use_c & (nz_fin == 1) & (zidx == z_c))
                    | (use_p & (nz_fin_p == 1) & (zidx == z_p))
                )  # [Z]
                v_owner_z = v_owner_z | (owned_anti[:, None] & owner_rec[None, :])

                placed = (
                    jnp.sum(take_e_add) + jnp.sum(take_c_add) + jnp.sum(tq)
                    + jnp.sum(take_mega) + jnp.sum(drain_m)
                )
                remaining = remaining - placed
                progress = placed > 0
                take_e_acc2 = take_e_acc + take_e_add
                take_c_acc2 = take_c_acc + take_c_add + tq + take_mega + drain_m
                return (remaining, progress, fuel - 1, take_e_acc2, take_c_acc2,
                        e_cum, c_cum, c_mask, c_zc_bits, c_gbits, c_pool, used,
                        p_usage, e_cm, e_co, c_cm, c_co, v_count, v_owner_z,
                        c_vm_st, c_vo_st)

            carry0 = (
                remaining0, jnp.bool_(True), remaining0 + jnp.int32(8),
                jnp.zeros((E,), jnp.int32), jnp.zeros((M,), jnp.int32),
                st.e_cum, st.c_cum, st.c_mask, st.c_zc_bits, st.c_gbits, st.c_pool,
                st.used, st.p_usage, st.e_cm, st.e_co, st.c_cm, st.c_co,
                st.v_count, st.v_owner_z, st.c_vm, st.c_vo,
            )
            out = jax.lax.while_loop(cond, body, carry0)
            (remaining, _progress, _fuel, take_e_acc, take_c_acc, e_cum, c_cum,
             c_mask, c_zc_bits, c_gbits, c_pool, used, p_usage, e_cm, e_co,
             c_cm, c_co, v_count, v_owner_z, c_vm_f, c_vo_f) = out
            if _DEBUG_EVENTS:
                # kernel diagnostic (perf work ONLY — see flag definition):
                # report events consumed instead of unplaced pods
                remaining = (remaining0 + jnp.int32(8)) - _fuel
            new_state = FFDState(
                e_cum=e_cum, c_cum=c_cum, c_mask=c_mask, c_zc_bits=c_zc_bits,
                c_gbits=c_gbits, c_pool=c_pool, used=used, p_usage=p_usage,
                e_cm=e_cm, e_co=e_co, c_cm=c_cm, c_co=c_co,
                v_count=v_count, v_owner_z=v_owner_z, c_vm=c_vm_f, c_vo=c_vo_f,
            )
            return new_state, (take_e_acc, take_c_acc, remaining)

        # zone_engine=False (caller knows V == 0) drops the zoned branch at
        # TRACE time. This matters beyond compile size: under vmap, lax.cond
        # lowers to executing BOTH branches + select, so a batched
        # consolidation row would pay the event engine's while_loop per scan
        # step even with zero zone constraints in the input.
        if not zone_engine:
            return fast(st)
        # the gathered flags cover every sig the group is member/owner of,
        # so the compacted dispatch test matches the dense one exactly
        constrained = jnp.any(o_v) | jnp.any(m_v & (vk == 1))
        return jax.lax.cond(constrained, zoned, fast, st)

    sparse = run_q_idx is not None

    def step(st: FFDState, run):
        if sparse:
            g, count, qr, vr = run
        else:
            (g, count), qr, vr = run, None, None
        # padded runs (count == 0) skip the whole body — bucketed S padding
        # costs ~nothing at runtime
        new_st, (te, tc, lo) = jax.lax.cond(
            count > 0,
            lambda s: step_body(s, g, count, qr, vr),
            lambda s: (
                s,
                (
                    jnp.zeros((E,), jnp.int32),
                    jnp.zeros((M,), jnp.int32),
                    jnp.int32(0),
                ),
            ),
            st,
        )
        # verdict mode (batched consolidation): only leftovers + final state
        # matter; stacking [S, E]/[S, M] takes per batch row would dominate
        # HBM at 10k nodes × thousands of runs × the subset axis
        if emit_takes:
            return new_st, (te, tc, lo)
        return new_st, lo

    S = run_group.shape[0]
    ring = None
    if run_ladder is not None:
        # Relax-ladder scan (solver/SPEC.md "Decode & ladder semantics"):
        # each run carries its pre-materialized rung groups — rung j's group
        # encodes the run's pod spec with its j lowest-weight preferences
        # dropped (relax.py ORIGINAL-order invariant). The cascade replays
        # the host relax loop's per-pod walk in one dispatch: pour the base
        # group for every still-unplaced pod, then ladder ONE pod up the
        # rungs until it places, then return to the base rung — a rung
        # placement can open a claim that un-relaxed twins may join, exactly
        # as the host loop's next redispatch would discover. Failed attempts
        # never mutate the carry, and identical pods fail identically once
        # one exhausts the ladder, so the remaining count is committed as
        # leftover without re-walking each twin.
        assert emit_takes and ckpt_every == 0 and init_state is None, (
            "run_ladder excludes verdict mode, checkpoint harvest, and resume"
        )
        Lw = run_ladder.shape[1]

        def step_ladder(st: FFDState, run):
            if sparse:
                # the run's index rows are the union over base + rung
                # groups (encode.sparse_run_tables ladder mode), so the
                # same gathered view is a correct superset for every rung
                g, count, lrow, qr, vr = run
            else:
                (g, count, lrow), qr, vr = run, None, None

            def cascade(st_in):
                # every iteration either places >= 1 pod (and pods place at
                # most `count` times) or advances the rung counter (which
                # resets only on placement), so the walk is bounded; fuel
                # makes the bound explicit for the while_loop
                fuel0 = (count + jnp.int32(1)) * jnp.int32(Lw + 2) + jnp.int32(4)

                def cond(c):
                    _, lvl, remaining, _, _, fuel = c
                    return (remaining > 0) & (lvl <= Lw) & (fuel > 0)

                def body(c):
                    st_, lvl, remaining, te_a, tc_a, fuel = c
                    is_base = lvl == 0
                    gv = lrow[jnp.clip(lvl - 1, 0, Lw - 1)]
                    valid = is_base | (gv >= 0)
                    g_cur = jnp.where(is_base, g, jnp.clip(gv, 0, G - 1))
                    # base pours the whole remainder (the closed-form pour
                    # already accounts for self-interactions); a rung pours
                    # exactly ONE pod — the host loop relaxes one pod per
                    # iteration, and its twins must retry from the base
                    cnt = jnp.where(is_base, remaining, jnp.int32(1))
                    new_st, (te, tc, lo) = jax.lax.cond(
                        valid,
                        lambda s: step_body(s, g_cur, cnt, qr, vr),
                        lambda s: (
                            s,
                            (
                                jnp.zeros((E,), jnp.int32),
                                jnp.zeros((M,), jnp.int32),
                                cnt,
                            ),
                        ),
                        st_,
                    )
                    placed = cnt - lo
                    nxt = jnp.where(
                        is_base,
                        jnp.int32(1),
                        jnp.where(placed > 0, jnp.int32(0), lvl + 1),
                    )
                    nxt = jnp.where(valid, nxt, jnp.int32(Lw + 1))
                    return (
                        new_st,
                        nxt,
                        remaining - placed,
                        te_a + te,
                        tc_a + tc,
                        fuel - 1,
                    )

                st_f, _, rem_f, te_f, tc_f, _ = jax.lax.while_loop(
                    cond,
                    body,
                    (
                        st_in,
                        jnp.int32(0),
                        count.astype(jnp.int32),
                        jnp.zeros((E,), jnp.int32),
                        jnp.zeros((M,), jnp.int32),
                        fuel0,
                    ),
                )
                return st_f, (te_f, tc_f, rem_f)

            return jax.lax.cond(
                count > 0,
                cascade,
                lambda s: (
                    s,
                    (
                        jnp.zeros((E,), jnp.int32),
                        jnp.zeros((M,), jnp.int32),
                        jnp.int32(0),
                    ),
                ),
                st,
            )

        state, ys = jax.lax.scan(
            step_ladder,
            state,
            (run_group, run_count, run_ladder, run_q_idx, run_v_idx)
            if sparse
            else (run_group, run_count, run_ladder),
        )
        take_e, take_c, leftover = ys
        out = FFDOutput(
            take_e=take_e, take_c=take_c, leftover=leftover, state=state
        )
        return out, None
    if ckpt_every > 0 and n_ckpt > 0:
        # carry a fixed-size snapshot ring through the scan: step pos=i+1
        # writes slot ((pos//K)-1) % n_ckpt when pos % K == 0. The write
        # happens OUTSIDE step's count>0 cond so padded steps still advance
        # the (deterministic) slot schedule — the host recomputes coverage
        # without fetching `prefix`.
        ring0 = jax.tree_util.tree_map(
            lambda a: jnp.zeros((n_ckpt,) + a.shape, a.dtype), state0
        )
        prefix0 = jnp.full((n_ckpt,), -1, jnp.int32)

        def step_ck(carry, run):
            st, ring_st, pref = carry
            if sparse:
                g, count, qr, vr, i = run
                new_st, ys_i = step(st, (g, count, qr, vr))
            else:
                g, count, i = run
                new_st, ys_i = step(st, (g, count))
            pos = i + jnp.int32(1)
            write = (pos % ckpt_every) == 0
            slot = ((pos // ckpt_every) - 1) % n_ckpt
            ring_st = jax.tree_util.tree_map(
                lambda r, s: r.at[slot].set(jnp.where(write, s, r[slot])),
                ring_st, new_st,
            )
            pref = pref.at[slot].set(jnp.where(write, pos, pref[slot]))
            return (new_st, ring_st, pref), ys_i

        (state, ring_states, prefix), ys = jax.lax.scan(
            step_ck,
            (state, ring0, prefix0),
            (run_group, run_count, run_q_idx, run_v_idx,
             jnp.arange(S, dtype=jnp.int32))
            if sparse
            else (run_group, run_count, jnp.arange(S, dtype=jnp.int32)),
        )
        ring = CheckpointRing(states=ring_states, prefix=prefix)
    else:
        state, ys = jax.lax.scan(
            step,
            state,
            (run_group, run_count, run_q_idx, run_v_idx)
            if sparse
            else (run_group, run_count),
        )
    if emit_takes:
        take_e, take_c, leftover = ys
    else:
        take_e = jnp.zeros((0, E), jnp.int32)
        take_c = jnp.zeros((0, M), jnp.int32)
        leftover = ys.reshape(S)
    out = FFDOutput(take_e=take_e, take_c=take_c, leftover=leftover, state=state)
    return out, ring


# --- jitted entry points -------------------------------------------------
#
# All three wrap the SAME traced body (_ffd_scan), so resume is
# decision-identical to a cold solve by construction. The
# `functools.partial(jax.jit)` decorator style keeps `__wrapped__` a plain
# traceable function, which consolidate.py and parallel/sharded.py vmap
# directly and tests/test_arg_spec_drift.py introspects. ffd_solve's
# signature is frozen by ARG_SPEC — the checkpoint/resume statics
# (ckpt_every, n_ckpt) live only on the new entry points.


@functools.partial(
    jax.jit, static_argnames=("max_claims", "emit_takes", "zone_engine")
)
def ffd_solve(
    run_group,
    run_count,
    group_req,
    group_compat_t,
    group_zc_bits,
    group_pool,
    group_pair_nok,
    group_device,
    type_alloc,
    type_charge,
    offer_zc_bits,
    pool_type,
    pool_zc_bits,
    pool_daemon,
    pool_limit,
    pool_usage0,
    node_free,
    node_compat,
    q_member,
    q_owner,
    q_kind,
    q_cap,
    node_q_member,
    node_q_owner,
    v_member,
    v_owner,
    v_kind,
    v_cap,
    v_primary,
    v_aff,
    v_count0,
    node_zone,
    zone_col_mask,
    node_dom2,
    col_axis,
    group_daxis,
    *,
    max_claims: int,
    emit_takes: bool = True,
    zone_engine: bool = True,
) -> FFDOutput:
    out, _ = _ffd_scan(
        run_group,
        run_count,
        group_req,
        group_compat_t,
        group_zc_bits,
        group_pool,
        group_pair_nok,
        group_device,
        type_alloc,
        type_charge,
        offer_zc_bits,
        pool_type,
        pool_zc_bits,
        pool_daemon,
        pool_limit,
        pool_usage0,
        node_free,
        node_compat,
        q_member,
        q_owner,
        q_kind,
        q_cap,
        node_q_member,
        node_q_owner,
        v_member,
        v_owner,
        v_kind,
        v_cap,
        v_primary,
        v_aff,
        v_count0,
        node_zone,
        zone_col_mask,
        node_dom2,
        col_axis,
        group_daxis,
        max_claims=max_claims,
        emit_takes=emit_takes,
        zone_engine=zone_engine,
    )
    return out


@functools.partial(
    jax.jit,
    static_argnames=("max_claims", "emit_takes", "zone_engine",
                     "ckpt_every", "n_ckpt"),
)
def ffd_solve_ckpt(
    run_group,
    run_count,
    group_req,
    group_compat_t,
    group_zc_bits,
    group_pool,
    group_pair_nok,
    group_device,
    type_alloc,
    type_charge,
    offer_zc_bits,
    pool_type,
    pool_zc_bits,
    pool_daemon,
    pool_limit,
    pool_usage0,
    node_free,
    node_compat,
    q_member,
    q_owner,
    q_kind,
    q_cap,
    node_q_member,
    node_q_owner,
    v_member,
    v_owner,
    v_kind,
    v_cap,
    v_primary,
    v_aff,
    v_count0,
    node_zone,
    zone_col_mask,
    node_dom2,
    col_axis,
    group_daxis,
    *,
    max_claims: int,
    emit_takes: bool = True,
    zone_engine: bool = True,
    ckpt_every: int = 16,
    n_ckpt: int = 4,
):
    """Cold solve that also harvests a checkpoint ring (device-resident;
    zero extra transfer unless the caller fetches it)."""
    return _ffd_scan(
        run_group,
        run_count,
        group_req,
        group_compat_t,
        group_zc_bits,
        group_pool,
        group_pair_nok,
        group_device,
        type_alloc,
        type_charge,
        offer_zc_bits,
        pool_type,
        pool_zc_bits,
        pool_daemon,
        pool_limit,
        pool_usage0,
        node_free,
        node_compat,
        q_member,
        q_owner,
        q_kind,
        q_cap,
        node_q_member,
        node_q_owner,
        v_member,
        v_owner,
        v_kind,
        v_cap,
        v_primary,
        v_aff,
        v_count0,
        node_zone,
        zone_col_mask,
        node_dom2,
        col_axis,
        group_daxis,
        max_claims=max_claims,
        emit_takes=emit_takes,
        zone_engine=zone_engine,
        ckpt_every=ckpt_every,
        n_ckpt=n_ckpt,
    )


@functools.partial(
    jax.jit,
    static_argnames=("max_claims", "emit_takes", "zone_engine",
                     "ckpt_every", "n_ckpt"),
)
def ffd_resume(
    init_state,  # FFDState pytree — a checkpoint from a prefix-valid solve
    run_group,
    run_count,
    group_req,
    group_compat_t,
    group_zc_bits,
    group_pool,
    group_pair_nok,
    group_device,
    type_alloc,
    type_charge,
    offer_zc_bits,
    pool_type,
    pool_zc_bits,
    pool_daemon,
    pool_limit,
    pool_usage0,
    node_free,
    node_compat,
    q_member,
    q_owner,
    q_kind,
    q_cap,
    node_q_member,
    node_q_owner,
    v_member,
    v_owner,
    v_kind,
    v_cap,
    v_primary,
    v_aff,
    v_count0,
    node_zone,
    zone_col_mask,
    node_dom2,
    col_axis,
    group_daxis,
    *,
    max_claims: int,
    emit_takes: bool = True,
    zone_engine: bool = True,
    ckpt_every: int = 16,
    n_ckpt: int = 4,
):
    """Replay only `runs[k:]` on top of checkpoint `init_state` (the carry
    after the first k runs). Returns takes FOR THE SUFFIX ONLY plus a fresh
    ring whose positions are suffix-relative."""
    return _ffd_scan(
        run_group,
        run_count,
        group_req,
        group_compat_t,
        group_zc_bits,
        group_pool,
        group_pair_nok,
        group_device,
        type_alloc,
        type_charge,
        offer_zc_bits,
        pool_type,
        pool_zc_bits,
        pool_daemon,
        pool_limit,
        pool_usage0,
        node_free,
        node_compat,
        q_member,
        q_owner,
        q_kind,
        q_cap,
        node_q_member,
        node_q_owner,
        v_member,
        v_owner,
        v_kind,
        v_cap,
        v_primary,
        v_aff,
        v_count0,
        node_zone,
        zone_col_mask,
        node_dom2,
        col_axis,
        group_daxis,
        max_claims=max_claims,
        emit_takes=emit_takes,
        zone_engine=zone_engine,
        init_state=init_state,
        ckpt_every=ckpt_every,
        n_ckpt=n_ckpt,
    )

@functools.partial(
    jax.jit, static_argnames=("max_claims", "emit_takes", "zone_engine")
)
def ffd_solve_ladder(
    run_ladder,  # [S, L] i32 — rung groups per run (-1 pad), leading axis
    run_group,
    run_count,
    group_req,
    group_compat_t,
    group_zc_bits,
    group_pool,
    group_pair_nok,
    group_device,
    type_alloc,
    type_charge,
    offer_zc_bits,
    pool_type,
    pool_zc_bits,
    pool_daemon,
    pool_limit,
    pool_usage0,
    node_free,
    node_compat,
    q_member,
    q_owner,
    q_kind,
    q_cap,
    node_q_member,
    node_q_owner,
    v_member,
    v_owner,
    v_kind,
    v_cap,
    v_primary,
    v_aff,
    v_count0,
    node_zone,
    zone_col_mask,
    node_dom2,
    col_axis,
    group_daxis,
    *,
    max_claims: int,
    emit_takes: bool = True,
    zone_engine: bool = True,
) -> FFDOutput:
    """Single-dispatch preference relaxation: the scan walks each run's
    pre-materialized rung groups (run_ladder row) inside the step, so the
    whole host relax loop collapses to one kernel launch. Takes accumulate
    across rungs per run; leftover counts pods that exhausted their ladder.
    Tensor contract: run_ladder leads, then the frozen ARG_SPEC 36 — the
    arena's per-entry residency and AOT prewarm stay valid unchanged."""
    out, _ = _ffd_scan(
        run_group,
        run_count,
        group_req,
        group_compat_t,
        group_zc_bits,
        group_pool,
        group_pair_nok,
        group_device,
        type_alloc,
        type_charge,
        offer_zc_bits,
        pool_type,
        pool_zc_bits,
        pool_daemon,
        pool_limit,
        pool_usage0,
        node_free,
        node_compat,
        q_member,
        q_owner,
        q_kind,
        q_cap,
        node_q_member,
        node_q_owner,
        v_member,
        v_owner,
        v_kind,
        v_cap,
        v_primary,
        v_aff,
        v_count0,
        node_zone,
        zone_col_mask,
        node_dom2,
        col_axis,
        group_daxis,
        max_claims=max_claims,
        emit_takes=emit_takes,
        zone_engine=zone_engine,
        run_ladder=run_ladder,
    )
    return out


@functools.partial(
    jax.jit, static_argnames=("max_claims", "emit_takes", "zone_engine")
)
def ffd_solve_sharded(
    run_group,  # [Nd, Sblk] i32 — contiguous run blocks, one per mesh device
    run_count,  # [Nd, Sblk] i32
    group_req,
    group_compat_t,
    group_zc_bits,
    group_pool,
    group_pair_nok,
    group_device,
    type_alloc,
    type_charge,
    offer_zc_bits,
    pool_type,
    pool_zc_bits,
    pool_daemon,
    pool_limit,
    pool_usage0,
    node_free,
    node_compat,
    q_member,
    q_owner,
    q_kind,
    q_cap,
    node_q_member,
    node_q_owner,
    v_member,
    v_owner,
    v_kind,
    v_cap,
    v_primary,
    v_aff,
    v_count0,
    node_zone,
    zone_col_mask,
    node_dom2,
    col_axis,
    group_daxis,
    *,
    max_claims: int,
    emit_takes: bool = True,
    zone_engine: bool = True,
) -> FFDOutput:
    """Block-local FFD scans over mesh-partitioned run blocks, one lane per
    device. The tensor contract is the frozen ARG_SPEC 36 — identical names
    and order to ffd_solve — with ONLY the two run arrays carrying a leading
    block axis [Nd, Sblk] (encode.mesh_run_blocks); the other 34 broadcast
    unbatched into every lane. Each lane runs the SAME traced scan body as
    the one-device solve from the initial carry (state0), so a lane's output
    is bit-identical to ffd_solve over its block in isolation. Placement is
    computation-follows-data: the backend device_puts the block axis with a
    NamedSharding over the mesh's "shards" axis and the broadcast args
    replicated, so each device scans exactly its own block with no
    collectives inside the solve. The carry exchange that stitches lanes
    into the sequential result — associative combine over FFDState plus
    fix-up replay of blocks whose placement changes under the true prefix
    carry (via ffd_resume, the universal escape hatch) — is host-side in
    backend._sharded_finish; see SPEC.md "Sharding semantics". Returns
    FFDOutput with a leading [Nd] axis on every leaf (state.used becomes
    [Nd] — per-lane claim slots, each lane numbering from 0)."""

    def lane(rg, rc):
        out, _ = _ffd_scan(
            rg,
            rc,
            group_req,
            group_compat_t,
            group_zc_bits,
            group_pool,
            group_pair_nok,
            group_device,
            type_alloc,
            type_charge,
            offer_zc_bits,
            pool_type,
            pool_zc_bits,
            pool_daemon,
            pool_limit,
            pool_usage0,
            node_free,
            node_compat,
            q_member,
            q_owner,
            q_kind,
            q_cap,
            node_q_member,
            node_q_owner,
            v_member,
            v_owner,
            v_kind,
            v_cap,
            v_primary,
            v_aff,
            v_count0,
            node_zone,
            zone_col_mask,
            node_dom2,
            col_axis,
            group_daxis,
            max_claims=max_claims,
            emit_takes=emit_takes,
            zone_engine=zone_engine,
        )
        return out

    return jax.vmap(lane)(run_group, run_count)


# ---------------------------------------------------------------------------
# Sparse constraint engine: compacted V/Q-axis evaluation (ISSUE 20)
# ---------------------------------------------------------------------------
#
# Constraint-heavy fleets (zone topology spread, pod affinity) paid dense
# rent: every run's fast branch evaluated full-width [E, Q]/[M, Q] hostname
# allowances and [M, V]/[V, Z] spread-count updates even though a run's
# group touches only a handful of sigs. The sparse entry points below take
# two LEADING run-major index tables (encode.sparse_run_tables) — per-run
# active-constraint index lists, -1 padded to a quantum-bucketed width — and
# the scan gathers just those columns, with masked scatter-adds writing the
# deltas back. The gathered member/owner flags make any superset list exact
# (a non-member column contributes the neutral element everywhere), so the
# sparse leg is bit-identical to the dense kernel, the native host mirror,
# and the oracle (3-leg parity; tests/test_sparse_constraints.py). The
# frozen ARG_SPEC 36 is untouched: like run_ladder and init_state, the
# index tables LEAD the signature as side entries (SPARSE_ARG_SPEC), so the
# arena's per-entry residency and the AOT shape table stay valid.

# Side-table tensor names (tests/test_arg_spec_drift.py pins the sparse
# kernel signatures against this table the same way ARG_SPEC pins
# ffd_solve's). Widths Kq/Kv are quantum-bucketed (encode.SPARSE_IDX_MULT)
# so compile buckets stay shared across fleets of similar density.
SPARSE_ARG_SPEC = (
    "run_q_idx",  # [S, Kq] i32 — per-run active hostname-sig indices (-1 pad)
    "run_v_idx",  # [S, Kv] i32 — per-run active zone-sig indices (-1 pad)
)


@functools.partial(
    jax.jit, static_argnames=("max_claims", "emit_takes", "zone_engine")
)
def ffd_solve_sparse(
    run_q_idx,  # [S, Kq] i32 — leading side table (SPARSE_ARG_SPEC)
    run_v_idx,  # [S, Kv] i32
    run_group,
    run_count,
    group_req,
    group_compat_t,
    group_zc_bits,
    group_pool,
    group_pair_nok,
    group_device,
    type_alloc,
    type_charge,
    offer_zc_bits,
    pool_type,
    pool_zc_bits,
    pool_daemon,
    pool_limit,
    pool_usage0,
    node_free,
    node_compat,
    q_member,
    q_owner,
    q_kind,
    q_cap,
    node_q_member,
    node_q_owner,
    v_member,
    v_owner,
    v_kind,
    v_cap,
    v_primary,
    v_aff,
    v_count0,
    node_zone,
    zone_col_mask,
    node_dom2,
    col_axis,
    group_daxis,
    *,
    max_claims: int,
    emit_takes: bool = True,
    zone_engine: bool = True,
) -> FFDOutput:
    """ffd_solve with compacted V/Q-axis evaluation — decision-identical,
    pays for constraint density instead of constraint existence."""
    out, _ = _ffd_scan(
        run_group,
        run_count,
        group_req,
        group_compat_t,
        group_zc_bits,
        group_pool,
        group_pair_nok,
        group_device,
        type_alloc,
        type_charge,
        offer_zc_bits,
        pool_type,
        pool_zc_bits,
        pool_daemon,
        pool_limit,
        pool_usage0,
        node_free,
        node_compat,
        q_member,
        q_owner,
        q_kind,
        q_cap,
        node_q_member,
        node_q_owner,
        v_member,
        v_owner,
        v_kind,
        v_cap,
        v_primary,
        v_aff,
        v_count0,
        node_zone,
        zone_col_mask,
        node_dom2,
        col_axis,
        group_daxis,
        max_claims=max_claims,
        emit_takes=emit_takes,
        zone_engine=zone_engine,
        run_q_idx=run_q_idx,
        run_v_idx=run_v_idx,
    )
    return out


@functools.partial(
    jax.jit,
    static_argnames=("max_claims", "emit_takes", "zone_engine",
                     "ckpt_every", "n_ckpt"),
)
def ffd_solve_ckpt_sparse(
    run_q_idx,
    run_v_idx,
    run_group,
    run_count,
    group_req,
    group_compat_t,
    group_zc_bits,
    group_pool,
    group_pair_nok,
    group_device,
    type_alloc,
    type_charge,
    offer_zc_bits,
    pool_type,
    pool_zc_bits,
    pool_daemon,
    pool_limit,
    pool_usage0,
    node_free,
    node_compat,
    q_member,
    q_owner,
    q_kind,
    q_cap,
    node_q_member,
    node_q_owner,
    v_member,
    v_owner,
    v_kind,
    v_cap,
    v_primary,
    v_aff,
    v_count0,
    node_zone,
    zone_col_mask,
    node_dom2,
    col_axis,
    group_daxis,
    *,
    max_claims: int,
    emit_takes: bool = True,
    zone_engine: bool = True,
    ckpt_every: int = 16,
    n_ckpt: int = 4,
):
    """ffd_solve_ckpt with compacted V/Q-axis evaluation. The harvested
    ring is interchangeable with the dense one (the carry IS the decision
    state and decisions are identical), so dense and sparse dispatches may
    resume from each other's checkpoints."""
    return _ffd_scan(
        run_group,
        run_count,
        group_req,
        group_compat_t,
        group_zc_bits,
        group_pool,
        group_pair_nok,
        group_device,
        type_alloc,
        type_charge,
        offer_zc_bits,
        pool_type,
        pool_zc_bits,
        pool_daemon,
        pool_limit,
        pool_usage0,
        node_free,
        node_compat,
        q_member,
        q_owner,
        q_kind,
        q_cap,
        node_q_member,
        node_q_owner,
        v_member,
        v_owner,
        v_kind,
        v_cap,
        v_primary,
        v_aff,
        v_count0,
        node_zone,
        zone_col_mask,
        node_dom2,
        col_axis,
        group_daxis,
        max_claims=max_claims,
        emit_takes=emit_takes,
        zone_engine=zone_engine,
        ckpt_every=ckpt_every,
        n_ckpt=n_ckpt,
        run_q_idx=run_q_idx,
        run_v_idx=run_v_idx,
    )


@functools.partial(
    jax.jit,
    static_argnames=("max_claims", "emit_takes", "zone_engine",
                     "ckpt_every", "n_ckpt"),
)
def ffd_resume_sparse(
    init_state,  # FFDState pytree — a checkpoint from a prefix-valid solve
    run_q_idx,  # [S', Kq] i32 — suffix rows of the solve's index table
    run_v_idx,  # [S', Kv] i32
    run_group,
    run_count,
    group_req,
    group_compat_t,
    group_zc_bits,
    group_pool,
    group_pair_nok,
    group_device,
    type_alloc,
    type_charge,
    offer_zc_bits,
    pool_type,
    pool_zc_bits,
    pool_daemon,
    pool_limit,
    pool_usage0,
    node_free,
    node_compat,
    q_member,
    q_owner,
    q_kind,
    q_cap,
    node_q_member,
    node_q_owner,
    v_member,
    v_owner,
    v_kind,
    v_cap,
    v_primary,
    v_aff,
    v_count0,
    node_zone,
    zone_col_mask,
    node_dom2,
    col_axis,
    group_daxis,
    *,
    max_claims: int,
    emit_takes: bool = True,
    zone_engine: bool = True,
    ckpt_every: int = 16,
    n_ckpt: int = 4,
):
    """ffd_resume with compacted V/Q-axis evaluation over the suffix."""
    return _ffd_scan(
        run_group,
        run_count,
        group_req,
        group_compat_t,
        group_zc_bits,
        group_pool,
        group_pair_nok,
        group_device,
        type_alloc,
        type_charge,
        offer_zc_bits,
        pool_type,
        pool_zc_bits,
        pool_daemon,
        pool_limit,
        pool_usage0,
        node_free,
        node_compat,
        q_member,
        q_owner,
        q_kind,
        q_cap,
        node_q_member,
        node_q_owner,
        v_member,
        v_owner,
        v_kind,
        v_cap,
        v_primary,
        v_aff,
        v_count0,
        node_zone,
        zone_col_mask,
        node_dom2,
        col_axis,
        group_daxis,
        max_claims=max_claims,
        emit_takes=emit_takes,
        zone_engine=zone_engine,
        init_state=init_state,
        ckpt_every=ckpt_every,
        n_ckpt=n_ckpt,
        run_q_idx=run_q_idx,
        run_v_idx=run_v_idx,
    )


@functools.partial(
    jax.jit, static_argnames=("max_claims", "emit_takes", "zone_engine")
)
def ffd_solve_ladder_sparse(
    run_ladder,  # [S, L] i32 — rung groups per run (-1 pad), leading axis
    run_q_idx,  # [S, Kq] i32 — index rows UNIONED over base + rung groups
    run_v_idx,  # [S, Kv] i32
    run_group,
    run_count,
    group_req,
    group_compat_t,
    group_zc_bits,
    group_pool,
    group_pair_nok,
    group_device,
    type_alloc,
    type_charge,
    offer_zc_bits,
    pool_type,
    pool_zc_bits,
    pool_daemon,
    pool_limit,
    pool_usage0,
    node_free,
    node_compat,
    q_member,
    q_owner,
    q_kind,
    q_cap,
    node_q_member,
    node_q_owner,
    v_member,
    v_owner,
    v_kind,
    v_cap,
    v_primary,
    v_aff,
    v_count0,
    node_zone,
    zone_col_mask,
    node_dom2,
    col_axis,
    group_daxis,
    *,
    max_claims: int,
    emit_takes: bool = True,
    zone_engine: bool = True,
) -> FFDOutput:
    """ffd_solve_ladder with compacted V/Q-axis evaluation. Each run's
    index rows are the UNION of active sigs over its base group and every
    materialized rung group (encode.sparse_run_tables ladder mode), so the
    one gathered view is a correct superset at every cascade level."""
    out, _ = _ffd_scan(
        run_group,
        run_count,
        group_req,
        group_compat_t,
        group_zc_bits,
        group_pool,
        group_pair_nok,
        group_device,
        type_alloc,
        type_charge,
        offer_zc_bits,
        pool_type,
        pool_zc_bits,
        pool_daemon,
        pool_limit,
        pool_usage0,
        node_free,
        node_compat,
        q_member,
        q_owner,
        q_kind,
        q_cap,
        node_q_member,
        node_q_owner,
        v_member,
        v_owner,
        v_kind,
        v_cap,
        v_primary,
        v_aff,
        v_count0,
        node_zone,
        zone_col_mask,
        node_dom2,
        col_axis,
        group_daxis,
        max_claims=max_claims,
        emit_takes=emit_takes,
        zone_engine=zone_engine,
        run_ladder=run_ladder,
        run_q_idx=run_q_idx,
        run_v_idx=run_v_idx,
    )
    return out


@functools.partial(
    jax.jit, static_argnames=("max_claims", "emit_takes", "zone_engine")
)
def ffd_solve_sharded_sparse(
    run_q_idx,  # [Nd, Sblk, Kq] i32 — index tables partitioned like the runs
    run_v_idx,  # [Nd, Sblk, Kv] i32
    run_group,  # [Nd, Sblk] i32 — contiguous run blocks, one per mesh device
    run_count,  # [Nd, Sblk] i32
    group_req,
    group_compat_t,
    group_zc_bits,
    group_pool,
    group_pair_nok,
    group_device,
    type_alloc,
    type_charge,
    offer_zc_bits,
    pool_type,
    pool_zc_bits,
    pool_daemon,
    pool_limit,
    pool_usage0,
    node_free,
    node_compat,
    q_member,
    q_owner,
    q_kind,
    q_cap,
    node_q_member,
    node_q_owner,
    v_member,
    v_owner,
    v_kind,
    v_cap,
    v_primary,
    v_aff,
    v_count0,
    node_zone,
    zone_col_mask,
    node_dom2,
    col_axis,
    group_daxis,
    *,
    max_claims: int,
    emit_takes: bool = True,
    zone_engine: bool = True,
) -> FFDOutput:
    """ffd_solve_sharded with compacted V/Q-axis evaluation: the two index
    tables carry the same leading [Nd, Sblk] block axis as the run arrays
    (they are run-major, so they partition identically over the mesh's
    "shards" axis), the other 34 broadcast replicated. This is the entry
    point that lets the mesh-sharded path accept V>0/Q>0 fleets — each
    lane runs the same compacted scan from its block-local carry, and the
    host stitch's spread-counter triggers (backend._shard_stitch) decide
    accept vs fixup replay. zone_engine should be True iff V > 0, exactly
    like the one-device dispatch."""

    def lane(rqi, rvi, rg, rc):
        out, _ = _ffd_scan(
            rg,
            rc,
            group_req,
            group_compat_t,
            group_zc_bits,
            group_pool,
            group_pair_nok,
            group_device,
            type_alloc,
            type_charge,
            offer_zc_bits,
            pool_type,
            pool_zc_bits,
            pool_daemon,
            pool_limit,
            pool_usage0,
            node_free,
            node_compat,
            q_member,
            q_owner,
            q_kind,
            q_cap,
            node_q_member,
            node_q_owner,
            v_member,
            v_owner,
            v_kind,
            v_cap,
            v_primary,
            v_aff,
            v_count0,
            node_zone,
            zone_col_mask,
            node_dom2,
            col_axis,
            group_daxis,
            max_claims=max_claims,
            emit_takes=emit_takes,
            zone_engine=zone_engine,
            run_q_idx=rqi,
            run_v_idx=rvi,
        )
        return out

    return jax.vmap(lane)(run_q_idx, run_v_idx, run_group, run_count)


# ---------------------------------------------------------------------------
# Scheduling classes: priority preemption + atomic gangs (ISSUE 9)
# ---------------------------------------------------------------------------
#
# The base scan stays CLASS-BLIND on purpose: priority-major, gang-contiguous
# run ordering is applied by the host sort (provisioning/scheduler.py
# ffd_sort_with_sigs), so ffd_solve's frozen ARG_SPEC — and with it the
# arena's residency partition, the AOT shape table, and the resume / ladder /
# sharded splices — is untouched. The class semantics that cannot be
# expressed as ordering (reclaiming capacity from lower-priority placements,
# all-or-nothing gang verdicts) run as SIDE KERNELS over the CLASS_ARG_SPEC
# tensors below, orchestrated per solve by solver/scheduling_class.py with
# bit-identical host references in solver/native.py.

# Side-table tensor names (encode.EncodedInput carries them; the drift test
# pins the kernel signatures against this table the same way ARG_SPEC pins
# ffd_solve's).
CLASS_ARG_SPEC = (
    "run_prio16",  # [S] uint16 — dense priority rank per run (higher = more important)
    "run_gang",  # [S] int32 — gang index per run, -1 = no gang
    "gang_size",  # [NG] int32 — declared member count per gang
    "gang_min_ranks",  # [NG] int32 — members that must place for commit
)

# Eviction-table wire format: the preemption planner's output rides the same
# packed-uint16 discipline as the claim delta (DELTA_* above) — a small
# header then fixed-width u16 rows — so the decode path's transfer ledger
# and overflow carve-out apply unchanged. Header: [overflow, entry_count].
# Each entry is (node_idx, victim_idx) as two uint16 words; indices that do
# not fit uint16 set the overflow flag and the solve declines to the host
# fallback (counted), exactly like the claim delta's wide re-fetch.
EVICT_HEADER_WORDS = 2
EVICT_ENTRY_U16 = 2


class GangStage(NamedTuple):
    """Gang staging carry: the FFDState snapshot taken BEFORE a gang's runs
    enter the scan (`base`), the gang index being staged, and the member
    placements accumulated so far. Atomic commit = keep scanning past the
    gang; rollback = resume the scan from `base` with the gang's runs
    stripped (the checkpoint-ring resume machinery replays exactly this
    suffix). Host-orchestrated: solver/scheduling_class.py carries one of
    these per open gang; the drift test pins the layout."""

    base: FFDState  # pre-gang scan carry (or the ring snapshot nearest it)
    gang: jax.Array  # int32 scalar — gang index being staged
    members_placed: jax.Array  # int32 scalar — members placed so far


@functools.partial(jax.jit)
def gang_commit(run_placed, run_gang, gang_size, gang_min_ranks):
    """Atomic gang verdict over a finished scan: per-gang placed counts via
    segment-sum of the per-run placed counts, committed iff at least
    min_ranks members placed. Returns (commit [NG] bool, placed [NG] i32).
    Bit-identical host references: native.gang_commit_host (numpy) and
    scheduling_class._gang_commit_py (oracle loop)."""
    ng = gang_size.shape[0]
    seg = jnp.where(run_gang >= 0, run_gang, ng)  # park non-gang runs
    placed = jnp.zeros(ng + 1, jnp.int32).at[seg].add(
        run_placed.astype(jnp.int32)
    )[:ng]
    commit = (placed >= gang_min_ranks) & (gang_min_ranks > 0)
    return commit, placed


@functools.partial(jax.jit)
def preemption_plan(node_free, victim_prio, victim_req, victim_ok, node_ok,
                    need, pod_prio):
    """Plan one preemption: find the first node (ascending index) where the
    free capacity plus the capacity reclaimed from a minimal prefix of its
    eligible victims covers `need`, and the victim mask realizing it.

    Victims arrive PRE-SORTED per node by ascending (priority rank, uid) —
    the host builds the tensors (scheduling_class.build_victim_tensors), so
    all three implementations walk the identical order. Eligibility is
    strict: victim_ok AND victim_prio < pod_prio. Ineligible victims
    contribute zero, so the running cumulative at position v is exactly the
    reclaim of the eligible prefix through v; the chosen prefix is the
    shortest one that fits (fit at k stays fit at k+1 — reclaim only grows).

    Shapes: node_free [E,R] i32, victim_prio [E,Vm] i32, victim_req
    [E,Vm,R] i32, victim_ok [E,Vm] bool, node_ok [E] bool, need [R] i32,
    pod_prio i32 scalar. Returns (node_idx i32, -1 = no plan; victim_mask
    [E,Vm] bool, hot only on the chosen node's row)."""
    E, Vm = victim_prio.shape
    eligible = victim_ok & (victim_prio < pod_prio)
    reclaim = jnp.where(eligible[:, :, None], victim_req, 0)
    cum = node_free[:, None, :] + jnp.cumsum(reclaim, axis=1)  # [E,Vm,R]
    fit0 = jnp.all(node_free >= need[None, :], axis=1)  # [E] free alone fits
    fit_at = jnp.all(cum >= need[None, None, :], axis=2)  # [E,Vm]
    any_fit = node_ok & (fit0 | jnp.any(fit_at, axis=1))
    node_idx = jnp.where(
        jnp.any(any_fit), jnp.argmax(any_fit).astype(jnp.int32), jnp.int32(-1)
    )
    # minimal prefix end per node: first position where the cumulative fits
    # (argmax of the monotone fit row); masked to the chosen node, and empty
    # when its free capacity alone fits
    kmin = jnp.argmax(fit_at, axis=1)  # [E]
    take = (
        eligible
        & (jnp.arange(Vm)[None, :] <= kmin[:, None])
        & ~fit0[:, None]
        & (jnp.arange(E)[:, None] == node_idx)
        & (node_idx >= 0)
    )
    return node_idx, take


def pack_evictions(entries):
    """Pack (node_idx, victim_idx) rows into the uint16 eviction table
    (EVICT_HEADER_WORDS then EVICT_ENTRY_U16 words per row). Overflow —
    any index above uint16 — sets header[0] and packs no rows: the caller
    must decline to the host fallback, mirroring the claim delta's wide
    re-fetch carve-out. Host-side helper (numpy), shared by every backend
    so the wire bytes are identical regardless of which planner ran."""
    n = len(entries)
    overflow = any(e >= 2**16 or v >= 2**16 for e, v in entries)
    if overflow:
        return np.asarray([1, 0], dtype=np.uint16)
    buf = np.zeros(EVICT_HEADER_WORDS + EVICT_ENTRY_U16 * n, dtype=np.uint16)
    buf[0] = 0
    buf[1] = n
    for i, (e, v) in enumerate(entries):
        buf[EVICT_HEADER_WORDS + 2 * i] = e
        buf[EVICT_HEADER_WORDS + 2 * i + 1] = v
    return buf


def unpack_evictions(buf):
    """Inverse of pack_evictions: (overflow, [(node_idx, victim_idx), ...])."""
    buf = np.asarray(buf, dtype=np.uint16)
    overflow = bool(buf[0])
    n = int(buf[1])
    rows = [
        (int(buf[EVICT_HEADER_WORDS + 2 * i]),
         int(buf[EVICT_HEADER_WORDS + 2 * i + 1]))
        for i in range(n)
    ]
    return overflow, rows


# --- decision provenance (obs/explain.py) --------------------------------
#
# Why pod p did NOT land on node e, computed where the decision was made:
# a SIDE KERNEL over the EXPLAIN_ARG_SPEC tables below plus the scan's own
# take_e — ffd_solve's frozen 36-tensor signature is untouched (the
# CLASS_ARG_SPEC precedent). The packed int32 buffer mirrors the claim
# delta's wire discipline: a small header with an overflow flag, uint16
# payload halves, and a carve-out — overflow (a node index above uint16)
# makes the HOST deriver (obs/explain.host_table) recompute the table
# instead of trusting truncated bits. Off by default: backend.py only
# dispatches this kernel when the explain knob is on, so the off path
# moves zero extra bytes across the tunnel.
#
# The reason enum and its precedence (smallest nonzero code wins) are the
# wire contract, pinned by tests/test_arg_spec_drift.py against the
# decoder-side names in obs/explain.REASON_NAMES and the SPEC.md table.

EXPLAIN_REASONS = (
    ("feasible", 0),
    ("zone", 1),
    ("capacity_type", 2),
    ("taint", 3),
    ("resources", 4),
    ("topology", 5),
    ("affinity", 6),
)
EXPLAIN_HEADER_WORDS = 3  # [overflow_flag, n_groups, top_k] i32
EXPLAIN_ENTRY_WORDS = 1   # e | (reason << 16) per rejected candidate

EXPLAIN_ARG_SPEC = (
    "take_e",       # [Sp, Ep] i32 — the scan's own output (device-resident)
    "run_group",    # [Sp] i32
    "group_req",    # [Gp, R] i32
    "node_free",    # [Ep, R] i32 (pre-solve)
    "node_compat",  # [Gp, Ep] bool (labels+taints admission)
    "node_zone",    # [Ep] i32 (-1 unknown)
    "node_ct",      # [Ep] i32 (-1 unknown)
    "group_zone",   # [Gp, Z] bool
    "group_ct",     # [Gp, C] bool
    "group_topo",   # [Gp] bool — group owns a spread engine constraint
    "group_aff",    # [Gp] bool — group owns affinity terms
    "e_count",      # i32 scalar — real node count inside the Ep padding
    "g_count",      # i32 scalar — real group count inside the Gp padding
)


def explain_words(n_groups: int, k: int) -> int:
    """Buffer length in int32 words: header + per-group (count + k entries)."""
    return EXPLAIN_HEADER_WORDS + n_groups * (1 + k * EXPLAIN_ENTRY_WORDS)


@functools.partial(jax.jit, static_argnames=("top_k",))
def explain_pack(take_e, run_group, group_req, node_free, node_compat,
                 node_zone, node_ct, group_zone, group_ct, group_topo,
                 group_aff, e_count, g_count, *, top_k: int):
    """Pack the per-group rejection table into one int32 wire buffer.

    Post-solve semantics: final free = node_free − Σ_s take_e[s,e]·req, so
    a node is "rejected" for group g iff it cannot admit+fit ONE MORE pod
    of g — with the fixed cause precedence zone > capacity_type > taint >
    resources > topology > affinity, and any node the group actually
    landed pods on reported feasible. All int32 arithmetic: the numpy twin
    obs/explain.reason_codes/rejection_table produces the same bits, which
    the randomized parity suite asserts.

    Layout: [overflow, g_count, top_k] then per group (padded rows
    zeroed/-1) one n_rejected word + top_k entry words, entry =
    e | (reason << 16), -1 = empty slot."""
    Sp, Ep = take_e.shape
    Gp = group_req.shape[0]
    take_e = take_e.astype(jnp.int32)
    req_s = group_req[run_group]                                # [Sp, R]
    usage = take_e.T @ req_s                                    # [Ep, R]
    free_final = node_free - usage
    Z = group_zone.shape[1]
    C = group_ct.shape[1]
    zid = jnp.clip(node_zone, 0, Z - 1)
    cid = jnp.clip(node_ct, 0, C - 1)
    zone_ok = jnp.where(node_zone[None, :] >= 0, group_zone[:, zid], True)
    ct_ok = jnp.where(node_ct[None, :] >= 0, group_ct[:, cid], True)
    fits = jnp.all(free_final[None, :, :] >= group_req[:, None, :], axis=-1)
    ghot = (run_group[None, :] == jnp.arange(Gp, dtype=jnp.int32)[:, None])
    placed = (ghot.astype(jnp.int32) @ take_e) > 0              # [Gp, Ep]
    code = jnp.where(
        ~zone_ok, 1,
        jnp.where(~ct_ok, 2,
        jnp.where(~node_compat, 3,
        jnp.where(~fits, 4,
        jnp.where(group_topo[:, None], 5,
        jnp.where(group_aff[:, None], 6, 0))))))
    code = jnp.where(placed, 0, code).astype(jnp.int32)
    e_idx = jnp.arange(Ep, dtype=jnp.int32)
    real_e = e_idx[None, :] < e_count
    real_g = jnp.arange(Gp, dtype=jnp.int32) < g_count
    rej = (code > 0) & real_e & real_g[:, None]
    n_rej = jnp.sum(rej, axis=1).astype(jnp.int32)              # [Gp]
    key = jnp.where(rej, e_idx[None, :], Ep)
    order = jnp.argsort(key, axis=1)[:, :top_k]
    ent_e = jnp.take_along_axis(key, order, axis=1)
    ent_c = jnp.take_along_axis(code, order, axis=1)
    valid = ent_e < Ep
    words = jnp.where(valid, ent_e | (ent_c << 16), -1).astype(jnp.int32)
    if words.shape[1] < top_k:  # fewer nodes than top-k: pad empty slots
        pad = jnp.full((Gp, top_k - words.shape[1]), -1, dtype=jnp.int32)
        words = jnp.concatenate([words, pad], axis=1)
    overflow = jnp.int32(Ep > 0xFFFF)
    header = jnp.stack([
        overflow, g_count.astype(jnp.int32), jnp.int32(top_k)
    ])
    rows = jnp.concatenate([n_rej[:, None], words], axis=1)     # [Gp, 1+K]
    return jnp.concatenate([header, rows.reshape(-1)])


def unpack_explain(flat, n_groups: int):
    """Inverse of explain_pack for the REAL group prefix: (overflow,
    n_rejected [G] i32, words [G, K] i32). Pure numpy — the backend's
    decode half of the EXPLAIN wire section."""
    flat = np.asarray(flat, dtype=np.int32)
    overflow = bool(flat[0])
    k = int(flat[2])
    body = flat[EXPLAIN_HEADER_WORDS:].reshape(-1, 1 + k)
    n_rej = np.ascontiguousarray(body[:n_groups, 0])
    words = np.ascontiguousarray(body[:n_groups, 1:])
    return overflow, n_rej, words


# --- compile observability (obs/telemetry.py; ISSUE 14) ------------------
#
# Every public jitted entry point is rebound to a telemetry hook that
# derives a dispatch signature ((shape, dtype) per array + the statics)
# and counts first sightings as compile events — arming the hot-path
# recompile detector once the operator marks the prewarm phase done.
# The hooks preserve `__wrapped__` (the plain traceable function
# consolidate.py and parallel/sharded.py vmap and the arg-spec drift
# test introspects) and proxy `.lower()` so prewarm_aot's AOT compiles
# register their signatures as prewarmed.

from ...obs import telemetry as _telemetry  # noqa: E402

ffd_apply_events = _telemetry.instrument("ffd_apply_events", ffd_apply_events)
ffd_solve = _telemetry.instrument("ffd_solve", ffd_solve, arg_names=ARG_SPEC)
ffd_solve_ckpt = _telemetry.instrument(
    "ffd_solve_ckpt", ffd_solve_ckpt, arg_names=ARG_SPEC)
ffd_resume = _telemetry.instrument(
    "ffd_resume", ffd_resume, arg_names=("init_state",) + tuple(ARG_SPEC))
ffd_solve_ladder = _telemetry.instrument(
    "ffd_solve_ladder", ffd_solve_ladder,
    arg_names=("run_ladder",) + tuple(ARG_SPEC))
ffd_solve_sharded = _telemetry.instrument(
    "ffd_solve_sharded", ffd_solve_sharded, arg_names=ARG_SPEC)
ffd_solve_sparse = _telemetry.instrument(
    "ffd_solve_sparse", ffd_solve_sparse,
    arg_names=tuple(SPARSE_ARG_SPEC) + tuple(ARG_SPEC))
ffd_solve_ckpt_sparse = _telemetry.instrument(
    "ffd_solve_ckpt_sparse", ffd_solve_ckpt_sparse,
    arg_names=tuple(SPARSE_ARG_SPEC) + tuple(ARG_SPEC))
ffd_resume_sparse = _telemetry.instrument(
    "ffd_resume_sparse", ffd_resume_sparse,
    arg_names=("init_state",) + tuple(SPARSE_ARG_SPEC) + tuple(ARG_SPEC))
ffd_solve_ladder_sparse = _telemetry.instrument(
    "ffd_solve_ladder_sparse", ffd_solve_ladder_sparse,
    arg_names=("run_ladder",) + tuple(SPARSE_ARG_SPEC) + tuple(ARG_SPEC))
ffd_solve_sharded_sparse = _telemetry.instrument(
    "ffd_solve_sharded_sparse", ffd_solve_sharded_sparse,
    arg_names=tuple(SPARSE_ARG_SPEC) + tuple(ARG_SPEC))
gang_commit = _telemetry.instrument("gang_commit", gang_commit)
preemption_plan = _telemetry.instrument("preemption_plan", preemption_plan)
explain_pack = _telemetry.instrument("explain_pack", explain_pack)
