"""SolverStateVault: durable solver resident state (ISSUE 17).

Streaming delta-solve (solver/streaming.py) made the solver STATEFUL —
resident encode cores, arena residency classes, checkpoint rings, a journal
cursor — so a process restart or TPU maintenance event costs a full
re-encode + AOT re-prewarm before the first decision lands. This module
makes restart-to-first-solve journal-lag-bounded instead of
cluster-size-bounded: a periodic *async* snapshot of the device-facing
resident model, written atomically to local disk off the hot path, plus a
restore path that re-seeds the encode caches and composes with the
streaming model's re-baseline machinery.

What a vault file holds (version 1):

  - the journal cursor (`seq` — the StreamingSolver's applied seq, or the
    journal head when no streaming model is wired) and the store's
    resource-version high-water mark, stamped for restore cross-checks;
  - encode-core DONORS: every cached `_EncodeCore` exported with its pod
    lists stripped and re-keyed by CONTENT — the ordered distinct pod
    signature sequence plus a content fingerprint of the catalog segment —
    because the live cache key embeds process-local object ids and interned
    signature numbers that mean nothing across a process boundary
    (encode_cache.install_vault_donors / adopt_vault_donor);
  - an arena MANIFEST: accounted bytes per (residency class, tenant) and
    per-bucket content digests (args / checkpoint ring / relax ladders).
    HBM buffers die with their process, so the manifest is verification
    and observability, not buffer state: a restored process re-adopts
    residency on its first solve (one packed cold upload), and a
    same-process restore whose live arena disagrees with the manifest
    invalidates it rather than trust unknowable residency;
  - tenant namespaces, so multi-tenant donor installs land per tenant.

Restore semantics (solver/SPEC.md "Durability semantics"): candidates are
scanned newest-first; a truncated / checksum-mismatched / wrong-epoch /
seq-ahead / store-behind file is SKIPPED (counted, flight-dumped as
`vault_restore_failed` when nothing restorable remains) — the operator
degrades to the cold re-encode path, never crashes, and never serves stale
decisions: donors are additionally content-verified at encode time, so a
donor that no longer matches the live pod/catalog content simply misses.

Fault sites (faults.py): `vault.write` fires before each snapshot write —
a failure skips the snapshot with a throttled WARN and the next interval
retries; `vault.corrupt` fires in the file-read path so chaos tests can
reject candidates without hand-crafting broken bytes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
import pickle
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .. import faults
from ..metrics.registry import (
    SOLVER_VAULT_AGE,
    SOLVER_VAULT_BYTES,
    SOLVER_VAULT_RESTORE_FAILURES,
    SOLVER_VAULT_RESTORE_SECONDS,
    SOLVER_VAULT_RESTORES,
    SOLVER_VAULT_SNAPSHOT_SECONDS,
)
from ..obs import telemetry as obstelemetry
from ..obs import trace as obstrace

log = logging.getLogger("karpenter_tpu")

VAULT_MAGIC = b"KVAULT1\n"
VAULT_VERSION = 1
_DIGEST_SIZE = 16
_HDR = len(VAULT_MAGIC) + _DIGEST_SIZE


class VaultCorrupt(Exception):
    """A vault candidate that must be skipped: truncated, checksum
    mismatch, unpicklable, or failing a restore cross-check."""


@dataclasses.dataclass
class RestoreReport:
    """What one successful restore did (surfaced on /healthz + dumps)."""

    path: str
    seq: int
    store_rv: Optional[int]
    donors_installed: int
    streaming: str  # "tail" | "rebaseline" | "baseline" | "none"
    arena: str  # "resident" | "cold" | "none"
    age_s: float
    skipped: List[Tuple[str, str]]  # (file, reason) for rejected candidates

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


# -- capture helpers ----------------------------------------------------------


def export_encode_donors() -> List[dict]:
    """Export every cached `_EncodeCore` (default + per-tenant namespaces)
    as a process-portable donor record. The live cache key is useless
    across processes — it embeds pod/type object ids and interned signature
    numbers — so each donor is re-keyed by CONTENT: the ordered distinct
    pod signature sequence (computed from the group representatives while
    they are still alive) plus the catalog content fingerprint the encoder
    stamped on the entry. Pod lists and the O(pods) run split are stripped:
    the [G]/[T]/[P] tables are pure functions of (signature sequence,
    catalog segment) and the adopter rebuilds the rest from its own pods.
    """
    import numpy as np

    from . import encode as em
    from . import encode_cache as ec

    donors: List[dict] = []
    namespaces = [(None, em._CORE_CACHE)]
    namespaces += [(tid, c) for tid, c in ec._TENANT_CORE_CACHES.items()]
    for tenant_id, cache in namespaces:
        for key, ent in list(cache.items()):
            core = ent[1]
            cat_fp = ent[4] if len(ent) > 4 else None
            if cat_fp is None or not core.group_snums:
                continue  # no content key / batch-local sigs: not portable
            try:
                sig_seq = tuple(
                    em._pod_signature(pl[0]) for pl in core.group_pods
                )
                stripped = dataclasses.replace(
                    core,
                    group_pods=[],
                    run_group=np.zeros(0, np.int32),
                    run_count=np.zeros(0, np.int32),
                    sorted_uids=core.sorted_uids[:0],
                )
                donors.append({
                    "tenant_id": tenant_id,
                    "sig_seq": sig_seq,
                    "ds_key": key[3],
                    "zones": key[4],
                    "cts": key[5],
                    "policy": key[6],
                    "cat_fp": cat_fp,
                    "core": stripped,
                })
            except Exception:  # noqa: BLE001 — one bad entry never aborts
                log.exception("solver vault: donor export skipped one core")
    return donors


def arena_manifest(arena) -> Optional[dict]:
    """Content manifest of an ArgumentArena's residency: accounted bytes
    per (class, tenant) plus per-bucket entry digests and the checkpoint /
    ladder digest sets. Digests only — device buffers cannot be persisted;
    the manifest lets a restore REPORT what residency existed and lets a
    same-process restore detect divergence (and invalidate) instead of
    trusting unknowable buffers."""
    if arena is None:
        return None
    try:
        buckets: Dict[str, list] = {}
        for key, rec in arena._buckets.items():
            _, tags = rec
            buckets[repr(key)] = [
                tag[1].hex() if tag is not None and tag[1] is not None
                else None
                for tag in tags
            ]
        ladders = sorted(
            dig.hex() for (dig, _arr) in arena._ladders.values()
        )
        ckpts = {repr(k): len(v) for k, v in arena._ckpts.items()}
        return {
            "total_bytes": int(arena.total_bytes()),
            "classes": {
                f"{cls}/{ten}": int(nb)
                for (cls, ten), nb in sorted(arena.bytes_by_class().items())
            },
            "buckets": buckets,
            "ladder_digests": ladders,
            "checkpoints": ckpts,
        }
    except Exception:  # noqa: BLE001 — manifest is observability, not state
        log.exception("solver vault: arena manifest capture failed")
        return None


class SolverStateVault:
    """Periodic async snapshots + cross-checked restore of the solver's
    resident state. Construction creates the vault directory; nothing is
    written until `snapshot_now()` / `maybe_snapshot()` runs, and nothing
    anywhere consults the vault unless one is explicitly wired — vault-off
    deployments are byte-identical to the pre-vault path."""

    def __init__(
        self,
        directory: str,
        interval_s: float = 5.0,
        keep: int = 3,
        epoch: str = "default",
        journal=None,
        store=None,
        streaming=None,
        arena_fn: Optional[Callable[[], object]] = None,
        clock=time.monotonic,
        warn_every_s: float = 30.0,
    ):
        if interval_s <= 0:
            raise ValueError(f"vault interval must be > 0, got {interval_s}")
        if keep < 1:
            raise ValueError(f"vault keep must be >= 1, got {keep}")
        self.dir = os.path.abspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.interval_s = float(interval_s)
        self.keep = int(keep)
        # the vault's journal-identity stamp: a file captured against one
        # journal/store lineage must not restore into another (the
        # "wrong-journal-epoch" rejection class)
        self.epoch = epoch
        self.journal = journal
        self.store = store
        self.streaming = streaming
        self.arena_fn = arena_fn
        self.clock = clock
        self.warn_every_s = float(warn_every_s)
        self._lock = threading.Lock()
        self._inflight = False
        self._n = 0
        self._last_attempt_at: Optional[float] = None
        self._last_snapshot_at: Optional[float] = None
        self._last_warn_at: Optional[float] = None
        self._last_path: Optional[str] = None
        self._last_bytes = 0
        self._last_seq = 0
        self.last_restore: Optional[RestoreReport] = None
        self.stats: Dict[str, int] = {
            "snapshots": 0,
            "write_failures": 0,
            "restores": 0,
            "restore_failures": 0,
            "donors_installed": 0,
        }

    # -- capture / snapshot ---------------------------------------------------

    def capture(self) -> dict:
        """Assemble the snapshot payload from the live resident state.
        Quick host work only (donor export walks the bounded core caches;
        the arena manifest hexes already-computed digests) — the expensive
        pickle + fsync happen in the caller, off the solve path."""
        from . import encode_cache as ec

        seq = 0
        if self.streaming is not None:
            seq = int(self.streaming.snapshot()["applied_seq"])
        elif self.journal is not None:
            seq = int(self.journal.rev())
        return {
            "version": VAULT_VERSION,
            "epoch": self.epoch,
            "seq": seq,
            "store_rv": (
                int(self.store.current_rv()) if self.store is not None
                else None
            ),
            "captured_at": self.clock(),
            "donors": export_encode_donors(),
            "arena": arena_manifest(
                self.arena_fn() if self.arena_fn is not None else None
            ),
            "tenants": sorted(ec._TENANT_CORE_CACHES),
            "core_rev": ec._CORE_REV,
        }

    def snapshot_now(self) -> Optional[str]:
        """Capture + atomic checksummed write (tmp, fsync, rename), prune
        to `keep`. Returns the written path, or None on failure — failures
        WARN at most every `warn_every_s` and never propagate: the solver
        keeps serving and the next interval retries."""
        t0 = time.perf_counter()
        try:
            faults.check("vault.write")
            payload = self.capture()
            blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
            digest = hashlib.blake2b(blob, digest_size=_DIGEST_SIZE).digest()
            with self._lock:
                self._n += 1
                n = self._n
            final = os.path.join(
                self.dir, f"vault-{payload['seq']:012d}-{n:06d}.vlt"
            )
            fd, tmp = tempfile.mkstemp(dir=self.dir, prefix=".vault-")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(VAULT_MAGIC)
                    f.write(digest)
                    f.write(blob)
                    f.flush()
                    # fsync BEFORE the rename: a crash between write and
                    # rename must never leave a torn file as the newest
                    # candidate (same hardening as controllers/snapshot.py)
                    os.fsync(f.fileno())
                os.replace(tmp, final)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            self._prune()
        except Exception as e:  # noqa: BLE001 — snapshots must never crash
            with self._lock:
                self.stats["write_failures"] += 1
                now = self.clock()
                warn = (
                    self._last_warn_at is None
                    or now - self._last_warn_at >= self.warn_every_s
                )
                if warn:
                    self._last_warn_at = now
            if warn:
                log.warning(
                    "solver vault: snapshot failed (%s: %s) — serving "
                    "continues; next interval retries",
                    type(e).__name__, e,
                )
            obstelemetry.note_event(
                "vault_write_failed", error=type(e).__name__
            )
            return None
        nbytes = _HDR + len(blob)
        SOLVER_VAULT_SNAPSHOT_SECONDS.observe(time.perf_counter() - t0)
        SOLVER_VAULT_BYTES.set(float(nbytes))
        SOLVER_VAULT_AGE.set(0.0)
        with self._lock:
            self.stats["snapshots"] += 1
            self._last_snapshot_at = self.clock()
            self._last_path = final
            self._last_bytes = nbytes
            self._last_seq = payload["seq"]
        return final

    def maybe_snapshot(self) -> bool:
        """Interval-gated ASYNC snapshot: spawns one background writer at
        most every `interval_s` (failures included — a failing disk retries
        at the cadence, it does not spin). Returns True when a snapshot was
        started. This is the hot-path entry: it costs two clock reads and a
        thread spawn per interval, nothing per solve."""
        with self._lock:
            if self._inflight:
                return False
            now = self.clock()
            if (
                self._last_attempt_at is not None
                and now - self._last_attempt_at < self.interval_s
            ):
                return False
            self._last_attempt_at = now
            self._inflight = True
        threading.Thread(
            target=self._snapshot_worker, daemon=True, name="solver-vault"
        ).start()
        return True

    def _snapshot_worker(self) -> None:
        try:
            self.snapshot_now()
        finally:
            with self._lock:
                self._inflight = False

    # -- files ----------------------------------------------------------------

    def candidates(self) -> List[str]:
        """Vault files newest-first (the seq+counter filename sorts
        lexicographically = numerically)."""
        try:
            names = [
                n for n in os.listdir(self.dir)
                if n.startswith("vault-") and n.endswith(".vlt")
            ]
        except OSError:
            return []
        return [os.path.join(self.dir, n) for n in sorted(names, reverse=True)]

    def _prune(self) -> None:
        for path in self.candidates()[self.keep:]:
            try:
                os.unlink(path)
            except OSError:
                pass

    def _read(self, path: str) -> dict:
        faults.check("vault.corrupt")
        with open(path, "rb") as f:
            raw = f.read()
        if len(raw) < _HDR or not raw.startswith(VAULT_MAGIC):
            raise VaultCorrupt(
                f"{os.path.basename(path)}: truncated or bad magic"
            )
        digest, blob = raw[len(VAULT_MAGIC):_HDR], raw[_HDR:]
        if hashlib.blake2b(blob, digest_size=_DIGEST_SIZE).digest() != digest:
            raise VaultCorrupt(
                f"{os.path.basename(path)}: checksum mismatch"
            )
        try:
            payload = pickle.loads(blob)
        except Exception as e:  # noqa: BLE001 — any decode failure is corrupt
            raise VaultCorrupt(
                f"{os.path.basename(path)}: unpicklable "
                f"({type(e).__name__}: {e})"
            ) from e
        if not isinstance(payload, dict) or payload.get("version") != VAULT_VERSION:
            raise VaultCorrupt(
                f"{os.path.basename(path)}: unknown payload version "
                f"{payload.get('version') if isinstance(payload, dict) else '?'}"
            )
        return payload

    # -- restore --------------------------------------------------------------

    def _cross_check(self, payload: dict) -> None:
        """The seq/state_rev cross-check table (SPEC.md "Durability
        semantics"): any mismatch rejects the candidate, which forces the
        clean re-baseline / cold re-encode path rather than risking
        decisions derived from another lineage or a future the live
        process has not reached."""
        if payload.get("epoch") != self.epoch:
            raise VaultCorrupt(
                f"journal epoch mismatch (vault {payload.get('epoch')!r}, "
                f"live {self.epoch!r})"
            )
        if self.journal is not None and payload["seq"] > self.journal.rev():
            raise VaultCorrupt(
                f"vault seq {payload['seq']} ahead of live journal "
                f"{self.journal.rev()} (journal lineage reset?)"
            )
        rv = payload.get("store_rv")
        if (
            self.store is not None and rv is not None
            and self.store.current_rv() < rv
        ):
            raise VaultCorrupt(
                f"store rv {self.store.current_rv()} behind vault rv {rv} "
                "(older store snapshot restored?)"
            )

    def _compose_streaming(self, payload: dict) -> str:
        """Compose with the streaming model: when the live model has
        already folded past the vault's seq the journal tail covers the
        gap ('tail' — pump() folds the rest); an attached model BEHIND the
        vault seq is a mismatch and is forced onto a clean re-baseline; a
        fresh model baselines on its first pump anyway."""
        s = self.streaming
        if s is None:
            return "none"
        if not getattr(s, "_attached", False):
            return "baseline"
        if s.snapshot()["applied_seq"] < payload["seq"]:
            s.force_rebaseline("vault_seq_mismatch")
            return "rebaseline"
        return "tail"

    def _compose_arena(self, payload: dict) -> str:
        """Verify live arena residency against the vaulted manifest. HBM
        buffers never survive a process, so a fresh process reports 'cold'
        (first solve re-adopts with one packed upload); a live arena whose
        digests diverge from the manifest is invalidated — residency the
        vault cannot vouch for is residency the next dispatch must not
        trust."""
        manifest = payload.get("arena")
        arena = self.arena_fn() if self.arena_fn is not None else None
        if arena is None or manifest is None:
            return "none"
        live = arena_manifest(arena)
        if live is None or not live["buckets"]:
            return "cold"
        if (
            live["buckets"] == manifest.get("buckets")
            and live["ladder_digests"] == manifest.get("ladder_digests")
        ):
            return "resident"
        try:
            arena.invalidate()
        except Exception:  # noqa: BLE001 — best-effort on divergence
            log.exception("solver vault: arena invalidate failed")
        return "cold"

    def restore(self, install: bool = True) -> Optional[RestoreReport]:
        """Scan candidates newest-first; the first one that reads clean AND
        passes the cross-checks is restored (encode donors installed,
        streaming/arena composed). Corrupt or mismatched candidates are
        skipped; if none survives, the failure is counted, flight-dumped
        (`vault_restore_failed`), and None returned — the caller proceeds
        on the cold path. An EMPTY vault directory returns None silently:
        a first boot is not a failure."""
        from . import encode_cache as ec

        t0 = time.perf_counter()
        skipped: List[Tuple[str, str]] = []
        with obstrace.span("vault.restore"):
            for path in self.candidates():
                try:
                    payload = self._read(path)
                    self._cross_check(payload)
                except VaultCorrupt as e:
                    skipped.append((os.path.basename(path), str(e)))
                    continue
                except Exception as e:  # noqa: BLE001 — torn reads, OS
                    # errors, injected faults: one bad candidate is a skip,
                    # never a boot failure
                    skipped.append((
                        os.path.basename(path),
                        f"{type(e).__name__}: {e}",
                    ))
                    continue
                installed = 0
                if install:
                    installed = ec.install_vault_donors(payload["donors"])
                streaming = self._compose_streaming(payload)
                arena = self._compose_arena(payload)
                age = max(0.0, self.clock() - payload.get("captured_at", 0.0))
                report = RestoreReport(
                    path=path,
                    seq=int(payload["seq"]),
                    store_rv=payload.get("store_rv"),
                    donors_installed=installed,
                    streaming=streaming,
                    arena=arena,
                    age_s=age,
                    skipped=skipped,
                )
                SOLVER_VAULT_RESTORES.inc()
                SOLVER_VAULT_RESTORE_SECONDS.observe(time.perf_counter() - t0)
                with self._lock:
                    self.stats["restores"] += 1
                    self.stats["donors_installed"] += installed
                    self.last_restore = report
                obstelemetry.note_event(
                    "vault_restore", seq=report.seq,
                    donors=installed, streaming=streaming, arena=arena,
                )
                log.info(
                    "solver vault: restored %s (seq=%d, %d donor core(s), "
                    "streaming=%s, arena=%s%s)",
                    os.path.basename(path), report.seq, installed, streaming,
                    arena,
                    f", {len(skipped)} corrupt candidate(s) skipped"
                    if skipped else "",
                )
                return report
        if skipped:
            # candidates existed and ALL were rejected: that is the
            # corruption-fallback path the operator must survive loudly
            SOLVER_VAULT_RESTORE_FAILURES.inc()
            with self._lock:
                self.stats["restore_failures"] += 1
            obstelemetry.note_event(
                "vault_restore_failed", candidates=len(skipped),
                first_error=skipped[0][1],
            )
            try:
                obstrace.dump(
                    "vault_restore_failed", candidates=len(skipped),
                    first_error=skipped[0][1],
                )
            except Exception:  # noqa: BLE001 — diagnostics never abort boot
                log.exception("solver vault: restore-failure dump failed")
            log.warning(
                "solver vault: restore FAILED — %d candidate(s) rejected "
                "(%s) — degrading to the cold re-encode path",
                len(skipped), skipped[0][1],
            )
        return None

    # -- introspection --------------------------------------------------------

    def vault_age_s(self) -> Optional[float]:
        with self._lock:
            if self._last_snapshot_at is None:
                return None
            return max(0.0, self.clock() - self._last_snapshot_at)

    def health(self) -> dict:
        """The /healthz "vault" object (registered as a telemetry provider
        by the operator) — also refreshes the age gauge so scrapes between
        snapshots see the true staleness."""
        age = self.vault_age_s()
        if age is not None:
            SOLVER_VAULT_AGE.set(age)
        with self._lock:
            return {
                "dir": self.dir,
                "interval_s": self.interval_s,
                "keep": self.keep,
                "epoch": self.epoch,
                "age_s": age,
                "last_seq": self._last_seq,
                "last_bytes": self._last_bytes,
                "last_restore": (
                    self.last_restore.as_dict()
                    if self.last_restore is not None else None
                ),
                **self.stats,
            }


class VaultController:
    """Controller-loop adapter: one `maybe_snapshot()` poke per reconcile.
    The snapshot itself runs on the vault's own daemon thread, so the
    controller tick — and the solve path it shares a loop with — never
    blocks on capture, pickling, or fsync."""

    name = "solver-vault"

    def __init__(self, vault: SolverStateVault):
        self.vault = vault

    def reconcile(self) -> bool:
        self.vault.maybe_snapshot()
        return False  # snapshots are not cluster progress
