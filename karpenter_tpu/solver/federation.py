"""Federated solver fleets: tenant routing, journal replication, cross-host
failover (ISSUE 18; SPEC.md "Federation semantics").

One process — one mesh, one journal, one TenantMux — caps aggregate
throughput and resident-state capacity at a single host. The federation
layer lifts that cap by composing EXISTING seams instead of inventing new
ones: each *host* runs today's SolverFleet/TenantMux stack behind the
submit/submit_fn SolveService surface, and this module adds exactly three
things on top:

- **Routing** (`HashRing` + `FederationRouter.route`): tenants
  consistent-hash to hosts, so adding/removing a host moves only ~1/N of
  the tenants (vnode ring — the classic bounded-disruption placement).
  A tenant's home host owns its queue, its arena residency namespace, and
  its journal cursor; `tenant_id=None` (un-federated local traffic) always
  routes to the self host, which is what keeps the knobs-off and
  single-host paths byte-identical.
- **Replication** (`JournalReplicator`): the ClusterJournal tail streams
  to peer-held replica buffers via a synchronous journal tap, objects
  deep-copied at event time (replication is a wire: the peer must see the
  event-time object, never a live reference). A host loss re-baselines the
  tenant on a peer from the replicated tail — journal-lag-bounded — rather
  than re-encoding the world.
- **Failover** (`FederationRouter.fail_host`): fencing a host removes it
  from the ring and requeues its outstanding facade tickets onto the
  survivors IN SUBMISSION ORDER. All of a tenant's outstanding work lived
  on its one home host, so per-tenant FIFO survives the move; facade
  tickets are first-wins, so a zombie host's late result can never
  double-act. This composes with (does not replace) the intra-host
  fence/requeue + vault-revive machinery: the fleet handles an OWNER loss
  inside a host, the router handles the HOST loss.

Hosts here are in-process service objects (tests), subprocess workers
behind pipes (bench's virtual 4-host soak, parallel/hostmesh.py), or — on
real deployments — whatever transport presents the SolveService surface.
"""

from __future__ import annotations

import copy
import hashlib
import inspect
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..metrics.registry import (
    FEDERATION_FAILOVERS,
    FEDERATION_HOSTS_HEALTHY,
    FEDERATION_REPLICATION_LAG,
    FEDERATION_TENANT_MOVES,
)
from .pipeline import DISRUPTION, PROVISIONING, SolveTicket


class FederationConfigError(ValueError):
    """Fail-closed federation configuration: bad host list, self host not a
    member, replication without a federation. Raised at construction so a
    typo'd deploy dies at boot, not at the first failover."""


class FederationMisroute(RuntimeError):
    """A submission routed to a host this process has no transport to (an
    unattached peer). Fail-closed: serving another host's tenant silently
    would fork its journal cursor and arena residency — the caller must
    fix placement or fence the peer."""


def parse_hosts(spec: str) -> List[str]:
    """Validate a `--federation-hosts` list: comma-separated, non-empty,
    unique host names. Raises FederationConfigError (fail-closed) on any
    malformed entry."""
    hosts = [h.strip() for h in (spec or "").split(",") if h.strip()]
    if not hosts:
        raise FederationConfigError(
            "federation host list is empty — pass host names as "
            "'h0,h1,...' or leave federation off"
        )
    if len(set(hosts)) != len(hosts):
        raise FederationConfigError(f"duplicate federation hosts in {spec!r}")
    return hosts


class HashRing:
    """Consistent-hash ring with virtual nodes: `route(key)` walks
    clockwise from sha1(key) to the next vnode. Stability contract (pinned
    by tests/test_federation.py): removing a host only re-homes keys that
    lived on it; adding a host steals ~1/N of the keyspace from the
    incumbents and moves nothing between surviving hosts."""

    def __init__(self, hosts, vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self._hosts: List[str] = []
        self._ring: List[tuple] = []  # sorted [(point, host)]
        for h in hosts:
            self.add(h)

    @staticmethod
    def _point(key: str) -> int:
        return int.from_bytes(
            hashlib.sha1(key.encode()).digest()[:8], "big"
        )

    @property
    def hosts(self) -> List[str]:
        return list(self._hosts)

    def add(self, host: str) -> None:
        if host in self._hosts:
            return
        self._hosts.append(host)
        for v in range(self.vnodes):
            self._ring.append((self._point(f"{host}#{v}"), host))
        self._ring.sort()

    def remove(self, host: str) -> None:
        if host not in self._hosts:
            return
        self._hosts.remove(host)
        self._ring = [(p, h) for p, h in self._ring if h != host]

    def route(self, key: str) -> str:
        if not self._ring:
            raise FederationConfigError("hash ring has no hosts")
        point = self._point(key)
        # binary search for the first vnode clockwise of `point`
        lo, hi = 0, len(self._ring)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._ring[mid][0] < point:
                lo = mid + 1
            else:
                hi = mid
        return self._ring[lo % len(self._ring)][1]


class _Outstanding:
    """One facade ticket's live routing record: enough to replay the
    submission verbatim on a survivor when its home host is fenced."""

    __slots__ = ("facade", "job", "tenant_id", "host", "requeued")

    def __init__(self, facade: SolveTicket, job: Callable, tenant_id, host):
        self.facade = facade
        self.job = job  # job(service) -> inner SolveTicket
        self.tenant_id = tenant_id
        self.host = host
        self.requeued = False


class JournalReplicator:
    """Replicates the ClusterJournal tail to peer hosts.

    Registered as a synchronous journal tap (state/cluster.py
    ClusterJournal.add_tap): every stamped event is deep-copied at event
    time and appended to each peer's bounded replica buffer. Peers
    acknowledge by draining (`drain_peer`); `lag()` is the seq distance
    between the journal head and the slowest peer's ack — the
    `karpenter_federation_journal_replication_lag` gauge.

    Consistency model (SPEC.md "Federation semantics"): the replica is a
    TAIL, not a base — a peer re-baselines by folding the tail onto its
    newest base snapshot (vault / store snapshot), exactly as the
    streaming model folds its own journal. A peer attached from the
    journal's birth holds the whole world (`rebuild_store` — the parity
    leg tests pin decision-identity through it)."""

    def __init__(self, journal, peers, maxlen: int = 4096,
                 clock=time.monotonic):
        if not peers:
            raise FederationConfigError(
                "journal replication needs at least one peer host"
            )
        self._journal = journal
        self._peers = list(peers)
        self._lock = threading.Lock()
        self.maxlen = max(1, int(maxlen))
        self._tails: Dict[str, deque] = {p: deque() for p in self._peers}
        base = journal.rev()
        self._acked: Dict[str, int] = {p: base for p in self._peers}
        self._head = base
        self.stats = {"replicated_events": 0, "overflows": 0}
        journal.add_tap(self._on_event)

    @property
    def peers(self) -> List[str]:
        return list(self._peers)

    def _on_event(self, ev) -> None:
        # deep-copy ONCE per event (the wire frame), shared by every peer
        # buffer — peers never mutate replica objects, they fold copies
        obj = copy.deepcopy(ev.obj)
        frame = type(ev)(ev.seq, ev.event, ev.kind, ev.key, obj)
        with self._lock:
            self._head = ev.seq
            self.stats["replicated_events"] += 1
            for p in self._peers:
                tail = self._tails[p]
                tail.append(frame)
                if len(tail) > self.maxlen:
                    tail.popleft()
                    self.stats["overflows"] += 1
        self._export()

    def drain_peer(self, peer: str) -> List:
        """The peer applies its replica tail: returns the buffered events
        in order and advances the peer's ack to the journal head."""
        with self._lock:
            tail = self._tails[peer]
            out = list(tail)
            tail.clear()
            self._acked[peer] = out[-1].seq if out else self._head
        self._export()
        return out

    def tail(self, peer: str) -> List:
        """Non-destructive view of a peer's replica buffer."""
        with self._lock:
            return list(self._tails[peer])

    def lag(self, peer: Optional[str] = None) -> int:
        with self._lock:
            if peer is not None:
                return max(0, self._head - self._acked[peer])
            return max(
                (max(0, self._head - a) for a in self._acked.values()),
                default=0,
            )

    def _export(self) -> None:
        FEDERATION_REPLICATION_LAG.set(float(self.lag()))
        for p in self._peers:
            FEDERATION_REPLICATION_LAG.set(float(self.lag(p)), peer=p)

    def rebuild_store(self, peer: str, store=None):
        """Fold a peer's replica tail into a store — the re-baseline leg a
        surviving host runs for an adopted tenant. With no base store the
        tail must cover the world (peer attached from journal birth)."""
        from ..controllers import store as st

        target = store if store is not None else st.Store()
        for ev in self.tail(peer):
            obj = copy.deepcopy(ev.obj)
            if ev.event == "DELETED":
                try:
                    target.delete(ev.kind, obj.meta.name, obj.meta.namespace)
                except Exception:  # noqa: BLE001 — delete of a never-seen key
                    pass
                continue
            try:
                if target.try_get(ev.kind, obj.meta.name,
                                  obj.meta.namespace) is None:
                    target.create(ev.kind, obj)
                else:
                    target.update(ev.kind, obj)
            except Exception:  # noqa: BLE001 — replica fold is best-effort
                pass
        return target


def _accepts_tenant_kw(fn) -> bool:
    try:
        return "tenant_id" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return True


class FederationRouter:
    """SolveService-compatible facade over a federation of host stacks.

    `submit`/`submit_fn` route by tenant (consistent hash), record an
    outstanding entry on the home host, and forward the inner ticket's
    resolution to a facade ticket. `fail_host` fences a host: ring
    removal + submission-ordered requeue of its outstanding entries onto
    the survivors (0 dropped by construction — every facade either already
    resolved or is resubmitted; first-wins delivery de-duplicates a zombie
    host's late result). `attach` wires a host name to a transport — the
    self host's local stack always, in-process peers in tests, pipe-backed
    workers in the bench soak."""

    def __init__(self, hosts, self_host: str, clock=time.monotonic,
                 replicator: Optional[JournalReplicator] = None,
                 own_services: bool = False):
        if isinstance(hosts, str):
            hosts = parse_hosts(hosts)
        else:
            hosts = list(hosts)
            if not hosts:
                raise FederationConfigError("federation host list is empty")
        if self_host not in hosts:
            raise FederationConfigError(
                f"self host {self_host!r} is not in the federation "
                f"host list {hosts}"
            )
        self.all_hosts = list(hosts)
        self.self_host = self_host
        self.clock = clock
        self.replicator = replicator
        self._own = bool(own_services)
        self._ring = HashRing(hosts)
        self._failed: set = set()
        self._services: Dict[str, object] = {}
        self._svc_tenant_kw: Dict[str, tuple] = {}
        self._outstanding: Dict[str, deque] = {h: deque() for h in hosts}
        self._placement: Dict[str, str] = {}
        self._lock = threading.Lock()
        self.stats: Dict[str, int] = {
            "routes": 0,
            "requeued": 0,
            "dropped": 0,
            "cross_host_failovers": 0,
            "tenant_moves": 0,
            "misroutes": 0,
        }
        self._export()

    # -- wiring ---------------------------------------------------------------

    def attach(self, host: str, service) -> None:
        if host not in self.all_hosts:
            raise FederationConfigError(
                f"cannot attach unknown host {host!r}"
            )
        with self._lock:
            self._services[host] = service
            self._svc_tenant_kw[host] = (
                _accepts_tenant_kw(service.submit),
                _accepts_tenant_kw(service.submit_fn),
            )

    def healthy_hosts(self) -> List[str]:
        with self._lock:
            return [h for h in self.all_hosts if h not in self._failed]

    # -- routing --------------------------------------------------------------

    def route(self, tenant_id: Optional[str]) -> str:
        """The home host for a tenant. `None` — un-federated local traffic
        (the operator's own controllers) — is ALWAYS the self host: the
        federation never re-homes work that was never a tenant's."""
        with self._lock:
            return self._route_locked(tenant_id)

    def _route_locked(self, tenant_id: Optional[str]) -> str:
        self.stats["routes"] += 1
        if tenant_id is None:
            return self.self_host
        host = self._ring.route(tenant_id)
        prev = self._placement.get(tenant_id)
        if prev is not None and prev != host:
            self.stats["tenant_moves"] += 1
            FEDERATION_TENANT_MOVES.inc(tenant=tenant_id)
        self._placement[tenant_id] = host
        return host

    # -- submission seam ------------------------------------------------------

    def submit(self, inp, kind: str = PROVISIONING, rev=None,
               tenant_id: Optional[str] = None) -> SolveTicket:
        if tenant_id is None:
            tenant_id = getattr(inp, "tenant_id", None)
        facade = SolveTicket(kind, rev=rev, tenant_id=tenant_id)

        def job(svc, host):
            if self._svc_tenant_kw[host][0]:
                return svc.submit(inp, kind, rev=rev, tenant_id=tenant_id)
            return svc.submit(inp, kind, rev=rev)

        self._dispatch(facade, job, tenant_id)
        return facade

    def submit_fn(self, dispatch_fn: Callable, kind: str = DISRUPTION,
                  tenant_id: Optional[str] = None) -> SolveTicket:
        facade = SolveTicket(kind, tenant_id=tenant_id)

        def job(svc, host):
            if self._svc_tenant_kw[host][1]:
                return svc.submit_fn(dispatch_fn, kind, tenant_id=tenant_id)
            return svc.submit_fn(dispatch_fn, kind)

        self._dispatch(facade, job, tenant_id)
        return facade

    def _dispatch(self, facade: SolveTicket, job, tenant_id,
                  requeue: bool = False) -> None:
        with self._lock:
            host = self._route_locked(tenant_id)
            svc = self._services.get(host)
        if svc is None:
            self.stats["misroutes"] += 1
            facade._deliver(error=FederationMisroute(
                f"tenant {tenant_id!r} is homed on {host!r}, which has no "
                f"attached transport here"
            ))
            return
        rec = _Outstanding(facade, job, tenant_id, host)
        try:
            inner = job(svc, host)
        except Exception as e:  # noqa: BLE001 — submission-time host loss
            if not requeue and self._is_host_loss(e):
                # the pipe/service died under the submit: fence the host
                # and re-dispatch THIS facade with the survivors' ring
                self.fail_host(host, reason=f"submit: {e}")
                if not facade.done():
                    self._dispatch(facade, job, tenant_id, requeue=True)
                return
            facade._deliver(error=e)
            return
        with self._lock:
            if rec.host in self._failed:
                # fenced between route and submit: the requeue pass missed
                # this record, replay it ourselves (first-wins dedups)
                rec.requeued = True
            else:
                self._outstanding[rec.host].append(rec)
        inner.on_done(lambda t, r=rec: self._on_inner_done(r, t))
        if rec.requeued and not facade.done():
            self._dispatch(facade, job, tenant_id, requeue=True)

    @staticmethod
    def _is_host_loss(e: BaseException) -> bool:
        """Submission failures that mean THE HOST is gone (fence + requeue)
        rather than this request being bad (deliver the error)."""
        from ..parallel.hostmesh import WorkerDead
        from .pipeline import ServiceStopped

        return isinstance(e, (WorkerDead, ServiceStopped, BrokenPipeError,
                              ConnectionError, OSError))

    def _on_inner_done(self, rec: _Outstanding, inner: SolveTicket) -> None:
        err = inner.error()
        with self._lock:
            host_down = rec.host in self._failed or rec.requeued
            try:
                self._outstanding[rec.host].remove(rec)
            except ValueError:
                pass
        if err is not None and host_down:
            # a fenced host's error resolution (ServiceStopped, broken
            # pipe): the requeue pass owns this facade now — swallowing
            # here is what makes failover drop-free instead of error-free
            return
        if err is not None and self._is_host_loss(err):
            # the host died UNDER this in-flight solve: re-insert the record
            # at the head (it was the oldest outstanding — FIFO) and fence,
            # which requeues it together with everything queued behind it
            with self._lock:
                if rec.host not in self._failed:
                    self._outstanding[rec.host].appendleft(rec)
            self.fail_host(rec.host, reason=f"inner: {err}")
            if not rec.requeued and not rec.facade.done():
                # fencing refused (last healthy host) — surface the loss
                with self._lock:
                    try:
                        self._outstanding[rec.host].remove(rec)
                    except ValueError:
                        pass
                rec.facade._deliver(error=err)
            return
        if err is not None:
            rec.facade._deliver(error=err)
        else:
            try:
                rec.facade._deliver(result=inner.result(0))
            except BaseException as e:  # noqa: BLE001 — late error surface
                rec.facade._deliver(error=e)

    # -- failover -------------------------------------------------------------

    def fail_host(self, host: str, reason: str = "") -> int:
        """Fence a host: remove it from the ring and requeue its
        outstanding submissions, IN ORDER, onto the survivors. Returns the
        number of requeued entries. Idempotent per host."""
        with self._lock:
            if host in self._failed or host not in self.all_hosts:
                return 0
            if len(self._failed) + 1 >= len(self.all_hosts):
                # fencing the LAST healthy host would strand every facade
                # with no requeue target — keep serving on it (mirrors the
                # fleet's zero-healthy revive posture)
                return 0
            self._failed.add(host)
            self._ring.remove(host)
            pending = list(self._outstanding[host])
            self._outstanding[host].clear()
            for rec in pending:
                rec.requeued = True
            self.stats["cross_host_failovers"] += 1
        FEDERATION_FAILOVERS.inc(host=host)
        for rec in pending:
            if rec.facade.done():
                continue
            self.stats["requeued"] += 1
            self._dispatch(rec.facade, rec.job, rec.tenant_id, requeue=True)
        self._export()
        return len(pending)

    def restore_host(self, host: str) -> None:
        """Unfence a recovered host: back into the ring; its former tenants
        re-home on their next route (counted as tenant moves)."""
        with self._lock:
            if host not in self._failed:
                return
            self._failed.discard(host)
            self._ring.add(host)
        self._export()

    # -- introspection / service surface --------------------------------------

    def _export(self) -> None:
        healthy = self.healthy_hosts()
        FEDERATION_HOSTS_HEALTHY.set(float(len(healthy)))
        for h in self.all_hosts:
            FEDERATION_HOSTS_HEALTHY.set(
                1.0 if h in healthy else 0.0, host=h
            )

    def federation_stats(self) -> Dict[str, object]:
        with self._lock:
            out = dict(self.stats)
            out["hosts"] = len(self.all_hosts)
            out["hosts_healthy"] = len(
                [h for h in self.all_hosts if h not in self._failed]
            )
            out["outstanding"] = sum(
                len(q) for q in self._outstanding.values()
            )
        if self.replicator is not None:
            out["replication_lag"] = self.replicator.lag()
        return out

    def health(self) -> Dict[str, object]:
        """Telemetry-provider payload for /healthz (mirrors streaming's):
        degraded when any host is fenced."""
        s = self.federation_stats()
        s["state"] = "ok" if s["hosts_healthy"] == s["hosts"] else "warn"
        return s

    def unresolved(self) -> int:
        with self._lock:
            return sum(
                0 if r.facade.done() else 1
                for q in self._outstanding.values() for r in q
            )

    def queue_depth(self) -> int:
        with self._lock:
            svcs = list(self._services.items())
            failed = set(self._failed)
        depth = 0
        for host, svc in svcs:
            if host in failed:
                continue
            try:
                depth += int(svc.queue_depth())
            except Exception:  # noqa: BLE001 — a dying peer reads as empty
                pass
        return depth

    def occupancy(self) -> float:
        svc = self._services.get(self.self_host)
        try:
            return float(svc.occupancy()) if svc is not None else 0.0
        except Exception:  # noqa: BLE001
            return 0.0

    def close(self) -> None:
        with self._lock:
            svcs = list(self._services.values())
            self._services.clear()
        if self._own:
            for svc in svcs:
                try:
                    svc.close()
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass

    def __getattr__(self, name):
        # introspection passthrough to the SELF host's stack (stats,
        # solver, resume_stats, ...) — mirrors TenantView's posture; the
        # routing surface above is always handled by the router itself
        svc = self._services.get(self.self_host)
        if svc is None:
            raise AttributeError(name)
        return getattr(svc, name)
