"""Pipelined solve service: the single owner of the device solve seam.

Every `Solver.solve()` in the control plane is a blocking round-trip: host
encode, device compute, link transfer, host decode — serialized per caller.
The `AsyncSolve` seam (backend.py) already splits dispatch from decode, but
each control loop still waits out its own round-trip before the next solve's
encode starts. `SolveService` turns the seam into a three-stage pipeline:

        dispatcher thread            device / link           decoder thread
    ┌──────────────────────┐   ┌─────────────────────┐   ┌─────────────────┐
    │ encode + dispatch N+1│ ∥ │ compute + d2h  N    │ ∥ │ decode      N−1 │
    └──────────────────────┘   └─────────────────────┘   └─────────────────┘

Host encode of request N+1 overlaps device compute of request N overlaps
host decode of request N−1. Controllers submit() and block on a
`SolveTicket`; the service serializes actual device ownership through one
dispatcher thread, so concurrent submitters never race the arena or the
encode cache.

Coalescing: provisioning-class requests are whole-cluster snapshots — a
newer snapshot strictly covers any older one still waiting in the queue
(`SolverInput.state_rev`, the encode-cache revision stamp, records which
snapshot each request carries). Submitting a new provisioning request
supersedes every provisioning request still QUEUED (not yet dispatched):
the stale snapshot never runs and its ticket raises `Superseded`, so a
caller can never act on a superseded snapshot. Requests already dispatched
are never cancelled — their results deliver normally.

Fairness: the dispatcher round-robins between the provisioning and
disruption classes, so a disruption controller probing candidate subsets
cannot starve pending-pod provisioning (or vice versa).

Resilience composes per-request, not per-dispatch: hand the service a
`ResilientSolver` and each submitted request passes through the breaker /
deadline / invariant gate exactly once — the deadline window opens when the
service dispatches (queue wait is not solve time), and overflow-retry
re-dispatches inside TPUSolver stay inside that one request's window. A
dead device mid-pipeline therefore drains in-flight requests onto the
fallback ladder individually; none are lost, none run twice.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from ..metrics.registry import (
    SOLVE_COALESCED,
    SOLVE_PIPELINE_DEPTH,
    SOLVE_PIPELINE_OCCUPANCY,
)
from ..obs import telemetry as obstelemetry
from ..obs import trace as obstrace

PROVISIONING = "provisioning"
DISRUPTION = "disruption"


class Superseded(Exception):
    """The request coalesced away: a newer cluster-state revision was
    submitted before this one dispatched. The newer request's solve covers
    the cluster; the caller must NOT act on this stale snapshot — defer to
    the next tick (the superseding ticket is available as `.by`)."""

    def __init__(self, by: Optional["SolveTicket"] = None):
        super().__init__("solve request superseded by a newer cluster snapshot")
        self.by = by


class ServiceStopped(Exception):
    """The service was stopped before this request could run (terminal:
    the ticket resolves with this error rather than stranding a waiter)."""


class SolveTicket:
    """Caller-side handle for a submitted request. result() blocks until the
    decode stage delivers (or re-raises the request's failure).

    Delivery is first-wins: once resolved, later deliveries are ignored —
    so a force-resolve racing a late decode can never overwrite a real
    result, and a requeued request can never double-act."""

    def __init__(self, kind: str, rev=None, tenant_id: Optional[str] = None):
        self.kind = kind
        self.rev = rev
        # tenancy attribution (solver/tenancy.py): scopes provisioning
        # coalescing (only same-tenant snapshots supersede each other) and
        # rides into the queue span / flight dumps. None = single-tenant.
        self.tenant_id = tenant_id
        # tracing correlation token, minted (or adopted from the submitting
        # layer's trace) at ticket creation; None when tracing is off
        self.solve_id: Optional[str] = None
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._result = None
        self._error: Optional[BaseException] = None
        self._callbacks = []

    def _deliver(self, result=None, error: Optional[BaseException] = None) -> bool:
        """Resolve the ticket. Returns True if THIS call delivered, False if
        the ticket was already resolved (the late delivery is dropped)."""
        with self._lock:
            if self._event.is_set():
                return False
            self._result = result
            self._error = error
            callbacks, self._callbacks = self._callbacks, []
            self._event.set()
        for cb in callbacks:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — observer must not break delivery
                pass
        return True

    def on_done(self, cb: Callable[["SolveTicket"], None]) -> None:
        """Invoke cb(ticket) at delivery (immediately if already resolved).
        Used by the fleet layer to forward owner-ticket results without a
        watcher thread per request."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        cb(self)

    def done(self) -> bool:
        return self._event.is_set()

    def superseded(self) -> bool:
        return isinstance(self._error, Superseded)

    def error(self) -> Optional[BaseException]:
        """The resolution error, if any (None while unresolved / on success)."""
        return self._error

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("solve ticket not resolved in time")
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    __slots__ = ("ticket", "inp", "fn", "rev", "trace", "queue_span", "cohort")

    def __init__(self, ticket: Optional[SolveTicket], inp=None, fn=None,
                 rev=None, trace=None, queue_span=None, cohort=None):
        self.ticket = ticket
        self.inp = inp
        self.fn = fn  # generic device work: fn() dispatches, returns finish()
        self.rev = rev
        self.trace = trace  # obs.trace.Trace carried across both workers
        self.queue_span = queue_span  # started at submit, ended at dispatch pop
        # fused cohort unit (submit_cohort): list of member _Requests that
        # dispatch as ONE device launch; the unit itself has ticket=None and
        # its members' tickets resolve individually at decode
        self.cohort = cohort


def _mint_trace(ticket: SolveTicket, kind: str):
    """Adopt the submitting thread's trace (fleet/provisioner minted it and
    owns completion) or mint one owned by this service: its completion is
    tied to ticket delivery. Returns (trace, queue_span)."""
    tr, owned = obstrace.adopt_or_begin(kind)
    if tr is None:
        return None, None
    ticket.solve_id = tr.solve_id
    obstrace.set_tenant(tr, ticket.tenant_id)
    if owned:
        ticket.on_done(
            lambda t, _tr=tr: obstrace.finish(_tr, obstrace.status_of(t.error()))
        )
    # cross-thread span: opens on the submitting thread, closed by the
    # dispatcher when it pops the request — queue wait is its own stage.
    # The TICKET's kind labels it (an adopted trace may carry a different
    # kind — e.g. a disruption probe fn under a provisioning trace), so
    # submit_fn work is attributable in /debug/trace; tenant rides along.
    qspan = tr.start_span("pipeline.queue", parent=tr.root)
    qspan.set(kind=ticket.kind)
    if ticket.tenant_id is not None:
        qspan.set(tenant_id=ticket.tenant_id)
    return tr, qspan


class SolveService:
    """Owns the device: all solve dispatches in the process serialize
    through this service's dispatcher thread (construction starts the
    worker threads; they are daemons and idle at zero cost)."""

    def __init__(self, solver, depth: int = 2, clock=time.monotonic):
        self.solver = solver
        self.depth = max(1, int(depth))
        self.clock = clock
        self._cv = threading.Condition()
        self._pending: Dict[str, deque] = {PROVISIONING: deque(), DISRUPTION: deque()}
        self._inflight: deque = deque()  # (_Request, finish_fn)
        self._active: set = set()  # tickets popped from pending, unresolved
        self._last_kind = DISRUPTION  # provisioning gets the first slot
        self._stopped = False
        self.stats: Dict[str, int] = {
            "submitted": 0,
            "dispatched": 0,
            "completed": 0,
            "failed": 0,
            "coalesced": 0,
        }
        # occupancy: wall-time fraction with >=1 request in flight (device or
        # link busy) since construction — 1.0 means the device never idled
        # between solves
        self._started_at = clock()
        self._busy_since: Optional[float] = None
        self._busy_s = 0.0
        self._decoding = 0  # requests popped from _inflight, still in finish()
        self._dispatching = 0  # requests popped from _pending, not yet in flight
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="solve-dispatch"
        )
        self._decoder = threading.Thread(
            target=self._decode_loop, daemon=True, name="solve-decode"
        )
        self._dispatcher.start()
        self._decoder.start()

    # -- submission ----------------------------------------------------------

    def submit(self, inp, kind: str = PROVISIONING, rev=None,
               tenant_id: Optional[str] = None) -> SolveTicket:
        """Queue a SolverInput. Provisioning-class submits coalesce: every
        provisioning request still queued (undispatched) FOR THE SAME
        TENANT is superseded — its ticket raises Superseded — because this
        newer snapshot covers it. Tenant A's snapshot says nothing about
        B's cluster, so cross-tenant requests are never coalesced; with
        tenancy off every tenant_id is None and the behavior is exactly
        the pre-tenancy one. `rev` is the snapshot's encode-cache revision
        stamp (SolverInput.state_rev), recorded for observability."""
        if rev is None:
            rev = getattr(inp, "state_rev", None)
        if tenant_id is None:
            tenant_id = getattr(inp, "tenant_id", None)
        ticket = SolveTicket(kind, rev=rev, tenant_id=tenant_id)
        with self._cv:
            if self._stopped:
                raise ServiceStopped("solve service is closed")
            # mint AFTER the stopped check: a rejected submit must not leak
            # an owned trace into the active set (its ticket never delivers)
            tr, qspan = _mint_trace(ticket, kind)
            if kind == PROVISIONING:
                self._coalesce_locked(tenant_id, ticket)
            self._pending[kind].append(
                _Request(ticket, inp=inp, rev=rev, trace=tr, queue_span=qspan)
            )
            self.stats["submitted"] += 1
            self._cv.notify_all()
        return ticket

    def submit_fn(self, dispatch_fn: Callable, kind: str = DISRUPTION,
                  tenant_id: Optional[str] = None) -> SolveTicket:
        """Queue generic device work: dispatch_fn() runs on the dispatcher
        thread (host prep + device dispatch) and returns a finish callable;
        finish() runs on the decoder thread and its return value resolves
        the ticket. Used by the disruption controller's batched speculative
        probes so they share the device queue (and its fairness) with
        ordinary solves. Never coalesced."""
        ticket = SolveTicket(kind, tenant_id=tenant_id)
        with self._cv:
            if self._stopped:
                raise ServiceStopped("solve service is closed")
            tr, qspan = _mint_trace(ticket, kind)
            self._pending[kind].append(
                _Request(ticket, fn=dispatch_fn, trace=tr, queue_span=qspan)
            )
            self.stats["submitted"] += 1
            self._cv.notify_all()
        return ticket

    def submit_cohort(self, members) -> list:
        """Queue a fused cohort: ONE device dispatch serves every member
        (the tenant mux gathered them under the WFQ prefix rule; the
        backend's solve_cohort_async fuses the launch — SPEC.md "Cohort
        semantics"). Each member dict carries inp / kind / rev / tenant_id /
        trace; one SolveTicket per member is returned, in order, and each
        resolves individually at decode. Same-tenant provisioning
        coalescing applies per member — a member's newer snapshot
        supersedes queued requests exactly as a solo submit would,
        including members of cohort units still queued."""
        if not members:
            return []
        tickets: list = []
        with self._cv:
            if self._stopped:
                raise ServiceStopped("solve service is closed")
            reqs: list = []
            for m in members:
                inp = m["inp"]
                kind = m.get("kind", PROVISIONING)
                rev = m.get("rev")
                if rev is None:
                    rev = getattr(inp, "state_rev", None)
                tenant_id = m.get("tenant_id")
                if tenant_id is None:
                    tenant_id = getattr(inp, "tenant_id", None)
                ticket = SolveTicket(kind, rev=rev, tenant_id=tenant_id)
                # adopt each member's own trace (minted by the mux), not the
                # submitting thread's ambient one — per-member span trees
                # must root and close independently of the fused dispatch
                with obstrace.attached(m.get("trace")):
                    tr, qspan = _mint_trace(ticket, kind)
                if kind == PROVISIONING:
                    self._coalesce_locked(tenant_id, ticket)
                reqs.append(
                    _Request(ticket, inp=inp, rev=rev, trace=tr,
                             queue_span=qspan)
                )
                self.stats["submitted"] += 1
                tickets.append(ticket)
            self._pending[reqs[0].ticket.kind].append(
                _Request(None, trace=reqs[0].trace, cohort=reqs)
            )
            self._cv.notify_all()
        return tickets

    def _supersede_locked(self, stale: _Request, ticket: SolveTicket) -> None:
        self.stats["coalesced"] += 1
        SOLVE_COALESCED.inc(kind=PROVISIONING)
        if stale.queue_span is not None:
            stale.queue_span.end("superseded")
        stale.ticket._deliver(error=Superseded(by=ticket))

    def _coalesce_locked(self, tenant_id, ticket: SolveTicket) -> None:
        """Supersede every provisioning request still queued for this
        tenant — plain requests AND members inside queued cohort units (a
        unit emptied of all its members is dropped from the queue whole)."""
        q = self._pending[PROVISIONING]
        keep: deque = deque()
        while q:
            stale = q.popleft()
            if stale.cohort is not None:
                live = []
                for m in stale.cohort:
                    if m.ticket.tenant_id != tenant_id:
                        live.append(m)
                        continue
                    self._supersede_locked(m, ticket)
                stale.cohort = live
                if live:
                    keep.append(stale)
                continue
            if stale.ticket.tenant_id != tenant_id:
                keep.append(stale)
                continue
            self._supersede_locked(stale, ticket)
        q.extend(keep)

    # -- introspection -------------------------------------------------------

    def occupancy(self) -> float:
        with self._cv:
            busy = self._busy_s
            if self._busy_since is not None:
                busy += self.clock() - self._busy_since
            wall = self.clock() - self._started_at
        return (busy / wall) if wall > 0 else 0.0

    def queue_depth(self) -> int:
        with self._cv:
            return sum(len(q) for q in self._pending.values())

    def resume_stats(self) -> Dict[str, float]:
        """Checkpoint-resume counters of the owned backend (zeros when the
        backend has none). The service serializes every device dispatch
        through one solver/arena, so a coalesced provisioning snapshot
        naturally resumes from the checkpoint its superseded predecessor's
        dispatch left device-resident — no extra wiring per request."""
        inner = self.solver
        # unwrap the resilience layer's delegation chain if present
        stats = getattr(inner, "stats", None) or {}
        return {
            "resume_solves": int(stats.get("resume_solves", 0)),
            "resume_runs_skipped": int(stats.get("resume_runs_skipped", 0)),
            "resume_hit_rate": float(getattr(inner, "resume_hit_rate", 0.0)),
        }

    def shard_stats(self) -> Dict[str, float]:
        """Mesh-sharded solve counters of the owned backend (zeros when the
        backend has none, or shards are off) — the ISSUE 7 bench keys. The
        per-device upload figure divides the partitioned h2d bytes by the
        mesh width the backend actually built (SPEC.md "Sharding
        semantics")."""
        inner = self.solver
        stats = getattr(inner, "stats", None) or {}
        ledger = getattr(inner, "ledger", None)
        mesh = None
        shard_mesh = getattr(inner, "_shard_mesh", None)
        if callable(shard_mesh):
            try:
                mesh = shard_mesh()
            except Exception:
                mesh = None
        n_dev = int(mesh.devices.size) if mesh is not None else 1
        per_dev = 0.0
        fn = getattr(ledger, "shard_upload_bytes_per_device", None)
        if callable(fn):
            per_dev = float(fn(n_dev))
        return {
            "mesh_devices": n_dev if mesh is not None else 0,
            "sharded_solves": int(stats.get("sharded_solves", 0)),
            "shard_fixup_runs": int(stats.get("shard_fixup_runs", 0)),
            "sharded_fallbacks": int(stats.get("sharded_fallbacks", 0)),
            "shard_resume_solves": int(stats.get("shard_resume_solves", 0)),
            "shard_resume_runs_skipped": int(
                stats.get("shard_resume_runs_skipped", 0)
            ),
            "shard_upload_bytes_per_device": per_dev,
        }

    def decode_stats(self) -> Dict[str, float]:
        """On-device decode + relax-ladder counters of the owned backend
        (zeros when the backend has none) — the ISSUE 6 bench keys."""
        inner = self.solver
        stats = getattr(inner, "stats", None) or {}
        ledger = getattr(inner, "ledger", None)
        return {
            "decode_bytes_per_solve": float(
                getattr(ledger, "decode_bytes_per_solve", 0.0) or 0.0
            ),
            "relax_dispatches_per_solve": float(
                stats.get("relax_dispatches", 0)
            ),
            "ladder_rungs_used": int(stats.get("ladder_rungs_used", 0)),
            "wide_refetches": int(stats.get("wide_refetches", 0)),
        }

    def streaming_stats(self) -> Dict[str, float]:
        """Streaming event-stage counters of the owned backend (zeros when
        the backend has none, or `--solver-streaming` is off) — the ISSUE 13
        bench keys. Hits are solves whose run tables reached the device as
        an edit-triplet scatter (arena.apply_run_events) instead of a packed
        re-upload; misses declined and paid adopt's normal path."""
        inner = self.solver
        stats = getattr(inner, "stats", None) or {}
        arena = getattr(inner, "arena", None)
        astats = getattr(arena, "stats", None) or {}
        return {
            "event_stage_hits": int(stats.get("event_stage_hits", 0)),
            "event_stage_misses": int(stats.get("event_stage_misses", 0)),
            "event_batches": int(astats.get("event_batches", 0)),
            "event_edits": int(astats.get("event_edits", 0)),
        }

    def slo_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-stage SLO burn rates (obs/slo.py) as seen through this
        pipeline's span feed — every solve it dispatches lands a
        pipeline.queue / backend.dispatch / solve observation when its
        trace finishes, so this surface is the bench/test view of the
        operator's /healthz slo object."""
        from ..obs import slo as obsslo

        return obsslo.burn_rates()

    def close(self) -> None:
        """Stop accepting work; fail queued (undispatched) requests with
        ServiceStopped; let in-flight requests drain (up to 30s)."""
        self.stop(drain_s=30.0)

    def stop(self, drain_s: float = 30.0) -> None:
        """Terminal stop: no ticket issued by this service is ever left
        unresolved. Queued (undispatched) requests fail with ServiceStopped
        immediately; in-flight requests get `drain_s` seconds to deliver
        their real result; anything still unresolved after the drain window
        (a wedged dispatch or decode) is force-resolved with ServiceStopped.
        First-wins delivery makes the force-resolve safe against a late
        decode racing it — whichever lands first is the resolution."""
        with self._cv:
            self._stopped = True
            for q in self._pending.values():
                while q:
                    req = q.popleft()
                    for m in (req.cohort if req.cohort is not None else (req,)):
                        if m.queue_span is not None:
                            m.queue_span.end("stopped")
                        if m.ticket._deliver(error=ServiceStopped(
                            "solve service stopped before this request"
                            " dispatched"
                        )):
                            self.stats["failed"] += 1
            self._cv.notify_all()
        for t in (self._dispatcher, self._decoder):
            t.join(timeout=drain_s)
        with self._cv:
            stranded = [tk for tk in self._active if not tk.done()]
            self._active.clear()
        for tk in stranded:
            if tk._deliver(error=ServiceStopped(
                "solve service stopped while this request was in flight"
            )):
                with self._cv:
                    self.stats["failed"] += 1

    # -- pipeline stages -----------------------------------------------------

    def _next_request_locked(self) -> Optional[_Request]:
        order = (
            (DISRUPTION, PROVISIONING)
            if self._last_kind == PROVISIONING
            else (PROVISIONING, DISRUPTION)
        )
        for kind in order:
            if self._pending[kind]:
                self._last_kind = kind
                return self._pending[kind].popleft()
        return None

    def _mark_busy_locked(self) -> None:
        if self._busy_since is None:
            self._busy_since = self.clock()

    def _mark_idle_locked(self) -> None:
        if self._busy_since is not None and not self._inflight and not self._decoding:
            self._busy_s += self.clock() - self._busy_since
            self._busy_since = None
        SOLVE_PIPELINE_OCCUPANCY.set(self._occupancy_locked())

    def _occupancy_locked(self) -> float:
        busy = self._busy_s
        if self._busy_since is not None:
            busy += self.clock() - self._busy_since
        wall = self.clock() - self._started_at
        return (busy / wall) if wall > 0 else 0.0

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                while not self._stopped and (
                    len(self._inflight) >= self.depth
                    or self._next_peek_locked() is None
                ):
                    self._cv.wait()
                if self._stopped and self._next_peek_locked() is None:
                    return
                req = self._next_request_locked()
                self._dispatching += 1
                if req.cohort is not None:
                    for m in req.cohort:
                        self._active.add(m.ticket)
                else:
                    self._active.add(req.ticket)
            for m in (req.cohort if req.cohort is not None else (req,)):
                if m.queue_span is not None:
                    m.queue_span.end()
            # encode + dispatch OUTSIDE the lock: this is the stage-1 host
            # work that overlaps stage-2 device compute and stage-3 decode
            try:
                if req.cohort is not None:
                    finish = self._dispatch_cohort(req)
                else:
                    with obstrace.attached(req.trace), \
                            obstrace.span("pipeline.dispatch"):
                        if req.fn is not None:
                            finish = req.fn()
                        else:
                            solve_async = getattr(
                                self.solver, "solve_async", None
                            )
                            if solve_async is not None:
                                finish = solve_async(req.inp).result
                            else:
                                # backend without an async seam (reference
                                # oracle): the whole solve runs at decode,
                                # stage overlap degrades gracefully to FIFO
                                inp = req.inp
                                finish = lambda _inp=inp: self.solver.solve(_inp)
            except BaseException as e:  # noqa: BLE001 — delivered to caller
                members = req.cohort if req.cohort is not None else (req,)
                with self._cv:
                    self.stats["failed"] += len(members)
                    self._dispatching -= 1
                    for m in members:
                        self._active.discard(m.ticket)
                    self._cv.notify_all()
                for m in members:
                    m.ticket._deliver(error=e)
                continue
            with self._cv:
                self.stats["dispatched"] += 1
                self._dispatching -= 1
                self._inflight.append((req, finish))
                self._mark_busy_locked()
                SOLVE_PIPELINE_DEPTH.set(len(self._inflight))
                self._cv.notify_all()
            # health-plane ring sample (obs/telemetry.py): the dispatcher
            # is the one thread guaranteed to run while solves flow, so it
            # carries the throttled sampler (off the lock; never raises)
            obstelemetry.maybe_sample()

    def _dispatch_cohort(self, unit: _Request):
        """Stage-1 for a fused unit: one solve_cohort_async call covers
        every member; the returned finish() yields member-aligned outcomes
        (result or exception). A backend without the cohort seam degrades
        to per-member solo dispatches that still share this one pipeline
        slot — correctness is identical, only the fusion win is lost."""
        members = unit.cohort
        inps = [m.inp for m in members]
        traces = [m.trace for m in members]
        with obstrace.attached(unit.trace), obstrace.span("pipeline.dispatch"):
            obstrace.annotate(cohort=len(members))
            sc = getattr(self.solver, "solve_cohort_async", None)
            if sc is not None:
                return sc(inps, traces=traces)
        handles: list = []
        solve_async = getattr(self.solver, "solve_async", None)
        for m in members:
            with obstrace.attached(m.trace), obstrace.span("pipeline.dispatch"):
                try:
                    if solve_async is not None:
                        handles.append(solve_async(m.inp).result)
                    else:
                        handles.append(lambda _inp=m.inp: self.solver.solve(_inp))
                except Exception as e:  # noqa: BLE001 — per-member outcome
                    handles.append(e)

        def finish():
            out: list = []
            for m, h in zip(members, handles):
                if isinstance(h, BaseException):
                    out.append(h)
                    continue
                try:
                    with obstrace.attached(m.trace):
                        out.append(h())
                except Exception as e:  # noqa: BLE001 — per-member outcome
                    out.append(e)
            return out

        return finish

    def _next_peek_locked(self) -> Optional[str]:
        for kind in (PROVISIONING, DISRUPTION):
            if self._pending[kind]:
                return kind
        return None

    def _decode_loop(self) -> None:
        while True:
            with self._cv:
                while not self._inflight and not (
                    self._stopped
                    and not self._dispatching
                    and self._next_peek_locked() is None
                ):
                    self._cv.wait()
                if not self._inflight:
                    return  # stopped, nothing left to drain
                req, finish = self._inflight.popleft()
                self._decoding += 1
                SOLVE_PIPELINE_DEPTH.set(len(self._inflight))
                self._cv.notify_all()  # a dispatch slot just freed
            if req.cohort is not None:
                self._decode_cohort(req, finish)
                continue
            try:
                with obstrace.attached(req.trace), \
                        obstrace.span("pipeline.decode"):
                    result = finish()
            except BaseException as e:  # noqa: BLE001 — delivered to caller
                with self._cv:
                    self.stats["failed"] += 1
                req.ticket._deliver(error=e)
            else:
                with self._cv:
                    self.stats["completed"] += 1
                req.ticket._deliver(result=result)
            with self._cv:
                self._decoding -= 1
                self._active.discard(req.ticket)
                self._mark_idle_locked()
                self._cv.notify_all()

    def _decode_cohort(self, req: _Request, finish) -> None:
        """Stage-3 for a fused unit: finish() returns member-aligned
        outcomes; each member's ticket resolves individually (a member's
        failure — poison replay exhausted, decode fault — never taints its
        co-members' results)."""
        members = req.cohort
        try:
            with obstrace.attached(req.trace), \
                    obstrace.span("pipeline.decode"):
                outcomes = finish()
        except BaseException as e:  # noqa: BLE001 — delivered to callers
            outcomes = [e] * len(members)
        if not isinstance(outcomes, (list, tuple)) \
                or len(outcomes) != len(members):
            err = RuntimeError("cohort finish returned misaligned outcomes")
            outcomes = [err] * len(members)
        for m, oc in zip(members, outcomes):
            if isinstance(oc, BaseException):
                with self._cv:
                    self.stats["failed"] += 1
                m.ticket._deliver(error=oc)
            else:
                with self._cv:
                    self.stats["completed"] += 1
                m.ticket._deliver(result=oc)
        with self._cv:
            self._decoding -= 1
            for m in members:
                self._active.discard(m.ticket)
            self._mark_idle_locked()
            self._cv.notify_all()
