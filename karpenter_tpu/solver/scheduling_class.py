"""Scheduling classes: priority, preemption, and gang scheduling.

The subsystem that makes `pod.priority` and the gang labels
(api/wellknown.py GANG_*) mean something end to end:

- **Ordering** lives in the canonical sort (provisioning/scheduler.py
  ffd_sort_with_sigs): priority-major, gang-contiguous — `(priority desc,
  gang_id, existing FFD key)` — shared by every backend, so ordering parity
  is automatic and the base kernels stay class-blind.
- **Atomic gangs** and **preemption** are post-scan passes orchestrated here
  around ANY inner `Solver`. The decision math runs through a *planner* with
  three bit-identical implementations — the python oracle in this module,
  the numpy host mirror (native.gang_commit_host / preemption_plan_host),
  and the jitted device kernels (tpu/ffd.py gang_commit / preemption_plan)
  — selected by the concrete backend at the bottom of the wrapper chain.

Gang rollback semantics: a sequential deterministic scan means "roll back to
the pre-gang carry and continue" is EXACTLY "re-solve with the gang's pods
stripped" — decisions before the gang's first run are unaffected (the scan
never looks ahead), and decisions after see the same carry either way. The
orchestrator therefore strips the first failing gang in scan order and
re-solves, at most once per gang; on the device path the checkpoint-ring
suffix resume replays only from the stripped gang's position (the
`ffd.GangStage` carry), so rounds cost the changed suffix, not the fleet.

Preemption semantics: after gangs settle, each still-unplaced pod (class-FFD
order) may claim capacity from strictly-lower-priority bound pods on
existing nodes. The planner picks the first node (ascending input order)
where free + the minimal prefix of its eligible victims — ascending
(priority, uid), so the least important evict first — covers the pod's
quantized request. Victims are planned as `SolverResult.evictions` and
executed by provisioning/preemption.py; the pending pod schedules on a later
reconcile once the capacity frees (Kubernetes preemption is asynchronous by
nature — convergence over reconciles, asserted by the kwok e2e).

Declines (counted, feature-skipped — the host-fallback discipline sharding
uses): preemption with an active topology/affinity engine (evictions would
invalidate V/Q domain counts mid-plan), eviction tables overflowing the
uint16 wire format, more evictions than MAX_EVICTIONS_PER_SOLVE in one
solve (cycle/thrash guard), and gangs larger than the claim budget.

Off-path inertness: with the knobs off — or on any priority-flat, gang-free
fleet — `ClassAwareSolver` delegates verbatim (same object path, zero
re-ordering: ffd_sort's class keys only engage when the batch carries >1
distinct priority or a gang), so today's solves are bit-identical.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import wellknown as wk
from ..api.objects import Pod, PodAffinityTerm, tolerates_all
from ..metrics.registry import (
    SOLVER_FALLBACK,
    SOLVER_GANGS_PLACED,
    SOLVER_GANGS_UNSCHEDULABLE,
    SOLVER_PREEMPTIONS,
    SOLVER_PRIORITY_INVERSIONS,
)
from ..provisioning.scheduler import (
    Eviction,
    SolverInput,
    SolverResult,
    ffd_sort,
)
from ..obs import explain as obsexplain
from ..obs import trace as obstrace
from ..scheduling.requirements import Requirements
from ..utils.resources import PODS

# Module knobs, set at startup from --solver-preemption / --solver-gang
# (operator/options.py); ffd_sort_with_sigs consults them too, so flipping
# one off removes BOTH the ordering keys and the pass it gates.
PRIORITY_ENABLED = True
GANG_ENABLED = True

# A gang needing more placements than one solve's claim budget can never
# commit atomically — declined up front (counted), not half-placed.
GANG_CLAIM_BUDGET = 4096
# Eviction-storm guard: one solve plans at most this many evictions; the
# remainder declines to the next reconcile (counted).
MAX_EVICTIONS_PER_SOLVE = 256

INT32_MAX = 2**31 - 1


def _pending(pods: Sequence[Pod]) -> List[Pod]:
    # the schedulable subset — the same filter every backend applies
    return [p for p in pods if not p.scheduling_gated and not p.bound]


def configure(preemption: bool = True, gang: bool = True) -> None:
    global PRIORITY_ENABLED, GANG_ENABLED
    PRIORITY_ENABLED = bool(preemption)
    GANG_ENABLED = bool(gang)


# ---------------------------------------------------------------------------
# Planner: three bit-identical implementations of the decision math
# ---------------------------------------------------------------------------


def _gang_commit_py(run_placed, run_gang, gang_size, gang_min_ranks):
    """Python-oracle gang verdict: sequential mirror of ffd.gang_commit."""
    ng = len(gang_size)
    placed = [0] * ng
    for c, g in zip(run_placed, run_gang):
        if g >= 0:
            placed[int(g)] += int(c)
    commit = [
        placed[i] >= int(gang_min_ranks[i]) and int(gang_min_ranks[i]) > 0
        for i in range(ng)
    ]
    return (np.asarray(commit, dtype=bool), np.asarray(placed, dtype=np.int32))


def _preemption_plan_py(node_free, victim_prio, victim_req, victim_ok,
                        node_ok, need, pod_prio):
    """Python-oracle preemption plan: sequential mirror of
    ffd.preemption_plan / native.preemption_plan_host."""
    E, Vm = len(victim_prio), len(victim_prio[0]) if len(victim_prio) else 0
    R = len(need)
    mask = np.zeros((E, Vm), dtype=bool)
    for e in range(E):
        if not node_ok[e]:
            continue
        cum = [int(x) for x in node_free[e]]
        chosen: List[int] = []
        if all(cum[r] >= int(need[r]) for r in range(R)):
            return e, mask  # free capacity alone fits: nothing to evict
        for v in range(Vm):
            if not (victim_ok[e][v] and int(victim_prio[e][v]) < int(pod_prio)):
                continue
            for r in range(R):
                cum[r] += int(victim_req[e][v][r])
            chosen.append(v)
            if all(cum[r] >= int(need[r]) for r in range(R)):
                mask[e, chosen] = True
                return e, mask
    return -1, mask


def _gang_commit_host(*args):
    from . import native

    return native.gang_commit_host(*args)


def _preemption_plan_host(*args):
    from . import native

    return native.preemption_plan_host(*args)


def _gang_commit_device(run_placed, run_gang, gang_size, gang_min_ranks):
    from .tpu import ffd

    commit, placed = ffd.gang_commit(
        np.asarray(run_placed, np.int32), np.asarray(run_gang, np.int32),
        np.asarray(gang_size, np.int32), np.asarray(gang_min_ranks, np.int32),
    )
    return np.asarray(commit), np.asarray(placed)


def _preemption_plan_device(node_free, victim_prio, victim_req, victim_ok,
                            node_ok, need, pod_prio):
    from .tpu import ffd

    node_idx, take = ffd.preemption_plan(
        np.asarray(node_free, np.int32), np.asarray(victim_prio, np.int32),
        np.asarray(victim_req, np.int32), np.asarray(victim_ok, bool),
        np.asarray(node_ok, bool), np.asarray(need, np.int32),
        np.int32(pod_prio),
    )
    return int(node_idx), np.asarray(take)


PLANNERS = {
    "oracle": (_gang_commit_py, _preemption_plan_py),
    "host": (_gang_commit_host, _preemption_plan_host),
    "device": (_gang_commit_device, _preemption_plan_device),
}


def select_planner(solver) -> str:
    """Planner leg for a wrapper chain: the concrete backend at the bottom
    picks it (device kernels for the TPU path, the numpy host mirror for the
    native core, the python oracle otherwise). All three are bit-identical —
    this only decides WHERE the math runs."""
    from .backend import concrete_backend

    name = type(concrete_backend(solver)).__name__
    if name == "TPUSolver":
        return "device"
    if name == "NativeSolver":
        return "host"
    return "oracle"


# ---------------------------------------------------------------------------
# Victim tensors (shared input builder — one order for every planner)
# ---------------------------------------------------------------------------


def build_victim_tensors(nodes, rkeys: Sequence[str]):
    """Per-node victim tables for the preemption planner, victims sorted
    ascending (priority, uid) — THE order all three implementations walk.
    Returns (node_free [E,R] i32, victim_prio [E,Vm] i32, victim_req
    [E,Vm,R] i32, victim_ok [E,Vm] bool, victim_uids [E][Vm]). Quantization
    matches encode: free and reclaim floor (conservative), padding rows are
    ineligible (ok=False, prio=INT32_MAX)."""
    from .encode import _quantize

    E = len(nodes)
    R = len(rkeys)
    vm = max([len(n.bound_pods) for n in nodes] + [1])
    node_free = np.zeros((E, R), np.int32)
    victim_prio = np.full((E, vm), INT32_MAX, np.int32)
    victim_req = np.zeros((E, vm, R), np.int32)
    victim_ok = np.zeros((E, vm), bool)
    victim_uids: List[List[Optional[str]]] = [[None] * vm for _ in range(E)]
    for e, n in enumerate(nodes):
        node_free[e] = _quantize(n.free, list(rkeys), ceil=False)
        victims = sorted(n.bound_pods, key=lambda b: (b.priority, b.uid))
        for v, b in enumerate(victims):
            victim_prio[e, v] = min(b.priority, INT32_MAX)
            req = _quantize(b.requests, list(rkeys), ceil=False)
            if PODS in rkeys:
                req[list(rkeys).index(PODS)] = 1
            victim_req[e, v] = req
            victim_ok[e, v] = bool(b.evictable)
            victim_uids[e][v] = b.uid
    return node_free, victim_prio, victim_req, victim_ok, victim_uids


# ---------------------------------------------------------------------------
# The class-aware solve seam
# ---------------------------------------------------------------------------


class _Deferred:
    """Minimal async-seam adapter: `.result()` runs the deferred solve. The
    pipelined service calls solve_async() on the dispatcher thread and
    result() on the decoder thread; a class-engaged solve is a multi-dispatch
    composite, so it runs whole at the decode stage (graceful FIFO, same as
    any backend without an async seam)."""

    def __init__(self, fn):
        self._fn = fn

    def result(self):
        return self._fn()


class ClassAwareSolver:
    """Wraps any Solver with priority/preemption/gang semantics. Inert —
    verbatim delegation, including the inner async seam — whenever the
    batch is priority-flat and gang-free or the knobs are off."""

    def __init__(self, inner, planner: str = "auto"):
        self.inner = inner
        self._planner_choice = planner
        # NOT named `stats`: wrapper attribute lookup must keep delegating
        # the concrete backend's stats dict (tests and bench read
        # op.solver.stats["device_solves"] through the chain)
        self.class_stats: Dict[str, int] = {
            "class_solves": 0,
            "gang_rounds": 0,
            "gangs_placed": 0,
            "gangs_unschedulable": 0,
            "preemptions": 0,
            "priority_inversions": 0,
            "declines": 0,
        }

    def __getattr__(self, name):
        return getattr(self.inner, name)

    # -- engagement ----------------------------------------------------------

    def _gangs(self, pods: Sequence[Pod]) -> Dict[str, Tuple[int, int, List[str]]]:
        out: Dict[str, Tuple[int, int, List[str]]] = {}
        for p in _pending(pods):
            g = p.gang()
            if g is None:
                continue
            gid, size, min_ranks = g
            prev = out.get(gid)
            if prev is None:
                out[gid] = (size, min_ranks, [p.meta.uid])
            else:
                out[gid] = (
                    max(prev[0], size), max(prev[1], min_ranks),
                    prev[2] + [p.meta.uid],
                )
        return out

    def _engaged(self, inp: SolverInput) -> bool:
        pending = _pending(inp.pods)
        if GANG_ENABLED and any(p.gang() for p in pending):
            return True
        if not PRIORITY_ENABLED or not pending:
            return False
        top = max(p.priority for p in pending)
        return any(
            b.priority < top and b.evictable
            for n in inp.nodes for b in n.bound_pods
        )

    # -- the Solver surface --------------------------------------------------

    def solve(self, inp: SolverInput) -> SolverResult:
        if not self._engaged(inp):
            return self.inner.solve(inp)
        with obstrace.span("class.solve"):
            return self._solve_class(inp)

    def solve_async(self, inp: SolverInput):
        if not self._engaged(inp):
            sa = getattr(self.inner, "solve_async", None)
            if sa is not None:
                return sa(inp)
            return _Deferred(lambda: self.inner.solve(inp))

        def run():
            # deferred: runs on the decoder thread, inside its attached trace
            with obstrace.span("class.solve"):
                return self._solve_class(inp)

        return _Deferred(run)

    def solve_cohort_async(self, inps, traces=None):
        """Cohort seam: engaged members (gang/priority semantics) run the
        class path — they cannot fuse, their solve is a multi-round plan —
        while the flat remainder rides the inner backend's fused cohort
        entry point. Outcome list order matches `inps`."""
        n = len(inps)
        traces = list(traces) if traces is not None else [None] * n
        inner_sc = getattr(self.inner, "solve_cohort_async", None)
        engaged = [i for i in range(n) if self._engaged(inps[i])]
        handles: dict = {}
        for i in engaged:
            with obstrace.attached(traces[i]):
                try:
                    handles[i] = self.solve_async(inps[i])
                except Exception as e:  # noqa: BLE001 — per-member outcome
                    handles[i] = e
        flat = [i for i in range(n) if i not in handles]
        flat_fin = None
        if flat and inner_sc is not None:
            flat_fin = inner_sc([inps[i] for i in flat],
                                traces=[traces[i] for i in flat])
        elif flat:
            for i in flat:
                with obstrace.attached(traces[i]):
                    try:
                        handles[i] = self.solve_async(inps[i])
                    except Exception as e:  # noqa: BLE001
                        handles[i] = e

        def finish():
            results: list = [None] * n
            if flat_fin is not None:
                for i, oc in zip(flat, flat_fin()):
                    results[i] = oc
            for i, h in handles.items():
                if isinstance(h, BaseException):
                    results[i] = h
                    continue
                try:
                    with obstrace.attached(traces[i]):
                        results[i] = h.result()
                except Exception as e:  # noqa: BLE001 — per-member outcome
                    results[i] = e
            return results

        return finish

    # -- class passes --------------------------------------------------------

    def _decline(self, reason: str) -> None:
        self.class_stats["declines"] += 1
        SOLVER_FALLBACK.inc(reason=f"class_{reason}")

    def _solve_class(self, inp: SolverInput) -> SolverResult:
        self.class_stats["class_solves"] += 1
        planner = self._planner_choice
        if planner == "auto":
            planner = select_planner(self.inner)
        gang_fn, plan_fn = PLANNERS[planner]

        pods = list(inp.pods)
        if GANG_ENABLED:
            pods = _inject_gang_affinity(pods)
        work = dataclasses.replace(inp, pods=pods) if pods is not inp.pods else inp

        res = self.inner.solve(work)
        gangs_unschedulable: List[str] = []

        # ---- atomic gang pass ---------------------------------------------
        if GANG_ENABLED:
            gangs = self._gangs(pods)
            # oversized gangs can never commit within one claim budget:
            # declined up front, stripped without a verdict round
            for gid, (size, _mr, members) in sorted(gangs.items()):
                if size > GANG_CLAIM_BUDGET:
                    self._decline("gang_claim_budget")
                    gangs_unschedulable.append(gid)
            if gangs_unschedulable:
                # all-or-nothing holds for declined gangs too: strip their
                # members and re-solve, or the base solve's partial
                # placements would leak through the decline
                pods = [
                    p for p in pods
                    if (p.gang() or ("",))[0] not in gangs_unschedulable
                ]
                work = dataclasses.replace(work, pods=pods)
                res = self.inner.solve(work)
            rounds = 0
            while gangs and rounds <= len(gangs):
                rounds += 1
                failing = self._first_failing_gang(
                    pods, res, gangs, gangs_unschedulable, gang_fn
                )
                if failing is None:
                    break
                gangs_unschedulable.append(failing)
                # rollback == strip + re-solve: decisions before the gang's
                # first run are order-stable, so this is the staged-carry
                # rollback of SPEC.md executed at the solve seam (the device
                # path's suffix resume replays only from the strip point)
                pods = [
                    p for p in pods
                    if (p.gang() or ("",))[0] != failing
                ]
                work = dataclasses.replace(work, pods=pods)
                res = self.inner.solve(work)
                self.class_stats["gang_rounds"] += 1
            committed = [g for g in gangs if g not in gangs_unschedulable]
            self.class_stats["gangs_placed"] += len(committed)
            self.class_stats["gangs_unschedulable"] += len(gangs_unschedulable)
            for g in committed:
                SOLVER_GANGS_PLACED.inc()
            for g in gangs_unschedulable:
                SOLVER_GANGS_UNSCHEDULABLE.inc()
            # provenance: per-gang verdicts are decision facts the result
            # object doesn't carry (beyond the unschedulable list) — staged
            # for the class-level explain capture below
            for gid, (_size, mr, members) in sorted(gangs.items()):
                obsexplain.note("gang", {
                    "gang": gid,
                    "committed": gid not in gangs_unschedulable,
                    "placed": sum(1 for u in members if u in res.placements),
                    "min_ranks": mr,
                })

        # ---- preemption pass ----------------------------------------------
        evictions: List[Eviction] = []
        if PRIORITY_ENABLED:
            evictions = self._plan_preemptions(inp, pods, res, plan_fn)

        # ---- surface ------------------------------------------------------
        errors = dict(res.errors)
        for gid in gangs_unschedulable:
            for p in inp.pods:
                g = p.gang()
                if g is not None and g[0] == gid:
                    errors[p.meta.uid] = (
                        f"gang {gid} unschedulable: fewer than min-ranks "
                        "members could place (all-or-nothing rollback)"
                    )
        inversions = _count_inversions(inp, res)
        if inversions:
            self.class_stats["priority_inversions"] += inversions
            SOLVER_PRIORITY_INVERSIONS.inc(inversions)
        obstrace.annotate(
            gangs_unschedulable=len(set(gangs_unschedulable)),
            preemptions=len(evictions),
        )
        final = dataclasses.replace(
            res,
            errors=errors,
            evictions=evictions,
            gangs_unschedulable=sorted(set(gangs_unschedulable)),
        )
        if obsexplain.enabled():
            # the class-level record supersedes the inner leg's (same
            # solve_id → store merge): it re-derives over the FINAL result
            # (post strip/re-solve, with evictions + gang verdicts attached)
            # so every leg that reaches here fingerprints the same facts
            obsexplain.capture(inp, final, "class", drain_notes=True)
        return final

    def _first_failing_gang(self, pods, res, gangs, already, gang_fn):
        """First gang in scan order whose verdict fails, via the planner's
        gang_commit over the per-pod run decomposition (runs of length one
        of the class-sorted pod list — a valid run split, so the segment-sum
        kernel consumes it unchanged)."""
        live = {g: v for g, v in gangs.items() if g not in already}
        if not live:
            return None
        gang_ids = sorted(live)
        rank = {g: i for i, g in enumerate(gang_ids)}
        spods = ffd_sort(_pending(pods))
        run_placed = [1 if p.meta.uid in res.placements else 0 for p in spods]
        run_gang = [
            rank.get((p.gang() or ("",))[0], -1) for p in spods
        ]
        gang_size = [live[g][0] for g in gang_ids]
        gang_min_ranks = [live[g][1] for g in gang_ids]
        commit, _placed = gang_fn(run_placed, run_gang, gang_size, gang_min_ranks)
        # scan order of gangs = first appearance in the sorted pod list
        for p in spods:
            g = p.gang()
            if g is None or g[0] not in rank:
                continue
            if not bool(commit[rank[g[0]]]):
                return g[0]
        return None

    def _plan_preemptions(self, inp, pods, res, plan_fn) -> List[Eviction]:
        candidates = [
            p for p in ffd_sort(_pending(pods))
            if p.meta.uid not in res.placements
        ]
        if not candidates or not inp.nodes:
            return []
        if not any(b.evictable for n in inp.nodes for b in n.bound_pods):
            return []
        # V/Q interaction: an eviction changes domain member counts the
        # engines already consumed — inexpressible mid-plan, decline whole
        if any(p.topology_spread or p.affinity_terms for p in pods):
            self._decline("preemption_topology")
            return []
        rkeys = sorted(
            {k for p in candidates for k in p.requests}
            | {k for n in inp.nodes for b in n.bound_pods for k in b.requests}
            | {"cpu", "memory", PODS}
        )
        node_free, victim_prio, victim_req, victim_ok, victim_uids = (
            build_victim_tensors(inp.nodes, rkeys)
        )
        from .encode import _quantize

        pods_col = rkeys.index(PODS)
        # the free tables reflect PRE-solve state: charge this solve's own
        # existing-node placements before planning, or the planner re-offers
        # capacity the committed placements already consumed
        node_rank = {n.id: e for e, n in enumerate(inp.nodes)}
        by_uid = {p.meta.uid: p for p in pods}
        for uid, placement in res.placements.items():
            if placement[0] != "node" or uid not in by_uid:
                continue
            e = node_rank.get(placement[1])
            if e is None:
                continue
            used = _quantize(by_uid[uid].requests, rkeys, ceil=True)
            used[pods_col] = max(used[pods_col], 1)
            node_free[e] = np.maximum(node_free[e] - used, 0)
        evictions: List[Eviction] = []
        for p in candidates:
            if len(evictions) >= MAX_EVICTIONS_PER_SOLVE:
                self._decline("eviction_budget")
                break
            need = _quantize(p.requests, rkeys, ceil=True)
            need[pods_col] = max(need[pods_col], 1)
            preqs = p.scheduling_requirements()
            node_ok = np.fromiter(
                (
                    n.schedulable
                    and tolerates_all(p.tolerations, n.taints)
                    and preqs.strictly_compatible(
                        Requirements.from_labels(n.labels)
                    )
                    for n in inp.nodes
                ),
                bool, len(inp.nodes),
            )
            if not node_ok.any():
                continue
            e, take = plan_fn(
                node_free, victim_prio, victim_req, victim_ok, node_ok,
                need, p.priority,
            )
            if e < 0:
                continue
            hot = np.flatnonzero(np.asarray(take)[e])
            if not len(hot):
                continue  # free capacity fit — nothing to evict
            for v in hot:
                evictions.append(Eviction(
                    node_id=inp.nodes[e].id,
                    pod_uid=victim_uids[e][int(v)],
                    victim_priority=int(victim_prio[e, int(v)]),
                    for_pod=p.meta.uid,
                ))
                node_free[e] += victim_req[e, int(v)]
                victim_ok[e, int(v)] = False
            # the freed capacity is spoken for: the pending pod lands there
            # next reconcile, so later candidates see the remainder
            node_free[e] = np.maximum(node_free[e] - need, 0)
        if evictions:
            # the wire format is the contract even on the host path: rows
            # that cannot pack (uint16 overflow) decline, like the claim
            # delta's wide re-fetch
            packed = _pack_rows(inp, evictions)
            if packed is None:
                self._decline("evict_overflow")
                evictions = []
            else:
                self.class_stats["preemptions"] += len(evictions)
                SOLVER_PREEMPTIONS.inc(len(evictions))
        return evictions


def _pack_rows(inp, evictions) -> Optional[List[Eviction]]:
    """Round-trip the planned evictions through the uint16 eviction table
    (ffd.pack_evictions wire format). Returns the decoded rows — identical
    by construction — or None on overflow (caller declines)."""
    try:
        from .tpu import ffd
    except Exception:  # jax unavailable: host-only branch keeps the rows
        return evictions
    node_rank = {n.id: e for e, n in enumerate(inp.nodes)}
    uid_rank: Dict[str, int] = {}
    entries = []
    for ev in evictions:
        uid_rank.setdefault(ev.pod_uid, len(uid_rank))
        entries.append((node_rank[ev.node_id], uid_rank[ev.pod_uid]))
    buf = ffd.pack_evictions(entries)
    overflow, rows = ffd.unpack_evictions(buf)
    if overflow:
        return None
    assert rows == entries
    return evictions


def _inject_gang_affinity(pods: List[Pod]) -> List[Pod]:
    """Rank-aware co-location: members of a gang labeled with
    GANG_TOPOLOGY_LABEL gain a PREFERRED self-affinity on that topology key
    — the ordinary relax ladder satisfies it when capacity allows and drops
    it (by weight) when it cannot, identically on every backend. Returns
    the input list unchanged (same object) when nothing injects."""
    out: List[Pod] = []
    changed = False
    for p in pods:
        g = p.gang()
        key = p.meta.labels.get(wk.GANG_TOPOLOGY_LABEL)
        if g is None or key not in wk.TOPOLOGY_KEYS:
            out.append(p)
            continue
        term = PodAffinityTerm(
            label_selector={wk.GANG_LABEL: g[0]},
            topology_key=key,
            weight=1,
        )
        out.append(dataclasses.replace(
            p, affinity_terms=[*p.affinity_terms, term]
        ))
        changed = True
    return out if changed else pods


def _count_inversions(inp: SolverInput, res: SolverResult,
                      cap_unplaced: int = 64, cap_placed: int = 512) -> int:
    """Priority inversions in a finished solve: an unplaced pod p and a
    strictly-lower-priority pod q placed on an existing node that admits p
    with a committed slot big enough for p. Priority-major scan order makes
    this structurally impossible (p was offered every target before q), so
    the parity tests assert the count stays 0; the metric exists to catch a
    future ordering regression in production, not to tolerate one."""
    pending = _pending(inp.pods)
    unplaced = [p for p in pending if p.meta.uid in res.errors][:cap_unplaced]
    if not unplaced:
        return 0
    by_uid = {p.meta.uid: p for p in pending}
    nodes = {n.id: n for n in inp.nodes}
    placed: List[Tuple[Pod, object]] = []
    for uid, (kind, target) in res.placements.items():
        if kind == "node" and uid in by_uid and target in nodes:
            placed.append((by_uid[uid], nodes[target]))
            if len(placed) >= cap_placed:
                break
    count = 0
    for p in unplaced:
        preqs = p.scheduling_requirements()
        for q, n in placed:
            if q.priority >= p.priority:
                continue
            if not tolerates_all(p.tolerations, n.taints):
                continue
            if not preqs.strictly_compatible(Requirements.from_labels(n.labels)):
                continue
            if all(
                q.requests.get_(k) >= p.requests.get_(k) for k in p.requests
            ):
                count += 1
                break
    return count
