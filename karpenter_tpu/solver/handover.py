"""Blue/green solver handover over the TenantMux seam (ISSUE 17).

Upgrade protocol (solver/SPEC.md "Durability semantics"):

  1. **restore** — the green (incoming) side hydrates from the
     SolverStateVault: encode-core donors installed, streaming cursor
     cross-checked, so its first encode adopts the blue side's tables
     instead of paying the cluster-size-bounded rebuild.
  2. **prewarm** — best-effort AOT warmup from the persistent compile
     cache (backend.warmup / prewarm_aot when the green solver exposes
     them), so takeover does not eat a first-call compile.
  3. **shadow parity** — each shadow input solves on BOTH sides
     (directly on the solvers — shadow work must not consume mux
     tickets) and the explain-record fingerprints (obs/explain.py) are
     diffed. ANY mismatch aborts the handover with the first-divergence
     paths; the blue side keeps serving.
  4. **cutover** — TenantMux.swap_downstream retargets the mux at the
     green service, drains the blue side's in-flight tickets (they
     resolve through their existing callbacks — zero drops), then closes
     it. Tickets still queued at the mux simply forward green from the
     swap onward.

The whole run is observable: a `handover` trace span, `note_event`
breadcrumbs per step, and a report dict the caller (bench --restore-suite,
tests) asserts `dropped == 0` against.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Sequence

from ..obs import explain as obsexplain
from ..obs import telemetry as obstelemetry
from ..obs import trace as obstrace

log = logging.getLogger("karpenter_tpu")


class HandoverAborted(Exception):
    """Shadow parity failed — the green side must not take over."""


def solve_fingerprint(solver, inp) -> str:
    """Explain-record fingerprint of one solver's decision on one input.
    The record derives from (encode structure, placements); the encode
    structure is a pure function of the input, so two solvers' fingerprints
    agree iff their DECISIONS agree."""
    from .encode import encode, quantize_input

    res = solver.solve(inp)
    enc = encode(quantize_input(inp))
    return obsexplain.fingerprint(obsexplain.build_record(enc, res, k=4))


class BlueGreenHandover:
    """One zero-downtime handover: restore → prewarm → shadow parity →
    cutover. Construct with the live mux and the already-built green
    service; `run()` returns the report (and raises HandoverAborted before
    touching the mux when parity fails)."""

    def __init__(self, mux, green_service, vault=None,
                 clock=time.monotonic):
        self.mux = mux
        self.green = green_service
        self.vault = vault
        self.clock = clock

    # -- steps ----------------------------------------------------------------

    def restore(self) -> Optional[dict]:
        if self.vault is None:
            return None
        report = self.vault.restore(install=True)
        return report.as_dict() if report is not None else None

    def prewarm(self) -> bool:
        """Best-effort AOT prewarm of the green solver from the persistent
        compile cache; absence of the seam (host-only solver, reference
        backend) is not a failure — takeover just pays a first-call."""
        solver = getattr(self.green, "solver", None)
        for name in ("prewarm_aot", "warmup"):
            fn = getattr(solver, name, None)
            if fn is None:
                continue
            try:
                fn()
                return True
            except Exception as e:  # noqa: BLE001 — prewarm is advisory
                log.warning(
                    "handover: green prewarm via %s failed (%s: %s)",
                    name, type(e).__name__, e,
                )
        return False

    def prove_parity(self, shadow_inputs: Sequence) -> List[dict]:
        """Solve every shadow input on both sides; return the mismatches
        (empty = parity proven). Solves go directly to the solvers so the
        shadow stream consumes no mux tickets and charges no tenant."""
        blue_solver = self.mux.solver
        green_solver = getattr(self.green, "solver", self.green)
        mismatches: List[dict] = []
        for i, inp in enumerate(shadow_inputs):
            blue_fp = solve_fingerprint(blue_solver, inp)
            green_fp = solve_fingerprint(green_solver, inp)
            if blue_fp != green_fp:
                mismatches.append(
                    {"shadow": i, "blue": blue_fp, "green": green_fp}
                )
        return mismatches

    # -- the protocol ---------------------------------------------------------

    def run(self, shadow_inputs: Sequence = (),
            drain_s: float = 5.0) -> Dict[str, object]:
        """Execute the full protocol. Raises HandoverAborted (blue keeps
        serving, green untouched by the mux) when any shadow input's
        decision diverges; otherwise cuts over and returns the report —
        `report["dropped"]` is the zero-drop acceptance gate."""
        t0 = self.clock()
        with obstrace.span("handover"):
            restored = self.restore()
            prewarmed = self.prewarm()
            mismatches = self.prove_parity(shadow_inputs)
            if mismatches:
                obstelemetry.note_event(
                    "handover_aborted", mismatches=len(mismatches),
                )
                raise HandoverAborted(
                    f"shadow parity failed on {len(mismatches)}/"
                    f"{len(shadow_inputs)} input(s): {mismatches[0]}"
                )
            swap = self.mux.swap_downstream(
                self.green, own=True, drain_s=drain_s
            )
        report = {
            "restored": restored,
            "prewarmed": prewarmed,
            "shadows": len(shadow_inputs),
            "mismatches": 0,
            "swap": swap,
            # undrained tickets are the only way the protocol can drop
            # work — the acceptance gate asserts this is 0
            "dropped": int(swap["timeouts"]),
            "duration_s": self.clock() - t0,
        }
        obstelemetry.note_event(
            "handover_complete", shadows=len(shadow_inputs),
            dropped=report["dropped"],
        )
        log.info(
            "handover: green took over (%d shadow(s) parity-proven, "
            "%d drained, %d dropped, %.2fs)",
            len(shadow_inputs), swap["drained"], report["dropped"],
            report["duration_s"],
        )
        return report
