"""Solver fleet: N health-probed SolveService owners behind one seam.

`SolveService` (pipeline.py) owns exactly one device — which makes one wedged
backend a single point of failure for every solve in the process (the bench
has demonstrated the failure mode twice: a hung TPU probe took the whole perf
suite down). `SolverFleet` fronts N owners — one per device, or per virtual
host-mesh slot when fewer than two real devices are visible — behind the same
submit()/submit_fn()/close() surface the controllers already speak:

    ┌────────────────────────────── SolverFleet ──────────────────────────────┐
    │  submit()/submit_fn()          canary watchdog          requeue/oracle  │
    │        │                             │                        ▲         │
    │  ┌─────▼─────┐  ┌───────────┐  ┌─────▼─────┐                  │         │
    │  │ owner-0   │  │ owner-1   │  │ owner-N   │   fence ─────────┘         │
    │  │ solver    │  │ solver    │  │ solver    │                            │
    │  │ arena     │  │ arena     │  │ arena     │   each owner: its own      │
    │  │ service   │  │ service   │  │ service   │   CircuitBreaker, its own  │
    │  │ breaker   │  │ breaker   │  │ breaker   │   ArgumentArena residency  │
    │  └───────────┘  └───────────┘  └───────────┘                            │
    └─────────────────────────────────────────────────────────────────────────┘

Liveness is probed, not assumed: a periodic tiny canary solve with a hard
real-time deadline runs against every healthy owner (watchdog thread, or
`probe_once()` driven directly by tests — no sleeps). A canary MISS — the
ticket not resolving inside the deadline — is what a *hung* dispatch looks
like from outside: no exception ever surfaces, so raised-error machinery
(resilient.py) never sees it. Misses feed the owner's fleet-level
`CircuitBreaker`; after `fence_after_misses` consecutive misses the owner is
FENCED:

1. the owner's service is stopped with a short drain (pipeline.py stop():
   every ticket it ever issued resolves — queued fail fast, in-flight get the
   drain window, wedged ones are force-resolved);
2. the owner's arena residency is invalidated (a wedged solve leaves device
   state unknowable — the owner re-adopts from scratch if it ever recovers);
3. every not-yet-resolved request is re-routed IN ORIGINAL SUBMISSION ORDER
   to a healthy owner (provisioning re-coalesces there: state_rev/Superseded
   semantics survive the re-route) or — when no healthy owner remains —
   input-carrying requests degrade to the python oracle. First-wins ticket
   delivery (pipeline.py) guarantees no request is dropped and none is acted
   on twice, even when a force-resolve races a late real decode.

A fenced owner is probed for recovery on its breaker's half-open schedule
(injected clock): a direct canary solve on a sacrificial thread — never on a
shared dispatcher — with the same hard deadline. Success un-fences the owner
behind a FRESH SolveService (the old dispatcher may still be parked inside
the hung XLA call; it is abandoned as a daemon).

Fleet state is exported as karpenter_solver_fleet_healthy (unlabeled total +
per-owner 0/1), karpenter_solver_failover_total,
karpenter_solver_requeued_solves_total, and
karpenter_solver_canary_latency_seconds. SPEC.md "Failover semantics" is the
contract; tests/test_solver_fleet.py drives every path via faults.py
wedge-class sites (solver.device_hang / device_lost / arena_corrupt).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence

from ..metrics.registry import (
    FLEET_CANARY_LATENCY,
    FLEET_FAILOVER,
    FLEET_HEALTHY,
    FLEET_REQUEUED,
)
from ..obs import telemetry as obstelemetry
from ..obs import trace as obstrace
from .backend import ReferenceSolver, Solver
from .pipeline import (
    DISRUPTION,
    PROVISIONING,
    ServiceStopped,
    SolveService,
    SolveTicket,
    Superseded,
)
from .resilient import OPEN, CircuitBreaker

log = logging.getLogger("karpenter_tpu")


def _set_fault_tag(solver, name: str) -> None:
    """Stamp the chaos-injection tag on the innermost solver that actually
    reads one (TPUSolver.fault_tag) — resilience wrappers delegate attribute
    READS to their inner solver, so setting on the wrapper would shadow."""
    obj = solver
    while obj is not None:
        d = getattr(obj, "__dict__", None) or {}
        if "fault_tag" in d:
            obj.fault_tag = name
            return
        obj = d.get("inner")


def default_canary_input(instance_types: Optional[Sequence] = None):
    """A minimal one-pod SolverInput for liveness probes. With no catalog
    given, a tiny generated slice is used (lazy — never on import)."""
    from ..api import wellknown as wk
    from ..api.objects import ObjectMeta, Pod
    from ..provisioning.scheduler import NodePoolSpec, SolverInput
    from ..scheduling.requirements import IN, Requirement, Requirements
    from ..utils.resources import Resources

    if instance_types is None:
        from ..catalog.catalog import CatalogSpec, generate

        instance_types = generate(CatalogSpec())
    types = list(instance_types)[:4]
    zones = tuple(sorted({o.zone for it in types for o in it.offerings}))
    reqs = Requirements.of(
        Requirement.create(wk.NODEPOOL_LABEL, IN, ["fleet-canary"])
    )
    pod = Pod(
        meta=ObjectMeta(name="fleet-canary", uid="fleet-canary"),
        requests=Resources.parse({"cpu": "100m", "memory": "64Mi"}),
    )
    np = NodePoolSpec(
        name="fleet-canary", weight=0, requirements=reqs, taints=[],
        instance_types=types,
    )
    return SolverInput(pods=[pod], nodes=[], nodepools=[np], zones=zones)


class _FleetBreaker(CircuitBreaker):
    """Per-owner fencing breaker. Does NOT export to the global
    karpenter_tpu_solver_breaker_state gauge — that series belongs to the
    per-request resilience breaker; fleet health has its own gauge."""

    def _export(self) -> None:  # noqa: D102 — deliberate no-op
        pass

    def _on_open(self, failures: int) -> None:  # noqa: D102 — deliberate no-op
        # the fence path writes its own flight record (reason=fleet_fence)
        # with richer tags; a second breaker_open dump would be noise
        pass


class _FleetEntry:
    """One logical fleet request across any number of owner re-routes."""

    __slots__ = ("ticket", "inp", "fn", "kind", "rev", "tenant_id", "owner",
                 "owner_ticket", "requeues", "trace")

    def __init__(self, ticket: SolveTicket, inp=None, fn=None,
                 kind: str = PROVISIONING, rev=None,
                 tenant_id: Optional[str] = None):
        self.ticket = ticket
        self.inp = inp
        self.fn = fn
        self.kind = kind
        self.rev = rev
        self.tenant_id = tenant_id
        self.owner: Optional["FleetOwner"] = None
        self.owner_ticket: Optional[SolveTicket] = None
        self.requeues = 0
        # one trace per LOGICAL request: it survives owner re-routes (each
        # placement attaches it, so the new owner's spans join the same tree)
        self.trace = None


def _mint_fleet_trace(entry: _FleetEntry) -> None:
    """Mint (or adopt, when the provisioner already opened one on this
    thread) the trace for a logical fleet request. When owned here, its
    completion is tied to FLEET-ticket delivery — owner tickets come and go
    across re-routes without finishing the tree."""
    tr, owned = obstrace.adopt_or_begin(entry.kind)
    if tr is None:
        return
    entry.trace = tr
    entry.ticket.solve_id = tr.solve_id
    obstrace.set_tenant(tr, entry.tenant_id)
    if owned:
        entry.ticket.on_done(
            lambda t, _tr=tr: obstrace.finish(_tr, obstrace.status_of(t.error()))
        )


class FleetOwner:
    """One device owner: solver + pipelined service + fencing breaker."""

    def __init__(self, index: int, solver: Solver, service: SolveService,
                 breaker: CircuitBreaker):
        self.index = index
        self.name = f"owner-{index}"
        self.solver = solver
        self.service = service
        self.breaker = breaker
        self.fenced = False
        self.fence_count = 0
        # owner-ticket -> _FleetEntry, insertion-ordered: the fence loop
        # replays survivors in original submission order so provisioning
        # revisions re-coalesce correctly on the new owner
        self.outstanding: "OrderedDict[SolveTicket, _FleetEntry]" = OrderedDict()


class SolverFleet:
    """N independently health-checked SolveService owners behind the
    SolveService surface the provisioner / disruption controller / bench
    already use (submit, submit_fn, occupancy, queue_depth, stats,
    resume/shard/decode_stats, close)."""

    def __init__(
        self,
        solver_factory: Callable[[int], Solver],
        size: int = 2,
        depth: int = 2,
        clock=time.monotonic,
        canary_input_fn: Optional[Callable] = None,
        canary_interval_s: float = 5.0,
        canary_deadline_s: float = 5.0,
        fence_after_misses: int = 2,
        recovery_probe_s: float = 30.0,
        fence_drain_s: float = 0.25,
        instance_types: Optional[Sequence] = None,
        start_monitor: bool = False,
        vault=None,
        host: str = "",
    ):
        # federation host identity (solver/federation.py): rides onto the
        # fleet series as a `host` label; empty (the single-host default)
        # is dropped from both series keys and exposition, so a
        # non-federated deploy's series are byte-identical to before
        self.host = host
        self.size = max(1, int(size))
        self.depth = depth
        self.clock = clock
        self.canary_interval_s = float(canary_interval_s)
        self.canary_deadline_s = float(canary_deadline_s)
        self.fence_after_misses = max(1, int(fence_after_misses))
        self.recovery_probe_s = float(recovery_probe_s)
        self.fence_drain_s = float(fence_drain_s)
        self._canary_input_fn = canary_input_fn or (
            lambda: default_canary_input(instance_types)
        )
        self._canary_cache = None
        # durable resident state (solver/vault.py): when wired, a fence
        # re-seeds the encode caches from the newest snapshot and — with
        # zero healthy owners left — tries to revive a fenced owner so
        # survivors restore warm instead of degrading to the cold oracle
        self.vault = vault
        self._oracle = ReferenceSolver()
        self._lock = threading.Lock()
        self._rr = 0  # round-robin cursor for disruption-class routing
        self._closing = False
        self._open: set = set()  # _FleetEntry not yet resolved
        # Superseded deliveries whose superseding owner-ticket is mid-
        # placement (coalescing fires INSIDE service.submit, before _place
        # can register the new entry): (stale_entry, superseding_owner_ticket)
        self._superseded_waiting: list = []
        self.fleet_stats: Dict[str, int] = {
            "fleet_submitted": 0,
            "requeued": 0,
            "oracle_degraded": 0,
            "failovers": 0,
            "recoveries": 0,
            "canary_probes": 0,
            "canary_misses": 0,
            "vault_restores": 0,
        }
        # fence notifications (solver/streaming.py): called AFTER an owner's
        # arena is invalidated, with the fence reason — the streaming model
        # force-rebaselines so resilient replays never extend a universe
        # whose device residency was just declared unknowable. Guarded:
        # listener failures never abort recovery.
        self.fence_listeners: List[Callable[[str], None]] = []
        self.owners: List[FleetOwner] = []
        for i in range(self.size):
            solver = solver_factory(i)
            _set_fault_tag(solver, f"owner-{i}")
            self.owners.append(FleetOwner(
                i, solver,
                SolveService(solver, depth=depth, clock=clock),
                _FleetBreaker(
                    threshold=self.fence_after_misses,
                    probe_interval_s=self.recovery_probe_s,
                    clock=clock,
                ),
            ))
        self._export_health()
        self._stop_event = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        if start_monitor:
            self.start()

    # -- submission (SolveService surface) -----------------------------------

    def submit(self, inp, kind: str = PROVISIONING, rev=None,
               tenant_id: Optional[str] = None) -> SolveTicket:
        if rev is None:
            rev = getattr(inp, "state_rev", None)
        if tenant_id is None:
            tenant_id = getattr(inp, "tenant_id", None)
        with self._lock:
            if self._closing:
                raise ServiceStopped("solver fleet is closed")
        ticket = SolveTicket(kind, rev=rev, tenant_id=tenant_id)
        entry = _FleetEntry(ticket, inp=inp, kind=kind, rev=rev,
                            tenant_id=tenant_id)
        _mint_fleet_trace(entry)
        with self._lock:
            self._open.add(entry)
            self.fleet_stats["fleet_submitted"] += 1
        self._place(entry)
        return ticket

    def submit_fn(self, dispatch_fn: Callable, kind: str = DISRUPTION,
                  tenant_id: Optional[str] = None) -> SolveTicket:
        with self._lock:
            if self._closing:
                raise ServiceStopped("solver fleet is closed")
        ticket = SolveTicket(kind, tenant_id=tenant_id)
        entry = _FleetEntry(ticket, fn=dispatch_fn, kind=kind,
                            tenant_id=tenant_id)
        _mint_fleet_trace(entry)
        with self._lock:
            self._open.add(entry)
            self.fleet_stats["fleet_submitted"] += 1
        self._place(entry)
        return ticket

    def submit_cohort(self, members) -> List[SolveTicket]:
        """Cohort seam for the tenant mux: the whole cohort places on ONE
        owner so the fused dispatch stays fused (each member dict carries
        inp / kind / rev / tenant_id / trace). Every member becomes its own
        _FleetEntry with its own fleet ticket — a fence re-routes survivors
        individually through the ordinary requeue path, so a cohort never
        re-fuses across a failover and per-member delivery guarantees are
        exactly the solo ones."""
        if not members:
            return []
        with self._lock:
            if self._closing:
                raise ServiceStopped("solver fleet is closed")
        entries: List[_FleetEntry] = []
        tickets: List[SolveTicket] = []
        for m in members:
            inp = m["inp"]
            kind = m.get("kind", PROVISIONING)
            rev = m.get("rev")
            if rev is None:
                rev = getattr(inp, "state_rev", None)
            tenant_id = m.get("tenant_id")
            if tenant_id is None:
                tenant_id = getattr(inp, "tenant_id", None)
            ticket = SolveTicket(kind, rev=rev, tenant_id=tenant_id)
            entry = _FleetEntry(ticket, inp=inp, kind=kind, rev=rev,
                                tenant_id=tenant_id)
            with obstrace.attached(m.get("trace")):
                _mint_fleet_trace(entry)
            entries.append(entry)
            tickets.append(ticket)
        with self._lock:
            for entry in entries:
                self._open.add(entry)
                self.fleet_stats["fleet_submitted"] += 1
        self._place_cohort(entries)
        return tickets

    # -- routing / re-routing -------------------------------------------------

    def _pick_owner(self, kind: str) -> Optional[FleetOwner]:
        with self._lock:
            healthy = [o for o in self.owners if not o.fenced]
            if not healthy:
                return None
            if kind == PROVISIONING:
                # all provisioning rides the primary (lowest-index healthy)
                # owner so snapshot coalescing sees every revision
                return healthy[0]
            o = healthy[self._rr % len(healthy)]
            self._rr += 1
            return o

    def _place(self, entry: _FleetEntry, requeued: bool = False) -> None:
        while True:
            owner = self._pick_owner(entry.kind)
            if owner is None:
                if requeued and entry.fn is None:
                    FLEET_REQUEUED.inc(target="oracle", host=self.host)
                self._degrade(entry)
                return
            try:
                # attach the logical request's trace so the owner's service
                # ADOPTS it (pipeline._mint_trace) instead of minting anew —
                # re-routes keep extending one tree
                with obstrace.attached(entry.trace):
                    obstrace.event("fleet.place", owner=owner.name,
                                   requeues=entry.requeues)
                    if entry.fn is not None:
                        ot = owner.service.submit_fn(
                            entry.fn, kind=entry.kind,
                            tenant_id=entry.tenant_id,
                        )
                    else:
                        ot = owner.service.submit(entry.inp, kind=entry.kind,
                                                  rev=entry.rev,
                                                  tenant_id=entry.tenant_id)
            except ServiceStopped:
                continue  # owner fenced between pick and submit; re-pick
            with self._lock:
                fenced_after = owner.fenced
                if not fenced_after:
                    entry.owner = owner
                    entry.owner_ticket = ot
                    owner.outstanding[ot] = entry
                # flush Superseded deliveries parked on the owner ticket this
                # submit just created (their coalescing callback ran inside
                # service.submit, before the mapping above existed)
                flushes = [e for (e, by_ot) in self._superseded_waiting
                           if by_ot is ot]
                if flushes:
                    self._superseded_waiting = [
                        (e, by_ot) for (e, by_ot) in self._superseded_waiting
                        if by_ot is not ot
                    ]
            for stale in flushes:
                self._resolve(stale, error=Superseded(by=entry.ticket))
            if fenced_after:
                # a fence raced this placement: its requeue snapshot cannot
                # have seen the entry, so this callback owns the re-route
                ot.on_done(lambda t, o=owner, e=entry:
                           self._on_owner_done(o, e, t, force_reroute=True))
            else:
                ot.on_done(lambda t, o=owner, e=entry:
                           self._on_owner_done(o, e, t))
            if requeued:
                FLEET_REQUEUED.inc(target="owner", host=self.host)
            return

    def _place_cohort(self, entries: List[_FleetEntry]) -> None:
        """Place a fused cohort on one owner via its submit_cohort seam.
        No healthy owner → members degrade individually (oracle); an owner
        without the seam → members place solo (correct, unfused)."""
        while True:
            owner = self._pick_owner(entries[0].kind)
            if owner is None:
                for entry in entries:
                    self._degrade(entry)
                return
            sub = getattr(owner.service, "submit_cohort", None)
            if sub is None:
                for entry in entries:
                    self._place(entry)
                return
            try:
                ots = sub([
                    dict(inp=e.inp, kind=e.kind, rev=e.rev,
                         tenant_id=e.tenant_id, trace=e.trace)
                    for e in entries
                ])
            except ServiceStopped:
                continue  # owner fenced between pick and submit; re-pick
            with self._lock:
                fenced_after = owner.fenced
                flushes: list = []
                for e, ot in zip(entries, ots):
                    if not fenced_after:
                        e.owner = owner
                        e.owner_ticket = ot
                        owner.outstanding[ot] = e
                    fl = [x for (x, by_ot) in self._superseded_waiting
                          if by_ot is ot]
                    if fl:
                        self._superseded_waiting = [
                            (x, by_ot)
                            for (x, by_ot) in self._superseded_waiting
                            if by_ot is not ot
                        ]
                        flushes.extend((x, e) for x in fl)
            for stale, by in flushes:
                self._resolve(stale, error=Superseded(by=by.ticket))
            for e, ot in zip(entries, ots):
                if fenced_after:
                    ot.on_done(lambda t, o=owner, en=e:
                               self._on_owner_done(o, en, t,
                                                   force_reroute=True))
                else:
                    ot.on_done(lambda t, o=owner, en=e:
                               self._on_owner_done(o, en, t))
            return

    def _degrade(self, entry: _FleetEntry) -> None:
        """No healthy owner: inputs replay on the python oracle (decision-
        compatible by construction — it IS the fallback ladder's last rung);
        device-bound closures cannot (their dispatch is bound to a specific
        owner's device state) and resolve ServiceStopped."""
        if entry.fn is not None:
            self._resolve(entry, error=ServiceStopped(
                "no healthy solver owner for device-bound work"
            ))
            return
        with self._lock:
            self.fleet_stats["oracle_degraded"] += 1
        try:
            with obstrace.attached(entry.trace), obstrace.span("fleet.oracle"):
                # degraded solves stay attributable: the oracle span carries
                # the tenant even though no owner service ever saw the request
                if entry.tenant_id is not None:
                    obstrace.annotate(tenant_id=entry.tenant_id,
                                      kind=entry.kind)
                res = self._oracle.solve(entry.inp)
        except Exception as e:  # noqa: BLE001 — delivered to the caller
            self._resolve(entry, error=e)
            return
        self._resolve(entry, result=res)

    def _reroute(self, entry: _FleetEntry) -> None:
        entry.requeues += 1
        old = entry.owner.name if entry.owner is not None else None
        if entry.trace is not None:
            # trace-level provenance: the span tree continues on a new owner;
            # the link records which owner's fence orphaned it
            entry.trace.add_link("requeued_from", old)
        log.info(
            "solver fleet: requeue #%d (from %s)", entry.requeues, old,
            extra={"solve_id": entry.ticket.solve_id},
        )
        with self._lock:
            self.fleet_stats["requeued"] += 1
        self._place(entry, requeued=True)

    def _resolve(self, entry: _FleetEntry, result=None,
                 error: Optional[BaseException] = None) -> None:
        delivered = entry.ticket._deliver(result=result, error=error)
        if delivered:
            with self._lock:
                self._open.discard(entry)

    def _on_owner_done(self, owner: FleetOwner, entry: _FleetEntry,
                       ticket: SolveTicket, force_reroute: bool = False) -> None:
        with self._lock:
            owner.outstanding.pop(ticket, None)
        if entry.ticket.done():
            return
        err = ticket.error()
        if err is None:
            self._resolve(entry, result=ticket.result())
            return
        if isinstance(err, Superseded):
            # map the superseding OWNER ticket back to its fleet ticket. The
            # coalescing delivery fires INSIDE service.submit — on the thread
            # running _place, BEFORE it can register the new owner ticket —
            # so a missed lookup usually means "mid-placement": park the
            # delivery and let _place flush it once the mapping exists.
            with self._lock:
                by_entry = owner.outstanding.get(err.by) if err.by is not None else None
                if by_entry is None and err.by is not None and not self._closing:
                    self._superseded_waiting.append((entry, err.by))
                    return
            self._resolve(entry, error=Superseded(
                by=by_entry.ticket if by_entry is not None else None
            ))
            return
        if isinstance(err, ServiceStopped):
            if self._closing:
                self._resolve(entry, error=err)
            elif force_reroute or not owner.fenced:
                # spontaneous stop, or a fence whose snapshot missed this
                # entry — the callback owns the re-route
                self._reroute(entry)
            # else: the fence loop re-routes it (ordered requeue)
            return
        self._resolve(entry, error=err)

    # -- fencing / recovery ---------------------------------------------------

    def _fence(self, owner: FleetOwner, reason: str) -> None:
        with self._lock:
            if owner.fenced or self._closing:
                return
            owner.fenced = True
            owner.fence_count += 1
            self.fleet_stats["failovers"] += 1
            survivors = list(owner.outstanding.values())
            owner.outstanding.clear()
        FLEET_FAILOVER.inc(owner=owner.name, host=self.host)
        obstelemetry.note_event("fleet_fence", owner=owner.name, reason=reason)
        log.warning(
            "solver fleet: FENCING %s (%s) — stopping its service, "
            "re-routing %d outstanding request(s)",
            owner.name, reason, len(survivors),
        )
        self._export_health()
        # flight-record BEFORE stop(): stop force-resolves the wedged solve's
        # ticket, which finishes (and thereby closes) its trace — the dump
        # must capture the partial span tree while it is still partial.
        # Guarded: a failed dump must not leave the owner fenced with its
        # service running and survivors never re-routed
        try:
            from ..obs import slo as obsslo

            # tag the dump with the SLO picture at fence time: whether the
            # fence happened inside an already-burning error budget is the
            # first triage question, answered without replaying the windows
            obstrace.dump("fleet_fence", owner=owner.name, fence_reason=reason,
                          fence_count=owner.fence_count,
                          requeued=len(survivors),
                          slo_state=obsslo.health()["state"])
        except Exception:  # noqa: BLE001 — diagnostics never abort the fence
            log.exception("solver fleet: flight-recorder dump failed while "
                          "fencing %s — continuing recovery", owner.name)
        # stop() resolves every ticket the owner's service ever issued:
        # queued fail fast, in-flight get the drain window, wedged ones are
        # force-resolved (ServiceStopped) — nothing can strand
        owner.service.stop(drain_s=self.fence_drain_s)
        # a wedged/failed solve leaves device residency unknowable: drop it
        # so a recovered owner re-adopts from scratch (SPEC.md "Failover
        # semantics" / arena re-adoption)
        inv = getattr(owner.solver, "invalidate_arena", None)
        if inv is not None:
            try:
                inv()
            except Exception:  # noqa: BLE001 — best-effort on a dead owner
                pass
        for listener in list(self.fence_listeners):
            try:
                listener(reason)
            except Exception:  # noqa: BLE001 — diagnostics never abort
                log.exception("solver fleet: fence listener failed")
        # durable resident state (solver/vault.py): the arena invalidation
        # and the streaming re-baseline above just wiped the warm state the
        # survivors' new owner needs — re-seed the encode caches from the
        # newest snapshot so requeued solves adopt instead of rebuilding
        if self.vault is not None:
            try:
                report = self.vault.restore(install=True)
            except Exception:  # noqa: BLE001 — recovery must not depend
                log.exception("solver fleet: vault restore failed during "
                              "fence recovery — continuing cold")
                report = None
            if report is not None:
                with self._lock:
                    self.fleet_stats["vault_restores"] += 1
                log.info(
                    "solver fleet: fence recovery restored vault seq=%d "
                    "(%d donor core(s)) for %s's survivors",
                    report.seq, report.donors_installed, owner.name,
                )
            if self.healthy_owners() == 0:
                # last owner down: with a vault in hand, a revived owner
                # serving warm beats the cold oracle degrade — try a direct
                # canary on each fenced owner before the survivors re-route
                for cand in self.owners:
                    if self._direct_canary(cand):
                        cand.breaker.record_success()
                        self._unfence(cand)
                        obstelemetry.note_event(
                            "fleet_vault_revive", owner=cand.name,
                        )
                        log.info(
                            "solver fleet: revived %s via vault-backed "
                            "fence recovery", cand.name,
                        )
                        break
                    cand.breaker.record_failure()
        for entry in survivors:  # original submission order
            if not entry.ticket.done():
                self._reroute(entry)

    def _unfence(self, owner: FleetOwner) -> None:
        # the old service's dispatcher may still be parked inside the hung
        # XLA call — abandon it (daemon) behind a fresh service
        owner.service = SolveService(owner.solver, depth=self.depth,
                                     clock=self.clock)
        with self._lock:
            owner.fenced = False
            self.fleet_stats["recoveries"] += 1
        log.info("solver fleet: %s recovered — un-fenced behind a fresh "
                 "service (arena re-adopts on first dispatch)", owner.name)
        self._export_health()

    # -- liveness probing -----------------------------------------------------

    def _canary_input(self):
        if self._canary_cache is None:
            self._canary_cache = self._canary_input_fn()
        return self._canary_cache

    def _probe_healthy(self, owner: FleetOwner) -> str:
        """Tiny canary solve through the owner's own pipeline with a hard
        REAL-TIME deadline: a wedged dispatcher never resolves the ticket,
        which is precisely the hang signature no exception path can see."""
        with self._lock:
            self.fleet_stats["canary_probes"] += 1
        t0 = time.monotonic()
        try:
            ticket = owner.service.submit(self._canary_input(), kind=DISRUPTION)
            ticket.result(timeout=self.canary_deadline_s)
        except TimeoutError:
            with self._lock:
                self.fleet_stats["canary_misses"] += 1
            owner.breaker.record_failure()
            log.warning(
                "solver fleet: canary MISS on %s (%d consecutive; fence at %d)",
                owner.name, owner.breaker.consecutive_failures,
                self.fence_after_misses,
            )
            if owner.breaker.state == OPEN:
                self._fence(owner, reason="canary deadline misses")
                return "fenced"
            return "miss"
        except Exception as e:  # noqa: BLE001 — a raising canary is a miss too
            with self._lock:
                self.fleet_stats["canary_misses"] += 1
            owner.breaker.record_failure()
            log.warning("solver fleet: canary ERROR on %s: %s", owner.name, e)
            if owner.breaker.state == OPEN:
                self._fence(owner, reason=f"canary errors ({type(e).__name__})")
                return "fenced"
            return "miss"
        owner.breaker.record_success()
        FLEET_CANARY_LATENCY.observe(time.monotonic() - t0, owner=owner.name,
                                     host=self.host)
        return "ok"

    def _direct_canary(self, owner: FleetOwner) -> bool:
        """Deadline-bounded canary solve DIRECTLY on the owner's solver, on
        a sacrificial daemon thread — never a shared dispatcher — so a
        still-wedged owner costs one thread, not a pipeline. Shared by the
        half-open recovery probe and the fence-time vault revive path;
        breaker accounting is the caller's."""
        box: dict = {}
        done = threading.Event()
        inp = self._canary_input()

        def run():
            try:
                box["result"] = owner.solver.solve(inp)
            except BaseException as e:  # noqa: BLE001 — probe verdict below
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True,
                             name=f"fleet-probe-{owner.name}")
        t.start()
        return done.wait(self.canary_deadline_s) and "error" not in box

    def _probe_fenced(self, owner: FleetOwner) -> str:
        """Half-open recovery probe (injected-clock schedule)."""
        if not owner.breaker.allow():
            return "fenced"
        if not self._direct_canary(owner):
            owner.breaker.record_failure()  # half-open -> re-open
            return "fenced"
        owner.breaker.record_success()
        self._unfence(owner)
        return "recovered"

    def probe_once(self) -> Dict[str, str]:
        """One canary pass over every owner. Called by the watchdog thread
        on its interval, or directly by tests (clock-injected, no sleeps
        beyond the canary deadline itself). Returns owner -> verdict."""
        verdicts: Dict[str, str] = {}
        for owner in self.owners:
            if self._closing:
                break
            with self._lock:
                fenced = owner.fenced
            t0 = time.monotonic()
            verdict = (
                self._probe_fenced(owner) if fenced else self._probe_healthy(owner)
            )
            obstrace.note_canary(owner.name, verdict, time.monotonic() - t0)
            verdicts[owner.name] = verdict
        return verdicts

    def _monitor_loop(self) -> None:
        while not self._stop_event.wait(self.canary_interval_s):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — the watchdog must survive
                log.exception("solver fleet: canary pass crashed")

    def start(self) -> None:
        """Start the background watchdog (daemon). Idempotent."""
        if self._monitor is not None:
            return
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="fleet-canary"
        )
        self._monitor.start()

    # -- health / introspection (SolveService surface) ------------------------

    def _export_health(self) -> None:
        with self._lock:
            healthy = sum(1 for o in self.owners if not o.fenced)
            bits = [(o.name, 0.0 if o.fenced else 1.0) for o in self.owners]
        FLEET_HEALTHY.set(float(healthy), host=self.host)
        for name, bit in bits:
            FLEET_HEALTHY.set(bit, owner=name, host=self.host)

    def healthy_owners(self) -> int:
        with self._lock:
            return sum(1 for o in self.owners if not o.fenced)

    def unresolved(self) -> int:
        """Fleet tickets not yet resolved (the soak harness's dropped-solve
        detector reads this after a full drain: it must be 0)."""
        with self._lock:
            return sum(1 for e in self._open if not e.ticket.done())

    @property
    def solver(self) -> Solver:
        """The primary owner's solver (SolveService-surface compatibility:
        introspection reads through `service.solver`)."""
        return self.owners[0].solver

    @property
    def stats(self) -> Dict[str, int]:
        agg: Dict[str, int] = {}
        for o in self.owners:
            for k, v in o.service.stats.items():
                agg[k] = agg.get(k, 0) + v
        with self._lock:
            agg.update(self.fleet_stats)
            agg["healthy_owners"] = sum(1 for o in self.owners if not o.fenced)
            agg["open"] = len(self._open)
        return agg

    def occupancy(self) -> float:
        return max(o.service.occupancy() for o in self.owners)

    def queue_depth(self) -> int:
        return sum(o.service.queue_depth() for o in self.owners)

    def resume_stats(self) -> Dict[str, float]:
        return self.owners[0].service.resume_stats()

    def shard_stats(self) -> Dict[str, float]:
        return self.owners[0].service.shard_stats()

    def decode_stats(self) -> Dict[str, float]:
        return self.owners[0].service.decode_stats()

    def streaming_stats(self) -> Dict[str, float]:
        return self.owners[0].service.streaming_stats()

    def close(self) -> None:
        """Stop the watchdog and every owner; every fleet ticket resolves
        (ServiceStopped for anything not already delivered)."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
        self._stop_event.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        for owner in self.owners:
            owner.service.stop(drain_s=self.fence_drain_s)
        with self._lock:
            leftover = list(self._open)
            self._open.clear()
        for entry in leftover:
            entry.ticket._deliver(error=ServiceStopped("solver fleet closed"))
