"""Resilient solver execution: deadline, classification, invariant gate,
circuit breaker, fallback routing.

The device is a failure domain the reference control plane never had: XLA
runtime errors, device OOM, compile stalls, and garbage decodes now sit on
the pod-scheduling critical path. `ResilientSolver` wraps any backend behind
the same `Solver` seam and guarantees the provisioner one of two outcomes per
solve: a result that passed the post-solve invariant gate, or an exception
AFTER the whole fallback chain (native → oracle) was exhausted — never a
silently corrupt result, never an unbounded stall.

Layers (each independently clock-injectable and testable):

- **Deadline** — a per-solve bound on the device path. `deadline_mode`
  "thread" enforces it in real time via a watchdog (an abandoned straggler
  thread keeps the doomed device call off the tick path); "posthoc" measures
  the injected clock around the call — deterministic, used by tests that
  script a clock advance into a fault site. Expired solves classify as
  ``timeout`` and replay on the fallback chain.
- **Classification** — failures split into ``timeout``, ``device_error``
  (transient: XLA/runtime/OOM — retrying the device later can succeed),
  ``encode_bug`` (deterministic: the same input will fail forever), and
  ``unknown``. Every fallback is counted by reason
  (``karpenter_tpu_solver_fallback_total``).
- **Invariant gate** — `check_invariants` validates a result BEFORE it can
  reach the provisioner: placements reference real nodes or claim slots, no
  node's free allocatable is oversubscribed (including pod slots), every
  claim's `pod_uids` are exactly the pods placed on it, and errors are
  disjoint from placements. A violating result is rejected and the solve
  replays on the next rung of the chain — a garbage decode can waste a solve,
  but it cannot create a corrupt NodeClaim.
- **Circuit breaker** — after `breaker_threshold` consecutive device-path
  failures the breaker opens and solves go STRAIGHT to fallback (no device
  dispatch, no deadline wait). After `breaker_probe_s` on the injected clock
  a half-open probe re-tries the device: success closes, failure re-opens.
  State is exported as ``karpenter_tpu_solver_breaker_state``
  (0=closed, 1=half-open, 2=open).

SPEC.md "Failure semantics" documents the ladder; tests/test_resilient_solver.py
and the chaos tests drive it via karpenter_tpu/faults.py.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..faults import DeviceError, FaultError
from ..metrics.registry import (
    SOLVER_BREAKER_STATE,
    SOLVER_DEADLINE_LEAKED_THREADS,
    SOLVER_FALLBACK,
)
from ..obs import trace as obstrace
from ..utils.resources import PODS
from .backend import AsyncSolve, ReferenceSolver, Solver
from .encode import quantize_input

log = logging.getLogger("karpenter_tpu")


class SolveTimeout(Exception):
    """The device path exceeded the per-solve deadline."""


class InvariantViolation(Exception):
    """Every rung of the fallback chain produced an invalid result."""


# -- failure classification ---------------------------------------------------

#: transient: retrying the device later can succeed (breaker territory)
DEVICE_ERROR = "device_error"
#: deterministic host/encode/decode bug: same input fails forever
ENCODE_BUG = "encode_bug"
TIMEOUT = "timeout"
UNKNOWN = "unknown"


def classify_failure(exc: BaseException) -> str:
    """Map a device-path exception to a fallback reason."""
    if isinstance(exc, SolveTimeout):
        return TIMEOUT
    if isinstance(exc, DeviceError):
        return DEVICE_ERROR
    if isinstance(exc, FaultError):  # other injected faults default transient
        return DEVICE_ERROR
    name = type(exc).__name__
    mod = type(exc).__module__ or ""
    # XLA/jax runtime surface: XlaRuntimeError (RuntimeError subclass),
    # jaxlib errors, resource exhaustion
    if "Xla" in name or mod.startswith(("jax", "jaxlib")):
        return DEVICE_ERROR
    if isinstance(exc, (RuntimeError, OSError, MemoryError, ConnectionError)):
        return DEVICE_ERROR
    # host-side determinism: shape/index/key/assertion failures in
    # encode/decode repeat on every retry of the same input
    if isinstance(exc, (ValueError, TypeError, KeyError, IndexError, AssertionError)):
        return ENCODE_BUG
    return UNKNOWN


# -- post-solve invariant gate ------------------------------------------------


def check_invariants(qinp, result) -> List[str]:
    """Validate a SolverResult against its (quantized) input. Returns a list
    of violation strings (empty = valid). Mirrors the scheduler's own
    commit-time rules so a correct backend always passes:

    - placements reference input nodes or in-range claim slots;
    - placement/error keys are schedulable input pods, and disjoint;
    - each claim's pod_uids are EXACTLY the pods placed on that slot;
    - no node's free allocatable is oversubscribed (any resource key, and
      one pod slot per pod — scheduler requires free[pods] >= 1 per add).
    """
    violations: List[str] = []
    pods_by_uid = {
        p.meta.uid: p
        for p in qinp.pods
        if not p.scheduling_gated and not p.bound
    }
    nodes = {n.id: n for n in qinp.nodes}
    n_claims = len(result.claims)

    placed_on_claim: Dict[int, set] = {}
    placed_on_node: Dict[str, list] = {}
    for uid, tgt in result.placements.items():
        if uid not in pods_by_uid:
            violations.append(f"placement for unknown/unschedulable pod {uid!r}")
            continue
        if not isinstance(tgt, tuple) or len(tgt) != 2:
            violations.append(f"malformed placement target {tgt!r} for {uid!r}")
        elif tgt[0] == "node":
            if tgt[1] not in nodes:
                violations.append(f"pod {uid!r} placed on phantom node {tgt[1]!r}")
            else:
                placed_on_node.setdefault(tgt[1], []).append(uid)
        elif tgt[0] == "claim":
            if not isinstance(tgt[1], int) or not (0 <= tgt[1] < n_claims):
                violations.append(
                    f"pod {uid!r} placed on out-of-range claim slot {tgt[1]!r} "
                    f"(claims={n_claims})"
                )
            else:
                placed_on_claim.setdefault(tgt[1], set()).add(uid)
        else:
            violations.append(f"unknown placement kind {tgt[0]!r} for {uid!r}")

    overlap = set(result.placements) & set(result.errors)
    if overlap:
        violations.append(
            f"{len(overlap)} pods both placed and errored (e.g. {sorted(overlap)[:3]})"
        )
    for uid in result.errors:
        if uid not in pods_by_uid:
            violations.append(f"error recorded for unknown pod {uid!r}")

    for i, claim in enumerate(result.claims):
        uids = list(claim.pod_uids)
        if len(set(uids)) != len(uids):
            violations.append(f"claim {i} lists duplicate pod uids")
        if set(uids) != placed_on_claim.get(i, set()):
            missing = placed_on_claim.get(i, set()) - set(uids)
            extra = set(uids) - placed_on_claim.get(i, set())
            violations.append(
                f"claim {i} pod_uids inconsistent with placements "
                f"(missing={sorted(missing)[:3]} extra={sorted(extra)[:3]})"
            )

    for node_id, uids in placed_on_node.items():
        free = nodes[node_id].free
        used: Dict[str, int] = {}
        for uid in uids:
            for k, v in pods_by_uid[uid].requests.items():
                if v > 0:
                    used[k] = used.get(k, 0) + v
        for k, v in used.items():
            if v > free.get_(k):
                violations.append(
                    f"node {node_id!r} oversubscribed on {k}: "
                    f"placed={v} free={free.get_(k)}"
                )
        if len(uids) > free.get_(PODS):
            violations.append(
                f"node {node_id!r} pod slots oversubscribed: "
                f"placed={len(uids)} free={free.get_(PODS)}"
            )
    return violations


# -- circuit breaker ----------------------------------------------------------

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
_STATE_GAUGE_VALUE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class CircuitBreaker:
    """Consecutive-failure breaker with clock-injectable half-open probes."""

    def __init__(self, threshold: int = 3, probe_interval_s: float = 30.0,
                 clock=time.monotonic, gauge=None, labels=None):
        self.threshold = max(1, int(threshold))
        self.probe_interval_s = probe_interval_s
        self.clock = clock
        # export target: the global per-process gauge by default; the
        # tenancy layer (solver/tenancy.py) passes its per-tenant gauge +
        # a {"tenant": ...} label set so each tenant's breaker exports its
        # OWN series instead of fighting over one global value
        self._gauge = SOLVER_BREAKER_STATE if gauge is None else gauge
        self._labels = dict(labels) if labels else {}
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._export()

    def _export(self) -> None:
        self._gauge.set(_STATE_GAUGE_VALUE[self._state], **self._labels)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive_failures

    def allow(self) -> bool:
        """May the device path run? Open flips to half-open (one probe
        allowed) once the probe interval elapses on the injected clock."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self.clock() - self._opened_at >= self.probe_interval_s:
                    self._state = HALF_OPEN
                    self._export()
                    return True
                return False
            # HALF_OPEN: one probe is already in flight this interval; route
            # concurrent solves to fallback until it reports
            return False

    def peek_allow(self) -> bool:
        """`allow()` without side effects: would the device path run right
        now? The tenancy scheduler (solver/tenancy.py) scans every tenant's
        breaker per dispatch decision — a mutating scan would flip OPEN ->
        HALF_OPEN (and consume the probe slot) for tenants it never picks."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return self.clock() - self._opened_at >= self.probe_interval_s
            return False  # HALF_OPEN: the probe slot is taken

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state != CLOSED:
                log.info("solver breaker: closed (device probe succeeded)")
            self._state = CLOSED
            self._opened_at = None
            self._export()

    def record_failure(self) -> None:
        opened = False
        with self._lock:
            self._consecutive_failures += 1
            failures = self._consecutive_failures
            if self._state == HALF_OPEN or (
                self._state == CLOSED
                and self._consecutive_failures >= self.threshold
            ):
                if self._state != OPEN:
                    log.warning(
                        "solver breaker: OPEN after %d consecutive device "
                        "failures — solves route straight to fallback; next "
                        "probe in %.0fs",
                        self._consecutive_failures, self.probe_interval_s,
                    )
                    opened = True
                self._state = OPEN
                self._opened_at = self.clock()
            self._export()
        if opened:
            # outside the lock: the hook writes a flight-recorder file
            self._on_open(failures)

    def _on_open(self, failures: int) -> None:
        """CLOSED/HALF_OPEN -> OPEN transition hook: the device path is
        about to be bypassed entirely — flight-record the evidence now."""
        obstrace.dump("breaker_open", failures=failures,
                      threshold=self.threshold)


# -- the wrapper --------------------------------------------------------------


class ResilientSolver(Solver):
    """Deadline + breaker + invariant gate + fallback routing around any
    `Solver`. Transparent on success (the inner result passes through
    untouched — parity with the unwrapped backend is asserted in
    tests/test_solver_parity.py), attribute access delegates to the inner
    solver (`stats`, `warmup`, `prewarm_aot`, ...).

    Resilience is PER-REQUEST, not per-dispatch: one solve_async() call is
    one breaker admission, one deadline window (opened at dispatch, when the
    pipelined SolveService hands the request to the device — queue wait is
    not solve time), one gate check, and at most one fallback replay — even
    when TPUSolver internally re-dispatches for claim-bucket overflow, or
    when the request was one row of a batched speculative-probe frontier.
    Under the SolveService this means a dead device drains each in-flight
    request onto the fallback ladder individually; the breaker trips on
    request failures, never on the fan-out of a single batched dispatch.
    """

    def __init__(
        self,
        inner: Solver,
        fallbacks: Optional[Sequence[Solver]] = None,
        deadline_s: Optional[float] = None,
        deadline_mode: Optional[str] = None,  # "thread" | "posthoc" | None=auto
        breaker: Optional[CircuitBreaker] = None,
        breaker_threshold: int = 3,
        breaker_probe_s: float = 30.0,
        clock=time.monotonic,
    ):
        self.inner = inner
        if fallbacks is None:
            # the existing fallback chain: native C++ core, then the python
            # oracle (NativeSolver degrades to the oracle internally too, but
            # an explicit final rung keeps the ladder honest if native's own
            # decode is what is broken)
            from .native import NativeSolver

            fallbacks = [NativeSolver(), ReferenceSolver()]
        self.fallbacks = list(fallbacks)
        self.deadline_s = deadline_s
        if deadline_mode is None:
            deadline_mode = "thread" if clock is time.monotonic else "posthoc"
        self.deadline_mode = deadline_mode
        self.clock = clock
        self.breaker = breaker or CircuitBreaker(
            threshold=breaker_threshold, probe_interval_s=breaker_probe_s,
            clock=clock,
        )
        self.resilient_stats: Dict[str, int] = {
            "solves": 0,
            "device_path": 0,
            "fallback": 0,
            "gate_rejections": 0,
            "breaker_short_circuits": 0,
        }
        # post-deadline stragglers: abandoned device calls that never
        # returned. Tracked (not just detached) so a wedging backend shows
        # up as a non-zero gauge instead of silent thread accumulation.
        self._strays: List[threading.Thread] = []
        self._strays_lock = threading.Lock()
        self._leak_logged = False

    def __getattr__(self, name):
        # delegation AFTER normal lookup fails: stats/warmup/prewarm_aot/
        # max_claims etc. read through to the wrapped backend
        return getattr(self.inner, name)

    # -- public seam --------------------------------------------------------

    def solve(self, inp):
        return self.solve_async(inp).result()

    def solve_async(self, inp) -> AsyncSolve:
        self.resilient_stats["solves"] += 1
        if not self.breaker.allow():
            self.resilient_stats["breaker_short_circuits"] += 1
            SOLVER_FALLBACK.inc(reason="breaker_open")
            obstrace.annotate(breaker="open", breaker_short_circuit=True)
            return AsyncSolve(lambda: self._fallback_solve(inp))
        self.resilient_stats["device_path"] += 1
        t0 = self.clock()
        inner_async = getattr(self.inner, "solve_async", None)
        handle = None
        if inner_async is not None:
            try:
                # dispatch eagerly: the async pipelining the provisioner seam
                # relies on (host work overlapping device compute) survives
                # the wrapper; the deadline window opened at t0
                handle = inner_async(inp)
            except Exception as e:  # noqa: BLE001 — classified below
                # rebind: `e` is unset once the except block exits, and the
                # lambda runs later (deferred AsyncSolve result)
                exc = e
                return AsyncSolve(lambda: self._handle_failure(inp, exc))

        def finish():
            try:
                if handle is not None:
                    res = self._wait(handle.result, t0)
                else:
                    res = self._wait(lambda: self.inner.solve(inp), t0)
            except Exception as e:  # noqa: BLE001 — classified
                return self._handle_failure(inp, e)
            with obstrace.span("resilient.gate"):
                violations = check_invariants(quantize_input(inp), res)
            if violations:
                self.resilient_stats["gate_rejections"] += 1
                self.breaker.record_failure()
                SOLVER_FALLBACK.inc(reason="invariant_gate")
                obstrace.annotate(gate_rejected=True,
                                  gate_violations=len(violations))
                obstrace.dump(
                    "invariant_gate", backend=type(self.inner).__name__,
                    violations=len(violations), first=violations[0],
                    solve_id=obstrace.current_solve_id(),
                )
                log.error(
                    "solver invariant gate REJECTED a %s result (%d "
                    "violations, e.g. %s) — replaying on fallback chain",
                    type(self.inner).__name__, len(violations), violations[0],
                )
                return self._fallback_solve(inp)
            self.breaker.record_success()
            return res

        return AsyncSolve(finish)

    # -- internals ----------------------------------------------------------

    def _wait(self, fn, t0: float):
        """Run the blocking device-path wait under the deadline."""
        if not self.deadline_s:
            return fn()
        if self.deadline_mode == "posthoc":
            # deterministic mode: measure the injected clock around the call;
            # a fault-plan hook advancing the clock mid-solve trips this
            res = fn()
            elapsed = self.clock() - t0
            if elapsed > self.deadline_s:
                raise SolveTimeout(
                    f"solve exceeded deadline: {elapsed:.3f}s > {self.deadline_s}s"
                )
            return res
        remaining = self.deadline_s - (time.monotonic() - t0)
        if remaining <= 0:
            raise SolveTimeout(f"deadline {self.deadline_s}s expired before wait")
        box: dict = {}
        done = threading.Event()

        def run():
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised in caller
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True, name="resilient-solve")
        t.start()
        if not done.wait(remaining):
            # abandon the straggler: a hung XLA call cannot be cancelled, but
            # it must not hold the control loop hostage. One short bounded
            # join gives an almost-done call its exit; anything still alive
            # after that is accounted as a leaked thread.
            t.join(timeout=0.05)
            if t.is_alive():
                self._track_stray(t)
            raise SolveTimeout(
                f"solve exceeded deadline {self.deadline_s}s (device call abandoned)"
            )
        self._reap_strays()
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _track_stray(self, t: threading.Thread) -> None:
        with self._strays_lock:
            self._strays.append(t)
            self._strays = [s for s in self._strays if s.is_alive()]
            n = len(self._strays)
            first = not self._leak_logged and n > 0
            if first:
                self._leak_logged = True
        if first:
            log.warning(
                "resilient-solve deadline leaked a device thread (%r never "
                "returned after its %.1fs deadline) — the backend is wedged, "
                "not slow; further leaks update "
                "karpenter_solver_deadline_leaked_threads without re-logging",
                t.name, self.deadline_s,
            )
        SOLVER_DEADLINE_LEAKED_THREADS.set(n)

    def _reap_strays(self) -> None:
        """Prune stragglers that eventually returned (their late result was
        discarded); the gauge tracks only the still-wedged ones."""
        with self._strays_lock:
            if not self._strays:
                return
            self._strays = [s for s in self._strays if s.is_alive()]
            n = len(self._strays)
        SOLVER_DEADLINE_LEAKED_THREADS.set(n)

    @property
    def leaked_threads(self) -> int:
        """Stragglers currently alive past their deadline (bench/test seam)."""
        with self._strays_lock:
            self._strays = [s for s in self._strays if s.is_alive()]
            return len(self._strays)

    def _handle_failure(self, inp, exc: BaseException):
        reason = classify_failure(exc)
        obstrace.annotate(failure_class=reason, failure=type(exc).__name__)
        self.breaker.record_failure()
        SOLVER_FALLBACK.inc(reason=reason)
        log.warning(
            "solver %s failed (%s: %s) — classified %r, falling back "
            "(consecutive failures: %d)",
            type(self.inner).__name__, type(exc).__name__, exc, reason,
            self.breaker.consecutive_failures,
        )
        return self._fallback_solve(inp)

    def _fallback_solve(self, inp):
        """Walk the chain; every rung's result faces the same gate."""
        # a replay must never trust device-resident state left by the
        # failed / gate-rejected solve — drop the arena first (argument
        # buffers, checkpoint ring, resident relax-ladder rung tables, AND
        # the mesh-sharded residency: per-device argument shards plus the
        # block-boundary carries that act as per-device checkpoint rings)
        # so the next device solve re-uploads from scratch (solver/arena.py)
        inv = getattr(self.inner, "invalidate_arena", None)
        if inv is not None:
            inv()
        self.resilient_stats["fallback"] += 1
        last_violations: List[str] = []
        with obstrace.span("resilient.fallback"):
            for fb in self.fallbacks:
                obstrace.annotate(rung=type(fb).__name__)
                try:
                    res = fb.solve(inp)
                except Exception as e:  # noqa: BLE001 — try the next rung
                    SOLVER_FALLBACK.inc(reason="fallback_error")
                    log.error("fallback %s failed: %s", type(fb).__name__, e)
                    continue
                last_violations = check_invariants(quantize_input(inp), res)
                if not last_violations:
                    return res
                SOLVER_FALLBACK.inc(reason="invariant_gate")
                log.error(
                    "invariant gate rejected fallback %s result (%s)",
                    type(fb).__name__, last_violations[0],
                )
            raise InvariantViolation(
                "every rung of the fallback chain failed or violated invariants: "
                + (last_violations[0] if last_violations
                   else "no rung produced a result")
            )
