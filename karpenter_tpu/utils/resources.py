"""Integer-exact resource math.

The reference does all resource arithmetic through k8s resource.Quantity
(sigs.k8s.io/karpenter pkg/utils/resources, consumed here per SURVEY.md §2.1).
We re-express quantities as exact integers so that the Python reference solver
and the TPU tensor solver operate on *identical* numbers:

  - cpu                  -> millicores (int)
  - memory / storage     -> bytes (int)
  - everything else      -> integer count (pods, gpus, ...)

The TPU path additionally quantizes to the canonical unit table in
`karpenter_tpu.solver.encode` (cpu: milli, memory: MiB rounded conservatively).
All control-plane bookkeeping stays byte-exact.

Reference behavior spec: pkg/providers/instancetype/types.go:305-451
(computeCapacity), designs/bin-packing.md:17-43 (FFD sort key).
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Mapping

# Canonical well-known resource names (mirror of k8s core v1).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"
NVIDIA_GPU = "nvidia.com/gpu"
AMD_GPU = "amd.com/gpu"
TPU_ACCEL = "google.com/tpu"
AWS_NEURON = "aws.amazon.com/neuron"
HABANA_GAUDI = "habana.ai/gaudi"
POD_ENI = "vpc.amazonaws.com/pod-eni"
EFA = "vpc.amazonaws.com/efa"

_BINARY_SUFFIX = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIX = {
    "n": -3,  # handled specially below (sub-unit)
    "u": -2,
    "m": -1,
    "": 0,
    "k": 1,
    "M": 2,
    "G": 3,
    "T": 4,
    "P": 5,
    "E": 6,
}

_QTY_RE = re.compile(r"^\s*([+-]?[0-9]+(?:\.[0-9]+)?)\s*([A-Za-z]*)\s*$")


def parse_quantity(value: object, resource: str) -> int:
    """Parse a k8s-style quantity into the canonical integer unit.

    cpu -> millicores; all other resources -> base units (bytes or count).
    Fractional results round *up* (a request of 1.5 pods of cpu must reserve
    at least that much), matching the conservative direction for requests.
    """
    if isinstance(value, bool):
        raise ValueError(f"invalid quantity {value!r}")
    if isinstance(value, int):
        return value * 1000 if resource == CPU else value
    if isinstance(value, float):
        return _ceil_scaled(value, 1000 if resource == CPU else 1)
    m = _QTY_RE.match(str(value))
    if not m:
        raise ValueError(f"invalid quantity {value!r} for {resource}")
    num_s, suffix = m.groups()

    scale = 1000 if resource == CPU else 1
    if suffix in _BINARY_SUFFIX:
        mult = _BINARY_SUFFIX[suffix] * scale
        return _ceil_rational(num_s, mult)
    if suffix in _DECIMAL_SUFFIX:
        exp = _DECIMAL_SUFFIX[suffix]
        # value * 10^(3*exp) * scale, exactly.
        num = _ceil_rational(num_s, 10 ** (3 * exp) * scale) if exp >= 0 else None
        if num is not None:
            return num
        # negative exponents: divide
        return _ceil_rational_div(num_s, 10 ** (3 * -exp), scale)
    raise ValueError(f"invalid quantity suffix {suffix!r} in {value!r}")


def _ceil_scaled(value: float, scale: int) -> int:
    from math import ceil

    return ceil(value * scale)


def _ceil_rational(num_s: str, mult: int) -> int:
    """ceil(decimal-string * mult) computed exactly with integers."""
    neg = num_s.startswith("-")
    num_s = num_s.lstrip("+-")
    if "." in num_s:
        whole, frac = num_s.split(".")
    else:
        whole, frac = num_s, ""
    denom = 10 ** len(frac)
    numer = int(whole + frac) if whole + frac else 0
    total = numer * mult
    q, r = divmod(total, denom)
    if neg:
        return -q  # ceil of a negative = truncate toward zero
    return q + (1 if r else 0)


def _ceil_rational_div(num_s: str, div: int, scale: int) -> int:
    neg = num_s.startswith("-")
    num_s = num_s.lstrip("+-")
    if "." in num_s:
        whole, frac = num_s.split(".")
    else:
        whole, frac = num_s, ""
    denom = 10 ** len(frac) * div
    numer = (int(whole + frac) if whole + frac else 0) * scale
    q, r = divmod(numer, denom)
    if neg:
        return -q
    return q + (1 if r else 0)


def format_quantity(amount: int, resource: str) -> str:
    """Human-readable rendering of a canonical integer quantity."""
    if resource == CPU:
        if amount % 1000 == 0:
            return str(amount // 1000)
        return f"{amount}m"
    if resource in (MEMORY, EPHEMERAL_STORAGE):
        for suffix in ("Ti", "Gi", "Mi", "Ki"):
            unit = _BINARY_SUFFIX[suffix]
            if amount % unit == 0 and amount != 0:
                return f"{amount // unit}{suffix}"
        return str(amount)
    return str(amount)


class Resources(Dict[str, int]):
    """A resource vector: name -> canonical integer amount.

    Missing keys are zero. All ops are exact integer arithmetic.
    """

    @classmethod
    def parse(cls, spec: Mapping[str, object] | None) -> "Resources":
        r = cls()
        for k, v in (spec or {}).items():
            r[k] = parse_quantity(v, k)
        return r

    def get_(self, key: str) -> int:
        return self.get(key, 0)

    def add(self, other: Mapping[str, int]) -> "Resources":
        out = Resources(self)
        for k, v in other.items():
            out[k] = out.get(k, 0) + v
        return out

    def sub(self, other: Mapping[str, int]) -> "Resources":
        out = Resources(self)
        for k, v in other.items():
            out[k] = out.get(k, 0) - v
        return out

    def fits(self, capacity: Mapping[str, int]) -> bool:
        """True if every requested amount is <= capacity (missing = 0)."""
        return all(v <= capacity.get(k, 0) for k, v in self.items() if v > 0)

    def exceeds(self, limit: Mapping[str, int]) -> bool:
        """True if any limited resource is exceeded (limit keys only)."""
        return any(self.get(k, 0) > v for k, v in limit.items())

    def positive(self) -> "Resources":
        return Resources({k: v for k, v in self.items() if v > 0})

    def max(self, other: Mapping[str, int]) -> "Resources":
        out = Resources(self)
        for k, v in other.items():
            if v > out.get(k, 0):
                out[k] = v
        return out

    def copy(self) -> "Resources":
        return Resources(self)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        inner = ", ".join(f"{k}={format_quantity(v, k)}" for k, v in sorted(self.items()))
        return f"Resources({inner})"


def merge(specs: Iterable[Mapping[str, int]]) -> Resources:
    out = Resources()
    for s in specs:
        out = out.add(s)
    return out


ZERO = Resources()
