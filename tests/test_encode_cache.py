"""Incremental encode cache (solver/encode_cache.py): bit-transparency,
invalidation classes, delta-channel stamps, and solver parity with the
cache hot.

The patch path must be SEMANTICS-INVISIBLE: for any pod-set delta it
accepts, the patched `EncodedInput` must equal a from-scratch build field
by field (SPEC.md "Encode cache"). Deltas the patch cannot express must
fall back to a full rebuild, never to a stale core.
"""

import dataclasses
import random

import numpy as np

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.controllers import store as st
from karpenter_tpu.provisioning.scheduler import SolverInput, ffd_sort_with_sigs
from karpenter_tpu.solver import encode as em
from karpenter_tpu.solver import encode_cache as ec
from karpenter_tpu.solver.encode import EncodedInput, quantize_input
from karpenter_tpu.state.cluster import Cluster

from tests.test_zone_device import (
    TSC1,
    ZONES,
    assert_zone_parity,
    mknode,
    mkpod,
    pool,
)

# Pod spec templates with DISTINCT (cpu, memory) sizes: the FFD block order
# (and with it the distinct-signature sequence) is then independent of pod
# uids, so any per-template multiplicity produces the same group universe.
_TEMPLATES = (
    dict(cpu="2", mem="4Gi", labels={"app": "w"}, topology_spread=[TSC1]),
    dict(cpu="1500m", mem="3Gi", labels={"app": "w"}),
    dict(cpu="1", mem="2Gi", labels={"app": "x"}),
    dict(cpu="500m", mem="1Gi", labels={"tier": "batch"}),
)


def _pods(tag, counts):
    out = []
    for t, cnt in enumerate(counts):
        for i in range(cnt):
            out.append(mkpod(f"{tag}-t{t}-{i:03d}", **_TEMPLATES[t]))
    return out


def _nodes():
    return [
        mknode("na", "zone-1a", matching=2),
        mknode("nb", "zone-1b", matching=0),
        mknode("nc", "zone-1c", matching=1),
    ]


def _inp(pods, nodes=None, nodepools=None, zones=ZONES, **kw):
    return quantize_input(
        SolverInput(
            pods=pods,
            nodes=_nodes() if nodes is None else nodes,
            nodepools=[pool()] if nodepools is None else nodepools,
            zones=zones,
            **kw,
        )
    )


def assert_encoded_equal(a: EncodedInput, b: EncodedInput):
    """Field-by-field equality over the full EncodedInput surface — arrays
    compare by dtype + contents, pods by uid (fresh builds make new lists)."""
    for f in dataclasses.fields(EncodedInput):
        if f.name == "core_rev":
            # provenance tag, not content: a patched encode keeps its
            # donor's revision while a fresh build mints a new one — the
            # divergence is the argument arena's staleness signal
            # (solver/arena.py), so transparency excludes it
            continue
        va, vb = getattr(a, f.name), getattr(b, f.name)
        if f.name == "group_pods":
            ua = [[p.meta.uid for p in g] for g in va]
            ub = [[p.meta.uid for p in g] for g in vb]
            assert ua == ub, f"group_pods: {ua} != {ub}"
        elif isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
            assert isinstance(va, np.ndarray) and isinstance(vb, np.ndarray), (
                f"{f.name}: {type(va)} vs {type(vb)}"
            )
            assert va.dtype == vb.dtype, f"{f.name}: dtype {va.dtype} != {vb.dtype}"
            assert va.shape == vb.shape, f"{f.name}: shape {va.shape} != {vb.shape}"
            assert np.array_equal(va, vb), f.name
        else:
            assert va == vb, f"{f.name}: {va!r} != {vb!r}"


def _fresh(inp):
    """Force a from-scratch encode (empty donor cache), restoring nothing —
    callers re-seed as needed."""
    em._CORE_CACHE.clear()
    return em.encode(inp)


class TestPatchTransparency:
    def test_exact_hit_returns_identical_encode(self):
        em._CORE_CACHE.clear()
        ec.reset_stats()
        inp = _inp(_pods("hit", (4, 3, 2, 2)))
        a = em.encode(inp)
        b = em.encode(inp)
        assert ec.STATS == {"hits": 1, "patches": 0, "rebuilds": 1,
                            "vault_adopts": 0}, ec.STATS
        assert_encoded_equal(a, b)

    def test_patched_equals_fresh_field_by_field(self):
        """Property suite: random per-template multiplicities (all-new pod
        objects, new uids — uids are NOT part of the signature) must patch,
        and the patched encode must equal a from-scratch build exactly."""
        rng = random.Random(7)
        em._CORE_CACHE.clear()
        ec.reset_stats()
        em.encode(_inp(_pods("base", (5, 4, 3, 2))))
        assert ec.STATS["rebuilds"] == 1
        for trial in range(8):
            counts = tuple(rng.randint(1, 9) for _ in _TEMPLATES)
            inp2 = _inp(_pods(f"d{trial}", counts))
            patched = em.encode(inp2)
            assert ec.STATS["patches"] == trial + 1, (trial, ec.STATS)
            fresh = _fresh(inp2)  # rebuild becomes the next trial's donor
            assert_encoded_equal(patched, fresh)

    def test_patch_after_removals_within_groups(self):
        """Same pod OBJECTS minus a subset (every group keeps >=1 pod) — the
        bound-pods / disruption-subset delta class."""
        rng = random.Random(11)
        em._CORE_CACHE.clear()
        ec.reset_stats()
        base = _pods("rm", (6, 5, 4, 3))
        nodes = _nodes()
        em.encode(_inp(base, nodes=nodes))
        by_tpl = {}
        for p in base:
            by_tpl.setdefault(p.meta.name.split("-")[1], []).append(p)
        kept = []
        for grp in by_tpl.values():
            k = rng.randint(1, len(grp))
            kept.extend(rng.sample(grp, k))
        inp2 = _inp(kept, nodes=nodes)
        patched = em.encode(inp2)
        assert ec.STATS["patches"] == 1, ec.STATS
        assert_encoded_equal(patched, _fresh(inp2))


class TestInvalidation:
    """Delta classes the patch cannot express MUST take the rebuild path
    (SPEC.md "Encode cache" invalidation rules)."""

    def _seed(self, counts=(3, 3, 2, 2)):
        em._CORE_CACHE.clear()
        ec.reset_stats()
        em.encode(_inp(_pods("seed", counts)))
        assert ec.STATS == {"hits": 0, "patches": 0, "rebuilds": 1,
                            "vault_adopts": 0}

    def test_new_signature_rebuilds(self):
        self._seed()
        extra = _pods("ns", (3, 3, 2, 2))
        extra.append(mkpod("ns-novel", cpu="250m", mem="512Mi",
                           labels={"brand": "new"}))
        em.encode(_inp(extra))
        assert ec.STATS["patches"] == 0 and ec.STATS["rebuilds"] == 2, ec.STATS

    def test_vanished_group_rebuilds(self):
        self._seed()
        em.encode(_inp(_pods("vg", (3, 3, 2, 0))))  # template 3 gone
        assert ec.STATS["patches"] == 0 and ec.STATS["rebuilds"] == 2, ec.STATS

    def test_catalog_change_rebuilds(self):
        self._seed()
        em.encode(_inp(_pods("cc", (3, 3, 2, 2)), nodepools=[pool(weight=5)]))
        assert ec.STATS["patches"] == 0 and ec.STATS["rebuilds"] == 2, ec.STATS

    def test_zone_universe_change_rebuilds(self):
        self._seed()
        em.encode(_inp(_pods("zc", (3, 3, 2, 2)), zones=ZONES[:2]))
        assert ec.STATS["patches"] == 0 and ec.STATS["rebuilds"] == 2, ec.STATS

    def test_presorted_inputs_bypass_the_cache(self):
        self._seed()
        pods = _pods("ps", (3, 3, 2, 2))
        srt = ffd_sort_with_sigs(pods)[0]
        n = len(em._CORE_CACHE)
        em.encode(
            SolverInput(pods=srt, nodes=[], nodepools=[pool()], zones=ZONES,
                        presorted=True)
        )
        assert ec.STATS == {"hits": 0, "patches": 0, "rebuilds": 1,
                            "vault_adopts": 0}, ec.STATS
        assert len(em._CORE_CACHE) == n  # never cached, never a donor


class TestStateRevStamp:
    def test_stamp_skips_deep_catalog_compare(self):
        """An equal (tracker identity, catalog element) stamp prefix proves
        the deep pools/daemonset segment without the tuple compare; a
        different tracker object with equal counters must NOT."""
        trk = object()
        stamp = (trk, (1, (0, 0, -1)), 7, 7)
        em._CORE_CACHE.clear()
        ec.reset_stats()
        inp = _inp(_pods("sr", (3, 2, 2, 1)), state_rev=stamp)
        em.encode(inp)  # donor entry carries the stamp
        pods_f = [p for p in inp.pods
                  if not p.scheduling_gated and p.node_name is None]
        key, _ids = em._core_key(pods_f, inp)
        presort = ffd_sort_with_sigs(pods_f, presorted=False)
        structure = em._group_structure(presort[0], presort[1])
        # fabricate a DIFFERENT deep catalog segment: only the stamp can match
        fake = key[:2] + (("other-pools",), key[3]) + key[4:]
        assert ec.try_patch(fake, presort, structure, em._CORE_CACHE,
                            stamp) is not None
        assert ec.try_patch(fake, presort, structure, em._CORE_CACHE,
                            None) is None
        other = (object(), (1, (0, 0, -1)), 7, 7)  # equal counters, new tracker
        assert ec.try_patch(fake, presort, structure, em._CORE_CACHE,
                            other) is None
        # the cheap zones/cts/policy segment is ALWAYS compared, stamp or not
        fake2 = key[:4] + (("zone-9z",),) + key[5:]
        assert ec.try_patch(fake2, presort, structure, em._CORE_CACHE,
                            stamp) is None

    def test_encode_deltas_counters(self):
        from karpenter_tpu.api.objects import (
            NodeClaimTemplate,
            NodePool,
            ObjectMeta,
        )

        store = st.Store()
        cluster = Cluster(store)
        deltas = cluster.encode_deltas
        t0, c0, p0, n0 = deltas.snapshot()
        assert t0 is deltas
        store.create(st.PODS, mkpod("ed-p0"))
        store.create(
            st.NODEPOOLS,
            NodePool(meta=ObjectMeta(name="ed"), template=NodeClaimTemplate()),
        )
        _, c1, p1, n1 = deltas.snapshot()
        assert p1 > p0 and c1 > c0
        # stamps with the same tracker and catalog element compare equal;
        # any catalog motion breaks the prefix
        assert (t0, (c1, "tok"))[:2] == (deltas, (c1, "tok"))
        assert (t0, (c0, "tok")) != (t0, (c1, "tok"))


class TestParityWithCacheHot:
    def test_solver_parity_on_patched_encode(self):
        """End-to-end: solve a base input, then a delta input whose encode is
        served by the patch path — reference/TPU parity must hold on both."""
        em._CORE_CACHE.clear()
        ec.reset_stats()
        base = _pods("par", (6, 4, 3, 2))
        assert_zone_parity(
            SolverInput(pods=base, nodes=_nodes(), nodepools=[pool()],
                        zones=ZONES),
            expect_device=None,
        )
        assert ec.STATS["rebuilds"] >= 1
        before = ec.STATS["patches"]
        delta = _pods("par2", (4, 6, 1, 5))
        assert_zone_parity(
            SolverInput(pods=delta, nodes=_nodes(), nodepools=[pool()],
                        zones=ZONES),
            expect_device=None,
        )
        assert ec.STATS["patches"] > before, ec.STATS
