"""Stacked same-axis TSC + positive affinity on ONE pod, ON DEVICE.

Round 5 (late): the zoned engine's allowed set already intersects the
spread budget with the affinity present-set — exactly the oracle's
sequential per-term narrowing — so a pod owning one TSC AND one positive
affinity on the same axis no longer falls back. That also unlocks the
Respect-mode relax loop for pods carrying a ScheduleAnyway spread plus a
weighted affinity (they materialize to this shape). Multiple terms of the
SAME kind still route to the oracle. Parity is the contract, fuzz +
corner-pinned; native (C++) covered too.
"""

import random

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import PodAffinityTerm, TopologySpreadConstraint
from karpenter_tpu.provisioning.scheduler import SolverInput

from tests.test_mixed_axis_device import CTS, ct_node, ctsc, mkinp
from tests.test_zone_device import (
    TSC1,
    TSC2,
    ZONES,
    assert_zone_parity,
    mknode,
    mkpod,
    pool,
)


def zaff(sel):
    return PodAffinityTerm(label_selector=sel, topology_key=wk.ZONE_LABEL, anti=False)


def caff(sel):
    return PodAffinityTerm(
        label_selector=sel, topology_key=wk.CAPACITY_TYPE_LABEL, anti=False
    )


class TestStackedOnDevice:
    def test_nonmember_affinity_never_bootstraps(self):
        # stacked pod whose affinity matches nobody (not even itself):
        # unschedulable on both paths
        pods = [mkpod("g0", labels={"app": "w"}, topology_spread=[TSC1],
                      affinity_terms=[zaff({"ghost": "x"})])]
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )
        assert tpu.errors

    def test_owner_not_member_tsc_with_member_affinity(self):
        tsc_other = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"other": "y"})
        pods = [mkpod(f"o{i}", labels={"svc": "db"}, topology_spread=[tsc_other],
                      affinity_terms=[zaff({"svc": "db"})]) for i in range(5)]
        nodes = [mknode("na", "zone-1a", matching=2, sel={"other": "y"}),
                 mknode("nb", "zone-1b")]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )

    def test_tsc_vs_affinity_zone_conflict(self):
        # members pinned on a count-skewed zone: the affinity restricts to
        # the member zone while the spread wants the min-count zone — the
        # joint set must match the oracle's narrowing
        nodes = [mknode("na", "zone-1a", matching=3, sel={"svc": "db"}),
                 mknode("nb", "zone-1b")]
        pods = [mkpod(f"m{i}", labels={"svc": "db", "app": "w"},
                      topology_spread=[TSC1], affinity_terms=[zaff({"svc": "db"})])
                for i in range(6)]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )

    def test_stacked_amid_mega_spread_run(self):
        pods = [mkpod(f"w{i:03d}", labels={"app": "w"}, topology_spread=[TSC1])
                for i in range(60)]
        pods += [mkpod("st", labels={"app": "w", "svc": "db"},
                       topology_spread=[TSC1], affinity_terms=[zaff({"svc": "db"})])]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_stacked_ct_axis(self):
        pods = [mkpod(f"c{i}", labels={"tier": "ct"},
                      topology_spread=[ctsc({"tier": "ct"})],
                      affinity_terms=[caff({"tier": "ct"})]) for i in range(4)]
        assert_zone_parity(mkinp(pods))

    def test_double_affinity_still_falls_back(self):
        pods = [mkpod("d0", labels={"a": "1", "b": "2"},
                      affinity_terms=[zaff({"a": "1"}), zaff({"b": "2"})])]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES),
            expect_device=False,
        )

    def test_native_stacked_parity(self):
        from karpenter_tpu.solver.backend import ReferenceSolver, quantize_input
        from karpenter_tpu.solver.native import NativeSolver

        nodes = [mknode("na", "zone-1a", matching=3, sel={"svc": "db"}),
                 mknode("nb", "zone-1b")]
        pods = [mkpod(f"m{i}", labels={"svc": "db", "app": "w"},
                      topology_spread=[TSC1], affinity_terms=[zaff({"svc": "db"})])
                for i in range(6)]
        inp = SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        ns = NativeSolver()
        out = ns.solve(inp)
        ref = ReferenceSolver().solve(quantize_input(inp))
        assert out.placements == ref.placements
        assert ns.stats["native_solves"] == 1, ns.stats


@pytest.mark.parametrize("seed", range(10))
def test_stacked_fuzz(seed):
    """Stacked pods on both axes beside plain spreads, antis, and existing
    nodes; device parity for every seed (no stacked-kind duplicates)."""
    rng = random.Random(11000 + seed)
    pods = []
    for i in range(rng.randrange(4, 18)):
        r = rng.random()
        if r < 0.3:
            pods.append(mkpod(f"s{i}", labels={"app": "w", "svc": "db"},
                              topology_spread=[rng.choice([TSC1, TSC2])],
                              affinity_terms=[zaff(rng.choice(
                                  [{"svc": "db"}, {"app": "w"}]))]))
        elif r < 0.45:
            pods.append(mkpod(f"c{i}", labels={"tier": "ct"},
                              topology_spread=[ctsc({"tier": "ct"},
                                                    skew=rng.choice([1, 2]))],
                              affinity_terms=[caff({"tier": "ct"})]))
        elif r < 0.6:
            pods.append(mkpod(f"t{i}", labels={"app": "w"}, topology_spread=[TSC1]))
        elif r < 0.7:
            pods.append(mkpod(f"a{i}", labels={"lock": f"l{i % 3}"},
                              affinity_terms=[PodAffinityTerm(
                                  label_selector={"lock": f"l{i % 3}"},
                                  topology_key=wk.ZONE_LABEL, anti=True)]))
        else:
            pods.append(mkpod(f"x{i}", labels=rng.choice(
                [{"svc": "db"}, {"app": "w"}, {}])))
    nodes = [ct_node(f"n{j}", rng.choice(ZONES), rng.choice(CTS),
                     matching=rng.randrange(0, 3),
                     sel=rng.choice([{"app": "w"}, {"svc": "db"}, {"tier": "ct"}]))
             for j in range(rng.randrange(0, 4))]
    assert_zone_parity(mkinp(pods, nodes), expect_device=None)
