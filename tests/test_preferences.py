"""Preference semantics: ScheduleAnyway TSCs, weighted pod-affinity, and
--preference-policy (scheduling.md:212-219; settings.md:38).

Preferences are treated as required and relaxed one at a time by ascending
weight; policy Ignore drops them up front (and keeps the solve on device).
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    TopologySpreadConstraint,
)
from karpenter_tpu.catalog.catalog import CatalogSpec, generate
from karpenter_tpu.provisioning.scheduler import (
    ExistingNode,
    NodePoolSpec,
    SolverInput,
)
from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver, quantize_input
from karpenter_tpu.utils.resources import Resources

CATALOG = generate(CatalogSpec())
ZONES = ("zone-1a", "zone-1b", "zone-1c")


def pool(name="default", reqs=None):
    r = Requirements.of(Requirement.create(wk.NODEPOOL_LABEL, IN, [name]))
    if reqs:
        r = r.union(reqs)
    return NodePoolSpec(name=name, weight=0, requirements=r, taints=[], instance_types=CATALOG)


def mkpod(name, cpu="500m", mem="512Mi", labels=None, **kw):
    return Pod(
        meta=ObjectMeta(name=name, uid=name, labels=labels or {}),
        requests=Resources.parse({"cpu": cpu, "memory": mem}),
        **kw,
    )


def mknode(nid, zone, free_cpu="8"):
    free = Resources.parse({"cpu": free_cpu, "memory": "32Gi"})
    free["pods"] = 110
    return ExistingNode(
        id=nid,
        labels={
            wk.ZONE_LABEL: zone,
            wk.CAPACITY_TYPE_LABEL: "on-demand",
            wk.HOSTNAME_LABEL: nid,
            wk.ARCH_LABEL: "amd64",
            wk.OS_LABEL: "linux",
        },
        taints=[],
        free=free,
    )


def solve(inp):
    return ReferenceSolver().solve(quantize_input(inp))


class TestScheduleAnywaySpread:
    def _pods(self, n):
        tsc = TopologySpreadConstraint(
            max_skew=1,
            topology_key=wk.ZONE_LABEL,
            when_unsatisfiable="ScheduleAnyway",
            label_selector={"app": "soft"},
        )
        return [
            mkpod(f"s{i}", labels={"app": "soft"}, topology_spread=[tsc])
            for i in range(n)
        ]

    def test_honored_when_satisfiable(self):
        # three pods, three zones of capacity: the soft spread behaves like a
        # hard one and lands one per zone
        inp = SolverInput(
            pods=self._pods(3), nodes=[], nodepools=[pool()], zones=ZONES
        )
        res = solve(inp)
        assert not res.errors
        zones = set()
        for c in res.claims:
            zr = c.requirements.get(wk.ZONE_LABEL)
            assert zr is not None
            zones.update(zr.values_list())
        assert len(zones) == 3

    def test_relaxed_when_impossible(self):
        # the pool only offers one zone: a HARD maxSkew=1 spread would leave
        # pods unschedulable past the first; the soft one relaxes instead
        one_zone = pool(
            reqs=Requirements.of(Requirement.create(wk.ZONE_LABEL, IN, ["zone-1a"]))
        )
        inp = SolverInput(
            pods=self._pods(3), nodes=[], nodepools=[one_zone], zones=ZONES
        )
        res = solve(inp)
        assert not res.errors, res.errors

        # hard variant really is impossible — proves relaxation did the work
        hard = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "soft"}
        )
        pods = [
            mkpod(f"h{i}", labels={"app": "soft"}, topology_spread=[hard])
            for i in range(3)
        ]
        res_hard = solve(
            SolverInput(pods=pods, nodes=[], nodepools=[one_zone], zones=ZONES)
        )
        assert res_hard.errors


class TestWeightedPodAffinity:
    def test_weighted_anti_honored_when_capacity_allows(self):
        term = PodAffinityTerm(
            label_selector={"svc": "db"},
            topology_key=wk.ZONE_LABEL,
            anti=True,
            weight=100,
        )
        pods = [
            mkpod(f"db{i}", labels={"svc": "db"}, affinity_terms=[term])
            for i in range(3)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        res = solve(inp)
        assert not res.errors
        zones = [
            tuple(sorted(c.requirements.get(wk.ZONE_LABEL).values_list()))
            for c in res.claims
        ]
        assert len(set(zones)) == len(zones) == 3, zones

    def test_weighted_anti_relaxed_when_impossible(self):
        # only one zone available: required anti would strand 2 pods; the
        # weighted term relaxes and all three schedule
        term = PodAffinityTerm(
            label_selector={"svc": "db"},
            topology_key=wk.ZONE_LABEL,
            anti=True,
            weight=100,
        )
        pods = [
            mkpod(f"db{i}", labels={"svc": "db"}, affinity_terms=[term])
            for i in range(3)
        ]
        one_zone = pool(
            reqs=Requirements.of(Requirement.create(wk.ZONE_LABEL, IN, ["zone-1b"]))
        )
        res = solve(SolverInput(pods=pods, nodes=[], nodepools=[one_zone], zones=ZONES))
        assert not res.errors, res.errors

    def test_relax_order_by_ascending_weight(self):
        # two soft anti terms, weights 10 (svc) and 90 (tier); only two zones
        # of capacity for three mutually-exclusive pods: the LOW-weight term
        # must be sacrificed first, keeping the heavy one satisfied
        nodes = [mknode("na", "zone-1a"), mknode("nb", "zone-1b")]
        def pods():
            out = []
            for i in range(3):
                out.append(
                    mkpod(
                        f"p{i}",
                        labels={"svc": "s", "tier": "t" if i < 2 else "u"},
                        affinity_terms=[
                            PodAffinityTerm({"svc": "s"}, wk.ZONE_LABEL, anti=True, weight=10),
                            PodAffinityTerm({"tier": "t"}, wk.ZONE_LABEL, anti=True, weight=90),
                        ],
                    )
                )
            return out
        one_pool = pool(
            reqs=Requirements.of(
                Requirement.create(wk.ZONE_LABEL, IN, ["zone-1a", "zone-1b"])
            )
        )
        res = solve(SolverInput(pods=pods(), nodes=nodes, nodepools=[one_pool], zones=ZONES))
        assert not res.errors, res.errors
        # the two tier=t pods must sit in different zones (heavy term held)
        zone_of = {}
        for uid, tgt in res.placements.items():
            if tgt[0] == "node":
                zone_of[uid] = "zone-1a" if tgt[1] == "na" else "zone-1b"
        claims_zone = {
            i: tuple(c.requirements.get(wk.ZONE_LABEL).values_list())
            for i, c in enumerate(res.claims)
        }
        for uid, tgt in res.placements.items():
            if tgt[0] == "claim":
                zone_of[uid] = claims_zone[tgt[1]][0]
        assert zone_of["p0"] != zone_of["p1"], zone_of


class TestPreferencePolicy:
    def test_ignore_drops_preferred_node_affinity(self):
        prefs = [(50, Requirements.of(Requirement.create("nonexistent-label", IN, ["x"])))]
        pods = [mkpod("p0", preferred_node_affinity=prefs)]
        inp = SolverInput(
            pods=pods, nodes=[], nodepools=[pool()], zones=ZONES,
            preference_policy="Ignore",
        )
        res = solve(inp)
        assert not res.errors

    def test_ignore_keeps_device_path(self):
        prefs = [(50, Requirements.of(Requirement.create(wk.ARCH_LABEL, IN, ["arm64"])))]
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL,
            when_unsatisfiable="ScheduleAnyway", label_selector={"app": "x"},
        )
        pods = [
            mkpod(f"p{i}", labels={"app": "x"},
                  preferred_node_affinity=list(prefs), topology_spread=[tsc])
            for i in range(4)
        ]
        inp = SolverInput(
            pods=pods, nodes=[], nodepools=[pool()], zones=ZONES,
            preference_policy="Ignore",
        )
        solver = TPUSolver()
        res = solver.solve(inp)
        assert not res.errors
        assert solver.stats["device_solves"] == 1, solver.stats

    def test_respect_serves_preferred_node_affinity_on_device(self):
        # round 5 (late): preferred node affinity materializes into the
        # required node-affinity term inside the relax loop — served on
        # device, honored when satisfiable
        prefs = [(50, Requirements.of(Requirement.create(wk.ARCH_LABEL, IN, ["arm64"])))]
        pods = [mkpod("p0", preferred_node_affinity=prefs)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        solver = TPUSolver()
        res = solver.solve(inp)
        assert not res.errors
        assert solver.stats["device_solves"] == 1, solver.stats
        # the preference was honored: the claim narrowed to arm64 types
        arch = res.claims[0].requirements.get(wk.ARCH_LABEL)
        assert arch is not None and arch.values_list() == ["arm64"]
