"""--enable-profiling endpoints (the pprof analog, settings.md:23):
sampling profile + all-thread stack dump on the metrics port, 404 when the
flag is off."""

import threading
import time
import urllib.request

from karpenter_tpu.operator.__main__ import serve_endpoints
from karpenter_tpu.operator.profiling import dump_stacks, sample_profile


def _get(port, path):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, ""


def test_sample_profile_sees_other_threads():
    stop = threading.Event()

    def busy_loop_fn():
        while not stop.is_set():
            sum(range(500))

    t = threading.Thread(target=busy_loop_fn, name="busy", daemon=True)
    t.start()
    try:
        report = sample_profile(0.3, interval_s=0.005)
    finally:
        stop.set()
    assert "busy_loop_fn" in report, report[:400]
    assert "thread-samples" in report


def test_stack_dump_lists_threads():
    out = dump_stacks()
    assert "--- thread" in out


def test_endpoints_gated_on_flag():
    srv_off = serve_endpoints(0, 0, enable_profiling=False)
    port_off = srv_off.server_address[1]
    status, _ = _get(port_off, "/debug/pprof/stacks")
    assert status == 404
    srv_on = serve_endpoints(0, 0, enable_profiling=True)
    port_on = srv_on.server_address[1]
    status, body = _get(port_on, "/debug/pprof/stacks")
    assert status == 200 and "--- thread" in body
    status, body = _get(port_on, "/debug/pprof/profile?seconds=0.2")
    assert status == 200 and "thread-samples" in body
    status, _ = _get(port_on, "/debug/pprof/nope")
    assert status == 404
    srv_off.shutdown()
    srv_on.shutdown()
