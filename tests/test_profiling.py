"""--enable-profiling endpoints (the pprof analog, settings.md:23):
sampling profile + all-thread stack dump on the metrics port, 404 when the
flag is off, drift-free sampling schedule, one profile at a time (429)."""

import threading
import time
import urllib.request

from karpenter_tpu.operator import profiling
from karpenter_tpu.operator.__main__ import serve_endpoints
from karpenter_tpu.operator.profiling import dump_stacks, handle, sample_profile


def _get(port, path):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, ""


def test_sample_profile_sees_other_threads():
    stop = threading.Event()

    def busy_loop_fn():
        while not stop.is_set():
            sum(range(500))

    t = threading.Thread(target=busy_loop_fn, name="busy", daemon=True)
    t.start()
    try:
        report = sample_profile(0.3, interval_s=0.005)
    finally:
        stop.set()
    assert "busy_loop_fn" in report, report[:400]
    assert "thread-samples" in report


def test_stack_dump_lists_threads():
    out = dump_stacks()
    assert "--- thread" in out


def test_sampling_schedule_is_drift_free():
    """Each tick sleeps toward an ABSOLUTE deadline (start + tick*interval),
    so per-tick stack-walk cost compresses the next sleep instead of
    stretching the effective period. With a fake clock charging 4ms of walk
    cost per 10ms tick, a naive sleep(interval) loop would take ~14ms/tick
    and land ~7 ticks in 0.1s; the compensated schedule keeps all 10."""
    WALK_COST = 0.004

    class Clock:
        def __init__(self):
            self.now = 0.0
            self.reads = 0

        def __call__(self):
            # charge the walk cost on the post-sample read: the loop reads
            # the clock once entering the tick and once before sleeping
            self.reads += 1
            if self.reads % 2 == 0:
                self.now += WALK_COST
            return self.now

    clk = Clock()
    sleeps = []

    def slp(dt):
        sleeps.append(dt)
        clk.now += dt

    report = sample_profile(0.1, interval_s=0.01, clock=clk, sleep=slp)
    assert "thread-samples" in report
    # full tick count despite the per-tick cost...
    assert len(sleeps) >= 9, sleeps
    # ...because every sleep was shortened to absorb the walk cost
    assert all(dt <= 0.01 - WALK_COST + 1e-9 for dt in sleeps), sleeps
    assert all(dt > 0 for dt in sleeps)


def test_concurrent_profile_rejected_with_429():
    assert profiling._PROFILE_LOCK.acquire(blocking=False)
    try:
        status, body = handle("/debug/pprof/profile", "seconds=0.1")
        assert status == 429
        assert body == "profile already in progress\n"
    finally:
        profiling._PROFILE_LOCK.release()
    status, body = handle("/debug/pprof/profile", "seconds=0.1")
    assert status == 200 and "thread-samples" in body


def test_endpoints_gated_on_flag():
    srv_off = serve_endpoints(0, 0, enable_profiling=False)
    port_off = srv_off.server_address[1]
    status, _ = _get(port_off, "/debug/pprof/stacks")
    assert status == 404
    srv_on = serve_endpoints(0, 0, enable_profiling=True)
    port_on = srv_on.server_address[1]
    status, body = _get(port_on, "/debug/pprof/stacks")
    assert status == 200 and "--- thread" in body
    status, body = _get(port_on, "/debug/pprof/profile?seconds=0.2")
    assert status == 200 and "thread-samples" in body
    status, _ = _get(port_on, "/debug/pprof/nope")
    assert status == 404
    srv_off.shutdown()
    srv_on.shutdown()
