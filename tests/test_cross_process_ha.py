"""Cross-process HA: two OS processes, flock'd file lease, kill -9 handoff.

VERDICT r4 missing #4: the lease previously lived in one process's memory,
so the deploy renderer's `replicas: 2` could never actually fail over.
This test runs the REAL two-replica shape: two operator processes sharing a
state dir (lease file + snapshot), the leader provisioning a workload, then
SIGKILL — the standby must acquire the lease within the lease duration,
re-hydrate from the snapshot, and resume the SAME claims (no duplicates).
Ref: /root/reference/Makefile:56 (DISABLE_LEADER_ELECTION),
charts/karpenter/values.yaml replicas: 2.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest


def _read_status(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def _wait_for(path, pred, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    last = None
    while time.monotonic() < deadline:
        last = _read_status(path)
        if last is not None and pred(last):
            return last
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}; last status: {last}")


def _spawn(role, dirpath):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the tunnel
    return subprocess.Popen(
        [sys.executable, "-m", "tests.ha_driver", role, dirpath],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
    )


def test_kill9_leader_standby_resumes(tmp_path):
    d = str(tmp_path)
    sa, sb = os.path.join(d, "status-a.json"), os.path.join(d, "status-b.json")
    a = _spawn("a", d)
    b = None
    try:
        st_a = _wait_for(
            sa, lambda s: s["leader"] and s["bound"] == 5, 90,
            "process A to lead and bind the workload",
        )
        claims_a, instances_a = st_a["claims"], st_a["instances"]
        assert claims_a and instances_a

        b = _spawn("b", d)
        _wait_for(sb, lambda s: not s["leader"], 60, "B to run as standby")
        # B must NOT steal the lease while A renews
        time.sleep(1.0)
        st_b = _read_status(sb)
        assert st_b is not None and not st_b["leader"], "standby stole the lease"

        time.sleep(0.5)  # one snapshot cadence: converged state on disk
        a.kill()  # SIGKILL: no resign, no cleanup — the crash case
        a.wait(timeout=10)

        st_b = _wait_for(
            sb,
            lambda s: s["leader"] and s["bound"] == 5,
            30,
            "standby takeover with restored workload",
        )
        # the dead leader's claims resumed — not re-provisioned duplicates
        assert st_b["claims"] == claims_a, (
            f"claims diverged after takeover: {st_b['claims']} != {claims_a}"
        )
        assert st_b["instances"] == instances_a
    finally:
        for p in (a, b):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=10)
        if b is not None and b.stderr:
            err = b.stderr.read().decode(errors="replace")[-2000:]
            if err.strip():
                print("B stderr tail:", err, file=sys.stderr)


def test_filelease_cas_serializes_two_backends(tmp_path):
    """Unit-level: two FileLeaseBackend handles on one path behave like the
    in-process store's optimistic concurrency — one CAS wins, one conflicts."""
    from karpenter_tpu.api.objects import ObjectMeta
    from karpenter_tpu.controllers import store as st
    from karpenter_tpu.controllers.filelease import FileLeaseBackend
    from karpenter_tpu.controllers.leaderelection import (
        LEADER_LEASE_NAME,
        LEASES,
        Lease,
    )

    path = str(tmp_path / "leader.lease")
    b1, b2 = FileLeaseBackend(path), FileLeaseBackend(path)
    assert b1.try_get(LEASES, LEADER_LEASE_NAME) is None
    b1.create(LEASES, Lease(meta=ObjectMeta(name=LEADER_LEASE_NAME),
                            holder="p1", renew_time=100.0))
    with pytest.raises(st.Conflict):
        b2.create(LEASES, Lease(meta=ObjectMeta(name=LEADER_LEASE_NAME),
                                holder="p2", renew_time=100.0))
    cur = b2.try_get(LEASES, LEADER_LEASE_NAME)
    assert cur.holder == "p1" and cur.meta.resource_version == 1
    # both observe rv=1; the second CAS must conflict
    b2.update_if(LEASES, Lease(meta=ObjectMeta(name=LEADER_LEASE_NAME),
                               holder="p2", renew_time=200.0), 1)
    with pytest.raises(st.Conflict):
        b1.update_if(LEASES, Lease(meta=ObjectMeta(name=LEADER_LEASE_NAME),
                                   holder="p1", renew_time=200.0), 1)
    cur = b1.try_get(LEASES, LEADER_LEASE_NAME)
    assert cur.holder == "p2" and cur.meta.resource_version == 2


def test_initial_acquisition_does_not_clear_restore(tmp_path):
    """r5 review: on_elected must fire only on REAL failovers. A fresh
    process acquiring a brand-new lease (takeover=False) must not
    clear-restore the snapshot over objects injected before the first tick."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import karpenter_tpu.controllers.store as st
    from karpenter_tpu.api.nodeclass import KwokNodeClass
    from karpenter_tpu.api.objects import NodePool, ObjectMeta, Pod
    from karpenter_tpu.controllers.snapshot import save_snapshot
    from karpenter_tpu.operator.operator import new_kwok_operator
    from karpenter_tpu.utils.resources import Resources

    snap = str(tmp_path / "state.snap")
    # a STALE snapshot missing the objects about to be injected
    seed = new_kwok_operator()
    save_snapshot(seed.store, seed.cloud, snap)

    op = new_kwok_operator(
        leader_elect=True,
        lease_path=str(tmp_path / "leader.lease"),
        lease_s=1.0, renew_s=0.3,
        snapshot_path=snap, snapshot_interval_s=999,
    )
    op.store.create(st.NODEPOOLS, NodePool(meta=ObjectMeta(name="default")))
    op.store.create(st.NODECLASSES, KwokNodeClass(meta=ObjectMeta(name="default")))
    op.store.create(
        st.PODS,
        Pod(meta=ObjectMeta(name="w0", uid="w0"),
            requests=Resources.parse({"cpu": "1", "memory": "2Gi"})),
    )
    op.manager.tick()  # first tick: creates the lease (takeover=False)
    assert op.manager.elector.is_leader()
    assert not op.manager.elector.takeover
    assert op.store.get(st.PODS, "w0") is not None, (
        "initial acquisition clear-restored over injected objects"
    )


def test_fenced_snapshot_rejects_deposed_writer(tmp_path):
    """r5 review: a deposed leader's in-flight snapshot write must lose
    against the new leader's (higher-fence) snapshots — last-writer-wins
    would roll the shared state file back."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import karpenter_tpu.controllers.store as st
    from karpenter_tpu.api.objects import ObjectMeta, Pod
    from karpenter_tpu.controllers.snapshot import restore_snapshot, save_snapshot
    from karpenter_tpu.operator.operator import new_kwok_operator
    from karpenter_tpu.utils.resources import Resources

    snap = str(tmp_path / "state.snap")
    op_old = new_kwok_operator()
    op_new = new_kwok_operator()
    op_new.store.create(
        st.PODS,
        Pod(meta=ObjectMeta(name="fresh", uid="fresh"),
            requests=Resources.parse({"cpu": "1", "memory": "1Gi"})),
    )
    # new leader (fence 7) writes; old deposed leader (fence 3) then lands
    assert save_snapshot(op_new.store, op_new.cloud, snap, fence_token=7)
    assert not save_snapshot(op_old.store, op_old.cloud, snap, fence_token=3)

    probe = new_kwok_operator()
    assert restore_snapshot(probe.store, probe.cloud, snap)
    assert probe.store.get(st.PODS, "fresh") is not None, (
        "stale snapshot clobbered the new leader's state"
    )


def test_elector_over_file_backend_handoff(tmp_path):
    """In-process pair of electors over the FILE backend (fast determinism
    check of expiry/takeover math on the wall-clock timebase)."""
    from karpenter_tpu.controllers.filelease import FileLeaseBackend
    from karpenter_tpu.controllers.leaderelection import LeaderElector

    path = str(tmp_path / "leader.lease")
    t = {"now": 1000.0}
    clock = lambda: t["now"]
    e1 = LeaderElector(FileLeaseBackend(path), "p1", lease_s=15, renew_s=10, clock=clock)
    e2 = LeaderElector(FileLeaseBackend(path), "p2", lease_s=15, renew_s=10, clock=clock)
    e1.tick()
    e2.tick()
    assert e1.is_leader() and not e2.is_leader()
    # renewal keeps the standby out
    t["now"] += 10
    e1.tick()
    t["now"] += 10
    e2.tick()
    assert not e2.is_leader(), "lease was renewed 10s ago"
    # silent death of p1: after expiry p2 takes over
    t["now"] += 20
    e2.tick()
    assert e2.is_leader()
