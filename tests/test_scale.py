"""Scale harness: provisioning / deprovisioning wall-clock measurement.

The hermetic analog of the reference's scale e2e suite (test/suites/scale/
provisioning_test.go:76-240 + MeasureProvisioningDurationFor, SURVEY.md §4.4):
drives node-dense and pod-dense scale-ups through the full control loop on
kwok and emits duration measurements with the same dimensions (test name,
node count, pods-per-node) as JSON lines on stderr — the Timestream-emission
stand-in. Sizes are scaled to CI (1 core); the shape, not the absolute
numbers, is what the harness preserves.
"""

import json
import sys
import time

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.controllers import store as st
from karpenter_tpu.operator.operator import new_kwok_operator

from tests.test_e2e_kwok import FakeClock, mkpod, mkpool


def emit(test: str, seconds: float, nodes: int, pods_per_node: int) -> None:
    print(
        json.dumps(
            {
                "measurement": "provisioning_duration_s",
                "test": test,
                "value": round(seconds, 3),
                "node_count": nodes,
                "pods_per_node": pods_per_node,
            }
        ),
        file=sys.stderr,
    )


class TestScale:
    def test_node_dense_scale_up(self):
        """N nodes x 1 pod/node (provisioning_test.go:76-121 shape)."""
        clock = FakeClock()
        op = new_kwok_operator(clock=clock, disruption=False)
        op.store.create(st.NODEPOOLS, mkpool())
        from karpenter_tpu.api.objects import TopologySpreadConstraint

        n = 30
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "dense"}
        )
        for i in range(n):
            op.store.create(
                st.PODS,
                mkpod(f"p{i:03d}", cpu="200m", mem="256Mi", labels={"app": "dense"},
                      topology_spread=[tsc]),
            )
        t0 = time.perf_counter()
        op.manager.settle(max_ticks=500)
        dt = time.perf_counter() - t0
        emit("node_dense", dt, n, 1)
        assert len(op.store.list(st.NODES)) == n
        assert all(p.node_name for p in op.store.list(st.PODS))

    def test_pod_dense_scale_up(self):
        """few nodes x many pods/node (provisioning_test.go:123-240 shape)."""
        clock = FakeClock()
        op = new_kwok_operator(clock=clock, disruption=False)
        op.store.create(st.NODEPOOLS, mkpool())
        pods = 300
        for i in range(pods):
            op.store.create(st.PODS, mkpod(f"p{i:03d}", cpu="100m", mem="128Mi"))
        t0 = time.perf_counter()
        op.manager.settle(max_ticks=500)
        dt = time.perf_counter() - t0
        nodes = op.store.list(st.NODES)
        emit("pod_dense", dt, len(nodes), pods // max(len(nodes), 1))
        assert all(p.node_name for p in op.store.list(st.PODS))
        # density proves packing: far fewer nodes than pods
        assert len(nodes) <= 4

    def test_deprovisioning(self):
        """consolidation tear-down wall-clock (deprovisioning measurement)."""
        clock = FakeClock()
        op = new_kwok_operator(clock=clock)
        op.clock = clock
        op.store.create(st.NODEPOOLS, mkpool())
        for i in range(60):
            op.store.create(st.PODS, mkpod(f"p{i:03d}", cpu="500m", mem="512Mi"))
        op.manager.settle(max_ticks=500)
        n_before = len(op.store.list(st.NODES))
        # workload shrinks: delete half the pods
        for i in range(0, 60, 2):
            p = op.store.get(st.PODS, f"p{i:03d}")
            p.meta.finalizers = []
            op.store.delete(st.PODS, f"p{i:03d}")
        clock.advance(30)
        t0 = time.perf_counter()
        op.manager.settle(max_ticks=500)
        dt = time.perf_counter() - t0
        emit("deprovision_half", dt, n_before, 0)
        assert all(p.node_name for p in op.store.list(st.PODS))


class TestScanAxisHeterogeneity:
    """S ≥ 1000 distinct pod specs: the kernel's only sequential axis is the
    run (scan) axis, and every other scenario in the repo collapses 50k pods
    to a few dozen runs — this pins correctness AND the device path on a
    realistically heterogeneous workload (VERDICT r3 'what's weak' #3)."""

    def test_1200_distinct_specs_parity(self):
        from karpenter_tpu.api.objects import ObjectMeta, Pod
        from karpenter_tpu.catalog.catalog import CatalogSpec, generate
        from karpenter_tpu.provisioning.scheduler import NodePoolSpec, SolverInput
        from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
        from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver
        from karpenter_tpu.solver.encode import encode, quantize_input
        from karpenter_tpu.utils.resources import Resources

        spec_pool = NodePoolSpec(
            name="default",
            weight=0,
            requirements=Requirements.of(
                Requirement.create(wk.NODEPOOL_LABEL, IN, ["default"])
            ),
            taints=[],
            instance_types=generate(CatalogSpec()),
        )

        pods = []
        for i in range(1200):
            cpu_m = 100 + (i % 400) * 10          # 400 cpu levels
            mem_mi = 64 + (i // 400) * 96 + (i % 7) * 32   # cross-cut levels
            for j in range(3):
                pods.append(
                    Pod(
                        meta=ObjectMeta(name=f"h{i:04d}-{j}", uid=f"h{i:04d}-{j}"),
                        requests=Resources.parse(
                            {"cpu": f"{cpu_m}m", "memory": f"{mem_mi}Mi"}
                        ),
                    )
                )
        inp = SolverInput(
            pods=pods, nodes=[], nodepools=[spec_pool],
            zones=("zone-1a", "zone-1b", "zone-1c"),
        )
        qinp = quantize_input(inp)
        enc = encode(qinp)
        assert enc.G >= 1000, f"scenario must stress the scan axis, G={enc.G}"
        ref = ReferenceSolver().solve(qinp)
        solver = TPUSolver(max_claims=4096)
        tpu = solver.solve(inp)
        assert solver.stats["device_solves"] == 1, solver.stats
        assert set(ref.errors) == set(tpu.errors)
        assert ref.placements == tpu.placements
        assert len(ref.claims) == len(tpu.claims)
        for rc, tc in zip(ref.claims, tpu.claims):
            assert rc.pod_uids == tc.pod_uids
