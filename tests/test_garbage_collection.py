"""GC race-safety for the claim-deletion direction.

The reference guards both GC directions against create/describe races with a
CreationTimestamp grace (pkg/controllers/nodeclaim/garbagecollection/
controller.go:57-60,85). Round-4 advisor finding: our claim-deletion
direction snapshotted the cloud BEFORE listing claims and applied no grace,
so a claim whose instance materialized between DescribeInstances and the
claim scan was deleted while healthy. These tests pin the fix: claims are
listed first (staleness only grows the live set) and young claims are never
reaped on a single missing describe.
"""

import time

from karpenter_tpu.api.objects import NodeClaim, ObjectMeta
from karpenter_tpu.controllers import store as st
from karpenter_tpu.controllers.garbagecollection import GarbageCollectionController
from karpenter_tpu.kwok.cloud import Instance, KwokCloud

from tests.test_e2e_kwok import FakeClock


def _setup():
    clock = FakeClock()
    store = st.Store()
    cloud = KwokCloud(store, [], clock=clock)
    gc = GarbageCollectionController(store, cloud, grace_s=30.0, clock=clock)
    return clock, store, cloud, gc


def _mkclaim(name, iid, created_at):
    return NodeClaim(
        meta=ObjectMeta(name=name, uid=name, creation_timestamp=created_at),
        provider_id=f"kwok://{iid}",
        launched=True,
    )


def _mkinst(cloud, iid, launch_time):
    inst = Instance(
        id=iid, instance_type="t", zone="zone-1a", capacity_type="on-demand",
        price=1.0, launch_time=launch_time,
    )
    cloud._instances[iid] = inst
    return inst


def test_young_claim_with_missing_instance_survives_grace():
    clock, store, cloud, gc = _setup()
    # claim just created; its CreateFleet may still be materializing
    store.create(st.NODECLAIMS, _mkclaim("young", "i-young", clock()))
    clock.advance(5)
    gc.reconcile()
    assert store.get(st.NODECLAIMS, "young") is not None

    # once past grace with the instance still absent, it IS reaped
    clock.advance(30)
    gc.reconcile()
    try:
        got = store.get(st.NODECLAIMS, "young")
    except st.NotFound:
        got = None
    assert got is None


def test_old_claim_with_vanished_instance_deleted():
    clock, store, cloud, gc = _setup()
    store.create(st.NODECLAIMS, _mkclaim("old", "i-gone", clock() - 120))
    gc.reconcile()
    try:
        got = store.get(st.NODECLAIMS, "old")
    except st.NotFound:
        got = None
    assert got is None


def test_instance_created_during_reconcile_keeps_claim():
    """The exact advisor race: instance creation lands between the claim
    scan and DescribeInstances. With claims listed FIRST, the late instance
    is still visible to describe, so the (old, healthy) claim survives."""
    clock, store, cloud, gc = _setup()
    store.create(st.NODECLAIMS, _mkclaim("racy", "i-racy", clock() - 120))

    orig_list = store.list

    def list_then_create(kind):
        out = orig_list(kind)
        if kind == st.NODECLAIMS and "i-racy" not in cloud._instances:
            _mkinst(cloud, "i-racy", clock())
        return out

    store.list = list_then_create
    try:
        gc.reconcile()
    finally:
        store.list = orig_list
    assert store.get(st.NODECLAIMS, "racy") is not None
    assert "i-racy" in {i.id for i in cloud.describe_instances()}


def test_orphan_instance_terminated_after_grace():
    clock, store, cloud, gc = _setup()
    _mkinst(cloud, "i-orphan", clock())
    gc.reconcile()  # young instance: kept
    assert "i-orphan" in {i.id for i in cloud.describe_instances()}
    clock.advance(31)
    gc.reconcile()
    assert "i-orphan" not in {
        i.id for i in cloud.describe_instances() if i.state == "running"
    }


def test_vanished_instance_claim_reaped_under_sim_clock():
    """Production wiring path: the operator's injected clock must agree with
    the creation stamps the provisioner writes, or the grace comparison goes
    negative and the vanished-claim direction never fires (r5 review
    finding). Drive the REAL loop under a FakeClock: provision, kill the
    instance out from under the claim, advance past grace, expect the claim
    gone and the pod re-bound on fresh capacity."""
    from karpenter_tpu.api.nodeclass import KwokNodeClass
    from karpenter_tpu.api.objects import NodePool, ObjectMeta, Pod
    from karpenter_tpu.operator.operator import new_kwok_operator
    from karpenter_tpu.utils.resources import Resources

    clock = FakeClock()
    op = new_kwok_operator(clock=clock)
    op.store.create(st.NODEPOOLS, NodePool(meta=ObjectMeta(name="default")))
    op.store.create(st.NODECLASSES, KwokNodeClass(meta=ObjectMeta(name="default")))
    op.store.create(
        st.PODS,
        Pod(meta=ObjectMeta(name="w0", uid="w0"),
            requests=Resources.parse({"cpu": "1", "memory": "2Gi"})),
    )
    for _ in range(20):
        op.manager.tick()
        clock.advance(1)
    claims = op.store.list(st.NODECLAIMS)
    assert len(claims) == 1 and claims[0].launched
    # creation stamp must come from the injected clock, not wall monotonic
    assert abs(claims[0].meta.creation_timestamp - clock()) < 100
    doomed = claims[0].name
    iid = claims[0].provider_id.rsplit("/", 1)[-1]

    # reclaim the instance out from under the claim (spot-reclaim shape)
    with op.cloud._lock:
        del op.cloud._instances[iid]
    clock.advance(40)  # past the 30s GC grace
    for _ in range(30):
        op.manager.tick()
        clock.advance(1)
    names = {c.name for c in op.store.list(st.NODECLAIMS)}
    assert doomed not in names, "vanished-instance claim never reaped"
    pod = op.store.get(st.PODS, "w0")
    assert pod.node_name, "pod not re-bound after phantom capacity reaped"


def test_debug_events_env_refuses_operator_start(monkeypatch):
    """KTPU_DEBUG_EVENTS corrupts every solve in the process (solver/tpu/
    ffd.py trace-time rewiring); the operator must fail closed (ADVICE r4)."""
    import pytest

    from karpenter_tpu.operator import options as opts

    monkeypatch.setenv("KTPU_DEBUG_EVENTS", "1")
    with pytest.raises(SystemExit):
        opts.parse([])
    monkeypatch.setenv("KTPU_DEBUG_EVENTS", "false")
    assert opts.parse([]) is not None
