"""Integer-exact resource math (karpenter_tpu/utils/resources.py)."""

import pytest

from karpenter_tpu.utils.resources import (
    CPU,
    MEMORY,
    Resources,
    format_quantity,
    parse_quantity,
)


class TestParseQuantity:
    def test_cpu_cores(self):
        assert parse_quantity("1", CPU) == 1000
        assert parse_quantity(2, CPU) == 2000
        assert parse_quantity("0.5", CPU) == 500

    def test_cpu_milli(self):
        assert parse_quantity("100m", CPU) == 100
        assert parse_quantity("1500m", CPU) == 1500

    def test_cpu_fractional_rounds_up(self):
        assert parse_quantity("0.0001", CPU) == 1  # 0.1m -> 1m

    def test_memory_binary_suffixes(self):
        assert parse_quantity("1Ki", MEMORY) == 1024
        assert parse_quantity("1Mi", MEMORY) == 1024**2
        assert parse_quantity("1Gi", MEMORY) == 1024**3
        assert parse_quantity("1.5Gi", MEMORY) == 1024**3 + 512 * 1024**2

    def test_memory_decimal_suffixes(self):
        assert parse_quantity("1k", MEMORY) == 1000
        assert parse_quantity("1M", MEMORY) == 10**6
        assert parse_quantity("1G", MEMORY) == 10**9

    def test_plain_count(self):
        assert parse_quantity("4", "nvidia.com/gpu") == 4
        assert parse_quantity("110", "pods") == 110

    def test_exactness_large(self):
        # 24Ti must be byte-exact (would overflow float32 mantissa)
        assert parse_quantity("24Ti", MEMORY) == 24 * 1024**4

    def test_invalid(self):
        with pytest.raises(ValueError):
            parse_quantity("abc", CPU)
        with pytest.raises(ValueError):
            parse_quantity("1Qx", MEMORY)

    def test_format_roundtrip(self):
        assert format_quantity(1500, CPU) == "1500m"
        assert format_quantity(2000, CPU) == "2"
        assert format_quantity(1024**3, MEMORY) == "1Gi"


class TestResources:
    def test_parse_add_sub(self):
        a = Resources.parse({"cpu": "1", "memory": "1Gi"})
        b = Resources.parse({"cpu": "500m", "memory": "512Mi"})
        s = a.add(b)
        assert s["cpu"] == 1500
        assert s["memory"] == 1024**3 + 512 * 1024**2
        d = s.sub(b)
        assert d["cpu"] == 1000

    def test_fits(self):
        req = Resources.parse({"cpu": "2", "memory": "4Gi"})
        cap = Resources.parse({"cpu": "4", "memory": "8Gi", "pods": "110"})
        assert req.fits(cap)
        assert not cap.fits(req)

    def test_fits_missing_capacity_key(self):
        req = Resources.parse({"nvidia.com/gpu": "1"})
        cap = Resources.parse({"cpu": "4"})
        assert not req.fits(cap)

    def test_zero_request_always_fits(self):
        req = Resources.parse({"cpu": "0"})
        assert req.fits(Resources())

    def test_exceeds(self):
        usage = Resources.parse({"cpu": "10"})
        assert usage.exceeds(Resources.parse({"cpu": "5"}))
        assert not usage.exceeds(Resources.parse({"cpu": "20"}))
        assert not usage.exceeds(Resources.parse({"memory": "1Gi"}))

    def test_max(self):
        a = Resources.parse({"cpu": "1", "memory": "4Gi"})
        b = Resources.parse({"cpu": "2", "memory": "1Gi"})
        m = a.max(b)
        assert m["cpu"] == 2000 and m["memory"] == 4 * 1024**3
