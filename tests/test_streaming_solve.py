"""Streaming delta-solve (ISSUE 13): journal-fed resident model parity.

The invariant under test is the subsystem's whole contract: after EVERY
folded event batch, the streamed `build_input()` must be decision-identical
to the snapshot path on the same universe — through randomized churn, fence
re-baselines, injected drift, and the backend's staged run-table scatters.
The journal itself (ordering, overflow -> lost, applied_rev) and the
disruption engine's mid-stream Superseded defer are pinned here too.
"""

import random

import numpy as np
import pytest

from karpenter_tpu.api.objects import (
    NodeClaimTemplate,
    NodePool,
    ObjectMeta,
    Pod,
)
from karpenter_tpu.catalog.catalog import CatalogSpec, generate
from karpenter_tpu.controllers import store as st
from karpenter_tpu.kwok.cloud import KwokCloud
from karpenter_tpu.kwok.cloudprovider import KwokCloudProvider
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.provisioning.provisioner import Provisioner
from karpenter_tpu.solver.backend import ReferenceSolver
from karpenter_tpu.solver.streaming import StreamingSolver
from karpenter_tpu.state.cluster import Cluster, ClusterJournal
from karpenter_tpu.utils.resources import Resources

TYPES = generate(CatalogSpec())


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def mkpool(name="general", weight=0):
    return NodePool(meta=ObjectMeta(name=name),
                    template=NodeClaimTemplate(), weight=weight)


def mkpod(name, cpu="500m", mem="512Mi", **kw):
    return Pod(meta=ObjectMeta(name=name, uid=name),
               requests=Resources.parse({"cpu": cpu, "memory": mem}), **kw)


def _env():
    store = st.Store()
    cloud = KwokCloud(store, TYPES)
    provider = KwokCloudProvider(cloud, TYPES)
    cluster = Cluster(store)
    return store, provider, cluster


def _assert_parity(streaming, snap, cluster, solver):
    """The bit-identity the subsystem promises: pending set, existing-node
    views, axes, and the solve decisions all match the snapshot path."""
    pend_s = streaming.pending_pods()
    pend_c = cluster.pending_pods()
    assert [p.meta.uid for p in pend_s] == [p.meta.uid for p in pend_c]
    inp_s = streaming.build_input(pend_s)
    inp_c = snap.build_input(pend_c)
    assert inp_s.zones == inp_c.zones
    assert inp_s.capacity_types == inp_c.capacity_types
    assert inp_s.nodes == inp_c.nodes  # ExistingNode dataclass equality
    assert [(p.name, p.weight, p.usage) for p in inp_s.nodepools] == [
        (p.name, p.weight, p.usage) for p in inp_c.nodepools
    ]
    a = solver.solve(inp_s)
    b = solver.solve(inp_c)
    assert a.placements == b.placements
    return a


class TestChurnTraceParity:
    def test_randomized_churn_trace_is_decision_identical(self):
        """A randomized arrival/deletion/bind/catalog churn trace through the
        REAL controllers (operator settle creates claims, fabricates nodes,
        binds pods): after every batch the streamed model must agree with a
        fresh snapshot, decisions included."""
        rng = random.Random(20260805)
        clock = FakeClock()
        op = new_kwok_operator(clock=clock)
        op.store.create(st.NODEPOOLS, mkpool("general"))
        streaming = StreamingSolver(op.cluster, op.cloud_provider,
                                    epoch_every=0, clock=clock)
        ref = ReferenceSolver()
        snap = Provisioner(op.store, op.cluster, op.cloud_provider, ref,
                           batch_idle_s=0, batch_max_s=0, clock=clock)
        n = 0
        extra_pool = False
        for step in range(14):
            roll = rng.random()
            if roll < 0.45 or n == 0:
                for _ in range(rng.randint(1, 4)):
                    op.store.create(st.PODS, mkpod(
                        f"c{n}", cpu=rng.choice(("250m", "500m", "1")),
                        mem=rng.choice(("256Mi", "512Mi", "1Gi"))))
                    n += 1
            elif roll < 0.60:
                pending = op.cluster.pending_pods()
                if pending:
                    victim = rng.choice(pending)
                    op.store.delete(st.PODS, victim.meta.name,
                                    namespace=victim.meta.namespace)
            elif roll < 0.75:
                # catalog-kind churn: a second pool appears/disappears —
                # inexpressible as a delta, must fall back snapshot-identical
                if extra_pool:
                    op.store.delete(st.NODEPOOLS, "burst")
                else:
                    op.store.create(st.NODEPOOLS, mkpool("burst", weight=50))
                extra_pool = not extra_pool
            else:
                # the real control loop: claims created, nodes fabricated
                # and registered, pods bound — node/claim/pod events stream
                clock.advance(1.0)
                op.manager.settle()
            streaming.pump()
            _assert_parity(streaming, snap, op.cluster, ref)
        assert streaming.stats["batches_applied"] > 0
        assert streaming.stats["drift_detected"] == 0

    def test_bound_and_gated_pods_drop_from_pending(self):
        store, provider, cluster = _env()
        store.create(st.NODEPOOLS, mkpool())
        streaming = StreamingSolver(cluster, provider, epoch_every=0)
        store.create(st.PODS, mkpod("a"))
        store.create(st.PODS, mkpod("b", scheduling_gated=True))
        store.create(st.PODS, mkpod("c"))
        streaming.pump()
        assert [p.meta.uid for p in streaming.pending_pods()] == ["a", "c"]
        # the binder's unbind/bind route fires MODIFIED through the store
        c = store.get(st.PODS, "c")
        c.node_name = "n0"  # .bound is derived from the binding
        store.update(st.PODS, c)
        streaming.pump()
        assert [p.meta.uid for p in streaming.pending_pods()] == ["a"]
        assert [p.meta.uid for p in cluster.pending_pods()] == ["a"]


class TestRebaseline:
    def test_epoch_check_rebaselines_on_injected_drift(self):
        """Corrupt the resident model behind the journal's back: the next
        epoch check must detect the divergence, count it, re-baseline, and
        come back parity-correct."""
        store, provider, cluster = _env()
        store.create(st.NODEPOOLS, mkpool())
        streaming = StreamingSolver(cluster, provider, epoch_every=1)
        for i in range(4):
            store.create(st.PODS, mkpod(f"p{i}"))
        streaming.pump()
        # simulate a missed fold (the bug class the check exists for)
        streaming._pods.pop("default/p1", None)
        assert len(streaming.pending_pods()) == 3
        before = streaming.stats["rebaseline_total"]
        store.create(st.PODS, mkpod("p4"))
        streaming.pump()  # folds p4, epoch check fires, drift -> re-baseline
        assert streaming.stats["drift_detected"] == 1
        assert streaming.stats["rebaseline_total"] == before + 1
        assert [p.meta.uid for p in streaming.pending_pods()] == [
            p.meta.uid for p in cluster.pending_pods()
        ]

    def test_fence_mid_stream_drops_no_events(self):
        """A fleet fence between two batches re-baselines the model, and the
        events that arrived around the fence all survive (the attach-then-
        list fold is level-triggered)."""
        from karpenter_tpu.metrics.registry import STREAMING_REBASELINE

        store, provider, cluster = _env()
        store.create(st.NODEPOOLS, mkpool())
        streaming = StreamingSolver(cluster, provider, epoch_every=0)
        store.create(st.PODS, mkpod("pre"))
        streaming.pump()
        store.create(st.PODS, mkpod("in-flight"))
        fences = STREAMING_REBASELINE.value(reason="fence")
        streaming.on_fence("canary_miss")
        store.create(st.PODS, mkpod("post"))
        streaming.pump()
        assert STREAMING_REBASELINE.value(reason="fence") == fences + 1
        assert [p.meta.uid for p in streaming.pending_pods()] == [
            "pre", "in-flight", "post"
        ]

    def test_journal_overflow_forces_rebaseline(self):
        store, provider, cluster = _env()
        store.create(st.NODEPOOLS, mkpool())
        streaming = StreamingSolver(cluster, provider, epoch_every=0)
        streaming.pump()
        cluster.journal.maxlen = 4
        before = streaming.stats["rebaseline_total"]
        for i in range(12):  # > maxlen: the buffer drops the oldest events
            store.create(st.PODS, mkpod(f"of{i}"))
        streaming.pump()
        assert streaming.stats["rebaseline_total"] == before + 1
        assert len(streaming.pending_pods()) == 12

    def test_pod_epoch_bump_resyncs(self):
        """An in-place sig mutation fires no store event — the epoch counter
        is the only signal, and pump must re-baseline on it."""
        store, provider, cluster = _env()
        store.create(st.NODEPOOLS, mkpool())
        store.create(st.PODS, mkpod("p"))
        streaming = StreamingSolver(cluster, provider, epoch_every=0)
        streaming.pump()
        before = streaming.stats["rebaseline_total"]
        p = store.get(st.PODS, "p")
        # warm the solver-sig cache as a real solve would: the epoch only
        # bumps when a mutation invalidates a POPULATED cache
        from karpenter_tpu.solver.encode import _pod_signature

        _pod_signature(p)
        p.requests = Resources.parse({"cpu": "2", "memory": "4Gi"})
        streaming.pump()
        assert streaming.stats["rebaseline_total"] == before + 1
        assert streaming.pending_pods()[0].requests == p.requests


class TestJournal:
    def test_seq_bumps_detached_and_buffers_attached(self):
        store = st.Store()
        j = ClusterJournal(store, maxlen=8)
        store.create(st.PODS, mkpod("a"))
        assert j.rev() == 1 and j.depth() == 0  # stamped, not buffered
        base = j.attach()
        store.create(st.PODS, mkpod("b"))
        store.create(st.PODS, mkpod("c"))
        events, lost = j.drain(base)
        assert not lost
        assert [(e.event, e.key) for e in events] == [
            ("ADDED", "default/b"), ("ADDED", "default/c")
        ]
        # events carry the LIVE stored object (level-triggered contract)
        assert events[0].obj is store.get(st.PODS, "b")

    def test_overflow_reports_lost(self):
        store = st.Store()
        j = ClusterJournal(store, maxlen=3)
        base = j.attach()
        for i in range(6):
            store.create(st.PODS, mkpod(f"p{i}"))
        assert j.overflows > 0
        events, lost = j.drain(base)
        assert lost and events == []
        # after a re-baseline at the current rev, the stream is clean again
        base = j.attach()
        store.create(st.PODS, mkpod("fresh"))
        events, lost = j.drain(base)
        assert not lost and len(events) == 1

    def test_mark_applied_is_monotonic(self):
        store = st.Store()
        j = ClusterJournal(store)
        j.mark_applied(5)
        j.mark_applied(3)  # late writer must not move it backwards
        assert j.applied_rev == 5


class TestStagedRunEvents:
    def test_staged_scatter_is_device_host_identical_and_decision_neutral(self):
        """With stream_run_events on, a warm re-solve whose run tables moved
        a little ships edit triplets instead of whole tables. After the
        staged scatter the DEVICE copy must equal the freshly encoded host
        arrays exactly (adopt trusts the tags), and decisions must match an
        unstaged control solver bit for bit."""
        import dataclasses as _dc

        from karpenter_tpu.provisioning.scheduler import SolverInput
        from karpenter_tpu.solver import backend
        from karpenter_tpu.solver.encode import encode, quantize_input

        from tests.test_solver_parity import ZONES, mkpod as kpod, pool

        pods = [kpod(f"p{i}", cpu=("250m", "500m", "750m", "1")[i % 4])
                for i in range(24)]
        inp1 = SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                           zones=ZONES)
        # same pod count, one spec's size changed: same compile bucket,
        # different run tables -> a small diff the staging can ship
        pods2 = list(pods)
        pods2[3] = _dc.replace(pods[3], requests=Resources.parse(
            {"cpu": "1", "memory": "1Gi"}))
        inp2 = SolverInput(pods=pods2, nodes=[], nodepools=[pool()],
                           zones=ZONES)

        streamed = backend.TPUSolver(max_claims=256)
        streamed.stream_run_events = True
        control = backend.TPUSolver(max_claims=256)
        r1 = streamed.solve(inp1)
        c1 = control.solve(inp1)
        assert r1.placements == c1.placements
        r2 = streamed.solve(inp2)
        c2 = control.solve(inp2)
        assert r2.placements == c2.placements
        stats = streamed.stats
        assert stats["event_stage_hits"] + stats["event_stage_misses"] > 0
        if stats["event_stage_hits"]:
            # the bucket's resident run tables equal the host encode exactly
            enc = encode(quantize_input(inp2))
            host_args, _dims, _prov = backend.host_kernel_args(
                enc, streamed._bucket)
            key = streamed.arena.bucket_key(host_args, None,
                                            ns=enc.tenant_id)
            dev, _tags = streamed.arena._buckets[key]
            assert (np.asarray(dev[0]) == np.asarray(host_args[0])).all()
            assert (np.asarray(dev[1]) == np.asarray(host_args[1])).all()

    def test_stage_declines_on_unknown_diff_base(self):
        """First sight of a bucket (no recorded host pair) must decline the
        stage and let adopt pay the normal upload — never scatter against an
        unverified base."""
        from karpenter_tpu.provisioning.scheduler import SolverInput
        from karpenter_tpu.solver import backend

        from tests.test_solver_parity import ZONES, mkpod as kpod, pool

        solver = backend.TPUSolver(max_claims=256)
        solver.stream_run_events = True
        inp = SolverInput(pods=[kpod("p0"), kpod("p1")], nodes=[],
                          nodepools=[pool()], zones=ZONES)
        solver.solve(inp)
        assert solver.stats["event_stage_misses"] >= 1
        assert solver.stats["event_stage_hits"] == 0


class TestDisruptionGuard:
    def _controller(self):
        from karpenter_tpu.disruption.controller import DisruptionController

        store, provider, cluster = _env()
        ctrl = DisruptionController(store, cluster, provider,
                                    ReferenceSolver())
        return ctrl, store, cluster

    def test_probe_defers_once_applied_rev_passes_prep_rev(self):
        from karpenter_tpu.solver.pipeline import Superseded

        ctrl, store, cluster = self._controller()

        class _Stub:
            def evaluate_prepared(self, prep, subsets):
                return "verdicts"

        ctrl._batched = _Stub()
        store.create(st.PODS, mkpod("x"))
        ctrl._prep_rev = cluster.journal.rev()
        # quiescent stream: the probe's universe is current -> no defer
        assert ctrl._evaluate_probe_batch(None, []) == "verdicts"
        # a streamed batch lands (and is applied) while the probe flies
        store.create(st.PODS, mkpod("y"))
        cluster.journal.mark_applied(cluster.journal.rev())
        with pytest.raises(Superseded):
            ctrl._evaluate_probe_batch(None, [])

    def test_reconcile_defers_the_tick_on_superseded(self):
        from karpenter_tpu.solver.pipeline import Superseded

        ctrl, _store, _cluster = self._controller()
        ctrl._candidates = lambda: [object()]
        ctrl._budget_allowance = lambda c: {}
        def _boom(method, candidates, budgets):
            raise Superseded()
        ctrl._evaluate = _boom
        assert ctrl.reconcile() is False
        assert ctrl.stats["superseded_defers"] == 1

    def test_prepared_universe_key_includes_journal_rev(self):
        """The per-reconcile prep cache must not survive a journal advance:
        the rev is part of the key, so a batch applied between probes forces
        a re-prepare on the next reconcile."""
        import inspect

        from karpenter_tpu.disruption import controller as dc

        src = inspect.getsource(dc.DisruptionController._prepared_universe)
        assert "journal.rev()" in src


class TestOperatorWiring:
    def test_streamed_operator_matches_snapshot_operator(self):
        """Same injected workload through two full operators — one streaming,
        one snapshot. The end state (bindings, node shapes) must agree."""
        def drive(streaming_on):
            clock = FakeClock()
            op = new_kwok_operator(clock=clock,
                                   solver_streaming=streaming_on,
                                   streaming_epoch_every=2)
            op.store.create(st.NODEPOOLS, mkpool())
            for i in range(6):
                op.store.create(st.PODS, mkpod(
                    f"p{i}", cpu=("250m", "500m", "1")[i % 3]))
            op.manager.settle()
            op.store.create(st.PODS, mkpod("late", cpu="100m", mem="128Mi"))
            clock.advance(1.0)
            op.manager.settle()
            pods = sorted((p.meta.name, p.bound) for p in op.store.list(st.PODS))
            nodes = sorted(
                n.meta.labels.get("node.kubernetes.io/instance-type", "")
                for n in op.store.list(st.NODES)
            )
            return op, pods, nodes

        op_s, pods_s, nodes_s = drive(True)
        _op_c, pods_c, nodes_c = drive(False)
        assert pods_s == pods_c
        assert nodes_s == nodes_c
        assert op_s.streaming is not None
        assert op_s.streaming.stats["streamed_solves"] > 0
        assert op_s.streaming.stats["drift_detected"] == 0

    def test_fleet_fence_listener_and_stage_flag_are_wired(self):
        from karpenter_tpu.solver.backend import TPUSolver, concrete_backend

        op = new_kwok_operator(solver=TPUSolver(max_claims=64),
                               solver_streaming=True, solver_fleet_size=2)
        try:
            fleet = op.solve_service
            assert op.streaming.on_fence in fleet.fence_listeners
            for o in fleet.owners:
                inner = concrete_backend(o.solver)
                if isinstance(inner, TPUSolver):
                    assert inner.stream_run_events is True
        finally:
            op.solve_service.close()

    def test_journal_seq_rides_trace_and_snapshot(self):
        from karpenter_tpu.obs import trace as obstrace

        obstrace.configure(enabled=True, ring=16)
        try:
            tr = obstrace.begin("provisioning")
            obstrace.set_journal(tr, 42)
            assert tr.journal_seq == 42
            with obstrace.attached(tr):
                assert obstrace.current_journal_seq() == 42
            obstrace.finish(tr, "ok")
            assert tr.snapshot()["journal_seq"] == 42
        finally:
            obstrace.configure(enabled=False, recorder=None)


@pytest.mark.slow
def test_streaming_soak_sustains_arrival_rate():
    """ISSUE 13 soak acceptance: >= 1k arrival-batches/sec through the
    journal -> fold -> assemble ingest path, zero drift, zero re-baselines
    past the initial baseline."""
    import bench

    out = bench._streaming_run(batches=1200, pods_per_batch=2, base_pods=32,
                               epoch_every=0, parity_every=0)
    assert out["arrival_batches_per_sec"] >= 1000, out
    assert out["streaming_drift_detected"] == 0, out
    assert out["rebaseline_total"] == 1, out  # the initial baseline only
