"""End-to-end control loop with the TPU solver backend.

Same hermetic loop as test_e2e_kwok.py, but every scheduling decision —
provisioning solves AND consolidation simulations (batched, vmapped) — runs
through the device kernels. End states must match what the reference backend
produces on identical inputs (the controller-level expression of the
bit-identical-decisions bar).
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.controllers import store as st
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.solver.backend import TPUSolver
from karpenter_tpu.utils.resources import Resources

from tests.test_e2e_kwok import FakeClock, mkpod, mkpool


@pytest.fixture
def op():
    clock = FakeClock()
    o = new_kwok_operator(clock=clock, solver=TPUSolver())
    o.clock = clock
    return o


def snapshot(o):
    """Comparable end-state: node shapes + pod placements (names differ)."""
    nodes = sorted(
        (n.meta.labels[wk.INSTANCE_TYPE_LABEL], n.meta.labels.get(wk.ZONE_LABEL, ""))
        for n in o.store.list(st.NODES)
    )
    pods = sorted((p.meta.name, p.node_name is not None) for p in o.store.list(st.PODS))
    return nodes, pods


class TestTPUBackendE2E:
    def test_provisioning_matches_reference(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        for i in range(8):
            op.store.create(st.PODS, mkpod(f"p{i}", cpu="500m", mem="1Gi"))
        op.manager.settle()
        assert op.solver.stats["device_solves"] >= 1
        nodes = op.store.list(st.NODES)
        assert len(nodes) == 1
        assert all(p.node_name for p in op.store.list(st.PODS))

        ref = new_kwok_operator(clock=FakeClock())
        ref.store.create(st.NODEPOOLS, mkpool())
        for i in range(8):
            ref.store.create(st.PODS, mkpod(f"p{i}", cpu="500m", mem="1Gi"))
        ref.manager.settle()
        assert snapshot(op) == snapshot(ref)

    def test_mixed_constraints_match_reference(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        ref = new_kwok_operator(clock=FakeClock())
        ref.store.create(st.NODEPOOLS, mkpool())
        for o in (op, ref):
            o.store.create(st.PODS, mkpod("arm", node_selector={wk.ARCH_LABEL: "arm64"}))
            o.store.create(st.PODS, mkpod("amd", node_selector={wk.ARCH_LABEL: "amd64"}))
            o.store.create(st.PODS, mkpod("zoned", node_selector={wk.ZONE_LABEL: "zone-1b"}))
            for i in range(4):
                o.store.create(st.PODS, mkpod(f"t{i}", cpu="250m", mem="256Mi"))
            o.manager.settle()
        assert snapshot(op) == snapshot(ref)

    def test_single_node_consolidation_batched(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        op.store.create(st.PODS, mkpod("big", cpu="14", mem="24Gi"))
        op.store.create(st.PODS, mkpod("small", cpu="100m", mem="128Mi"))
        op.manager.settle()
        old_price = op.store.list(st.NODECLAIMS)[0].price
        big = op.store.get(st.PODS, "big")
        big.meta.finalizers = []
        op.store.delete(st.PODS, "big")
        op.clock.advance(30)
        op.manager.settle()
        nodes = op.store.list(st.NODES)
        assert len(nodes) == 1
        assert op.store.list(st.NODECLAIMS)[0].price < old_price
        assert op.store.get(st.PODS, "small").node_name == nodes[0].meta.name

    def test_multi_node_consolidation_batched(self, op):
        from karpenter_tpu.api.objects import TopologySpreadConstraint

        op.store.create(st.NODEPOOLS, mkpool())
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "x"}
        )
        for i in range(3):
            op.store.create(
                st.PODS,
                mkpod(f"p{i}", cpu="200m", mem="256Mi", labels={"app": "x"},
                      topology_spread=[tsc]),
            )
        op.manager.settle()
        assert len(op.store.list(st.NODES)) == 3
        for i in range(3):
            p = op.store.get(st.PODS, f"p{i}")
            p.topology_spread = []
            op.store.update(st.PODS, p)
        op.clock.advance(30)
        op.manager.settle()
        assert len(op.store.list(st.NODES)) < 3
        assert all(p.node_name for p in op.store.list(st.PODS))
