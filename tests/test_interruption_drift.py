"""Interruption handling and drift detection e2e.

Mirrors the reference's interruption controller behavior (SURVEY.md §3.4:
SQS event -> ICE-cache spot offering -> delete NodeClaim -> replacement) and
hash-based drift (drift.go:34-74 behaviorally): bumping the NodeClass image
version drifts and replaces nodes.
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.nodeclass import KwokNodeClass
from karpenter_tpu.api.objects import ObjectMeta
from karpenter_tpu.controllers import store as st
from karpenter_tpu.controllers.interruption import (
    NOOP,
    SPOT_INTERRUPTION,
    STATE_CHANGE,
    InterruptionQueue,
    Message,
)
from karpenter_tpu.operator.operator import new_kwok_operator

from tests.test_e2e_kwok import FakeClock, mkpod, mkpool


@pytest.fixture
def op():
    clock = FakeClock()
    o = new_kwok_operator(clock=clock)
    o.clock = clock
    return o


def provision_one(op, pod_name="p", **kw):
    op.store.create(st.PODS, mkpod(pod_name, **kw))
    op.manager.settle()
    return op.store.list(st.NODECLAIMS)[0]


class TestInterruption:
    def test_spot_interruption_replaces_and_ices(self, op):
        pool = mkpool()
        from karpenter_tpu.scheduling.requirements import IN, Requirement

        pool.template.requirements.add(
            Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, [wk.CAPACITY_TYPE_SPOT])
        )
        op.store.create(st.NODEPOOLS, pool)
        claim = provision_one(op)
        assert claim.capacity_type == wk.CAPACITY_TYPE_SPOT
        old_instance = claim.provider_id.rsplit("/", 1)[-1]
        op.interruption_queue.send(
            Message(kind=SPOT_INTERRUPTION, instance_id=old_instance)
        )
        op.manager.settle()
        # offering ICE'd
        assert op.cloud_provider.unavailable.is_unavailable(
            wk.CAPACITY_TYPE_SPOT, claim.instance_type, claim.zone
        )
        # old instance gone, replacement exists, pod rebound
        assert not op.cloud.describe_instances([old_instance])
        claims = op.store.list(st.NODECLAIMS)
        assert len(claims) == 1 and claims[0].name != claim.name
        # replacement avoided the ICE'd offering
        assert (claims[0].instance_type, claims[0].zone) != (claim.instance_type, claim.zone)
        assert op.store.get(st.PODS, "p").node_name == claims[0].node_name

    def test_noop_and_benign_state_change_ignored(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        claim = provision_one(op)
        iid = claim.provider_id.rsplit("/", 1)[-1]
        op.interruption_queue.send(Message(kind=NOOP, instance_id=iid))
        op.interruption_queue.send(Message(kind=STATE_CHANGE, instance_id=iid, state="running"))
        op.manager.settle()
        assert op.store.list(st.NODECLAIMS)[0].name == claim.name  # untouched

    def test_state_change_stopping_drains(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        claim = provision_one(op)
        iid = claim.provider_id.rsplit("/", 1)[-1]
        op.interruption_queue.send(Message(kind=STATE_CHANGE, instance_id=iid, state="stopping"))
        op.manager.settle()
        claims = op.store.list(st.NODECLAIMS)
        assert claims and claims[0].name != claim.name  # replaced

    def test_queue_visibility(self):
        q = InterruptionQueue()
        for i in range(25):
            q.send(Message(kind=NOOP, instance_id=str(i)))
        batch = q.receive()
        assert len(batch) == 10  # 10-message batches (sqs.go:57-77)
        q.requeue_inflight()
        assert len(q) == 25  # undeleted messages return


class TestDrift:
    def test_nodeclass_image_bump_drifts_and_replaces(self, op):
        nc = KwokNodeClass(meta=ObjectMeta(name="default"), image_version="v1")
        op.store.create(st.NODECLASSES, nc)
        op.store.create(st.NODEPOOLS, mkpool())
        claim = provision_one(op)
        assert claim.drifted is None
        # bump the image version -> hash changes -> drift -> replacement
        nc.image_version = "v2"
        op.store.update(st.NODECLASSES, nc)
        op.clock.advance(30)
        op.manager.settle()
        claims = op.store.list(st.NODECLAIMS)
        assert len(claims) == 1
        assert claims[0].name != claim.name
        assert claims[0].drifted is None  # fresh claim records the new hash
        assert op.store.get(st.PODS, "p").node_name == claims[0].node_name

    def test_nodepool_template_change_drifts(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        claim = provision_one(op)
        pool = op.store.list(st.NODEPOOLS)[0]
        pool.template.labels["team"] = "new-team"
        op.store.update(st.NODEPOOLS, pool)
        op.clock.advance(30)
        op.manager.settle()
        claims = op.store.list(st.NODECLAIMS)
        assert claims[0].name != claim.name  # replaced due to NodePoolDrifted

    def test_nodeclass_readiness(self, op):
        bad = KwokNodeClass(meta=ObjectMeta(name="bad"), instance_families=["nonexistent"])
        op.store.create(st.NODECLASSES, bad)
        op.manager.settle()
        assert not op.store.get(st.NODECLASSES, "bad").ready
        good = KwokNodeClass(meta=ObjectMeta(name="good"), instance_families=["m5", "c5"])
        op.store.create(st.NODECLASSES, good)
        op.manager.settle()
        assert op.store.get(st.NODECLASSES, "good").ready
