"""Interruption controller throughput at 100 / 1k / 5k / 15k messages —
the reference's one real Go benchmark
(pkg/controllers/interruption/interruption_benchmark_test.go:58-75),
run as a perf-smoke: correctness asserted exactly, rate asserted loosely
(CI-safe floor) and printed for the record."""

import time

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import NodeClaim, ObjectMeta
from karpenter_tpu.controllers import store as st
from karpenter_tpu.controllers.interruption import (
    NOOP,
    SPOT_INTERRUPTION,
    STATE_CHANGE,
    InterruptionController,
    InterruptionQueue,
    Message,
)


def _mkstore(n_claims):
    store = st.Store()
    for i in range(n_claims):
        store.create(
            st.NODECLAIMS,
            NodeClaim(
                meta=ObjectMeta(name=f"c{i:05d}", labels={wk.NODEPOOL_LABEL: "p"}),
                nodepool="p",
                provider_id=f"kwok:///zone-1a/i-{i:05d}",
                instance_type="m5.large",
                zone="zone-1a",
                capacity_type="spot",
            ),
        )
    return store


def _run(n_msgs, n_claims=2000):
    store = _mkstore(n_claims)
    q = InterruptionQueue()
    ctrl = InterruptionController(store, q)
    # the reference's mix: actionable interruptions + noops + unknown ids
    for i in range(n_msgs):
        if i % 5 == 4:
            q.send(Message(kind=NOOP))
        elif i % 5 == 3:
            q.send(Message(kind=STATE_CHANGE, instance_id=f"i-{i % n_claims:05d}",
                           state="rebooting"))  # non-actionable state
        elif i % 7 == 6:
            q.send(Message(kind=SPOT_INTERRUPTION, instance_id="i-unknown"))
        else:
            q.send(Message(kind=SPOT_INTERRUPTION,
                           instance_id=f"i-{i % n_claims:05d}"))
    t0 = time.perf_counter()
    while ctrl.reconcile():
        pass
    dt = time.perf_counter() - t0
    return dt, store


class TestInterruptionThroughput:
    def test_throughput_ladder(self):
        rates = {}
        for n in (100, 1_000, 5_000, 15_000):
            dt, store = _run(n)
            rates[n] = n / dt
            # every actionable message for a live claim got it deleted
            # (no finalizers in this fixture: deletion purges outright)
            survivors = store.list(st.NODECLAIMS)
            hit = {f"c{(i % 2000):05d}" for i in range(n)
                   if i % 5 not in (3, 4) and i % 7 != 6}
            for c in survivors:
                assert c.name not in hit, f"{c.name} survived an interruption"
        print("\n[bench] interruption msgs/s: "
              + " ".join(f"{n}={rates[n]:,.0f}" for n in sorted(rates)))
        # loose floor: the indexed path is >100k/s on this box; 2k/s would
        # only fail if the per-message linear scan regression returns
        assert rates[15_000] > 2_000, f"throughput collapsed: {rates}"

    def test_index_handles_midbatch_deletes_and_new_claims(self):
        store = _mkstore(5)
        q = InterruptionQueue()
        ctrl = InterruptionController(store, q)
        # same claim twice in one batch: second lookup must see the deletion
        q.send(Message(kind=SPOT_INTERRUPTION, instance_id="i-00001"))
        q.send(Message(kind=SPOT_INTERRUPTION, instance_id="i-00001"))
        ctrl.reconcile()
        # no finalizers in this fixture: deletion purges outright, and the
        # second message must tolerate the stale index entry
        assert store.try_get(st.NODECLAIMS, "c00001") is None
        # a claim created AFTER the last batch is visible to the next one
        store.create(
            st.NODECLAIMS,
            NodeClaim(meta=ObjectMeta(name="late"), nodepool="p",
                      provider_id="kwok:///zone-1a/i-late",
                      instance_type="m5.large", zone="zone-1a",
                      capacity_type="spot"),
        )
        q.send(Message(kind=SPOT_INTERRUPTION, instance_id="i-late"))
        ctrl.reconcile()
        assert store.try_get(st.NODECLAIMS, "late") is None

    def test_index_sees_claims_registered_after_controller_start(self):
        """Watch-driven index: a claim whose provider_id lands AFTER the
        controller was constructed (and after earlier batches) must still
        resolve — the informer-style index updates on the MODIFIED event,
        not on a batch-start rebuild."""
        store = _mkstore(1)
        q = InterruptionQueue()
        ctrl = InterruptionController(store, q)
        q.send(Message(kind=NOOP))
        ctrl.reconcile()  # a batch happens before the new claim exists
        claim = NodeClaim(meta=ObjectMeta(name="fresh"), nodepool="p",
                          instance_type="m5.large", zone="zone-1a",
                          capacity_type="spot")
        store.create(st.NODECLAIMS, claim)
        claim.provider_id = "kwok:///zone-1a/i-fresh"  # launch sets it later
        store.update(st.NODECLAIMS, claim)
        q.send(Message(kind=SPOT_INTERRUPTION, instance_id="i-fresh"))
        ctrl.reconcile()
        assert store.try_get(st.NODECLAIMS, "fresh") is None

    def test_index_miss_falls_back_to_exact_scan(self):
        """A lagging watch delivery (dispatch queue draining behind a slow
        watcher) must not drop an interruption: an index miss re-checks the
        store directly before giving up — messages are deleted either way,
        so a miss here would never be retried."""
        store = _mkstore(3)
        q = InterruptionQueue()
        ctrl = InterruptionController(store, q)
        with ctrl._index_lock:
            ctrl._index.pop("i-00002")  # simulate the lag
        q.send(Message(kind=SPOT_INTERRUPTION, instance_id="i-00002"))
        ctrl.reconcile()
        assert store.try_get(st.NODECLAIMS, "c00002") is None

    def test_rebound_provider_id_survives_old_claim_deletion(self):
        """Provider id re-bound to a newer claim: deleting the OLD claim
        must not retire the new claim's index entry or poison the negative
        cache — its interruptions still deliver."""
        store = _mkstore(1)
        q = InterruptionQueue()
        ctrl = InterruptionController(store, q)
        newc = NodeClaim(meta=ObjectMeta(name="newc"), nodepool="p",
                         provider_id="kwok:///zone-1a/i-00000",
                         instance_type="m5.large", zone="zone-1a",
                         capacity_type="spot")
        store.create(st.NODECLAIMS, newc)  # re-binds i-00000
        store.delete(st.NODECLAIMS, "c00000")  # old claim goes away
        q.send(Message(kind=SPOT_INTERRUPTION, instance_id="i-00000"))
        ctrl.reconcile()
        assert store.try_get(st.NODECLAIMS, "newc") is None, (
            "interruption for the re-bound id was dropped"
        )
