"""Convex (ADMM) solver backend: feasibility parity, quality dominance,
loud fallback, disruption e2e, and knobs-off inertness.

The convex backend (solver/convex.py) is ALLOWED to place differently
from FFD — cheaper shapes are its point — but never invalidly (the same
invariant gate + min-values post-check guard both backends), never with
MORE nodes on known-optima fleets, and never silently: every decline or
fallback is counted and the FFD result is returned verbatim.
"""

import random

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.cloudprovider.types import InstanceType, Offering
from karpenter_tpu.provisioning.scheduler import ExistingNode, SolverInput
from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
from karpenter_tpu.solver.backend import TPUSolver
from karpenter_tpu.solver.convex import ConvexSolver, find_convex
from karpenter_tpu.solver.encode import quantize_input
from karpenter_tpu.solver.resilient import check_invariants
from karpenter_tpu.utils.resources import Resources

from tests.test_solver_parity import ZONES, mkpod, pool


def mktype(name, cpu, mem_gib, price, ct="on-demand"):
    reqs = Requirements.of(
        Requirement.create(wk.INSTANCE_TYPE_LABEL, IN, [name]),
        Requirement.create(wk.ARCH_LABEL, IN, ["amd64"]),
        Requirement.create(wk.OS_LABEL, IN, ["linux"]),
        Requirement.create(wk.ZONE_LABEL, IN, list(ZONES)),
        Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, [ct]),
    )
    cap = Resources.parse({"cpu": str(cpu), "memory": f"{mem_gib}Gi"})
    cap["pods"] = 110
    return InstanceType(
        name=name, requirements=reqs, capacity=cap, overhead=Resources(),
        offerings=[Offering(zone=z, capacity_type=ct, price=price)
                   for z in ZONES],
    )


def mknode(name, zone="zone-1a", cpu="8", mem="32Gi", pods=110):
    lab = {
        wk.ZONE_LABEL: zone,
        wk.HOSTNAME_LABEL: name,
        wk.CAPACITY_TYPE_LABEL: "on-demand",
        wk.ARCH_LABEL: "amd64",
        wk.OS_LABEL: "linux",
    }
    free = Resources.parse({"cpu": cpu, "memory": mem})
    free["pods"] = pods
    return ExistingNode(id=name, labels=lab, taints=[], free=free)


class TestFeasibilityParity:
    """Randomized fleets: whatever the convex backend returns must pass
    the SAME validity bar as FFD — zero invariant violations, zero
    fallbacks (a fallback would mean the gate or convergence tripped)."""

    def test_randomized_fleets_never_trip_the_gate(self):
        rng = random.Random(20419)
        for trial in range(6):
            n_nodes = rng.randint(0, 3)
            nodes = [
                mknode(f"n{trial}-{j}", zone=ZONES[j % len(ZONES)],
                       cpu=str(rng.choice([4, 8, 16])))
                for j in range(n_nodes)
            ]
            pods = [
                mkpod(f"t{trial}-p{i}", cpu=str(rng.choice([1, 2, 3])),
                      mem=f"{rng.choice([1, 2, 4])}Gi")
                for i in range(rng.randint(4, 24))
            ]
            inp = SolverInput(pods=pods, nodes=nodes, nodepools=[pool()],
                              zones=ZONES,
                              capacity_types=("on-demand", "spot"))
            cv = ConvexSolver(TPUSolver())
            res = cv.solve(inp)
            assert check_invariants(quantize_input(inp), res) == [], (
                trial, res.errors)
            assert cv.convex_stats["convex_fallbacks"] == 0, (
                trial, cv.convex_stats)
            assert cv.convex_stats["convex_solves"] == 1, (
                trial, cv.convex_stats)
            # every pod accounted for: placed or carried as an error
            placed = {u for u, t in res.placements.items() if t is not None}
            errored = set(res.errors)
            assert placed | errored >= {p.meta.uid for p in pods}

    def test_existing_capacity_filled_first(self):
        # two half-usable nodes + pods that split across them and one claim:
        # sunk existing capacity must fill before any claim opens (the FFD
        # kernel's own semantics, kept by the node-first rounding tier)
        nodes = [mknode("n1"), mknode("n2", zone="zone-1b")]
        pods = [mkpod(f"q{i:02d}", cpu="3", mem="4Gi") for i in range(8)]
        inp = SolverInput(pods=pods, nodes=nodes, nodepools=[pool()],
                          zones=ZONES, capacity_types=("on-demand", "spot"))
        cv = ConvexSolver(TPUSolver())
        res = cv.solve(inp)
        assert not res.errors
        on_node = [u for u, t in res.placements.items() if t[0] == "node"]
        assert len(on_node) == 4  # 2 x 3cpu per 8cpu node
        assert len(res.claims) == 1  # remainder packs onto ONE claim
        assert cv.convex_stats["convex_fallbacks"] == 0


class TestQualityDominance:
    """Known-optima fleets: convex must never provision MORE nodes than
    FFD, and must beat it where FFD's weight-greedy order is provably
    suboptimal (the bench quality suite's rightsize config)."""

    def _contention_input(self, n_pods=96):
        boutique = mktype("boutique.xlarge", 4, 16, 1.0)
        warehouse = mktype("warehouse.4xlarge", 16, 64, 0.9)
        pools = [
            pool("boutique", weight=100, types=[boutique]),
            pool("warehouse", weight=0, types=[warehouse]),
        ]
        pods = [mkpod(f"w{i:03d}", cpu="1", mem="1Gi") for i in range(n_pods)]
        return SolverInput(pods=pods, nodes=[], nodepools=pools, zones=ZONES,
                           capacity_types=("on-demand",))

    def test_uniform_fleet_ties_ffd(self):
        # one pool, one shape: FFD is optimal; convex must tie, not scatter
        t = mktype("std.xlarge", 4, 16, 1.0)
        pods = [mkpod(f"u{i:02d}", cpu="1", mem="1Gi") for i in range(12)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool(types=[t])],
                          zones=ZONES, capacity_types=("on-demand",))
        r_ffd = TPUSolver().solve(inp)
        cv = ConvexSolver(TPUSolver())
        r_cv = cv.solve(inp)
        assert not r_ffd.errors and not r_cv.errors
        assert len(r_cv.claims) == len(r_ffd.claims) == 3

    def test_rightsize_contention_beats_ffd(self):
        inp = self._contention_input()
        r_ffd = TPUSolver().solve(inp)
        cv = ConvexSolver(TPUSolver())
        r_cv = cv.solve(inp)
        assert not r_ffd.errors and not r_cv.errors
        # FFD follows pool weight onto 4-cpu $1.00 nodes; the convex
        # objective follows price onto 16-cpu $0.90 nodes
        assert len(r_ffd.claims) == 24
        assert len(r_cv.claims) == 6
        assert cv.convex_stats["convex_fallbacks"] == 0

    def test_convex_never_worse_on_catalog_fleets(self):
        rng = random.Random(77)
        for trial in range(3):
            pods = [
                mkpod(f"c{trial}-{i}", cpu=str(rng.choice([1, 2])),
                      mem=f"{rng.choice([1, 2])}Gi")
                for i in range(rng.randint(8, 32))
            ]
            inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                              zones=ZONES,
                              capacity_types=("on-demand", "spot"))
            r_ffd = TPUSolver().solve(inp)
            cv = ConvexSolver(TPUSolver())
            r_cv = cv.solve(inp)
            assert not r_cv.errors
            assert len(r_cv.claims) <= len(r_ffd.claims), (
                trial, len(r_cv.claims), len(r_ffd.claims))


class TestLoudFallback:
    def test_nonconvergence_falls_back_loudly(self):
        # max_iters=1 cannot converge on a real problem: the solve must
        # complete via the FFD fallback AND the failure must be counted —
        # never a silent quality downgrade
        pods = [mkpod(f"p{i}", cpu="1", mem="1Gi") for i in range(12)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                          zones=ZONES, capacity_types=("on-demand", "spot"))
        cv = ConvexSolver(TPUSolver(), max_iters=1)
        res = cv.solve(inp)
        assert not res.errors  # the fallback FFD leg still solved it
        assert cv.convex_stats["convex_fallbacks"] == 1
        assert cv.convex_stats["convex_solves"] == 0

    def test_per_pool_backend_label_declines(self):
        # one pool pinned to ffd: the selection gate requires EVERY pool to
        # resolve convex, so the solve delegates verbatim (counted decline)
        p1 = pool("a")
        p2 = pool("b")
        p2.solver_backend = "ffd"
        pods = [mkpod("p0"), mkpod("p1")]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[p1, p2],
                          zones=ZONES, capacity_types=("on-demand", "spot"))
        cv = ConvexSolver(TPUSolver())
        res = cv.solve(inp)
        assert not res.errors
        assert cv.convex_stats["convex_declines"] == 1
        assert cv.convex_stats["convex_solves"] == 0


class TestConsolidateGlobal:
    def test_one_shot_proposal_and_dispatch_budget(self):
        t = mktype("std.4xlarge", 16, 64, 0.9)
        nodes = [mknode(f"c{j}") for j in range(1, 4)]
        nodes.append(mknode("surv", cpu="16", mem="64Gi"))
        pods = [mkpod(f"m{j}{k}", cpu="1", mem="1Gi")
                for j in range(3) for k in range(2)]
        inp = SolverInput(pods=pods, nodes=nodes,
                          nodepools=[pool(types=[t])], zones=ZONES,
                          capacity_types=("on-demand",))
        cv = ConvexSolver(TPUSolver())
        dispatches = 0
        inner = cv._dispatch

        def counting(prob):
            nonlocal dispatches
            dispatches += 1
            return inner(prob)

        cv._dispatch = counting
        cands = [(f"c{j}", 0.5,
                  frozenset({f"m{j - 1}{k}" for k in range(2)}))
                 for j in range(1, 4)]
        proposal = cv.consolidate_global(inp, cands)
        assert proposal is not None
        assert sorted(proposal["delete"]) == ["c1", "c2", "c3"]
        assert proposal["iterations"] > 0
        assert dispatches == 1  # ONE device program for the whole decision
        assert all(m < 0.2 for m in proposal["stay_mass"].values())

    def test_infeasible_consolidation_declines(self):
        # survivor too small for even two candidates' pods: no >=2-subset
        # can empty, so the global pass must decline (probe ladder's job)
        t = mktype("std.4xlarge", 16, 64, 0.9)
        nodes = [mknode(f"c{j}") for j in range(1, 4)]
        nodes.append(mknode("surv", cpu="2", mem="64Gi"))
        pods = [mkpod(f"m{j}{k}", cpu="1", mem="1Gi")
                for j in range(3) for k in range(2)]
        inp = SolverInput(pods=pods, nodes=nodes,
                          nodepools=[pool(types=[t])], zones=ZONES,
                          capacity_types=("on-demand",))
        cv = ConvexSolver(TPUSolver())
        assert cv.consolidate_global(inp, [
            (f"c{j}", 0.5, frozenset({f"m{j - 1}{k}" for k in range(2)}))
            for j in range(1, 4)
        ]) is None
        assert cv.convex_stats["global_declines"] == 1


class TestDisruptionE2E:
    """The operator-level seam: solver_convex=True wires ConvexSolver
    inside the resilience wrap and the disruption controller finds it for
    the one-shot global pass; the probe ladder remains the cross-check."""

    def _settle_consolidation(self, op):
        from karpenter_tpu.controllers import store as st
        from tests.test_e2e_kwok import mkpool, mkpod as e2epod

        from karpenter_tpu.api.objects import TopologySpreadConstraint

        op.store.create(st.NODEPOOLS, mkpool())
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.HOSTNAME_LABEL,
            label_selector={"app": "x"},
        )
        for i in range(3):
            op.store.create(
                st.PODS,
                e2epod(f"p{i}", cpu="200m", mem="256Mi",
                       labels={"app": "x"}, topology_spread=[tsc]),
            )
        op.manager.settle()
        assert len(op.store.list(st.NODES)) == 3
        for i in range(3):
            p = op.store.get(st.PODS, f"p{i}")
            p.topology_spread = []
            op.store.update(st.PODS, p)
        op.clock.advance(30)
        op.manager.settle()
        nodes = op.store.list(st.NODES)
        pods = op.store.list(st.PODS)
        assert all(p.node_name for p in pods)
        return len(nodes)

    def test_convex_operator_consolidates_like_probe_ladder(self):
        from karpenter_tpu.operator.operator import new_kwok_operator
        from tests.test_e2e_kwok import FakeClock

        results = {}
        for convex in (False, True):
            clock = FakeClock()
            op = new_kwok_operator(clock=clock, solver_convex=convex)
            op.clock = clock
            results[convex] = self._settle_consolidation(op)
            if convex:
                from karpenter_tpu.disruption.controller import (
                    DisruptionController,
                )

                cv = find_convex(op.provisioner.solver)
                assert cv is not None
                dc = next(c for c in op.manager.controllers
                          if isinstance(c, DisruptionController))
                assert dc._convex is cv
        # both control loops converge the fleet to the same node count
        assert results[True] == results[False] < 3


class TestKnobsOffInertness:
    def test_solver_convex_off_is_byte_identical(self):
        # knob off: the operator must build the EXACT solver object graph
        # it built before this feature existed — no wrapper in the chain
        from karpenter_tpu.operator.operator import new_kwok_operator
        from tests.test_e2e_kwok import FakeClock

        from karpenter_tpu.disruption.controller import DisruptionController

        op = new_kwok_operator(clock=FakeClock())
        assert find_convex(op.provisioner.solver) is None
        dc = next(c for c in op.manager.controllers
                  if isinstance(c, DisruptionController))
        assert dc._convex is None

    def test_unselected_solve_is_inner_result_verbatim(self):
        # default_backend="ffd": selection never engages; the wrapper must
        # return the inner solver's result OBJECT, not a reconstruction
        pods = [mkpod("p0"), mkpod("p1")]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                          zones=ZONES, capacity_types=("on-demand", "spot"))
        inner = TPUSolver()
        cv = ConvexSolver(inner, default_backend="ffd")
        r_direct = inner.solve(inp)
        r_wrapped = cv.solve(inp)
        assert r_wrapped.placements == r_direct.placements
        assert [c.requests for c in r_wrapped.claims] == [
            c.requests for c in r_direct.claims]
        assert cv.convex_stats["convex_solves"] == 0


class TestMetricsWiring:
    def test_convex_counters_move(self):
        from karpenter_tpu.metrics import registry as reg

        pods = [mkpod(f"p{i}", cpu="1", mem="1Gi") for i in range(8)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()],
                          zones=ZONES, capacity_types=("on-demand", "spot"))
        before = reg.REGISTRY.expose()
        cv = ConvexSolver(TPUSolver())
        res = cv.solve(inp)
        assert not res.errors
        after = reg.REGISTRY.expose()

        def val(dump, needle):
            return sum(
                float(line.rsplit(" ", 1)[1])
                for line in dump.splitlines()
                if line.startswith(needle)
            )

        assert (val(after, "karpenter_solver_convex_solves_total")
                > val(before, "karpenter_solver_convex_solves_total"))
