"""Config 5 e2e: multi-node consolidation at fleet scale, one command.

BASELINE.json configs[5] — the disruption engine must consolidate a large
underutilized fleet through the batched device evaluator, deleting 100+
nodes in a SINGLE multi-consolidation command (reference semantics: one
command per loop, heuristic cost-ordered prefix — disruption.md:97-106,
designs/consolidation.md:5-36). bench.py measures the same seam at 10k
nodes on real hardware.
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    Budget,
    Disruption,
    NodeClaimTemplate,
    NodePool,
    ObjectMeta,
    Pod,
    TopologySpreadConstraint,
)
from karpenter_tpu.controllers import store as st
from karpenter_tpu.disruption.controller import DisruptionController
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.solver.backend import TPUSolver
from karpenter_tpu.utils.resources import Resources

from tests.test_e2e_kwok import FakeClock

N = 104  # >100 nodes in one command; fits a single replacement node's pod cap


@pytest.fixture
def op():
    clock = FakeClock()
    o = new_kwok_operator(clock=clock, solver=TPUSolver())
    o.clock = clock
    return o


def test_multi_node_consolidation_hundred_nodes_one_command(op):
    op.store.create(
        st.NODEPOOLS,
        NodePool(
            meta=ObjectMeta(name="default"),
            template=NodeClaimTemplate(),
            disruption=Disruption(
                consolidation_policy="WhenEmptyOrUnderutilized",
                consolidate_after_s=0.0,
                budgets=[Budget(nodes="100%")],
            ),
        ),
    )
    tsc = TopologySpreadConstraint(
        max_skew=1, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "wide"}
    )
    for i in range(N):
        op.store.create(
            st.PODS,
            Pod(
                meta=ObjectMeta(name=f"w{i:03d}", uid=f"w{i:03d}", labels={"app": "wide"}),
                requests=Resources.parse({"cpu": "150m", "memory": "192Mi"}),
                topology_spread=[tsc],
            ),
        )
    op.manager.settle(max_ticks=600)
    assert len(op.store.list(st.NODES)) == N, "hostname spread must fan out 1 pod/node"

    # record every executed command to prove ONE multi-node command does it
    dc = next(c for c in op.manager.controllers if isinstance(c, DisruptionController))
    executed = []
    orig = dc._execute

    def spy(cmd):
        executed.append((cmd.method, len(cmd.candidates)))
        return orig(cmd)

    dc._execute = spy

    for i in range(N):
        p = op.store.get(st.PODS, f"w{i:03d}")
        p.topology_spread = []
        op.store.update(st.PODS, p)
    op.clock.advance(30)
    op.manager.settle(max_ticks=600)

    pods = op.store.list(st.PODS)
    nodes = op.store.list(st.NODES)
    assert all(p.node_name for p in pods), "every pod rebinds"
    assert len(nodes) <= 3, f"fleet should collapse, still {len(nodes)} nodes"
    multi = [e for e in executed if e[0] == "multi-consolidation"]
    assert multi, f"no multi-consolidation command executed: {executed}"
    assert max(n for _m, n in multi) >= 100, (
        f"expected >=100 candidates in one command: {executed}"
    )
    assert dc.stats.get("batched_prefixes_evaluated", 0) > 0, (
        "prefix search must run on the batched device evaluator"
    )
