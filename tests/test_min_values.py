"""NodePool minValues flexibility floors enforced DURING Solve.

Reference semantics (website/.../concepts/nodepools.md:268-330; scale e2e
variants test/suites/scale/provisioning_test.go:179,215): a requirement with
minValues demands that many distinct values among a claim's surviving
instance types; a pod whose constraints would narrow a claim below the floor
cannot use that NodePool. Enforced by the oracle at every narrowing step
(scheduler.min_values_ok) and by the tensor backends via the equivalent
final-state post-check (backend.min_values_post_check).
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import ObjectMeta, Pod
from karpenter_tpu.catalog.catalog import CatalogSpec, generate
from karpenter_tpu.provisioning.scheduler import NodePoolSpec, SolverInput
from karpenter_tpu.scheduling.requirements import (
    EXISTS,
    IN,
    Requirement,
    Requirements,
)
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver, quantize_input
from karpenter_tpu.solver.native import NativeSolver

from tests.test_solver_parity import assert_parity, mkpod

CATALOG = generate(CatalogSpec())
ZONES = ("zone-1a", "zone-1b", "zone-1c")
FAMILY_KEY = "karpenter.tpu/instance-family"
N_FAMILIES = len({it.requirements.get(FAMILY_KEY).values_list()[0] for it in CATALOG})


def mv_pool(min_values: int, key: str = FAMILY_KEY):
    return NodePoolSpec(
        name="flex",
        weight=0,
        requirements=Requirements.of(
            Requirement.create(wk.NODEPOOL_LABEL, IN, ["flex"]),
            Requirement.create(key, EXISTS, (), min_values=min_values),
        ),
        taints=[],
        instance_types=CATALOG,
    )


class TestOracleMinValues:
    def test_floor_satisfied_schedules(self):
        inp = SolverInput(
            pods=[mkpod(f"p{i}") for i in range(5)],
            nodes=[],
            nodepools=[mv_pool(min_values=2)],
            zones=ZONES,
        )
        res = ReferenceSolver().solve(quantize_input(inp))
        assert not res.errors
        for c in res.claims:
            fams = {
                t.requirements.get(FAMILY_KEY).values_list()[0]
                for t in CATALOG
                if t.name in set(c.instance_type_names)
            }
            assert len(fams) >= 2

    def test_narrowing_below_floor_fails(self):
        # pin the pod to ONE family: the floor (2 families) can never be met
        pod = mkpod("pinned", node_selector={FAMILY_KEY: "m5"})
        inp = SolverInput(
            pods=[pod], nodes=[], nodepools=[mv_pool(min_values=2)], zones=ZONES
        )
        res = ReferenceSolver().solve(quantize_input(inp))
        assert "pinned" in res.errors

    def test_impossible_floor_fails_everything(self):
        inp = SolverInput(
            pods=[mkpod("p0")],
            nodes=[],
            nodepools=[mv_pool(min_values=N_FAMILIES + 10)],
            zones=ZONES,
        )
        res = ReferenceSolver().solve(quantize_input(inp))
        assert res.errors

    def test_second_pool_picks_up_rejected_pod(self):
        # higher-weight pool has an unreachable floor; the pod lands on the
        # plain lower-weight pool instead
        plain = NodePoolSpec(
            name="plain",
            weight=0,
            requirements=Requirements.of(
                Requirement.create(wk.NODEPOOL_LABEL, IN, ["plain"])
            ),
            taints=[],
            instance_types=CATALOG,
        )
        strict = mv_pool(min_values=N_FAMILIES + 10)
        strict.weight = 50
        inp = SolverInput(
            pods=[mkpod("p0")], nodes=[], nodepools=[strict, plain], zones=ZONES
        )
        res = ReferenceSolver().solve(quantize_input(inp))
        assert not res.errors
        assert res.claims[0].nodepool == "plain"


class TestBackendsMinValues:
    def test_parity_floor_satisfied(self):
        inp = SolverInput(
            pods=[mkpod(f"p{i}", cpu="500m", mem="512Mi") for i in range(12)],
            nodes=[],
            nodepools=[mv_pool(min_values=3)],
            zones=ZONES,
        )
        ref, tpu = assert_parity(inp)
        assert not ref.errors

    def test_device_falls_back_on_violation(self):
        # the pinned pod violates the floor: the device post-check must route
        # the solve to the oracle, whose verdict (error) is authoritative
        pod = mkpod("pinned", node_selector={FAMILY_KEY: "m5"})
        inp = SolverInput(
            pods=[pod], nodes=[], nodepools=[mv_pool(min_values=2)], zones=ZONES
        )
        solver = TPUSolver()
        res = solver.solve(inp)
        assert "pinned" in res.errors
        ref = ReferenceSolver().solve(quantize_input(inp))
        assert set(res.errors) == set(ref.errors)

    def test_native_falls_back_on_violation(self):
        pod = mkpod("pinned", node_selector={FAMILY_KEY: "m5"})
        inp = SolverInput(
            pods=[pod], nodes=[], nodepools=[mv_pool(min_values=2)], zones=ZONES
        )
        solver = NativeSolver()
        res = solver.solve(inp)
        assert "pinned" in res.errors

    def test_parity_mixed_floor_and_plain_pools(self):
        plain = NodePoolSpec(
            name="plain",
            weight=0,
            requirements=Requirements.of(
                Requirement.create(wk.NODEPOOL_LABEL, IN, ["plain"])
            ),
            taints=[],
            instance_types=CATALOG,
        )
        strict = mv_pool(min_values=2)
        strict.weight = 50
        pods = [mkpod(f"p{i}") for i in range(6)]
        pods.append(mkpod("pinned", node_selector={FAMILY_KEY: "c5"}))
        inp = SolverInput(
            pods=pods, nodes=[], nodepools=[strict, plain], zones=ZONES
        )
        assert_parity(inp)
