"""PV zonal topology (website/.../concepts/scheduling.md:430+).

A pod whose PVC is bound to a zonal PV must schedule in the PV's zone; an
unbound (WaitForFirstConsumer) claim imposes nothing at schedule time and
binds to a PV in the landing zone afterwards.
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    NodeClaimTemplate,
    NodePool,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
)
from karpenter_tpu.controllers import store as st
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.solver.backend import TPUSolver
from karpenter_tpu.utils.resources import Resources

from tests.test_e2e_kwok import FakeClock, mkpool
from tests.test_solver_parity import assert_parity, mkpod, pool
from karpenter_tpu.provisioning.scheduler import SolverInput

ZONES = ("zone-1a", "zone-1b", "zone-1c")


@pytest.fixture
def op():
    clock = FakeClock()
    o = new_kwok_operator(clock=clock, solver=TPUSolver())
    o.clock = clock
    return o


def mkvolpod(name, claims, **kw):
    return Pod(
        meta=ObjectMeta(name=name, uid=name),
        requests=Resources.parse({"cpu": "500m", "memory": "512Mi"}),
        volume_claims=list(claims),
        **kw,
    )


class TestSolverLevel:
    def test_volume_zone_restriction_parity(self):
        # volume_zones pins the pod to zone-1b on both backends
        pods = [mkpod(f"p{i}") for i in range(3)]
        pods.append(mkpod("pinned"))
        pods[-1].volume_zones = ("zone-1b",)
        ref, tpu = assert_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )
        assert not ref.errors
        tgt = ref.placements["pinned"]
        assert tgt[0] == "claim"
        zr = ref.claims[tgt[1]].requirements.get(wk.ZONE_LABEL)
        assert zr.values_list() == ["zone-1b"]


class TestE2E:
    def test_bound_zonal_pv_pins_pod(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        op.store.create(
            st.PERSISTENTVOLUMES,
            PersistentVolume(meta=ObjectMeta(name="pv-b"), zones=["zone-1b"]),
        )
        op.store.create(
            st.PERSISTENTVOLUMECLAIMS,
            PersistentVolumeClaim(meta=ObjectMeta(name="data"), volume_name="pv-b"),
        )
        op.store.create(st.PODS, mkvolpod("db", ["data"]))
        op.manager.settle()
        pod = op.store.get(st.PODS, "db")
        assert pod.node_name is not None
        node = op.store.get(st.NODES, pod.node_name)
        assert node.meta.labels[wk.ZONE_LABEL] == "zone-1b"

    def test_unbound_claim_late_binds_in_landing_zone(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        op.store.create(
            st.PERSISTENTVOLUMECLAIMS,
            PersistentVolumeClaim(meta=ObjectMeta(name="scratch")),
        )
        op.store.create(st.PODS, mkvolpod("web", ["scratch"]))
        op.manager.settle()
        pod = op.store.get(st.PODS, "web")
        assert pod.node_name is not None
        node = op.store.get(st.NODES, pod.node_name)
        pvc = op.store.get(st.PERSISTENTVOLUMECLAIMS, "scratch")
        assert pvc.volume_name is not None, "claim should late-bind"
        pv = op.store.get(st.PERSISTENTVOLUMES, pvc.volume_name)
        assert pv.zones == [node.meta.labels[wk.ZONE_LABEL]]
        # the pod is now zone-pinned for any future reschedule
        op.manager.settle()
        assert op.store.get(st.PODS, "web").volume_zones == (
            node.meta.labels[wk.ZONE_LABEL],
        )

    def test_conflicting_volumes_unschedulable(self, op):
        op.store.create(st.NODEPOOLS, mkpool())
        for name, zone in (("pv-a", "zone-1a"), ("pv-b", "zone-1b")):
            op.store.create(
                st.PERSISTENTVOLUMES,
                PersistentVolume(meta=ObjectMeta(name=name), zones=[zone]),
            )
            op.store.create(
                st.PERSISTENTVOLUMECLAIMS,
                PersistentVolumeClaim(meta=ObjectMeta(name=f"c-{zone}"), volume_name=name),
            )
        op.store.create(st.PODS, mkvolpod("torn", ["c-zone-1a", "c-zone-1b"]))
        op.manager.settle()
        assert op.store.get(st.PODS, "torn").node_name is None
        assert not op.store.list(st.NODES)
