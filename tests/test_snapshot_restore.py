"""Durability: snapshot/restore + kill-restore-converge (no instance leaks).

Mirrors the reference kwok provider's ConfigMap instance backup every 5s +
restore at boot (kwok/ec2/ec2.go:112-232), extended to the whole store (the
in-process store is this framework's API server). A restarted process must
rebuild the exact cluster; orphaned cloud instances (their NodeClaim lost)
must be garbage-collected, not leaked.
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.controllers import store as st
from karpenter_tpu.controllers.snapshot import save_snapshot
from karpenter_tpu.operator.operator import new_kwok_operator

from tests.test_e2e_kwok import FakeClock, mkpod, mkpool


def boot(tmp_path, clock=None):
    clock = clock or FakeClock()
    o = new_kwok_operator(
        clock=clock, snapshot_path=str(tmp_path / "snap.bin"), snapshot_interval_s=5.0
    )
    o.clock = clock
    return o


def test_restart_rebuilds_cluster(tmp_path):
    op = boot(tmp_path)
    op.store.create(st.NODEPOOLS, mkpool())
    for i in range(5):
        op.store.create(st.PODS, mkpod(f"p{i}", cpu="500m"))
    op.manager.settle()
    nodes0 = {n.meta.name for n in op.store.list(st.NODES)}
    claims0 = {c.name for c in op.store.list(st.NODECLAIMS)}
    assert nodes0 and claims0
    op.clock.advance(10)
    op.manager.tick()  # snapshot cadence fires

    # "kill" the process: a fresh operator restores from the same path
    op2 = boot(tmp_path)
    assert {n.meta.name for n in op2.store.list(st.NODES)} == nodes0
    assert {c.name for c in op2.store.list(st.NODECLAIMS)} == claims0
    assert {p.meta.name for p in op2.store.list(st.PODS)} == {f"p{i}" for i in range(5)}
    assert len(op2.cloud.describe_instances()) == len(claims0)
    # the restored loop converges without churn: no new nodes, pods bound
    op2.manager.settle()
    assert {n.meta.name for n in op2.store.list(st.NODES)} == nodes0
    assert all(p.node_name for p in op2.store.list(st.PODS))


def test_orphaned_instance_gc_after_restore(tmp_path):
    op = boot(tmp_path)
    op.store.create(st.NODEPOOLS, mkpool())
    op.store.create(st.PODS, mkpod("p0", cpu="500m"))
    op.manager.settle()
    assert len(op.cloud.describe_instances()) == 1
    # lose the NodeClaim + Node from the snapshot (simulates state written
    # before a crash mid-deletion): instance must NOT leak after restore
    claim = op.store.list(st.NODECLAIMS)[0]
    node = op.store.list(st.NODES)[0]
    claim.meta.finalizers = []
    node.meta.finalizers = []
    op.store.update(st.NODECLAIMS, claim)
    op.store.update(st.NODES, node)
    op.store.delete(st.NODECLAIMS, claim.name)
    op.store.delete(st.NODES, node.meta.name)
    pod = op.store.get(st.PODS, "p0")
    pod.node_name = None
    pod.phase = "Pending"
    op.store.update(st.PODS, pod)
    save_snapshot(op.store, op.cloud, str(tmp_path / "snap.bin"), now=op.clock())

    op2 = boot(tmp_path)
    assert len(op2.cloud.describe_instances()) == 1, "orphan restored"
    op2.clock.advance(60)  # past the GC grace period
    op2.manager.settle()
    # GC reaped the orphan; the pending pod re-provisioned a fresh node
    ids = {i.id for i in op2.cloud.describe_instances()}
    assert len(ids) == 1
    claims = op2.store.list(st.NODECLAIMS)
    assert len(claims) == 1
    assert op2.store.get(st.PODS, "p0").node_name is not None


def test_snapshot_cadence(tmp_path):
    import os

    op = boot(tmp_path)
    op.store.create(st.NODEPOOLS, mkpool())
    op.manager.tick()
    path = str(tmp_path / "snap.bin")
    assert os.path.exists(path), "first tick writes the initial snapshot"
    mtime0 = os.path.getmtime(path)
    op.manager.tick()  # within the 5s window: no rewrite
    assert os.path.getmtime(path) == mtime0
    op.clock.advance(6)
    op.manager.tick()
    # content may be identical; cadence is what we assert (file rewritten)
    assert os.path.getmtime(path) >= mtime0


def test_restore_rebases_monotonic_clocks(tmp_path):
    """A reboot resets CLOCK_MONOTONIC: the restored process starts near 0
    while the snapshot carries large timestamps. Restore must rebase them so
    ages are preserved — GC grace and expiry keep firing."""
    clock_hi = FakeClock()
    clock_hi.t = 500_000.0
    op = new_kwok_operator(
        clock=clock_hi, snapshot_path=str(tmp_path / "snap.bin")
    )
    op.clock = clock_hi
    op.store.create(st.NODEPOOLS, mkpool())
    op.store.create(st.PODS, mkpod("p0", cpu="500m"))
    op.manager.settle()
    # orphan the instance (claim+node lost in the crash)
    claim = op.store.list(st.NODECLAIMS)[0]
    node = op.store.list(st.NODES)[0]
    claim.meta.finalizers = []
    node.meta.finalizers = []
    op.store.update(st.NODECLAIMS, claim)
    op.store.update(st.NODES, node)
    op.store.delete(st.NODECLAIMS, claim.name)
    op.store.delete(st.NODES, node.meta.name)
    pod = op.store.get(st.PODS, "p0")
    pod.meta.finalizers = []
    op.store.delete(st.PODS, "p0")
    save_snapshot(op.store, op.cloud, str(tmp_path / "snap.bin"), now=clock_hi())

    # "reboot": fresh process with a small monotonic clock
    clock_lo = FakeClock()
    clock_lo.t = 100.0
    op2 = new_kwok_operator(
        clock=clock_lo, snapshot_path=str(tmp_path / "snap.bin")
    )
    op2.clock = clock_lo
    insts = op2.cloud.describe_instances()
    assert len(insts) == 1
    assert insts[0].launch_time <= clock_lo(), "launch_time rebased into the new epoch"
    clock_lo.advance(60)  # past GC grace in the NEW epoch
    op2.manager.settle()
    assert not op2.cloud.describe_instances(), "orphan reaped after rebase"
