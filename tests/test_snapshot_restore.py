"""Durability: snapshot/restore + kill-restore-converge (no instance leaks).

Mirrors the reference kwok provider's ConfigMap instance backup every 5s +
restore at boot (kwok/ec2/ec2.go:112-232), extended to the whole store (the
in-process store is this framework's API server). A restarted process must
rebuild the exact cluster; orphaned cloud instances (their NodeClaim lost)
must be garbage-collected, not leaked.
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.controllers import store as st
from karpenter_tpu.controllers.snapshot import save_snapshot
from karpenter_tpu.operator.operator import new_kwok_operator

from tests.test_e2e_kwok import FakeClock, mkpod, mkpool


def boot(tmp_path, clock=None):
    clock = clock or FakeClock()
    o = new_kwok_operator(
        clock=clock, snapshot_path=str(tmp_path / "snap.bin"), snapshot_interval_s=5.0
    )
    o.clock = clock
    return o


def test_restart_rebuilds_cluster(tmp_path):
    op = boot(tmp_path)
    op.store.create(st.NODEPOOLS, mkpool())
    for i in range(5):
        op.store.create(st.PODS, mkpod(f"p{i}", cpu="500m"))
    op.manager.settle()
    nodes0 = {n.meta.name for n in op.store.list(st.NODES)}
    claims0 = {c.name for c in op.store.list(st.NODECLAIMS)}
    assert nodes0 and claims0
    op.clock.advance(10)
    op.manager.tick()  # snapshot cadence fires

    # "kill" the process: a fresh operator restores from the same path
    op2 = boot(tmp_path)
    assert {n.meta.name for n in op2.store.list(st.NODES)} == nodes0
    assert {c.name for c in op2.store.list(st.NODECLAIMS)} == claims0
    assert {p.meta.name for p in op2.store.list(st.PODS)} == {f"p{i}" for i in range(5)}
    assert len(op2.cloud.describe_instances()) == len(claims0)
    # the restored loop converges without churn: no new nodes, pods bound
    op2.manager.settle()
    assert {n.meta.name for n in op2.store.list(st.NODES)} == nodes0
    assert all(p.node_name for p in op2.store.list(st.PODS))


def test_orphaned_instance_gc_after_restore(tmp_path):
    op = boot(tmp_path)
    op.store.create(st.NODEPOOLS, mkpool())
    op.store.create(st.PODS, mkpod("p0", cpu="500m"))
    op.manager.settle()
    assert len(op.cloud.describe_instances()) == 1
    # lose the NodeClaim + Node from the snapshot (simulates state written
    # before a crash mid-deletion): instance must NOT leak after restore
    claim = op.store.list(st.NODECLAIMS)[0]
    node = op.store.list(st.NODES)[0]
    claim.meta.finalizers = []
    node.meta.finalizers = []
    op.store.update(st.NODECLAIMS, claim)
    op.store.update(st.NODES, node)
    op.store.delete(st.NODECLAIMS, claim.name)
    op.store.delete(st.NODES, node.meta.name)
    pod = op.store.get(st.PODS, "p0")
    pod.node_name = None
    pod.phase = "Pending"
    op.store.update(st.PODS, pod)
    save_snapshot(op.store, op.cloud, str(tmp_path / "snap.bin"), now=op.clock())

    op2 = boot(tmp_path)
    assert len(op2.cloud.describe_instances()) == 1, "orphan restored"
    op2.clock.advance(60)  # past the GC grace period
    op2.manager.settle()
    # GC reaped the orphan; the pending pod re-provisioned a fresh node
    ids = {i.id for i in op2.cloud.describe_instances()}
    assert len(ids) == 1
    claims = op2.store.list(st.NODECLAIMS)
    assert len(claims) == 1
    assert op2.store.get(st.PODS, "p0").node_name is not None


def test_snapshot_cadence(tmp_path):
    import os

    op = boot(tmp_path)
    op.store.create(st.NODEPOOLS, mkpool())
    op.manager.tick()
    path = str(tmp_path / "snap.bin")
    assert os.path.exists(path), "first tick writes the initial snapshot"
    mtime0 = os.path.getmtime(path)
    op.manager.tick()  # within the 5s window: no rewrite
    assert os.path.getmtime(path) == mtime0
    op.clock.advance(6)
    op.manager.tick()
    # content may be identical; cadence is what we assert (file rewritten)
    assert os.path.getmtime(path) >= mtime0


def test_restore_rebases_monotonic_clocks(tmp_path):
    """A reboot resets CLOCK_MONOTONIC: the restored process starts near 0
    while the snapshot carries large timestamps. Restore must rebase them so
    ages are preserved — GC grace and expiry keep firing."""
    clock_hi = FakeClock()
    clock_hi.t = 500_000.0
    op = new_kwok_operator(
        clock=clock_hi, snapshot_path=str(tmp_path / "snap.bin")
    )
    op.clock = clock_hi
    op.store.create(st.NODEPOOLS, mkpool())
    op.store.create(st.PODS, mkpod("p0", cpu="500m"))
    op.manager.settle()
    # orphan the instance (claim+node lost in the crash)
    claim = op.store.list(st.NODECLAIMS)[0]
    node = op.store.list(st.NODES)[0]
    claim.meta.finalizers = []
    node.meta.finalizers = []
    op.store.update(st.NODECLAIMS, claim)
    op.store.update(st.NODES, node)
    op.store.delete(st.NODECLAIMS, claim.name)
    op.store.delete(st.NODES, node.meta.name)
    pod = op.store.get(st.PODS, "p0")
    pod.meta.finalizers = []
    op.store.delete(st.PODS, "p0")
    save_snapshot(op.store, op.cloud, str(tmp_path / "snap.bin"), now=clock_hi())

    # "reboot": fresh process with a small monotonic clock
    clock_lo = FakeClock()
    clock_lo.t = 100.0
    op2 = new_kwok_operator(
        clock=clock_lo, snapshot_path=str(tmp_path / "snap.bin")
    )
    op2.clock = clock_lo
    insts = op2.cloud.describe_instances()
    assert len(insts) == 1
    assert insts[0].launch_time <= clock_lo(), "launch_time rebased into the new epoch"
    clock_lo.advance(60)  # past GC grace in the NEW epoch
    op2.manager.settle()
    assert not op2.cloud.describe_instances(), "orphan reaped after rebase"


def test_time_travel_preserves_lease_and_expiry_ages(tmp_path):
    """VERDICT r4 next #7: rebasable fields come from the CLOCK metadata
    marker, not a hardcoded list — including Lease.renew_time. Time-travel a
    snapshot into a different epoch and assert the age math that depends on
    each field: a foreign lease's REMAINING duration is preserved (the new
    process neither seizes instantly nor waits forever), and a claim's
    expiry age carries over."""
    from karpenter_tpu.api.objects import NodeClaimTemplate, NodePool, ObjectMeta
    from karpenter_tpu.controllers.leaderelection import (
        LEADER_LEASE_NAME,
        LeaderElector,
    )
    from karpenter_tpu.controllers.snapshot import restore_snapshot

    snap = str(tmp_path / "snap.bin")
    clock_hi = FakeClock()
    clock_hi.t = 500_000.0
    op = new_kwok_operator(clock=clock_hi, leader_elect=True,
                           identity="old-leader", snapshot_path=snap)
    pool = NodePool(meta=ObjectMeta(name="default"),
                    template=NodeClaimTemplate(expire_after_s=300.0))
    op.store.create(st.NODEPOOLS, pool)
    op.store.create(st.PODS, mkpod("p0", cpu="500m"))
    for _ in range(30):
        op.manager.tick()
    assert op.manager.elector.is_leader()
    claim = op.store.list(st.NODECLAIMS)[0]
    # age the world: claim is 100s old, lease renewed 5s ago (10s remain)
    clock_hi.advance(95)
    op.manager.tick()  # renews the lease (renew_s/2 elapsed)
    clock_hi.advance(5)
    save_snapshot(op.store, op.cloud, snap, now=clock_hi())

    # time-travel into a small-epoch process
    clock_lo = FakeClock()
    clock_lo.t = 100.0
    op2 = new_kwok_operator(clock=clock_lo, snapshot_path=snap)
    lease = op2.store.get("leases", LEADER_LEASE_NAME)
    assert lease.holder == "old-leader"
    assert lease.renew_time <= clock_lo(), "renew_time rebased into new epoch"

    # a NEW identity must wait out the REMAINING lease (~10s), not 15s, not 0
    e2 = LeaderElector(op2.store, "new-leader", clock=clock_lo)
    e2.tick()
    assert not e2.is_leader(), "seized an unexpired restored lease"
    clock_lo.advance(11)  # past the remaining duration
    e2.tick()
    assert e2.is_leader(), "restored lease never expired (renew_time skew)"
    assert e2.takeover

    # claim expiry: 100s of its 300s lifetime elapsed pre-snapshot, so it
    # expires ~200s into the new epoch, not ~300s
    claim2 = op2.store.get(st.NODECLAIMS, claim.name)
    age_now = clock_lo() - claim2.meta.creation_timestamp
    assert 95 <= age_now <= 120, f"claim age not preserved: {age_now}"


def test_snapshot_stall_bounded_at_10k_nodes(tmp_path):
    """VERDICT r4 weak #3/next #6: the 5s snapshot pauses every store
    mutation while it serializes. At config-5 scale (10k nodes + claims +
    instances + 12k pods) the full pickle measured ~270ms per save; the
    incremental blob cache must keep the steady-state save — and therefore
    the worst-case mutation stall — well under that, scaling with the
    change rate instead of cluster size."""
    import time as _time

    from karpenter_tpu.api.objects import Node, NodeClaim, ObjectMeta, Pod
    from karpenter_tpu.controllers.snapshot import restore_snapshot
    from karpenter_tpu.kwok.cloud import Instance
    from karpenter_tpu.utils.resources import Resources

    clock = FakeClock()
    op = new_kwok_operator(clock=clock)
    for j in range(10_000):
        name = f"n{j:05d}"
        op.store.create(
            st.NODECLAIMS,
            NodeClaim(meta=ObjectMeta(name=name),
                      provider_id=f"kwok:///z/{name}", launched=True),
        )
        op.store.create(st.NODES, Node(meta=ObjectMeta(name=name)))
        op.cloud._instances[name] = Instance(
            id=name, instance_type="m5.large", zone="zone-1a",
            capacity_type="on-demand", price=0.1, launch_time=clock(),
        )
    for i in range(12_000):
        op.store.create(
            st.PODS,
            Pod(meta=ObjectMeta(name=f"p{i}", uid=f"p{i}"),
                requests=Resources.parse({"cpu": "100m"})),
        )

    path = str(tmp_path / "stall.snap")
    cache: dict = {}
    t0 = _time.perf_counter()
    save_snapshot(op.store, op.cloud, path, now=clock(), blob_cache=cache)
    cold_ms = (_time.perf_counter() - t0) * 1000

    steady = []
    for it in range(4):
        for j in range(20):  # realistic inter-snapshot change rate
            c = op.store.get(st.NODECLAIMS, f"n{(it * 20 + j):05d}")
            op.store.update(st.NODECLAIMS, c)
        t0 = _time.perf_counter()
        save_snapshot(op.store, op.cloud, path, now=clock(), blob_cache=cache)
        steady.append((_time.perf_counter() - t0) * 1000)
    steady_ms = sorted(steady)[len(steady) // 2]
    # the bound: steady-state must beat the cold full-serialize decisively
    # (measured ~70ms vs ~270-530ms on the dev rig). The RATIO is the
    # load-bearing assertion — wall-clock numbers swing under CI/CPU
    # contention (this box has one core), so the absolute ceiling is a
    # loose backstop only.
    assert steady_ms < cold_ms * 0.6, (cold_ms, steady)
    assert steady_ms < 450, f"steady-state snapshot stall {steady}ms"

    # cache correctness: the incremental file restores the full cluster
    op2 = new_kwok_operator(clock=clock)
    assert restore_snapshot(op2.store, op2.cloud, path, now=clock())
    assert len(op2.store.list(st.NODECLAIMS)) == 10_000
    assert len(op2.cloud.describe_instances()) == 10_000


def test_condition_since_rebases_across_restore(tmp_path):
    """Dict-valued clock stamps (Node.condition_since) must rebase too, or
    the repair controller sees conditions aged by the downtime delta and
    force-deletes healthy-until-recently nodes (r5 review finding)."""
    from karpenter_tpu.api.objects import Node, ObjectMeta
    from karpenter_tpu.controllers.snapshot import restore_snapshot

    snap = str(tmp_path / "snap.bin")
    clock_hi = FakeClock()
    clock_hi.t = 500_000.0
    op = new_kwok_operator(clock=clock_hi)
    n = Node(meta=ObjectMeta(name="sick"))
    n.set_condition("Unhealthy", "True", clock_hi())  # stamped NOW
    op.store.create(st.NODES, n)
    clock_hi.advance(10)  # condition is 10s old at snapshot time
    save_snapshot(op.store, op.cloud, snap, now=clock_hi())

    clock_lo = FakeClock()
    clock_lo.t = 100.0
    op2 = new_kwok_operator(clock=clock_lo)
    assert restore_snapshot(op2.store, op2.cloud, snap, now=clock_lo())
    n2 = op2.store.get(st.NODES, "sick")
    age = clock_lo() - n2.condition_since["Unhealthy"]
    assert 9 <= age <= 12, f"condition age skewed after restore: {age}"


def test_torn_snapshot_is_detected_and_boot_proceeds_empty(tmp_path):
    """A crash mid-write (or bit rot) must be DETECTED at restore via the
    checksum frame and skipped — the process boots empty and reconverges
    instead of raising an UnpicklingError out of boot."""
    op = boot(tmp_path)
    op.store.create(st.NODEPOOLS, mkpool())
    op.store.create(st.PODS, mkpod("p0", cpu="500m"))
    op.manager.settle()
    op.clock.advance(10)
    op.manager.tick()
    path = tmp_path / "snap.bin"
    raw = path.read_bytes()
    path.write_bytes(raw[: len(raw) // 2])  # torn mid-payload

    op2 = boot(tmp_path)  # must not raise
    assert op2.store.list(st.PODS) == []
    assert op2.store.list(st.NODEPOOLS) == []


def test_checksum_flip_is_detected_and_boot_proceeds_empty(tmp_path):
    from karpenter_tpu.controllers.snapshot import restore_snapshot

    op = boot(tmp_path)
    op.store.create(st.NODEPOOLS, mkpool())
    op.manager.settle()
    op.clock.advance(10)
    op.manager.tick()
    path = tmp_path / "snap.bin"
    raw = bytearray(path.read_bytes())
    raw[-1] ^= 0xFF  # one flipped payload byte
    path.write_bytes(bytes(raw))

    op2 = new_kwok_operator(clock=FakeClock())
    assert not restore_snapshot(op2.store, op2.cloud, str(path))
    assert op2.store.list(st.NODEPOOLS) == []


def test_legacy_unframed_snapshot_still_restores(tmp_path):
    """Pre-framing snapshot files are bare pickle (first byte \\x80) — they
    must keep restoring so an upgraded binary can boot from a file the old
    binary wrote."""
    from karpenter_tpu.controllers.snapshot import _SNAP_HDR, restore_snapshot

    op = boot(tmp_path)
    op.store.create(st.NODEPOOLS, mkpool())
    op.manager.settle()
    op.clock.advance(10)
    op.manager.tick()
    path = tmp_path / "snap.bin"
    raw = path.read_bytes()
    path.write_bytes(raw[_SNAP_HDR:])  # strip the frame: legacy layout

    op2 = new_kwok_operator(clock=FakeClock())
    assert restore_snapshot(op2.store, op2.cloud, str(path))
    assert {p.meta.name for p in op2.store.list(st.NODEPOOLS)} == {
        p.meta.name for p in op.store.list(st.NODEPOOLS)
    }
