"""Checkpointed FFD scan with incremental suffix resume: parity + ledger.

ISSUE 5 acceptance: a warm re-solve that resumes from a checkpoint ring
slot is DECISION-IDENTICAL to a cold full-scan solve of the same input —
by construction (the snapshot is the complete scan carry), proven here
property-style across randomized fleets and mutation points. The ledger
invariants ride along: an exact repeat stays a zero-upload exact hit, a
resumed solve uploads only the suffix run arrays, and a fallback replay
invalidates the checkpoint ring together with the arena residency it
lives in.

The ring snapshots every ckpt_every steps of the PADDED run axis, so the
test solver uses ckpt_every=2 with 16 slots: for fleets of ~24 runs
(padded to 32) every even scan position stays resident and any mutation
at run index >= 2 finds a covering slot.
"""

import dataclasses
import random

from karpenter_tpu import faults
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver
from karpenter_tpu.solver.resilient import ResilientSolver

from tests.test_e2e_kwok import FakeClock
from tests.test_solver_parity import ZONES, mkpod, pool
from tests.test_transfer_arena import _assert_same

N_SPECS = 24


def _warm_solver():
    return TPUSolver(ckpt_every=2, ckpt_slots=16)


def _fleet(rng=None, n_specs=N_SPECS, prefix="p"):
    """n_specs DISTINCT pod sizes -> ~n_specs FFD runs; replica counts
    randomized when an rng is given. Spec k=0 is the smallest size, i.e.
    the LAST run in the kernel's descending FFD order."""
    pods = []
    for k in range(n_specs):
        count = rng.randrange(3, 8) if rng else 4
        for j in range(count):
            pods.append(
                mkpod(f"{prefix}{k:02d}-{j}", cpu=f"{100 + 7 * k}m",
                      mem=f"{64 + 16 * k}Mi")
            )
    return SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)


def _add_replica(inp, k, uid):
    """A new pod with spec k's scheduling signature: changes one run's
    count without disturbing the signature universe (a NEW size would
    rebuild the encode core and legitimately cold-solve)."""
    donor_cpu = f"{100 + 7 * k}m"
    donor_mem = f"{64 + 16 * k}Mi"
    pods = list(inp.pods) + [mkpod(uid, cpu=donor_cpu, mem=donor_mem)]
    return dataclasses.replace(inp, pods=pods)


def _del_replica(inp, k, prefix="p"):
    name = f"{prefix}{k:02d}-0"
    pods = [p for p in inp.pods if p.meta.name != name]
    assert len(pods) == len(inp.pods) - 1
    return dataclasses.replace(inp, pods=pods)


def _mknode(name="n1", zone="zone-1a"):
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.provisioning.scheduler import ExistingNode
    from karpenter_tpu.utils.resources import Resources

    free = Resources.parse({"cpu": "8", "memory": "32Gi"})
    free["pods"] = 110
    return ExistingNode(
        id=name,
        labels={
            wk.ZONE_LABEL: zone,
            wk.HOSTNAME_LABEL: name,
            wk.CAPACITY_TYPE_LABEL: "on-demand",
            wk.ARCH_LABEL: "amd64",
            wk.OS_LABEL: "linux",
        },
        taints=[],
        free=free,
    )


# -- deterministic core: append-tail resume ---------------------------------


def test_append_tail_resumes_and_matches_cold():
    """Appending replicas of the smallest spec changes only the LAST run's
    count: the warm solver must resume (skipping a non-trivial prefix) and
    decide exactly as a resume-disabled cold solver."""
    inp = _fleet()
    tail = _add_replica(inp, 0, "tail-0")
    warm, cold = _warm_solver(), TPUSolver(resume=False)
    _assert_same(warm.solve(inp), cold.solve(inp), "baseline")
    _assert_same(warm.solve(tail), cold.solve(tail), "append-tail")
    assert warm.stats["resume_solves"] == 1, warm.stats
    assert warm.stats["resume_runs_skipped"] > 0, warm.stats
    assert warm.resume_hit_rate == 0.5


def test_resume_disabled_knob_never_resumes():
    inp = _fleet()
    s = TPUSolver(resume=False)
    s.solve(inp)
    s.solve(_add_replica(inp, 0, "tail-0"))
    assert s.stats["resume_solves"] == 0


# -- property suite: randomized fleets x mutation points --------------------


def test_random_mutations_resume_identical_to_cold():
    """Across randomized fleets and mutation classes, a warm solver with
    checkpoints and a cold resume-disabled solver must be bit-identical on
    every step — whether or not the mutation admitted a resume. Node-table
    changes rewrite non-run kernel args, so the context signature must
    force those solves cold."""
    rng = random.Random(0xC5)
    resumes = 0
    for trial in range(8):
        inp = _fleet(rng, prefix=f"t{trial}x")
        kind = ("append_tail", "mid_insert", "delete", "node_change")[trial % 4]
        if kind == "append_tail":
            mut = _add_replica(inp, 0, f"t{trial}-tail")
        elif kind == "mid_insert":
            k = rng.randrange(4, N_SPECS - 4)
            mut = _add_replica(inp, k, f"t{trial}-mid{k}")
        elif kind == "delete":
            mut = _del_replica(inp, rng.randrange(2, N_SPECS - 2),
                               prefix=f"t{trial}x")
        else:  # node_change: the node table feeds e_* kernel args
            mut = dataclasses.replace(inp, nodes=[_mknode(f"t{trial}-n")])
        warm, cold = _warm_solver(), TPUSolver(resume=False)
        _assert_same(warm.solve(inp), cold.solve(inp), f"{trial}:{kind}:base")
        _assert_same(warm.solve(mut), cold.solve(mut), f"{trial}:{kind}:mut")
        if kind == "node_change":
            assert warm.stats["resume_solves"] == 0, (
                f"{kind}: resumed across a node-table change"
            )
        resumes += warm.stats["resume_solves"]
    # the suite must actually exercise the resume path, or the parity
    # property proves nothing
    assert resumes >= 3, f"only {resumes} resumes across the property suite"


# -- ledger invariants -------------------------------------------------------


def test_exact_repeat_stays_zero_upload_exact_hit():
    """The identical-run-list carve-out: an exact repeat must remain the
    arena's zero-upload exact hit, NOT a degenerate full-skip resume that
    would pay suffix-run uploads for nothing."""
    s = _warm_solver()
    inp = _fleet()
    s.solve(inp)
    s.solve(inp)
    assert s.stats["resume_solves"] == 0
    assert s.ledger.solve["h2d_bytes"] == 0
    assert s.ledger.solve["h2d_msgs"] == 0
    assert s.ledger.outcomes["exact_hit"] == 1


def test_resumed_solve_uploads_only_suffix_runs():
    """A resumed dispatch re-uploads the stale run entries (one packed
    arena message) plus the two suffix run arrays — strictly less than the
    cold full upload; the unchanged 34 non-run args and the checkpoint
    itself never cross the link again."""
    s = _warm_solver()
    inp = _fleet()
    s.solve(inp)
    full_bytes = s.ledger.solve["h2d_bytes"]
    assert full_bytes > 0
    s.solve(_add_replica(inp, 0, "tail-0"))
    assert s.stats["resume_solves"] == 1
    assert 0 < s.ledger.solve["h2d_bytes"] < full_bytes
    # <= 3 messages: 1 packed delta upload + 2 suffix run arrays
    assert s.ledger.solve["h2d_msgs"] <= 3, dict(s.ledger.solve)
    # outcomes are cumulative: only the cold solve paid a full upload; the
    # resumed solve classified as a delta
    assert s.ledger.outcomes["full_upload"] == 1
    assert s.ledger.outcomes["delta_upload"] == 1


# -- fallback replay invalidates the ring ------------------------------------


def test_fallback_replay_invalidates_checkpoint_ring():
    """A device failure drops checkpoint records together with arena
    residency (they are one residency class): the post-recovery solve runs
    cold off fresh uploads — and only later re-solves resume again."""
    inner = TPUSolver(ckpt_every=2, ckpt_slots=16)
    rs = ResilientSolver(inner, fallbacks=[ReferenceSolver()],
                         clock=FakeClock())
    cold = TPUSolver(resume=False)
    inp = _fleet()
    _assert_same(rs.solve(inp), cold.solve(inp), "warm")
    assert inner.arena._ckpts, "first device solve recorded no checkpoint"

    plan = faults.FaultPlan(seed=0)
    plan.fail_n("solver.device_dispatch", 1)
    tail = _add_replica(inp, 0, "tail-0")
    with faults.active(plan):
        replayed = rs.solve(tail)
    assert plan.fired["solver.device_dispatch"] == 1
    assert not inner.arena._ckpts, "fallback replay left checkpoints resident"
    _assert_same(replayed, cold.solve(tail), "fallback-replay")

    # recovered: the next solve must NOT trust the dropped ring (cold), but
    # it re-records, so the one after resumes again
    _assert_same(rs.solve(tail), cold.solve(tail), "recovered-cold")
    assert inner.stats["resume_solves"] == 0
    tail2 = _add_replica(tail, 0, "tail-1")
    _assert_same(rs.solve(tail2), cold.solve(tail2), "recovered-resume")
    assert inner.stats["resume_solves"] == 1, inner.stats
