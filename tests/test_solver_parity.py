"""Differential parity: TPU tensor solver vs exact reference solver.

BASELINE.json north_star correctness bar: "node-claim decisions bit-identical
to the Go path on the kwok scheduling test suite" — here re-expressed as
bit-identical decisions between karpenter_tpu's two backends on randomized
and structured scenarios (configs 1-2: FFD + nodeSelector/taints masks).

Comparison is exact: placements map, claim count/order, per-claim nodepool,
surviving instance-type sets, zone/capacity-type domains, and error sets.
"""

import random

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import ObjectMeta, Pod, Taint, Toleration
from karpenter_tpu.catalog.catalog import CatalogSpec, generate
from karpenter_tpu.provisioning.scheduler import ExistingNode, NodePoolSpec, SolverInput
from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver
from karpenter_tpu.solver.encode import quantize_input
from karpenter_tpu.utils.resources import Resources

CATALOG = generate(CatalogSpec())
ZONES = ("zone-1a", "zone-1b", "zone-1c")


def pool(name="default", weight=0, reqs=None, taints=None, limits=None, types=None):
    r = Requirements.of(Requirement.create(wk.NODEPOOL_LABEL, IN, [name]))
    if reqs:
        r = r.union(reqs)
    return NodePoolSpec(
        name=name, weight=weight, requirements=r, taints=taints or [],
        instance_types=types if types is not None else CATALOG,
        limits=limits or Resources(),
    )


def assert_parity(inp: SolverInput):
    ref = ReferenceSolver().solve(quantize_input(inp))
    tpu = TPUSolver().solve(inp)
    assert set(ref.errors) == set(tpu.errors), (
        f"errors diverge: ref={sorted(ref.errors)} tpu={sorted(tpu.errors)}"
    )
    assert ref.placements == tpu.placements, _diff(ref.placements, tpu.placements)
    assert len(ref.claims) == len(tpu.claims)
    for i, (rc, tc) in enumerate(zip(ref.claims, tpu.claims)):
        assert rc.nodepool == tc.nodepool, f"claim {i} pool: {rc.nodepool} != {tc.nodepool}"
        assert sorted(rc.instance_type_names) == sorted(tc.instance_type_names), (
            f"claim {i} types diverge: ref={len(rc.instance_type_names)} tpu={len(tc.instance_type_names)}\n"
            f"ref-only={set(rc.instance_type_names) - set(tc.instance_type_names)}\n"
            f"tpu-only={set(tc.instance_type_names) - set(rc.instance_type_names)}"
        )
        assert rc.pod_uids == tc.pod_uids, f"claim {i} pods: {rc.pod_uids} != {tc.pod_uids}"
        for key in (wk.ZONE_LABEL, wk.CAPACITY_TYPE_LABEL):
            rv = rc.requirements.get(key)
            tv = tc.requirements.get(key)
            rset = set(rv.values_list()) if rv and not rv.complement else None
            tset = set(tv.values_list()) if tv and not tv.complement else None
            if rset is not None or tset is not None:
                # compare effective domains (None = universe)
                universe = set(ZONES) if key == wk.ZONE_LABEL else {"on-demand", "spot"}
                assert (rset or universe) == (tset or universe), (
                    f"claim {i} {key}: {rset} != {tset}"
                )
    return ref, tpu


def _diff(a, b):
    keys = set(a) | set(b)
    lines = [f"{k}: ref={a.get(k)} tpu={b.get(k)}" for k in sorted(keys) if a.get(k) != b.get(k)]
    return "placements diverge:\n" + "\n".join(lines[:20])


def mkpod(name, cpu="1", mem="1Gi", labels=None, **kw):
    return Pod(
        meta=ObjectMeta(name=name, uid=name, labels=labels or {}),
        requests=Resources.parse({"cpu": cpu, "memory": mem}),
        **kw,
    )


class TestConfig1FFD:
    """BASELINE config 1: cpu/mem-only pods, single NodePool, full catalog."""

    def test_single_pod(self):
        assert_parity(SolverInput(pods=[mkpod("p")], nodes=[], nodepools=[pool()], zones=ZONES))

    def test_identical_pods(self):
        pods = [mkpod(f"p{i:03d}", cpu="500m", mem="512Mi") for i in range(20)]
        assert_parity(SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES))

    def test_heterogeneous_sizes(self):
        random.seed(1)
        pods = [
            mkpod(f"p{i:03d}", cpu=f"{random.choice([100, 250, 500, 1000, 2000, 4000])}m",
                  mem=f"{random.choice([128, 256, 512, 1024, 4096])}Mi")
            for i in range(60)
        ]
        ref, tpu = assert_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )
        assert not ref.errors

    def test_unschedulable_pod(self):
        pods = [mkpod("big", cpu="999"), mkpod("ok", cpu="1")]
        assert_parity(SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES))

    def test_pods_capacity_axis(self):
        # tiny pods bounded by the pods resource, not cpu/mem
        small = [it for it in CATALOG if it.name == "m5.medium"]
        pods = [mkpod(f"t{i:03d}", cpu="1m", mem="1Mi") for i in range(65)]
        assert_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool(types=small)], zones=ZONES)
        )


class TestConfig2Masks:
    """BASELINE config 2: nodeSelector + taints/tolerations over mixed pools."""

    def test_arch_selector(self):
        pods = [mkpod(f"a{i}", node_selector={wk.ARCH_LABEL: "arm64"}) for i in range(5)]
        pods += [mkpod(f"b{i}", node_selector={wk.ARCH_LABEL: "amd64"}) for i in range(5)]
        assert_parity(SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES))

    def test_spot_ondemand_pools(self):
        spot_pool = pool(
            "spot", weight=10,
            reqs=Requirements.of(Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, ["spot"])),
        )
        od_pool = pool(
            "od", weight=1,
            reqs=Requirements.of(Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, ["on-demand"])),
        )
        pods = [mkpod(f"p{i:02d}") for i in range(10)]
        # an OD-only pod must skip the higher-weight spot pool
        pods.append(mkpod("odonly", node_selector={wk.CAPACITY_TYPE_LABEL: "on-demand"}))
        assert_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[spot_pool, od_pool], zones=ZONES)
        )

    def test_tainted_pool_with_tolerations(self):
        t = Taint(key="gpu", value="true", effect=wk.EFFECT_NO_SCHEDULE)
        gpu_pool = pool("gpu", weight=50, taints=[t])
        cpu_pool = pool("cpu", weight=1)
        tol = Toleration(key="gpu", value="true", effect=wk.EFFECT_NO_SCHEDULE)
        pods = [mkpod(f"g{i}", tolerations=[tol]) for i in range(3)]
        pods += [mkpod(f"c{i}") for i in range(3)]
        assert_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[gpu_pool, cpu_pool], zones=ZONES)
        )

    def test_zone_selectors(self):
        pods = [
            mkpod(f"p{i}", node_selector={wk.ZONE_LABEL: ZONES[i % 3]}) for i in range(9)
        ]
        assert_parity(SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES))

    def test_gpu_resource(self):
        pods = [
            Pod(
                meta=ObjectMeta(name=f"g{i}", uid=f"g{i}"),
                requests=Resources.parse({"cpu": "4", "memory": "8Gi", "nvidia.com/gpu": "1"}),
            )
            for i in range(3)
        ]
        assert_parity(SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES))

    def test_limits(self):
        capped = pool("capped", weight=10, limits=Resources.parse({"cpu": "8"}))
        backup = pool("backup", weight=1)
        pods = [mkpod(f"p{i:02d}", cpu="2", mem="2Gi") for i in range(12)]
        assert_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[capped, backup], zones=ZONES)
        )

    def test_limit_charge_uses_first_pod_survivors(self):
        # SPEC: a claim charges the min capacity over its surviving options AT
        # CREATION (= after its first pod). A small type that survives one pod
        # but not a full node must lower the charge — heterogeneous capacities
        # expose any backend that charges the full-node surviving set instead.
        from karpenter_tpu.cloudprovider.types import InstanceType, Offering

        def mktype(name, cpu, mem_gib, pods_cap, price):
            reqs = Requirements.of(
                Requirement.create(wk.INSTANCE_TYPE_LABEL, IN, [name]),
                Requirement.create(wk.ARCH_LABEL, IN, ["amd64"]),
                Requirement.create(wk.OS_LABEL, IN, ["linux"]),
                Requirement.create(wk.ZONE_LABEL, IN, list(ZONES)),
                Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, ["on-demand"]),
            )
            cap = Resources.parse({"cpu": str(cpu), "memory": f"{mem_gib}Gi"})
            cap["pods"] = pods_cap
            return InstanceType(
                name=name, requirements=reqs, capacity=cap, overhead=Resources(),
                offerings=[Offering(zone=z, capacity_type="on-demand", price=price) for z in ZONES],
            )

        big = mktype("big.4xlarge", 16, 64, 100, 2.0)
        small = mktype("small.large", 2, 8, 10, 0.3)
        capped = pool("capped", limits=Resources.parse({"cpu": "10"}), types=[big, small])
        pods = [mkpod(f"p{i:02d}", cpu="1", mem="1Gi") for i in range(20)]
        ref, _ = assert_parity(SolverInput(pods=pods, nodes=[], nodepools=[capped], zones=ZONES))
        # oracle semantics: every claim charges small's 2 cpu -> both claims fit
        assert not ref.errors and len(ref.claims) == 2


class TestExistingNodesParity:
    def mknode(self, name, zone="zone-1a", cpu="8", mem="32Gi", pods=110):
        lab = {
            wk.ZONE_LABEL: zone,
            wk.HOSTNAME_LABEL: name,
            wk.CAPACITY_TYPE_LABEL: "on-demand",
            wk.ARCH_LABEL: "amd64",
            wk.OS_LABEL: "linux",
        }
        free = Resources.parse({"cpu": cpu, "memory": mem})
        free["pods"] = pods
        return ExistingNode(id=name, labels=lab, taints=[], free=free)

    def test_fill_existing_then_spill(self):
        nodes = [self.mknode("n1"), self.mknode("n2", zone="zone-1b")]
        pods = [mkpod(f"p{i:02d}", cpu="3", mem="4Gi") for i in range(8)]
        assert_parity(SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES))

    def test_node_selector_vs_existing(self):
        nodes = [self.mknode("n1", zone="zone-1a")]
        pods = [mkpod(f"p{i}", node_selector={wk.ZONE_LABEL: "zone-1b"}) for i in range(3)]
        assert_parity(SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES))


class TestManyDistinctSpecs:
    def test_thousand_plus_runs(self):
        """S >= 1000 distinct pod specs: the kernel's only sequential axis is
        runs, and the headline bench collapses 50k pods to ~27 runs — this
        pins the scan axis at realistic heterogeneity (VERDICT r3 'what's
        weak' #3)."""
        pods = [
            mkpod(f"p{i:04d}", cpu=f"{37 + i}m", mem=f"{64 + (i % 40)}Mi")
            for i in range(1100)
        ]
        solver = TPUSolver()
        ref = ReferenceSolver().solve(quantize_input(
            SolverInput(pods=list(pods), nodes=[], nodepools=[pool()], zones=ZONES)
        ))
        tpu = solver.solve(
            SolverInput(pods=list(pods), nodes=[], nodepools=[pool()], zones=ZONES)
        )
        assert solver.stats["device_solves"] == 1, solver.stats
        assert ref.placements == tpu.placements
        assert set(ref.errors) == set(tpu.errors)
        assert len(ref.claims) == len(tpu.claims)


class TestRandomizedParity:
    @pytest.mark.parametrize("seed", range(6))
    def test_fuzz(self, seed):
        rng = random.Random(seed)
        pods = []
        for i in range(rng.randint(10, 80)):
            kw = {}
            r = rng.random()
            if r < 0.2:
                kw["node_selector"] = {wk.ARCH_LABEL: rng.choice(["amd64", "arm64"])}
            elif r < 0.3:
                kw["node_selector"] = {wk.ZONE_LABEL: rng.choice(ZONES)}
            elif r < 0.35:
                kw["node_selector"] = {wk.CAPACITY_TYPE_LABEL: rng.choice(["spot", "on-demand"])}
            pods.append(
                mkpod(
                    f"p{i:03d}",
                    cpu=f"{rng.choice([50, 100, 500, 1000, 2000, 7000])}m",
                    mem=f"{rng.choice([64, 300, 1024, 3000, 9000])}Mi",
                    **kw,
                )
            )
        pools = [pool("a", weight=5), pool("b", weight=1)]
        if seed % 2:
            pools[0] = pool(
                "a", weight=5,
                reqs=Requirements.of(Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, ["spot"])),
            )
        if seed % 3 == 0:
            # minValues axis: a flexibility floor on instance family; some
            # pods pin a single family to force floor violations + fallback
            from karpenter_tpu.scheduling.requirements import EXISTS

            pools[1] = pool(
                "b", weight=1,
                reqs=Requirements.of(
                    Requirement.create(
                        "karpenter.tpu/instance-family", EXISTS, (),
                        min_values=rng.randint(2, 4),
                    )
                ),
            )
            for p in pods:
                if rng.random() < 0.1:
                    p.node_selector = {
                        "karpenter.tpu/instance-family": rng.choice(["m5", "c5"])
                    }
        assert_parity(SolverInput(pods=pods, nodes=[], nodepools=pools, zones=ZONES))


class TestNativeParity:
    """Third leg: the compiled C++ core must match the python oracle too."""

    def _assert_native(self, inp):
        from karpenter_tpu.solver.native import NativeSolver

        ref = ReferenceSolver().solve(quantize_input(inp))
        nat_solver = NativeSolver()
        nat = nat_solver.solve(inp)
        assert nat_solver.stats["native_solves"] == 1
        assert set(ref.errors) == set(nat.errors)
        assert ref.placements == nat.placements
        assert len(ref.claims) == len(nat.claims)
        for rc, tc in zip(ref.claims, nat.claims):
            assert rc.nodepool == tc.nodepool
            assert sorted(rc.instance_type_names) == sorted(tc.instance_type_names)
            assert rc.pod_uids == tc.pod_uids

    def test_basic(self):
        pods = [mkpod(f"p{i:03d}", cpu="500m", mem="512Mi") for i in range(20)]
        self._assert_native(SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES))

    def test_heterogeneous_with_selectors(self):
        random.seed(7)
        pods = []
        for i in range(50):
            kw = {}
            if i % 5 == 0:
                kw["node_selector"] = {wk.ARCH_LABEL: random.choice(["amd64", "arm64"])}
            pods.append(
                mkpod(f"p{i:03d}", cpu=f"{random.choice([100, 500, 2000])}m",
                      mem=f"{random.choice([128, 1024, 4096])}Mi", **kw)
            )
        pools = [pool("a", weight=5), pool("b", weight=1)]
        self._assert_native(SolverInput(pods=pods, nodes=[], nodepools=pools, zones=ZONES))

    def test_limits_and_existing_nodes(self):
        from karpenter_tpu.utils.resources import Resources as Rs

        nodes = [TestExistingNodesParity().mknode("n1"), TestExistingNodesParity().mknode("n2", zone="zone-1b")]
        capped = pool("capped", weight=10, limits=Rs.parse({"cpu": "8"}))
        backup = pool("backup", weight=1)
        pods = [mkpod(f"p{i:02d}", cpu="2", mem="2Gi") for i in range(12)]
        self._assert_native(SolverInput(pods=pods, nodes=nodes, nodepools=[capped, backup], zones=ZONES))

    def test_native_speed_at_scale(self):
        import sys as _sys
        import time as _time

        _sys.path.insert(0, ".")
        from bench import build_input
        from karpenter_tpu.solver.native import solve_encoded
        from karpenter_tpu.solver.encode import encode as _encode, quantize_input as _q

        inp = build_input(10_000)
        enc = _encode(_q(inp))
        t0 = _time.perf_counter()
        out = solve_encoded(enc, 4096)
        dt = _time.perf_counter() - t0
        assert out is not None
        leftover = out[2]
        assert leftover.sum() == 0
        print(f"\nnative 10k-pod solve: {dt*1000:.1f}ms", file=_sys.stderr)
        assert dt < 5.0  # compiled-class performance


class TestHostnameConstraintsParity:
    """Hostname TSC + anti-affinity now run ON DEVICE (closed-form caps)."""

    def test_hostname_spread_on_device(self):
        from karpenter_tpu.api.objects import TopologySpreadConstraint

        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "web"}
        )
        pods = [
            mkpod(f"p{i:02d}", cpu="200m", mem="256Mi", labels={"app": "web"},
                  topology_spread=[tsc])
            for i in range(6)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        solver = TPUSolver()
        ref, tpu = assert_parity(inp)
        # and confirm it actually took the device path
        solver.solve(inp)
        assert solver.stats["device_solves"] == 1
        assert len(tpu.claims) == 6  # one pod per hostname at skew 1

    def test_hostname_spread_skew2(self):
        from karpenter_tpu.api.objects import TopologySpreadConstraint

        tsc = TopologySpreadConstraint(
            max_skew=2, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "web"}
        )
        pods = [
            mkpod(f"p{i:02d}", cpu="100m", mem="128Mi", labels={"app": "web"},
                  topology_spread=[tsc])
            for i in range(7)
        ]
        ref, tpu = assert_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )
        assert len(tpu.claims) == 4  # ceil(7/2)

    def test_hostname_anti_affinity_on_device(self):
        from karpenter_tpu.api.objects import PodAffinityTerm

        term = PodAffinityTerm(
            label_selector={"app": "db"}, topology_key=wk.HOSTNAME_LABEL, anti=True
        )
        pods = [
            mkpod(f"db{i}", cpu="250m", mem="512Mi", labels={"app": "db"},
                  affinity_terms=[term])
            for i in range(4)
        ]
        # plus unconstrained filler pods that share nodes freely
        pods += [mkpod(f"f{i}", cpu="100m", mem="128Mi") for i in range(4)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        solver = TPUSolver()
        ref, tpu = assert_parity(inp)
        solver.solve(inp)
        assert solver.stats["device_solves"] == 1

    def test_mixed_with_existing_nodes(self):
        from karpenter_tpu.api.objects import TopologySpreadConstraint

        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "x"}
        )
        helper = TestExistingNodesParity()
        n1 = helper.mknode("n1")
        n1.pod_labels.append({"app": "x"})  # existing matching pod counts
        pods = [
            mkpod(f"p{i}", cpu="200m", mem="256Mi", labels={"app": "x"},
                  topology_spread=[tsc])
            for i in range(3)
        ]
        assert_parity(SolverInput(pods=pods, nodes=[n1], nodepools=[pool()], zones=ZONES))

    def test_nodes_without_hostname_label(self):
        """A node missing kubernetes.io/hostname still forms a hostname
        domain (defaults to its id) — SPEC.md; both backends must agree.
        Regression: the oracle used to reject such nodes for TSC pods while
        the device kernel admitted them."""
        from karpenter_tpu.api.objects import TopologySpreadConstraint

        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "w"}
        )
        free = Resources.parse({"cpu": "4", "memory": "16Gi"})
        free["pods"] = 20
        nodes = [
            ExistingNode(
                id=f"n{j}",
                labels={wk.ZONE_LABEL: "zone-1a", wk.CAPACITY_TYPE_LABEL: "on-demand"},
                taints=[],
                free=Resources(free),
            )
            for j in range(2)
        ]
        pods = [
            mkpod(f"p{i}", cpu="500m", mem="512Mi", labels={"app": "w"},
                  topology_spread=[tsc])
            for i in range(4)
        ] + [mkpod(f"f{i}", cpu="250m", mem="256Mi") for i in range(3)]
        ref, tpu = assert_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )
        # skew-1 spread: at most one matching pod lands per (unlabeled) node
        per_node = {}
        for uid, tgt in tpu.placements.items():
            if uid.startswith("p") and tgt[0] == "node":
                per_node[tgt[1]] = per_node.get(tgt[1], 0) + 1
        assert all(v <= 1 for v in per_node.values()), per_node
