"""Durable solver resident state (solver/vault.py + solver/handover.py).

ISSUE 17 acceptance surface:
- donor round trip: a vault written by one "process" re-seeds a fresh
  process's encode cache, and the adopted core is bit-identical to a cold
  build (a stale vault may cost time, never change a decision);
- corruption fallback: truncated / checksum-flipped / wrong-epoch /
  seq-ahead candidates are SKIPPED — restore degrades to the cold path
  with a `vault_restore_failed` flight dump, never a crash;
- chaos: a `vault.write` fault skips the snapshot with a throttled WARN
  and the next attempt retries; serving never stops;
- blue/green: TenantMux.swap_downstream drains before closing (zero
  drops) and BlueGreenHandover aborts on shadow-parity divergence with
  the blue side untouched.
"""

import os
import threading
import time

import pytest

from karpenter_tpu import faults
from karpenter_tpu.obs import trace as obstrace
from karpenter_tpu.obs.recorder import FlightRecorder
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.solver import encode as em
from karpenter_tpu.solver import encode_cache as ec
from karpenter_tpu.solver.backend import ReferenceSolver
from karpenter_tpu.solver.handover import (
    BlueGreenHandover,
    HandoverAborted,
    solve_fingerprint,
)
from karpenter_tpu.solver.pipeline import DISRUPTION, SolveService
from karpenter_tpu.solver.tenancy import TenantMux, TenantRegistry, TenantSpec
from karpenter_tpu.solver.vault import (
    VAULT_MAGIC,
    SolverStateVault,
    VaultController,
    export_encode_donors,
)

from tests.test_encode_cache import _inp, _nodes, _pods, assert_encoded_equal
from tests.test_zone_device import ZONES, pool


def _simulate_restart():
    """Everything process-local dies with the process: core caches, the
    catalog-fingerprint memo, tenant namespaces, installed donors, stats.
    Only the vault files on disk survive."""
    em._CORE_CACHE.clear()
    em._CAT_FP_CACHE.clear()
    ec._TENANT_CORE_CACHES.clear()
    ec.clear_vault_donors()
    ec.reset_stats()


@pytest.fixture(autouse=True)
def _clean_encode_state():
    _simulate_restart()
    yield
    _simulate_restart()
    faults.use(None)


# -- donor round trip ---------------------------------------------------------


class TestDonorRoundTrip:
    def test_restored_encode_adopts_and_matches_cold_build(self, tmp_path):
        """The tentpole property: encode warm, snapshot, 'restart', restore,
        re-encode with all-new pod objects (same uids — object ids and
        interned signature numbers are process-local and must not matter).
        The first encode must ADOPT the vault donor instead of rebuilding,
        and the result must equal a cold build field by field."""
        counts = (4, 3, 2, 2)
        enc_cold = em.encode(_inp(_pods("rt", counts)))
        assert ec.STATS["rebuilds"] == 1

        vault = SolverStateVault(str(tmp_path), interval_s=1.0)
        assert vault.snapshot_now() is not None

        _simulate_restart()
        restorer = SolverStateVault(str(tmp_path), interval_s=1.0)
        report = restorer.restore(install=True)
        assert report is not None and report.donors_installed == 1
        assert report.skipped == []

        enc2 = em.encode(_inp(_pods("rt", counts)))
        assert ec.STATS == {"hits": 0, "patches": 0, "rebuilds": 0,
                            "vault_adopts": 1}, ec.STATS
        assert_encoded_equal(enc2, enc_cold)

    def test_adopted_core_is_a_patch_donor_for_deltas(self, tmp_path):
        """After adoption the entry is a first-class cache citizen: pod-set
        deltas inside the signature universe patch off it."""
        em.encode(_inp(_pods("pd", (4, 3, 2, 2))))
        vault = SolverStateVault(str(tmp_path))
        vault.snapshot_now()
        _simulate_restart()
        SolverStateVault(str(tmp_path)).restore(install=True)
        em.encode(_inp(_pods("pd", (4, 3, 2, 2))))
        assert ec.STATS["vault_adopts"] == 1
        delta = em.encode(_inp(_pods("pd2", (2, 5, 1, 3))))
        assert ec.STATS["patches"] == 1, ec.STATS
        _simulate_restart()
        assert_encoded_equal(delta, em.encode(_inp(_pods("pd2", (2, 5, 1, 3)))))

    def test_content_mismatch_never_adopts(self, tmp_path):
        """A donor whose catalog content diverges from the live input must
        MISS (rebuild), not serve stale tables — the self-verification that
        makes a stale vault a slowdown, never a wrong decision."""
        em.encode(_inp(_pods("cm", (3, 2, 2, 1))))
        vault = SolverStateVault(str(tmp_path))
        vault.snapshot_now()
        _simulate_restart()
        SolverStateVault(str(tmp_path)).restore(install=True)
        # same pods, different catalog (weight changes the content fp)
        em.encode(_inp(_pods("cm", (3, 2, 2, 1)), nodepools=[pool(weight=5)]))
        assert ec.STATS["vault_adopts"] == 0
        assert ec.STATS["rebuilds"] == 1, ec.STATS

    def test_export_strips_pod_scale_state(self):
        em.encode(_inp(_pods("ex", (5, 4, 3, 2))))
        donors = export_encode_donors()
        assert len(donors) == 1
        core = donors[0]["core"]
        assert core.group_pods == []
        assert len(core.run_group) == 0 and len(core.run_count) == 0
        assert len(core.sorted_uids) == 0
        assert donors[0]["cat_fp"] is not None
        assert len(donors[0]["sig_seq"]) == len(core.group_snums)


# -- vault files: atomicity, pruning, cadence ---------------------------------


class TestVaultFiles:
    def test_snapshot_writes_atomically_and_prunes(self, tmp_path):
        em.encode(_inp(_pods("at", (2, 2, 1, 1))))
        vault = SolverStateVault(str(tmp_path), keep=2)
        paths = [vault.snapshot_now() for _ in range(4)]
        assert all(p is not None for p in paths)
        names = sorted(os.listdir(tmp_path))
        # no temp files left behind, pruned to keep=2, newest survive
        assert all(n.startswith("vault-") and n.endswith(".vlt")
                   for n in names), names
        assert len(names) == 2
        assert vault.candidates()[0] == paths[-1]
        with open(paths[-1], "rb") as f:
            assert f.read(len(VAULT_MAGIC)) == VAULT_MAGIC

    def test_maybe_snapshot_interval_gates(self, tmp_path):
        clk = [0.0]
        vault = SolverStateVault(str(tmp_path), interval_s=5.0,
                                 clock=lambda: clk[0])
        assert vault.maybe_snapshot() is True
        deadline = time.monotonic() + 5.0
        while vault._inflight and time.monotonic() < deadline:
            time.sleep(0.005)
        assert vault.stats["snapshots"] == 1
        assert vault.maybe_snapshot() is False  # inside the interval
        clk[0] = 5.1
        assert vault.maybe_snapshot() is True

    def test_controller_adapter_pokes_the_vault(self, tmp_path):
        vault = SolverStateVault(str(tmp_path), interval_s=0.001)
        ctrl = VaultController(vault)
        assert ctrl.reconcile() is False
        deadline = time.monotonic() + 5.0
        while not vault.stats["snapshots"] and time.monotonic() < deadline:
            time.sleep(0.005)
        assert vault.stats["snapshots"] == 1


# -- corruption fallback ------------------------------------------------------


class TestCorruptionFallback:
    def _vaulted(self, tmp_path, tag="cf"):
        em.encode(_inp(_pods(tag, (3, 2, 2, 1))))
        vault = SolverStateVault(str(tmp_path))
        path = vault.snapshot_now()
        assert path is not None
        return path

    def _assert_cold_fallback(self, tmp_path, tag, **vault_kw):
        """Restore must return None (counted + dumped), and the process
        must serve from the cold path with the exact cold-boot decision."""
        rec_dir = tmp_path / "flight"
        rec_dir.mkdir()
        obstrace.configure(enabled=True,
                           recorder=FlightRecorder(dir=str(rec_dir)))
        try:
            _simulate_restart()
            restorer = SolverStateVault(str(tmp_path), **vault_kw)
            assert restorer.restore(install=True) is None
            assert restorer.stats["restore_failures"] == 1
            dumps = list(rec_dir.glob("*")) if rec_dir.exists() else []
            assert any("vault_restore_failed" in p.name for p in dumps), dumps
            # cold path still serves, decision-identical to a cold boot
            got = em.encode(_inp(_pods(tag, (3, 2, 2, 1))))
            assert ec.STATS["vault_adopts"] == 0
            assert ec.STATS["rebuilds"] == 1
            _simulate_restart()
            assert_encoded_equal(got, em.encode(_inp(_pods(tag, (3, 2, 2, 1)))))
        finally:
            obstrace.configure(enabled=False, recorder=None)

    def test_truncated_vault_degrades_to_cold(self, tmp_path):
        path = self._vaulted(tmp_path, "tr")
        with open(path, "rb") as f:
            raw = f.read()
        with open(path, "wb") as f:
            f.write(raw[: len(raw) // 2])
        self._assert_cold_fallback(tmp_path, "tr")

    def test_checksum_flip_degrades_to_cold(self, tmp_path):
        path = self._vaulted(tmp_path, "ck")
        with open(path, "rb") as f:
            raw = bytearray(f.read())
        raw[-1] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(raw))
        self._assert_cold_fallback(tmp_path, "ck")

    def test_wrong_journal_epoch_degrades_to_cold(self, tmp_path):
        self._vaulted(tmp_path, "ep")
        self._assert_cold_fallback(tmp_path, "ep", epoch="other-lineage")

    def test_seq_ahead_of_journal_degrades_to_cold(self, tmp_path):
        class _Journal:
            def __init__(self, rev):
                self._rev = rev

            def rev(self):
                return self._rev

        em.encode(_inp(_pods("sq", (3, 2, 2, 1))))
        writer = SolverStateVault(str(tmp_path), journal=_Journal(40))
        assert writer.snapshot_now() is not None
        _simulate_restart()
        # the live journal restarted behind the vault's cursor: lineage reset
        behind = SolverStateVault(str(tmp_path), journal=_Journal(7))
        assert behind.restore(install=True) is None
        assert behind.stats["restore_failures"] == 1
        # a journal AT the vault seq restores fine
        level = SolverStateVault(str(tmp_path), journal=_Journal(40))
        assert level.restore(install=True) is not None

    def test_store_rv_behind_vault_degrades_to_cold(self, tmp_path):
        class _Store:
            def __init__(self, rv):
                self._rv = rv

            def current_rv(self):
                return self._rv

        em.encode(_inp(_pods("rv", (3, 2, 2, 1))))
        assert SolverStateVault(
            str(tmp_path), store=_Store(90)
        ).snapshot_now() is not None
        _simulate_restart()
        older = SolverStateVault(str(tmp_path), store=_Store(12))
        assert older.restore(install=True) is None
        newer = SolverStateVault(str(tmp_path), store=_Store(90))
        assert newer.restore(install=True) is not None

    def test_newest_corrupt_falls_back_to_older_good_candidate(self, tmp_path):
        em.encode(_inp(_pods("fb", (3, 2, 2, 1))))
        vault = SolverStateVault(str(tmp_path), keep=3)
        vault.snapshot_now()
        newest = vault.snapshot_now()
        with open(newest, "wb") as f:
            f.write(b"garbage")
        _simulate_restart()
        report = SolverStateVault(str(tmp_path)).restore(install=True)
        assert report is not None and report.donors_installed == 1
        assert [os.path.basename(newest)] == [n for n, _ in report.skipped]

    def test_empty_vault_dir_is_a_silent_fresh_boot(self, tmp_path):
        vault = SolverStateVault(str(tmp_path))
        assert vault.restore(install=True) is None
        assert vault.stats["restore_failures"] == 0


# -- chaos: fault sites -------------------------------------------------------


class TestVaultFaults:
    def test_write_fault_skips_snapshot_and_next_attempt_retries(self, tmp_path):
        em.encode(_inp(_pods("wf", (2, 2, 1, 1))))
        vault = SolverStateVault(str(tmp_path))
        plan = faults.FaultPlan(seed=3)
        plan.fail_n("vault.write", 2, OSError("disk full (injected)"))
        with faults.active(plan):
            assert vault.snapshot_now() is None
            assert vault.snapshot_now() is None
            # serving continues while writes fail
            em.encode(_inp(_pods("wf2", (1, 2, 1, 1))))
            # the plan expires: the retry lands
            assert vault.snapshot_now() is not None
        assert plan.fired["vault.write"] == 2
        assert vault.stats["write_failures"] == 2
        assert vault.stats["snapshots"] == 1
        assert len(vault.candidates()) == 1

    def test_write_warn_is_throttled(self, tmp_path, caplog):
        clk = [0.0]
        vault = SolverStateVault(str(tmp_path), clock=lambda: clk[0],
                                 warn_every_s=30.0)
        plan = faults.FaultPlan()
        plan.fail_n("vault.write", 3, OSError("injected"))
        with faults.active(plan), caplog.at_level("WARNING", "karpenter_tpu"):
            vault.snapshot_now()
            clk[0] = 5.0
            vault.snapshot_now()  # inside the throttle window: silent
            clk[0] = 40.0
            vault.snapshot_now()  # window elapsed: warns again
        warns = [r for r in caplog.records if "snapshot failed" in r.message]
        assert len(warns) == 2, [r.message for r in warns]
        assert vault.stats["write_failures"] == 3

    def test_corrupt_fault_rejects_candidates(self, tmp_path):
        em.encode(_inp(_pods("cf2", (2, 2, 1, 1))))
        vault = SolverStateVault(str(tmp_path))
        vault.snapshot_now()
        _simulate_restart()
        plan = faults.FaultPlan()
        plan.script("vault.corrupt",
                    faults.FaultError("injected torn read"))
        restorer = SolverStateVault(str(tmp_path))
        with faults.active(plan):
            assert restorer.restore(install=True) is None
        assert restorer.stats["restore_failures"] == 1
        # the fault cleared: the same file restores
        assert restorer.restore(install=True) is not None


# -- blue/green handover ------------------------------------------------------


class _SlowSolver(ReferenceSolver):
    def __init__(self, delay_s=0.02):
        super().__init__()
        self.delay_s = delay_s
        self.solves = 0

    def solve(self, inp):
        self.solves += 1
        time.sleep(self.delay_s)
        return super().solve(inp)


class _DivergentSolver(ReferenceSolver):
    """Drops one placement: a green build whose DECISIONS differ."""

    def solve(self, inp):
        res = super().solve(inp)
        if res.placements:
            res.placements = dict(res.placements)
            res.placements.pop(next(iter(res.placements)))
        return res


def _solver_input(tag, counts=(3, 2, 2, 1)):
    return SolverInput(pods=_pods(tag, counts), nodes=_nodes(),
                       nodepools=[pool()], zones=ZONES)


def _mux(solver):
    registry = TenantRegistry([
        TenantSpec("t0", weight=1.0, max_queue_depth=128)
    ])
    return TenantMux(SolveService(solver), registry, own_service=True)


class TestHandover:
    def test_swap_downstream_drains_before_closing_zero_drops(self):
        blue_solver = _SlowSolver()
        mux = _mux(blue_solver)
        green = SolveService(ReferenceSolver())
        inp = _solver_input("sw")
        try:
            tickets = [mux.submit(inp, tenant_id="t0", kind=DISRUPTION)
                       for _ in range(8)]
            rep = mux.swap_downstream(green, own=True, drain_s=60.0)
            assert rep["timeouts"] == 0
            assert rep["old_service_closed"] is True
            tickets += [mux.submit(inp, tenant_id="t0", kind=DISRUPTION)
                        for _ in range(3)]
            for t in tickets:
                t.result(timeout=60)  # every ticket resolves: zero drops
            assert mux._service is green
        finally:
            mux.close()

    def test_full_handover_protocol_zero_drops(self, tmp_path):
        em.encode(_inp(_pods("ho", (2, 2, 1, 1))))
        SolverStateVault(str(tmp_path)).snapshot_now()
        _simulate_restart()
        mux = _mux(_SlowSolver())
        green = SolveService(ReferenceSolver())
        inp = _solver_input("ho2")
        try:
            tickets = [mux.submit(inp, tenant_id="t0", kind=DISRUPTION)
                       for _ in range(6)]
            ho = BlueGreenHandover(
                mux, green, vault=SolverStateVault(str(tmp_path))
            )
            rep = ho.run(shadow_inputs=[inp], drain_s=60.0)
            assert rep["dropped"] == 0
            assert rep["mismatches"] == 0
            assert rep["restored"] is not None
            assert rep["restored"]["donors_installed"] == 1
            tickets.append(mux.submit(inp, tenant_id="t0", kind=DISRUPTION))
            for t in tickets:
                t.result(timeout=60)
            assert mux._service is green
        finally:
            mux.close()

    def test_parity_mismatch_aborts_with_blue_untouched(self):
        mux = _mux(ReferenceSolver())
        blue = mux._service
        green = SolveService(_DivergentSolver())
        inp = _solver_input("pa")
        try:
            with pytest.raises(HandoverAborted):
                BlueGreenHandover(mux, green).run(shadow_inputs=[inp])
            # blue keeps serving: the mux never saw the swap
            assert mux._service is blue
            mux.submit(inp, tenant_id="t0", kind=DISRUPTION).result(timeout=60)
        finally:
            mux.close()
            green.close()

    def test_solve_fingerprint_separates_decisions(self):
        inp = _solver_input("fp")
        same = solve_fingerprint(ReferenceSolver(), inp)
        assert same == solve_fingerprint(ReferenceSolver(), inp)
        assert same != solve_fingerprint(_DivergentSolver(), inp)


# -- config validation --------------------------------------------------------


class TestConfig:
    def test_bad_knobs_fail_closed(self, tmp_path):
        with pytest.raises(ValueError):
            SolverStateVault(str(tmp_path), interval_s=0)
        with pytest.raises(ValueError):
            SolverStateVault(str(tmp_path), keep=0)

    def test_health_surface(self, tmp_path):
        clk = [100.0]
        vault = SolverStateVault(str(tmp_path), clock=lambda: clk[0])
        em.encode(_inp(_pods("hs", (2, 1, 1, 1))))
        vault.snapshot_now()
        clk[0] = 107.5
        h = vault.health()
        assert h["age_s"] == pytest.approx(7.5)
        assert h["snapshots"] == 1 and h["write_failures"] == 0
        assert h["last_bytes"] > 0
