"""Threaded soak: the REAL run loop (Manager.run) under concurrent store
mutations from foreign threads — the production execution mode every other
test skips (they drive the deterministic tick() directly). Exercises the
store's lock discipline, the watch dispatch, and controller re-entrancy
against wall-clock timing instead of a fake clock.
"""

import threading
import time

from karpenter_tpu.api.objects import ObjectMeta, Pod
from karpenter_tpu.controllers import store as st
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.utils.resources import Resources

from tests.test_e2e_kwok import mkpool


def test_threaded_run_loop_with_concurrent_mutators():
    op = new_kwok_operator()  # real monotonic clock
    op.store.create(st.NODEPOOLS, mkpool())
    errors = []

    # capture controller exceptions at the CONTROLLER level: Manager.tick
    # catches and logs reconcile crashes internally, so a tick-level wrapper
    # would never see them — wrap each reconcile instead
    def guard(ctrl):
        orig = ctrl.reconcile

        def wrapped():
            try:
                return orig()
            except Exception as e:  # pragma: no cover
                errors.append(f"{ctrl.name}: {e!r}")
                raise

        ctrl.reconcile = wrapped

    for ctrl in op.manager.controllers:
        guard(ctrl)
    loop_thread = op.manager.run(interval_s=0.005)

    def mutator(tid):
        try:
            for i in range(40):
                name = f"t{tid}-p{i}"
                op.store.create(
                    st.PODS,
                    Pod(
                        meta=ObjectMeta(name=name, uid=name),
                        requests=Resources.parse(
                            {"cpu": "250m", "memory": "256Mi"}
                        ),
                    ),
                )
                if i % 5 == 4:
                    time.sleep(0.002)
        except Exception as e:  # pragma: no cover
            errors.append(repr(e))

    threads = [threading.Thread(target=mutator, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "mutator deadlocked (store lock discipline)"

    # the loop converges against the real clock
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        pods = op.store.list(st.PODS)
        if pods and all(p.node_name for p in pods):
            break
        time.sleep(0.05)
    op.manager.stop()
    loop_thread.join(timeout=10)
    assert not loop_thread.is_alive(), "run loop failed to stop"
    assert not errors, errors
    pods = op.store.list(st.PODS)
    assert len(pods) == 160
    unbound = [p.meta.name for p in pods if not p.node_name]
    assert not unbound, f"threaded loop did not converge: {unbound[:10]}"
