"""Test harness: force a virtual 8-device CPU mesh before any backend init.

Multi-chip hardware is not available in CI; shardings are validated on a
virtual CPU mesh (SURVEY.md §7 / driver contract). NOTE: this environment's
axon site hook force-sets jax_platforms="axon,cpu" (real-TPU tunnel first) in
jax.config at interpreter start — env vars alone do NOT override it, so we
update jax.config directly here, before any backend initializes. bench.py
intentionally does NOT do this: it runs on the real chip.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Persistent compile cache: the solver kernels bucket their shapes, so
# compilations amortize across tests and sessions.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_compile_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
