"""Test harness: force a virtual 8-device CPU mesh before any backend init.

Multi-chip hardware is not available in CI; shardings are validated on a
virtual CPU mesh (SURVEY.md §7 / driver contract). NOTE: this environment's
axon site hook force-sets jax_platforms="axon,cpu" (real-TPU tunnel first) in
jax.config at interpreter start — env vars alone do NOT override it, so we
update jax.config directly here, before any backend initializes. bench.py
intentionally does NOT do this: it runs on the real chip.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Persistent compile cache: the solver kernels bucket their shapes, so
# compilations amortize across tests and sessions.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_compile_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import threading  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_nondaemon_threads():
    """Assert no NON-DAEMON thread outlives a test module.

    Dispatcher/decoder/watchdog threads (solver/pipeline.py, solver/fleet.py,
    solver/resilient.py) are all daemons by contract — a non-daemon survivor
    means some code path spawned an unjoinable thread that would hang
    interpreter shutdown. Daemon stragglers (abandoned wedged dispatches) are
    allowed: they are exactly what the leaked-thread gauge accounts for.
    """
    before = {t.ident for t in threading.enumerate()}
    yield
    leaked = [
        t for t in threading.enumerate()
        if t.is_alive() and not t.daemon and t is not threading.main_thread()
        and t.ident not in before
    ]
    assert not leaked, (
        "non-daemon thread(s) leaked by this test module: "
        + ", ".join(repr(t.name) for t in leaked)
    )
