"""Test harness: force a virtual 8-device CPU mesh before jax initializes.

Multi-chip hardware is not available in CI; shardings are validated on a
virtual CPU mesh (SURVEY.md §7 / driver contract). Must run before any
`import jax` anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
