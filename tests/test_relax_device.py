"""Respect-mode preferences ON DEVICE (relax-and-redispatch, VERDICT r4 #9).

ScheduleAnyway topology spread and weighted positive pod affinity —
production's most common soft constraints (kube injects default SA spreads)
— previously routed every Respect-mode solve to the Python oracle. The
relax loop (solver/relax.py + backend._relax_solve) must reproduce the
oracle's per-pod ascending-weight relaxation bit-identically while serving
the solve from the device kernel. Reference semantics: scheduling.md:212-219.
"""

import random

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    TopologySpreadConstraint,
)
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver, quantize_input

from tests.test_zone_device import ZONES, mknode, mkpod, pool


def sa_tsc(sel, key=wk.ZONE_LABEL, skew=1):
    return TopologySpreadConstraint(
        max_skew=skew, topology_key=key, label_selector=sel,
        when_unsatisfiable="ScheduleAnyway",
    )


def waff(sel, weight, key=wk.ZONE_LABEL):
    return PodAffinityTerm(label_selector=sel, topology_key=key, anti=False,
                           weight=weight)


from tests.test_zone_device import assert_zone_parity as assert_relax_parity  # noqa: E402 — one parity contract, one implementation


class TestScheduleAnywayOnDevice:
    def _pods(self, n, sel=None):
        sel = sel or {"app": "soft"}
        return [
            mkpod(f"s{i}", labels=dict(sel), topology_spread=[sa_tsc(sel)])
            for i in range(n)
        ]

    def test_satisfiable_behaves_hard_one_dispatch(self):
        inp = SolverInput(pods=self._pods(3), nodes=[], nodepools=[pool()],
                          zones=ZONES)
        ref, tpu = assert_relax_parity(inp)
        zones = set()
        for c in tpu.claims:
            zr = c.requirements.get(wk.ZONE_LABEL)
            zones.update(zr.values_list())
        assert len(zones) == 3

    def test_impossible_relaxes_on_device(self):
        one_zone = pool(extra=Requirements.of(
            Requirement.create(wk.ZONE_LABEL, IN, ["zone-1a"])))
        inp = SolverInput(pods=self._pods(3), nodes=[], nodepools=[one_zone],
                          zones=ZONES)
        ref, tpu = assert_relax_parity(inp)
        assert not tpu.errors, tpu.errors  # relaxation did the work

    def test_sa_ct_spread_relaxes(self):
        spot_only = pool(extra=Requirements.of(
            Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, ["spot"])))
        pods = [
            mkpod(f"c{i}", labels={"tier": "ct"},
                  topology_spread=[sa_tsc({"tier": "ct"},
                                          key=wk.CAPACITY_TYPE_LABEL)])
            for i in range(4)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[spot_only], zones=ZONES)
        assert_relax_parity(inp)

    def test_mixed_hard_zone_plus_sa_ct(self):
        # hard zone TSC pods + ScheduleAnyway ct spread pods in ONE solve:
        # the relax loop's materialized encode runs the mixed-axis device
        # path (round-5 features composing)
        pods = [
            mkpod(f"z{i}", labels={"app": "w"},
                  topology_spread=[TopologySpreadConstraint(
                      max_skew=1, topology_key=wk.ZONE_LABEL,
                      label_selector={"app": "w"})])
            for i in range(6)
        ] + [
            mkpod(f"c{i}", labels={"tier": "ct"},
                  topology_spread=[sa_tsc({"tier": "ct"},
                                          key=wk.CAPACITY_TYPE_LABEL)])
            for i in range(3)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        assert_relax_parity(inp)

    def test_with_existing_nodes(self):
        nodes = [mknode("n-a", "zone-1a", matching=2, sel={"app": "soft"}),
                 mknode("n-b", "zone-1b")]
        inp = SolverInput(pods=self._pods(5), nodes=nodes, nodepools=[pool()],
                          zones=ZONES)
        assert_relax_parity(inp)


class TestWeightedAffinityOnDevice:
    def test_satisfiable_weighted_affinity(self):
        pods = [
            mkpod(f"a{i}", labels={"svc": "db"},
                  affinity_terms=[waff({"svc": "db"}, weight=10)])
            for i in range(4)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        assert_relax_parity(inp)

    def test_stacked_soft_constraints_relax_on_device(self):
        # SA spread + weighted affinity on ONE pod materializes to a
        # TSC+affinity stack — ON DEVICE since the late-round-5 joint
        # narrowing (test_stacked_device.py); the relax loop keeps every
        # iteration on the kernel, and the oracle's ascending-weight order
        # (weight-0 spread drops before the weight-50 affinity) is
        # reproduced by the redispatch sequence.
        nodes = [mknode("n-a", "zone-1a", matching=3, sel={"svc": "db"})]
        nodes[0].free["cpu"] = 2000  # room for little
        pods = [
            mkpod(f"m{i}", cpu="1", labels={"svc": "db", "app": "x"},
                  topology_spread=[sa_tsc({"app": "x"})],
                  affinity_terms=[waff({"svc": "db"}, weight=50)])
            for i in range(4)
        ]
        inp = SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        ref, tpu = assert_relax_parity(inp)

    def test_weighted_anti_on_device_admission_only(self):
        # round 5 (late): weighted ANTI terms materialize ADMISSION-ONLY
        # (encode kind 3) — they block and commit like a required anti for
        # the owning pod but never register, so satisfied preferences never
        # constrain later members (the oracle's original-pod bookkeeping)
        nodes = [mknode("n-a", "zone-1a"), mknode("n-b", "zone-1b")]
        pods = [
            mkpod("w0", labels={"svc": "x"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"svc": "x"}, topology_key=wk.ZONE_LABEL,
                      anti=True, weight=5)]),
            mkpod("m1", labels={"svc": "x"}),
            mkpod("m2", labels={"svc": "x"}),
        ]
        inp = SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        ref, tpu = assert_relax_parity(inp)
        # m1/m2 are NOT blocked by w0's satisfied preference
        assert not tpu.errors

    def test_weighted_anti_relaxes_past_capacity(self):
        # five singleton locks over three zones: two pods must drop their
        # preference — per-pod ascending-weight relaxation, all on device
        pods = [
            mkpod(f"l{i}", labels={"lock": "k"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"lock": "k"}, topology_key=wk.ZONE_LABEL,
                      anti=True, weight=7)])
            for i in range(5)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        ref, tpu = assert_relax_parity(inp)
        assert not tpu.errors

    def test_weighted_hostname_anti_on_device(self):
        # Q-axis admission-only: the hostname allowance already treats
        # kind 3 as an anti, and the e_co/c_co owner registrations are
        # kind-1-gated — satisfied hostname preferences never block members
        nodes = [mknode("n-a", "zone-1a", matching=1, sel={"svc": "x"}),
                 mknode("n-b", "zone-1b")]
        pods = [
            mkpod("w0", labels={"svc": "x"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"svc": "x"},
                      topology_key=wk.HOSTNAME_LABEL, anti=True, weight=5)]),
            mkpod("m1", labels={"svc": "x"}),
        ]
        inp = SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        ref, tpu = assert_relax_parity(inp)
        assert not tpu.errors

    def test_weighted_hostname_anti_relaxes(self):
        # self-matching hostname singletons beyond node capacity: fresh
        # claims are singletons too; oracle relaxation kicks in only when
        # the pool itself cannot open more claims (it can), so every pod
        # lands on its own target — parity pins the exact shape
        pods = [
            mkpod(f"h{i}", labels={"lock": "k"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"lock": "k"},
                      topology_key=wk.HOSTNAME_LABEL, anti=True, weight=3)])
            for i in range(4)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        ref, tpu = assert_relax_parity(inp)
        assert not tpu.errors


@pytest.mark.parametrize("seed", range(8))
def test_relax_fuzz(seed):
    """Random mixes of hard zone/ct constraints with SA spreads and weighted
    positive affinity; every seed must be served by the device relax loop
    with oracle-exact output."""
    rng = random.Random(7000 + seed)
    pods = []
    for i in range(rng.randrange(6, 22)):
        r = rng.random()
        name = f"p{i:03d}"
        if r < 0.3:
            pods.append(mkpod(name, labels={"app": "soft"},
                              topology_spread=[sa_tsc({"app": "soft"})]))
        elif r < 0.45:
            pods.append(mkpod(name, labels={"tier": "ct"},
                              topology_spread=[sa_tsc({"tier": "ct"},
                                                      key=wk.CAPACITY_TYPE_LABEL,
                                                      skew=rng.choice([1, 2]))]))
        elif r < 0.6:
            pods.append(mkpod(name, labels={"svc": "db"},
                              affinity_terms=[waff({"svc": "db"},
                                                   weight=rng.choice([1, 10, 50]))]))
        elif r < 0.75:
            pods.append(mkpod(name, labels={"app": "hard"},
                              topology_spread=[TopologySpreadConstraint(
                                  max_skew=1, topology_key=wk.ZONE_LABEL,
                                  label_selector={"app": "hard"})]))
        else:
            pods.append(mkpod(name, cpu=rng.choice(["500m", "1", "2"])))
    nodes = [
        mknode(f"n{j}", rng.choice(ZONES), matching=rng.randrange(0, 3),
               sel=rng.choice([{"app": "soft"}, {"svc": "db"}]))
        for j in range(rng.randrange(0, 4))
    ]
    pools = [pool()]
    if rng.random() < 0.35:
        # constrained pool universe makes some soft spreads impossible —
        # the relaxation path, not just the satisfiable fast path
        pools = [pool(extra=Requirements.of(
            Requirement.create(wk.ZONE_LABEL, IN, ["zone-1a"])))]
    inp = SolverInput(pods=pods, nodes=nodes, nodepools=pools, zones=ZONES)
    assert_relax_parity(inp)


class TestPreferredNodeAffinityOnDevice:
    """Preferred node affinity under Respect (round 5, late): active terms
    union into the required node-affinity term per relax iteration — honored
    when satisfiable, dropped ascending-weight when not, all on device."""

    def _prefs(self, *pairs):
        return [
            (w, Requirements.of(Requirement.create(k, IN, vals)))
            for (w, k, vals) in pairs
        ]

    def test_honored_when_satisfiable(self):
        pods = [mkpod("p0", preferred_node_affinity=self._prefs(
            (50, wk.ARCH_LABEL, ["arm64"])))]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        ref, tpu = assert_relax_parity(inp)
        arch = tpu.claims[0].requirements.get(wk.ARCH_LABEL)
        assert arch is not None and arch.values_list() == ["arm64"]

    def test_relaxed_when_impossible(self):
        # amd64-only pool: the arm64 preference must drop, pod still places
        amd_pool = pool(extra=Requirements.of(
            Requirement.create(wk.ARCH_LABEL, IN, ["amd64"])))
        pods = [mkpod("p0", preferred_node_affinity=self._prefs(
            (50, wk.ARCH_LABEL, ["arm64"])))]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[amd_pool], zones=ZONES)
        ref, tpu = assert_relax_parity(inp)
        assert not tpu.errors

    def test_ascending_weight_drop_order(self):
        # two prefs against an amd64-only pool: the oracle drops the LOWEST
        # weight first (zone-1b, w=10), then the impossible arm64 (w=50),
        # then places — parity pins the exact drop sequence.
        amd_pool = pool(extra=Requirements.of(
            Requirement.create(wk.ARCH_LABEL, IN, ["amd64"])))
        pods = [mkpod("p0", preferred_node_affinity=self._prefs(
            (10, wk.ZONE_LABEL, ["zone-1b"]), (50, wk.ARCH_LABEL, ["arm64"])))]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[amd_pool], zones=ZONES)
        ref, tpu = assert_relax_parity(inp)
        assert not tpu.errors

    def test_combined_with_sa_spread(self):
        sel = {"app": "soft"}
        pods = [
            mkpod(f"s{i}", labels=dict(sel), topology_spread=[sa_tsc(sel)],
                  preferred_node_affinity=self._prefs((30, wk.ZONE_LABEL, ["zone-1c"])))
            for i in range(3)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        assert_relax_parity(inp)


class TestWeightedAntiCtAxis:
    """CT-axis and cross-axis coverage for admission-only (kind-3) antis —
    review finding: the zone tests alone left the ct path unpinned."""

    def test_ct_weighted_anti_singletons(self):
        # singleton locks across {on-demand, spot}: third pod relaxes
        pods = [
            mkpod(f"c{i}", labels={"lock": "k"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"lock": "k"},
                      topology_key=wk.CAPACITY_TYPE_LABEL, anti=True, weight=4)])
            for i in range(3)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        ref, tpu = assert_relax_parity(inp)
        assert not tpu.errors

    def test_zone_member_of_ct_kind3_sig_stays_on_device(self):
        # a zone-TSC pod whose labels match a CT-axis kind-3 selector:
        # kind-3 membership binds no axis (it never registers, so members
        # are never blocked) — the mixed solve must stay kernel-served and
        # oracle-exact
        from karpenter_tpu.api.objects import TopologySpreadConstraint

        pods = [
            mkpod(f"z{i}", labels={"app": "w"},
                  topology_spread=[TopologySpreadConstraint(
                      max_skew=1, topology_key=wk.ZONE_LABEL,
                      label_selector={"app": "w"})])
            for i in range(4)
        ] + [
            mkpod("wa", labels={"pick": "1"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"app": "w"},  # selects the zone pods
                      topology_key=wk.CAPACITY_TYPE_LABEL, anti=True,
                      weight=9)])
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        ref, tpu = assert_relax_parity(inp)


@pytest.mark.parametrize("seed", range(6))
def test_weighted_anti_fuzz(seed):
    """Weighted antis on BOTH axes beside required antis, hard spreads, and
    existing nodes — parity per seed (the kind-3 validation fuzz, checked
    in per review)."""
    from tests.test_mixed_axis_device import CTS, ct_node, mkinp
    from karpenter_tpu.api.objects import TopologySpreadConstraint

    TSC1 = TopologySpreadConstraint(
        max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "w"})
    rng = random.Random(13000 + seed)
    pods = []
    for i in range(rng.randrange(4, 16)):
        r = rng.random()
        if r < 0.3:
            pods.append(mkpod(f"w{i}", labels={"lock": f"k{i % 3}"},
                              affinity_terms=[PodAffinityTerm(
                                  label_selector={"lock": f"k{i % 3}"},
                                  topology_key=rng.choice(
                                      [wk.ZONE_LABEL, wk.CAPACITY_TYPE_LABEL]),
                                  anti=True, weight=rng.choice([1, 10]))]))
        elif r < 0.5:
            pods.append(mkpod(f"t{i}", labels={"app": "w"}, topology_spread=[TSC1]))
        elif r < 0.65:
            pods.append(mkpod(f"r{i}", labels={"lock": f"k{i % 3}"},
                              affinity_terms=[PodAffinityTerm(
                                  label_selector={"lock": f"k{i % 3}"},
                                  topology_key=wk.ZONE_LABEL, anti=True)]))
        else:
            pods.append(mkpod(f"x{i}", labels=rng.choice(
                [{"lock": "k0"}, {"app": "w"}, {}])))
    nodes = [ct_node(f"n{j}", rng.choice(ZONES), rng.choice(CTS),
                     matching=rng.randrange(0, 2),
                     sel=rng.choice([{"lock": "k0"}, {"app": "w"}]))
             for j in range(rng.randrange(0, 4))]
    assert_relax_parity(mkinp(pods, nodes), expect_device=None)


def test_custom_key_weighted_anti_stays_on_oracle():
    """Custom topology keys have no kind-3 encoding; the relax plan must
    decline so the whole solve (preferences intact) replays on the oracle —
    exact-path pinned per the repo's routing-test convention."""
    pods = [
        mkpod("c0", labels={"svc": "x"},
              affinity_terms=[PodAffinityTerm(
                  label_selector={"svc": "x"}, topology_key="rack",
                  anti=True, weight=5)])
    ]
    inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
    ref = ReferenceSolver().solve(quantize_input(inp))
    solver = TPUSolver()
    tpu = solver.solve(inp)
    assert ref.placements == tpu.placements
    assert solver.stats["fallback_solves"] == 1, solver.stats


class TestRelaxOrderingParity:
    def test_gated_and_bound_pods_do_not_perturb_ffd_order(self):
        """Regression: solve_async must FFD-sort the FILTERED pod list.
        A gated/bound pod holding a signature's first uid slot inside an
        equal-(cpu,mem) block used to shift signature first-appearance in
        the unfiltered sort, regrouping the schedulable pods into a
        processing order the oracle (which sorts only schedulable pods)
        never sees."""
        from karpenter_tpu.utils.resources import Resources

        sel_x, sel_y = {"app": "ox"}, {"app": "oy"}
        gated = mkpod("a0", labels=dict(sel_y),
                      topology_spread=[sa_tsc(sel_y)], scheduling_gated=True)
        bound = mkpod("a1", labels=dict(sel_y),
                      topology_spread=[sa_tsc(sel_y)], node_name="pre-bound")
        p1 = mkpod("a2", labels=dict(sel_x), topology_spread=[sa_tsc(sel_x)])
        p2 = mkpod("a3", labels=dict(sel_y), topology_spread=[sa_tsc(sel_y)])
        # One existing node with room for exactly ONE pod: whichever pod is
        # processed first claims it, so an order swap shows up in placements.
        n = mknode("n-tight", "zone-1a")
        n.free = Resources.parse({"cpu": "1", "memory": "1Gi"})
        n.free["pods"] = 10
        inp = SolverInput(pods=[gated, bound, p1, p2], nodes=[n],
                          nodepools=[pool()], zones=ZONES)
        ref, tpu = assert_relax_parity(inp)
        assert ref.placements.get("a2") == ("node", "n-tight"), ref.placements
