"""Batcher coalescing engine + event recorder dedupe."""

import threading

from karpenter_tpu.batcher.batcher import Batcher
from karpenter_tpu.events.recorder import Event, Recorder


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestBatcher:
    def test_coalesces_and_splits_results(self):
        calls = []

        def exec_fn(key, reqs):
            calls.append((key, list(reqs)))
            return [r * 10 for r in reqs]

        clock = FakeClock()
        b = Batcher("test", exec_fn, idle_s=0.1, max_s=1.0, clock=clock)
        w1 = b.add("k", 1)
        w2 = b.add("k", 2)
        clock.t = 0.2  # idle window elapsed
        assert b.poll()
        assert w1() == 10 and w2() == 20
        assert len(calls) == 1 and calls[0][1] == [1, 2]

    def test_max_items_flushes_immediately(self):
        def exec_fn(key, reqs):
            return list(reqs)

        b = Batcher("test", exec_fn, idle_s=10, max_s=10, max_items=3)
        waiters = [b.add("k", i) for i in range(3)]
        # third add hit max_items -> flushed without poll
        assert [w() for w in waiters] == [0, 1, 2]

    def test_buckets_are_independent(self):
        def exec_fn(key, reqs):
            return [f"{key}:{r}" for r in reqs]

        clock = FakeClock()
        b = Batcher("test", exec_fn, idle_s=0.01, max_s=1, clock=clock)
        wa = b.add("a", 1)
        wb = b.add("b", 2)
        clock.t = 0.2
        b.poll()
        assert wa() == "a:1" and wb() == "b:2"

    def test_errors_propagate_to_all_waiters(self):
        def exec_fn(key, reqs):
            raise RuntimeError("cloud down")

        b = Batcher("test", exec_fn, idle_s=0, max_s=0)
        w = b.add("k", 1)
        try:
            w()
            assert False, "should raise"
        except RuntimeError as e:
            assert "cloud down" in str(e)


class TestRecorder:
    def test_dedupe_window(self):
        clock = FakeClock()
        r = Recorder(dedupe_ttl_s=60, clock=clock)
        e = Event("pods", "p", "Warning", "FailedScheduling", "nope")
        assert r.publish(e)
        assert not r.publish(e)  # deduped
        clock.t = 61
        assert r.publish(e)  # TTL elapsed
        assert len(r.events("pods", "p")) == 2

    def test_filtering(self):
        r = Recorder()
        r.publish(Event("pods", "a", "Normal", "X", "m"))
        r.publish(Event("nodes", "b", "Normal", "Y", "m"))
        assert len(r.events("pods")) == 1
        assert r.events("nodes", "b")[0].reason == "Y"


class TestWatcherContention:
    def test_slow_watcher_does_not_stall_mutations(self):
        """Watchers dispatch OUTSIDE the store lock: one slow watcher must
        not serialize other threads' mutations behind it (the failure mode
        the reference's workqueues exist to prevent — VERDICT r3 'what's
        weak' #8)."""
        import threading
        import time as _time

        from karpenter_tpu.api.objects import ObjectMeta, Pod
        from karpenter_tpu.controllers import store as st

        store = st.Store()
        entered = threading.Event()
        release = threading.Event()

        def slow_watcher(event, kind, obj):
            if obj.meta.name == "blocker":
                entered.set()
                release.wait(timeout=5)

        store.watch(st.PODS, slow_watcher)

        def make(name):
            store.create(st.PODS, Pod(meta=ObjectMeta(name=name, uid=name)))

        t1 = threading.Thread(target=make, args=("blocker",))
        t1.start()
        assert entered.wait(timeout=5), "watcher never entered"
        # while the slow watcher is stuck, another thread's mutation and
        # reads must complete promptly
        t0 = _time.perf_counter()
        make("free")
        assert store.try_get(st.PODS, "free") is not None
        elapsed = _time.perf_counter() - t0
        release.set()
        t1.join(timeout=5)
        assert elapsed < 1.0, f"mutation stalled {elapsed:.1f}s behind a slow watcher"
