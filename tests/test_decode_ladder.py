"""On-device decode + device-resident relax ladder (ISSUE 6).

Two transfer-side contracts. (1) The packed claim-delta fetch
(ffd.compact_takes / compact_claim_meta -> backend._pack_dispatch) must
reconstruct a SolverResult decision-identical to the dense take-table
decode — including the >65535/over-capacity overflow carve-out, where the
host must detect the flag and re-fetch full width rather than misdecode.
(2) The single-dispatch relax ladder (ffd.ffd_solve_ladder) must commit
the same rung per pod as the host relax-and-redispatch loop, which itself
is pinned to the Python oracle — a 3-way parity across every preference
kind relax.py supports.
"""

import random

import numpy as np
import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import TopologySpreadConstraint
from karpenter_tpu.provisioning.scheduler import SolverInput
from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
from karpenter_tpu.solver import backend
from karpenter_tpu.solver.arena import ArgumentArena
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver, quantize_input
from karpenter_tpu.solver.tpu import ffd

from tests.test_relax_device import sa_tsc, waff
from tests.test_zone_device import ZONES, mknode, mkpod, pool


def _assert_same_decisions(a, b, tag):
    """The parity contract: errors, placements, and claim identity. NOT
    dataclass equality — SolverResult carries path-dependent extras."""
    assert set(a.errors) == set(b.errors), f"{tag}: errors diverge"
    assert a.placements == b.placements, f"{tag}: placements diverge"
    assert len(a.claims) == len(b.claims), f"{tag}: claim count diverges"
    for i, (ca, cb) in enumerate(zip(a.claims, b.claims)):
        assert ca.nodepool == cb.nodepool, f"{tag}: claim {i} nodepool"
        assert sorted(ca.instance_type_names) == sorted(cb.instance_type_names), (
            f"{tag}: claim {i} types"
        )
        assert ca.pod_uids == cb.pod_uids, f"{tag}: claim {i} pods"


def _random_fleet(rng, n_pods):
    """Mixed fleet: plain pods, hard zone spreads, a couple of deployment
    waves — enough claim/node-take variety to exercise every delta field
    (multi-entry runs, daemon-opened claims, pours into existing nodes)."""
    pods = []
    for i in range(n_pods):
        kind = rng.randrange(4)
        cpu = rng.choice(["1", "2", "500m"])
        mem = rng.choice(["1Gi", "2Gi", "512Mi"])
        if kind == 0:
            pods.append(mkpod(f"p{i}", cpu, mem))
        else:
            app = f"app-{rng.randrange(3)}"
            tsc = TopologySpreadConstraint(
                max_skew=1, topology_key=wk.ZONE_LABEL,
                label_selector={"app": app},
            )
            pods.append(mkpod(f"p{i}", cpu, mem, labels={"app": app},
                              topology_spread=[tsc]))
    nodes = []
    if rng.random() < 0.5:
        nodes = [mknode("n-a", "zone-1a", matching=rng.randrange(3),
                        sel={"app": "app-0"}),
                 mknode("n-b", "zone-1b")]
    return SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)


class TestDeltaDecodeParity:
    def test_randomized_fleet_delta_vs_dense(self):
        """Property-style: seeded random fleets, delta decode vs dense
        decode must be decision-identical with zero wide re-fetches."""
        rng = random.Random(0x15506)
        for trial in range(5):
            inp = _random_fleet(rng, 12 + trial * 9)
            delta = TPUSolver()
            dense = TPUSolver(device_decode=False)
            rd = delta.solve(inp)
            rn = dense.solve(inp)
            _assert_same_decisions(rd, rn, f"trial {trial}")
            assert delta.stats["device_solves"] == 1, delta.stats
            assert delta.stats["wide_refetches"] == 0, delta.stats

    def test_oversize_take_value_trips_overflow(self):
        """A take >65535 cannot travel as a uint16 half-word: the flag must
        be raised even when the entry count is comfortably under cap."""
        take_e = np.zeros((2, 3), np.int32)
        take_c = np.zeros((2, 2), np.int32)
        take_c[0, 0] = 65536  # first value outside uint16 range
        take_e[1, 1] = 7
        overflow, n, _, _ = ffd.compact_takes(take_e, take_c, cap=16)
        assert int(overflow) == 1
        assert int(n) == 2
        take_c[0, 0] = 65535  # largest representable value: no overflow
        overflow, _, _, _ = ffd.compact_takes(take_e, take_c, cap=16)
        assert int(overflow) == 0

    def test_entry_count_over_capacity_trips_overflow(self):
        take_e = np.ones((2, 4), np.int32)  # 8 entries > cap 4
        take_c = np.zeros((2, 2), np.int32)
        overflow, n, _, _ = ffd.compact_takes(take_e, take_c, cap=4)
        assert int(overflow) == 1 and int(n) == 8

    def test_uniq_meta_over_capacity_trips_overflow(self):
        M, Wm = 32, 2
        cm = np.arange(M * Wm, dtype=np.int32).reshape(M, Wm)  # all distinct
        zc = np.zeros(M, np.uint32)
        gb = np.zeros((M, 1), np.uint32)
        pl = np.zeros(M, np.int32)
        overflow_u, n_u, _, _ = ffd.compact_claim_meta(cm, zc, gb, pl, cap_u=16)
        assert int(overflow_u) == 1 and int(n_u) == M

    def test_forced_overflow_takes_wide_refetch_path(self, monkeypatch):
        """End-to-end overflow: shrink the entry capacity below what the
        fleet needs, so the kernel raises the flag and the host must serve
        the solve from the full-width re-fetch — decision-identical to the
        dense path, with the carve-out counted in stats and metrics."""
        from karpenter_tpu.metrics.registry import SOLVER_WIDE_REFETCH

        monkeypatch.setattr(backend, "delta_capacity", lambda *a: 2)
        inp = _random_fleet(random.Random(7), 30)
        before = SOLVER_WIDE_REFETCH.value()
        delta = TPUSolver()
        dense = TPUSolver(device_decode=False)
        rd = delta.solve(inp)
        rn = dense.solve(inp)
        _assert_same_decisions(rd, rn, "forced overflow")
        assert delta.stats["wide_refetches"] >= 1, delta.stats
        assert SOLVER_WIDE_REFETCH.value() >= before + 1

    def test_knob_off_keeps_dense_path(self):
        inp = _random_fleet(random.Random(3), 10)
        dense = TPUSolver(device_decode=False)
        dense.solve(inp)
        assert dense.stats["wide_refetches"] == 0
        assert dense.stats["device_solves"] == 1


# -- relax ladder: 3-way parity across the preference kinds -------------------


def _three_way(inp, expect_ladder=True):
    """Oracle vs host relax loop vs single-dispatch ladder. The host loop
    is already pinned to the oracle (test_relax_device.py); this pins the
    ladder to BOTH, plus the one-dispatch accounting claim."""
    ref = ReferenceSolver().solve(quantize_input(inp))
    host = TPUSolver(relax_ladder=False)
    lad = TPUSolver()
    r_host = host.solve(inp)
    r_lad = lad.solve(inp)
    _assert_same_decisions(ref, r_host, "oracle vs host loop")
    _assert_same_decisions(ref, r_lad, "oracle vs ladder")
    if expect_ladder:
        assert lad.stats["ladder_solves"] >= 1, lad.stats
        assert lad.stats["relax_dispatches"] == 1, lad.stats
        assert lad.stats["ladder_rungs_used"] >= 1, lad.stats
    return lad


class TestLadderParity:
    def _one_zone_pool(self):
        return pool(extra=Requirements.of(
            Requirement.create(wk.ZONE_LABEL, IN, ["zone-1a"])))

    def test_schedule_anyway_spreads(self):
        # one-zone pool makes every SA zone spread beyond the first pod
        # impossible: the whole fleet must walk its ladder
        sel = {"app": "soft"}
        pods = [mkpod(f"s{i}", labels=dict(sel), topology_spread=[sa_tsc(sel)])
                for i in range(6)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[self._one_zone_pool()],
                          zones=ZONES)
        _three_way(inp)

    def test_weighted_positive_pod_affinity(self):
        # weighted affinity toward a label that only lives in zone-1b while
        # the pool is pinned to zone-1a: the preference must drop
        nodes = [mknode("n-b", "zone-1b", matching=2, sel={"svc": "db"})]
        pods = [mkpod(f"a{i}", labels={"svc": "web"},
                      affinity_terms=[waff({"svc": "db"}, weight=10)])
                for i in range(4)]
        inp = SolverInput(pods=pods, nodes=nodes,
                          nodepools=[self._one_zone_pool()], zones=ZONES)
        _three_way(inp)

    def test_preferred_node_affinity(self):
        amd_pool = pool(extra=Requirements.of(
            Requirement.create(wk.ARCH_LABEL, IN, ["amd64"])))
        prefs = [
            (10, Requirements.of(Requirement.create(
                wk.ZONE_LABEL, IN, ["zone-1b"]))),
            (50, Requirements.of(Requirement.create(
                wk.ARCH_LABEL, IN, ["arm64"]))),
        ]
        pods = [mkpod(f"n{i}", preferred_node_affinity=list(prefs))
                for i in range(3)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[amd_pool], zones=ZONES)
        _three_way(inp)

    def test_mixed_preference_kinds_one_solve(self):
        sel = {"app": "mix"}
        pods = [
            mkpod(f"m{i}", labels=dict(sel), topology_spread=[sa_tsc(sel)],
                  preferred_node_affinity=[(30, Requirements.of(
                      Requirement.create(wk.ZONE_LABEL, IN, ["zone-1c"])))])
            for i in range(4)
        ] + [
            mkpod(f"w{i}", labels={"svc": "web"},
                  affinity_terms=[waff({"svc": "db"}, weight=5)])
            for i in range(2)
        ]
        inp = SolverInput(pods=pods, nodes=[],
                          nodepools=[self._one_zone_pool()], zones=ZONES)
        _three_way(inp)

    def test_satisfiable_prefs_stay_single_dispatch(self):
        # nothing needs to relax: still exactly one dispatch, rung 0 wins
        sel = {"app": "easy"}
        pods = [mkpod(f"e{i}", labels=dict(sel), topology_spread=[sa_tsc(sel)])
                for i in range(3)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        lad = TPUSolver()
        ref = ReferenceSolver().solve(quantize_input(inp))
        _assert_same_decisions(ref, lad.solve(inp), "satisfiable")
        assert lad.stats["relax_dispatches"] <= 1, lad.stats

    def test_ladder_composes_with_delta_decode(self):
        # both ISSUE 6 paths on at once (the default production config)
        sel = {"app": "both"}
        pods = [mkpod(f"b{i}", labels=dict(sel), topology_spread=[sa_tsc(sel)])
                for i in range(5)]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[self._one_zone_pool()],
                          zones=ZONES)
        lad = _three_way(inp)
        assert lad.stats["wide_refetches"] == 0, lad.stats


class TestLadderResidency:
    def test_arena_invalidation_drops_resident_rungs(self):
        """The resilience layer's fallback replay calls arena.invalidate();
        a stale device-resident rung table surviving that would let a
        post-fault solve walk rungs from before the fault."""
        arena = ArgumentArena()
        key = ("bucket",)
        table = np.arange(12, dtype=np.int32).reshape(4, 3)
        arena.put_ladder(key, table, dev="resident")
        assert arena.get_ladder(key, table) == "resident"
        # content drift alone must miss (digest mismatch)
        other = table.copy()
        other[0, 0] = 99
        assert arena.get_ladder(key, other) is None
        arena.invalidate()
        assert arena.get_ladder(key, table) is None

    def test_repeat_solve_reuses_resident_ladder(self):
        sel = {"app": "resident"}
        pods = [mkpod(f"r{i}", labels=dict(sel), topology_spread=[sa_tsc(sel)])
                for i in range(4)]
        one_zone = pool(extra=Requirements.of(
            Requirement.create(wk.ZONE_LABEL, IN, ["zone-1a"])))
        inp = SolverInput(pods=pods, nodes=[], nodepools=[one_zone], zones=ZONES)
        solver = TPUSolver()
        r1 = solver.solve(inp)
        assert solver.stats["ladder_solves"] >= 1, solver.stats
        n_resident = len(solver.arena._ladders) if solver.arena else 0
        r2 = solver.solve(inp)
        _assert_same_decisions(r1, r2, "repeat solve")
        if solver.arena is not None:
            assert len(solver.arena._ladders) == n_resident, (
                "re-solving the same fleet grew ladder residency"
            )
