"""Admission validation — the CEL-rule analog
(hack/validation/{requirements,labels}.sh; karpenter.sh_nodepools.yaml
x-kubernetes-validations). The store rejects invalid NodePools/NodeClaims
at create/update, exactly where the reference's API server does.
"""

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    Budget,
    Disruption,
    NodeClaimTemplate,
    NodePool,
    ObjectMeta,
)
from karpenter_tpu.api.validation import ValidationError, validate_nodepool
from karpenter_tpu.controllers import store as st
from karpenter_tpu.operator.operator import new_kwok_operator
from karpenter_tpu.scheduling.requirements import (
    EXISTS,
    GT,
    IN,
    Requirement,
    Requirements,
)

from tests.test_e2e_kwok import FakeClock


def mk(reqs=None, labels=None, budgets=None):
    np_obj = NodePool(
        meta=ObjectMeta(name="p"),
        template=NodeClaimTemplate(),
        disruption=Disruption(budgets=budgets or [Budget()]),
    )
    if reqs:
        np_obj.template.requirements = reqs
    if labels:
        np_obj.template.labels = labels
    return np_obj


class TestRules:
    def test_valid_pool_passes(self):
        reqs = Requirements.of(
            Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, ["spot"]),
            Requirement.create("karpenter.tpu/instance-family", EXISTS, ()),
            Requirement.create("example.com/team", IN, ["ml"]),
        )
        assert validate_nodepool(mk(reqs=reqs)) == []

    def test_restricted_requirement_domain(self):
        reqs = Requirements.of(Requirement.create("karpenter.sh/custom", IN, ["x"]))
        errs = validate_nodepool(mk(reqs=reqs))
        assert any("restricted" in e for e in errs)

    def test_restricted_tpu_domain_key(self):
        reqs = Requirements.of(Requirement.create("karpenter.tpu/secret-knob", IN, ["x"]))
        errs = validate_nodepool(mk(reqs=reqs))
        assert any("restricted" in e for e in errs)

    def test_in_requires_values(self):
        reqs = Requirements.of(Requirement.create("example.com/team", IN, []))
        errs = validate_nodepool(mk(reqs=reqs))
        assert any("must have a value" in e for e in errs)

    def test_min_values_needs_enough_values(self):
        reqs = Requirements.of(
            Requirement.create("karpenter.tpu/instance-family", IN, ["m5"], min_values=3)
        )
        errs = validate_nodepool(mk(reqs=reqs))
        assert any("minValues" in e for e in errs)

    def test_min_values_bound(self):
        reqs = Requirements.of(
            Requirement.create("karpenter.tpu/instance-family", EXISTS, (), min_values=51)
        )
        errs = validate_nodepool(mk(reqs=reqs))
        assert any("1..50" in e for e in errs)

    def test_hostname_label_restricted(self):
        errs = validate_nodepool(mk(labels={wk.HOSTNAME_LABEL: "x"}))
        assert any("hostname" in e for e in errs)

    def test_budget_shape(self):
        errs = validate_nodepool(mk(budgets=[Budget(nodes="150%")]))
        assert any("percentage" in e for e in errs)
        errs = validate_nodepool(mk(budgets=[Budget(nodes="10", schedule="0 9 * * *")]))
        assert any("schedule" in e for e in errs)
        errs = validate_nodepool(
            mk(budgets=[Budget(nodes="10", schedule="bogus cron", duration_s=60.0)])
        )
        assert any("cron" in e for e in errs)
        assert validate_nodepool(
            mk(budgets=[Budget(nodes="55%", schedule="0 9 * * 1-5", duration_s=3600.0)])
        ) == []


class TestStoreAdmission:
    def test_store_rejects_invalid_nodepool(self):
        op = new_kwok_operator(clock=FakeClock())
        bad = mk(labels={wk.NODEPOOL_LABEL: "oops"})
        with pytest.raises(ValidationError):
            op.store.create(st.NODEPOOLS, bad)
        assert op.store.try_get(st.NODEPOOLS, "p") is None

    def test_store_rejects_invalid_update(self):
        import copy

        op = new_kwok_operator(clock=FakeClock())
        good = mk()
        op.store.create(st.NODEPOOLS, good)
        # a client submits a FRESH object (in-place mutation of the live
        # stored object is already visible and only grandfathered — the
        # documented update-admission caveat in store.update)
        bad = copy.deepcopy(good)
        bad.disruption.budgets = [Budget(nodes="-3")]
        with pytest.raises(ValidationError):
            op.store.update(st.NODEPOOLS, bad)
        assert op.store.get(st.NODEPOOLS, "p").disruption.budgets[0].nodes != "-3"

    def test_update_grandfathers_legacy_invalid_objects(self):
        import copy

        op = new_kwok_operator(clock=FakeClock())
        legacy = mk(budgets=[Budget(nodes="-3")])
        # bypass admission the way a restored snapshot does
        with op.store._lock:
            legacy.meta.resource_version = op.store._next_rv()
            op.store._objects[st.NODEPOOLS][op.store._key(legacy)] = legacy
        upd = copy.deepcopy(legacy)
        upd.weight = 7
        op.store.update(st.NODEPOOLS, upd)  # must not brick the object
        assert op.store.get(st.NODEPOOLS, "p").weight == 7


    def test_update_if_enforces_admission(self):
        """CAS updates go through the same admission as update() — update_if
        is generic store infrastructure, not a lease-only side door."""
        import copy

        op = new_kwok_operator(clock=FakeClock())
        good = mk()
        op.store.create(st.NODEPOOLS, good)
        bad = copy.deepcopy(good)
        bad.disruption.budgets = [Budget(nodes="-3")]
        with pytest.raises(ValidationError):
            op.store.update_if(st.NODEPOOLS, bad, good.meta.resource_version)
        assert op.store.get(st.NODEPOOLS, "p").disruption.budgets[0].nodes != "-3"


    def test_empty_nodeclass_ref_rejected(self):
        op = new_kwok_operator(clock=FakeClock())
        bad = mk()
        bad.template.node_class_ref = ""
        with pytest.raises(ValidationError, match="nodeClassRef"):
            op.store.create(st.NODEPOOLS, bad)


    def test_min_values_lower_bound(self):
        from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements

        op = new_kwok_operator(clock=FakeClock())
        bad = mk()
        bad.template.requirements = bad.template.requirements.union(
            Requirements.of(
                Requirement.create("karpenter.tpu/instance-family", IN,
                                   ["m5", "c5"], min_values=-3)
            )
        )
        with pytest.raises(ValidationError, match="1..50"):
            op.store.create(st.NODEPOOLS, bad)

    def test_min_values_zero_rejected(self):
        from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements

        op = new_kwok_operator(clock=FakeClock())
        bad = mk()
        bad.template.requirements = bad.template.requirements.union(
            Requirements.of(
                Requirement.create("karpenter.tpu/instance-family", IN,
                                   ["m5", "c5"], min_values=0)
            )
        )
        with pytest.raises(ValidationError, match="1..50"):
            op.store.create(st.NODEPOOLS, bad)
