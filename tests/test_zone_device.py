"""Zone topology spread + inter-pod affinity ON DEVICE (BASELINE configs 3-4).

The zone event engine (solver/tpu/ffd.py) must make bit-identical decisions
to the oracle for zone-granular DoNotSchedule TSCs and required
(anti-)affinity — including claim zone commitment (argmin/argmax count, lex),
per-zone consecutive budgets, first-fit preemption as the min-count floor
rises, and the balanced-phase cycle batching. Reference semantics:
/root/reference/website/content/en/preview/concepts/scheduling.md:383-429.
"""

import random

import pytest

from karpenter_tpu.api import wellknown as wk
from karpenter_tpu.api.objects import (
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    TopologySpreadConstraint,
)
from karpenter_tpu.catalog.catalog import CatalogSpec, generate
from karpenter_tpu.provisioning.scheduler import ExistingNode, NodePoolSpec, SolverInput
from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
from karpenter_tpu.solver.backend import ReferenceSolver, TPUSolver
from karpenter_tpu.solver.encode import quantize_input
from karpenter_tpu.utils.resources import Resources

CATALOG = generate(CatalogSpec())
ZONES = ("zone-1a", "zone-1b", "zone-1c")


def pool(name="default", weight=0, extra=None):
    r = Requirements.of(Requirement.create(wk.NODEPOOL_LABEL, IN, [name]))
    if extra:
        r = r.union(extra)
    return NodePoolSpec(
        name=name, weight=weight, requirements=r, taints=[], instance_types=CATALOG
    )


def mkpod(name, cpu="1", mem="1Gi", labels=None, **kw):
    return Pod(
        meta=ObjectMeta(name=name, uid=name, labels=labels or {}),
        requests=Resources.parse({"cpu": cpu, "memory": mem}),
        **kw,
    )


def mknode(name, zone, matching=0, sel=None):
    free = Resources.parse({"cpu": "8", "memory": "32Gi"})
    free["pods"] = 50
    n = ExistingNode(
        id=name,
        labels={
            wk.ZONE_LABEL: zone,
            wk.HOSTNAME_LABEL: name,
            wk.CAPACITY_TYPE_LABEL: "on-demand",
            wk.ARCH_LABEL: "amd64",
            wk.OS_LABEL: "linux",
        },
        taints=[],
        free=free,
    )
    n.pod_labels.extend([dict(sel or {"app": "w"})] * matching)
    return n


DEVICE_SOLVES_SEEN = {"n": 0}  # cumulative across the fuzz seeds


def assert_zone_parity(inp, expect_device=True):
    """Parity + EXACT path assertion: expect_device=True requires the
    device kernel served the solve, False requires the fallback chain did
    (a scenario regressing off its expected path fails its own test —
    VERDICT r4 weak #5), None skips the path assert (mixed/unknown)."""
    ref = ReferenceSolver().solve(quantize_input(inp))
    solver = TPUSolver()
    tpu = solver.solve(inp)
    DEVICE_SOLVES_SEEN["n"] += solver.stats["device_solves"]
    assert set(ref.errors) == set(tpu.errors), (
        f"errors: ref={sorted(ref.errors)} tpu={sorted(tpu.errors)}"
    )
    assert ref.placements == tpu.placements, _diff(ref.placements, tpu.placements)
    assert len(ref.claims) == len(tpu.claims)
    for i, (rc, tc) in enumerate(zip(ref.claims, tpu.claims)):
        assert rc.nodepool == tc.nodepool, f"claim {i}"
        assert sorted(rc.instance_type_names) == sorted(tc.instance_type_names), (
            f"claim {i} types"
        )
        assert rc.pod_uids == tc.pod_uids, f"claim {i} pods"
    if expect_device:
        assert solver.stats["device_solves"] == 1, solver.stats
    elif expect_device is False:
        assert solver.stats["device_solves"] == 0, (
            "expected the fallback chain, device kernel served it: "
            f"{solver.stats}"
        )
    return ref, tpu


def _diff(a, b):
    keys = set(a) | set(b)
    lines = [
        f"{k}: ref={a.get(k)} tpu={b.get(k)}"
        for k in sorted(keys)
        if a.get(k) != b.get(k)
    ]
    return "placements diverge:\n" + "\n".join(lines[:20])


TSC1 = TopologySpreadConstraint(
    max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "w"}
)
TSC2 = TopologySpreadConstraint(
    max_skew=2, topology_key=wk.ZONE_LABEL, label_selector={"app": "w"}
)


class TestZoneSpreadOnDevice:
    def test_fresh_claims_skew1(self):
        pods = [
            mkpod(f"p{i:02d}", cpu="2", mem="4Gi", labels={"app": "w"},
                  topology_spread=[TSC1])
            for i in range(9)
        ]
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )
        zones = set()
        for c in tpu.claims:
            zr = c.requirements.get(wk.ZONE_LABEL)
            assert zr is not None and len(zr.values_list()) == 1  # committed
            zones.add(zr.values_list()[0])
        assert zones == set(ZONES)  # spread across all three AZs

    def test_unbalanced_existing_counts(self):
        """Transient phase: pre-existing matching pods skew the counts; the
        pour must follow the oracle's first-fit preemption exactly."""
        nodes = [mknode("na", "zone-1a", 3), mknode("nb", "zone-1b", 0),
                 mknode("nc", "zone-1c", 1)]
        pods = [
            mkpod(f"p{i:02d}", cpu="500m", mem="1Gi", labels={"app": "w"},
                  topology_spread=[TSC1])
            for i in range(12)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )

    def test_skew2_unbalanced(self):
        nodes = [mknode("na", "zone-1a", 5), mknode("nb", "zone-1b", 2),
                 mknode("nc", "zone-1c", 0)]
        pods = [
            mkpod(f"p{i:02d}", cpu="250m", mem="512Mi", labels={"app": "w"},
                  topology_spread=[TSC2])
            for i in range(30)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )

    def test_zone_plus_hostname_tsc(self):
        htsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "w"}
        )
        pods = [
            mkpod(f"h{i:02d}", cpu="500m", mem="1Gi", labels={"app": "w"},
                  topology_spread=[TSC1, htsc])
            for i in range(6)
        ]
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )
        assert len(tpu.claims) == 6  # hostname skew 1: one pod per claim

    def test_zone_selector_interaction(self):
        zsel = {wk.ZONE_LABEL: "zone-1b"}
        pods = [
            mkpod(f"t{i:02d}", cpu="1", mem="2Gi", labels={"app": "w"},
                  topology_spread=[TSC1])
            for i in range(7)
        ] + [mkpod(f"z{i:02d}", cpu="1", mem="2Gi", node_selector=zsel) for i in range(4)]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_mixed_with_plain_pods(self):
        pods = [
            mkpod(f"t{i:02d}", cpu="2", mem="4Gi", labels={"app": "w"},
                  topology_spread=[TSC1])
            for i in range(6)
        ] + [mkpod(f"u{i:02d}", cpu="1", mem="2Gi") for i in range(8)]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[mknode("na", "zone-1a", 0)],
                        nodepools=[pool()], zones=ZONES)
        )

    def test_large_run_cycles(self):
        """Balanced phase at scale: 300 identical spread pods must batch via
        rotation rounds (and still match the oracle pod-for-pod)."""
        pods = [
            mkpod(f"p{i:03d}", cpu="500m", mem="1Gi", labels={"app": "w"},
                  topology_spread=[TSC1])
            for i in range(300)
        ]
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )
        assert not tpu.errors


class TestZoneAffinityOnDevice:
    def test_anti_affinity_exhausts_zones(self):
        anti = PodAffinityTerm(
            label_selector={"app": "db"}, topology_key=wk.ZONE_LABEL, anti=True
        )
        pods = [
            mkpod(f"db{i}", cpu="1", mem="2Gi", labels={"app": "db"},
                  affinity_terms=[anti])
            for i in range(4)
        ]
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )
        # 3 zones -> the 4th anti pod cannot schedule
        assert len(tpu.errors) == 1

    def test_positive_affinity_bootstrap(self):
        aff = PodAffinityTerm(
            label_selector={"app": "web"}, topology_key=wk.ZONE_LABEL, anti=False
        )
        pods = [
            mkpod(f"w{i}", cpu="1", mem="2Gi", labels={"app": "web"},
                  affinity_terms=[aff])
            for i in range(6)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_positive_affinity_follows_existing(self):
        aff = PodAffinityTerm(
            label_selector={"app": "web"}, topology_key=wk.ZONE_LABEL, anti=False
        )
        nodes = [mknode("nb", "zone-1b", 2, {"app": "web"})]
        pods = [
            mkpod(f"f{i}", cpu="1", mem="2Gi", labels={"x": "y"}, affinity_terms=[aff])
            for i in range(4)
        ]
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )
        # all followers co-locate with the existing web pods on nb
        assert all(t == ("node", "nb") for t in tpu.placements.values())

    def test_symmetric_anti_block(self):
        anti = PodAffinityTerm(
            label_selector={"app": "x"}, topology_key=wk.ZONE_LABEL, anti=True
        )
        pods = [
            mkpod("owner", cpu="2", mem="4Gi", labels={"o": "1"}, affinity_terms=[anti])
        ] + [mkpod(f"x{i}", cpu="1", mem="2Gi", labels={"app": "x"}) for i in range(3)]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_tsc_with_symmetric_anti_joint_narrowing(self):
        """A TSC pod that also matches a placed anti owner's selector must
        commit to a zone satisfying BOTH (SPEC.md joint narrowing)."""
        anti = PodAffinityTerm(
            label_selector={"tier": "fe"}, topology_key=wk.ZONE_LABEL, anti=True
        )
        pods = [
            mkpod("owner", cpu="2", mem="4Gi", labels={"o": "1"}, affinity_terms=[anti])
        ] + [
            mkpod(f"fe{i}", cpu="1", mem="2Gi", labels={"tier": "fe", "app": "w"},
                  topology_spread=[TSC2])
            for i in range(5)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )


class TestJointNarrowingFallbackPath:
    """Joint narrowing over combined constraints (SPEC.md): TSC+affinity on
    one pod runs ON DEVICE since round 5 (the engine's allowed set is the
    joint intersection); stacked SAME-kind terms (two positive affinities)
    still route to the oracle, which must narrow over the joint set too."""

    def _bignode(self, name, zone, pls):
        n = mknode(name, zone, 0)
        n.pod_labels.extend(pls)
        return n

    def test_tsc_plus_positive_affinity_commits_jointly(self):
        nodes = [
            self._bignode("na", "zone-1a", [{"app": "x"}]),
            self._bignode("nb", "zone-1b", [{"app": "x"}, {"svc": "web"}]),
            self._bignode("nc", "zone-1c", [{"app": "x"}]),
        ]
        aff = PodAffinityTerm(
            label_selector={"svc": "web"}, topology_key=wk.ZONE_LABEL, anti=False
        )
        # too big for the nodes -> forces a fresh-claim commit
        pod = mkpod("p", cpu="12", mem="24Gi", labels={"app": "x"},
                    topology_spread=[TSC1], affinity_terms=[aff])
        ref, tpu = assert_zone_parity(
            SolverInput(pods=[pod], nodes=nodes, nodepools=[pool()], zones=ZONES)
        )
        assert not tpu.errors
        zr = tpu.claims[0].requirements.get(wk.ZONE_LABEL)
        assert zr.values_list() == ["zone-1b"]  # the only jointly-valid zone

    def test_stacked_positive_affinity_commits_jointly(self):
        nodes = [
            self._bignode("na", "zone-1a", [{"svc": "web"}, {"svc": "web"}]),
            self._bignode("nb", "zone-1b", [{"svc": "web"}, {"svc": "db"}]),
        ]
        a1 = PodAffinityTerm(label_selector={"svc": "web"},
                             topology_key=wk.ZONE_LABEL, anti=False)
        a2 = PodAffinityTerm(label_selector={"svc": "db"},
                             topology_key=wk.ZONE_LABEL, anti=False)
        pod = mkpod("q", cpu="12", mem="24Gi", affinity_terms=[a1, a2])
        ref, tpu = assert_zone_parity(
            SolverInput(pods=[pod], nodes=nodes, nodepools=[pool()], zones=ZONES),
            expect_device=False,
        )
        assert not tpu.errors
        zr = tpu.claims[0].requirements.get(wk.ZONE_LABEL)
        assert zr.values_list() == ["zone-1b"]


class TestZoneFuzzParity:
    SELS = [{"app": "w"}, {"app": "db"}, {"tier": "fe"}]

    def _scenario(self, seed):
        rng = random.Random(seed)
        pools = [pool("p1", 10)]
        if rng.random() < 0.4:
            pools.append(
                pool("p0", 50,
                     Requirements.of(Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, ["spot"])))
            )
        nodes = []
        for j in range(rng.randint(0, 5)):
            n = mknode(f"n{j}", rng.choice(ZONES), 0)
            n.free = Resources.parse({"cpu": rng.choice(["4", "8"]), "memory": "16Gi"})
            n.free["pods"] = 30
            for _ in range(rng.randint(0, 4)):
                n.pod_labels.append(dict(rng.choice(self.SELS)))
            nodes.append(n)
        pods = []
        for i in range(rng.randint(5, 35)):
            labels = dict(rng.choice(self.SELS)) if rng.random() < 0.7 else {}
            tsp, aft = [], []
            r = rng.random()
            if r < 0.12:
                # combined TSC + anti-affinity on one pod (may self-match via
                # the pod's own labels) — the device path must narrow jointly
                tsp.append(
                    TopologySpreadConstraint(
                        max_skew=rng.choice([1, 2]), topology_key=wk.ZONE_LABEL,
                        label_selector=dict(rng.choice(self.SELS)))
                )
                aft.append(PodAffinityTerm(
                    label_selector=dict(labels) if labels and rng.random() < 0.5
                    else dict(rng.choice(self.SELS)),
                    topology_key=wk.ZONE_LABEL, anti=True))
            elif r < 0.3:
                tsp.append(
                    TopologySpreadConstraint(
                        max_skew=rng.choice([1, 1, 2]), topology_key=wk.ZONE_LABEL,
                        label_selector=dict(rng.choice(self.SELS)))
                )
            elif r < 0.45:
                aft.append(PodAffinityTerm(label_selector=dict(rng.choice(self.SELS)),
                                           topology_key=wk.ZONE_LABEL, anti=True))
            elif r < 0.55:
                aft.append(PodAffinityTerm(label_selector=dict(rng.choice(self.SELS)),
                                           topology_key=wk.ZONE_LABEL, anti=False))
            elif r < 0.62:
                tsp.append(
                    TopologySpreadConstraint(max_skew=1, topology_key=wk.HOSTNAME_LABEL,
                                             label_selector=dict(rng.choice(self.SELS)))
                )
            elif r < 0.70:
                # capacity-type domain terms (round 4: domain-axis swap) —
                # may mix with other pods' zone sigs, exercising both the
                # swapped device path and the mixed-axis fallback
                if rng.random() < 0.6:
                    tsp.append(
                        TopologySpreadConstraint(
                            max_skew=1, topology_key=wk.CAPACITY_TYPE_LABEL,
                            label_selector=dict(rng.choice(self.SELS)))
                    )
                else:
                    aft.append(PodAffinityTerm(
                        label_selector=dict(rng.choice(self.SELS)),
                        topology_key=wk.CAPACITY_TYPE_LABEL,
                        anti=rng.random() < 0.5))
            elif r < 0.76:
                # positive hostname affinity (round 4: Q kind 2 bootstrap)
                aft.append(PodAffinityTerm(
                    label_selector=dict(labels) if labels and rng.random() < 0.6
                    else dict(rng.choice(self.SELS)),
                    topology_key=wk.HOSTNAME_LABEL, anti=False))
            sel = {}
            if rng.random() < 0.2:
                sel = {wk.ZONE_LABEL: rng.choice(ZONES)}
            pods.append(
                Pod(
                    meta=ObjectMeta(name=f"p{i:03d}", uid=f"p{i:03d}", labels=labels),
                    requests=Resources.parse(
                        {"cpu": rng.choice(["250m", "500m", "1", "2"]),
                         "memory": rng.choice(["512Mi", "1Gi", "2Gi"])}
                    ),
                    node_selector=sel, topology_spread=tsp, affinity_terms=aft,
                )
            )
        return SolverInput(pods=pods, nodes=nodes, nodepools=pools, zones=ZONES)

    @staticmethod
    def _expected_device(inp) -> bool:
        """Independent prediction of the encoder's device/fallback routing,
        replicated from encode's documented group rules (one construct per
        fuzz pod, so stacks never occur): a pod falls back iff (a) its
        owned+anti-membership domain axes span BOTH zone and ct, or (b) it
        owns positive hostname affinity (kind 2) while also being
        domain-constrained (member of any zone/ct anti sig). A divergence
        between this predictor and the encoder fails the seed loudly —
        per-seed exact-path assertions replace the old cumulative
        'some seed hit device' guard (VERDICT r4 weak #5)."""
        def matches(labels, sel):
            return all(labels.get(k) == v for k, v in sel.items())

        anti_sigs = []  # (axis, selector) of every owned anti term
        for p in inp.pods:
            for t in p.affinity_terms:
                if t.anti and t.topology_key == wk.ZONE_LABEL:
                    anti_sigs.append((0, t.label_selector))
                elif t.anti and t.topology_key == wk.CAPACITY_TYPE_LABEL:
                    anti_sigs.append((1, t.label_selector))
        for p in inp.pods:
            axes = set()
            domain_bound = False
            has_h2 = False
            for t in p.topology_spread:
                if t.topology_key == wk.ZONE_LABEL:
                    axes.add(0)
                elif t.topology_key == wk.CAPACITY_TYPE_LABEL:
                    axes.add(1)
            for t in p.affinity_terms:
                if t.topology_key == wk.ZONE_LABEL:
                    axes.add(0)
                elif t.topology_key == wk.CAPACITY_TYPE_LABEL:
                    axes.add(1)
                elif t.topology_key == wk.HOSTNAME_LABEL and not t.anti:
                    has_h2 = True
            for ax, sel in anti_sigs:
                if matches(p.meta.labels, sel):
                    axes.add(ax)
                    domain_bound = True
            if axes or domain_bound:
                domain_bound = True
            if len(axes) > 1:
                return False  # two-axis pod
            if has_h2 and domain_bound:
                return False  # kind-2 + domain-constrained
        return True

    @pytest.mark.parametrize("seed", range(16))
    def test_fuzz(self, seed):
        inp = self._scenario(seed)
        expected = self._expected_device(inp)
        assert_zone_parity(inp, expect_device=expected)
        key = "fuzz_device" if expected else "fuzz_fallback"
        DEVICE_SOLVES_SEEN[key] = DEVICE_SOLVES_SEEN.get(key, 0) + 1

    def test_fuzz_exercised_both_paths(self):
        """Defined after the parametrized seeds (pytest runs in definition
        order): the seed pool must cover BOTH routings, or the per-seed
        exact assertions above degrade to one-sided. Only meaningful over
        the FULL seed pool — a -k'd subset legitimately covers one side."""
        ran = (
            DEVICE_SOLVES_SEEN.get("fuzz_device", 0)
            + DEVICE_SOLVES_SEEN.get("fuzz_fallback", 0)
        )
        if ran < 16:
            pytest.skip(f"only {ran}/16 fuzz seeds ran in this session")
        assert DEVICE_SOLVES_SEEN.get("fuzz_device", 0) > 0
        assert DEVICE_SOLVES_SEEN.get("fuzz_fallback", 0) > 0


class TestNativeZoneParity:
    """Third leg for constrained workloads: the C++ core's per-pod zone/
    hostname path (native/ffd_core.cpp) must match the oracle bit-for-bit
    (VERDICT r3 next #8: constrained CPU-only deployments keep compiled-class
    speed instead of degrading to the interpreter)."""

    def _assert_native(self, inp):
        from karpenter_tpu.solver.native import NativeSolver

        ref = ReferenceSolver().solve(quantize_input(inp))
        solver = NativeSolver()
        nat = solver.solve(inp)
        assert solver.stats["native_solves"] == 1, solver.stats
        assert set(ref.errors) == set(nat.errors), (
            f"errors: ref={sorted(ref.errors)} nat={sorted(nat.errors)}"
        )
        assert ref.placements == nat.placements, _diff(ref.placements, nat.placements)
        assert len(ref.claims) == len(nat.claims)
        for i, (rc, tc) in enumerate(zip(ref.claims, nat.claims)):
            assert rc.nodepool == tc.nodepool, f"claim {i}"
            assert sorted(rc.instance_type_names) == sorted(tc.instance_type_names), f"claim {i}"
            assert rc.pod_uids == tc.pod_uids, f"claim {i}"

    def test_zone_spread_fresh_claims(self):
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "x"}
        )
        pods = [
            mkpod(f"p{i:02d}", labels={"app": "x"}, topology_spread=[tsc])
            for i in range(9)
        ]
        self._assert_native(SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES))

    def test_anti_affinity_exhausts_zones(self):
        term = PodAffinityTerm(label_selector={"svc": "lock"},
                               topology_key=wk.ZONE_LABEL, anti=True)
        pods = [
            mkpod(f"a{i}", labels={"svc": "lock"}, affinity_terms=[term])
            for i in range(5)
        ]
        self._assert_native(SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES))

    def test_positive_affinity_follows_existing(self):
        term = PodAffinityTerm(label_selector={"svc": "web"},
                               topology_key=wk.ZONE_LABEL, anti=False)
        n = mknode("n0", "zone-1b", 0)
        n.free = Resources.parse({"cpu": "1", "memory": "2Gi"})
        n.free["pods"] = 5
        n.pod_labels = [{"svc": "web"}]
        pods = [
            mkpod(f"w{i}", labels={"svc": "web"}, affinity_terms=[term])
            for i in range(4)
        ]
        self._assert_native(SolverInput(pods=pods, nodes=[n], nodepools=[pool()], zones=ZONES))

    def test_hostname_spread(self):
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.HOSTNAME_LABEL, label_selector={"app": "h"}
        )
        pods = [
            mkpod(f"h{i}", labels={"app": "h"}, topology_spread=[tsc])
            for i in range(4)
        ]
        self._assert_native(SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES))

    def test_hostname_anti_affinity(self):
        term = PodAffinityTerm(label_selector={"svc": "solo"},
                               topology_key=wk.HOSTNAME_LABEL, anti=True)
        pods = [
            mkpod(f"s{i}", labels={"svc": "solo"}, affinity_terms=[term])
            for i in range(3)
        ]
        pods += [mkpod(f"f{i}", cpu="250m", mem="256Mi") for i in range(4)]
        self._assert_native(SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES))

    @pytest.mark.parametrize("seed", range(0, 16, 2))
    def test_fuzz_native(self, seed):
        inp = TestZoneFuzzParity()._scenario(seed)
        from karpenter_tpu.solver.native import NativeSolver

        ref = ReferenceSolver().solve(quantize_input(inp))
        solver = NativeSolver()
        nat = solver.solve(inp)
        # constrained fuzz scenarios may still contain oracle-only constructs
        # (fallback groups); when the native core DID run, results must match
        if solver.stats["native_solves"]:
            assert set(ref.errors) == set(nat.errors)
            assert ref.placements == nat.placements, _diff(ref.placements, nat.placements)


class TestEventBatchingParity:
    """Directed coverage for the zoned branch's closed-form batching: the
    mega-generation path (balanced pure-TSC into fresh claims, config 3's
    shape) and multi-claim opening (constant-zone commits, config 4's
    shape) must stay bit-identical to the oracle."""

    def test_mega_generations_multi_app(self):
        # several apps, each a large balanced run into fresh claims
        pods = []
        for a in range(3):
            tsc = TopologySpreadConstraint(
                max_skew=1, topology_key=wk.ZONE_LABEL,
                label_selector={"app": f"m{a}"})
            for i in range(120):
                pods.append(
                    mkpod(f"a{a}p{i:03d}", cpu="500m", mem="1Gi",
                          labels={"app": f"m{a}"}, topology_spread=[tsc]))
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )
        assert not tpu.errors

    def test_mega_with_skew2_and_remainder(self):
        # maxSkew=2 and a pod count that leaves ragged chunk remainders
        tsc = TopologySpreadConstraint(
            max_skew=2, topology_key=wk.ZONE_LABEL, label_selector={"app": "r"})
        pods = [
            mkpod(f"r{i:03d}", cpu="1", mem="2Gi", labels={"app": "r"},
                  topology_spread=[tsc])
            for i in range(157)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_mega_after_draining_existing_targets(self):
        # existing nodes absorb the head of the run; fresh-claim generations
        # take over mid-run once the targets drain
        nodes = [mknode("na", "zone-1a", 0), mknode("nb", "zone-1b", 0)]
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "d"})
        pods = [
            mkpod(f"d{i:03d}", cpu="500m", mem="1Gi", labels={"app": "d"},
                  topology_spread=[tsc])
            for i in range(90)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )

    def test_multi_open_positive_affinity_wave(self):
        # config-4 shape: a large wave follows its own label into one zone —
        # all claims must open in few events and still match the oracle
        term = PodAffinityTerm(label_selector={"svc": "web"},
                               topology_key=wk.ZONE_LABEL, anti=False)
        pods = [
            mkpod(f"w{i:03d}", cpu="1", mem="2Gi", labels={"svc": "web"},
                  affinity_terms=[term])
            for i in range(150)
        ]
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )
        # each claim satisfies the term CLAIM-LOCALLY (co-located matching
        # pods), so claims legitimately stay zone-flexible; what matters is
        # parity and that the wave didn't shatter into per-pod claims
        assert not tpu.errors
        assert len(tpu.claims) <= 4, len(tpu.claims)

    def test_multi_open_anti_member_wave(self):
        # members of an anti sig (not owners): lex-zone commit, constant
        # across claims — multi-open path with blocked-zone exclusions
        anti = PodAffinityTerm(label_selector={"svc": "noisy"},
                               topology_key=wk.ZONE_LABEL, anti=True)
        owner = mkpod("owner", cpu="500m", mem="1Gi", labels={"tag": "o"},
                      affinity_terms=[anti])
        members = [
            mkpod(f"n{i:03d}", cpu="1", mem="2Gi", labels={"svc": "noisy"})
            for i in range(80)
        ]
        assert_zone_parity(
            SolverInput(pods=[owner] + members, nodes=[], nodepools=[pool()],
                        zones=ZONES)
        )


class TestClosedFormBatching:
    """Directed parity for the water-fill mega + aff-bulk closed forms
    (round 4): each scenario is shaped so the eventful path would need many
    trickle events, and the closed form must reproduce the sequential layout
    bit-for-bit — unbalanced starting counts, multi-claim residue drains,
    and both affinity modes (claim-local bootstrap / zone-committed)."""

    def test_waterfill_from_unbalanced_counts(self):
        # 2cpu pods pinned to zone-1a run first (FFD size order) and seed
        # unbalanced sig counts; the spread run then water-fills from floors
        # (7, 0, 0) — the balanced-only closed form never fires here
        pods = [
            mkpod(f"pin{i}", cpu="2", labels={"app": "w"},
                  node_selector={wk.ZONE_LABEL: "zone-1a"})
            for i in range(7)
        ]
        pods += [
            mkpod(f"s{i:03d}", cpu="1", labels={"app": "w"},
                  topology_spread=[TSC1])
            for i in range(90)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_waterfill_multi_residue_drains(self):
        # three same-sig waves, descending size: each leaves partially-full
        # claims, and the last (tiny) wave must drain SEVERAL residues per
        # zone in slot order before opening fresh claims
        pods = []
        for wave, (cpu, n) in enumerate([("2", 40), ("1", 40), ("100m", 200)]):
            pods += [
                mkpod(f"w{wave}p{i:03d}", cpu=cpu, mem="256Mi",
                      labels={"app": "w"}, topology_spread=[TSC1])
                for i in range(n)
            ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_waterfill_with_node_targets_stays_exact(self):
        # eligible nodes in eligible zones disable the closed form (no_node
        # guard) — the eventful path must still match the oracle
        nodes = [mknode("n-a", "zone-1a"), mknode("n-b", "zone-1b")]
        pods = [
            mkpod(f"s{i:03d}", cpu="1", labels={"app": "w"},
                  topology_spread=[TSC1])
            for i in range(60)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )

    def test_owner_not_member_spread(self):
        # the TSC selector does NOT match the pods' own labels: pours never
        # advance the rotation counts, so the closed forms must stay off
        # (is_self guard) and the eventful path must match the oracle
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "w"}
        )
        pods = [
            mkpod(f"x{i:02d}", cpu="1", labels={"app": "x"},
                  topology_spread=[tsc])
            for i in range(12)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_owner_not_member_spread_with_node_targets(self):
        # same, but with a node target in EVERY zone: balanced zero counts
        # satisfy every other cycle condition, so without the is_self guard
        # the cycle would rotate 4/4/4 across nodes while the sequential
        # pour (counts never advance → every zone stays allowed) fills the
        # lex-first node to capacity first
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "w"}
        )
        nodes = [mknode(f"n-{z[-1]}", z) for z in ZONES]
        pods = [
            mkpod(f"x{i:02d}", cpu="1", labels={"app": "x"},
                  topology_spread=[tsc])
            for i in range(12)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )

    def test_aff_bulk_zone_free_bootstrap(self):
        # self-matching positive zone affinity with no committed zone
        # anywhere: pods satisfy the term claim-locally; the tiny second
        # wave drains every first-wave residue in one prefix pour
        def web(n, prefix, cpu, mem):
            return [
                mkpod(f"{prefix}{i:03d}", cpu=cpu, mem=mem,
                      labels={"svc": "web"},
                      affinity_terms=[PodAffinityTerm(
                          label_selector={"svc": "web"},
                          topology_key=wk.ZONE_LABEL, anti=False)])
                for i in range(n)
            ]
        pods = web(30, "a", "2", "2Gi") + web(120, "b", "100m", "64Mi")
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_aff_bulk_committed_mode(self):
        # a pinned member commits the zone first (single-zone claim records
        # the count), so the affinity waves run in committed mode: drains
        # and opens all pin to the argmax zone
        pods = [
            mkpod("seed", cpu="2", labels={"svc": "web"},
                  node_selector={wk.ZONE_LABEL: "zone-1b"})
        ]
        pods += [
            mkpod(f"f{i:03d}", cpu="1", labels={"svc": "web"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"svc": "web"},
                      topology_key=wk.ZONE_LABEL, anti=False)])
            for i in range(60)
        ]
        pods += [
            mkpod(f"g{i:03d}", cpu="100m", mem="64Mi", labels={"svc": "web"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"svc": "web"},
                      topology_key=wk.ZONE_LABEL, anti=False)])
            for i in range(90)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )


class TestCapacityTypeDomain:
    """Capacity-type TSC/affinity ON DEVICE via the domain-axis swap
    (round 4, closing the last spread/affinity fallback): the V engine is
    domain-generic, so ct-granular sigs present lex-ordered capacity types
    as the domain axis — same kernel, different column masks. The reference
    supports exactly three topology keys; this is the third
    (scheduling.md:383-387)."""

    def _ct_tsc(self, max_skew=1):
        return TopologySpreadConstraint(
            max_skew=max_skew,
            topology_key=wk.CAPACITY_TYPE_LABEL,
            label_selector={"app": "w"},
        )

    def test_ct_spread_parity_on_device(self):
        pods = [
            mkpod(f"s{i:03d}", cpu="1", labels={"app": "w"},
                  topology_spread=[self._ct_tsc()])
            for i in range(60)
        ]
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )
        # the spread is real: maxSkew=1 over two capacity types needs at
        # least one committed claim per ct
        cts = set()
        for c in tpu.claims:
            r = c.requirements.get(wk.CAPACITY_TYPE_LABEL)
            if r is not None and len(r.values_list()) == 1:
                cts.add(r.values_list()[0])
        assert cts == {"on-demand", "spot"}, cts

    def test_ct_anti_affinity_parity_on_device(self):
        # singleton locks: one per capacity type, third is unschedulable
        pods = []
        for i in range(3):
            pods.append(
                mkpod(f"l{i}", cpu="1", labels={"svc": f"lock-{i % 1}"},
                      affinity_terms=[PodAffinityTerm(
                          label_selector={"svc": "lock-0"},
                          topology_key=wk.CAPACITY_TYPE_LABEL, anti=True)])
            )
            pods[-1].meta.labels = {"svc": "lock-0"}
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_ct_positive_affinity_parity_on_device(self):
        pods = [
            mkpod(f"w{i:03d}", cpu="500m", labels={"svc": "web"},
                  affinity_terms=[PodAffinityTerm(
                      label_selector={"svc": "web"},
                      topology_key=wk.CAPACITY_TYPE_LABEL, anti=False)])
            for i in range(40)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_ct_spread_with_existing_nodes(self):
        nodes = [mknode("n-od", "zone-1a"), mknode("n-sp", "zone-1b")]
        nodes[1].labels[wk.CAPACITY_TYPE_LABEL] = "spot"
        pods = [
            mkpod(f"s{i:03d}", cpu="1", labels={"app": "w"},
                  topology_spread=[self._ct_tsc()])
            for i in range(24)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )

    def test_mixed_zone_and_ct_sigs_stay_on_device(self):
        # one solve mixing zone- and ct-granular sigs runs on DEVICE since
        # round 5 (concatenated domain columns, per-group axis binding) —
        # cross-axis TSC membership (both groups select app=w) included
        pods = [
            mkpod(f"z{i:02d}", cpu="1", labels={"app": "w"},
                  topology_spread=[TSC1])
            for i in range(9)
        ]
        pods += [
            mkpod(f"c{i:02d}", cpu="1", labels={"app": "w"},
                  topology_spread=[self._ct_tsc()])
            for i in range(9)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_ct_spread_native_parity(self):
        from karpenter_tpu.solver.native import NativeSolver

        from karpenter_tpu.solver.encode import quantize_input as qi

        pods = [
            mkpod(f"s{i:03d}", cpu="1", labels={"app": "w"},
                  topology_spread=[self._ct_tsc()])
            for i in range(30)
        ]
        inp = SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        ref = ReferenceSolver().solve(qi(inp))
        solver = NativeSolver()
        nat = solver.solve(inp)
        assert solver.stats["native_solves"] == 1, solver.stats
        assert set(ref.errors) == set(nat.errors)
        assert ref.placements == nat.placements


class TestPositiveHostnameAffinity:
    """Positive hostname affinity ON DEVICE (Q kind 2, round 4): the group
    co-locates on one node/claim — per-target allowance where members are
    present, plus a one-claim bootstrap budget when no members exist
    anywhere. Overflow pods are unschedulable, exactly as the oracle."""

    def _aff(self, sel=None):
        return PodAffinityTerm(
            label_selector=sel or {"svc": "db"},
            topology_key=wk.HOSTNAME_LABEL,
            anti=False,
        )

    def _small_pool(self):
        small = [t for t in CATALOG if t.name == "m5.large"]
        return NodePoolSpec(
            name="default", weight=0,
            requirements=Requirements.of(
                Requirement.create(wk.NODEPOOL_LABEL, IN, ["default"])
            ),
            taints=[], instance_types=small,
        )

    def test_bootstrap_one_claim_overflow_unschedulable(self):
        pods = [
            mkpod(f"d{i}", cpu="500m", mem="512Mi", labels={"svc": "db"},
                  affinity_terms=[self._aff()])
            for i in range(7)
        ]
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[self._small_pool()],
                        zones=ZONES)
        )
        assert len(tpu.claims) == 1, "the group must co-locate on ONE claim"
        assert tpu.errors, "overflow pods must be unschedulable"

    def test_members_on_existing_node_pin_the_group(self):
        n = mknode("n-db", "zone-1a", matching=2, sel={"svc": "db"})
        pods = [
            mkpod(f"d{i}", cpu="500m", mem="512Mi", labels={"svc": "db"},
                  affinity_terms=[self._aff()])
            for i in range(5)
        ]
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=[n], nodepools=[self._small_pool()],
                        zones=ZONES)
        )
        # members exist on n-db: pods join it (no bootstrap claim allowed)
        assert not tpu.claims, [c.pod_uids for c in tpu.claims]

    def test_bootstrap_onto_existing_node(self):
        # zero members anywhere + compatible EXISTING nodes: the bootstrap
        # lands on the first node first-fit; overflow beyond it errors
        nodes = [mknode("n-a", "zone-1a"), mknode("n-b", "zone-1b")]
        pods = [
            mkpod(f"d{i}", cpu="2", labels={"svc": "db"},
                  affinity_terms=[self._aff()])
            for i in range(6)
        ]
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )
        assert not tpu.claims and tpu.errors

    def test_owner_not_member_needs_existing_members(self):
        # followers don't carry the label: no bootstrap is possible, so
        # without member-holding targets every pod errors
        pods = [
            mkpod(f"f{i}", cpu="500m", labels={"role": "follower"},
                  affinity_terms=[self._aff()])
            for i in range(4)
        ]
        ref, tpu = assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[self._small_pool()],
                        zones=ZONES)
        )
        assert len(tpu.errors) == 4

    def test_mixed_with_plain_pods_and_spread(self):
        # kind-2 group beside plain pods and a zone-spread group: the spread
        # group keeps the zoned path, the kind-2 group keeps the fast path
        pods = [
            mkpod(f"d{i}", cpu="500m", mem="512Mi", labels={"svc": "db"},
                  affinity_terms=[self._aff()])
            for i in range(3)
        ]
        pods += [mkpod(f"u{i}", cpu="1") for i in range(5)]
        pods += [
            mkpod(f"s{i}", cpu="1", labels={"app": "w"}, topology_spread=[TSC1])
            for i in range(6)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_h2_plus_own_zone_constraint_falls_back_exactly(self):
        # a pod owning BOTH a positive hostname affinity and a zone TSC
        # routes the whole solve to the oracle (the bootstrap budget is not
        # threaded through the zoned engine) — parity must hold
        pods = [
            mkpod(f"x{i}", cpu="500m", labels={"svc": "db", "app": "w"},
                  affinity_terms=[self._aff()], topology_spread=[TSC1])
            for i in range(4)
        ]
        assert_zone_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES),
            expect_device=False,
        )


class TestPositiveHostnameAffinityNative:
    """The C++ core's kind-2 port must match the oracle on the same shapes
    the device tests pin (bootstrap single target, member pinning,
    owner-not-member, overflow-unschedulable)."""

    def _native_parity(self, inp):
        from karpenter_tpu.solver.native import NativeSolver

        ref = ReferenceSolver().solve(quantize_input(inp))
        solver = NativeSolver()
        nat = solver.solve(inp)
        assert solver.stats["native_solves"] == 1, solver.stats
        assert set(ref.errors) == set(nat.errors), (
            f"ref={sorted(ref.errors)} nat={sorted(nat.errors)}"
        )
        assert ref.placements == nat.placements, _diff(ref.placements, nat.placements)
        return ref, nat

    def test_native_bootstrap_and_overflow(self):
        small = [t for t in CATALOG if t.name == "m5.large"]
        spool = NodePoolSpec(
            name="default", weight=0,
            requirements=Requirements.of(
                Requirement.create(wk.NODEPOOL_LABEL, IN, ["default"])
            ),
            taints=[], instance_types=small,
        )
        aff = PodAffinityTerm(label_selector={"svc": "db"},
                              topology_key=wk.HOSTNAME_LABEL, anti=False)
        pods = [
            mkpod(f"d{i}", cpu="500m", mem="512Mi", labels={"svc": "db"},
                  affinity_terms=[aff])
            for i in range(7)
        ]
        ref, nat = self._native_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[spool], zones=ZONES)
        )
        assert len(nat.claims) == 1 and nat.errors

    def test_native_member_node_pinning(self):
        aff = PodAffinityTerm(label_selector={"svc": "db"},
                              topology_key=wk.HOSTNAME_LABEL, anti=False)
        n = mknode("n-db", "zone-1a", matching=2, sel={"svc": "db"})
        pods = [
            mkpod(f"d{i}", cpu="500m", labels={"svc": "db"},
                  affinity_terms=[aff])
            for i in range(5)
        ]
        ref, nat = self._native_parity(
            SolverInput(pods=pods, nodes=[n], nodepools=[pool()], zones=ZONES)
        )
        assert not nat.claims

    def test_native_owner_not_member(self):
        aff = PodAffinityTerm(label_selector={"svc": "db"},
                              topology_key=wk.HOSTNAME_LABEL, anti=False)
        pods = [
            mkpod(f"f{i}", cpu="500m", labels={"role": "follower"},
                  affinity_terms=[aff])
            for i in range(4)
        ]
        ref, nat = self._native_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )
        assert len(nat.errors) == 4

    def test_native_mixed_with_plain(self):
        aff = PodAffinityTerm(label_selector={"svc": "db"},
                              topology_key=wk.HOSTNAME_LABEL, anti=False)
        pods = [
            mkpod(f"d{i}", cpu="500m", mem="512Mi", labels={"svc": "db"},
                  affinity_terms=[aff])
            for i in range(3)
        ]
        pods += [mkpod(f"u{i}", cpu="1") for i in range(5)]
        self._native_parity(
            SolverInput(pods=pods, nodes=[], nodepools=[pool()], zones=ZONES)
        )

    def test_native_bootstrap_onto_existing_node(self):
        # zero members anywhere + a compatible EXISTING node: the bootstrap
        # lands on that single node first-fit (not a fresh claim, not spread
        # across several nodes) and the rest of the group follows it
        aff = PodAffinityTerm(label_selector={"svc": "db"},
                              topology_key=wk.HOSTNAME_LABEL, anti=False)
        nodes = [mknode("n-a", "zone-1a"), mknode("n-b", "zone-1b")]
        pods = [
            mkpod(f"d{i}", cpu="2", labels={"svc": "db"},
                  affinity_terms=[aff])
            for i in range(6)  # 6x2cpu > one 8cpu node: overflow must error
        ]
        ref, nat = self._native_parity(
            SolverInput(pods=pods, nodes=nodes, nodepools=[pool()], zones=ZONES)
        )
        assert not nat.claims and nat.errors, (
            [c.pod_uids for c in nat.claims], nat.errors
        )


class TestPoolLimitsTaintsFuzz:
    """Fuzz axes the main generator doesn't stress: multiple weighted pools
    with LIMITS and TAINTS + randomized tolerations, crossed with every
    domain-constraint family — pool-limit charging interacts with the
    closed forms' funding math (trips0) and taints with pool admission.
    A 48-seed offline sweep passed when this landed; CI keeps 8."""

    SELS = [{"app": "a"}, {"app": "b"}, {"svc": "web"}]

    def _scenario(self, seed):
        from karpenter_tpu.api.objects import Taint, Toleration

        rng = random.Random(seed)
        pools = []
        for pi in range(rng.randint(1, 3)):
            reqs = Requirements.of(
                Requirement.create(wk.NODEPOOL_LABEL, IN, [f"p{pi}"])
            )
            taints = []
            if rng.random() < 0.5:
                taints.append(Taint(key=f"team-{pi}", value="x", effect="NoSchedule"))
            limits = {}
            if rng.random() < 0.6:
                limits = {"cpu": rng.choice([4000, 8000, 16000, 32000])}
            pools.append(NodePoolSpec(
                name=f"p{pi}", weight=rng.randint(0, 50), requirements=reqs,
                taints=taints, instance_types=CATALOG, limits=limits,
            ))
        nodes = [mknode(f"n{j}", rng.choice(ZONES)) for j in range(rng.randint(0, 3))]
        pods = []
        for i in range(rng.randint(6, 28)):
            from karpenter_tpu.api.objects import Toleration as _T

            labels = dict(rng.choice(self.SELS)) if rng.random() < 0.6 else {}
            tols = []
            for pi in range(3):
                if rng.random() < 0.4:
                    tols.append(_T(key=f"team-{pi}", operator="Equal",
                                   value="x", effect="NoSchedule"))
            tsp, aft = [], []
            r = rng.random()
            if r < 0.25:
                tsp.append(TopologySpreadConstraint(
                    max_skew=1, topology_key=wk.ZONE_LABEL,
                    label_selector=dict(rng.choice(self.SELS))))
            elif r < 0.4:
                tsp.append(TopologySpreadConstraint(
                    max_skew=1, topology_key=wk.CAPACITY_TYPE_LABEL,
                    label_selector=dict(rng.choice(self.SELS))))
            elif r < 0.5:
                aft.append(PodAffinityTerm(
                    label_selector=dict(rng.choice(self.SELS)),
                    topology_key=wk.ZONE_LABEL, anti=rng.random() < 0.5))
            pods.append(mkpod(
                f"q{i:03d}", cpu=rng.choice(["500m", "1", "2"]), labels=labels,
                topology_spread=tsp, affinity_terms=aft, tolerations=tols,
            ))
        return SolverInput(pods=pods, nodes=nodes, nodepools=pools, zones=ZONES)

    @pytest.mark.parametrize("seed", range(300, 308))
    def test_fuzz_limits_taints(self, seed):
        inp = self._scenario(seed)
        assert_zone_parity(
            inp, expect_device=TestZoneFuzzParity._expected_device(inp)
        )


class TestIgnorePolicyFuzz:
    """--preference-policy=Ignore keeps preference-carrying pods ON DEVICE
    (preferred terms drop before encode): fuzz ScheduleAnyway spreads and
    weighted affinity beside required zone spread, asserting parity against
    the oracle under the same policy AND that the device path served every
    solve. A 40-seed offline sweep passed when this landed; CI keeps 4."""

    SELS = [{"app": "a"}, {"app": "b"}]

    def _scenario(self, seed):
        rng = random.Random(seed)
        pods = []
        for i in range(rng.randint(6, 24)):
            labels = dict(rng.choice(self.SELS)) if rng.random() < 0.6 else {}
            tsp, aft = [], []
            r = rng.random()
            if r < 0.3:
                tsp.append(TopologySpreadConstraint(
                    max_skew=1, topology_key=wk.ZONE_LABEL,
                    label_selector=dict(rng.choice(self.SELS)),
                    when_unsatisfiable="ScheduleAnyway"))
            elif r < 0.5:
                aft.append(PodAffinityTerm(
                    label_selector=dict(rng.choice(self.SELS)),
                    topology_key=wk.ZONE_LABEL,
                    anti=rng.random() < 0.5, weight=rng.choice([1, 50, 100])))
            elif r < 0.7:
                tsp.append(TopologySpreadConstraint(
                    max_skew=1, topology_key=wk.ZONE_LABEL,
                    label_selector=dict(rng.choice(self.SELS))))
            pods.append(mkpod(f"g{i:03d}", cpu=rng.choice(["500m", "1"]),
                              labels=labels, topology_spread=tsp,
                              affinity_terms=aft))
        nodes = [mknode(f"n{j}", rng.choice(ZONES))
                 for j in range(rng.randint(0, 2))]
        return SolverInput(pods=pods, nodes=nodes, nodepools=[pool()],
                           zones=ZONES, preference_policy="Ignore")

    @pytest.mark.parametrize("seed", range(500, 504))
    def test_fuzz_ignore_policy(self, seed):
        assert_zone_parity(self._scenario(seed), expect_device=True)
